"""Concurrent job scheduler over the file-spool queue.

Replaces the daemon's one-message-at-a-time blocking loop
(``engine/daemon.py::QueueConsumer.run``) with a production serving shape:

- a **dispatcher** thread scans ``pending/`` and admits messages in
  (priority class, per-tenant fairness, FIFO) order, claiming each by the
  same atomic rename the daemon uses, into a bounded hand-off queue;
- a **worker pool** executes claimed jobs concurrently.  Device-bound
  phases go through the **device pool** (``service/device_pool.py``): each
  job gets a ``DeviceLease`` for 1..N chips (``service.devices_per_job``
  default, per-submit ``devices`` override), handed to the callback via
  ``JobContext.device_token`` — still Lock-protocol compatible, acquired
  inside ``SearchJob.run`` around the compiled-search phase.  Small jobs
  pack onto DISTINCT chips and run their device phases concurrently;
  large jobs claim a contiguous sub-mesh and score through the
  pjit-sharded path (``parallel/sharded.py``).  CPU-bound staging/parse
  still overlaps device time — the service-level analog of the
  host/device pipelining the backends do per batch;
- a **failure policy**: per-job timeout (message ``timeout_s`` overrides
  the config default), retry with exponential backoff + jitter, bounded
  attempts, then dead-letter into ``failed/`` with the recorded traceback.
  Retries persist their state (``attempts``, ``next_retry_at``) INTO the
  message file and move it back to ``pending/`` — a scheduler crash between
  attempts loses nothing;
- **cooperative cancellation** (``utils/cancel.py``): every attempt gets a
  ``CancelToken`` via ``JobContext``.  A per-attempt timeout, an absolute
  submit deadline (``service.deadline_at``), an operator ``DELETE
  /jobs/<id>``, or the stall **watchdog** trips the token; the job unwinds
  at its next checkpoint-group boundary — releasing the device token and
  writing no partial results — and the worker requeues or terminates the
  message cleanly.  Only an attempt that ignores the cancel past
  ``cancel_grace_s`` is abandoned (counted on ``/metrics``); spool moves
  still only ever happen in the owning worker, so even a zombie can never
  corrupt queue state;
- **quarantine**: every claim increments a persisted ``service.claims``
  counter, so a message that crash-loops the process (and therefore never
  reaches the handled-failure/dead-letter path) moves to a ``quarantine/``
  spool state after ``quarantine_after`` claims instead of cycling through
  requeue forever;
- **heartbeat files** (``engine/daemon.py::ClaimHeartbeat``) touched for
  every running claim, so ``requeue_stale()`` distinguishes crashed claims
  from slow jobs;
- graceful drain: ``shutdown()`` stops admission, requeues
  claimed-but-unstarted messages, waits for running jobs, and leaves
  ``running/`` empty.

Priority classes come from message metadata: ``priority`` is ``"high"`` /
``"normal"`` / ``"low"`` (or an int, lower = sooner); ``tenant`` scopes
fairness — among equal priorities the dispatcher favors the tenant with the
fewest in-flight jobs, so one tenant's burst cannot starve the rest.
"""

from __future__ import annotations

import json
import os
import queue as _queue_mod
import random
import threading
import time
import traceback
from dataclasses import dataclass, field
from pathlib import Path

from ..engine.daemon import (
    FP_COMPLETE,
    QUEUE_ANNOTATE,
    ClaimHeartbeat,
    _STATES,
    clear_heartbeat,
    sweep_orphan_tmp,
)
from ..models import faults
from ..parallel.distributed import process_identity
from ..utils import tracing
from ..utils.cancel import (
    CancelToken,
    DeadlineExceededError,
    JobCancelledError,
    StreamIdleError,
)
from ..utils.config import ServiceConfig
from ..utils.failpoints import failpoint, record_recovery, register_failpoint
from ..utils.logger import logger
from .device_pool import DevicePool, resolve_pool_size
from .health import HealthTracker
from .leases import (
    FP_TAKEOVER_SCAN,
    FenceRejectedError,
    LeaseStore,
    ReplicaRegistry,
    owned_shards,
    shard_of,
)

FP_RETRY_PUBLISH = register_failpoint(
    "sched.retry_publish",
    "between a retry's updated tmp write and its republish into pending/")
FP_CANCEL_DELIVER = register_failpoint(
    "sched.cancel_deliver",
    "between a cancel decision (timeout/deadline/user/watchdog) and its "
    "delivery to the attempt's CancelToken")
FP_DRAIN_HANDOFF = register_failpoint(
    "drain.handoff",
    "inside a replica's drain begin — after the drain request is noticed, "
    "while claims may still be in flight (a crash here is a victim killed "
    "mid-drain; takeover must complete its work exactly once)")
FP_RETIRE_ACK = register_failpoint(
    "fleet.retire_ack",
    "between a drained replica going idle and its retire ack write (a "
    "crash here leaves the ack unwritten; the controller falls back to "
    "process-exit + registry staleness)")
FP_HOST_HEARTBEAT = register_failpoint(
    "host.heartbeat",
    "inside the host watchdog's freshness pass over the registry's per-"
    "process beat groups (raise here counts every REMOTE process's beats "
    "as missed — the whole-host eviction path without killing a process)")

PRIORITY_CLASSES = {"high": 0, "normal": 1, "low": 2}

# terminal + live job states surfaced via /jobs
JOB_STATES = ("queued", "claimed", "running", "retry_wait", "done", "failed",
              "cancelled", "quarantined")
TERMINAL_STATES = ("done", "failed", "cancelled", "quarantined")


def _priority_rank(value) -> int:
    if isinstance(value, (int, float)):
        return int(value)
    return PRIORITY_CLASSES.get(str(value), PRIORITY_CLASSES["normal"])


@dataclass
class RetryPolicy:
    """Exponential backoff with additive jitter; attempts are bounded."""

    max_attempts: int = 3
    base_s: float = 1.0
    max_s: float = 60.0
    jitter: float = 0.1            # delay *= 1 + U[0, jitter]

    def backoff_s(self, attempt: int) -> float:
        """Delay before retry number ``attempt`` (1-based: after the first
        failure attempt=1).  Always >= base_s * 2^(attempt-1) capped at
        max_s; jitter only ADDS (de-synchronizes retry thundering herds
        without ever retrying early)."""
        delay = min(self.max_s, self.base_s * (2.0 ** (attempt - 1)))
        return delay * (1.0 + random.random() * self.jitter)

    @staticmethod
    def from_config(cfg: ServiceConfig) -> "RetryPolicy":
        return RetryPolicy(
            max_attempts=cfg.max_attempts,
            base_s=cfg.backoff_base_s,
            max_s=cfg.backoff_max_s,
            jitter=cfg.backoff_jitter,
        )


@dataclass
class JobRecord:
    """In-memory tracking row for one message (served by ``GET /jobs``)."""

    msg_id: str
    ds_id: str = ""
    tenant: str = "default"
    priority: str | int = "normal"
    state: str = "queued"
    attempts: int = 0
    published_at: float = 0.0
    claimed_at: float = 0.0
    started_at: float = 0.0
    finished_at: float = 0.0
    next_retry_at: float = 0.0
    deadline_at: float = 0.0
    cancel_requested: str = ""     # "" | "user" (DELETE /jobs/<id>)
    error: str = ""
    trace_id: str = ""             # end-to-end trace (GET /jobs/<id>/trace)
    # streamed first results (ISSUE 13): the latest provisional-annotation
    # summary from the running search ({provisional, group, n_scored,
    # n_ions, annotations, fdr_10pct, top}); {} until the first
    # FDR-rankable group lands
    partial: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "msg_id": self.msg_id, "ds_id": self.ds_id, "tenant": self.tenant,
            "priority": self.priority, "state": self.state,
            "attempts": self.attempts, "published_at": self.published_at,
            "claimed_at": self.claimed_at, "started_at": self.started_at,
            "finished_at": self.finished_at,
            "next_retry_at": self.next_retry_at,
            "deadline_at": self.deadline_at,
            "cancel_requested": self.cancel_requested, "error": self.error,
            "trace_id": self.trace_id,
            "partial": dict(self.partial),
        }


@dataclass
class JobContext:
    """Handed to callbacks that accept a second argument."""

    msg_id: str
    attempt: int
    # this job's DeviceLease (service/device_pool.py): Lock-protocol
    # compatible — ``with ctx.device_token:`` still works — but a grant is
    # 1..N chips (``.devices`` after acquire), not the old global token
    device_token: object = field(repr=False, default=None)
    metrics: object = field(repr=False, default=None)
    # cooperative cancellation: callbacks check this at phase / checkpoint-
    # group boundaries (utils/cancel.CancelToken; None for legacy callers)
    cancel: object = field(repr=False, default=None)
    # fence gate (service/leases.py, ISSUE 8): callbacks call this before
    # durable side effects (result store, ledger commit); it raises
    # FenceRejectedError when a peer replica fenced this claim out, so a
    # stale replica can never double-commit.  None for legacy callers.
    fence: object = field(repr=False, default=None)
    # end-to-end tracing (utils/tracing.TraceContext for THIS attempt's
    # span): callbacks attach it so every phase/batch span lands in the
    # job's trace; None for legacy callers
    trace: object = field(repr=False, default=None)
    # streamed first results (ISSUE 13): callbacks call this with the
    # provisional-annotation payload when the first FDR-rankable group
    # lands — it updates the job record's ``partial`` field served by
    # GET /jobs.  None for legacy callers.
    set_partial: object = field(repr=False, default=None)


def _callback_takes_ctx(fn) -> bool:
    """Callbacks may be legacy single-arg (``cb(msg)``, plain daemon style)
    or service-aware (``cb(msg, ctx)``)."""
    import inspect

    try:
        params = list(inspect.signature(fn).parameters.values())
    except (TypeError, ValueError):
        return False
    positional = [
        p for p in params
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
    ]
    if any(p.kind == p.VAR_POSITIONAL for p in params):
        return True
    return len(positional) >= 2


class _Attempt(threading.Thread):
    """One callback invocation, joinable with a timeout.  A timed-out
    attempt is cancelled cooperatively through its ``JobContext.cancel``
    token and given ``cancel_grace_s`` to unwind; only one that ignores the
    cancel is abandoned (daemon thread — Python cannot kill it).  All spool
    file moves happen in the owning worker, so even an abandoned attempt
    can never corrupt queue state."""

    def __init__(self, fn, msg, ctx, takes_ctx: bool):
        super().__init__(daemon=True, name=f"attempt-{ctx.msg_id}-{ctx.attempt}")
        self.fn, self.msg, self.ctx, self.takes_ctx = fn, msg, ctx, takes_ctx
        self.error: BaseException | None = None
        self.tb: str = ""

    def run(self) -> None:
        try:
            # thread hop: the attempt span context becomes ambient, so every
            # phase/backend/isocalc span in the callback nests under it
            with tracing.attach(self.ctx.trace):
                if self.takes_ctx:
                    self.fn(self.msg, self.ctx)
                else:
                    self.fn(self.msg)
        except BaseException as exc:  # noqa: BLE001 — recorded, not swallowed
            self.error = exc
            self.tb = traceback.format_exc()


class JobScheduler:
    """Drain the spool with a worker pool under the service failure policy."""

    # shared-state registry checked by the smlint guarded-by rule
    # (docs/ANALYSIS.md): dispatcher, workers, watchdog, replica loop, and
    # HTTP handlers all touch these maps — mutations only under
    # _records_lock.  _owned and _draining are excluded deliberately: each
    # is replaced wholesale by one writer (the replica loop) and read
    # racily by design.
    _GUARDED_BY = {"_records": "_records_lock", "_live": "_records_lock",
                   "_trace_roots": "_records_lock",
                   "_lease_by_msg": "_records_lock",
                   "_inflight_by_tenant": "_records_lock",
                   "_terminal_count": "_records_lock",
                   "_fenced_count": "_records_lock"}

    def __init__(
        self,
        queue_dir: str | Path,
        callback,
        config: ServiceConfig | None = None,
        queue: str = QUEUE_ANNOTATE,
        metrics=None,
        admission=None,
        trace_dir: str | Path | None = None,
        slo=None,
        device_pool: DevicePool | None = None,
        resources=None,
    ):
        self.root = Path(queue_dir) / queue
        for s in _STATES:
            (self.root / s).mkdir(parents=True, exist_ok=True)
        self.callback = callback
        self._cb_takes_ctx = _callback_takes_ctx(callback)
        self.cfg = config or ServiceConfig()
        # end-to-end tracing: per-job JSONL files land here (None disables
        # the file sink; spans still reach the flight recorder)
        self.trace_dir = str(trace_dir) if trace_dir else None
        # live root trace contexts + their submit timestamps, by msg_id —
        # the seam every terminal outcome closes the root "submit" span at
        self._trace_roots: dict[str, tuple[tracing.TraceContext, float]] = {}
        self.retry = RetryPolicy.from_config(self.cfg)
        self.metrics = metrics
        # service-level admission controller (service/admission.py): the
        # scheduler reports terminal outcomes + attempt latency into it
        self.admission = admission
        # SLO tracker (service/telemetry.py): queue-wait observed at each
        # job's first attempt start, e2e latency at every terminal outcome
        self.slo = slo
        # resource governor (ISSUE 10, service/resources.py): the replica
        # loop runs its bounded-retention GC sweep on gc_interval_s,
        # scoped to this replica's shards via owns_msg — N replicas sweep
        # one spool without double-reaping, and takeover shifts sweep
        # ownership with shard ownership.  None = no GC, no budget.
        self.resources = resources
        # the device POOL (ISSUE 7): jobs lease 1..N chips; small jobs pack
        # onto distinct chips, sub-mesh jobs claim contiguous runs.  The
        # pool still speaks the old single-token Lock protocol, and
        # ``device_token`` stays as the back-compat alias for code that
        # poked the PR 1 lock directly.
        if device_pool is not None:
            self.device_pool = device_pool
        else:
            size = resolve_pool_size(self.cfg)
            self.device_pool = DevicePool(
                size, max_bypass=self.cfg.device_pool_max_bypass,
                hosts=self.cfg.device_pool_hosts,
                health=HealthTracker.from_config(
                    size, self.cfg, hosts=self.cfg.device_pool_hosts))
        # classified device faults from the scoring seam reach the pool's
        # health tracker through the models-side listener seam (ISSUE 14,
        # models/faults.py) — quarantine/probe verdicts then shape every
        # later grant, incl. this scheduler's retry re-lease
        faults.set_fault_listener(self.device_pool.health)
        self.device_token = self.device_pool
        # multi-replica protocol (ISSUE 8, service/leases.py): this
        # replica's identity in the registry, its epoch-numbered fenced
        # leases, and the logical shard partition it claims from.  With
        # replicas=1 and no peer heartbeats this degenerates to the old
        # single-owner behavior (the replica owns every shard).
        self.replica_id = self.cfg.replica_id
        # pod identity (ISSUE 17): this scheduler process's (process_id,
        # host), stamped into tracing records, registry beats (the host
        # watchdog's grouping key), telemetry samples, and GET /peers
        self.identity = process_identity()
        tracing.set_process(self.identity["process_id"],
                            self.identity["host"])
        # host-watchdog memory: host domains currently evicted for missed
        # process beats.  Replica-loop-only state (single writer) — not in
        # _GUARDED_BY for the same reason _owned/_draining are excluded.
        self._evicted_hosts: set[int] = set()
        self.registry = ReplicaRegistry(
            self.root, self.replica_id,
            stale_after_s=self.cfg.replica_stale_after_s)
        self.epoch = self.registry.register()
        self.leases = LeaseStore(self.root, self.replica_id,
                                 epoch=self.epoch, metrics=metrics)
        self._lease_by_msg: dict[str, object] = {}
        self._owned: set[int] = set(range(self.cfg.spool_shards))
        self._fenced_count = 0
        # zero-loss drain (ISSUE 11): once a drain request is noticed the
        # replica stops claiming (owned = ∅, peers adopt the shards),
        # finishes or releases in-flight work, acks, and the serve loop
        # exits.  _draining is replica-loop-written, read racily.
        self._draining = False
        self._drain_done = threading.Event()
        self._records: dict[str, JobRecord] = {}
        self._records_lock = threading.Lock()
        # live attempts by msg_id: (CancelToken, _Attempt) — the seam the
        # DELETE endpoint and the stall watchdog deliver cancels through
        self._live: dict[str, tuple[CancelToken, _Attempt]] = {}
        # bounded hand-off: at most `workers` messages sit claimed-but-
        # unstarted, so a SIGTERM drain requeues a bounded set
        self._handoff: _queue_mod.Queue = _queue_mod.Queue(maxsize=max(1, self.cfg.workers))
        self._stop = threading.Event()
        self._drained = threading.Event()
        self._threads: list[threading.Thread] = []
        self._inflight_by_tenant: dict[str, int] = {}
        self._terminal_count = 0
        # heartbeat gossip suppliers (ISSUE 20): the server registers
        # callables (admin address, pool occupancy, stream in-flight) whose
        # values fold into every registry beat so peers can discover this
        # replica's admin API and fleet status without another channel.
        # Written once at wiring time, read by the replica beat loop.
        self._gossip: dict[str, object] = {}
        self._started = False
        if metrics is not None:
            self._init_metrics(metrics)

    # ------------------------------------------------------------- metrics
    def _init_metrics(self, m) -> None:
        self.m_jobs = m.counter(
            "sm_jobs_total", "Terminal job outcomes by state", ("state",))
        self.m_retries = m.counter(
            "sm_job_retries_total", "Retry attempts scheduled")
        self.m_timeouts = m.counter(
            "sm_job_timeouts_total", "Attempts that exceeded the per-job timeout")
        self.m_cancels = m.counter(
            "sm_jobs_cancelled_total", "Cancellations delivered, by reason",
            ("reason",))
        self.m_abandoned = m.counter(
            "sm_job_abandoned_total",
            "Timed-out attempts still alive after the cancel grace period")
        self.m_quarantined = m.counter(
            "sm_jobs_quarantined_total",
            "Messages parked in quarantine/ after crash-looping claims")
        self.m_stream_reranks = m.counter(
            "sm_stream_reranks_total",
            "Provisional stream re-ranks published via the partial seam")
        self.m_running = m.gauge(
            "sm_jobs_running", "Jobs currently executing in the worker pool")
        self.m_duration = m.histogram(
            "sm_job_duration_seconds", "Per-attempt job wall clock")
        self.m_backoff = m.histogram(
            "sm_retry_backoff_seconds", "Backoff delays scheduled before retries",
            buckets=(0.01, 0.05, 0.25, 1.0, 5.0, 30.0, 120.0))
        # per-chip in_use gauge + grant/wait metrics (idempotent when the
        # service already attached them to the shared pool)
        self.device_pool.attach_metrics(m)
        # replica-labeled families (ISSUE 8): identity, shard ownership,
        # takeovers, fence rejections, peer liveness
        self.m_replica_up = m.gauge(
            "sm_replica_up", "1 while this replica is serving", ("replica",))
        self.m_replica_up.labels(replica=self.replica_id).set(1)
        self.m_shards_owned = m.gauge(
            "sm_replica_shards_owned",
            "Spool shards this replica currently owns", ("replica",))
        self.m_takeover_requeues = m.counter(
            "sm_replica_takeover_requeues_total",
            "Stale peer claims fenced + requeued by this replica's takeover "
            "scans", ("replica",))
        self.m_replica_beats = m.counter(
            "sm_replica_heartbeats_total",
            "Registry heartbeats written", ("replica",))
        self.m_fenced_claims = m.counter(
            "sm_replica_fenced_claims_total",
            "Local claims abandoned because a peer fenced them out",
            ("replica",))
        # pod-level families (ISSUE 17): what the host watchdog observes
        # and does, per pod process
        self.m_pod_processes = m.gauge(
            "sm_pod_processes",
            "Distinct pod processes observed in the replica registry's "
            "beat groups")
        self.m_pod_process_up = m.gauge(
            "sm_pod_process_up",
            "1 while the pod process's registry beat group is fresh, per "
            "process", ("process",))
        self.m_pod_host_evictions = m.counter(
            "sm_pod_host_evictions_total",
            "Host domains evicted by the watchdog after missed process "
            "heartbeats")
        self.m_pod_host_evictions.inc(0)
        m.add_collector(self._collect_queue_depths)
        m.add_collector(self._collect_replicas)

    def _collect_queue_depths(self, m) -> None:
        g = m.gauge("sm_queue_depth", "Messages per spool state", ("state",))
        for s in _STATES:
            g.labels(state=s).set(len(list(self.root.glob(f"{s}/*.json"))))

    def _collect_replicas(self, m) -> None:
        peers = self.registry.peers()
        m.gauge("sm_replica_peers_alive",
                "Replicas with a fresh registry heartbeat (incl. self)").set(
            sum(1 for p in peers if p.get("alive")))
        age = m.gauge("sm_replica_peer_age_seconds",
                      "Age of each replica's last registry heartbeat",
                      ("replica",))
        for p in peers:
            age.labels(replica=str(p.get("replica_id", "?"))).set(
                float(p.get("age_s", 0.0)))
        self.m_shards_owned.labels(replica=self.replica_id).set(
            len(self._owned))

    # ------------------------------------------------------------- records
    def _record(self, msg_id: str) -> JobRecord:
        with self._records_lock:
            rec = self._records.get(msg_id)
            if rec is None:
                rec = self._records[msg_id] = JobRecord(msg_id=msg_id)
            return rec

    def jobs(self) -> list[dict]:
        with self._records_lock:
            return [r.to_dict() for r in self._records.values()]

    def stats(self) -> dict:
        with self._records_lock:
            by_state: dict[str, int] = {}
            for r in self._records.values():
                by_state[r.state] = by_state.get(r.state, 0) + 1
        return {
            "workers": self.cfg.workers,
            "states": by_state,
            "terminal": self._terminal_count,
            "stopping": self._stop.is_set(),
        }

    def _set_partial(self, rec: JobRecord, payload: dict) -> None:
        """Streamed first results (ISSUE 13): the running search published
        a provisional-annotation summary — surface it on the job record
        so GET /jobs shows rankable results while later batches run.
        Stream re-ranks (ISSUE 19) ride the same seam with a ``stream``
        coverage block; it feeds the re-rank counter and the chunk-commit
        -> partial SLO histogram."""
        with self._records_lock:
            rec.partial = dict(payload or {})
        stream = (payload or {}).get("stream")
        if isinstance(stream, dict):
            if self.metrics:
                self.m_stream_reranks.inc()
            lat = stream.get("commit_to_partial_s")
            if self.slo is not None and lat is not None:
                self.slo.observe_stream_partial(float(lat))

    def _note_terminal(self, rec: JobRecord) -> None:
        with self._records_lock:
            self._terminal_count += 1
        if self.admission is not None:
            self.admission.note_terminal(rec.msg_id)

    # -------------------------------------------------------------- tracing
    def _trace_ctx(self, msg_id: str,
                   msg: dict | None) -> tuple[tracing.TraceContext, float]:
        """Root trace context + submit timestamp for a message.  The ids
        come from ``service.trace`` (stamped at POST /submit), so a
        restarted scheduler — or a later attempt — continues the SAME trace
        and appends to the SAME file; messages published without one
        (direct spool drops, the blocking daemon) get a root minted at
        first claim."""
        with self._records_lock:
            hit = self._trace_roots.get(msg_id)
        if hit is not None:
            return hit
        svc = msg.get("service", {}) if isinstance(msg, dict) else {}
        t = svc.get("trace") if isinstance(svc, dict) else None
        t = t if isinstance(t, dict) else {}
        trace_id = str(t.get("trace_id") or tracing.new_id())
        span_id = str(t.get("span") or tracing.new_id())
        start = float(t.get("start") or
                      (msg or {}).get("published_at") or time.time())
        file = (str(tracing.trace_path(self.trace_dir, trace_id))
                if self.trace_dir else "")
        ctx = tracing.TraceContext(trace_id=trace_id, span_id=span_id,
                                   job_id=msg_id, file=file)
        with self._records_lock:
            self._trace_roots[msg_id] = (ctx, start)
        return ctx, start

    def _close_trace(self, rec: JobRecord, state: str) -> None:
        """Terminal outcome: close the root ``submit`` span (its duration is
        submit → terminal, covering queueing + every attempt)."""
        with self._records_lock:
            hit = self._trace_roots.pop(rec.msg_id, None)
        if hit is None:
            return
        ctx, start = hit
        if self.slo is not None:
            self.slo.observe_terminal(rec.msg_id, state, start)
        tracing.emit_span(
            ctx, "submit", ts=start, dur=time.time() - start,
            span_id=ctx.span_id, state=state, msg_id=rec.msg_id,
            ds_id=rec.ds_id, attempts=rec.attempts,
            **({"error": rec.error[:500]} if rec.error else {}))

    # ------------------------------------------------------------ replicas
    def _recompute_owned(self) -> set[int]:
        """Shards this replica owns right now: rendezvous hashing over the
        ACTIVE replica set (alive minus draining; self included unless
        draining).  A dead peer's shards land here the moment its heartbeat
        passes the staleness horizon; a draining peer's land here the
        moment its drain sentinel appears — while the victim's fresh
        heartbeats keep its in-flight claims safe from takeover."""
        owned = (set() if self._draining else
                 owned_shards(self.replica_id, self.registry.active(),
                              self.cfg.spool_shards))
        prev = self._owned
        self._owned = owned
        gained = owned - prev
        if gained and prev != owned:
            logger.info("replica %s: shard ownership now %s (+%s)",
                        self.replica_id, sorted(owned), sorted(gained))
        return owned

    def owns_msg(self, msg_id: str) -> bool:
        """Claim filter: does this replica's partition cover ``msg_id``?"""
        return shard_of(msg_id, self.cfg.spool_shards) in self._owned

    def _rescue_age_s(self) -> float:
        """Liveness failsafe horizon: a message this old is claimable (or
        requeueable) REGARDLESS of shard ownership.  Ownership is an
        optimization — atomic renames + fences make cross-partition claims
        safe — so a transient registry disagreement that leaves a shard
        unowned can stall work at most this long."""
        return max(5.0, 10.0 * self.cfg.stale_after_s)

    def peers(self) -> dict:
        """``GET /peers``: the replica registry view + this replica's
        identity — what peers poll to approximate global admission."""
        return {
            "replica_id": self.replica_id,
            "epoch": self.epoch,
            "process_id": self.identity["process_id"],
            "host": self.identity["host"],
            "evicted_hosts": sorted(self._evicted_hosts),
            "shards": self.cfg.spool_shards,
            "owned": sorted(self._owned),
            "fenced_claims": self._fenced_count,
            "draining": self._draining,
            "replicas": self.registry.peers(),
        }

    def live_claims(self) -> int:
        """Claims this replica currently holds (claimed or running)."""
        with self._records_lock:
            return len(self._lease_by_msg)

    def peer_admission_summaries(self) -> list[dict]:
        """Alive PEER replicas' admission summaries (excl. self) — the
        AdmissionController folds these into its global estimates."""
        return [p.get("admission", {}) | {"replica_id": p.get("replica_id")}
                for p in self.registry.peers(include_self=False)
                if p.get("alive") and isinstance(p.get("admission"), dict)]

    # ---------------------------------------------------------- dispatcher
    def _scan_pending(self, now: float) -> list[tuple[tuple, Path, dict]]:
        """Eligible pending messages with their admission sort key.  Only
        messages in OWNED shards are read at all — the shard filter works
        on the filename, so a replica never pays I/O for its peers'
        partitions."""
        if self._draining:
            return []                 # draining: claim nothing new, not
                                      # even orphan rescues — peers own it
        out = []
        with self._records_lock:
            inflight = dict(self._inflight_by_tenant)
        rescue_age = self._rescue_age_s()
        for p in sorted(self.root.glob("pending/*.json")):
            if shard_of(p.stem, self.cfg.spool_shards) not in self._owned:
                # orphan rescue: an unowned message aging past the failsafe
                # horizon gets claimed anyway (see _rescue_age_s)
                try:
                    if now - p.stat().st_mtime < rescue_age:
                        continue
                except FileNotFoundError:
                    continue
            try:
                msg = json.loads(p.read_text())
                if not isinstance(msg, dict):
                    msg = {}
            except FileNotFoundError:
                continue              # claimed by another scheduler mid-scan
            except (OSError, json.JSONDecodeError):
                # poison payload — still admitted; claim+run dead-letters it
                msg = {}
            svc = msg.get("service", {})
            if float(svc.get("next_retry_at", 0.0)) > now:
                continue              # backoff not elapsed yet
            tenant = str(msg.get("tenant", "default"))
            rank = _priority_rank(msg.get("priority", "normal"))
            published = float(msg.get("published_at", 0.0))
            key = (rank, inflight.get(tenant, 0), published, p.name)
            out.append((key, p, msg))
        out.sort(key=lambda t: t[0])
        return out

    def _claim(self, p: Path) -> Path | None:
        dst = self.root / "running" / p.name
        try:
            os.replace(p, dst)        # atomic claim (same as QueueConsumer)
            return dst
        except FileNotFoundError:
            return None               # another scheduler/daemon won the race

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                admitted = self._admit_one()
            except Exception:         # the dispatcher must never die
                logger.error("scheduler: dispatcher error", exc_info=True)
                admitted = False
            if not admitted:
                self._stop.wait(self.cfg.poll_interval_s)
        self._drain_handoff()
        self._drained.set()

    def _bump_claims(self, claimed: Path, msg: dict) -> dict:
        """Persist a per-message claim counter INTO the claimed file.  The
        handled-failure path persists ``service.attempts``; claims count the
        attempts that never got to be handled — a job that hard-crashes the
        process cycles claim → crash → requeue_stale without ever moving its
        attempt counter, and this is the evidence that breaks the cycle."""
        svc = dict(msg.get("service", {}))
        svc["claims"] = int(svc.get("claims", 0)) + 1
        # queue-wait evidence for offline analysis (scripts/load_sweep.py's
        # multi-replica mix reads it from drained messages)
        svc["claimed_at"] = time.time()
        svc["claimed_by"] = self.replica_id
        updated = {**msg, "service": svc}
        tmp = self.root / "pending" / f".{claimed.name}.tmp"
        try:
            tmp.write_text(json.dumps(updated, indent=2))
            os.replace(tmp, claimed)
        except OSError:
            logger.warning("scheduler: could not persist claim count for %s",
                           claimed.name, exc_info=True)
        return updated

    def _admit_one(self) -> bool:
        """Claim and hand off the single best eligible message, then return
        so the next admission re-scans with FRESH fairness keys (per-tenant
        in-flight counts move with every claim)."""
        for _key, p, msg in self._scan_pending(time.time()):
            if self._stop.is_set() or self._draining:
                return False
            claimed = self._claim(p)
            if claimed is None:
                continue              # another scheduler/daemon won the race
            msg_id = claimed.stem
            # the rename is the mutex; the lease is the fence.  Claiming
            # bumps the fence past any prior holder's token, so a ghost
            # replica that once held this message can no longer write.
            lease = self.leases.claim(msg_id)
            if isinstance(msg, dict) and msg:
                msg = self._bump_claims(claimed, msg)
                claims = int(msg.get("service", {}).get("claims", 0))
                if self.cfg.quarantine_after and \
                        claims > self.cfg.quarantine_after:
                    self._quarantine(claimed, msg, claims)
                    return True       # progress made; rescan immediately
            rec = self._record(msg_id)
            rec.ds_id = str(msg.get("ds_id", ""))
            rec.tenant = str(msg.get("tenant", "default"))
            rec.priority = msg.get("priority", "normal")
            rec.published_at = float(msg.get("published_at", 0.0))
            rec.attempts = int(msg.get("service", {}).get("attempts", 0))
            rec.state = "claimed"
            rec.claimed_at = time.time()
            ctx, _start = self._trace_ctx(msg_id, msg)
            rec.trace_id = ctx.trace_id
            tracing.event("claim", ctx=ctx, tenant=rec.tenant,
                          attempts=rec.attempts, replica=self.replica_id,
                          fence=lease.fence,
                          claims=int(msg.get("service", {}).get("claims", 0)))
            with self._records_lock:
                self._inflight_by_tenant[rec.tenant] = (
                    self._inflight_by_tenant.get(rec.tenant, 0) + 1)
                self._lease_by_msg[msg_id] = lease
            # blocks when all workers are busy and the hand-off buffer is
            # full — natural admission backpressure
            while not self._stop.is_set():
                try:
                    self._handoff.put((claimed, msg), timeout=0.2)
                    return True
                except _queue_mod.Full:
                    continue
            self._requeue_unstarted(claimed, msg)
            return False
        return False

    def _requeue_unstarted(self, claimed: Path, msg: dict) -> None:
        rec = self._record(claimed.stem)
        try:
            os.replace(claimed, self.root / "pending" / claimed.name)
        except FileNotFoundError:
            return
        clear_heartbeat(claimed)
        rec.state = "queued"
        with self._records_lock:
            t = rec.tenant
            self._inflight_by_tenant[t] = max(0, self._inflight_by_tenant.get(t, 1) - 1)
            lease = self._lease_by_msg.pop(claimed.stem, None)
        if lease is not None:
            # holder cleared, fence KEPT: the next claim bumps past it
            self.leases.release(lease)
        logger.info("scheduler: requeued claimed-but-unstarted %s", claimed.name)

    def _drain_handoff(self) -> None:
        """On shutdown: claimed-but-unstarted messages go back to pending/."""
        while True:
            try:
                claimed, msg = self._handoff.get_nowait()
            except _queue_mod.Empty:
                return
            self._requeue_unstarted(claimed, msg)

    # -------------------------------------------------------------- worker
    def _job_timeout_s(self, msg: dict) -> float:
        svc = msg.get("service", {}) if isinstance(msg, dict) else {}
        return float(svc.get("timeout_s", msg.get("timeout_s",
                                                  self.cfg.job_timeout_s)))

    def _job_max_attempts(self, msg: dict) -> int:
        svc = msg.get("service", {}) if isinstance(msg, dict) else {}
        return int(svc.get("max_attempts", msg.get("max_attempts",
                                                   self.retry.max_attempts)))

    def _job_devices(self, msg: dict) -> int:
        """Chips this job's lease asks for: per-submit ``devices`` (or
        ``service.devices``) overrides ``service.devices_per_job``; the
        result is clamped to [1, pool size] so an 8-chip submit on a 4-chip
        pool degrades to the whole pool instead of waiting forever."""
        svc = msg.get("service", {}) if isinstance(msg, dict) else {}
        raw = svc.get("devices", (msg or {}).get(
            "devices", self.cfg.devices_per_job)) if isinstance(msg, dict) \
            else self.cfg.devices_per_job
        try:
            n = int(raw)
        except (TypeError, ValueError):
            n = self.cfg.devices_per_job
        return max(1, min(n, self.device_pool.size))

    def _deadline_at(self, msg: dict) -> float:
        """Absolute deadline for a message: ``service.deadline_at`` (set by
        the API from ``deadline_s`` at submit) wins; a raw ``deadline_s`` is
        anchored at publish time.  0 = no deadline."""
        if isinstance(msg, dict) and msg.get("mode") == "stream":
            # open-ended jobs (ISSUE 19): an acquisition has no known
            # length, so a submit-pinned deadline is dead-on-arrival —
            # liveness is bounded by service.stream.idle_timeout_s instead
            return 0.0
        svc = msg.get("service", {}) if isinstance(msg, dict) else {}
        if svc.get("deadline_at"):
            return float(svc["deadline_at"])
        d = float(svc.get("deadline_s", msg.get("deadline_s", 0.0) or 0.0))
        if d > 0:
            return float(msg.get("published_at") or time.time()) + d
        return 0.0

    def _worker_loop(self) -> None:
        while True:
            try:
                claimed, msg = self._handoff.get(timeout=0.2)
            except _queue_mod.Empty:
                if self._stop.is_set() and self._drained.is_set():
                    return
                continue
            try:
                self._run_one(claimed, msg)
            except Exception:        # never kill a worker thread
                logger.error("scheduler: internal error running %s",
                             claimed.name, exc_info=True)

    def _run_one(self, claimed: Path, msg: dict) -> None:
        msg_id = claimed.stem
        rec = self._record(msg_id)
        hb = None
        lease = None
        attempt = None
        running_metric = False
        try:
            if rec.cancel_requested:
                # DELETE raced the dispatcher's claim: honor it before
                # spending an attempt (or the device) on a dead job
                self._terminal_cancelled(claimed, msg, rec,
                                         "cancelled by user before start")
                return
            deadline_at = self._deadline_at(msg)
            rec.deadline_at = deadline_at
            if deadline_at and time.time() >= deadline_at:
                # expired while queued: a late answer is a wrong answer
                self._terminal_deadline(claimed, msg, rec,
                                        "deadline exceeded before start")
                return
            if not self._fence_ok(rec, "attempt_start"):
                # claimed-but-unstarted work fenced away while this worker
                # was busy (or the process paused): never start the attempt
                return
            if not isinstance(msg, dict) or not msg:
                # poison message (unparseable JSON): dead-letter immediately,
                # keeping the raw payload as evidence (daemon contract)
                raw = ""
                try:
                    raw = claimed.read_text()
                    msg = json.loads(raw)
                    if not isinstance(msg, dict):
                        raise ValueError("message must be a JSON object")
                except (OSError, ValueError, json.JSONDecodeError) as exc:
                    self._dead_letter(claimed, {"raw": raw}, rec,
                                      f"poison message: {exc}", "")
                    return
            rec.state = "running"
            rec.started_at = time.time()
            rec.attempts += 1
            if self.metrics:
                self.m_running.inc()
                running_metric = True
            token = CancelToken(deadline_at or None)
            with self._records_lock:
                claim_lease = self._lease_by_msg.get(msg_id)
            # the claim heartbeat renews the fenced lease too; a renewal
            # that discovers the lease LOST (a peer takeover fenced us out)
            # cancels the attempt early — no point finishing work whose
            # commit will be rejected
            hb = ClaimHeartbeat(
                claimed, interval_s=self.cfg.heartbeat_interval_s,
                lease=claim_lease, lease_store=self.leases,
                on_lost=lambda: (
                    rec.state == "running"
                    and self._deliver_cancel(
                        token, rec, "fenced: lease lost to a peer takeover")))
            hb.start()
            root, _start = self._trace_ctx(msg_id, msg)
            rec.trace_id = root.trace_id
            if self.slo is not None:
                # _start is the submit timestamp (service.trace.start /
                # published_at), so queue wait covers the whole spool dwell
                self.slo.job_started(msg_id, _start, rec.started_at,
                                     rec.attempts)
            attempt_trace = root.child()
            # this attempt's chip lease: acquired INSIDE the callback
            # (SearchJob's device_hold seam / hold_cancellable), released by
            # its ``with`` exit — and unconditionally in the finally below,
            # so a crashed or abandoned attempt can never leak chips
            lease = self.device_pool.lease(self._job_devices(msg),
                                           msg_id=msg_id)
            ctx = JobContext(msg_id=msg_id, attempt=rec.attempts,
                             device_token=lease,
                             metrics=self.metrics, cancel=token,
                             trace=attempt_trace,
                             fence=(None if claim_lease is None else
                                    (lambda _l=claim_lease:
                                     self.leases.check(_l))),
                             set_partial=(lambda p, _r=rec:
                                          self._set_partial(_r, p)))
            attempt = _Attempt(self.callback, msg, ctx, self._cb_takes_ctx)
            with self._records_lock:
                self._live[msg_id] = (token, attempt)
            timeout_s = self._job_timeout_s(msg)
            if deadline_at:
                timeout_s = min(timeout_s, max(0.0, deadline_at - time.time()))
            t0 = time.perf_counter()
            attempt.start()
            if isinstance(msg, dict) and msg.get("mode") == "stream":
                # open-ended attempt (ISSUE 19): an acquisition's wall
                # clock is unknowable up front, so the flat per-attempt
                # timeout does not apply — liveness is owned by
                # stream.idle_timeout_s (raised inside the attempt) and
                # the progress-reset stall watchdog, either of which
                # cancels the token.  Once ANY cancel lands, an attempt
                # that fails to unwind within cancel_grace_s falls
                # through to the abandoned-thread handling below, same
                # as a timed-out batch attempt.
                while attempt.is_alive() and not token.cancelled():
                    attempt.join(timeout=0.5)
                if attempt.is_alive():
                    attempt.join(timeout=self.cfg.cancel_grace_s)
            else:
                attempt.join(timeout=timeout_s)
            timed_out = attempt.is_alive()
            abandoned = False
            if timed_out:
                # the abandoned-thread fix: deliver a cooperative cancel and
                # give the attempt a bounded grace to unwind — releasing the
                # device token and skipping the store — before the spool
                # moves happen
                reason = ("deadline exceeded mid-attempt"
                          if token.deadline_exceeded() else
                          f"timeout: attempt {rec.attempts} exceeded "
                          f"{timeout_s:.1f}s")
                self._deliver_cancel(token, rec, reason)
                attempt.join(timeout=self.cfg.cancel_grace_s)
                abandoned = attempt.is_alive()
                if abandoned and self.metrics:
                    self.m_abandoned.inc()
            dt = time.perf_counter() - t0
            # the attempt span: its body ran in the _Attempt thread (where
            # attempt_trace was ambient); the worker owns the measured
            # duration and therefore the emission
            tracing.emit_span(
                root, "attempt", ts=rec.started_at, dur=dt,
                span_id=attempt_trace.span_id, parent_id=root.span_id,
                attempt=rec.attempts, timed_out=bool(timed_out),
                abandoned=bool(abandoned))
            # the attempt is over (or abandoned): stop the claim heartbeat
            # BEFORE any terminal outcome, so an in-flight renewal can
            # never re-create the fenced lease file after _drop_lease
            # clears it (the outcome writes are fence-gated — the
            # heartbeat only informs staleness, and the write window is
            # far inside the staleness horizon)
            hb.stop()
            hb = None
            if self.metrics:
                self.m_duration.observe(dt)
            if self.admission is not None:
                self.admission.observe_latency(dt)
            if not timed_out and attempt.error is None:
                # clean completion — including one that outran a late cancel:
                # the work is done and stored, so "done" is the honest state
                self._finish(claimed, rec)
                return
            if timed_out and self.metrics and not token.deadline_exceeded():
                self.m_timeouts.inc()
            is_cancel_exc = isinstance(attempt.error, JobCancelledError)
            is_fence = isinstance(attempt.error, FenceRejectedError) or (
                token.cancelled()
                and str(token.reason or "").startswith("fenced"))
            if is_fence:
                # a peer fenced this claim out mid-attempt: every write is
                # forfeit — the message (and its spool file) belongs to the
                # takeover replica now
                self._note_fenced(rec, token.reason or str(attempt.error))
            elif isinstance(attempt.error, StreamIdleError):
                # the acquisition went silent past its idle timeout —
                # terminal like a deadline: retrying cannot conjure chunks
                self._terminal_cancelled(
                    claimed, msg, rec,
                    str(attempt.error) + (" (abandoned)" if abandoned else ""))
            elif token.deadline_exceeded() or \
                    isinstance(attempt.error, DeadlineExceededError):
                err = token.reason or str(attempt.error)
                self._terminal_deadline(
                    claimed, msg, rec,
                    err + (" (abandoned)" if abandoned else ""))
            elif rec.cancel_requested == "user":
                self._terminal_cancelled(
                    claimed, msg, rec,
                    (token.reason or "cancelled by user")
                    + (" (abandoned)" if abandoned else ""))
            elif is_cancel_exc and isinstance(msg, dict) \
                    and msg.get("mode") == "stream" \
                    and str(token.reason or "").startswith("drain"):
                # drain hand-off (ISSUE 19): the acquisition is alive and
                # its chunk log durable — republish immediately with no
                # backoff and no attempt burned, so a peer replica resumes
                # from the streaming checkpoint
                self._stream_handoff(claimed, msg, rec)
            elif timed_out or is_cancel_exc:
                # timeout / watchdog stall — a normal failure under the
                # retry policy (the next attempt may behave)
                err = token.reason or str(attempt.error) or "cancelled"
                if abandoned:
                    err += " (abandoned)"
                self._handle_failure(claimed, msg, rec, err, "")
            else:
                self._handle_failure(claimed, msg, rec,
                                     str(attempt.error), attempt.tb)
        finally:
            with self._records_lock:
                self._live.pop(msg_id, None)
            if lease is not None:
                if attempt is None or not attempt.is_alive():
                    # idempotent: normally already released by the callback's
                    # ``with`` exit; this is the cancel/crash backstop (pool
                    # invariant: a dead attempt never holds chips)
                    lease.release()
                elif lease.locked():
                    # abandoned zombie still computing: don't grant its
                    # chips to a second job mid-flight, but don't leak them
                    # forever either (the PR 7 leak) — a reaper reclaims
                    # the lease the moment the thread exits, or forcibly
                    # after the lease_reap_after_s TTL
                    logger.warning(
                        "scheduler: abandoned attempt for %s still holds "
                        "devices %s — reap on exit or after %.0fs",
                        msg_id, lease.devices, self.cfg.lease_reap_after_s)
                    self._watch_zombie(msg_id, lease, attempt)
                else:
                    lease.release()   # zombie never got a grant: deregister
            if hb is not None:
                hb.stop()
            if running_metric:
                self.m_running.dec()
            with self._records_lock:
                t = rec.tenant
                self._inflight_by_tenant[t] = max(
                    0, self._inflight_by_tenant.get(t, 1) - 1)

    def _watch_zombie(self, msg_id: str, lease, attempt) -> None:
        """Reclaim an abandoned attempt's chip lease (ISSUE 11 satellite —
        the PR 7 zombie-lease leak).  A per-zombie watcher joins the stuck
        thread: the lease is reaped the moment it exits, or forcibly after
        ``lease_reap_after_s`` (0 = wait for the thread forever).  Release
        is idempotent, so the zombie's own late ``with`` exit is safe."""
        ttl = self.cfg.lease_reap_after_s

        def _reap():
            attempt.join(timeout=ttl if ttl > 0 else None)
            forced = attempt.is_alive()
            if forced:
                logger.warning(
                    "scheduler: zombie attempt for %s outlived the %.0fs "
                    "lease TTL — force-reaping devices %s (the thread may "
                    "still touch them until it exits)",
                    msg_id, ttl, lease.devices)
            self.device_pool.reap(lease,
                                  reason="ttl" if forced else "exit")

        threading.Thread(target=_reap, daemon=True,
                         name=f"lease-reap-{msg_id}").start()

    # ------------------------------------------------------- cancellation
    def _deliver_cancel(self, token: CancelToken, rec: JobRecord,
                        reason: str) -> None:
        """The single seam every cancellation (timeout, deadline, user,
        watchdog) passes through on its way to the attempt's token."""
        failpoint(FP_CANCEL_DELIVER)
        delivered = token.cancel(reason)
        kind = ("deadline" if reason.startswith("deadline") else
                "stalled" if reason.startswith("stalled") else
                "fenced" if reason.startswith("fenced") else
                "host_evicted" if reason.startswith("host") else
                "drain" if reason.startswith("drain") else
                "user" if "user" in reason else "timeout")
        if delivered:
            with self._records_lock:
                hit = self._trace_roots.get(rec.msg_id)
            tracing.event("cancel", ctx=hit[0] if hit else None,
                          reason=reason, kind=kind)
        if delivered and self.metrics:
            if kind != "deadline":   # deadline counts once, at its terminal
                self.m_cancels.labels(reason=kind).inc()
        rec.error = reason

    def cancel(self, msg_id: str, reason: str = "cancelled by user") -> str:
        """``DELETE /jobs/<id>``.  Returns the disposition:

        - ``"cancelling"`` — a cancel was delivered to a live/claimed
          attempt; the job unwinds at its next cooperative checkpoint;
        - ``"cancelled"``  — the message was still queued and is now
          terminally cancelled (moved to ``failed/`` with the reason);
        - ``"terminal"``   — already done/failed/cancelled/quarantined;
        - ``"not_found"``  — unknown msg_id.
        """
        with self._records_lock:
            rec = self._records.get(msg_id)
            live = self._live.get(msg_id)
        if rec is not None and rec.state in TERMINAL_STATES:
            return "terminal"
        if live is not None:
            token, _attempt = live
            rec.cancel_requested = "user"
            self._deliver_cancel(token, rec, reason)
            return "cancelling"
        # queued (pending/retry_wait): terminally cancel by atomic rename —
        # losing the race to the dispatcher's claim degrades to the flag path
        src = self.root / "pending" / f"{msg_id}.json"
        dst = self.root / "failed" / f"{msg_id}.json"
        try:
            os.replace(src, dst)
        except FileNotFoundError:
            with self._records_lock:
                rec = self._records.get(msg_id)
                live = self._live.get(msg_id)
            if live is not None:
                token, _attempt = live
                rec.cancel_requested = "user"
                self._deliver_cancel(token, rec, reason)
                return "cancelling"
            if rec is not None and rec.state in ("claimed", "queued",
                                                 "running", "retry_wait"):
                # claimed-but-unstarted (hand-off buffer): the worker honors
                # the flag before starting the attempt
                rec.cancel_requested = "user"
                return "cancelling"
            return "not_found"
        try:
            msg = json.loads(dst.read_text())
            if not isinstance(msg, dict):
                msg = {}
        except (OSError, json.JSONDecodeError):
            msg = {}
        msg["error"] = reason
        msg["cancelled"] = True
        dst.write_text(json.dumps(msg, indent=2))
        self.leases.clear(msg_id)
        rec = self._record(msg_id)
        rec.state = "cancelled"
        rec.error = reason
        rec.finished_at = time.time()
        ctx, _start = self._trace_ctx(msg_id, msg)
        rec.trace_id = ctx.trace_id
        tracing.event("cancel", ctx=ctx, reason=reason, kind="user")
        self._close_trace(rec, "cancelled")
        self._note_terminal(rec)
        if self.metrics:
            self.m_jobs.labels(state="cancelled").inc()
            self.m_cancels.labels(reason="user").inc()
        logger.info("scheduler: %s cancelled while queued", msg_id)
        return "cancelled"

    def _watchdog_loop(self) -> None:
        """Cancel attempts whose per-phase progress heartbeat stalled —
        ``CancelToken.check()`` doubles as the progress touch, so any job
        that keeps reaching phase/checkpoint boundaries stays alive."""
        while not self._stop.wait(self.cfg.watchdog_interval_s):
            now = time.time()
            with self._records_lock:
                live = [(mid, tok) for mid, (tok, _a) in self._live.items()]
            for msg_id, token in live:
                if token.cancelled():
                    continue
                stalled = now - token.last_progress
                if stalled >= self.cfg.watchdog_stall_s:
                    rec = self._record(msg_id)
                    logger.warning(
                        "scheduler: watchdog cancelling %s — no progress "
                        "for %.1fs (last phase %r)", msg_id, stalled,
                        token.progress_phase)
                    self._deliver_cancel(
                        token, rec,
                        f"stalled: no progress for {stalled:.1f}s "
                        f"(last phase {token.progress_phase or 'unknown'})")

    # ----------------------------------------------------------- fencing
    def _fence_ok(self, rec: JobRecord, what: str) -> bool:
        """The write gate (ISSUE 8): every spool-mutating outcome calls
        this first.  False = a peer fenced this claim out; the caller must
        abandon ALL writes (the bookkeeping is already done here)."""
        with self._records_lock:
            lease = self._lease_by_msg.get(rec.msg_id)
        if lease is None:
            return True               # legacy claim (no lease recorded)
        try:
            self.leases.check(lease)
            return True
        except FenceRejectedError as exc:
            self._note_fenced(rec, f"{what}: {exc}")
            return False

    def _note_fenced(self, rec: JobRecord, why: str) -> None:
        """A peer replica fenced this claim out.  Locally the claim is
        finished business — free the admission slot, count it for
        ``wait_for_terminal`` waiters, drop the trace root (the takeover
        replica continues and closes the SAME trace) — but the spool,
        results, and ledger are NOT touched: they belong to the new owner."""
        why = str(why)
        with self._records_lock:
            self._lease_by_msg.pop(rec.msg_id, None)
            self._trace_roots.pop(rec.msg_id, None)
            self._fenced_count += 1
            self._terminal_count += 1
        rec.state = "queued"          # from this replica's view: back in line
        rec.error = why if why.startswith("fenced") else f"fenced: {why}"
        tracing.event("fence_reject", replica=self.replica_id,
                      msg_id=rec.msg_id, why=why[:300])
        if self.metrics:
            self.m_fenced_claims.labels(replica=self.replica_id).inc()
        if self.admission is not None:
            self.admission.note_terminal(rec.msg_id)
        logger.warning("scheduler[%s]: claim on %s fenced out — abandoning "
                       "all writes (%s)", self.replica_id, rec.msg_id, why)

    def _drop_lease(self, msg_id: str, terminal: bool) -> None:
        with self._records_lock:
            lease = self._lease_by_msg.pop(msg_id, None)
        if terminal:
            self.leases.clear(msg_id)
        elif lease is not None:
            self.leases.release(lease)

    # ----------------------------------------------------------- outcomes
    def _finish(self, claimed: Path, rec: JobRecord) -> None:
        if not self._fence_ok(rec, "complete"):
            return
        # same seam as the daemon consumer's: job succeeded, message not yet
        # in done/ — a crash here must reprocess idempotently, never lose it
        failpoint(FP_COMPLETE, path=claimed)
        os.replace(claimed, self.root / "done" / claimed.name)
        clear_heartbeat(claimed)
        self._drop_lease(rec.msg_id, terminal=True)
        rec.state = "done"
        rec.finished_at = time.time()
        self._close_trace(rec, "done")
        self._note_terminal(rec)
        if self.metrics:
            self.m_jobs.labels(state="done").inc()
        logger.info("scheduler: %s done (attempt %d)", claimed.name, rec.attempts)

    def _handle_failure(self, claimed: Path, msg: dict, rec: JobRecord,
                        error: str, tb: str) -> None:
        if not self._fence_ok(rec, "retry_republish"):
            return
        max_attempts = self._job_max_attempts(msg)
        rec.error = error
        if rec.attempts >= max_attempts:
            self._dead_letter(claimed, msg, rec, error, tb)
            return
        delay = self.retry.backoff_s(rec.attempts)
        rec.state = "retry_wait"
        rec.next_retry_at = time.time() + delay
        with self._records_lock:
            hit = self._trace_roots.get(rec.msg_id)
        tracing.event("retry", ctx=hit[0] if hit else None,
                      attempt=rec.attempts, max_attempts=max_attempts,
                      delay_s=round(delay, 3), error=error[:500])
        if self.metrics:
            self.m_retries.inc()
            self.m_backoff.observe(delay)
        # persist retry state INTO the message, then atomically republish:
        # a scheduler crash here leaves either the old running/ copy (crash
        # recovery requeues it) or the updated pending/ copy — never neither
        updated = dict(msg)
        svc = dict(updated.get("service", {}))
        svc["attempts"] = rec.attempts
        svc["next_retry_at"] = rec.next_retry_at
        svc["last_error"] = error
        updated["service"] = svc
        tmp = self.root / "pending" / f".{claimed.name}.tmp"
        tmp.write_text(json.dumps(updated, indent=2))
        failpoint(FP_RETRY_PUBLISH, path=tmp)
        os.replace(tmp, self.root / "pending" / claimed.name)
        claimed.unlink()
        clear_heartbeat(claimed)
        self._drop_lease(rec.msg_id, terminal=False)
        logger.warning(
            "scheduler: %s attempt %d/%d failed (%s); retry in %.2fs",
            claimed.name, rec.attempts, max_attempts, error, delay)

    def _stream_handoff(self, claimed: Path, msg: dict, rec: JobRecord) -> None:
        """Drain hand-off of a live acquisition (ISSUE 19): the unwound
        stream attempt's message goes straight back to pending/ so a peer
        replica (this one stopped claiming) picks it up and resumes from
        the streaming checkpoint — the chunk log + manifest + search
        checkpoint shards, all durable and replica-agnostic.  Unlike a
        retry: no backoff (the acquisition is live NOW) and no attempt
        burned (the hand-off is controller-initiated, not a failure)."""
        if not self._fence_ok(rec, "stream_handoff"):
            return
        rec.attempts = max(0, rec.attempts - 1)
        rec.state = "queued"
        rec.next_retry_at = 0.0
        updated = dict(msg)
        svc = dict(updated.get("service", {}))
        svc["attempts"] = rec.attempts
        svc.pop("next_retry_at", None)
        svc["last_error"] = rec.error or "drain: stream hand-off"
        updated["service"] = svc
        tmp = self.root / "pending" / f".{claimed.name}.tmp"
        tmp.write_text(json.dumps(updated, indent=2))
        failpoint(FP_RETRY_PUBLISH, path=tmp)
        os.replace(tmp, self.root / "pending" / claimed.name)
        try:
            claimed.unlink()
        except FileNotFoundError:
            pass
        clear_heartbeat(claimed)
        self._drop_lease(rec.msg_id, terminal=False)
        record_recovery("stream.drain_handoff")
        with self._records_lock:
            hit = self._trace_roots.get(rec.msg_id)
        tracing.event("stream.handoff", ctx=hit[0] if hit else None,
                      replica=self.replica_id)
        logger.info("scheduler: %s stream acquisition handed off to a peer "
                    "(drain)", claimed.name)

    def _cancel_live_streams(self, reason: str) -> None:
        """Deliver a drain cancel to every live ``mode=stream`` attempt —
        an open-ended acquisition never finishes on its own, so a draining
        replica must actively unwind it into the hand-off path instead of
        waiting out drain_timeout_s against an instrument."""
        with self._records_lock:
            live = [(mid, tok, att) for mid, (tok, att) in self._live.items()]
        for msg_id, token, att in live:
            m = getattr(att, "msg", None)
            if isinstance(m, dict) and m.get("mode") == "stream" \
                    and not token.cancelled():
                self._deliver_cancel(token, self._record(msg_id), reason)

    def _dead_letter(self, claimed: Path, msg: dict, rec: JobRecord,
                     error: str, tb: str) -> None:
        if not self._fence_ok(rec, "dead_letter"):
            return
        failed = dict(msg) if msg else {}
        failed["error"] = error
        if tb:
            failed["traceback"] = tb
        failed["attempts"] = rec.attempts
        (self.root / "failed" / claimed.name).write_text(
            json.dumps(failed, indent=2))
        try:
            claimed.unlink()
        except FileNotFoundError:
            pass
        clear_heartbeat(claimed)
        self._drop_lease(rec.msg_id, terminal=True)
        rec.state = "failed"
        rec.error = error
        rec.finished_at = time.time()
        self._close_trace(rec, "failed")
        self._note_terminal(rec)
        if self.metrics:
            self.m_jobs.labels(state="failed").inc()
        logger.error("scheduler: %s dead-lettered after %d attempt(s): %s",
                     claimed.name, rec.attempts, error)

    def _terminal_cancelled(self, claimed: Path, msg: dict, rec: JobRecord,
                            error: str) -> None:
        """User cancel honored: the message is terminal (never retried),
        filed under failed/ with ``cancelled: true`` for the audit trail."""
        if not self._fence_ok(rec, "terminal_cancel"):
            return
        failed = dict(msg) if isinstance(msg, dict) and msg else {}
        failed["error"] = error
        failed["cancelled"] = True
        failed["attempts"] = rec.attempts
        (self.root / "failed" / claimed.name).write_text(
            json.dumps(failed, indent=2))
        try:
            claimed.unlink()
        except FileNotFoundError:
            pass
        clear_heartbeat(claimed)
        self._drop_lease(rec.msg_id, terminal=True)
        rec.state = "cancelled"
        rec.error = error
        rec.finished_at = time.time()
        self._close_trace(rec, "cancelled")
        self._note_terminal(rec)
        if self.metrics:
            self.m_jobs.labels(state="cancelled").inc()
        logger.info("scheduler: %s cancelled (%s)", claimed.name, error)

    def _terminal_deadline(self, claimed: Path, msg: dict, rec: JobRecord,
                           error: str) -> None:
        """Deadline exceeded: terminal — retrying a job whose answer is
        already too late only wastes the device."""
        if self.metrics:
            self.m_cancels.labels(reason="deadline").inc()
        with self._records_lock:
            hit = self._trace_roots.get(rec.msg_id)
        tracing.event("deadline", ctx=hit[0] if hit else None,
                      deadline_at=rec.deadline_at, error=error[:500])
        self._dead_letter(claimed, msg if isinstance(msg, dict) else {},
                          rec, error, "")

    def _quarantine(self, claimed: Path, msg: dict, claims: int) -> None:
        """A message claimed ``claims`` times without ever reaching a
        terminal outcome is crash-looping the worker process (a handled
        failure would have dead-lettered it via max_attempts).  Park it in
        quarantine/ with the accumulated evidence instead of cycling
        through requeue forever."""
        rec = self._record(claimed.stem)
        rec.ds_id = str(msg.get("ds_id", ""))
        rec.tenant = str(msg.get("tenant", "default"))
        reason = (f"quarantined after {claims} claims without a terminal "
                  f"outcome (quarantine_after="
                  f"{self.cfg.quarantine_after}); suspected crash-looper")
        q = dict(msg)
        q["quarantined_at"] = time.time()
        q["quarantine_reason"] = reason
        (self.root / "quarantine" / claimed.name).write_text(
            json.dumps(q, indent=2))
        claimed.unlink()
        clear_heartbeat(claimed)
        self._drop_lease(claimed.stem, terminal=True)
        rec.state = "quarantined"
        rec.error = reason
        rec.finished_at = time.time()
        ctx, _start = self._trace_ctx(claimed.stem, msg)
        rec.trace_id = ctx.trace_id
        tracing.event("quarantine", ctx=ctx, claims=claims)
        self._close_trace(rec, "quarantined")
        self._note_terminal(rec)
        if self.metrics:
            self.m_jobs.labels(state="quarantined").inc()
            self.m_quarantined.inc()
        logger.error("scheduler: %s %s", claimed.name, reason)

    # ---------------------------------------------------------- replication
    def _beat_summary(self) -> dict:
        """What this replica gossips in its registry heartbeat: owned
        shards + replica-local admission state, so peers (and ``GET
        /peers``) can approximate global quotas and shed decisions."""
        s: dict = {"owned": sorted(self._owned), "workers": self.cfg.workers,
                   "fenced_claims": self._fenced_count,
                   "draining": self._draining,
                   # pod identity (ISSUE 17): the host watchdog groups
                   # peers by process_id to detect whole-host death
                   "process_id": self.identity["process_id"],
                   "host": self.identity["host"]}
        if self.admission is not None:
            s["admission"] = self.admission.stats()
        # fleet-view gossip (ISSUE 20): admin address / pool occupancy /
        # stream in-flight suppliers, each exception-safe — a broken
        # supplier must not stop the heartbeat (losing the beat would look
        # like replica death and trigger takeover)
        for key, fn in self._gossip.items():
            try:
                s[key] = fn() if callable(fn) else fn
            except Exception:
                logger.warning("scheduler: gossip supplier %r failed", key,
                               exc_info=True)
        return s

    def add_gossip(self, key: str, supplier) -> None:
        """Register a heartbeat gossip field: ``supplier()`` (or a constant)
        is folded into every ``_beat_summary``.  Wire-time only."""
        self._gossip[key] = supplier

    # -------------------------------------------------------- host watchdog
    def _host_watchdog(self, now: float) -> None:
        """Missed process heartbeats → whole-host eviction → mesh shrink
        (ISSUE 17 tentpole).  Every pod process heartbeats the shared
        registry with its ``process_id``; a process whose EVERY beat is
        older than ``host_stale_after_s`` is declared dead.  Its chip range
        (process ``i`` ↔ pool host domain ``i``) is fenced in one unit
        (``HealthTracker.evict_host`` composing with PR 14 quarantine),
        and in-flight attempts holding any of those chips are cancelled
        into the normal retry path — the re-leased attempt resumes from
        checkpoint on the shrunken cross-host mesh.  A returning process
        (fresh beats again) zeroes its chips' re-probe cooldown so the
        half-open pass readmits them immediately."""
        health = self.device_pool.health
        groups = self.registry.peers_by_process()
        beats_ok = True
        try:
            failpoint(FP_HOST_HEARTBEAT)
        except Exception as exc:
            beats_ok = False
            logger.warning("host watchdog: heartbeat read failed (%s) — "
                           "treating remote process beats as missed", exc)
        my_pid = self.identity["process_id"]
        stale = self.cfg.host_stale_after_s
        if self.metrics:
            self.m_pod_processes.set(len(groups) or 1)
        for pid, members in sorted(groups.items()):
            fresh = pid == my_pid or (beats_ok and any(
                float(m.get("age_s", float("inf"))) <= stale
                for m in members))
            host_name = next((str(m.get("host")) for m in members
                              if m.get("host")), f"process-{pid}")
            if self.metrics:
                self.m_pod_process_up.labels(process=str(pid)).set(
                    1 if fresh else 0)
            if not fresh and pid not in self._evicted_hosts and \
                    0 <= pid < health.hosts:
                self._evict_host(pid, host_name, members)
            elif fresh and pid in self._evicted_hosts:
                self._evicted_hosts.discard(pid)
                made_due = health.host_returned(pid)
                tracing.event("host_return", host=pid, name=host_name,
                              chips=made_due)
                logger.warning(
                    "host watchdog: host %s (process %d) is heartbeating "
                    "again — %d chip(s) made due for half-open re-probe",
                    host_name, pid, len(made_due))

    def _evict_host(self, pid: int, host_name: str, members: list) -> None:
        """Fence a dead process's whole chip range and cancel the attempts
        holding any of it (they retry on the survivors)."""
        health = self.device_pool.health
        ages = [float(m.get("age_s", 0.0)) for m in members]
        reason = (f"host {host_name} (process {pid}) missed heartbeats "
                  f"for {min(ages) if ages else float('inf'):.1f}s")
        chips = health.evict_host(pid, reason)
        self._evicted_hosts.add(pid)
        record_recovery("host.evict")
        tracing.event("host_evict", host=pid, name=host_name, chips=chips)
        if self.metrics:
            self.m_pod_host_evictions.inc()
        logger.error("host watchdog: EVICTED host %s (process %d) — "
                     "chip(s) %s fenced", host_name, pid, chips)
        if not chips:
            return
        lost = set(chips)
        with self._records_lock:
            live = list(self._live.items())
        for msg_id, (token, attempt) in live:
            if token.cancelled():
                continue
            lease = getattr(attempt.ctx, "device_token", None)
            held = set(getattr(lease, "devices", ()) or ())
            if held & lost:
                rec = self._record(msg_id)
                self._deliver_cancel(
                    token, rec,
                    f"host {host_name} evicted: lease chip(s) "
                    f"{sorted(held & lost)} lost mid-attempt")

    # --------------------------------------------------------------- drain
    def _begin_drain(self) -> None:
        """A drain request landed (fleet controller scale-down, or an
        operator touching the registry sentinel): stop claiming — peers
        adopt the shards via ``registry.active()`` — and let in-flight
        work finish or unwind under its normal failure policy."""
        self._draining = True
        # victim-killed-mid-drain seam: a crash here leaves claims in
        # running/ with fresh-then-stale heartbeats; peers fence + requeue
        # them and complete the work exactly once
        failpoint(FP_DRAIN_HANDOFF)
        self._recompute_owned()
        # live acquisitions hand off NOW — they would otherwise outlive
        # the drain window waiting on the instrument (ISSUE 19)
        self._cancel_live_streams(
            "drain: handing off live acquisition to a peer")
        tracing.event("drain.begin", replica=self.replica_id,
                      claims=self.live_claims())
        logger.info("replica %s: drain requested — releasing shard "
                    "ownership, %d claim(s) in flight",
                    self.replica_id, self.live_claims())

    def _drain_idle(self) -> bool:
        """True once nothing is claimed, running, or buffered — every
        in-flight message reached a terminal outcome, was requeued, or was
        fenced away."""
        with self._records_lock:
            if self._lease_by_msg or self._live:
                return False
        return self._handoff.empty()

    def _ack_drain(self) -> None:
        failpoint(FP_RETIRE_ACK)
        self.registry.ack_drain()
        record_recovery("fleet.drain_complete")
        self._drain_done.set()
        tracing.event("drain.ack", replica=self.replica_id)
        logger.info("replica %s: drain complete — acked, ready to retire",
                    self.replica_id)

    def drain_complete(self) -> bool:
        """True once this replica drained and acked; the serve loop (and
        the bare replica harness) exits and shuts down on this."""
        return self._drain_done.is_set()

    def _takeover_scan(self) -> None:
        """One takeover pass: recompute shard ownership from the live
        replica set, fence + requeue stale claims in owned shards, and
        sweep orphaned tmp/lease debris — scoped so a LIVE peer's in-flight
        work in shards we don't own is never reaped."""
        failpoint(FP_TAKEOVER_SCAN)
        owned = self._recompute_owned()
        if self._draining:
            return                    # nothing owned; adopt no peer work
        n = self._requeue_stale_owned(self.cfg.stale_after_s)
        if n:
            logger.info("replica %s: takeover requeued %d stale claim(s)",
                        self.replica_id, n)
        sweep_orphan_tmp(self.root, max_age_s=self.cfg.stale_after_s,
                         shards=owned, total_shards=self.cfg.spool_shards)
        self.leases.sweep_orphans(self.root,
                                  max_age_s=self.cfg.stale_after_s)

    def _replica_loop(self) -> None:
        """Registry heartbeat + takeover scan in one thread.  Both fire
        immediately on start (a restarted replica must re-announce itself
        and adopt its shards before the first claim cycle), then on their
        own cadences.  A beat/scan fault never kills the loop."""
        next_beat = 0.0
        next_scan = 0.0
        next_gc = 0.0
        next_host = 0.0
        gc_interval = (self.resources.cfg.gc_interval_s
                       if self.resources is not None else float("inf"))
        hw_interval = (self.cfg.host_watchdog_interval_s
                       if self.cfg.host_watchdog_interval_s > 0
                       else float("inf"))
        tick = max(0.02, min(self.cfg.replica_heartbeat_interval_s,
                             self.cfg.takeover_interval_s,
                             gc_interval, hw_interval) / 4.0)
        while not self._stop.is_set():
            now = time.time()
            # zero-loss drain (ISSUE 11): notice the request once, then ack
            # as soon as every in-flight claim resolved.  Heartbeats keep
            # going while draining so peers never fence live work.
            try:
                if not self._draining and self.registry.drain_requested():
                    self._begin_drain()
                if self._draining and not self._drain_done.is_set() and \
                        self._drain_idle():
                    self._ack_drain()
            except OSError:
                logger.warning("replica %s: drain check failed",
                               self.replica_id, exc_info=True)
            if now >= next_beat:
                try:
                    self.registry.beat(summary=self._beat_summary())
                    if self.metrics:
                        self.m_replica_beats.labels(
                            replica=self.replica_id).inc()
                except OSError:
                    logger.warning("replica %s: heartbeat write failed",
                                   self.replica_id, exc_info=True)
                next_beat = now + self.cfg.replica_heartbeat_interval_s
            if now >= next_scan:
                try:
                    self._takeover_scan()
                except OSError:
                    logger.warning("replica %s: takeover scan failed",
                                   self.replica_id, exc_info=True)
                next_scan = now + self.cfg.takeover_interval_s
            if hw_interval != float("inf") and now >= next_host:
                # pod host watchdog (ISSUE 17): missed process beats →
                # whole-host eviction; a watchdog fault never kills the loop
                try:
                    self._host_watchdog(now)
                except OSError:
                    logger.warning("replica %s: host watchdog scan failed",
                                   self.replica_id, exc_info=True)
                next_host = now + hw_interval
            if self.resources is not None and now >= next_gc:
                # bounded-retention GC (ISSUE 10): shard-scoped like the
                # takeover sweeps above — a GC fault never kills the loop
                try:
                    self.resources.gc_tick(owns_msg=self.owns_msg)
                except OSError:
                    logger.warning("replica %s: resource GC tick failed",
                                   self.replica_id, exc_info=True)
                next_gc = now + gc_interval
            self._stop.wait(tick)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        if self._started:
            raise RuntimeError("scheduler already started")
        self._started = True
        self._recompute_owned()
        # crash recovery first: claims with dead heartbeats in OWNED shards
        # are fenced + returned to pending
        n = self.requeue_stale()
        if n:
            logger.info("scheduler: requeued %d stale claim(s) on startup", n)
        # orphaned publish/retry tmp files older than the staleness horizon
        # can have no live writer — the crash that leaked them also killed
        # it; scoped to owned shards so peers' in-flight tmps survive
        sweep_orphan_tmp(self.root, max_age_s=self.cfg.stale_after_s,
                         shards=self._owned,
                         total_shards=self.cfg.spool_shards)
        r = threading.Thread(target=self._replica_loop, daemon=True,
                             name=f"sched-replica-{self.replica_id}")
        r.start()
        self._threads.append(r)
        d = threading.Thread(target=self._dispatch_loop, daemon=True,
                             name="sched-dispatch")
        d.start()
        self._threads.append(d)
        for i in range(self.cfg.workers):
            w = threading.Thread(target=self._worker_loop, daemon=True,
                                 name=f"sched-worker-{i}")
            w.start()
            self._threads.append(w)
        if self.cfg.watchdog_stall_s > 0:
            wd = threading.Thread(target=self._watchdog_loop, daemon=True,
                                  name="sched-watchdog")
            wd.start()
            self._threads.append(wd)
        logger.info("scheduler: started (%d workers, queue %s, replica %s "
                    "epoch %d, %d/%d shards)",
                    self.cfg.workers, self.root, self.replica_id, self.epoch,
                    len(self._owned), self.cfg.spool_shards)

    def requeue_stale(self) -> int:
        """Heartbeat-aware crash recovery, scoped to OWNED shards and
        fence-bumped (ISSUE 8): dead claims return to pending/ with their
        previous holder's token invalidated first."""
        return self._requeue_stale_owned(self.cfg.stale_after_s)

    def _requeue_stale_owned(self, max_age_s: float) -> int:
        from ..engine.daemon import heartbeat_path

        n = 0
        now = time.time()
        rescue_age = self._rescue_age_s()
        for p in self.root.glob("running/*.json"):
            msg_id = p.stem
            in_owned = shard_of(msg_id, self.cfg.spool_shards) in self._owned
            with self._records_lock:
                if msg_id in self._lease_by_msg:
                    continue          # our own live claim
            hb = heartbeat_path(p)
            try:
                ref = hb.stat().st_mtime if hb.exists() else p.stat().st_mtime
            except FileNotFoundError:
                continue              # finished between glob and stat
            # freshest sign of life: claim heartbeat OR lease renewal
            ref = max(ref, self.leases.renewed_at(msg_id))
            if now - ref < max_age_s:
                continue
            if not in_owned and now - ref < rescue_age:
                continue              # a peer's partition — not ours to reap
                                      # unless it aged past the failsafe
            # fence FIRST, move second: any write the dead (or merely
            # silent) holder tries after this bump is rejected, so the
            # requeue can never produce a double completion
            self.leases.bump(msg_id)
            try:
                os.replace(p, self.root / "pending" / p.name)
            except FileNotFoundError:
                continue              # the holder finished in the window
            clear_heartbeat(p)
            n += 1
            if self.metrics:
                self.m_takeover_requeues.labels(
                    replica=self.replica_id).inc()
        if n:
            record_recovery("replica.takeover_requeue", n)
        return n

    def shutdown(self, timeout_s: float | None = None) -> bool:
        """Graceful drain: stop admission, requeue claimed-but-unstarted,
        wait for running jobs.  Returns True when fully drained in time."""
        timeout_s = self.cfg.drain_timeout_s if timeout_s is None else timeout_s
        self._stop.set()
        # a live acquisition waits on the instrument indefinitely: unwind
        # it into the hand-off path so the worker join below can finish
        self._cancel_live_streams("drain: service shutting down")
        deadline = time.time() + timeout_s
        ok = True
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.time()))
            ok = ok and not t.is_alive()
        # belt and braces: anything still claimed (worker died mid-move)
        self._drain_handoff()
        # drop out of the registry so peers adopt our shards immediately
        # instead of waiting out the staleness horizon
        self.registry.retire()
        # detach the fault listener only if it is still ours — a newer
        # scheduler's registration (tests build many per process) survives
        faults.clear_fault_listener(self.device_pool.health)
        if self.metrics:
            self.m_replica_up.labels(replica=self.replica_id).set(0)
        logger.info("scheduler: shutdown %s", "clean" if ok else "TIMED OUT")
        return ok

    def wait_for_terminal(self, n: int, timeout_s: float = 60.0) -> bool:
        """Block until ``n`` jobs reached a terminal state (tests/smoke)."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if self._terminal_count >= n:
                return True
            time.sleep(0.02)
        return self._terminal_count >= n
