"""Spool partitioning, fenced lease claims, and the replica registry
(ISSUE 8 tentpole — the multi-owner protocol under ``service/scheduler.py``).

The reference METASPACE engine survived worker loss because Spark re-ran
lost partitions and RabbitMQ redelivered unacked messages; our file spool
had exactly one scheduler process, so one crash stalled every queued
dataset.  This module is the shared-nothing replacement:

- **shards** — the spool is logically partitioned into ``P`` shards by
  ``shard_of(msg_id) = crc32(msg_id) % P``.  The on-disk layout is
  unchanged (``pending/*.json`` etc. — every existing tool still works);
  partitioning is a *claim filter*: a replica only claims messages in
  shards it owns, so N replicas drain one spool without scanning each
  other's work.

- **ownership** — rendezvous (highest-random-weight) hashing of
  ``(shard, replica_id)`` over the ALIVE replica set.  Every replica
  computes the same assignment from the same inputs; when a replica's
  heartbeat lapses it drops out of the alive set and its shards
  redistribute over the survivors with minimal movement — no coordinator,
  no election.  Ownership is an *optimization*, not the safety argument:
  two replicas that transiently both believe they own a shard are
  arbitrated by the atomic claim rename, and stale writers by fences.

- **fenced leases** — every claim persists an epoch-numbered lease in
  ``<queue-root>/leases/<msg_id>.json``: ``(holder, epoch, fence)``.  The
  fence is a per-message monotonic token bumped on every (re)claim AND on
  every takeover requeue, so a replica that claimed a message, went
  silent past the staleness horizon, and then woke up fails its fence
  check — its complete/requeue/ledger-commit writes are rejected
  (``FenceRejectedError``) while the takeover replica's succeed.  This is
  what prevents split-brain double-completion.  The residual TOCTOU
  window between a passing check and the spool rename is closed by the
  rename itself: exactly one of (stale holder's move, fencer's move) can
  win, because the source path only exists once.

- **replica registry** — ``<queue-root>/replicas/<replica_id>.json``
  heartbeat files carry ``(epoch, beat time, shards owned, admission
  summary)``.  Replicas poll the registry (and ``GET /peers`` serves it)
  to approximate global tenant quotas and shed decisions with
  replica-local admission state.

Failpoints (docs/RECOVERY.md): ``lease.renew`` (a renewal I/O fault must
not kill the claim), ``lease.fence_reject`` (armed, the next fence check
behaves as if a peer fenced this holder out — the abort path is exercised
without needing a real race), ``replica.heartbeat`` (a beat-write fault
must not kill the replica), ``takeover.scan`` (a crash inside the
takeover scan must leave a recoverable spool).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import zlib
from dataclasses import dataclass
from pathlib import Path

from ..utils.failpoints import failpoint, record_recovery, register_failpoint
from ..utils.logger import logger

FP_LEASE_RENEW = register_failpoint(
    "lease.renew", "inside a claim's lease renewal write (I/O error)")
FP_FENCE_REJECT = register_failpoint(
    "lease.fence_reject",
    "inside a fence check; armed, the holder behaves as fenced out by a peer")
FP_REPLICA_HEARTBEAT = register_failpoint(
    "replica.heartbeat", "inside a replica registry beat write (I/O error)")
FP_TAKEOVER_SCAN = register_failpoint(
    "takeover.scan", "at the top of a replica's takeover/orphan scan pass")


class FenceRejectedError(RuntimeError):
    """A stale replica's write was rejected by the fence protocol: another
    replica bumped this message's fence (takeover requeue or re-claim)
    after this holder's lease went stale.  The holder must abandon ALL
    writes for the claim — spool moves, retry republish, result store,
    ledger commit — the message now belongs to someone else."""


# ------------------------------------------------------------------ shards
def shard_of(msg_id: str, total_shards: int) -> int:
    """Stable shard of a message id (crc32 — cheap enough to call per
    directory entry without reading the file)."""
    if total_shards <= 1:
        return 0
    return zlib.crc32(msg_id.encode()) % total_shards


def owned_shards(replica_id: str, alive: set[str] | list[str],
                 total_shards: int) -> set[int]:
    """Shards ``replica_id`` owns under rendezvous hashing over ``alive``
    (which must include ``replica_id`` itself).  Deterministic: every
    replica computes the same assignment from the same alive set."""
    members = sorted(set(alive) | {replica_id})
    if len(members) == 1:
        return set(range(max(1, total_shards)))
    out = set()
    for s in range(max(1, total_shards)):
        best = max(members, key=lambda r: _rendezvous_weight(s, r))
        if best == replica_id:
            out.add(s)
    return out


def _rendezvous_weight(shard: int, replica_id: str) -> int:
    h = hashlib.blake2b(f"{shard}:{replica_id}".encode(),
                        digest_size=8).digest()
    return int.from_bytes(h, "big")


# ------------------------------------------------------------------ leases
@dataclass
class Lease:
    """One replica's claim on one message: the fence token triple the
    holder presents at every write seam."""

    msg_id: str
    holder: str
    epoch: int
    fence: int
    acquired_at: float = 0.0


class LeaseStore:
    """Fencing-token lease files under ``<queue-root>/leases/``.

    Writes are tmp+``os.replace`` atomic.  The fence counter NEVER resets
    while a message is live: release (between attempts) clears the holder
    but keeps the fence, so a ghost holder from an earlier claim can never
    present a passing token again.  ``clear`` (terminal outcomes) removes
    the file — a missing lease also fails every check."""

    def __init__(self, queue_root: str | Path, replica_id: str,
                 epoch: int = 0, metrics=None):
        self.dir = Path(queue_root) / "leases"
        self.dir.mkdir(parents=True, exist_ok=True)
        self.replica_id = replica_id
        self.epoch = epoch
        self._m_rejects = None
        if metrics is not None:
            self.attach_metrics(metrics)

    def attach_metrics(self, m) -> None:
        self._m_rejects = m.counter(
            "sm_replica_fence_rejections_total",
            "Writes rejected because a peer fenced this holder out",
            ("replica",))

    def _path(self, msg_id: str) -> Path:
        return self.dir / f"{msg_id}.json"

    def _read(self, msg_id: str) -> dict | None:
        try:
            d = json.loads(self._path(msg_id).read_text())
            return d if isinstance(d, dict) else None
        except (OSError, ValueError):
            return None

    def _write(self, msg_id: str, d: dict) -> None:
        # unique tmp per writer: a dispatcher claim and a takeover bump can
        # target the same lease concurrently (they arbitrate by last-write;
        # the fence check after the fact resolves who really owns it) — a
        # SHARED tmp name would let one writer's os.replace steal the
        # other's tmp out from under it
        import uuid

        tmp = self.dir / f".{msg_id}.{uuid.uuid4().hex[:8]}.tmp"
        tmp.write_text(json.dumps(d))
        os.replace(tmp, self._path(msg_id))

    # ---------------------------------------------------------- lifecycle
    def claim(self, msg_id: str) -> Lease:
        """Record this replica's claim.  MUST be called only after winning
        the atomic ``pending/ -> running/`` rename (the rename is the
        mutex; the lease is the fence).  Bumps the fence past any prior
        holder's token."""
        prior = self._read(msg_id) or {}
        lease = Lease(msg_id=msg_id, holder=self.replica_id,
                      epoch=self.epoch,
                      fence=int(prior.get("fence", 0)) + 1,
                      acquired_at=time.time())
        self._write(msg_id, {
            "msg_id": msg_id, "holder": lease.holder, "epoch": lease.epoch,
            "fence": lease.fence, "acquired_at": lease.acquired_at,
            "renewed_at": lease.acquired_at,
        })
        return lease

    def renew(self, lease: Lease) -> bool:
        """Extend a held lease (called from the claim-heartbeat thread).
        Returns False when the lease was lost — a peer bumped the fence or
        cleared the file — so the holder can cancel its attempt early
        instead of discovering the rejection at commit time."""
        failpoint(FP_LEASE_RENEW, path=self._path(lease.msg_id))
        cur = self._read(lease.msg_id)
        if cur is None or int(cur.get("fence", -1)) != lease.fence or \
                cur.get("holder") != lease.holder or \
                int(cur.get("epoch", -1)) != lease.epoch:
            return False
        cur["renewed_at"] = time.time()
        self._write(lease.msg_id, cur)
        return True

    def check(self, lease: Lease) -> None:
        """The fence gate every write seam passes through (spool complete,
        retry republish, dead-letter, result store, ledger commit).
        Raises ``FenceRejectedError`` when this holder no longer owns the
        message.  The armed failpoint simulates exactly that — the
        injected fault IS a fence rejection, so chaos runs exercise the
        abort path deterministically."""
        try:
            failpoint(FP_FENCE_REJECT, path=self._path(lease.msg_id))
        except Exception as exc:
            self._note_reject(lease, f"injected: {exc}")
            raise FenceRejectedError(
                f"lease for {lease.msg_id} fenced (injected): {exc}") from exc
        cur = self._read(lease.msg_id)
        if cur is None:
            self._note_reject(lease, "lease file gone")
            raise FenceRejectedError(
                f"lease for {lease.msg_id} is gone — message reached a "
                f"terminal state under another owner")
        if int(cur.get("fence", -1)) != lease.fence or \
                cur.get("holder") != lease.holder or \
                int(cur.get("epoch", -1)) != lease.epoch:
            self._note_reject(
                lease,
                f"held fence {lease.fence} (epoch {lease.epoch}), current "
                f"{cur.get('fence')} held by {cur.get('holder')!r} "
                f"(epoch {cur.get('epoch')})")
            raise FenceRejectedError(
                f"stale fence for {lease.msg_id}: held {lease.fence} "
                f"(epoch {lease.epoch}), current {cur.get('fence')} by "
                f"{cur.get('holder')!r}")

    def _note_reject(self, lease: Lease, why: str) -> None:
        record_recovery("lease.fence_reject")
        if self._m_rejects is not None:
            self._m_rejects.labels(replica=self.replica_id).inc()
        logger.warning("lease: %s fence REJECTED for holder %s/%d: %s",
                       lease.msg_id, lease.holder, lease.epoch, why)

    def bump(self, msg_id: str) -> int:
        """Takeover fence bump: invalidate the current holder's token
        BEFORE requeueing its message.  Any write the stale holder tries
        after this fails its fence check.  Returns the new fence."""
        cur = self._read(msg_id) or {}
        fence = int(cur.get("fence", 0)) + 1
        self._write(msg_id, {
            "msg_id": msg_id, "holder": "", "epoch": self.epoch,
            "fence": fence, "fenced_by": self.replica_id,
            "fenced_at": time.time(), "renewed_at": 0.0,
        })
        return fence

    def release(self, lease: Lease) -> None:
        """Between-attempts release (retry republish, claimed-but-unstarted
        requeue): clear the holder, KEEP the fence — the next claim must
        still bump past this token."""
        cur = self._read(lease.msg_id)
        if cur is None or int(cur.get("fence", -1)) != lease.fence:
            return                    # already fenced/cleared by a peer
        cur["holder"] = ""
        cur["renewed_at"] = 0.0
        try:
            self._write(lease.msg_id, cur)
        except OSError:
            logger.warning("lease: could not release %s", lease.msg_id,
                           exc_info=True)

    def clear(self, msg_id: str) -> None:
        """Terminal outcome: the message left pending/running forever, the
        lease file goes with it."""
        try:
            self._path(msg_id).unlink()
        except FileNotFoundError:
            pass
        except OSError:
            logger.warning("lease: could not clear %s", msg_id, exc_info=True)

    def renewed_at(self, msg_id: str) -> float:
        """Last renewal timestamp (0.0 when unknown) — takeover scans
        combine this with the claim-heartbeat mtime for staleness."""
        cur = self._read(msg_id)
        return float(cur.get("renewed_at", 0.0)) if cur else 0.0

    def sweep_orphans(self, queue_root: str | Path,
                      max_age_s: float = 300.0) -> int:
        """Remove lease files whose message no longer sits in pending/ or
        running/ (crash between a terminal move and ``clear``).  Age-gated
        so a publish->claim in flight right now is never swept."""
        root = Path(queue_root)
        n = 0
        now = time.time()
        for p in self.dir.glob("*.json"):
            msg = p.stem
            if (root / "pending" / f"{msg}.json").exists() or \
                    (root / "running" / f"{msg}.json").exists():
                continue
            try:
                if now - p.stat().st_mtime >= max_age_s:
                    p.unlink()
                    n += 1
            except FileNotFoundError:
                continue
        # tmp debris from a crash inside a lease/beat write
        for d in (self.dir, root / "replicas"):
            for p in d.glob(".*.tmp"):
                try:
                    if now - p.stat().st_mtime >= max_age_s:
                        p.unlink()
                        n += 1
                except FileNotFoundError:
                    continue
        if n:
            record_recovery("lease.orphan_sweep", n)
        return n


# ---------------------------------------------------------------- registry
class ReplicaRegistry:
    """Replica liveness + gossip summaries via heartbeat files.

    Each replica owns ``<queue-root>/replicas/<replica_id>.json`` and
    rewrites it every ``replica_heartbeat_interval_s``; peers stat/read
    the directory to compute the alive set (rendezvous input) and to
    approximate global admission state.  ``register()`` bumps the stored
    epoch so a restarted replica is distinguishable from its previous
    life (leases carry the epoch)."""

    # smlint guarded-by registry (ISSUE 12 satellite, docs/ANALYSIS.md):
    # the replica loop re-registers after a drain clear while API /peers
    # handlers and the fleet controller's reconcile thread read epoch for
    # lease stamping / drain acks — the epoch bump must be atomic with the
    # sentinel clear it pairs with.
    _GUARDED_BY = {"epoch": "_lock"}

    def __init__(self, queue_root: str | Path, replica_id: str,
                 stale_after_s: float = 8.0):
        self.dir = Path(queue_root) / "replicas"
        self.dir.mkdir(parents=True, exist_ok=True)
        self.replica_id = replica_id
        self.stale_after_s = stale_after_s
        self._lock = threading.Lock()
        self.epoch = 0

    def _path(self, rid: str) -> Path:
        return self.dir / f"{rid}.json"

    def register(self) -> int:
        """First beat: epoch = prior epoch + 1 (or 1).  Returns the epoch
        this replica's leases will carry.  A beat I/O fault here does not
        abort registration — epoch persistence is best-effort (the
        per-message fence counter, not the epoch, is the safety argument).

        Any drain request left over from a PREVIOUS incarnation is cleared:
        a drain addresses an incarnation, not an identity — the controller
        that wanted the old process gone saw it exit; if it still wants
        this one gone it re-requests (docs/SERVICE.md "Elasticity model")."""
        prior = self._read(self.replica_id) or {}
        with self._lock:
            self.epoch = int(prior.get("epoch", 0)) + 1
        self.clear_drain(self.replica_id)
        try:
            (self.dir / f".{self.replica_id}.json.tmp").unlink(missing_ok=True)
            self.beat()
        except OSError:
            logger.warning("replica %s: registration beat failed",
                           self.replica_id, exc_info=True)
        return self.epoch

    def beat(self, summary: dict | None = None) -> None:
        """Write this replica's heartbeat (+ optional admission summary).
        An I/O fault here must not kill the replica — the caller's loop
        catches ``OSError`` and tries again next tick."""
        path = self._path(self.replica_id)
        failpoint(FP_REPLICA_HEARTBEAT, path=path)
        rec = {
            "replica_id": self.replica_id, "epoch": self.epoch,
            "pid": os.getpid(), "beat_at": time.time(),
        }
        if summary:
            rec.update(summary)
        tmp = self.dir / f".{self.replica_id}.json.tmp"
        tmp.write_text(json.dumps(rec))
        os.replace(tmp, path)

    def _read(self, rid: str) -> dict | None:
        try:
            d = json.loads(self._path(rid).read_text())
            return d if isinstance(d, dict) else None
        except (OSError, ValueError):
            return None

    def peers(self, include_self: bool = True) -> list[dict]:
        """Every registered replica's latest beat, with ``age_s``,
        ``alive``, and ``draining`` (a drain sentinel exists) computed."""
        out = []
        now = time.time()
        draining = self.draining_ids()
        for p in sorted(self.dir.glob("*.json")):
            rec = self._read(p.stem)
            if rec is None:
                continue
            if not include_self and rec.get("replica_id") == self.replica_id:
                continue
            age = now - float(rec.get("beat_at", 0.0))
            rec["age_s"] = round(age, 3)
            rec["alive"] = age < self.stale_after_s
            rec["draining"] = str(rec.get("replica_id", "")) in draining
            out.append(rec)
        return out

    def peers_by_process(self) -> dict[int, list[dict]]:
        """Peers grouped by pod process id (ISSUE 17): the scheduler's beat
        summaries gossip ``process_id``/``host`` since the pod layer, so a
        whole host's replicas form one group — the host watchdog's unit of
        liveness.  Peers without a process id (old replicas, bare tools)
        are omitted rather than guessed."""
        groups: dict[int, list[dict]] = {}
        for p in self.peers():
            pid = p.get("process_id")
            if pid is None:
                continue
            try:
                groups.setdefault(int(pid), []).append(p)
            except (TypeError, ValueError):
                continue
        return groups

    def alive(self) -> set[str]:
        """Replica ids with a fresh heartbeat (always includes self)."""
        out = {self.replica_id}
        for rec in self.peers():
            if rec["alive"]:
                out.add(str(rec["replica_id"]))
        return out

    def active(self) -> set[str]:
        """The shard-ownership membership set: alive replicas MINUS those
        with a drain request.  A draining replica keeps heartbeating (so
        its in-flight claims are not fenced prematurely) but drops out of
        rendezvous ownership immediately — peers adopt its shards while it
        finishes or releases what it already holds (zero-loss drain).
        NB: ``owned_shards`` unions the caller back in, so a draining
        replica must special-case its own ownership to the empty set
        (``JobScheduler._recompute_owned`` does)."""
        return self.alive() - self.draining_ids()

    # ------------------------------------------------------- drain protocol
    def _drain_path(self, rid: str) -> Path:
        return self.dir / f"{rid}.drain"

    def request_drain(self, rid: str, by: str = "") -> None:
        """Mark ``rid`` draining (the fleet controller's scale-down seam).
        The sentinel is a separate file so the victim's own heartbeat
        rewrites never clobber it."""
        tmp = self.dir / f".{rid}.drain.tmp"
        tmp.write_text(json.dumps({
            "replica_id": rid, "requested_at": time.time(), "by": by,
            "acked_at": 0.0,
        }))
        os.replace(tmp, self._drain_path(rid))

    def drain_requested(self, rid: str | None = None) -> bool:
        return self._drain_path(rid or self.replica_id).exists()

    def ack_drain(self) -> None:
        """The draining replica's retire ack: all claims finished or
        released, nothing more will be written — the controller may count
        the drain complete once the process also exits."""
        p = self._drain_path(self.replica_id)
        try:
            cur = json.loads(p.read_text())
            if not isinstance(cur, dict):
                cur = {}
        except (OSError, ValueError):
            cur = {"replica_id": self.replica_id}
        cur["acked_at"] = time.time()
        cur["epoch"] = self.epoch
        tmp = self.dir / f".{self.replica_id}.drain.tmp"
        tmp.write_text(json.dumps(cur))
        os.replace(tmp, p)

    def drain_acked(self, rid: str) -> bool:
        try:
            cur = json.loads(self._drain_path(rid).read_text())
            return isinstance(cur, dict) and float(cur.get("acked_at", 0)) > 0
        except (OSError, ValueError):
            return False

    def clear_drain(self, rid: str) -> None:
        try:
            self._drain_path(rid).unlink(missing_ok=True)
        except OSError:
            logger.warning("replica registry: could not clear drain "
                           "sentinel for %s", rid, exc_info=True)

    def draining_ids(self) -> set[str]:
        return {p.stem for p in self.dir.glob("*.drain")}

    def retire(self) -> None:
        """Graceful shutdown: drop out of the alive set immediately so
        peers take over without waiting out the staleness horizon."""
        try:
            self._path(self.replica_id).unlink()
        except OSError:
            pass
