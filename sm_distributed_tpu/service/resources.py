"""Disk-budget governor + bounded-retention GC (ISSUE 10 tentpole).

The chaos/overload/replica layers (PRs 2/4/8) made the service survive
crashes, floods, and peer death — but a full disk still killed attempts
mid-write, and every long-lived directory (per-job ``traces/``, isocalc
cache shards, spool ``done/``, dead-letter, replica-registry debris) grew
without bound.  This module makes *resource* exhaustion a degradation, not
a death:

**Preflight** — :meth:`ResourceGovernor.preflight` is called at every
governed write seam (checkpoint shards, result store, spool publish, cache
shards; trace appends go through the cheaper :meth:`trace_gate`).  It
checks projected headroom against two constraints:

- filesystem free space minus ``resources.min_free_bytes`` (statvfs,
  cached ~250 ms);
- ``resources.disk_budget_bytes`` minus bytes used under the governed
  roots (work dir, results dir, spool), rescanned every GC tick and
  advanced between ticks by the preflights' own size estimates.

**Degrade order** — as the remaining headroom shrinks the governor sheds
in the configured order (``ResourcesConfig`` floors):

====== ============================== =================================
level  trigger                        effect
====== ============================== =================================
1      remaining < trace_floor_bytes  trace-FILE writes dropped (the
                                      flight-recorder ring keeps flowing)
2      remaining < cache_floor_bytes  isocalc cache-shard writes dropped
                                      (patterns stay in memory)
3      remaining < submit_floor_bytes POST /submit sheds with a
                                      structured **507** + Retry-After
                                      (service/admission.py)
deny   remaining - est < 0            essential writes (checkpoint /
                                      results / publish) raise
                                      ``ResourceBudgetError`` — the
                                      normal failure/retry path, BEFORE
                                      a torn write hits the real floor
====== ============================== =================================

**Bounded-retention GC** — :meth:`gc_tick` runs from the scheduler's
replica loop (scheduler-owned, so the sweep is replica-shard-scoped and
composes with PR 8 takeover sweeps).  Directory classes and their knobs:

- ``traces``   — per-job JSONL files: ``tracing.retention_age_s`` /
  ``tracing.retention_max_bytes`` (oldest first past the size cap);
- ``done``     — drained spool messages: ``resources.done_retention_age_s``
  (scoped to shards this replica owns);
- ``failed``   — dead-letter + quarantine evidence:
  ``resources.failed_retention_age_s`` (shard-scoped);
- ``cache``    — isocalc pattern shards:
  ``resources.cache_retention_max_bytes`` (oldest shards first; removal
  only costs recompute);
- ``registry`` — crashed replicas' heartbeat files (they never retire):
  ``resources.registry_retention_age_s``.  Stale *lease* files are swept
  by the scheduler's takeover scan (``LeaseStore.sweep_orphans``), which
  runs in the same loop.

Everything exports through ``sm_disk_*`` / ``sm_gc_*`` gauges+counters
(docs/OBSERVABILITY.md) and the ``GET /debug/resources`` snapshot.

A process-global singleton (same pattern as the breaker) lets the engine
layers consult the governor through module functions without importing the
service composition; with no governor installed every check is a single
``is None`` test — offline CLI runs pay nothing.
"""

from __future__ import annotations

import errno
import json
import os
import threading
import time
from pathlib import Path

from ..utils import tracing
from ..utils.config import ResourcesConfig, TracingConfig
from ..utils.logger import logger

# degrade levels (gauge sm_disk_degrade_level).  ISSUE 16 inserted
# no_read_cache between the isocalc-cache and shed-submits levels: read-path
# cache fills are shed BEFORE submits — losing cache warmth only costs
# latency, while shedding submits loses work
LEVEL_OK = 0
LEVEL_NO_TRACES = 1
LEVEL_NO_CACHE = 2
LEVEL_NO_READ_CACHE = 3
LEVEL_SHED_SUBMITS = 4

_LEVEL_NAMES = {LEVEL_OK: "ok", LEVEL_NO_TRACES: "no_traces",
                LEVEL_NO_CACHE: "no_cache",
                LEVEL_NO_READ_CACHE: "no_read_cache",
                LEVEL_SHED_SUBMITS: "shed_submits"}

# statvfs / level cache TTL: preflights sit on write paths — one stat
# syscall per TTL window, not per write
_FREE_TTL_S = 0.25


class ResourceBudgetError(OSError):
    """An essential write was denied by the disk-budget preflight.  An
    ``OSError`` with ``errno.ENOSPC`` on purpose: callers already treat a
    full disk as a failed attempt, and the retry policy / chaos recovery
    handle it identically to the kernel's own ENOSPC."""

    def __init__(self, seam: str, message: str):
        super().__init__(errno.ENOSPC, message)
        self.seam = seam


class ResourceGovernor:
    """Preflight + degrade levels + retention GC over the governed roots."""

    # smlint guarded-by registry (docs/ANALYSIS.md): sampling/preflight
    # state is touched by worker threads, the scheduler's replica loop,
    # and HTTP handlers
    _GUARDED_BY = {"_used": "_lock", "_pending": "_lock", "_free": "_lock",
                   "_free_at": "_lock", "_level": "_lock",
                   "_degraded_writes": "_lock", "_denied": "_lock",
                   "_gc_stats": "_lock", "_gc_runs": "_lock",
                   "_last_gc_at": "_lock"}

    def __init__(self, cfg: ResourcesConfig,
                 work_dir: str | Path | None = None,
                 results_dir: str | Path | None = None,
                 queue_root: str | Path | None = None,
                 trace_dir: str | Path | None = None,
                 cache_dir: str | Path | None = None,
                 tracing_cfg: TracingConfig | None = None,
                 metrics=None, replica_id: str = "",
                 read_cache_dir: str | Path | None = None,
                 read_cache_max_bytes: int = 0,
                 stream_dir: str | Path | None = None,
                 stream_retention_age_s: float = 0.0,
                 stream_idle_timeout_s: float = 0.0):
        self.cfg = cfg
        self.tracing_cfg = tracing_cfg or TracingConfig()
        self.replica_id = replica_id
        self.roots = [Path(p) for p in (work_dir, results_dir, queue_root)
                      if p]
        self.statvfs_path = self.roots[0] if self.roots else Path(".")
        self.queue_root = Path(queue_root) if queue_root else None
        self.trace_dir = Path(trace_dir) if trace_dir else None
        self.cache_dir = Path(cache_dir) if cache_dir else None
        # read-path tile cache (ISSUE 16): dir + byte cap flow from the
        # server wiring (ReadPathConfig.cache_disk_max_bytes), not from
        # ResourcesConfig — the read path owns its own sizing knob
        self.read_cache_dir = Path(read_cache_dir) if read_cache_dir else None
        self.read_cache_max_bytes = int(read_cache_max_bytes)
        # live-acquisition chunk logs (ISSUE 19): dir + age flow from the
        # server wiring (StreamConfig.retention_age_s) like the read cache
        self.stream_dir = Path(stream_dir) if stream_dir else None
        self.stream_retention_age_s = float(stream_retention_age_s)
        self.stream_idle_timeout_s = float(stream_idle_timeout_s)
        self._lock = threading.Lock()
        self._used = 0                # bytes under the roots, last scan
        self._pending = 0             # preflighted-but-not-rescanned bytes
        self._free: float = float("inf")
        self._free_at = 0.0
        self._level = LEVEL_OK
        self._degraded_writes: dict[str, int] = {}
        self._denied: dict[str, int] = {}
        self._gc_stats: dict[str, dict[str, int]] = {}
        self._gc_runs = 0
        self._last_gc_at = 0.0
        self._metrics = None
        if metrics is not None:
            self.attach_metrics(metrics)
        # first usage scan so level() is meaningful before the first tick
        self.rescan_usage()

    @property
    def enabled(self) -> bool:
        return bool(self.cfg.min_free_bytes or self.cfg.disk_budget_bytes)

    # -------------------------------------------------------------- metrics
    def attach_metrics(self, m) -> None:
        self._metrics = m
        m.counter("sm_disk_writes_denied_total",
                  "Essential writes denied by the disk-budget preflight",
                  ("seam",))
        m.counter("sm_disk_degraded_writes_total",
                  "Optional writes dropped under disk pressure", ("kind",))
        m.counter("sm_gc_removed_files_total",
                  "Files removed by the retention sweeper", ("dir",))
        m.counter("sm_gc_reclaimed_bytes_total",
                  "Bytes reclaimed by the retention sweeper", ("dir",))
        m.counter("sm_gc_runs_total", "Retention sweep passes completed")
        m.add_collector(self._collect)

    def _collect(self, m) -> None:
        free = self._statvfs_free()
        with self._lock:
            used = self._used + self._pending
            level = self._level
        m.gauge("sm_disk_free_bytes",
                "Filesystem free bytes under the governed roots").set(
            free if free != float("inf") else 0)
        m.gauge("sm_disk_used_bytes",
                "Bytes used under the governed roots (last GC scan + "
                "preflighted writes)").set(used)
        m.gauge("sm_disk_budget_bytes",
                "Configured disk budget (0 = free-space constraint only)"
                ).set(self.cfg.disk_budget_bytes)
        m.gauge("sm_disk_degrade_level",
                "Disk-pressure degrade level (0=ok 1=no traces 2=no cache "
                "3=no read cache 4=shed submits)").set(level)

    def _count(self, family: str, key: str) -> None:
        m = self._metrics
        if m is None:
            return
        if family == "denied":
            m.counter("sm_disk_writes_denied_total",
                      "Essential writes denied by the disk-budget preflight",
                      ("seam",)).labels(seam=key).inc()
        else:
            m.counter("sm_disk_degraded_writes_total",
                      "Optional writes dropped under disk pressure",
                      ("kind",)).labels(kind=key).inc()

    # ------------------------------------------------------------ headroom
    def _statvfs_free(self) -> float:
        """Free bytes on the filesystem under the roots (cached)."""
        now = time.monotonic()
        with self._lock:
            if now - self._free_at < _FREE_TTL_S:
                return self._free
        try:
            st = os.statvfs(self.statvfs_path)
            free = float(st.f_bavail) * st.f_frsize
        except OSError:
            # an unreadable filesystem must not wedge the write paths;
            # the budget constraint (if configured) still governs
            logger.warning("resources: statvfs(%s) failed",
                           self.statvfs_path, exc_info=True)
            free = float("inf")
        with self._lock:
            self._free = free
            self._free_at = now
        return free

    def remaining(self) -> float:
        """Headroom in bytes before the hard floor: the binding minimum of
        the free-space and budget constraints (inf when neither is
        configured — the governor is inert)."""
        cfg = self.cfg
        out = float("inf")
        if cfg.min_free_bytes:
            out = min(out, self._statvfs_free() - cfg.min_free_bytes)
        if cfg.disk_budget_bytes:
            with self._lock:
                used = self._used + self._pending
            out = min(out, float(cfg.disk_budget_bytes) - used)
        return out

    def level(self) -> int:
        """Current degrade level, with transition logging."""
        rem = self.remaining()
        cfg = self.cfg
        if rem < cfg.submit_floor_bytes:
            new = LEVEL_SHED_SUBMITS
        elif rem < cfg.read_cache_floor_bytes:
            new = LEVEL_NO_READ_CACHE
        elif rem < cfg.cache_floor_bytes:
            new = LEVEL_NO_CACHE
        elif rem < cfg.trace_floor_bytes:
            new = LEVEL_NO_TRACES
        else:
            new = LEVEL_OK
        with self._lock:
            old, self._level = self._level, new
        if new != old:
            logger.warning(
                "resources: disk-pressure level %s -> %s (%.1f MB headroom "
                "remaining)", _LEVEL_NAMES[old], _LEVEL_NAMES[new],
                rem / 2**20 if rem != float("inf") else float("inf"))
            tracing.event("disk_pressure", from_level=_LEVEL_NAMES[old],
                          to_level=_LEVEL_NAMES[new],
                          remaining_bytes=int(min(rem, 2**62)))
        return new

    # ------------------------------------------------------------ the gates
    def preflight(self, seam: str, est_bytes: int = 0) -> None:
        """Essential-write gate (checkpoint / results / publish / cache):
        raises :class:`ResourceBudgetError` when the write would breach
        the hard floor.  Accepted writes advance the pending-bytes
        estimate so a burst between GC rescans cannot overshoot."""
        if not self.enabled:
            return
        if self.remaining() - max(0, est_bytes) < 0:
            with self._lock:
                self._denied[seam] = self._denied.get(seam, 0) + 1
            self._count("denied", seam)
            tracing.event("disk_denied", seam=seam, est_bytes=int(est_bytes))
            raise ResourceBudgetError(
                seam,
                f"disk budget exhausted at seam {seam!r} (est "
                f"{est_bytes} B over the floor) — "
                f"min_free={self.cfg.min_free_bytes} "
                f"budget={self.cfg.disk_budget_bytes}")
        if est_bytes > 0:
            with self._lock:
                self._pending += int(est_bytes)

    def trace_gate(self) -> bool:
        """Per-record trace-file gate (installed via
        ``tracing.set_file_gate``): False = drop the file write (level >=
        1).  Must never raise — it sits inside every span emission."""
        if not self.enabled or self.level() < LEVEL_NO_TRACES:
            return True
        with self._lock:
            self._degraded_writes["trace"] = \
                self._degraded_writes.get("trace", 0) + 1
        self._count("degraded", "trace")
        return False

    def allow_cache(self) -> bool:
        """Cache-shard gate (ops/isocalc.py): False = skip the shard write
        (level >= 2); generation keeps the patterns in memory."""
        if not self.enabled or self.level() < LEVEL_NO_CACHE:
            return True
        with self._lock:
            self._degraded_writes["cache"] = \
                self._degraded_writes.get("cache", 0) + 1
        self._count("degraded", "cache")
        return False

    def allow_read_cache_fill(self) -> bool:
        """Read-path cache-fill gate (service/readpath.py): False = serve
        the read from its source segment/npz without caching the result
        (level >= 3).  Reads never shed here — only their cache warmth."""
        if not self.enabled or self.level() < LEVEL_NO_READ_CACHE:
            return True
        with self._lock:
            self._degraded_writes["read_cache"] = \
                self._degraded_writes.get("read_cache", 0) + 1
        self._count("degraded", "read_cache")
        return False

    def submits_shed(self) -> bool:
        """Admission gate (service/admission.py): True = shed new submits
        with a structured 507 + Retry-After."""
        return self.enabled and self.level() >= LEVEL_SHED_SUBMITS

    # ------------------------------------------------------------------ GC
    def rescan_usage(self) -> int:
        """Walk the governed roots and reset the usage estimate (GC-tick
        cadence; preflights advance it between scans)."""
        total = 0
        for root in self.roots:
            try:
                for dirpath, _dirnames, filenames in os.walk(root):
                    for name in filenames:
                        try:
                            total += os.lstat(
                                os.path.join(dirpath, name)).st_size
                        except OSError:
                            continue  # unlinked mid-walk
            except OSError:
                continue              # root vanished (tests tear down)
        with self._lock:
            self._used = total
            self._pending = 0
        return total

    def _reap(self, cls: str, victims: list[Path]) -> tuple[int, int]:
        n = reclaimed = 0
        for p in victims:
            try:
                size = p.stat().st_size
                p.unlink()
            except OSError:
                continue              # already gone / being written
            n += 1
            reclaimed += size
        if n:
            with self._lock:
                st = self._gc_stats.setdefault(
                    cls, {"files": 0, "bytes": 0})
                st["files"] += n
                st["bytes"] += reclaimed
            m = self._metrics
            if m is not None:
                m.counter("sm_gc_removed_files_total",
                          "Files removed by the retention sweeper",
                          ("dir",)).labels(dir=cls).inc(n)
                m.counter("sm_gc_reclaimed_bytes_total",
                          "Bytes reclaimed by the retention sweeper",
                          ("dir",)).labels(dir=cls).inc(reclaimed)
            logger.info("resources: gc removed %d %s file(s) (%.1f MB)",
                        n, cls, reclaimed / 2**20)
        return n, reclaimed

    @staticmethod
    def _aged(paths, max_age_s: float, now: float) -> list[Path]:
        out = []
        for p in paths:
            try:
                if now - p.stat().st_mtime >= max_age_s:
                    out.append(p)
            except OSError:
                continue
        return out

    @staticmethod
    def _over_size_cap(paths, cap_bytes: int) -> list[Path]:
        """Oldest-first victims until the set fits under ``cap_bytes``."""
        sized = []
        total = 0
        for p in paths:
            try:
                st = p.stat()
            except OSError:
                continue
            sized.append((st.st_mtime, st.st_size, p))
            total += st.st_size
        sized.sort()                  # oldest first
        victims = []
        for _mtime, size, p in sized:
            if total <= cap_bytes:
                break
            victims.append(p)
            total -= size
        return victims

    def _sweep_traces(self, now: float) -> None:
        d = self.trace_dir
        tcfg = self.tracing_cfg
        if d is None or not d.is_dir():
            return
        files = list(d.glob("*.jsonl"))
        victims: list[Path] = []
        if tcfg.retention_age_s > 0:
            victims += self._aged(files, tcfg.retention_age_s, now)
        if tcfg.retention_max_bytes > 0:
            keep = [p for p in files if p not in set(victims)]
            victims += self._over_size_cap(keep, tcfg.retention_max_bytes)
        for p in victims:
            # drop any cached append handle first, so a late append to the
            # same trace id reopens instead of writing to a dead inode
            tracing.close_file(p)
        self._reap("traces", victims)

    def _sweep_spool(self, now: float, owns_msg) -> None:
        root = self.queue_root
        if root is None:
            return
        for cls, sub_dirs, age in (
                ("done", ("done",), self.cfg.done_retention_age_s),
                ("failed", ("failed", "quarantine"),
                 self.cfg.failed_retention_age_s)):
            if age <= 0:
                continue
            victims = []
            for sub in sub_dirs:
                for p in self._aged((root / sub).glob("*.json"), age, now):
                    # replica scoping: only reap messages in shards this
                    # replica owns — a peer sweeps its own partitions
                    if owns_msg is not None and not owns_msg(p.stem):
                        continue
                    victims.append(p)
            self._reap(cls, victims)

    def _sweep_cache(self, now: float) -> None:
        d = self.cache_dir
        cap = self.cfg.cache_retention_max_bytes
        if d is None or not d.is_dir():
            return
        # aged tmp debris is always fair game; shards only under a cap
        victims = self._aged(d.glob("tmp_*.npz"), 3600.0, now)
        if cap > 0:
            victims += self._over_size_cap(
                list(d.glob("theor_peaks_*.npz")), cap)
        self._reap("cache", victims)

    def _sweep_read_cache(self, now: float) -> None:
        d = self.read_cache_dir
        cap = self.read_cache_max_bytes
        if d is None or not d.is_dir():
            return
        # aged fill tmps are always fair game; committed tiles only under
        # the cap (oldest first — eviction just costs a re-render)
        victims = self._aged(d.glob("*.tmp"), 3600.0, now)
        if cap > 0:
            victims += self._over_size_cap(list(d.glob("*.png")), cap)
        self._reap("read_cache", victims)

    def _sweep_stream(self, now: float) -> None:
        """Chunk-log retention (ISSUE 19).  Torn append tmps are fair game
        after an hour.  A dataset's whole log is reclaimed once its
        manifest says ``finished`` and it has sat idle past
        ``service.stream.retention_age_s`` — OR, for an ABANDONED
        acquisition (client vanished, finish never posted), once the
        manifest has been idle past ``retention_age_s + idle_timeout_s``:
        by then the stream job is certainly terminal (``StreamIdleError``
        fires at most ``idle_timeout_s`` after the last commit), so the
        chunk files can't keep eating governed disk forever.  When
        ``idle_timeout_s`` is 0 the operator opted into open-ended
        acquisitions and unfinished logs are never reaped."""
        d = self.stream_dir
        age = self.stream_retention_age_s
        idle_timeout = self.stream_idle_timeout_s
        if d is None or not d.is_dir():
            return
        self._reap("stream", self._aged(d.glob("*/.*.tmp"), 3600.0, now))
        if age <= 0:
            return
        for ds_dir in sorted(d.iterdir()):
            man = ds_dir / "manifest.json"
            if not ds_dir.is_dir() or not man.is_file():
                continue
            try:
                finished = bool(json.loads(man.read_text()).get("finished"))
                idle_s = now - man.stat().st_mtime
            except (OSError, ValueError):
                continue
            reap = (idle_s >= age if finished
                    else idle_timeout > 0 and idle_s >= age + idle_timeout)
            if reap:
                lock = ds_dir / ".lock"
                self._reap("stream",
                           sorted(ds_dir.glob("chunk_*.npz"))
                           + ([lock] if lock.exists() else []) + [man])
                try:
                    ds_dir.rmdir()
                except OSError:
                    pass          # stray file left behind -> next tick

    def _sweep_registry(self, now: float) -> None:
        root = self.queue_root
        age = self.cfg.registry_retention_age_s
        if root is None or age <= 0:
            return
        reg = root / "replicas"
        if not reg.is_dir():
            return
        victims = [p for p in self._aged(reg.glob("*.json"), age, now)
                   if p.stem != self.replica_id]
        self._reap("registry", victims)

    def gc_tick(self, owns_msg=None) -> dict:
        """One retention sweep + usage rescan (scheduler replica loop).
        ``owns_msg(msg_id)`` scopes the spool classes to this replica's
        shards so N replicas sweep one spool without double-reaping."""
        now = time.time()
        self._sweep_traces(now)
        self._sweep_spool(now, owns_msg)
        self._sweep_cache(now)
        self._sweep_read_cache(now)
        self._sweep_stream(now)
        self._sweep_registry(now)
        self.rescan_usage()
        with self._lock:
            self._gc_runs += 1
            self._last_gc_at = now
        m = self._metrics
        if m is not None:
            m.counter("sm_gc_runs_total",
                      "Retention sweep passes completed").inc()
        self.level()                  # re-evaluate after reclaiming space
        return self.snapshot()

    # ------------------------------------------------------------ snapshot
    def snapshot(self) -> dict:
        """``GET /debug/resources``: the full governor picture."""
        from ..models import oom

        rem = self.remaining()
        with self._lock:
            body = {
                "enabled": self.enabled,
                "level": self._level,
                "level_name": _LEVEL_NAMES[self._level],
                "remaining_bytes": (int(rem) if rem != float("inf")
                                    else None),
                "used_bytes": self._used,
                "pending_bytes": self._pending,
                "budget_bytes": self.cfg.disk_budget_bytes,
                "min_free_bytes": self.cfg.min_free_bytes,
                "floors_bytes": {
                    "trace": self.cfg.trace_floor_bytes,
                    "cache": self.cfg.cache_floor_bytes,
                    "read_cache": self.cfg.read_cache_floor_bytes,
                    "submit": self.cfg.submit_floor_bytes,
                },
                "degraded_writes": dict(self._degraded_writes),
                "denied_writes": dict(self._denied),
                "gc": {"runs": self._gc_runs,
                       "last_run_at": self._last_gc_at,
                       "classes": {k: dict(v)
                                   for k, v in self._gc_stats.items()}},
                "roots": [str(r) for r in self.roots],
            }
        body["free_bytes"] = (int(self._free)
                              if self._free != float("inf") else None)
        body["oom"] = oom.snapshot()
        return body


# ------------------------------------------------------- process singleton
_singleton_lock = threading.Lock()
_governor: ResourceGovernor | None = None


def set_governor(governor: ResourceGovernor | None) -> None:
    """Install (or clear) the process-global governor.  The service does
    this at startup/shutdown; offline CLI runs never install one, so the
    module gates below stay single-``is None``-test cheap."""
    global _governor
    with _singleton_lock:
        _governor = governor


def get_governor() -> ResourceGovernor | None:
    return _governor


def preflight(seam: str, est_bytes: int = 0) -> None:
    """Module-level essential-write gate for the engine seams (checkpoint
    shards, result store, spool publish): no-op without a governor."""
    g = _governor
    if g is not None:
        g.preflight(seam, est_bytes)


def allow_cache() -> bool:
    """Module-level cache-shard gate for ops/isocalc.py."""
    g = _governor
    return True if g is None else g.allow_cache()
