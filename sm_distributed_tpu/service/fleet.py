"""Elastic replica fleet: SLO-driven autoscaling controller (ISSUE 11).

PR 8 gave the service N replicas over one partitioned spool; PR 6 gave it
SLO telemetry.  This module closes the loop: a **FleetController**
supervises replica subprocesses (spawn / monitor / drain / retire) and
makes hysteresis-damped scale decisions between ``fleet.min_replicas`` and
``fleet.max_replicas`` from the live signals the service already exports —
``/slo`` error-budget burn, admission queue depth, and device-pool
occupancy (``/debug/timeseries``).  GSPMD (arXiv:2105.04663) is the
blueprint for the mesh side: leases span hosts via the device pool's
host dimension (``service.device_pool_hosts``), and the controller reads
per-host occupancy so it reasons about host-level failure domains.

The robustness core is **zero-loss membership change**:

- **scale-down is a drain, not a kill**: the controller writes a drain
  sentinel into the replica registry (``ReplicaRegistry.request_drain``);
  the victim notices, drops out of rendezvous ownership (peers adopt its
  shards immediately — ``registry.active()`` excludes draining replicas),
  stops claiming, finishes or releases its in-flight work under the normal
  failure policy, **acks** (``fleet.retire_ack`` seam), and retires.
  Fenced leases make the handoff safe by construction: even a victim that
  stalls mid-drain and gets force-killed is just a crashed replica — peers
  fence + requeue its claims and complete them exactly once;
- **scale-up re-partitions without double-claims**: a spawned replica
  registers, every replica's rendezvous set gains it, and transient
  ownership disagreement is arbitrated by the atomic claim rename + fence
  bump (PR 8's safety argument, unchanged);
- **crash ≠ drain**: a supervised process that exits *without* a drain
  request (or goes heartbeat-stale) is a crash — the controller replaces
  it (repair to ``min_replicas`` bypasses hysteresis and cooldown) while
  the survivors' takeover scans recover its claims.  A drained replica
  leaves no heartbeat file (it retires) and its drain sentinel is cleaned
  by the controller; a crashed one leaves a stale heartbeat the retention
  GC eventually removes.

The decision rule is a PURE function (``decide``) over a signal snapshot —
unit-testable with synthetic snapshots, no subprocesses — wrapped by the
controller loop that enforces it with a per-event ``cooldown_s`` and
``hysteresis_ticks`` so flapping traffic cannot thrash the fleet.

Metrics: ``sm_fleet_replicas``, ``sm_fleet_target_replicas``,
``sm_fleet_scale_events_total{direction=}``, ``sm_fleet_drains_total``,
``sm_fleet_crashes_total``, ``sm_fleet_spawn_failures_total`` — on the
hosting service's ``/metrics`` when the controller runs beside replica r0
(``serve --fleet``).  Failpoints: ``fleet.spawn`` (controller killed
mid-spawn), plus the scheduler-side ``drain.handoff`` and
``fleet.retire_ack`` (docs/RECOVERY.md).
"""

from __future__ import annotations

import inspect
import json
import subprocess
import threading
import time
from dataclasses import dataclass, replace
from pathlib import Path

from ..engine.daemon import QUEUE_ANNOTATE
from ..utils import tracing
from ..utils.config import FleetConfig, ServiceConfig
from ..utils.failpoints import failpoint, record_recovery, register_failpoint
from ..utils.logger import logger
from .leases import ReplicaRegistry

FP_FLEET_SPAWN = register_failpoint(
    "fleet.spawn",
    "between a scale-up decision and the replica subprocess launch (a "
    "crash here is the controller killed mid-spawn)")


# ------------------------------------------------------------------ signals
@dataclass(frozen=True)
class FleetSignals:
    """One snapshot of everything the decision rule reads.  Collected from
    the live service (``service_signals``) or the spool alone
    (``spool_signals``); built literally in the unit tests."""

    queue_depth: int                     # pending/ messages (admission queue)
    alive: int                           # non-draining replicas with fresh
                                         # heartbeats
    burn: float | None = None            # worst /slo error-budget burn
                                         # (None: no SLO data yet)
    occupancy: float | None = None       # pool-wide chip occupancy 0..1
    per_host_in_use: tuple | None = None # chips held per host failure domain


@dataclass(frozen=True)
class FleetState:
    """The controller's decision memory (immutable; ``decide`` returns the
    successor state)."""

    last_scale_at: float = 0.0
    high_ticks: int = 0                  # consecutive ticks under pressure
    low_ticks: int = 0                   # consecutive ticks of relief


def _pressure(cfg: FleetConfig, s: FleetSignals) -> bool:
    if s.alive <= 0:
        return True
    if s.queue_depth / s.alive >= cfg.queue_high_per_replica:
        return True
    if s.burn is not None and s.burn >= cfg.scale_up_burn:
        return True
    if cfg.occupancy_high > 0 and s.occupancy is not None and \
            s.occupancy >= cfg.occupancy_high:
        return True
    return False


def _relief(cfg: FleetConfig, s: FleetSignals) -> bool:
    if s.alive <= 0:
        return False
    if s.queue_depth / s.alive > cfg.queue_low_per_replica:
        return False
    if s.burn is not None and s.burn > cfg.scale_down_burn:
        return False
    return True


def decide(cfg: FleetConfig, state: FleetState, signals: FleetSignals,
           now: float) -> tuple[int, FleetState]:
    """The scale decision: ``(+1 | 0 | -1, next_state)``.

    Ordering of the guards IS the policy:

    1. **repair** — below ``min_replicas`` scales up immediately (a crash
       replacement is not a scaling decision; hysteresis and cooldown do
       not apply), above ``max_replicas`` drains immediately;
    2. **hysteresis** — pressure/relief must hold ``hysteresis_ticks``
       consecutive ticks before acting (one hot scrape never moves the
       fleet); an act consumes the accumulated ticks;
    3. **cooldown** — at least ``cooldown_s`` must have passed since the
       last scale event (flapping traffic oscillates inside the cooldown
       and the fleet stands still);
    4. **clamps** — never above ``max_replicas`` or below ``min_replicas``.
    """
    if signals.alive < cfg.min_replicas:
        return 1, replace(state, last_scale_at=now, high_ticks=0,
                          low_ticks=0)
    if signals.alive > cfg.max_replicas:
        return -1, replace(state, last_scale_at=now, high_ticks=0,
                           low_ticks=0)
    up = _pressure(cfg, signals)
    down = _relief(cfg, signals)
    high = state.high_ticks + 1 if up else 0
    low = state.low_ticks + 1 if down and not up else 0
    state = replace(state, high_ticks=high, low_ticks=low)
    cooled = now - state.last_scale_at >= cfg.cooldown_s
    if up and high >= cfg.hysteresis_ticks and cooled and \
            signals.alive < cfg.max_replicas:
        return 1, replace(state, last_scale_at=now, high_ticks=0)
    if low >= cfg.hysteresis_ticks and cooled and \
            signals.alive > cfg.min_replicas:
        return -1, replace(state, last_scale_at=now, low_ticks=0)
    return 0, state


# ------------------------------------------------------------ signal sources
def spool_signals(queue_root: str | Path, registry: ReplicaRegistry):
    """Signals from the shared spool alone (no HTTP): queue depth from
    ``pending/``, membership from registry heartbeats.  What the bare
    load-sweep harness and a standalone controller use."""
    root = Path(queue_root)

    def _collect() -> FleetSignals:
        try:
            depth = len(list((root / "pending").glob("*.json")))
        except OSError:
            depth = 0
        alive = sum(1 for p in registry.peers()
                    if p.get("alive") and not p.get("draining"))
        return FleetSignals(queue_depth=depth, alive=alive)

    return _collect


def service_signals(service):
    """Signals from a live in-process ``AnnotationService`` (the ``serve
    --fleet`` shape): `/slo` error-budget burn from the SLO tracker, queue
    depth from the spool, pool occupancy + per-host holds from the newest
    ``/debug/timeseries`` sample (falling back to the pool itself)."""
    registry = service.scheduler.registry
    root = service.queue_dir / service.queue

    def _collect() -> FleetSignals:
        try:
            depth = len(list((root / "pending").glob("*.json")))
        except OSError:
            depth = 0
        alive = sum(1 for p in registry.peers()
                    if p.get("alive") and not p.get("draining"))
        burn = None
        slo = getattr(service, "slo", None)
        if slo is not None:
            burns = [s.get("error_budget_burn")
                     for s in slo.report().get("slos", {}).values()]
            burns = [b for b in burns if b is not None]
            burn = max(burns) if burns else None
        occupancy = None
        per_host = None
        mon = getattr(service, "telemetry", None)
        samples = mon.timeseries(1) if mon is not None else []
        if samples and samples[-1].get("device_pool_ratio") is not None:
            occupancy = float(samples[-1]["device_pool_ratio"])
            ph = samples[-1].get("device_pool_per_host_in_use")
            per_host = tuple(ph) if ph else None
        elif getattr(service, "device_pool", None) is not None:
            snap = service.device_pool.snapshot()
            occupancy = snap["in_use"] / max(1, snap["size"])
            per_host = tuple(snap.get("per_host_in_use", ()))
        return FleetSignals(queue_depth=depth, alive=alive, burn=burn,
                            occupancy=occupancy, per_host_in_use=per_host)

    return _collect


# ---------------------------------------------------------------- controller
@dataclass
class _Child:
    """One supervised replica subprocess."""

    rid: str
    proc: subprocess.Popen
    spawned_at: float
    host: str = ""                       # named pod host it was placed on
    registered: bool = False             # first registry heartbeat seen
    draining: bool = False
    drain_requested_at: float = 0.0


class FleetController:
    """Supervise replica subprocesses and autoscale the fleet.

    ``spawn(rid)`` launches one replica process serving the shared spool
    under that identity and returns its ``Popen`` — the production shape
    builds a ``serve`` command (``serve_spawn``), the harnesses inject
    bare schedulers.  ``self_replica_id`` names a replica living in THIS
    process (serve --fleet runs the controller beside r0); it counts
    toward the fleet but is never chosen as a drain victim.
    """

    # smlint guarded-by registry (docs/ANALYSIS.md): the loop thread, the
    # public status()/shutdown() entry points, and metric collectors all
    # touch the child table and decision state — mutations only under
    # _lock.  *_locked methods document the caller-holds-lock exception.
    _GUARDED_BY = {"_children": "_lock", "_state": "_lock",
                   "_next_ordinal": "_lock", "scale_events": "_lock",
                   "drains_total": "_lock", "crashes_total": "_lock"}

    def __init__(self, queue_dir: str | Path, cfg: FleetConfig,
                 service_cfg: ServiceConfig, spawn,
                 signals=None, metrics=None, self_replica_id: str | None = None,
                 queue: str = QUEUE_ANNOTATE, replica_prefix: str = "fr",
                 hosts=None, warm_host=None):
        self.root = Path(queue_dir) / queue
        self.cfg = cfg
        self.service_cfg = service_cfg
        self.spawn = spawn
        self.self_replica_id = self_replica_id
        self.replica_prefix = replica_prefix
        # host-aware placement (ISSUE 17): named pod hosts replicas are
        # spread over, least-loaded first.  A 2-arg spawn factory receives
        # (rid, host); the legacy 1-arg shape keeps working (host-blind).
        # warm_host(host) runs ONCE before the first replica lands on each
        # new host — the per-host primer warm-up seam (its XLA cache is
        # cold until something compiles there).
        self.hosts = tuple(str(h) for h in hosts or ())
        self.warm_host = warm_host
        self._warmed_hosts: set[str] = set()
        self._spawn_takes_host = False
        try:
            params = list(inspect.signature(spawn).parameters.values())
            self._spawn_takes_host = (
                any(p.kind == p.VAR_POSITIONAL for p in params)
                or len([p for p in params
                        if p.kind in (p.POSITIONAL_ONLY,
                                      p.POSITIONAL_OR_KEYWORD)]) >= 2)
        except (TypeError, ValueError):
            pass
        self.registry = ReplicaRegistry(
            self.root, self_replica_id or "fleet-controller",
            stale_after_s=service_cfg.replica_stale_after_s)
        self.signals = signals if signals is not None else \
            spool_signals(self.root, self.registry)
        self._lock = threading.Lock()
        self._children: dict[str, _Child] = {}
        self._state = FleetState()
        self._next_ordinal = 1
        self.scale_events = {"up": 0, "down": 0}
        self.drains_total = 0
        self.crashes_total = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._m_replicas = self._m_target = None
        self._m_scale = self._m_drains = self._m_crashes = None
        self._m_spawn_fail = self._m_hosts = None
        if metrics is not None:
            self.attach_metrics(metrics)

    # ------------------------------------------------------------- metrics
    def attach_metrics(self, m) -> None:
        self._m_replicas = m.gauge(
            "sm_fleet_replicas",
            "Non-draining replicas with a fresh registry heartbeat")
        self._m_target = m.gauge(
            "sm_fleet_target_replicas",
            "Fleet size the controller is currently steering toward")
        self._m_scale = m.counter(
            "sm_fleet_scale_events_total",
            "Autoscaling actions taken, by direction", ("direction",))
        self._m_drains = m.counter(
            "sm_fleet_drains_total",
            "Zero-loss drains completed (ack + exit) by scale-down victims")
        self._m_crashes = m.counter(
            "sm_fleet_crashes_total",
            "Supervised replicas that exited without a drain request")
        self._m_spawn_fail = m.counter(
            "sm_fleet_spawn_failures_total",
            "Replica spawns that never registered a heartbeat in time")
        self._m_hosts = m.gauge(
            "sm_fleet_hosts",
            "Host failure domains of the device pool the fleet schedules "
            "over")
        self._m_hosts.set(self.service_cfg.device_pool_hosts)

    # ------------------------------------------------------------- liveness
    def alive_replicas(self) -> list[dict]:
        """Registry truth: non-draining replicas with fresh heartbeats."""
        return [p for p in self.registry.peers()
                if p.get("alive") and not p.get("draining")
                and str(p.get("replica_id", "")) != "fleet-controller"]

    def status(self) -> dict:
        with self._lock:
            children = {rid: {
                "pid": c.proc.pid, "registered": c.registered,
                "draining": c.draining,
                "exited": c.proc.poll(),
                **({"host": c.host} if c.host else {}),
            } for rid, c in self._children.items()}
            state = self._state
            events = dict(self.scale_events)
            drains, crashes = self.drains_total, self.crashes_total
        return {
            "alive": len(self.alive_replicas()),
            "min": self.cfg.min_replicas, "max": self.cfg.max_replicas,
            "children": children, "scale_events": events,
            "drains_total": drains, "crashes_total": crashes,
            "high_ticks": state.high_ticks, "low_ticks": state.low_ticks,
            "last_scale_at": state.last_scale_at,
        }

    # -------------------------------------------------------------- actions
    def _new_rid_locked(self) -> str:
        # monotonically increasing ordinals: a respawn is a NEW identity,
        # so a dead incarnation's registry/lease debris can never be
        # mistaken for the replacement's
        rid = f"{self.replica_prefix}{self._next_ordinal}"
        self._next_ordinal += 1
        return rid

    def _pick_host_locked(self) -> str:
        """Least-loaded named host (caller holds the lock): spread replicas
        over the pod's hosts; ties break toward the earlier name so
        placement is deterministic."""
        if not self.hosts:
            return ""
        load = {h: 0 for h in self.hosts}
        for c in self._children.values():
            if c.host in load and c.proc.poll() is None:
                load[c.host] += 1
        return min(self.hosts, key=lambda h: (load[h], self.hosts.index(h)))

    def _scale_up(self, now: float) -> None:
        with self._lock:
            rid = self._new_rid_locked()
            host = self._pick_host_locked()
        if host and host not in self._warmed_hosts and \
                self.warm_host is not None:
            # per-host primer warm-up (ISSUE 17): the first replica placed
            # on a host pays that host's cold XLA cache — warm it before
            # the replica takes traffic; a warm-up failure is logged, not
            # fatal (the replica just compiles on first use)
            try:
                self.warm_host(host)
            except Exception:
                logger.warning("fleet: primer warm-up for host %s failed",
                               host, exc_info=True)
        if host:
            self._warmed_hosts.add(host)
        # the controller-killed-mid-spawn seam: a crash here loses only
        # the controller — no replica, no claims; the restarted controller
        # re-reads the registry and repairs the fleet
        failpoint(FP_FLEET_SPAWN)
        try:
            proc = (self.spawn(rid, host) if self._spawn_takes_host
                    else self.spawn(rid))
        except OSError as exc:
            logger.error("fleet: spawn of %s failed: %s", rid, exc)
            if self._m_spawn_fail is not None:
                self._m_spawn_fail.inc()
            return
        with self._lock:
            self._children[rid] = _Child(rid=rid, proc=proc, spawned_at=now,
                                         host=host)
            self.scale_events["up"] += 1
        if self._m_scale is not None:
            self._m_scale.labels(direction="up").inc()
        tracing.event("fleet.scale", direction="up", rid=rid,
                      **({"host": host} if host else {}))
        logger.info("fleet: scale UP — spawned replica %s (pid %d%s)",
                    rid, proc.pid, f" on host {host}" if host else "")

    def _pending_spawns_locked(self) -> int:
        """Children spawned but not yet registered (still importing / warming
        up).  They count toward the fleet for decisions — otherwise the
        repair rule re-spawns every tick of the registration lag and the
        fleet storms past its ceiling."""
        return sum(1 for c in self._children.values()
                   if not c.registered and not c.draining
                   and c.proc.poll() is None)

    def _pick_victim_locked(self) -> _Child | None:
        """Newest REGISTERED non-draining child (LIFO — the seed replica
        and this process's own replica are never drained by autoscaling;
        a child that hasn't registered yet would wipe the drain sentinel
        when it does)."""
        candidates = [c for c in self._children.values()
                      if c.registered and not c.draining
                      and c.proc.poll() is None]
        if not candidates:
            return None
        return max(candidates, key=lambda c: c.spawned_at)

    def _scale_down(self, now: float) -> None:
        with self._lock:
            victim = self._pick_victim_locked()
            if victim is None:
                return
            victim.draining = True
            victim.drain_requested_at = now
            self.scale_events["down"] += 1
        self.registry.request_drain(victim.rid, by="fleet-controller")
        if self._m_scale is not None:
            self._m_scale.labels(direction="down").inc()
        tracing.event("fleet.scale", direction="down", rid=victim.rid)
        logger.info("fleet: scale DOWN — draining replica %s", victim.rid)

    # ----------------------------------------------------------- reconcile
    def _reconcile(self, now: float) -> None:
        """Sweep the child table: finished drains are cleaned up and
        counted; exits without a drain request are crashes (the decide
        loop repairs the fleet back to min on its next tick); stalled
        drains past ``drain_timeout_s`` are force-killed (from there the
        victim is just a crashed replica — takeover recovers its claims);
        spawns that never registered a heartbeat in ``spawn_timeout_s``
        are failed and culled."""
        with self._lock:
            children = list(self._children.values())
        alive_ids = {str(p.get("replica_id")) for p in self.registry.peers()
                     if p.get("alive")}
        for c in children:
            if not c.registered and c.rid in alive_ids:
                # child drain-sentinel state rides the same lock as the
                # child table (ISSUE 12 satellite): status()/metric reads
                # must never see a half-applied registered/draining pair
                with self._lock:
                    c.registered = True
                    re_request = c.draining
                if re_request and not self.registry.drain_requested(c.rid):
                    # the victim registered AFTER the drain request and
                    # wiped the sentinel (register clears prior-incarnation
                    # drains) — re-request against the live incarnation
                    self.registry.request_drain(c.rid, by="fleet-controller")
            rc = c.proc.poll()
            if rc is not None:
                if c.draining:
                    # drained: ack + exit = zero-loss completion; remove
                    # the sentinel so a future replica under this id (none
                    # is ever minted, but operators can) starts clean
                    acked = self.registry.drain_acked(c.rid)
                    self.registry.clear_drain(c.rid)
                    with self._lock:
                        self._children.pop(c.rid, None)
                        self.drains_total += 1
                    if self._m_drains is not None:
                        self._m_drains.inc()
                    record_recovery("fleet.drain_complete"
                                    if acked else "fleet.drain_exit_unacked")
                    logger.info("fleet: replica %s drained (rc=%s, "
                                "acked=%s)", c.rid, rc, acked)
                else:
                    with self._lock:
                        self._children.pop(c.rid, None)
                        self.crashes_total += 1
                    if self._m_crashes is not None:
                        self._m_crashes.inc()
                    record_recovery("fleet.crash_detected")
                    logger.warning("fleet: replica %s exited rc=%s without "
                                   "a drain request — counting it crashed; "
                                   "survivors take over its shards", c.rid, rc)
                continue
            if c.draining and now - c.drain_requested_at >= \
                    self.cfg.drain_timeout_s:
                logger.error("fleet: replica %s stalled mid-drain for "
                             ">%.0fs — force-killing (takeover will fence "
                             "+ requeue its claims)",
                             c.rid, self.cfg.drain_timeout_s)
                c.proc.kill()
                continue
            if not c.registered and c.rid not in alive_ids and \
                    now - c.spawned_at >= self.cfg.spawn_timeout_s:
                logger.error("fleet: replica %s never registered within "
                             "%.0fs — killing the spawn",
                             c.rid, self.cfg.spawn_timeout_s)
                if self._m_spawn_fail is not None:
                    self._m_spawn_fail.inc()
                c.proc.kill()
                with self._lock:
                    self._children.pop(c.rid, None)

    # ------------------------------------------------------------ the loop
    def tick(self, now: float | None = None) -> int:
        """One supervision + decision cycle (the loop body; tests call it
        directly).  Returns the action taken (+1/0/-1)."""
        now = time.time() if now is None else now
        self._reconcile(now)
        try:
            signals = self.signals()
        except Exception:
            logger.warning("fleet: signal collection failed", exc_info=True)
            return 0
        with self._lock:
            state = self._state
            pending = self._pending_spawns_locked()
        if pending:
            signals = replace(signals, alive=signals.alive + pending)
        delta, new_state = decide(self.cfg, state, signals, now)
        with self._lock:
            self._state = new_state
        if self._m_replicas is not None:
            self._m_replicas.set(signals.alive)
            self._m_target.set(max(self.cfg.min_replicas,
                                   min(self.cfg.max_replicas,
                                       signals.alive + delta)))
        if delta > 0:
            self._scale_up(now)
        elif delta < 0:
            self._scale_down(now)
        return delta

    def _loop(self) -> None:
        while not self._stop.wait(self.cfg.decide_interval_s):
            try:
                self.tick()
            except Exception:         # the controller must never die
                logger.error("fleet: controller tick failed", exc_info=True)

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("fleet controller already started")
        self.tick()                   # first decision immediately (repair
                                      # an under-min fleet before sleeping)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="fleet-controller")
        self._thread.start()
        logger.info("fleet: controller up (min=%d max=%d, %d host(s))",
                    self.cfg.min_replicas, self.cfg.max_replicas,
                    self.service_cfg.device_pool_hosts)

    def shutdown(self, drain: bool = True,
                 timeout_s: float | None = None) -> None:
        """Stop the loop and retire the children: request drains (zero
        loss), wait out the drain timeout, then escalate to SIGTERM/kill."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        timeout_s = self.cfg.drain_timeout_s if timeout_s is None else timeout_s
        with self._lock:
            children = list(self._children.values())
        if drain:
            for c in children:
                if c.proc.poll() is None and not c.draining:
                    c.draining = True
                    c.drain_requested_at = time.time()
                    self.registry.request_drain(c.rid, by="fleet-shutdown")
        deadline = time.time() + timeout_s
        for c in children:
            try:
                c.proc.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                c.proc.terminate()
                try:
                    c.proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    c.proc.kill()
        # final reconcile so drains that completed during shutdown are
        # counted and their sentinels cleaned, then sweep what remains
        self._reconcile(time.time())
        with self._lock:
            leftovers = list(self._children)
            self._children.clear()
        for rid in leftovers:
            self.registry.clear_drain(rid)
        logger.info("fleet: controller stopped")


# --------------------------------------------------------------- spawn glue
def serve_spawn(queue_dir: str | Path, sm_config_path: str | Path,
                extra_args: tuple = (), env: dict | None = None):
    """Production spawn factory: each replica is a full ``serve`` process
    over the shared spool under its own identity, with an ephemeral admin
    port (the parent already owns the configured one) and its own fleet
    controller DISABLED (exactly one controller per fleet)."""
    import os
    import sys

    def _spawn(rid: str, host: str = "") -> subprocess.Popen:
        cmd = [sys.executable, "-m", "sm_distributed_tpu.engine.cli",
               "serve", str(queue_dir), "--sm-config", str(sm_config_path),
               "--replica-id", rid, "--port", "0", *extra_args]
        child_env = dict(env) if env is not None else dict(os.environ)
        if host:
            # named-host placement (ISSUE 17): the replica's pod identity
            # — process_identity() reads SM_HOST_NAME — so its beats group
            # under the right host for the watchdog
            child_env["SM_HOST_NAME"] = host
        return subprocess.Popen(cmd, env=child_env)

    return _spawn


def write_child_config(sm_config, work_dir: str | Path) -> Path:
    """Serialize the resolved SMConfig for spawned replicas, with
    ``fleet.enabled`` forced off so children never start their own
    controllers."""
    import dataclasses

    d = dataclasses.asdict(sm_config)
    d["service"]["fleet"]["enabled"] = False
    out = Path(work_dir) / "fleet"
    out.mkdir(parents=True, exist_ok=True)
    p = out / "replica_sm.json"
    tmp = out / ".replica_sm.json.tmp"
    tmp.write_text(json.dumps(d, indent=2))
    tmp.replace(p)
    return p
