"""Service layer — long-running annotation service over the spool daemon.

The reference deploys the engine behind RabbitMQ with one blocking consumer
per daemon process (SURVEY.md #16); this subsystem is the production-serving
shape the ROADMAP north star asks for on top of the same spool contract:

- ``scheduler``  — concurrent job scheduler: worker pool draining the spool,
  priority classes + per-tenant fairness, device-bound phases serialized via
  a TPU token while CPU staging/parse overlap;
- ``scheduler``  — failure policy: per-job timeout, retry with exponential
  backoff + jitter, bounded attempts, dead-letter into ``failed/`` with the
  recorded traceback, heartbeat files for crash-vs-slow discrimination;
- ``metrics``    — counters/gauges/histograms with Prometheus text
  exposition, threaded through ``phase_timer`` and ``DatasetResidency``;
- ``api``        — stdlib ``http.server`` admin API (``/healthz``,
  ``/metrics``, ``/jobs``, ``POST /submit``);
- ``server``     — ``AnnotationService`` composing all of the above with
  graceful SIGTERM shutdown (drain running, requeue claimed-but-unstarted).

Everything here is exercisable on CPU (``JAX_PLATFORMS=cpu``) with fake job
callbacks — see ``tests/test_service.py``.
"""

from .metrics import Counter, Gauge, Histogram, MetricsRegistry
from .scheduler import JobRecord, JobScheduler, RetryPolicy
from .server import AnnotationService

__all__ = [
    "AnnotationService",
    "Counter",
    "Gauge",
    "Histogram",
    "JobRecord",
    "JobScheduler",
    "MetricsRegistry",
    "RetryPolicy",
]
