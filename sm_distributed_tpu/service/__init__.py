"""Service layer — long-running annotation service over the spool daemon.

The reference deploys the engine behind RabbitMQ with one blocking consumer
per daemon process (SURVEY.md #16); this subsystem is the production-serving
shape the ROADMAP north star asks for on top of the same spool contract:

- ``scheduler``  — concurrent job scheduler: worker pool draining the spool,
  priority classes + per-tenant fairness, device-bound phases running under
  per-job **device-pool leases** (``device_pool``: 1..N chips per job —
  small jobs pack onto distinct chips and run concurrently, sub-mesh jobs
  score pjit-sharded) while CPU staging/parse overlap;
- ``scheduler``  — failure policy: per-job timeout with COOPERATIVE
  cancellation (``utils/cancel.CancelToken`` threaded through the job,
  checked at checkpoint-group boundaries), retry with exponential backoff +
  jitter, bounded attempts, dead-letter into ``failed/`` with the recorded
  traceback, heartbeat files for crash-vs-slow discrimination, deadline
  propagation, a stall watchdog, and crash-loop quarantine;
- ``admission``  — overload protection for ``POST /submit``: bounded queue
  depth, per-tenant quotas, EWMA latency shedding with hysteresis —
  structured 429/503 + ``Retry-After`` instead of an unbounded backlog;
- ``metrics``    — counters/gauges/histograms with Prometheus text
  exposition, threaded through ``phase_timer`` and ``DatasetResidency``;
- ``telemetry``  — device/HBM monitor + SLO tracker: per-device HBM
  gauges, device-token occupancy, XLA persistent-cache size/hit-miss,
  a bounded metric-snapshot ring (``GET /debug/timeseries``), and
  queue-wait / first-annotation / e2e SLO histograms with attainment
  and error-budget burn served by ``GET /slo``;
- ``api``        — stdlib ``http.server`` admin API (``/healthz``,
  ``/metrics``, ``/jobs``, ``POST /submit``, ``DELETE /jobs/<id>``);
- ``fleet``      — elastic replica fleet (docs/SERVICE.md "Elasticity
  model"): a FleetController supervising replica subprocesses, scaling
  between ``fleet.min_replicas`` and ``fleet.max_replicas`` on /slo
  error-budget burn + queue depth + pool occupancy, with zero-loss drain
  on scale-down and crash-vs-drain discrimination;
- ``server``     — ``AnnotationService`` composing all of the above (plus
  the device circuit breaker, ``models/breaker.py``) with graceful SIGTERM
  shutdown (drain running, requeue claimed-but-unstarted).

The overload/degradation layer is proven end to end by
``scripts/load_sweep.py`` (docs/SERVICE.md "Overload & degradation model").

Everything here is exercisable on CPU (``JAX_PLATFORMS=cpu``) with fake job
callbacks — see ``tests/test_service.py``.
"""

from .admission import AdmissionController
from .device_pool import DeviceLease, DevicePool
from .fleet import FleetController, FleetSignals, FleetState
from .metrics import Counter, Gauge, Histogram, MetricsRegistry
from .scheduler import JobRecord, JobScheduler, RetryPolicy
from .server import AnnotationService
from .telemetry import DeviceMonitor, SLOTracker

__all__ = [
    "AdmissionController",
    "AnnotationService",
    "Counter",
    "DeviceLease",
    "DeviceMonitor",
    "DevicePool",
    "FleetController",
    "FleetSignals",
    "FleetState",
    "Gauge",
    "Histogram",
    "JobRecord",
    "JobScheduler",
    "MetricsRegistry",
    "RetryPolicy",
    "SLOTracker",
]
