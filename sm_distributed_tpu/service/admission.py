"""Admission control for ``POST /submit`` (ISSUE 4 tentpole).

The spool used to accept every submit unconditionally — under a sustained
burst arriving faster than chips score (the arXiv:2102.05604 regime) the
backlog, and every client's latency, grew without bound.  This controller
makes overload a *structured, fast* rejection instead of a slow failure:

- **bounded depth** — at most ``admission.max_queue_depth`` messages may be
  admitted-but-not-terminal across the service (429 ``queue_full``);
- **per-tenant quotas** — at most ``admission.max_tenant_inflight`` per
  tenant (429 ``tenant_quota``), so one tenant's burst cannot consume the
  whole bound and starve the rest (the dispatcher already runs tenant-fair
  *admission order*; this bounds tenant *occupancy*);
- **latency shedding with hysteresis** — an EWMA of recent job latency
  crossing ``admission.latency_shed_s`` sheds ALL submits (503
  ``latency_overload``) until the EWMA falls back below
  ``admission.effective_resume_s``; the gap prevents flapping at the
  threshold.

Every shed carries ``retry_after_s`` (surfaced as the HTTP ``Retry-After``
header).  Occupancy is tracked exactly by ``msg_id``: the API confirms an
admission after the durable publish, and the scheduler reports terminal
outcomes (done / failed / cancelled / quarantined).  On restart the pending
backlog is re-synced from the spool so quotas survive a bounce.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass
from pathlib import Path

from ..utils.config import AdmissionConfig
from ..utils.logger import logger
from .resources import get_governor


@dataclass
class Decision:
    """Outcome of one admission attempt, ready to serialize as the HTTP
    response (429/503 + Retry-After + structured body on shed)."""

    accepted: bool
    status: int = 202
    reason: str = "accepted"
    retry_after_s: float = 0.0
    detail: str = ""

    def body(self) -> dict:
        return {
            "error": self.detail or self.reason,
            "reason": self.reason,
            "retry_after_s": round(self.retry_after_s, 3),
        }


class AdmissionController:
    """Thread-safe occupancy + latency tracking behind ``/submit``."""

    # shared-state registry checked by the smlint guarded-by rule
    # (docs/ANALYSIS.md): mutated only under _lock (*_locked methods are
    # the documented caller-holds-lock exception)
    _GUARDED_BY = {"_depth": "_lock", "_tenant_inflight": "_lock",
                   "_tenant_by_msg": "_lock", "_ewma": "_lock",
                   "_shedding": "_lock"}

    def __init__(self, cfg: AdmissionConfig, metrics=None):
        self.cfg = cfg
        self._lock = threading.Lock()
        self._tenant_inflight: dict[str, int] = {}
        self._tenant_by_msg: dict[str, str] = {}
        self._depth = 0
        self._ewma: float | None = None
        self._shedding = False
        # multi-replica gossip (ISSUE 8): a provider returning the alive
        # PEERS' admission summaries (each the shape of ``stats()``), fed
        # from the replica registry.  Admission state stays replica-local;
        # quotas and shed decisions apply to the local + peer-reported
        # APPROXIMATION of the global picture.
        self._peer_view = None
        self.m_decisions = None
        if metrics is not None:
            self._init_metrics(metrics)

    def set_peer_view(self, provider) -> None:
        """Install the peer-summary provider (``JobScheduler.
        peer_admission_summaries``).  ``None`` restores single-replica
        behavior."""
        self._peer_view = provider

    def _peer_summaries(self) -> list[dict]:
        if self._peer_view is None:
            return []
        try:
            return [p for p in self._peer_view() if isinstance(p, dict)]
        except Exception:
            logger.warning("admission: peer view failed; using local state "
                           "only", exc_info=True)
            return []

    # -------------------------------------------------------------- metrics
    def _init_metrics(self, m) -> None:
        self.m_decisions = m.counter(
            "sm_admission_total",
            "Submit admission decisions, by outcome and reason",
            ("decision", "reason"))
        m.add_collector(self._collect)

    def _collect(self, m) -> None:
        with self._lock:
            ewma = self._ewma or 0.0
            depth = self._depth
            shed = self._shedding
        m.gauge("sm_admission_latency_ewma_s",
                "EWMA of recent job latency driving the shed decision").set(ewma)
        m.gauge("sm_admission_depth",
                "Admitted-but-not-terminal messages tracked by admission").set(depth)
        m.gauge("sm_admission_shedding",
                "1 while the latency-overload shed is engaged").set(int(shed))

    def _count(self, decision: str, reason: str) -> None:
        if self.m_decisions is not None:
            self.m_decisions.labels(decision=decision, reason=reason).inc()

    # ------------------------------------------------------------ admission
    def try_admit(self, tenant: str) -> Decision:
        """Reserve one slot for ``tenant`` (or shed).  The caller MUST
        follow up with ``confirm(msg_id, tenant)`` after a durable publish,
        or ``abort(tenant)`` if publishing failed.

        With a peer view installed, the depth/quota/shed checks run
        against the local + peer-reported GLOBAL estimate (with each
        bound scaled by nothing — the bounds are cluster-wide), so N
        replicas approximately enforce one shared quota without shared
        state.  Peer numbers are one heartbeat old at worst; the
        approximation errs by at most one beat's worth of admissions."""
        cfg = self.cfg
        # disk exhaustion (ISSUE 10, service/resources.py): the LAST step
        # of the degrade order — traces and cache writes are already being
        # dropped by the time submits shed.  507 Insufficient Storage with
        # Retry-After: accepting a job we cannot durably store its results
        # for would only convert the client's retry into a dead-letter.
        governor = get_governor()
        if governor is not None and governor.submits_shed():
            d = Decision(False, 507, "disk_exhausted", cfg.retry_after_s,
                         "disk budget exhausted: new submits shed until "
                         "the retention sweeper (or an operator) frees "
                         "space")
            self._count("shed", d.reason)
            return d
        peers = self._peer_summaries()
        peer_depth = sum(int(p.get("depth", 0)) for p in peers)
        peer_tenant = sum(int((p.get("tenants") or {}).get(tenant, 0))
                          for p in peers)
        peer_ewmas = [float(p["latency_ewma_s"]) for p in peers
                      if isinstance(p.get("latency_ewma_s"), (int, float))]
        peer_shedding = any(p.get("shedding") for p in peers)
        with self._lock:
            depth = self._depth + peer_depth
            tenant_inflight = self._tenant_inflight.get(tenant, 0) + peer_tenant
            shed_ewma = max([self._ewma or 0.0] + peer_ewmas)
            if self._shedding or (
                    peer_shedding and cfg.latency_shed_s > 0) or (
                    cfg.latency_shed_s > 0
                    and shed_ewma >= cfg.latency_shed_s
                    and not self._shedding and peer_ewmas
                    and shed_ewma > (self._ewma or 0.0)):
                d = Decision(False, 503, "latency_overload", cfg.retry_after_s,
                             f"job latency EWMA {shed_ewma:.2f}s over the "
                             f"{cfg.latency_shed_s:.2f}s shed threshold"
                             + ("" if self._shedding else " (peer-reported)"))
            elif cfg.max_queue_depth and depth >= cfg.max_queue_depth:
                d = Decision(False, 429, "queue_full", cfg.retry_after_s,
                             f"queue depth {depth} at the "
                             f"{cfg.max_queue_depth} bound"
                             + (f" ({peer_depth} on peers)"
                                if peer_depth else ""))
            elif cfg.max_tenant_inflight and tenant_inflight >= \
                    cfg.max_tenant_inflight:
                d = Decision(False, 429, "tenant_quota", cfg.retry_after_s,
                             f"tenant {tenant!r} at its "
                             f"{cfg.max_tenant_inflight} in-flight quota"
                             + (f" ({peer_tenant} on peers)"
                                if peer_tenant else ""))
            else:
                self._depth += 1
                self._tenant_inflight[tenant] = (
                    self._tenant_inflight.get(tenant, 0) + 1)
                d = Decision(True)
        self._count("accepted" if d.accepted else "shed", d.reason)
        return d

    def confirm(self, msg_id: str, tenant: str) -> None:
        """Bind a reserved slot to its published msg_id so the scheduler's
        terminal report can release it."""
        with self._lock:
            self._tenant_by_msg[msg_id] = tenant

    def abort(self, tenant: str) -> None:
        """Release a reservation whose publish failed."""
        with self._lock:
            self._release_locked(tenant)

    def _release_locked(self, tenant: str) -> None:
        self._depth = max(0, self._depth - 1)
        n = self._tenant_inflight.get(tenant, 0) - 1
        if n > 0:
            self._tenant_inflight[tenant] = n
        else:
            self._tenant_inflight.pop(tenant, None)

    # ------------------------------------------------- scheduler-side hooks
    def note_terminal(self, msg_id: str) -> None:
        """A tracked message reached done/failed/cancelled/quarantined.
        Unknown msg_ids (direct QueuePublisher submits) are a no-op."""
        with self._lock:
            tenant = self._tenant_by_msg.pop(msg_id, None)
            if tenant is not None:
                self._release_locked(tenant)

    def observe_latency(self, seconds: float) -> None:
        """Fold one completed attempt's wall clock into the EWMA and apply
        the shed/resume hysteresis."""
        cfg = self.cfg
        with self._lock:
            a = cfg.ewma_alpha
            self._ewma = seconds if self._ewma is None else (
                a * seconds + (1.0 - a) * self._ewma)
            if cfg.latency_shed_s <= 0:
                return
            if not self._shedding and self._ewma >= cfg.latency_shed_s:
                self._shedding = True
                logger.warning(
                    "admission: latency shed ENGAGED (EWMA %.2fs >= %.2fs)",
                    self._ewma, cfg.latency_shed_s)
            elif self._shedding and self._ewma <= cfg.effective_resume_s:
                self._shedding = False
                logger.info(
                    "admission: latency shed released (EWMA %.2fs <= %.2fs)",
                    self._ewma, cfg.effective_resume_s)

    # ---------------------------------------------------------------- state
    def sync_from_spool(self, queue_root: str | Path,
                        owns_msg=None) -> int:
        """Re-adopt the pending backlog after a restart so depth/quota
        tracking survives a service bounce.  Only ``pending/`` is adopted —
        running claims re-enter tracking when they terminate as unknown
        no-ops, which errs on the permissive side.

        Multi-replica: ``owns_msg(msg_id)`` scopes adoption to this
        replica's shards — peers adopt (and gossip) their own partitions,
        so the global estimate counts each message once."""
        n = 0
        for p in sorted(Path(queue_root).glob("pending/*.json")):
            if owns_msg is not None and not owns_msg(p.stem):
                continue
            try:
                msg = json.loads(p.read_text())
                tenant = str(msg.get("tenant", "default")) \
                    if isinstance(msg, dict) else "default"
            except (OSError, json.JSONDecodeError):
                tenant = "default"
            with self._lock:
                self._depth += 1
                self._tenant_inflight[tenant] = (
                    self._tenant_inflight.get(tenant, 0) + 1)
                self._tenant_by_msg[p.stem] = tenant
            n += 1
        if n:
            logger.info("admission: adopted %d pending message(s) from the spool", n)
        return n

    def stats(self) -> dict:
        with self._lock:
            return {
                "depth": self._depth,
                "tenants": dict(self._tenant_inflight),
                "latency_ewma_s": self._ewma,
                "shedding": self._shedding,
            }
