"""Device-pool allocator: 1..N-chip leases instead of ONE TPU token.

ISSUE 7 tentpole.  Since PR 1 the scheduler serialized every job's
device-bound phase behind a single ``threading.Lock`` (``device_token``) —
correct on a 1-chip host, but ``MULTICHIP_r*.json`` shows 8 chips visible
and the lock let exactly one of them work at a time.  This module replaces
the token with a **pool**:

- a job asks for ``1..N`` chips (``service.devices_per_job`` default, a
  per-submit ``devices`` field overrides);
- **small jobs pack**: two 1-chip jobs get DISTINCT chips and run their
  device phases concurrently;
- **large jobs claim a contiguous sub-mesh**: an N-chip lease is a
  contiguous run of device indices, which ``parallel/mesh.make_mesh``
  turns into a pixels×formulas mesh for the pjit/GSPMD-sharded scoring
  path (``parallel/sharded.py``);
- **FIFO-ish fairness**: waiters are served in arrival order; a waiter
  whose request cannot currently be satisfied is skipped (so small jobs
  keep packing around a waiting sub-mesh job), but after ``max_bypass``
  skips the starved waiter *seals* the queue — no later grant is made
  until the pool drains enough to serve it;
- **crash/cancel safety**: a lease is released by its ``with`` exit on the
  happy path AND unconditionally by the scheduler worker's ``finally`` —
  release is idempotent, and releasing a never-granted lease simply
  deregisters it from the wait queue (the cancelled-while-waiting path).

Backward compatibility: ``DeviceLease`` speaks the ``threading.Lock``
protocol (``acquire(timeout=)`` / ``release()`` / ``locked()`` / context
manager), so ``utils/cancel.hold_cancellable`` — and every callback that
did ``with ctx.device_token:`` — works unchanged.  ``DevicePool`` itself
also speaks it (each ``acquire`` takes one chip), so code that poked the
old ``scheduler.device_token`` lock still behaves.

Metrics (``attach_metrics``): ``sm_device_pool_in_use{device=}``,
``sm_device_pool_devices``, ``sm_device_pool_waiters``,
``sm_device_pool_grants_total``, ``sm_device_pool_wait_seconds``.
"""

from __future__ import annotations

import sys
import threading
import time

from ..utils.logger import logger
from .health import HealthTracker, host_of_ranges, split_host_ranges


class DeviceLease:
    """A (pending or granted) claim on ``n`` chips from a :class:`DevicePool`.

    Lock-protocol compatible: ``acquire`` blocks (or polls, with
    ``timeout``) until the pool grants a contiguous run of ``n`` chips;
    the lease KEEPS its queue position across timed-out polls, so the
    ``hold_cancellable`` poll loop cannot lose its place in line.
    """

    def __init__(self, pool: "DevicePool", n: int, msg_id: str = ""):
        self.pool = pool
        self.n = int(n)
        self.msg_id = msg_id
        self.devices: tuple[int, ...] = ()   # granted chip indices
        self.last_wait_s: float = 0.0        # first-acquire -> grant
        self._bypassed = 0                   # grants that jumped this waiter
        self._queued = False
        self._waiting_since = 0.0

    @property
    def hosts(self) -> tuple[int, ...]:
        """Host failure domains this grant spans (ISSUE 11): empty while
        ungranted, one host for packed small jobs, several for a sub-mesh
        lease spanning the host dimension."""
        return tuple(sorted({self.pool.host_of(i) for i in self.devices}))

    # ------------------------------------------------- lock protocol
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        return self.pool._acquire(self, blocking, timeout)

    def release(self) -> None:
        self.pool._release(self)

    def locked(self) -> bool:
        return bool(self.devices)

    def __enter__(self) -> "DeviceLease":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        state = f"devices={self.devices}" if self.devices else \
            ("waiting" if self._queued else "idle")
        return f"DeviceLease(n={self.n}, msg_id={self.msg_id!r}, {state})"


class DevicePool:
    """Allocate contiguous chip runs to leases, FIFO-ish, crash-safe."""

    # shared-state registry checked by the smlint guarded-by rule
    # (docs/ANALYSIS.md): mutated only under _cond (methods named *_locked
    # are the documented caller-holds-lock exception)
    _GUARDED_BY = {"_owner": "_cond", "_waiters": "_cond",
                   "_compat": "_cond", "grants_total": "_cond",
                   "releases_total": "_cond", "leases_reaped_total": "_cond"}

    def __init__(self, size: int, max_bypass: int = 64, hosts: int = 1,
                 health: HealthTracker | None = None):
        if size <= 0:
            raise ValueError(f"device pool size must be positive, got {size}")
        self.size = int(size)
        self.max_bypass = max(0, int(max_bypass))
        # host dimension (ISSUE 11): the pool's chips split into `hosts`
        # failure domains — the jax.distributed host×chip topology,
        # simulated on CPU.  Grants PREFER a run within one host (a
        # single-host sub-mesh has no cross-host collectives and dies with
        # exactly one host); a lease wider than a host spans hosts and
        # reports them.  Since ISSUE 17 the split is EXPLICIT per-host
        # ranges (split_host_ranges warns on ragged configs) instead of
        # silently degrading a non-dividing host count to one host.
        self.host_ranges = split_host_ranges(self.size, max(1, int(hosts)))
        self.hosts = len(self.host_ranges)
        self.chips_per_host = self.size // self.hosts   # legacy accessor
        self._host_of = host_of_ranges(self.host_ranges)
        self._host_starts = frozenset(lo for lo, _ in self.host_ranges)
        self._max_host_chips = max(hi - lo for lo, hi in self.host_ranges)
        # per-chip health (ISSUE 14, service/health.py): quarantined chips
        # are excluded from grants, granted chips are lease-time probed,
        # and a half-open re-probe readmits recovered chips.  The tracker
        # has its own leaf lock; the pool always takes _cond first.
        self.health = health if health is not None else \
            HealthTracker(self.size, hosts=self.hosts)
        self._cond = threading.Condition()
        self._owner: list[DeviceLease | None] = [None] * self.size
        self._waiters: list[DeviceLease] = []
        self._compat: list[DeviceLease] = []   # legacy single-token grants
        self.grants_total = 0
        self.releases_total = 0
        self.leases_reaped_total = 0
        self._m_grants = None
        self._m_wait = None
        self._m_in_use = None
        self._m_waiters = None
        self._m_reaped = None

    # ------------------------------------------------------------ metrics
    def attach_metrics(self, registry) -> None:
        if self._m_grants is not None:
            return
        self._m_grants = registry.counter(
            "sm_device_pool_grants_total", "Device-pool leases granted")
        self._m_wait = registry.histogram(
            "sm_device_pool_wait_seconds",
            "Lease wait from first acquire to grant",
            buckets=(0.001, 0.01, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0, 600.0))
        self._m_in_use = registry.gauge(
            "sm_device_pool_in_use",
            "1 when the chip is held by a job lease, per device", ("device",))
        for i in range(self.size):
            self._m_in_use.labels(device=str(i)).set(0)
        registry.gauge(
            "sm_device_pool_devices",
            "Chips in the scheduler's device pool").set(self.size)
        self._m_waiters = registry.gauge(
            "sm_device_pool_waiters", "Leases currently waiting for chips")
        registry.gauge(
            "sm_device_pool_hosts",
            "Host failure domains the pool's chips split into").set(
            self.hosts)
        self._m_reaped = registry.counter(
            "sm_device_pool_leases_reaped_total",
            "Abandoned-attempt leases reclaimed by the zombie reaper",
            ("reason",))
        # per-chip health family (ISSUE 14): sm_device_health{device=},
        # quarantines/probes/readmits/host-evictions counters
        self.health.attach_metrics(registry)

    # ---------------------------------------------------------- inspection
    def lease(self, n: int, msg_id: str = "") -> DeviceLease:
        """A new unacquired lease for ``n`` chips (clamped to the pool)."""
        return DeviceLease(self, max(1, min(int(n), self.size)), msg_id)

    def in_use_count(self) -> int:
        with self._cond:
            return sum(o is not None for o in self._owner)

    def per_device_in_use(self) -> list[bool]:
        with self._cond:
            return [o is not None for o in self._owner]

    def occupancy(self) -> float:
        """Fraction of chips currently held (the pool-wide ratio the old
        single-token occupancy generalizes to)."""
        return self.in_use_count() / self.size

    def waiters(self) -> int:
        with self._cond:
            return len(self._waiters)

    def host_of(self, i: int) -> int:
        """Host failure domain of chip index ``i``."""
        return self._host_of[int(i)]

    def snapshot(self) -> dict:
        """One point-in-time view (telemetry ring / debugging)."""
        health = self.health.snapshot()
        with self._cond:
            per_host = [0] * self.hosts
            for i, o in enumerate(self._owner):
                if o is not None:
                    per_host[self._host_of[i]] += 1
            return {
                "size": self.size,
                "hosts": self.hosts,
                "in_use": sum(o is not None for o in self._owner),
                "per_host_in_use": per_host,
                "waiters": len(self._waiters),
                "grants_total": self.grants_total,
                "holders": {
                    str(i): o.msg_id for i, o in enumerate(self._owner)
                    if o is not None},
                "health": health,
            }

    # ---------------------------------------------------- grant machinery
    def _find_chips(self, n: int) -> tuple[int, ...] | None:
        """The chips a grant of ``n`` would take right now (caller holds
        the lock), or None.  Quarantined chips (``service/health.py``) are
        excluded as if permanently busy.  Preference order: a contiguous
        run within ONE host (fewest failure domains, no cross-host
        collectives), then any contiguous run, then — ONLY when quarantine
        has fragmented the pool — a non-contiguous pick of free healthy
        chips (warned at grant; a healthy-but-busy pool still waits for a
        contiguous run, exactly the pre-health semantics).  A request
        larger than the surviving healthy pool clamps down to it (the
        mesh-shrink path: the job reshapes rather than waiting forever)."""
        quarantined = self.health.quarantined()
        healthy_total = self.size - len(quarantined)
        if healthy_total <= 0:
            return None
        n_eff = min(n, healthy_total)
        if self.hosts > 1 and n_eff <= self._max_host_chips:
            start = self._scan_run(n_eff, True, quarantined)
            if start is not None:
                return tuple(range(start, start + n_eff))
        start = self._scan_run(n_eff, False, quarantined)
        if start is not None:
            return tuple(range(start, start + n_eff))
        if quarantined:
            free = [i for i in range(self.size)
                    if self._owner[i] is None and i not in quarantined]
            if len(free) >= n_eff:
                return tuple(free[:n_eff])   # host-major order
        return None

    def _scan_run(self, n: int, within_host: bool,
                  quarantined: frozenset[int]) -> int | None:
        run = 0
        for i in range(self.size):
            if self._owner[i] is None and i not in quarantined:
                if within_host and run and i in self._host_starts:
                    run = 0           # a host boundary breaks the run
                run += 1
            else:
                run = 0
            if run >= n:
                return i - n + 1
        return None

    def _grant_allowed(self, lease: DeviceLease) -> bool:
        """FIFO-ish admission (caller holds the lock): every EARLIER waiter
        either (a) can be satisfied right now — it wins, we wait; (b) cannot
        and has bypass budget left — skip it (small jobs pack around a
        waiting sub-mesh job); or (c) cannot and is starved past
        ``max_bypass`` — the queue is sealed behind it."""
        for w in self._waiters:
            if w is lease:
                return True
            if self._find_chips(w.n) is not None:
                return False
            if w._bypassed >= self.max_bypass:
                return False
        return True

    def _grant_locked(self, lease: DeviceLease,
                      chips: tuple[int, ...]) -> None:
        # caller holds self._cond
        for w in self._waiters:
            if w is lease:
                break
            w._bypassed += 1
        self._waiters.remove(lease)
        lease._queued = False
        lease.devices = tuple(chips)
        if len(chips) < lease.n:
            logger.warning(
                "device pool: clamped %d-chip lease for %s to the %d "
                "surviving healthy chip(s) %s (quarantine shrank the pool)",
                lease.n, lease.msg_id or "anonymous", len(chips), chips)
        if any(b - a != 1 for a, b in zip(chips, chips[1:])):
            logger.warning(
                "device pool: NON-CONTIGUOUS grant %s for %s — quarantine "
                "fragmented the pool (cross-chip collectives may cross "
                "fenced slots)", chips, lease.msg_id or "anonymous")
        for i in lease.devices:
            self._owner[i] = lease
        self.grants_total += 1
        lease.last_wait_s = time.monotonic() - lease._waiting_since
        if self._m_grants is not None:
            self._m_grants.inc()
            self._m_wait.observe(lease.last_wait_s)
            for i in lease.devices:
                self._m_in_use.labels(device=str(i)).set(1)
            self._m_waiters.set(len(self._waiters))

    def _acquire(self, lease: DeviceLease, blocking: bool,
                 timeout: float) -> bool:
        deadline = (time.monotonic() + timeout
                    if blocking and timeout is not None and timeout >= 0
                    else None)
        # half-open recovery (ISSUE 14): quarantined chips past their
        # re-probe cooldown get one probe here, OUTSIDE the pool lock —
        # a recovered chip rejoins the pool before this grant is evaluated
        self.health.reprobe_due()
        while True:
            granted = False
            with self._cond:
                if lease.devices:
                    raise RuntimeError(
                        f"lease for {lease.msg_id or 'anonymous'} already "
                        f"holds devices {lease.devices}")
                if not lease._queued:
                    lease._queued = True
                    lease._bypassed = 0
                    lease._waiting_since = time.monotonic()
                    self._waiters.append(lease)
                    if self._m_waiters is not None:
                        self._m_waiters.set(len(self._waiters))
                while True:
                    if self._grant_allowed(lease):
                        chips = self._find_chips(lease.n)
                        if chips is not None:
                            self._grant_locked(lease, chips)
                            granted = True
                            break
                    if not blocking:
                        return False  # stays queued — position is retained
                    if deadline is not None:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            return False  # stays queued — position retained
                        self._cond.wait(remaining)
                    else:
                        self._cond.wait()
            # lease-time health probe (ISSUE 14), outside the lock: device
            # work must never serialize the pool.  A probe failure
            # quarantines the chip; the grant is returned and re-evaluated
            # over the survivors (position kept at the queue head).
            bad = self.health.probe_lease(lease.devices)
            if not bad:
                return True
            logger.warning(
                "device pool: lease-time probe quarantined chip(s) %s — "
                "re-granting %s from the surviving pool", bad,
                lease.msg_id or "anonymous")
            self._regrant(lease)

    def _regrant(self, lease: DeviceLease) -> None:
        """Return a probe-rejected grant's chips and requeue the lease at
        the FRONT (it had already won the FIFO race; the probe verdict
        must not cost it its place in line)."""
        with self._cond:
            for i in lease.devices:
                if self._owner[i] is lease:
                    self._owner[i] = None
            if self._m_in_use is not None:
                for i in lease.devices:
                    self._m_in_use.labels(device=str(i)).set(0)
            lease.devices = ()
            lease._queued = True
            self._waiters.insert(0, lease)
            if self._m_waiters is not None:
                self._m_waiters.set(len(self._waiters))
            self._cond.notify_all()

    def _release(self, lease: DeviceLease) -> None:
        """Idempotent: frees granted chips, or deregisters a still-waiting
        lease (cancel/crash while queued), or no-ops."""
        with self._cond:
            if lease._queued:
                try:
                    self._waiters.remove(lease)
                except ValueError:
                    pass
                lease._queued = False
                if self._m_waiters is not None:
                    self._m_waiters.set(len(self._waiters))
            if lease.devices:
                for i in lease.devices:
                    if self._owner[i] is lease:
                        self._owner[i] = None
                if self._m_in_use is not None:
                    for i in lease.devices:
                        self._m_in_use.labels(device=str(i)).set(0)
                lease.devices = ()
                self.releases_total += 1
            self._cond.notify_all()

    def reap(self, lease: DeviceLease, reason: str = "exit") -> None:
        """Reclaim an abandoned attempt's lease (ISSUE 11 satellite: the
        zombie-lease leak).  ``reason`` is ``"exit"`` (the zombie thread
        finished) or ``"ttl"`` (forced after ``lease_reap_after_s``).
        No-ops when the lease already released itself (idempotent)."""
        with self._cond:
            held = bool(lease.devices) or lease._queued
            if held:
                self.leases_reaped_total += 1
        if not held:
            return
        lease.release()
        if self._m_reaped is not None:
            self._m_reaped.labels(reason=reason).inc()
        logger.info("device pool: reaped abandoned lease for %s (%s)",
                    lease.msg_id or "anonymous", reason)

    # ------------------------------------- legacy single-token protocol
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        """Back-compat with the old ``scheduler.device_token`` Lock: each
        call takes ONE chip; ``release`` frees the most recent grant."""
        lease = self.lease(1, msg_id="_token")
        ok = lease.acquire(blocking=blocking, timeout=timeout)
        if ok:
            with self._cond:
                self._compat.append(lease)
        else:
            lease.release()              # deregister the failed waiter
        return ok

    def release(self) -> None:
        with self._cond:
            if not self._compat:
                raise RuntimeError("release of un-acquired device-pool token")
            lease = self._compat.pop()
        lease.release()

    def locked(self) -> bool:
        """The single-token analog: True when EVERY chip is held."""
        with self._cond:
            return all(o is not None for o in self._owner)

    def __enter__(self) -> "DevicePool":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False


def resolve_pool_size(cfg=None, backend: str | None = None) -> int:
    """Pool size: an explicit ``service.device_pool_size`` wins; 0 = auto —
    the local jax device count when this process uses (or, for the
    ``jax_tpu`` backend, will use) jax, else 1 chip, which reproduces the
    old single-token behavior exactly."""
    explicit = int(getattr(cfg, "device_pool_size", 0) or 0)
    if explicit > 0:
        return explicit
    mod = sys.modules.get("jax")
    if mod is None and backend == "jax_tpu":
        try:
            import jax as mod  # noqa: F811 — the serve path needs it anyway
        except Exception as exc:
            logger.warning("device pool: jax unavailable (%s); "
                           "falling back to a 1-chip pool", exc)
            return 1
    if mod is None:
        return 1
    try:
        return max(1, int(mod.local_device_count()))
    except Exception as exc:
        logger.warning("device pool: jax.local_device_count() failed (%s); "
                       "falling back to a 1-chip pool", exc)
        return 1
