"""Per-chip device health tracking (ISSUE 14 tentpole, layer 2).

The DevicePool (PRs 7/11) schedules jobs onto chips but had no opinion
about whether a chip still *works*: PR 4's process-global breaker assumed
one device per process, so a single sticky chip either degraded every job
to the numpy oracle or kept getting re-leased forever.  Production
accelerator fleets (the GSPMD pod-scale setting, arXiv:2105.04663) treat
device health as pool state; this module is that state:

- every chip is ``ok`` / ``suspect`` / ``quarantined``.  Scoring-path
  faults arrive classified (``models/faults.py``) through the listener
  seam: a **sticky** fault on a 1-chip lease quarantines the chip
  outright; on an N-chip sharded lease the culprit cannot be read off the
  exception, so every leased chip turns *suspect* and a per-chip **probe**
  attributes the failure — probe failures quarantine, probe passes stay
  suspect (their fault counter still advances, so a chip that keeps
  killing sharded jobs while passing probes is quarantined after
  ``service.health_fault_quarantine`` strikes).  **Transient** faults only
  advance the counter (retry-same-chip is the policy); ``report_ok``
  resets it;
- the **lease-time probe**: the pool probes every granted chip with a
  tiny device round-trip — ``jax.device_put`` onto the chip + host
  readback — following the ``utils/devicemem`` import-light convention
  (no-op when jax was never imported, or for simulated chips beyond the
  visible device count).  Deliberately COMPILE-FREE: jax initializes its
  persistent compilation cache at most once per process, so a jitted
  probe running before the first backend's ``enable_compile_cache`` would
  latch the cache off service-wide (the compile-census gate catches
  exactly this).  A probe failure at grant time quarantines the chip
  before the job ever touches it and the pool re-grants from the
  survivors;
- **quarantined chips are excluded from grants** (``DevicePool`` treats
  them as permanently busy, relaxing contiguity when quarantine fragments
  the pool), a whole **host failure domain is evicted** when
  ``service.health_host_evict_fraction`` of its chips are out, and a
  **half-open re-probe** after ``service.health_reprobe_after_s`` readmits
  recovered chips to service.  The tracker never quarantines the LAST
  healthy chip — total loss must surface as job failures and the per-chip
  breaker's numpy degrade, not as a pool that can grant nothing forever.

Observability: ``sm_device_health{device=}`` (0 ok / 1 suspect / 2
quarantined), ``sm_device_quarantines_total``, ``sm_device_probes_total
{result=}``, ``sm_device_readmits_total``, ``sm_device_host_evictions_
total``; ``device.quarantine`` / ``device.probe`` / ``device.readmit`` /
``device.host_evict`` trace + recovery events; ``GET /debug/devices`` and
health keys on ``GET /debug/timeseries``.

Chaos/test seam: real chip faults cannot occur on the CPU CI mesh, so the
probe consults ``SM_HEALTH_BAD_CHIPS`` (comma-separated chip indices, or
:meth:`HealthTracker.simulate_bad` in-process) — the probe-level analog of
the ``SM_FAILPOINTS`` grammar, used by ``scripts/device_chaos.py`` and the
``device.probe`` failpoint scenarios.  NEVER set in production.
"""

from __future__ import annotations

import os
import sys
import threading
import time

from ..utils.failpoints import failpoint, record_recovery, register_failpoint
from ..utils.logger import logger
from ..utils import tracing

STATE_OK = "ok"
STATE_SUSPECT = "suspect"
STATE_QUARANTINED = "quarantined"
_STATE_CODE = {STATE_OK: 0, STATE_SUSPECT: 1, STATE_QUARANTINED: 2}

FP_DEVICE_PROBE = register_failpoint(
    "device.probe",
    "inside the per-chip health probe (lease-time and half-open re-probe); "
    "a raised error counts as a probe FAILURE for the chip under probe — "
    "at grant time that quarantines the chip and the pool re-grants from "
    "the survivors")

def _device_probe(chip: int) -> tuple[bool, str]:
    """Probe one chip: True = healthy (or unprobeable — CPU, jax never
    imported, simulated chip beyond the visible devices: absence of
    evidence is not a fault).  The failpoint fires FIRST so probe faults
    are injectable even where no real device exists.

    The probe is a DMA round-trip, not a kernel launch: ``device_put``
    onto the chip, sync, read the bytes back on host.  A wedged/fenced
    chip fails its transfers just like its launches, and a compile-free
    probe can never initialize XLA's once-per-process persistent
    compilation cache before the backends configure it (see module
    docstring)."""
    failpoint(FP_DEVICE_PROBE)
    jax = sys.modules.get("jax")
    if jax is None:
        return True, "no-jax"
    try:
        devs = jax.local_devices()
    except Exception as exc:
        logger.debug("health probe: jax.local_devices() failed (%s)", exc)
        return True, "no-devices"
    if chip >= len(devs):
        return True, "not-visible"     # simulated pool chip (CI smokes)
    import numpy as np

    sent = np.arange(4, dtype=np.int32)
    back = np.asarray(jax.block_until_ready(
        jax.device_put(sent, devs[chip])))
    return bool(np.array_equal(back, sent)), "device"


def split_host_ranges(size: int, hosts: int) -> tuple[tuple[int, int], ...]:
    """Explicit per-host chip ranges ``((lo, hi), ...)`` — the ISSUE 17
    replacement for the ``chips_per_host = size // hosts`` guess, which
    silently attributed a ragged pool's trailing chips to the WRONG host
    (``7 // (7 // 2)`` puts chip 6 on a third, nonexistent host).  The
    split is as even as possible: the first ``size % hosts`` hosts get one
    extra chip.  Ragged configs are legal but warned — real pods are
    rectangular, so raggedness usually means a typo'd pool size; a host
    count exceeding the pool clamps to one chip per host."""
    size, hosts = max(1, int(size)), max(1, int(hosts))
    if hosts > size:
        logger.warning(
            "device health: %d hosts for a %d-chip pool — clamping to "
            "%d single-chip host domain(s)", hosts, size, size)
        hosts = size
    base, extra = divmod(size, hosts)
    if extra:
        logger.warning(
            "device health: %d chips split raggedly over %d hosts (%d "
            "host(s) get %d chips, %d get %d) — check the pool size",
            size, hosts, extra, base + 1, hosts - extra, base)
    ranges, lo = [], 0
    for h in range(hosts):
        hi = lo + base + (1 if h < extra else 0)
        ranges.append((lo, hi))
        lo = hi
    return tuple(ranges)


def host_of_ranges(ranges) -> list[int]:
    """Flat chip -> host lookup table for ``split_host_ranges`` output."""
    return [h for h, (lo, hi) in enumerate(ranges) for _ in range(hi - lo)]


def _parse_sim_bad(text: str | None) -> frozenset[int]:
    if not text:
        return frozenset()
    out = set()
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            out.add(int(part))
        except ValueError:
            logger.warning("SM_HEALTH_BAD_CHIPS: ignoring non-integer %r",
                           part)
    return frozenset(out)


class HealthTracker:
    """Per-chip health states + fault counters for one DevicePool."""

    # shared-state registry checked by the smlint guarded-by rule
    # (docs/ANALYSIS.md): fault reports, probes, and pool grant scans all
    # touch these maps — mutations only under _lock.  Probes themselves
    # (device work) run OUTSIDE the lock; only their verdicts re-enter it.
    _GUARDED_BY = {"_state": "_lock", "_faults": "_lock",
                   "_quarantined_at": "_lock", "_reason": "_lock",
                   "quarantines_total": "_lock", "readmits_total": "_lock",
                   "probes_total": "_lock", "host_evictions_total": "_lock",
                   "_sim_bad": "_lock"}

    def __init__(self, size: int, hosts: int = 1,
                 probe_on_lease: bool = True,
                 fault_quarantine: int = 3,
                 reprobe_after_s: float = 60.0,
                 host_evict_fraction: float = 0.75,
                 probe_fn=None):
        self.size = int(size)
        # explicit per-host chip ranges (ISSUE 17 satellite): the old
        # `size // hosts` integer division misattributed a ragged pool's
        # trailing chips; host_ranges is the single source of truth for
        # chip -> host everywhere in this tracker
        self.host_ranges = split_host_ranges(self.size, hosts)
        self.hosts = len(self.host_ranges)
        self._host_of = host_of_ranges(self.host_ranges)
        self.probe_on_lease = bool(probe_on_lease)
        self.fault_quarantine = max(1, int(fault_quarantine))
        self.reprobe_after_s = float(reprobe_after_s)
        self.host_evict_fraction = float(host_evict_fraction)
        self._probe_fn = probe_fn or _device_probe
        self._lock = threading.Lock()
        self._state = [STATE_OK] * self.size
        self._faults = [0] * self.size           # consecutive fault strikes
        self._quarantined_at = [0.0] * self.size
        self._reason = [""] * self.size
        self.quarantines_total = 0
        self.readmits_total = 0
        self.probes_total = {"pass": 0, "fail": 0}
        self.host_evictions_total = 0
        self._sim_bad = _parse_sim_bad(os.environ.get("SM_HEALTH_BAD_CHIPS"))
        self._metrics = None
        self._m_health = None
        self._m_quarantines = None
        self._m_probes = None
        self._m_readmits = None
        self._m_evictions = None
        if self._sim_bad:
            logger.warning("device health: simulating bad chips %s "
                           "(SM_HEALTH_BAD_CHIPS — chaos/test seam)",
                           sorted(self._sim_bad))

    @classmethod
    def from_config(cls, size: int, cfg, hosts: int = 1) -> "HealthTracker":
        """Build from ``ServiceConfig`` knobs (scheduler/service seam)."""
        return cls(size, hosts=hosts,
                   probe_on_lease=cfg.health_probe_on_lease,
                   fault_quarantine=cfg.health_fault_quarantine,
                   reprobe_after_s=cfg.health_reprobe_after_s,
                   host_evict_fraction=cfg.health_host_evict_fraction)

    # ------------------------------------------------------------- metrics
    def attach_metrics(self, registry) -> None:
        if self._m_health is not None:
            return
        self._metrics = registry
        self._m_health = registry.gauge(
            "sm_device_health",
            "Chip health (0=ok, 1=suspect, 2=quarantined), per device",
            ("device",))
        for i in range(self.size):
            self._m_health.labels(device=str(i)).set(
                _STATE_CODE[self.state_of(i)])
        self._m_quarantines = registry.counter(
            "sm_device_quarantines_total",
            "Chips fenced out of the device pool (sticky faults, probe "
            "failures, fault-count strikes, host evictions)")
        self._m_probes = registry.counter(
            "sm_device_probes_total",
            "Per-chip health probes (lease-time + half-open re-probes), "
            "by result", ("result",))
        self._m_readmits = registry.counter(
            "sm_device_readmits_total",
            "Quarantined chips returned to service by a passing re-probe")
        self._m_evictions = registry.counter(
            "sm_device_host_evictions_total",
            "Whole host failure domains evicted after too many of their "
            "chips were quarantined")
        for fam in (self._m_quarantines, self._m_readmits,
                    self._m_evictions):
            fam.inc(0)               # expose the 0 sample immediately

    def _export_state_locked(self, chip: int) -> None:
        if self._m_health is not None:
            self._m_health.labels(device=str(chip)).set(
                _STATE_CODE[self._state[chip]])

    # ---------------------------------------------------------- inspection
    def state_of(self, chip: int) -> str:
        with self._lock:
            return self._state[chip]

    def states(self) -> list[str]:
        with self._lock:
            return list(self._state)

    def quarantined(self) -> frozenset[int]:
        with self._lock:
            return frozenset(i for i, s in enumerate(self._state)
                             if s == STATE_QUARANTINED)

    def healthy_count(self) -> int:
        with self._lock:
            return sum(s != STATE_QUARANTINED for s in self._state)

    def snapshot(self) -> dict:
        """The ``GET /debug/devices`` health body + the pool snapshot's
        ``health`` key."""
        with self._lock:
            chips = [{
                "device": i,
                "state": self._state[i],
                "host": self._host_of[i],
                "faults": self._faults[i],
                **({"quarantined_at": round(self._quarantined_at[i], 3),
                    "reason": self._reason[i]}
                   if self._state[i] == STATE_QUARANTINED else {}),
            } for i in range(self.size)]
            return {
                "chips": chips,
                "ok": sum(s == STATE_OK for s in self._state),
                "suspect": sum(s == STATE_SUSPECT for s in self._state),
                "quarantined": sum(
                    s == STATE_QUARANTINED for s in self._state),
                "quarantines_total": self.quarantines_total,
                "readmits_total": self.readmits_total,
                "probes_total": dict(self.probes_total),
                "host_evictions_total": self.host_evictions_total,
                "simulated_bad": sorted(self._sim_bad),
            }

    # --------------------------------------------------------- fault input
    def report_fault(self, devices, kind: str, error: str = "") -> None:
        """A classified non-OOM device fault from the scoring seam
        (``models/faults.py`` listener contract).  Transient: advance the
        strike counter (quarantine only on repeat offenders).  Sticky on a
        1-chip lease: quarantine outright.  Sticky on an N-chip lease:
        probe-attribute the culprit."""
        chips = [int(d) for d in devices if 0 <= int(d) < self.size]
        if not chips:
            return
        if kind == "sticky" and len(chips) == 1:
            self._strike(chips[0], sticky=True,
                         reason=f"sticky fault: {error[:200]}")
            return
        if kind == "sticky":
            # shared-lease fault: the exception cannot name the chip —
            # every leased chip is suspect until the probe attributes it
            with self._lock:
                for c in chips:
                    if self._state[c] == STATE_OK:
                        self._state[c] = STATE_SUSPECT
                        self._export_state_locked(c)
            bad = self.probe_chips(chips)
            for c in bad:
                self._quarantine(c, f"probe failed after sticky lease "
                                    f"fault: {error[:160]}")
            if not bad:
                # unattributable: everyone takes a strike — a chip that
                # keeps killing sharded jobs while passing probes still
                # quarantines after fault_quarantine strikes
                for c in chips:
                    self._strike(c, sticky=False,
                                 reason=f"repeated lease faults: "
                                        f"{error[:160]}")
            return
        # transient: counter only
        for c in chips:
            self._strike(c, sticky=False,
                         reason=f"repeated transient faults: {error[:160]}")

    def report_ok(self, devices) -> None:
        """A clean device group on these chips: suspect -> ok, counters
        reset.  Quarantine is only undone by a passing re-probe."""
        with self._lock:
            for d in devices:
                c = int(d)
                if not 0 <= c < self.size:
                    continue
                self._faults[c] = 0
                if self._state[c] == STATE_SUSPECT:
                    self._state[c] = STATE_OK
                    self._export_state_locked(c)

    def _strike(self, chip: int, sticky: bool, reason: str) -> None:
        with self._lock:
            if self._state[chip] == STATE_QUARANTINED:
                return
            self._faults[chip] += 1
            strikes = self._faults[chip]
            if self._state[chip] == STATE_OK:
                self._state[chip] = STATE_SUSPECT
                self._export_state_locked(chip)
        if sticky or strikes >= self.fault_quarantine:
            self._quarantine(chip, reason)

    # ----------------------------------------------------------- quarantine
    def _quarantine(self, chip: int, reason: str,
                    evicting_host: bool = False) -> bool:
        """Fence one chip out of placement.  Refuses (False) when it would
        leave ZERO healthy chips — a fully-dead pool must fail jobs through
        the breaker/retry policy, not grant nothing forever."""
        with self._lock:
            if self._state[chip] == STATE_QUARANTINED:
                return True
            healthy = sum(s != STATE_QUARANTINED for s in self._state)
            if healthy <= 1:
                logger.error(
                    "device health: refusing to quarantine chip %d (%s) — "
                    "it is the last healthy chip in the pool", chip, reason)
                return False
            self._state[chip] = STATE_QUARANTINED
            self._quarantined_at[chip] = time.time()
            self._reason[chip] = reason
            self._faults[chip] = 0
            self.quarantines_total += 1
            self._export_state_locked(chip)
            if self._m_quarantines is not None:
                self._m_quarantines.inc()
        logger.error("device health: chip %d QUARANTINED (%s)", chip, reason)
        tracing.event("device_quarantine", device=chip, reason=reason[:300])
        record_recovery("device.quarantine")
        if not evicting_host:
            self._check_host_evict(self._host_of[chip])
        return True

    def _check_host_evict(self, host: int) -> None:
        """Evict the whole host failure domain once ``host_evict_fraction``
        of its chips are quarantined — a host with that many bad chips is
        failing as a unit (PCIe/host bridge, not individual dies), and a
        sub-mesh straddling it would keep discovering that one chip at a
        time."""
        if self.hosts <= 1 or self.host_evict_fraction >= 1.0:
            return
        lo, hi = self.host_ranges[host]
        with self._lock:
            members = range(lo, hi)
            quarantined = [i for i in members
                           if self._state[i] == STATE_QUARANTINED]
            remaining = [i for i in members
                         if self._state[i] != STATE_QUARANTINED]
            frac = len(quarantined) / max(1, len(list(members)))
        if frac < self.host_evict_fraction or not remaining:
            return
        logger.error("device health: evicting host %d (%d/%d chips "
                     "quarantined >= %.0f%%)", host, len(quarantined),
                     len(quarantined) + len(remaining),
                     100 * self.host_evict_fraction)
        self.evict_host(host, f"host {host} evicted "
                              f"({len(quarantined)} chips out)")

    def evict_host(self, host: int, reason: str) -> list[int]:
        """Fence a WHOLE host failure domain in one unit (ISSUE 17: the
        scheduler's host watchdog calls this when every process heartbeat
        from the host went stale — a dead process takes all its chips with
        it).  The last-healthy-chip refusal still applies per chip, so
        evicting the final surviving host leaves one chip in service.
        Returns the chips newly quarantined; idempotent."""
        if not 0 <= host < self.hosts:
            return []
        lo, hi = self.host_ranges[host]
        with self._lock:
            remaining = [i for i in range(lo, hi)
                         if self._state[i] != STATE_QUARANTINED]
        if not remaining:
            return []
        evicted = [c for c in remaining
                   if self._quarantine(c, reason, evicting_host=True)]
        if evicted:
            with self._lock:
                self.host_evictions_total += 1
            tracing.event("device_host_evict", host=host, chips=evicted)
            record_recovery("device.host_evict")
            if self._m_evictions is not None:
                self._m_evictions.inc()
        return evicted

    def host_returned(self, host: int) -> list[int]:
        """An evicted host's process is heartbeating again: zero the
        re-probe cooldown for its quarantined chips so the next half-open
        pass (``reprobe_due``) readmits them immediately instead of
        waiting out ``reprobe_after_s``.  Returns the chips made due."""
        if not 0 <= host < self.hosts:
            return []
        lo, hi = self.host_ranges[host]
        with self._lock:
            due = [c for c in range(lo, hi)
                   if self._state[c] == STATE_QUARANTINED]
            for c in due:
                self._quarantined_at[c] = 0.0
        return due

    # --------------------------------------------------------------- probes
    def probe_chips(self, chips) -> list[int]:
        """Probe each chip (device work — never under the lock); returns
        the chips that FAILED."""
        bad = []
        for c in chips:
            c = int(c)
            try:
                ok, how = self._probe_fn(c)
            except Exception as exc:
                ok, how = False, f"error: {exc}"
            sim = False
            with self._lock:
                if c in self._sim_bad:
                    ok, sim = False, True
                self.probes_total["pass" if ok else "fail"] += 1
                if self._m_probes is not None:
                    self._m_probes.labels(
                        result="pass" if ok else "fail").inc()
            tracing.event("device_probe", device=c, ok=bool(ok),
                          how="simulated" if sim else str(how)[:120])
            if not ok:
                bad.append(c)
        return bad

    def probe_lease(self, chips) -> list[int]:
        """The lease-time probe (pool grant seam): quarantines probe
        failures and returns them so the pool can re-grant.  No-op list
        when the probe is disabled."""
        if not self.probe_on_lease:
            return []
        bad = self.probe_chips(chips)
        out = []
        for c in bad:
            if self._quarantine(c, "lease-time probe failed"):
                out.append(c)
        return out

    def reprobe_due(self, now: float | None = None) -> list[int]:
        """Half-open recovery: re-probe quarantined chips whose cooldown
        elapsed; passing chips are READMITTED to service.  A failing
        re-probe re-arms the cooldown.  Returns the readmitted chips."""
        if self.reprobe_after_s <= 0:
            return []
        now = time.time() if now is None else now
        with self._lock:
            due = [i for i, s in enumerate(self._state)
                   if s == STATE_QUARANTINED
                   and now - self._quarantined_at[i] >= self.reprobe_after_s]
        if not due:
            return []
        bad = set(self.probe_chips(due))
        readmitted = []
        with self._lock:
            for c in due:
                if c in bad:
                    self._quarantined_at[c] = now   # re-arm the cooldown
                    continue
                self._state[c] = STATE_OK
                self._faults[c] = 0
                self._reason[c] = ""
                self.readmits_total += 1
                self._export_state_locked(c)
                if self._m_readmits is not None:
                    self._m_readmits.inc()
                readmitted.append(c)
        for c in readmitted:
            logger.warning("device health: chip %d READMITTED after a "
                           "passing re-probe", c)
            tracing.event("device_readmit", device=c)
            record_recovery("device.readmit")
        return readmitted

    # ------------------------------------------------------------ test seam
    def simulate_bad(self, chips) -> None:
        """In-process analog of ``SM_HEALTH_BAD_CHIPS``: make the probe
        fail for these chips (chaos harnesses only — the CPU CI mesh has
        no real way to break a chip)."""
        with self._lock:
            self._sim_bad = frozenset(int(c) for c in chips)
