"""Admin API over stdlib ``http.server`` — no web framework in the image.

Endpoints (the reference exposes none of this; operators had to shell into
RabbitMQ's management UI):

- ``GET /healthz``   liveness + spool depths; 200 while serving, 503 once
  shutdown has begun (load balancers stop routing before the drain ends);
- ``GET /metrics``   Prometheus text exposition from the service registry;
- ``GET /jobs``      JSON array of the scheduler's job records (filter with
  ``?state=running`` etc.);
- ``POST /submit``   body = a spool message (``ds_id`` + ``input_path`` at
  minimum, optional ``priority``/``tenant``/``service.timeout_s``); returns
  ``{"msg_id": ...}`` 202.  Publishing goes through ``QueuePublisher`` so a
  submitted job is durable before the response leaves.

``ThreadingHTTPServer`` keeps scrapes responsive while workers run; every
handler is read-only except ``/submit``, which only appends to ``pending/``.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..utils.logger import logger


class AdminAPI:
    """Own the HTTP server thread; routes delegate to the service object."""

    def __init__(self, service, host: str = "127.0.0.1", port: int = 0):
        self.service = service
        api = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # route access logs to ours
                logger.debug("admin-api: " + fmt, *args)

            def _reply(self, status: int, body: bytes, ctype: str) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _reply_json(self, status: int, obj) -> None:
                self._reply(status, json.dumps(obj).encode(),
                            "application/json")

            def do_GET(self):
                try:
                    url = urlparse(self.path)
                    if url.path == "/healthz":
                        body, status = api._healthz()
                        self._reply_json(status, body)
                    elif url.path == "/metrics":
                        text = api.service.metrics.expose()
                        self._reply(200, text.encode(),
                                    "text/plain; version=0.0.4")
                    elif url.path == "/jobs":
                        q = parse_qs(url.query)
                        self._reply_json(200, api._jobs(q.get("state", [None])[0]))
                    else:
                        self._reply_json(404, {"error": "not found"})
                except Exception as exc:  # noqa: BLE001
                    logger.error("admin-api: GET %s failed", self.path,
                                 exc_info=True)
                    self._reply_json(500, {"error": str(exc)})

            def do_POST(self):
                try:
                    if urlparse(self.path).path != "/submit":
                        self._reply_json(404, {"error": "not found"})
                        return
                    n = int(self.headers.get("Content-Length", 0))
                    raw = self.rfile.read(n) if n else b""
                    try:
                        msg = json.loads(raw or b"{}")
                        if not isinstance(msg, dict):
                            raise ValueError("message must be a JSON object")
                        dst = api.service.publisher.publish(msg)
                    except (ValueError, json.JSONDecodeError) as exc:
                        self._reply_json(400, {"error": str(exc)})
                        return
                    self._reply_json(202, {"msg_id": dst.stem,
                                           "spooled": str(dst)})
                except Exception as exc:  # noqa: BLE001
                    logger.error("admin-api: POST %s failed", self.path,
                                 exc_info=True)
                    self._reply_json(500, {"error": str(exc)})

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self._thread: threading.Thread | None = None

    # --------------------------------------------------------------- routes
    def _healthz(self) -> tuple[dict, int]:
        svc = self.service
        stats = svc.scheduler.stats()
        body = {
            "status": "stopping" if stats["stopping"] else "ok",
            "uptime_s": round(time.time() - svc.started_at, 3),
            "workers": stats["workers"],
            "jobs": stats["states"],
            "queue": svc.queue_depths(),
        }
        return body, (503 if stats["stopping"] else 200)

    def _jobs(self, state: str | None) -> list[dict]:
        jobs = self.service.scheduler.jobs()
        if state:
            jobs = [j for j in jobs if j["state"] == state]
        return jobs

    # ------------------------------------------------------------ lifecycle
    @property
    def address(self) -> tuple[str, int]:
        return self._server.server_address[:2]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name="admin-api")
        self._thread.start()
        logger.info("admin-api: listening on http://%s:%d", *self.address)

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
