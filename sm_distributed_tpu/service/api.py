"""Admin API over stdlib ``http.server`` — no web framework in the image.

Endpoints (the reference exposes none of this; operators had to shell into
RabbitMQ's management UI):

- ``GET /healthz``   liveness + spool depths + admission state; 200 while
  serving, 503 once shutdown has begun (load balancers stop routing before
  the drain ends);
- ``GET /metrics``   Prometheus text exposition from the service registry;
- ``GET /jobs``      JSON array of the scheduler's job records (filter with
  ``?state=running`` etc.);
- ``POST /submit``   body = a spool message (``ds_id`` + ``input_path`` at
  minimum, optional ``priority``/``tenant``/``deadline_s``/
  ``service.timeout_s``); returns ``{"msg_id": ...}`` 202.  Publishing goes
  through ``QueuePublisher`` so a submitted job is durable before the
  response leaves.  Overload protection sits in front: a shed submit gets a
  structured **429** (``queue_full`` / ``tenant_quota``) or **503**
  (``latency_overload`` / draining) with a ``Retry-After`` header and a
  JSON body naming the reason (``service/admission.py``).  Malformed
  payloads get a structured **400**, never a traceback;
- ``DELETE /jobs/<id>``  cooperative cancel: a queued message terminates
  immediately, a running attempt unwinds at its next checkpoint boundary
  (``utils/cancel.py``); 202 while cancelling, 200 when already terminal-
  cancelled here, 409 for finished jobs, 404 for unknown ids;
- ``GET /jobs/<id>/trace``  the job's end-to-end trace (utils/tracing.py)
  as Chrome trace-event JSON — Perfetto-loadable, one root ``submit`` span
  covering admission → claim → every SearchJob phase → per-batch scoring →
  isocalc workers → store_results.  ``?raw=1`` returns the raw records;
- ``GET /debug/events?n=``  the most recent N flight-recorder records
  (default 256) — every span/event from every job plus traceless service
  events (admission sheds, breaker flips);
- ``GET /slo``  objective / attainment / error-budget burn per latency SLI
  (queue-wait, submit→first-annotation, end-to-end), computed from the
  live histograms (``service/telemetry.py``);
- ``GET /debug/timeseries?n=``  the telemetry monitor's bounded ring of
  periodic metric snapshots (per-device HBM, device-token occupancy,
  queue depths, XLA cache size, RSS);
- ``GET /debug/resources``  the resource governor's snapshot
  (``service/resources.py``): disk degrade level + headroom, per-seam
  preflight denials, retention-GC stats, and the HBM-OOM safe-batch
  registry.  Submits shed by a disk-budget breach return **507** with a
  ``Retry-After`` header (the last step of the traces → cache → submits
  degrade order);
- ``GET /debug/compile``  the cold-start lattice view (ISSUE 13): every
  recorded shape bucket with primed/missing status (``service/primer.py``)
  plus the runtime retrace census per attributed call site
  (``analysis/retrace.py``);
- ``GET /debug/devices``  the chip-level device-pool view (ISSUE 14):
  per-chip health state + fault strikes + quarantine evidence
  (``service/health.py``), lease holders, probe/quarantine/readmit/
  host-eviction totals, and per-chip breaker states;
- ``GET /fleet/metrics`` / ``GET /fleet/slo`` / ``GET /fleet/status``
  the fleet observability plane (ISSUE 20, ``service/fleetview.py``):
  every live replica's exposition merged into one pane (counters summed,
  gauges re-labelled ``{replica=}``, histograms bucket-merged),
  fleet-wide SLO attainment from the merged buckets, and the replica /
  host / pool / stream roll-up — peer scrape failures degrade to a
  partial view with ``sm_fleetview_scrape_errors_total{replica=}``
  evidence, never a 500;
- ``GET /debug/profile?seconds=``  single-flight on-demand
  ``jax.profiler`` capture around in-flight work: per-kernel device-time
  attribution (fused scoring kernel vs gather/segment-sum chain vs
  transfers) + ``device_kernel`` spans injected into running jobs'
  traces (409 while another capture runs);
- ``GET /datasets`` / ``GET /datasets/<id>/annotations`` /
  ``GET /annotations`` / ``GET /datasets/<id>/images/<sf_adduct>``  the
  result read path (ISSUE 16, ``service/readpath.py``): dataset listing,
  filtered/sorted/keyset-paginated annotation queries, cross-dataset
  per-molecule cohorts, and PNG ion-image tiles — read-admission sheds
  return a structured **429** with ``Retry-After``, independent of the
  write-side admission.

``ThreadingHTTPServer`` keeps scrapes responsive while workers run; every
handler is read-only except ``/submit`` (appends to ``pending/``) and
``DELETE /jobs/<id>`` (cancels one message).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..utils import tracing
from ..utils.logger import logger

# message fields /submit validates beyond the publisher's ds_id/input_path
# requirement: (field, predicate, expectation) — anything else passes
# through untouched (the spool message schema is open)
def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_submit(msg) -> list[str]:
    """Structural validation for a /submit payload; returns problem list
    (empty = valid).  Catches the malformed shapes that used to surface as
    a 500 traceback deep inside the scheduler."""
    if not isinstance(msg, dict):
        return ["message must be a JSON object"]
    errs = []
    if "mode" in msg and msg["mode"] not in ("batch", "stream"):
        errs.append("'mode' must be \"batch\" or \"stream\"")
    # a stream submit has no input file — the chunk log IS the input, so
    # input_path is auto-filled with a "stream://<ds_id>" sentinel
    required = (("ds_id",) if msg.get("mode") == "stream"
                else ("ds_id", "input_path"))
    for req in required:
        v = msg.get(req)
        if not isinstance(v, str) or not v:
            errs.append(f"{req!r} is required and must be a non-empty string")
    for name in ("tenant", "ds_name"):
        if name in msg and not isinstance(msg[name], str):
            errs.append(f"{name!r} must be a string")
    if "priority" in msg and not (
            isinstance(msg["priority"], (int, str))
            and not isinstance(msg["priority"], bool)):
        errs.append("'priority' must be a string class or an int rank")
    if "deadline_s" in msg:
        if not _is_num(msg["deadline_s"]) or msg["deadline_s"] <= 0:
            errs.append("'deadline_s' must be a positive number of seconds")
    if "devices" in msg and not (
            isinstance(msg["devices"], int)
            and not isinstance(msg["devices"], bool)
            and msg["devices"] > 0):
        errs.append("'devices' must be a positive integer chip count")
    svc = msg.get("service", {})
    if not isinstance(svc, dict):
        errs.append("'service' must be an object")
    else:
        for name in ("timeout_s", "deadline_s", "deadline_at"):
            if name in svc and (not _is_num(svc[name]) or svc[name] <= 0):
                errs.append(f"'service.{name}' must be a positive number")
        if "max_attempts" in svc and not (
                isinstance(svc["max_attempts"], int)
                and not isinstance(svc["max_attempts"], bool)
                and svc["max_attempts"] > 0):
            errs.append("'service.max_attempts' must be a positive integer")
        if "devices" in svc and not (
                isinstance(svc["devices"], int)
                and not isinstance(svc["devices"], bool)
                and svc["devices"] > 0):
            errs.append("'service.devices' must be a positive integer "
                        "chip count")
    return errs


class AdminAPI:
    """Own the HTTP server thread; routes delegate to the service object."""

    def __init__(self, service, host: str = "127.0.0.1", port: int = 0):
        self.service = service
        api = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # route access logs to ours
                logger.debug("admin-api: " + fmt, *args)

            def _reply(self, status: int, body: bytes, ctype: str,
                       headers: dict | None = None) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _reply_json(self, status: int, obj,
                            headers: dict | None = None) -> None:
                self._reply(status, json.dumps(obj).encode(),
                            "application/json", headers)

            def _reply_read(self, result) -> None:
                """Render a ReadPath handler result: PNG bytes or JSON."""
                status, body, headers = result
                if isinstance(body, (bytes, bytearray)):
                    self._reply(status, bytes(body), "image/png", headers)
                else:
                    self._reply_json(status, body, headers)

            def do_GET(self):
                try:
                    url = urlparse(self.path)
                    if url.path == "/healthz":
                        body, status = api._healthz()
                        self._reply_json(status, body)
                    elif url.path == "/metrics":
                        text = api.service.metrics.expose()
                        self._reply(200, text.encode(),
                                    "text/plain; version=0.0.4")
                    elif url.path == "/jobs":
                        q = parse_qs(url.query)
                        self._reply_json(200, api._jobs(q.get("state", [None])[0]))
                    elif url.path == "/debug/events":
                        q = parse_qs(url.query)
                        n = int(q.get("n", ["256"])[0] or 256)
                        self._reply_json(
                            200, tracing.flight_recorder.recent(n))
                    elif url.path == "/debug/resources":
                        status, body = api._resources()
                        self._reply_json(status, body)
                    elif url.path == "/debug/devices":
                        status, body = api._devices()
                        self._reply_json(status, body)
                    elif url.path == "/debug/compile":
                        status, body = api._compile()
                        self._reply_json(status, body)
                    elif url.path == "/debug/timeseries":
                        q = parse_qs(url.query)
                        n = q.get("n", [None])[0]
                        status, body = api._timeseries(
                            int(n) if n else None)
                        self._reply_json(status, body)
                    elif url.path == "/slo":
                        status, body = api._slo()
                        self._reply_json(status, body)
                    elif url.path == "/fleet/metrics":
                        status, text = api._fleet_metrics()
                        self._reply(status, text.encode(),
                                    "text/plain; version=0.0.4")
                    elif url.path == "/fleet/slo":
                        status, body = api._fleet_slo()
                        self._reply_json(status, body)
                    elif url.path == "/fleet/status":
                        status, body = api._fleet_status()
                        self._reply_json(status, body)
                    elif url.path == "/debug/profile":
                        q = parse_qs(url.query)
                        s = q.get("seconds", [None])[0]
                        try:
                            seconds = float(s) if s else None
                        except ValueError:
                            self._reply_json(
                                400, {"error": "'seconds' must be a number",
                                      "reason": "invalid_request"})
                            return
                        status, body = api._profile(seconds)
                        self._reply_json(status, body)
                    elif url.path == "/peers":
                        self._reply_json(200, api._peers())
                    elif url.path == "/datasets" or url.path == "/annotations" \
                            or (url.path.startswith("/datasets/")
                                and url.path.strip("/").split("/")[2:3]
                                in (["annotations"], ["images"])):
                        rp = getattr(api.service, "readpath", None)
                        if rp is None:
                            self._reply_json(
                                404, {"error": "read path not configured",
                                      "reason": "not_found"})
                            return
                        q = parse_qs(url.query)
                        parts = url.path.strip("/").split("/")
                        if url.path == "/datasets":
                            self._reply_read(rp.handle_datasets())
                        elif url.path == "/annotations":
                            self._reply_read(rp.handle_cohort(q))
                        elif len(parts) == 3:
                            self._reply_read(
                                rp.handle_annotations(parts[1], q))
                        elif len(parts) == 4:
                            self._reply_read(
                                rp.handle_tile(parts[1], parts[3], q))
                        else:
                            self._reply_json(404, {"error": "not found"})
                    elif (parts := url.path.strip("/").split("/"))[0] == \
                            "jobs" and len(parts) == 3 and parts[2] == "trace":
                        q = parse_qs(url.query)
                        status, body = api._trace(
                            parts[1], raw=q.get("raw", ["0"])[0] not in
                            ("0", "", "false"))
                        self._reply_json(status, body)
                    elif parts[0] == "jobs" and len(parts) == 2:
                        # one record, partial preview included — the poll
                        # surface a live acquisition watches its
                        # provisional FDR ranking through (ISSUE 19)
                        job = next((j for j in api.service.scheduler.jobs()
                                    if j["msg_id"] == parts[1]), None)
                        if job is None:
                            self._reply_json(404, {"error": "not found"})
                        else:
                            self._reply_json(200, job)
                    else:
                        self._reply_json(404, {"error": "not found"})
                except Exception as exc:  # noqa: BLE001
                    logger.error("admin-api: GET %s failed", self.path,
                                 exc_info=True)
                    self._reply_json(500, {"error": str(exc)})

            def do_POST(self):
                try:
                    path = urlparse(self.path).path
                    parts = path.strip("/").split("/")
                    if path == "/submit":
                        status, body, headers = api._submit(self._read_body())
                        self._reply_json(status, body, headers)
                    elif len(parts) == 3 and parts[0] == "datasets" \
                            and parts[1] and parts[2] == "pixels":
                        status, body, headers = api._stream_pixels(
                            parts[1], self._read_body())
                        self._reply_json(status, body, headers)
                    elif len(parts) == 3 and parts[0] == "datasets" \
                            and parts[1] and parts[2] == "finish":
                        status, body = api._stream_finish(parts[1])
                        self._reply_json(status, body)
                    else:
                        self._reply_json(404, {"error": "not found"})
                except Exception as exc:  # noqa: BLE001
                    logger.error("admin-api: POST %s failed", self.path,
                                 exc_info=True)
                    self._reply_json(500, {"error": str(exc)})

            def do_DELETE(self):
                try:
                    parts = urlparse(self.path).path.strip("/").split("/")
                    if len(parts) != 2 or parts[0] != "jobs":
                        self._reply_json(
                            404, {"error": "not found",
                                  "reason": "want DELETE /jobs/<msg_id>"})
                        return
                    if not parts[1]:
                        self._reply_json(400, {"error": "missing msg_id",
                                               "reason": "invalid_request"})
                        return
                    status, body = api._cancel(parts[1])
                    self._reply_json(status, body)
                except Exception as exc:  # noqa: BLE001
                    logger.error("admin-api: DELETE %s failed", self.path,
                                 exc_info=True)
                    self._reply_json(500, {"error": str(exc)})

            def _read_body(self) -> bytes:
                n = int(self.headers.get("Content-Length", 0) or 0)
                return self.rfile.read(n) if n else b""

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self._thread: threading.Thread | None = None

    # --------------------------------------------------------------- routes
    def _healthz(self) -> tuple[dict, int]:
        svc = self.service
        stats = svc.scheduler.stats()
        body = {
            "status": "stopping" if stats["stopping"] else "ok",
            "uptime_s": round(time.time() - svc.started_at, 3),
            "workers": stats["workers"],
            "jobs": stats["states"],
            "queue": svc.queue_depths(),
        }
        adm = getattr(svc, "admission", None)
        if adm is not None:
            body["admission"] = adm.stats()
        return body, (503 if stats["stopping"] else 200)

    def _jobs(self, state: str | None) -> list[dict]:
        jobs = self.service.scheduler.jobs()
        if state:
            jobs = [j for j in jobs if j["state"] == state]
        return jobs

    def _submit(self, raw: bytes) -> tuple[int, dict, dict | None]:
        """Validate → admit → publish; returns (status, body, headers)."""
        svc = self.service
        try:
            msg = json.loads(raw or b"{}")
        except json.JSONDecodeError as exc:
            return 400, {"error": f"malformed JSON: {exc}",
                         "reason": "invalid_json"}, None
        errs = validate_submit(msg)
        if errs:
            return 400, {"error": "; ".join(errs),
                         "reason": "invalid_message"}, None
        if svc.stopping():
            return 503, {"error": "service is draining",
                         "reason": "stopping", "retry_after_s": 5.0}, \
                {"Retry-After": "5"}
        tenant = str(msg.get("tenant", "default"))
        adm = getattr(svc, "admission", None)
        decision = adm.try_admit(tenant) if adm is not None else None
        if decision is not None and not decision.accepted:
            # traceless flight-recorder event: the shed job never gets a
            # trace, but GET /debug/events still shows WHY it bounced
            tracing.event("admission.shed", reason=decision.body().get(
                "reason", ""), tenant=tenant, status=decision.status)
            return decision.status, decision.body(), \
                {"Retry-After": str(max(1, int(round(decision.retry_after_s))))}
        try:
            if msg.get("mode") == "stream" and not msg.get("input_path"):
                # the scheduler/engine read the chunk log, never this path;
                # the sentinel satisfies the publisher's contract and makes
                # the dataset's provenance legible in the spool message
                msg["input_path"] = f"stream://{msg['ds_id']}"
            # deadline propagation: pin the ABSOLUTE deadline at submit time
            # so queueing delay counts against it end to end.  Stream jobs
            # are exempt (ISSUE 19): an acquisition has no known length —
            # their liveness bound is service.stream.idle_timeout_s
            if "deadline_s" in msg and msg.get("mode") != "stream":
                service_block = dict(msg.get("service", {}))
                service_block.setdefault(
                    "deadline_at", time.time() + float(msg["deadline_s"]))
                msg["service"] = service_block
            # mint the job's trace HERE (ISSUE 5): the ids travel inside the
            # message, so the scheduler — this process or the one after a
            # crash — continues the same trace file end to end
            service_block = dict(msg.get("service", {}))
            trace = service_block.get("trace")
            if not (isinstance(trace, dict) and trace.get("trace_id")):
                trace = {"trace_id": tracing.new_id(),
                         "span": tracing.new_id(), "start": time.time()}
                service_block["trace"] = trace
                msg["service"] = service_block
            dst = svc.publisher.publish(msg)
        except (ValueError, OSError) as exc:
            if decision is not None:
                adm.abort(tenant)
            return 400, {"error": str(exc), "reason": "invalid_message"}, None
        if decision is not None:
            adm.confirm(dst.stem, tenant)
        trace_dir = getattr(svc, "trace_dir", None)
        ctx = tracing.TraceContext(
            trace_id=trace["trace_id"], span_id=trace["span"],
            job_id=dst.stem,
            file=str(tracing.trace_path(trace_dir, trace["trace_id"]))
            if trace_dir else "")
        tracing.event("submit", ctx=ctx, tenant=tenant,
                      ds_id=str(msg.get("ds_id", "")),
                      priority=str(msg.get("priority", "normal")))
        return 202, {"msg_id": dst.stem, "spooled": str(dst),
                     "trace_id": trace["trace_id"]}, None

    def _stream_pixels(self, ds_id: str,
                       raw: bytes) -> tuple[int, dict, dict | None]:
        """``POST /datasets/<id>/pixels`` (ISSUE 19): append one spectra
        chunk to the dataset's crash-safe chunk log.  Body::

            {"seq": 0, "coords": [[x, y], ...],
             "mzs":  [[...], ...],  "ints": [[...], ...]}

        Idempotent by ``seq`` — a byte-identical retry (lost ack) gets a
        200 with ``duplicate: true``; a conflicting payload under the same
        seq gets a 409.  Out-of-order seqs are fine."""
        svc = self.service
        ingest = getattr(svc, "stream_ingest", None)
        if ingest is None:
            return 404, {"error": "streaming ingest not configured",
                         "reason": "not_found"}, None
        if svc.stopping():
            return 503, {"error": "service is draining",
                         "reason": "stopping", "retry_after_s": 5.0}, \
                {"Retry-After": "5"}
        try:
            body = json.loads(raw or b"{}")
        except json.JSONDecodeError as exc:
            return 400, {"error": f"malformed JSON: {exc}",
                         "reason": "invalid_json"}, None
        errs = []
        if not isinstance(body, dict):
            errs.append("body must be a JSON object")
        else:
            if not (isinstance(body.get("seq"), int)
                    and not isinstance(body.get("seq"), bool)
                    and body["seq"] >= 0):
                errs.append("'seq' must be a non-negative integer")
            for name in ("coords", "mzs", "ints"):
                if not isinstance(body.get(name), list):
                    errs.append(f"{name!r} must be a list")
            if not errs and not (len(body["coords"]) == len(body["mzs"])
                                 == len(body["ints"])):
                errs.append("'coords', 'mzs' and 'ints' must have one entry "
                            "per spectrum")
        if errs:
            return 400, {"error": "; ".join(errs),
                         "reason": "invalid_message"}, None
        from ..engine.stream import ChunkConflictError, StreamGapError
        from .resources import ResourceBudgetError

        try:
            out = ingest.append_chunk(
                ds_id, body["seq"], body["coords"],
                list(zip(body["mzs"], body["ints"])))
        except ChunkConflictError as exc:
            return 409, {"error": str(exc), "reason": "chunk_conflict"}, None
        except StreamGapError as exc:
            return 409, {"error": str(exc), "reason": "stream_finished"}, None
        except ResourceBudgetError as exc:
            return 507, {"error": str(exc), "reason": "disk_budget",
                         "retry_after_s": 5.0}, {"Retry-After": "5"}
        except ValueError as exc:
            return 400, {"error": str(exc), "reason": "invalid_message"}, None
        return 200, {"ds_id": ds_id, **out}, None

    def _stream_finish(self, ds_id: str) -> tuple[int, dict]:
        """``POST /datasets/<id>/finish``: seal the acquisition.  409 when
        the committed sequence has gaps; idempotent once sealed."""
        ingest = getattr(self.service, "stream_ingest", None)
        if ingest is None:
            return 404, {"error": "streaming ingest not configured",
                         "reason": "not_found"}
        from ..engine.stream import StreamEmptyError, StreamGapError

        try:
            out = ingest.finish(ds_id)
        except StreamEmptyError as exc:
            return 409, {"error": str(exc), "reason": "stream_empty"}
        except StreamGapError as exc:
            return 409, {"error": str(exc), "reason": "stream_gap"}
        return 200, {"ds_id": ds_id, **out}

    def _trace(self, msg_id: str, raw: bool = False) -> tuple[int, dict]:
        """``GET /jobs/<id>/trace``: resolve msg_id → trace_id (scheduler
        record first, then the message file in any spool state), read the
        per-job JSONL, return Chrome trace JSON (or raw records)."""
        svc = self.service
        trace_id = next((j["trace_id"] for j in svc.scheduler.jobs()
                         if j["msg_id"] == msg_id and j.get("trace_id")), "")
        if not trace_id:
            # not claimed yet (or a restarted service): the ids live in the
            # spool message itself
            root = svc.queue_dir / svc.queue
            for state in ("pending", "running", "done", "failed",
                          "quarantine"):
                p = root / state / f"{msg_id}.json"
                try:
                    msg = json.loads(p.read_text())
                    trace_id = str(msg.get("service", {})
                                   .get("trace", {}).get("trace_id", ""))
                    if trace_id:
                        break
                except (OSError, json.JSONDecodeError, AttributeError):
                    continue
        if not trace_id:
            return 404, {"error": f"no trace for job {msg_id!r}",
                         "reason": "not_found"}
        trace_dir = getattr(svc, "trace_dir", None)
        path = tracing.trace_path(trace_dir, trace_id) if trace_dir else None
        records = tracing.read_trace(path) if path else []
        if not records:
            return 404, {"error": f"trace file for {trace_id} is empty or "
                                  "missing", "reason": "not_found",
                         "trace_id": trace_id}
        if raw:
            return 200, {"trace_id": trace_id, "msg_id": msg_id,
                         "records": records}
        return 200, tracing.to_chrome_trace(records)

    def _timeseries(self, n: int | None) -> tuple[int, dict]:
        """``GET /debug/timeseries?n=`` — the telemetry monitor's snapshot
        ring (device HBM, token occupancy, queue depths, cache size, RSS);
        newest last."""
        mon = getattr(self.service, "telemetry", None)
        if mon is None:
            return 404, {"error": "telemetry monitor not configured",
                         "reason": "not_found"}
        samples = mon.timeseries(n)
        return 200, {
            "interval_s": mon.cfg.sample_interval_s,
            "capacity": mon.cfg.timeseries_len,
            "enabled": bool(self.service.sm_config.telemetry.enabled),
            "n": len(samples),
            "samples": samples,
        }

    def _compile(self) -> tuple[int, dict]:
        """``GET /debug/compile`` (ISSUE 13): the cold-start lattice view —
        every recorded shape bucket with its primed/missing status
        (service/primer.py), plus the runtime retrace census (observed
        compile events/signatures per attributed site, analysis/retrace.py)
        so primed-but-never-hit and hit-but-never-primed buckets are both
        visible from one endpoint."""
        from ..analysis import retrace

        primer = getattr(self.service, "primer", None)
        snap = retrace.snapshot()
        body = {
            "primer": (primer.snapshot() if primer is not None else None),
            "retrace": {
                "events_total": snap["events_total"],
                "signatures_total": snap["signatures_total"],
                "sites": {
                    site: {"events": ent["events"],
                           "signatures": len(ent["signatures"])}
                    for site, ent in snap["sites"].items()
                },
            },
        }
        return 200, body

    def _devices(self) -> tuple[int, dict]:
        """``GET /debug/devices`` (ISSUE 14) — the device pool's chip-level
        view: per-chip health (``ok``/``suspect``/``quarantined`` with
        fault strikes, quarantine reason and timestamp), current lease
        holders, per-host occupancy, probe/quarantine/readmit/eviction
        totals (``service/health.py``), and every per-chip circuit
        breaker's state (``models/breaker.py``)."""
        pool = getattr(self.service, "device_pool", None)
        if pool is None:
            return 404, {"error": "device pool not configured",
                         "reason": "not_found"}
        from ..models.breaker import breakers_snapshot

        return 200, {**pool.snapshot(), "breakers": breakers_snapshot()}

    def _resources(self) -> tuple[int, dict]:
        """``GET /debug/resources`` — the resource governor's snapshot
        (ISSUE 10): degrade level, headroom, per-seam denials, GC stats,
        and the OOM safe-batch registry (service/resources.py)."""
        governor = getattr(self.service, "resources", None)
        if governor is None:
            return 404, {"error": "resource governor not configured",
                         "reason": "not_found"}
        return 200, governor.snapshot()

    def _peers(self) -> dict:
        """``GET /peers`` — the replica registry view (ISSUE 8): this
        replica's identity/shards plus every peer's last heartbeat, shard
        ownership, and gossiped admission summary.  Replicas poll each
        other's registries through the shared spool; this endpoint gives
        operators (and cross-node pollers) the same picture over HTTP."""
        return self.service.scheduler.peers()

    def _slo(self) -> tuple[int, dict]:
        """``GET /slo`` — objective / attainment / error-budget burn per
        SLI, computed from the live histograms (service/telemetry.py)."""
        slo = getattr(self.service, "slo", None)
        if slo is None:
            return 404, {"error": "SLO tracker not configured",
                         "reason": "not_found"}
        return 200, slo.report()

    def _fleet_metrics(self) -> tuple[int, str]:
        """``GET /fleet/metrics`` (ISSUE 20) — every live replica's
        exposition merged into one: counters summed, gauges re-labelled
        ``{replica=}``, histograms bucket-merged.  Peer failures degrade
        to a partial view with evidence comments, never an error."""
        fv = getattr(self.service, "fleetview", None)
        if fv is None:
            return 404, "# fleetview not configured (service.fleetview)\n"
        return 200, fv.metrics_text()

    def _fleet_slo(self) -> tuple[int, dict]:
        """``GET /fleet/slo`` — fleet-wide attainment / error-budget burn
        for the five SLIs, computed from the merged histogram buckets."""
        fv = getattr(self.service, "fleetview", None)
        if fv is None:
            return 404, {"error": "fleetview not configured",
                         "reason": "not_found"}
        return fv.slo()

    def _fleet_status(self) -> tuple[int, dict]:
        """``GET /fleet/status`` — replicas, hosts, evictions, pool
        occupancy, in-flight stream acquisitions, scrape evidence."""
        fv = getattr(self.service, "fleetview", None)
        if fv is None:
            return 404, {"error": "fleetview not configured",
                         "reason": "not_found"}
        return fv.status()

    def _profile(self, seconds: float | None) -> tuple[int, dict]:
        """``GET /debug/profile?seconds=`` (ISSUE 20) — single-flight
        ``jax.profiler`` capture around in-flight work: per-kernel device
        time attribution + ``device_kernel`` span injection into running
        jobs' traces.  409 while another capture runs."""
        prof = getattr(self.service, "profiler", None)
        if prof is None:
            return 404, {"error": "device profiler not configured",
                         "reason": "not_found"}
        return prof.run(seconds)

    def _cancel(self, msg_id: str) -> tuple[int, dict]:
        disposition = self.service.scheduler.cancel(msg_id)
        status = {"cancelling": 202, "cancelled": 200,
                  "terminal": 409, "not_found": 404}[disposition]
        body = {"msg_id": msg_id, "state": disposition}
        if disposition == "terminal":
            body["error"] = "job already reached a terminal state"
        elif disposition == "not_found":
            body["error"] = "unknown msg_id"
        return status, body

    # ------------------------------------------------------------ lifecycle
    @property
    def address(self) -> tuple[str, int]:
        return self._server.server_address[:2]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name="admin-api")
        self._thread.start()
        logger.info("admin-api: listening on http://%s:%d", *self.address)

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
