"""Ahead-of-time XLA cache primer (ISSUE 13, tentpole front 2).

PR 12 made the compile surface declared and attributed; the shape-bucket
lattice (``ops/buckets.py``) makes it CLOSED — every dataset size maps
into a finite set of executables identified by recorded ``BucketSpec``s.
This module walks that set and compiles it into the persistent XLA cache
**before traffic arrives**, so a cold submit loads executables from disk
instead of paying 40–120 s of XLA compile (BENCH_r05 cold numbers):

- :func:`prime_spec` AOT-compiles ONE spec: it rebuilds the exact jitted
  program a real backend would construct (``models/msm_jax.make_flat_jits``
  — same function objects, same closure, same static_argnames) and lowers
  it against ``jax.ShapeDtypeStruct`` avals derived from the spec, so the
  persistent-cache entry it writes is byte-for-byte the entry a later job
  looks up.  No device arrays are materialized and no device time is
  spent — compilation is host work, which is why the primer can run while
  chips serve traffic without ever touching a device-pool lease;
- :class:`CachePrimer` is the scheduler-idle background thread
  (``service.prime`` config): it waits for the spool to sit idle, primes
  un-primed specs one at a time (re-checking idleness between specs — a
  real job arriving pauses the cycle at the next spec boundary), and
  records progress per spec in ``prime_manifest.json`` next to the cache,
  so a primer killed mid-cycle resumes where it stopped and a second run
  is a no-op;
- ``scripts/prime_cache.py`` drives the same :func:`prime_once` offline
  (deploy-time priming), and ``GET /debug/compile`` serves
  :meth:`CachePrimer.snapshot` — the primed-vs-missing bucket view.

Sharded (multi-chip lease) specs prime too (ISSUE 14 — the follow-up
PR 13 left): a recorded mesh-shaped spec carries its full lease topology
(mesh axes, per-shard pixel capacity, every host-plan shape), so
:func:`prime_spec` rebuilds the byte-identical ``jit(shard_map(step))``
program over a mesh of the first ``devices`` local chips and AOT-compiles
it — including the SHRUNKEN meshes a post-quarantine re-lease produces,
which record their own topology-keyed spec at first dispatch and are warm
for every later job of that lease shape.  A host with fewer visible
devices than the mesh skips the spec (``skipped:devices``); legacy
manifest entries recorded before the topology fields exist skip as
``skipped:legacy_spec``.  The ``sm_prime_*`` metric family is documented
in docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path

from ..analysis.numerics import numerics_surface
from ..analysis.surface import compile_surface
from ..ops import buckets as shape_buckets
from ..utils.logger import logger

# No jax.jit call sites live here — the jitted programs are built by
# models/msm_jax.make_flat_jits (registered in THAT module's surface).
# This declaration attributes the AOT ``.compile()`` frames the retrace
# tracer sees when the primer pays a compile (scripts/compile_census.py
# requires every observed site's module to carry a registry).
COMPILE_SURFACE = compile_surface(__name__, {
    "prime_spec":
        "statics=closure(recorded BucketSpec statics); buckets=the "
        "ops/buckets lattice itself — the primer only ever compiles "
        "specs the backends recorded (flat AND mesh-shaped sharded, "
        "keyed on lease topology), so its surface is a subset of "
        "models/msm_jax's plus parallel/sharded's",
})

# Declared numerics contract (ISSUE 15): the primer rebuilds the
# BYTE-identical program a recorded spec dispatched (same function
# objects, same partial closure, same statics), so a primed executable
# is bit-for-bit the one a later real job looks up — priming can never
# change results.
NUMERICS = numerics_surface(__name__, {
    "prime_spec":
        "contract=bit_exact; test=tests/test_buckets.py::"
        "test_primer_idempotent_and_resumable",
})


def _flat_lower_call(spec: dict):
    """(jitted fn, positional ShapeDtypeStruct avals, static kwargs) for
    one recorded flat-path spec — the exact calling convention of
    ``JaxBackend._dispatch`` for that variant."""
    import jax
    import numpy as np

    from ..models.msm_jax import make_flat_jits

    S = jax.ShapeDtypeStruct
    i32, f32 = np.int32, np.float32
    n, g = int(spec["n_resident"]), int(spec["g"])
    c, wc = int(spec["c"]), int(spec["wc"])
    b, k = int(spec["b"]), int(spec["k"])
    common = {
        "nrows": int(spec["nrows"]), "ncols": int(spec["ncols"]),
        "nlevels": int(spec["nlevels"]),
        "do_preprocessing": bool(spec["do_preprocessing"]),
        "q": float(spec["q"]),
    }
    fn = make_flat_jits(common)[spec["variant"]]
    # compacted-cube specs (ISSUE 18): the resident intensity aval carries
    # the recorded dtype, and int8 appends the per-tile scale vector after
    # the traced n_real scalar — exactly JaxBackend._flat_call's tail
    cube_dtype = spec.get("cube_dtype") or "f32"
    in_dtype = {"f32": f32, "bf16": None, "int8": np.int8}[cube_dtype]
    if in_dtype is None:
        import ml_dtypes  # jax dependency; baked into the image

        in_dtype = ml_dtypes.bfloat16
    resident = [S((n,), i32), S((n,), in_dtype)]
    plan = [S((c,), i32), S((c, wc), i32), S((c, wc), i32), S((b,), i32),
            S((b, k), f32), S((b,), i32), S((), i32)]
    if cube_dtype == "int8":
        from ..ops.quantize import QTILE

        plan = plan + [S((n // QTILE,), f32)]
    statics = dict(gc_width=int(spec["gc_width"]), b=b, k=k)
    if spec["variant"] in ("plain", "fused"):
        # the fused Pallas variant shares the plain call shape exactly —
        # only the jitted program differs (models/msm_jax._VARIANTS)
        args = resident + [S((g,), i32)] + plan
    elif spec["variant"] == "band":
        args = resident + [S((), i32), S((g,), i32)] + plan
        statics["w_cap"] = int(spec["w_cap"])
    elif spec["variant"] == "compact":
        r_pad = int(spec["r_pad"])
        args = resident + [S((r_pad,), i32), S((r_pad,), i32), S((), i32),
                           S((g,), i32)] + plan
        statics["n_keep"] = int(spec["n_keep"])
    else:
        raise ValueError(f"unknown flat variant {spec['variant']!r}")
    return fn, args, statics


def _sharded_lower_call(spec: dict):
    """(jitted mesh step, positional sharded ShapeDtypeStruct avals) for
    one recorded sharded spec — the exact calling convention of
    ``ShardedJaxBackend._dispatch`` for that variant, rebuilt over a mesh
    of the first ``spec['devices']`` local chips (the pool hands leases
    out host-major, so the primed assignment matches the common case)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import FORMULAS_AXIS, PIXELS_AXIS
    from ..parallel.sharded import build_sharded_score_factory

    n_dev = int(spec["devices"])
    pix, form = int(spec["mesh_pix"]), int(spec["mesh_form"])
    mesh = Mesh(
        np.array(jax.local_devices()[:n_dev]).reshape(pix, form),
        (PIXELS_AXIS, FORMULAS_AXIS))
    make = build_sharded_score_factory(
        mesh,
        p_loc=int(spec["p_loc"]),
        nrows=int(spec["nrows"]), ncols=int(spec["ncols"]),
        nlevels=int(spec["nlevels"]),
        do_preprocessing=bool(spec["do_preprocessing"]),
        q=float(spec["q"]))
    n_keep, w_cap = int(spec["n_keep"]), int(spec["w_cap"])
    fn = make(int(spec["gc_width"]), n_keep, w_cap)
    i32, f32 = np.int32, np.float32
    n, b, k = int(spec["n_resident"]), int(spec["b"]), int(spec["k"])
    g, c = int(spec["g"]), int(spec["c"])
    wc, w = int(spec["wc"]), int(spec["w"])
    r_pad = int(spec["r_pad"])

    def S(shape, dtype, part):
        return jax.ShapeDtypeStruct(
            shape, dtype, sharding=NamedSharding(mesh, part))

    # bf16-compacted residents (ISSUE 18) record their dtype on the spec;
    # int8 never reaches the mesh path (ShardedJaxBackend falls back)
    if spec.get("cube_dtype") == "bf16":
        import ml_dtypes  # jax dependency; baked into the image

        in_dtype = ml_dtypes.bfloat16
    else:
        in_dtype = f32
    # run/band plan blocks mirror ShardedJaxBackend._dispatch: compact
    # ships (S, F*r_pad) run lists, band/plain ship (S, F) dummies/starts
    rp_w = form * r_pad if n_keep else form
    args = [
        S((pix, n), i32, P(PIXELS_AXIS, None)),            # px_s
        S((pix, n), in_dtype, P(PIXELS_AXIS, None)),       # in_s
        S((pix, g), i32, P(PIXELS_AXIS, FORMULAS_AXIS)),   # pos
        S((c,), i32, P(FORMULAS_AXIS)),                    # starts
        S((c, wc), i32, P(FORMULAS_AXIS, None)),           # r_lo_loc
        S((c, wc), i32, P(FORMULAS_AXIS, None)),           # r_hi_loc
        S((w,), i32, P(FORMULAS_AXIS)),                    # inv
        S((b, k), f32, P(FORMULAS_AXIS, None)),            # theor_ints
        S((b,), i32, P(FORMULAS_AXIS)),                    # n_valid
        S((pix, rp_w), i32, P(PIXELS_AXIS, FORMULAS_AXIS)),  # run_pos
        S((pix, rp_w), i32, P(PIXELS_AXIS, FORMULAS_AXIS)),  # run_delta
        S((pix, form), i32, P(PIXELS_AXIS, FORMULAS_AXIS)),  # n_b
        S((1,), i32, P(None)),                             # n_real
    ]
    return fn, args


def prime_spec(spec: dict, sm_config=None) -> str:
    """AOT-compile one recorded BucketSpec into the persistent XLA cache.
    Returns ``"compiled"`` or ``"skipped:<reason>"``; raises on a real
    compile failure (the caller counts it as an error).

    ``sm_config`` (when given) points the persistent cache first —
    without a cache dir the compile would only warm this process."""
    kind = spec.get("kind")
    if kind not in ("flat", "sharded"):
        return f"skipped:{kind or 'unknown'}"
    if sm_config is not None:
        from ..parallel.distributed import compile_cache_path, enable_compile_cache

        enable_compile_cache(sm_config)
        cache_dir = compile_cache_path(sm_config)
        if cache_dir is not None:
            # XLA's cache writer skips (with a warning) when the dir is
            # missing — a primed-into-nothing cycle would claim success
            Path(cache_dir).mkdir(parents=True, exist_ok=True)
    if kind == "sharded":
        # topology-keyed mesh specs (ISSUE 14): skip gracefully where the
        # host cannot hold the mesh, or the entry predates the fields
        if any(spec.get(key) in (None, "None", "", 0)
               for key in ("mesh_pix", "mesh_form", "p_loc", "w", "k", "g",
                           "c", "wc")):
            return "skipped:legacy_spec"  # pre-topology manifest entry
        import jax

        if jax.local_device_count() < int(spec["devices"]):
            return "skipped:devices"
        fn, args = _sharded_lower_call(spec)
        fn.lower(*args).compile()
        return "compiled"
    fn, args, statics = _flat_lower_call(spec)
    fn.lower(*args, **statics).compile()
    return "compiled"


def _env_key() -> str:
    """The environment a primed entry is valid for (a cache entry compiled
    under another jax/backend is a different cache entry)."""
    import jax

    dev = jax.devices()[0]
    return f"{jax.__version__}|{dev.platform}|{dev.device_kind}"


class _PrimeManifest:
    """Per-spec prime progress, persisted next to the XLA cache so an
    interrupted primer resumes and a second run is a no-op (smlint
    guarded-by)."""

    _GUARDED_BY = {"_done": "_lock"}

    def __init__(self, cache_dir: Path | None):
        self._lock = threading.Lock()
        self._path = (Path(cache_dir) / "prime_manifest.json"
                      if cache_dir is not None else None)
        self._done: dict[str, str] = {}
        if self._path is not None:
            try:
                raw = json.loads(self._path.read_text())
                self._done = {str(k): str(v)
                              for k, v in raw.get("primed", {}).items()}
            except (OSError, ValueError):
                pass                  # absent/corrupt = nothing primed

    def primed(self, key: str, env: str) -> bool:
        with self._lock:
            return self._done.get(key) == env

    def mark(self, key: str, env: str) -> None:
        with self._lock:
            self._done[key] = env
            snapshot = dict(self._done)
        if self._path is None:
            return
        tmp = self._path.with_name(self._path.name + ".tmp")
        try:
            self._path.parent.mkdir(parents=True, exist_ok=True)
            tmp.write_text(json.dumps({"primed": snapshot}))
            os.replace(tmp, self._path)
        except OSError:
            logger.warning("could not write prime manifest %s", self._path,
                           exc_info=True)

    def count(self) -> int:
        with self._lock:
            return len(self._done)


class CachePrimer:
    """Scheduler-idle background primer (``service.prime``).

    ``busy``: a zero-arg callable returning True while real work is in
    flight (pending spool depth or live claims) — a prime cycle starts
    only after ``idle_after_s`` of continuous idleness and re-checks
    between specs, so priming never delays a job (and never touches a
    device-pool lease: AOT lowering is host-side compilation)."""

    _GUARDED_BY = {"_status": "_lock", "_cycles": "_lock",
                   "_last_cycle_s": "_lock"}

    def __init__(self, sm_config, busy=None, metrics=None):
        from ..parallel.distributed import compile_cache_path

        self.sm_config = sm_config
        self.cfg = sm_config.service.prime
        self.busy = busy or (lambda: False)
        self._cache_dir = compile_cache_path(sm_config)
        shape_buckets.bind_manifest_dir(self._cache_dir)
        self._manifest = _PrimeManifest(self._cache_dir)
        self._lock = threading.Lock()
        self._status: dict[str, str] = {}      # spec_key -> last outcome
        self._cycles = 0
        self._last_cycle_s = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._metrics = metrics
        if metrics is not None:
            self.m_compiled = metrics.counter(
                "sm_prime_compiled_total",
                "Bucket executables AOT-compiled into the persistent "
                "XLA cache by the primer")
            self.m_skipped = metrics.counter(
                "sm_prime_skipped_total",
                "Primer specs skipped (already primed, non-flat kind, "
                "or cycle aborted)", ("reason",))
            self.m_errors = metrics.counter(
                "sm_prime_errors_total",
                "Primer compile attempts that raised")
            self.m_cycles = metrics.counter(
                "sm_prime_cycles_total", "Idle prime cycles run")
            self.g_known = metrics.gauge(
                "sm_prime_known_buckets",
                "Bucket specs recorded in the lattice manifest")
            self.g_primed = metrics.gauge(
                "sm_prime_primed_buckets",
                "Bucket specs proven primed for this environment")
            self.g_last = metrics.gauge(
                "sm_prime_last_cycle_seconds",
                "Wall clock of the most recent prime cycle")

    # ---------------------------------------------------------------- specs
    def known_specs(self) -> list[dict]:
        """Recorded specs: this process's registry folded with the
        persisted bucket manifest (other replicas/processes record too)."""
        specs = {shape_buckets.spec_key(s): s
                 for s in shape_buckets.recorded_specs()}
        if self._cache_dir is not None:
            for s in shape_buckets.load_manifest(self._cache_dir):
                specs.setdefault(shape_buckets.spec_key(s), s)
        return list(specs.values())

    # ---------------------------------------------------------------- prime
    def prime_once(self, max_specs: int | None = None,
                   abort_when_busy: bool = True) -> dict:
        """One prime cycle: compile every known, un-primed, flat spec.
        Returns ``{compiled, skipped, errors, aborted}``.  Idempotent —
        primed specs are skipped via the prime manifest, so an
        interrupted cycle resumes exactly where it stopped."""
        env = _env_key()
        out = {"compiled": 0, "skipped": 0, "errors": 0, "aborted": False}
        limit = max_specs if max_specs is not None else (
            self.cfg.max_specs_per_cycle or None)
        t0 = time.perf_counter()
        for spec in self.known_specs():
            if self._stop.is_set() or (abort_when_busy and self.busy()):
                # a real job arrived: yield immediately — the next idle
                # cycle resumes from the manifest
                out["aborted"] = True
                break
            if limit is not None and out["compiled"] >= limit:
                out["aborted"] = True
                break
            key = shape_buckets.spec_key(spec)
            if self._manifest.primed(key, env):
                out["skipped"] += 1
                self._note(key, "primed", "already_primed")
                continue
            try:
                status = prime_spec(spec, sm_config=self.sm_config)
            except Exception:
                out["errors"] += 1
                self._note(key, "error", None)
                if self._metrics is not None:
                    self.m_errors.inc()
                logger.warning("primer: compile failed for %s", key,
                               exc_info=True)
                continue
            if status == "compiled":
                out["compiled"] += 1
                self._manifest.mark(key, env)
                self._note(key, "primed", None)
                if self._metrics is not None:
                    self.m_compiled.inc()
                logger.info("primer: compiled bucket %s", key)
            else:
                out["skipped"] += 1
                self._note(key, status, status.split(":", 1)[-1])
        dt = time.perf_counter() - t0
        with self._lock:
            self._cycles += 1
            self._last_cycle_s = dt
        if self._metrics is not None:
            self.m_cycles.inc()
            self.g_last.set(dt)
            self._refresh_gauges()
        return out

    def _note(self, key: str, status: str, skip_reason: str | None) -> None:
        with self._lock:
            self._status[key] = status
        if skip_reason and self._metrics is not None:
            self.m_skipped.labels(reason=skip_reason).inc()

    def _refresh_gauges(self) -> None:
        self.g_known.set(len(self.known_specs()))
        self.g_primed.set(self._manifest.count())

    # ------------------------------------------------------------- snapshot
    def snapshot(self) -> dict:
        """The ``GET /debug/compile`` body's primer half: every known
        bucket with its primed/missing status."""
        env = _env_key()
        with self._lock:
            status = dict(self._status)
            cycles, last = self._cycles, self._last_cycle_s
        buckets = []
        primed = missing = 0
        for spec in self.known_specs():
            key = shape_buckets.spec_key(spec)
            if self._manifest.primed(key, env):
                st = "primed"
                primed += 1
            else:
                st = status.get(key, "missing")
                if not st.startswith("skipped"):
                    st = "missing"
                missing += 1
            buckets.append({**spec, "status": st})
        return {
            "enabled": bool(self.cfg.enabled),
            "env": env,
            "cache_dir": (str(self._cache_dir)
                          if self._cache_dir is not None else None),
            "known": len(buckets),
            "primed": primed,
            "missing": missing,
            "cycles": cycles,
            "last_cycle_s": round(last, 3),
            "buckets": buckets,
        }

    # ------------------------------------------------------------ lifecycle
    def _loop(self) -> None:
        idle_since: float | None = None
        while not self._stop.is_set():
            if self.busy():
                idle_since = None
            elif idle_since is None:
                idle_since = time.time()
            elif time.time() - idle_since >= self.cfg.idle_after_s:
                try:
                    res = self.prime_once()
                except Exception:
                    logger.warning("primer cycle failed", exc_info=True)
                    res = {"aborted": True}
                # everything known is primed: sleep the rescan interval;
                # an aborted cycle retries as soon as idleness returns
                if not res.get("aborted"):
                    self._stop.wait(self.cfg.interval_s)
                idle_since = None
            self._stop.wait(min(0.5, self.cfg.idle_after_s or 0.5))

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="cache-primer")
        self._thread.start()
        logger.info("primer: idle cache priming up (idle_after=%.1fs)",
                    self.cfg.idle_after_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
