"""AnnotationService — the long-running serving composition.

Wires together the spool (``QueuePublisher`` for ``POST /submit``), the
``JobScheduler`` worker pool, the metrics registry (phase-timer observer +
residency collector + spool depth gauges), and the ``AdminAPI``, with
POSIX-graceful shutdown: SIGTERM/SIGINT stop admission, requeue
claimed-but-unstarted messages, drain running jobs, then stop the API —
``running/`` is empty on a clean exit, so a restart resumes exactly the
pending backlog.
"""

from __future__ import annotations

import signal
import threading
import time
from pathlib import Path

from ..engine.daemon import QUEUE_ANNOTATE, QueuePublisher, _STATES
from ..models import faults, oom
from ..models.breaker import attach_metrics as attach_breaker_metrics
from ..models.breaker import get_device_breaker
from ..utils import tracing
from ..utils.config import SMConfig
from ..utils.failpoints import attach_metrics as attach_failpoint_metrics
from ..utils.logger import add_phase_observer, logger, remove_phase_observer
from .admission import AdmissionController
from .api import AdminAPI
from .device_pool import DevicePool, resolve_pool_size
from .metrics import MetricsRegistry, build_info_collector, process_collector
from .resources import ResourceGovernor, set_governor
from .scheduler import JobScheduler
from .telemetry import DeviceMonitor, SLOTracker


class AnnotationService:
    def __init__(
        self,
        queue_dir: str | Path,
        callback,
        sm_config: SMConfig | None = None,
        queue: str = QUEUE_ANNOTATE,
        residency=None,
        with_api: bool = True,
    ):
        self.sm_config = sm_config or SMConfig.get_conf()
        cfg = self.sm_config.service
        self.queue_dir = Path(queue_dir)
        self.queue = queue
        self.metrics = MetricsRegistry()
        self.publisher = QueuePublisher(queue_dir, queue=queue)
        # end-to-end tracing (ISSUE 5, docs/OBSERVABILITY.md): per-job JSONL
        # files + the flight-recorder ring behind /jobs/<id>/trace and
        # /debug/events.  tracing.enabled=false keeps only the no-op stubs.
        tracing.configure(enabled=self.sm_config.tracing.enabled,
                          ring_size=self.sm_config.tracing.ring_size)
        self.trace_dir = (self.sm_config.trace_dir
                          if self.sm_config.tracing.enabled else None)
        # replica identity (ISSUE 8): stamped on every trace record and
        # telemetry sample this process emits
        tracing.set_replica(cfg.replica_id)
        # overload protection in front of /submit: bounded depth, per-tenant
        # quotas, EWMA latency shedding (service/admission.py); the
        # scheduler feeds terminal outcomes + attempt latency back into it.
        # State is replica-local; the spool re-adoption and the peer view
        # are wired after the scheduler exists (it owns the shard map).
        self.admission = AdmissionController(cfg.admission, metrics=self.metrics)
        # SLO instrumentation (service/telemetry.py): queue-wait / first-
        # annotation / e2e histograms recorded at the scheduler's seams,
        # attainment served by GET /slo
        self.slo = SLOTracker(self.metrics, self.sm_config.telemetry)
        # multi-chip device pool (ISSUE 7): resolved against the configured
        # backend so a jax_tpu service leases out every visible chip, while
        # a numpy_ref service keeps the degenerate 1-chip pool (= the old
        # single-token serialization)
        pool_size = resolve_pool_size(cfg, backend=self.sm_config.backend)
        # per-chip health (ISSUE 14, service/health.py): quarantined chips
        # leave placement, lease-time probes fence dead chips before a job
        # touches them, half-open re-probes readmit recovered ones —
        # surfaced on GET /debug/devices and sm_device_* metrics
        from .health import HealthTracker

        self.device_pool = DevicePool(
            pool_size,
            max_bypass=cfg.device_pool_max_bypass,
            hosts=cfg.device_pool_hosts,
            health=HealthTracker.from_config(
                pool_size, cfg, hosts=cfg.device_pool_hosts))
        self.device_pool.attach_metrics(self.metrics)
        # resource governor (ISSUE 10, service/resources.py): disk-budget
        # preflight at every governed write seam, degrade order traces →
        # cache → 507 submits, bounded-retention GC run from the
        # scheduler's replica loop.  Installed as the process singleton so
        # the engine seams (checkpoints, results, publish, cache shards)
        # and the admission controller consult it without plumbing;
        # tracing's file gate makes trace appends the FIRST thing dropped.
        read_cache_dir = Path(self.sm_config.work_dir) / "read_cache"
        from ..engine.stream import StreamIngest, stream_root

        stream_dir = stream_root(self.sm_config)
        self.resources = ResourceGovernor(
            self.sm_config.resources,
            work_dir=self.sm_config.work_dir,
            results_dir=self.sm_config.storage.results_dir,
            queue_root=self.queue_dir / queue,
            trace_dir=self.trace_dir,
            cache_dir=Path(self.sm_config.work_dir) / "isocalc_cache",
            tracing_cfg=self.sm_config.tracing,
            metrics=self.metrics, replica_id=cfg.replica_id,
            read_cache_dir=read_cache_dir,
            read_cache_max_bytes=cfg.read.cache_disk_max_bytes,
            stream_dir=stream_dir,
            stream_retention_age_s=cfg.stream.retention_age_s,
            stream_idle_timeout_s=cfg.stream.idle_timeout_s)
        set_governor(self.resources)
        tracing.set_file_gate(self.resources.trace_gate)
        # live-acquisition ingest (ISSUE 19, engine/stream.py): the HTTP
        # chunk seam (POST /datasets/<id>/pixels|finish) appends into the
        # crash-safe chunk log that StreamSearchJob re-scores from; shared
        # work_dir means any replica can serve appends for any acquisition
        self.stream_ingest = StreamIngest(stream_dir, metrics=self.metrics)
        # result read path (ISSUE 16, service/readpath.py): governed LRU +
        # segment reader + tile renderer behind the GET endpoints; cache
        # fills consult the governor's no-read-cache degrade level
        from .readpath import ReadPath

        self.readpath = ReadPath(
            self.sm_config.storage.results_dir, cfg.read,
            governor=self.resources, metrics=self.metrics, slo=self.slo,
            disk_dir=read_cache_dir) if cfg.read.enabled else None
        # HBM-OOM adaptive-scoring telemetry (models/oom.py): events,
        # converged backoffs, and the learned safe batch on /metrics
        oom.attach_metrics(self.metrics)
        # classified device-fault telemetry (models/faults.py, ISSUE 14):
        # sm_device_faults_total{kind=} beside the oom/breaker families
        faults.attach_metrics(self.metrics)
        # compile-retrace attribution (ISSUE 12, analysis/retrace.py):
        # every XLA compilation this process pays for is attributed to its
        # call site + abstract signature (sm_compile_* on /metrics, a
        # `compile` event on the owning job's trace) — the runtime half of
        # the COMPILE_SURFACE closed-signature-set invariant
        if self.sm_config.telemetry.retrace:
            from ..analysis import retrace

            retrace.enable(metrics=self.metrics)
        self.scheduler = JobScheduler(
            queue_dir, callback, config=cfg, queue=queue, metrics=self.metrics,
            admission=self.admission, trace_dir=self.trace_dir, slo=self.slo,
            device_pool=self.device_pool, resources=self.resources)
        # ahead-of-time cache primer (ISSUE 13, service/primer.py): when
        # the spool sits idle, AOT-compile the recorded shape-bucket
        # lattice into the persistent XLA cache so a cold submit loads
        # executables instead of compiling.  Constructed even when
        # disabled — GET /debug/compile serves its primed-vs-missing view
        # either way; only the idle thread is gated on the knob.
        from .primer import CachePrimer

        self.primer = CachePrimer(
            self.sm_config, busy=self._primer_busy, metrics=self.metrics)
        # replica-scoped spool re-adoption + the registry-backed peer view:
        # each replica tracks its own shards and folds the peers' gossiped
        # summaries into its quota/shed decisions (GET /peers serves both)
        self.admission.sync_from_spool(self.queue_dir / queue,
                                       owns_msg=self.scheduler.owns_msg)
        self.admission.set_peer_view(self.scheduler.peer_admission_summaries)
        # device & memory telemetry: HBM/occupancy/cache sampler feeding
        # gauges + the GET /debug/timeseries snapshot ring
        from ..parallel.distributed import compile_cache_path

        self.telemetry = DeviceMonitor(
            self.metrics, self.sm_config.telemetry,
            device_pool=self.device_pool,
            queue_root=self.queue_dir / queue,
            compile_cache_dir=compile_cache_path(self.sm_config),
            replica_id=cfg.replica_id,
            readpath=self.readpath, stream_ingest=self.stream_ingest)
        # device-backend circuit breaker: configure the process singleton
        # from THIS service's knobs and export its state on /metrics
        get_device_breaker(cfg)
        attach_breaker_metrics(self.metrics)
        self.residency = residency
        self.started_at = time.time()
        self._stop_requested = threading.Event()
        self._shutdown_done = threading.Event()
        self._shutdown_once = threading.Lock()
        self._phase_hist = self.metrics.histogram(
            "sm_phase_seconds", "Pipeline phase wall clock by phase name",
            ("phase",))
        # chaos observability: sm_failpoints_injected_total{name=} and
        # sm_recovery_events_total{event=} surface on /metrics
        attach_failpoint_metrics(self.metrics)
        # isocalc cold-path observability (ISSUE 3): pattern counter +
        # per-generation worker/rate gauges, plus a scrape-window rate
        from ..ops import isocalc as isocalc_mod
        from .metrics import rate_collector

        isocalc_mod.attach_metrics(self.metrics)
        rate_collector(self.metrics, "sm_isocalc_patterns_scrape_rate_per_s",
                       "Isotope patterns computed per second, over the "
                       "window since the previous scrape",
                       isocalc_mod.patterns_total)
        # build identity + process health (ISSUE 5 satellite): dashboards
        # need a version/backend join key and leak-spotting gauges (RSS,
        # threads, FDs) the load sweep only catches in tests
        build_info_collector(self.metrics, backend=self.sm_config.backend)
        process_collector(self.metrics)
        if residency is not None:
            self.metrics.add_collector(self._collect_residency)
        self.api = AdminAPI(self, host=cfg.http_host,
                            port=cfg.http_port) if with_api else None
        # fleet observability plane (ISSUE 20, service/fleetview.py):
        # /fleet/* aggregation across live replicas + /debug/profile
        # on-demand device capture.  The admin address, pool occupancy and
        # in-flight stream count are gossiped through registry heartbeats
        # so peers can scrape this replica without another channel — the
        # API binds its socket in __init__, so the address is final here.
        from .fleetview import DeviceProfiler, FleetView

        self.fleetview = (FleetView(self, cfg.fleetview)
                          if cfg.fleetview.enabled and with_api else None)
        self.profiler = DeviceProfiler(self, self.sm_config.telemetry.profile)
        if self.api is not None:
            self.scheduler.add_gossip(
                "admin", lambda: "%s:%d" % self.api.address)
        self.scheduler.add_gossip("pool", self._gossip_pool)
        self.scheduler.add_gossip("streams_in_flight",
                                  self.stream_ingest.in_flight)

    def _gossip_pool(self) -> dict:
        """The heartbeat-sized pool summary peers fold into /fleet/status
        (the full per-chip view stays on this replica's /debug/devices)."""
        snap = self.device_pool.snapshot()
        return {"size": snap["size"], "in_use": snap["in_use"],
                "waiters": snap["waiters"]}

    # -------------------------------------------------------------- metrics
    def _observe_phase(self, phase: str, seconds: float) -> None:
        self._phase_hist.labels(phase=phase).observe(seconds)

    def _collect_residency(self, m: MetricsRegistry) -> None:
        """Scrape-time pull of ``DatasetResidency.stats`` into counters
        (the stats ARE cumulative, so exposing their current value under a
        counter type is faithful)."""
        stats = self.residency.stats
        hits = m.counter("sm_residency_hits_total",
                         "Residency cache hits", ("cache",))
        misses = m.counter("sm_residency_misses_total",
                           "Residency cache misses", ("cache",))
        for cache in ("dataset", "backend"):
            h = hits.labels(cache=cache)
            miss = misses.labels(cache=cache)
            # counters only move forward; set via delta from the live stats
            h.inc(max(0.0, stats[f"{cache}_hits"] - h.value))
            miss.inc(max(0.0, stats[f"{cache}_misses"] - miss.value))

    def queue_depths(self) -> dict:
        root = self.queue_dir / self.queue
        return {s: len(list(root.glob(f"{s}/*.json"))) for s in _STATES}

    def _primer_busy(self) -> bool:
        """Real work in flight?  The primer only runs while this is False
        (and re-checks between specs), so priming never delays a job."""
        if self.scheduler.live_claims() > 0:
            return True
        root = self.queue_dir / self.queue
        return any(True for _ in root.glob("pending/*.json")) or \
            any(True for _ in root.glob("running/*.json"))

    def stopping(self) -> bool:
        """True once shutdown began — /submit sheds with 503 from here on."""
        return self._stop_requested.is_set()

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        # additive registration (ISSUE 5 satellite): the old single-slot
        # set_phase_observer silently evicted any other observer
        add_phase_observer(self._observe_phase)
        # first-annotation SLI: msm_basic notifies once per search when the
        # first checkpoint group's metrics land (producer-side observer
        # list, same pattern as phase observers)
        from ..models.msm_basic import add_first_annotation_observer

        add_first_annotation_observer(self.slo.note_first_annotation)
        if self.sm_config.telemetry.enabled:
            self.telemetry.start()
        self.scheduler.start()
        if self.sm_config.service.prime.enabled:
            self.primer.start()
        if self.api is not None:
            self.api.start()
        logger.info("service: up (queue=%s)", self.queue_dir / self.queue)

    def shutdown(self, timeout_s: float | None = None) -> bool:
        """Drain and stop everything; safe to call more than once.  A
        concurrent caller BLOCKS until the in-flight drain finishes —
        otherwise the main thread (run_forever's finally) can exit the
        process while the signal-drain thread is still mid-retire,
        leaving registry/heartbeat debris behind (ISSUE 11: a retired
        replica must leave nothing)."""
        with self._shutdown_once:
            if self._stop_requested.is_set():
                first = False
            else:
                self._stop_requested.set()
                first = True
        if not first:
            self._shutdown_done.wait(
                timeout=(timeout_s if timeout_s is not None else
                         self.sm_config.service.drain_timeout_s) + 10.0)
            return True
        logger.info("service: shutdown requested — draining")
        self.primer.stop()
        ok = self.scheduler.shutdown(timeout_s)
        if self.api is not None:
            self.api.stop()
        self.telemetry.stop()
        from ..models.msm_basic import remove_first_annotation_observer

        remove_first_annotation_observer(self.slo.note_first_annotation)
        remove_phase_observer(self._observe_phase)
        # detach the resource governor so a later service (tests run many
        # per process) starts from its own budget, not this one's
        from .resources import get_governor

        if get_governor() is self.resources:
            tracing.set_file_gate(None)
            set_governor(None)
        self._shutdown_done.set()
        return ok

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT → graceful drain.  Only valid in the main thread."""

        def _handler(signum, frame):
            logger.info("service: received signal %d", signum)
            # handler must return fast; the drain happens in a helper thread
            threading.Thread(target=self.shutdown, daemon=True,
                             name="signal-drain").start()

        signal.signal(signal.SIGTERM, _handler)
        signal.signal(signal.SIGINT, _handler)

    def run_forever(self, max_terminal: int | None = None,
                    idle_timeout_s: float | None = None) -> int:
        """Block until shutdown (signal or programmatic).  ``max_terminal``
        stops after N jobs reach a terminal state (smoke tests);
        ``idle_timeout_s`` stops after the spool stays empty that long."""
        idle_since = None
        try:
            while not self._stop_requested.is_set():
                if self.scheduler.drain_complete():
                    # zero-loss drain (ISSUE 11): the replica acked — every
                    # claim resolved, nothing more will be written; exit so
                    # the controller can count the drain done
                    logger.info("service: drain acked — retiring")
                    break
                if max_terminal is not None and \
                        self.scheduler._terminal_count >= max_terminal:
                    break
                if idle_timeout_s is not None:
                    depths = self.queue_depths()
                    busy = depths["pending"] or depths["running"]
                    if busy:
                        idle_since = None
                    elif idle_since is None:
                        idle_since = time.time()
                    elif time.time() - idle_since >= idle_timeout_s:
                        break
                time.sleep(0.1)
        finally:
            self.shutdown()
        return 0
