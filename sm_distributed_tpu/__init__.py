"""sm_distributed_tpu — TPU-native spatial-metabolomics annotation engine.

A from-scratch, TPU-first (JAX / XLA / pjit / Pallas) framework with the
capabilities of the METASPACE annotation engine (reference:
``frulo/SM_distributed``, see SURVEY.md): FDR-controlled molecular annotation
of imaging-mass-spectrometry (imzML) datasets.

Where the reference runs ion-image extraction and MSM scoring as a Spark-RDD
pipeline over a CPU cluster (``sm/engine/msm_basic/*`` [U]), this framework
holds the (pixels x m/z) spectral cube as a mesh-sharded device array,
precomputes theoretical isotope patterns into a device-resident tensor, and
runs extraction -> scoring -> target/decoy FDR as one fused XLA graph vmapped
over formula batches, selectable behind a config-level backend switch
(``backend: numpy_ref | jax_tpu``) with the NumPy backend as parity oracle.
"""

__version__ = "0.1.0"
