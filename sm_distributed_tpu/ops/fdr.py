"""Target/decoy FDR engine.

Reference: ``sm/engine/fdr.py::FDR`` [U] (SURVEY.md #10): for every
(formula, target adduct), sample ``decoy_sample_size`` implausible elemental
adducts from ``DECOY_ADDUCTS``; score decoy ions with the same MSM pipeline;
rank targets against decoys per target adduct; report each annotation at the
minimal passing FDR level in {0.05, 0.1, 0.2, 0.5}.

Decoy sampling is explicitly seeded (SURVEY.md §7 hard part 3): the reference
uses an unseeded RNG, which makes runs irreproducible — here the seed lives
in config (``fdr.seed``) so numpy_ref and jax_tpu backends rank identically.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import pandas as pd

# The reference's implausible-adduct list (sm/engine/fdr.py::DECOY_ADDUCTS [U]).
DECOY_ADDUCTS: tuple[str, ...] = tuple(
    "+" + el
    for el in (
        "He Li Be B C N O F Ne Mg Al Si P S Cl Ar Ca Sc Ti V Cr Mn Fe Co Ni Cu Zn "
        "Ga Ge As Se Br Kr Rb Sr Y Zr Nb Mo Ru Rh Pd Ag Cd In Sn Sb Te I Xe Cs Ba "
        "La Ce Pr Nd Sm Eu Gd Tb Dy Ho Ir Th Pt Os Yb Lu Tm Er Pb Tl Hg Au W Ta Hf Re"
    ).split()
)

FDR_LEVELS: tuple[float, ...] = (0.05, 0.1, 0.2, 0.5)


@dataclass
class DecoyAssignment:
    """Sampled decoys: maps each (sf, target_adduct) to its decoy adducts."""

    sample: dict[tuple[str, str], tuple[str, ...]]
    decoy_sample_size: int

    def all_ion_tuples(
        self, sfs: list[str], target_adducts: tuple[str, ...]
    ) -> tuple[list[tuple[str, str]], list[bool]]:
        """Deduplicated (sf, adduct) list to score + per-ion target flag.
        A decoy ion sampled under several target adducts is scored once
        (reference dedups the same way before theor-peak generation [U])."""
        pairs: list[tuple[str, str]] = []
        flags: list[bool] = []
        seen: set[tuple[str, str]] = set()
        for sf in sfs:
            for ta in target_adducts:
                key = (sf, ta)
                if key not in seen:
                    seen.add(key)
                    pairs.append(key)
                    flags.append(True)
        for (sf, _ta), decoys in self.sample.items():
            for da in decoys:
                key = (sf, da)
                if key not in seen:
                    seen.add(key)
                    pairs.append(key)
                    flags.append(False)
        return pairs, flags


class FDR:
    """Reference-compatible FDR engine (class name kept, SURVEY.md #10)."""

    def __init__(
        self,
        decoy_sample_size: int = 20,
        target_adducts: tuple[str, ...] = ("+H", "+Na", "+K"),
        seed: int = 42,
    ):
        if decoy_sample_size < 1:
            raise ValueError("decoy_sample_size must be >= 1")
        self.decoy_sample_size = decoy_sample_size
        self.target_adducts = tuple(target_adducts)
        self.seed = seed
        candidates = [a for a in DECOY_ADDUCTS if a not in self.target_adducts]
        if decoy_sample_size > len(candidates):
            raise ValueError(
                f"decoy_sample_size {decoy_sample_size} exceeds the "
                f"{len(candidates)} available decoy adducts"
            )
        self._candidates = candidates

    def decoy_adduct_selection(self, sfs: list[str]) -> DecoyAssignment:
        """Sample decoy adducts per (formula, target adduct) — reference:
        ``FDR.decoy_adduct_selection`` storing ``target_decoy_add`` [U]."""
        rng = np.random.default_rng(self.seed)
        cand = np.array(self._candidates)
        sample: dict[tuple[str, str], tuple[str, ...]] = {}
        for sf in sfs:
            for ta in self.target_adducts:
                picks = rng.choice(cand, size=self.decoy_sample_size, replace=False)
                sample[(sf, ta)] = tuple(picks)
        return DecoyAssignment(sample=sample, decoy_sample_size=self.decoy_sample_size)

    @staticmethod
    def _qvalues(target_msm: np.ndarray, decoy_msm: np.ndarray, decoy_sample_size: int
                 ) -> np.ndarray:
        """q-value per target: FDR(t) = (#decoys>=t / decoy_sample_size) /
        #targets>=t, monotonized by the reverse running minimum.  Ties count
        the decoy first (conservative)."""
        n_t = target_msm.size
        if n_t == 0:
            return np.zeros(0)
        scores = np.concatenate([target_msm, decoy_msm])
        is_target = np.concatenate([
            np.ones(n_t, dtype=bool), np.zeros(decoy_msm.size, dtype=bool)
        ])
        # sort by score desc; on ties decoys come first (is_target False < True)
        order = np.lexsort((is_target, -scores))
        s_target = is_target[order]
        cum_t = np.cumsum(s_target)
        cum_d = np.cumsum(~s_target)
        fdr = (cum_d / decoy_sample_size) / np.maximum(cum_t, 1)
        q = np.minimum.accumulate(fdr[::-1])[::-1]
        # map back to each target's position in the sorted array
        q_target_sorted = q[s_target]
        target_order = order[s_target]  # original target indices, by score desc
        out = np.empty(n_t)
        out[target_order] = q_target_sorted
        return out

    def estimate_fdr(self, msm_df: pd.DataFrame, assignment: DecoyAssignment
                     ) -> pd.DataFrame:
        """Annotate target ions with q-values + snapped FDR levels.

        ``msm_df`` columns: sf, adduct, msm — one row per scored ion (targets
        and decoys).  Returns the target rows with added ``fdr`` (continuous
        q-value) and ``fdr_level`` (smallest passing level from FDR_LEVELS, or
        1.0) — reference: ``FDR.estimate_fdr`` [U].
        """
        # Vectorized ranking (VERDICT r1 weak #8: the per-ion dict loops cost
        # ~5M dict.gets at 80k-formula scale).  Decoy scores resolve through
        # ONE left merge per target adduct; ordering matches the original
        # loops exactly (targets in msm_df row order, decoys in
        # (target-row, sampled-decoy) order), so q-values are bit-identical.
        frames = []
        for ta in self.target_adducts:
            t = msm_df[msm_df.adduct == ta]
            if t.empty:
                continue
            sfs_arr = t.sf.to_numpy()
            target_msm = t.msm.to_numpy(dtype=np.float64)
            dec_lists = [assignment.sample.get((sf, ta), ()) for sf in sfs_arr]
            k = max((len(d) for d in dec_lists), default=0)
            if k:
                dec = np.array([list(d) + [""] * (k - len(d)) for d in dec_lists])
                pairs = pd.DataFrame({
                    "sf": np.repeat(sfs_arr, k), "adduct": dec.ravel()})
                pairs = pairs[pairs.adduct != ""]
                merged = pairs.merge(msm_df[["sf", "adduct", "msm"]],
                                     on=["sf", "adduct"], how="left")
                decoy_msm = merged.msm.fillna(0.0).to_numpy(dtype=np.float64)
            else:
                decoy_msm = np.zeros(0)
            q = self._qvalues(target_msm, decoy_msm, self.decoy_sample_size)
            level = np.select([q <= lv for lv in FDR_LEVELS],
                              FDR_LEVELS, default=1.0)
            frames.append(pd.DataFrame({
                "sf": sfs_arr, "adduct": ta, "msm": target_msm,
                "fdr": q, "fdr_level": level,
            }))
        if not frames:
            return pd.DataFrame(
                columns=["sf", "adduct", "msm", "fdr", "fdr_level"])
        out = pd.concat(frames, ignore_index=True)
        # "sf" as the final key makes the row order a TOTAL order: without
        # it, exact-MSM ties kept the incoming table order, which depends
        # on the internal parallel.order_ions batching knob
        return out.sort_values(
            ["adduct", "msm", "sf"], ascending=[True, False, True]
        ).reset_index(drop=True)
