"""Fused window-gather + MSM-moment Pallas kernel (ISSUE 18).

The flat scoring path (models/msm_jax.fused_score_fn_flat_banded) is a
chain of XLA dispatches over the same bytes: histogram scatter -> per-chunk
band slice -> membership matmul -> materialized (B*K, P) image block ->
moments kernel -> metric epilogues.  The image block round-trips HBM
between the matmul and the moments pass — at DESI shapes that is ~1 GB
written and ~1 GB re-read per 256-ion batch that the roofline ledger
(docs/PERF.md) charges to pure memory traffic.

This kernel fuses the band matmul WITH the moment reductions so each image
tile lives only in VMEM: grid ``(C, 2, nt)`` — C m/z-sorted window chunks
(the ``ion_window_chunks`` plan) x the exact two-pass centered-moment
schedule x nt pixel tiles.  TPU grids run sequentially, so the per-chunk
``(1, Wc, 5)`` partials block stays resident across the pass/tile steps
and accumulates in place (flushed when the chunk index advances).  Only
the PRINCIPAL image rows (chaos needs the full spatial layout of peak 0)
are written back at full width — 1/K of the unfused image traffic.

Banding is data-dependent (each chunk reads grid rows
``[start_c, start_c + gc_width + 2)``), which Pallas expresses with
SCALAR PREFETCH: the histogram is reshaped to ``(cols_p/SC, SC, P)``
super-rows and the block index map fetches ``nsb`` super-rows starting at
``starts[c] // SC`` — the in-kernel rank shift ``starts[c] - SC *
(starts[c] // SC)`` re-aligns window ranks exactly like the unfused
path's clamped ``dynamic_slice`` shift.

Numerics: the membership matmul accumulates the same quantized-grid
integer sums (< 2**24, order-free) at ``Precision.HIGHEST``, so principal
images, pixel sums, maxima and positive counts — hence chaos and the
spectral pattern match — are BIT-EXACT versus the unfused path; the
centered norm/dot reductions tile in ``pt`` columns instead of XLA's tree,
so the spatial correlation moves within the declared ulp ceiling.  The
exact contracts are declared below and proven by tests/test_score_pallas.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..analysis.numerics import numerics_surface
from ..analysis.surface import compile_surface

NUMERICS = numerics_surface(__name__, {
    # principal rows + sums/vmax/nn are exact integer-grid sums (any
    # association order) at HIGHEST precision; normsq/dots re-associate
    # per pixel tile -> same ulp class as the moments kernel it replaces.
    "fused_window_moments":
        "contract=ulp(16); test=tests/test_score_pallas.py::"
        "test_fused_matches_unfused; padded=whp",
})

COMPILE_SURFACE = compile_surface(__name__, {
    "fused_window_moments":
        "statics=gc_width,k,interpret; buckets=one executable per "
        "(cols_p, P) scratch x (C, Wc) chunk-plan shape; every dimension "
        "rides the shape-bucket lattice (peak_bucket/row_bucket + the "
        "formula_batch ladder), and starts/n_real are traced scalar-"
        "prefetch operands, so dataset sizes inside a bucket share one "
        "executable",
})

# f32 sublane height: the histogram super-row granularity.  The scalar-
# prefetch block index map can only address whole blocks, so chunk bands
# are fetched as nsb super-rows of SC grid rows and the <SC-row residual
# start offset becomes an in-kernel rank shift.
SC = 8
# VMEM budget in f32 cells for one grid step's resident set (band tile +
# membership + image tile + partials) — same scoped-VMEM envelope as
# ops/moments_pallas._MAX_CELLS.
_MAX_CELLS = 2 * 1024 * 1024
# pixel-tile ladder (lanes): largest dividing tile wins
_PT_LADDER = (4096, 2048, 1024, 512, 256, 128)


def n_super_blocks(gc_width: int) -> int:
    """Super-rows per chunk band: cover gc_width + 2 rows from any
    within-super-row start offset, i.e. ceil((gc + 2 + SC - 1) / SC) —
    the shift (<= SC - 1) eats into the first super-row."""
    return (gc_width + 2 + 2 * (SC - 1)) // SC


def cols_padded(g: int, gc_width: int) -> int:
    """Histogram scratch rows for the fused path: the unfused scratch
    width rounded up to whole super-rows, plus nsb - 1 spare super-rows so
    ``starts // SC + nsb`` stays in bounds without clamping (starts <= g;
    see the inequality chain in fused_window_moments)."""
    base = max(g + 1, gc_width + 2)
    return -(-base // SC) * SC + (n_super_blocks(gc_width) - 1) * SC


def pick_tile(n_pix: int, wc: int, ipc: int, gc_width: int):
    """Largest pixel tile (multiple of 128 dividing n_pix) whose resident
    set fits the VMEM budget, or None when none fits / n_pix is off the
    128-lane lattice (the caller then keeps the unfused path)."""
    if n_pix <= 0 or n_pix % 128 != 0:
        return None
    rows = n_super_blocks(gc_width) * SC
    for pt in _PT_LADDER:
        if n_pix % pt != 0:
            continue
        cells = (rows * pt          # staged band tile
                 + wc * rows        # membership matrix
                 + wc * pt          # image tile
                 + ipc * pt         # principal output block
                 + wc * 5)          # partials block
        if cells <= _MAX_CELLS:
            return pt
    return None


def fused_fit(wc: int, ipc: int, n_pix: int, gc_width: int) -> bool:
    """True when the fused kernel can run COMPILED for this plan shape."""
    return pick_tile(n_pix, wc, ipc, gc_width) is not None


def _fused_kernel(starts_ref, s3_ref, nr_ref, wh_ref, rlo_ref, rhi_ref,
                  out_ref, prin_ref, *, ipc: int, k: int, pt: int):
    """One (chunk, pass, tile) step.

    Pass 0 accumulates sums/vmax/nn; pass 1 re-derives the image tile
    (one extra VMEM matmul — memory-bound, the band tile is already
    staged) and accumulates the centered normsq/dots with the mean taken
    from the pass-0 sums.  The partials block's index map ignores
    (pass, tile), so it stays VMEM-resident per chunk — the standard
    Pallas accumulation pattern.  Principal rows are written on BOTH
    passes (bit-identical values) so every visited output block is fully
    defined.
    """
    ps = pl.program_id(1)
    t = pl.program_id(2)
    wc = ipc * k
    c = pl.program_id(0)
    rows = wh_ref.shape[0]
    # re-align local window ranks to the fetched super-row origin: staged
    # row r holds global grid row s3*SC + r, i.e. local rank r - shift
    shift = starts_ref[c] - s3_ref[c] * SC

    band = wh_ref[...]                                    # (nsb*SC, pt)
    lo = rlo_ref[0, :] + shift                            # (Wc,)
    hi = rhi_ref[0, :] + shift
    gg = jax.lax.broadcasted_iota(jnp.int32, (wc, rows), 1)
    d = ((gg > lo[:, None]) & (gg <= hi[:, None])).astype(jnp.float32)
    # integer-grid sums < 2**24: exact in f32 at HIGHEST in any order
    imgs = jnp.dot(d, band, precision=jax.lax.Precision.HIGHEST,
                   preferred_element_type=jnp.float32)    # (Wc, pt)
    prin_ref[0] = imgs.reshape(ipc, k, pt)[:, 0, :]

    @pl.when((ps == 0) & (t == 0))
    def _init():
        out_ref[0] = jnp.zeros((wc, 5), jnp.float32)

    @pl.when(ps == 0)
    def _pass0():
        acc = out_ref[0]
        # pad pixel columns are exact zeros (pad peaks scatter 0.0), so
        # sums/vmax/nn need no n_real mask — same argument as the masked
        # jnp moments (images >= 0: window sums of nonnegative intensity)
        sums = acc[:, 0] + jnp.sum(imgs, axis=1)
        vmax = jnp.maximum(acc[:, 3], jnp.max(imgs, axis=1))
        nn = acc[:, 4] + jnp.sum((imgs > 0.0).astype(jnp.float32), axis=1)
        out_ref[0] = jnp.stack([sums, acc[:, 1], acc[:, 2], vmax, nn],
                               axis=1)

    @pl.when(ps == 1)
    def _pass1():
        acc = out_ref[0]
        nre = nr_ref[0]
        mean = acc[:, 0:1] / nre.astype(jnp.float32)      # (Wc, 1)
        col = jax.lax.broadcasted_iota(jnp.int32, (wc, pt), 1) + t * pt
        cent = jnp.where(col < nre, imgs - mean, 0.0)
        c3 = cent.reshape(ipc, k, pt)
        dots = jnp.sum(c3 * c3[:, 0:1, :], axis=2).reshape(wc)
        normsq = jnp.sum(cent * cent, axis=1)
        out_ref[0] = jnp.stack(
            [acc[:, 0], acc[:, 1] + normsq, acc[:, 2] + dots,
             acc[:, 3], acc[:, 4]], axis=1)


@partial(jax.jit, static_argnames=("gc_width", "k", "interpret"))
def fused_window_moments(whp, starts, r_lo_loc, r_hi_loc, n_real, *,
                         gc_width: int, k: int, interpret: bool = False):
    """Fused band-matmul + moments over every chunk of the plan.

    Args:
      whp: (cols_p, P) f32 histogram scratch, ``cols_p ==
        cols_padded(g, gc_width)`` (whole super-rows; spare rows are
        zero-initialized and never referenced by a window).
      starts: (C,) i32 chunk grid offsets (``ion_window_chunks``).
      r_lo_loc / r_hi_loc: (C, Wc) i32 local window rank bounds.
      n_real: traced i32 scalar (or python int) — REAL pixel count for
        the lattice-padded grid; pads past it are masked out of the
        centered reductions exactly like the masked moments kernel.
      gc_width / k: static band width and isotope-peak count.
      interpret: run the Pallas interpreter (CPU fallback / tests).

    Returns:
      partials: (C, Wc, 5) f32 — columns (sums, normsq, dots, vmax, nn)
        per window row, in the PLAN's chunk-sorted ion order.
      principal: (C, ipc, P) f32 principal (peak-0) images per ion.
    """
    cols_p, n_pix = whp.shape
    C, wc = r_lo_loc.shape
    if cols_p % SC != 0:
        raise ValueError(f"cols_p={cols_p} must be a multiple of SC={SC}")
    if wc % k != 0:
        raise ValueError(f"Wc={wc} not divisible by k={k}")
    ipc = wc // k
    pt = pick_tile(n_pix, wc, ipc, gc_width)
    if pt is None:
        if not interpret:
            raise ValueError(
                f"fused kernel unfit for n_pix={n_pix}, wc={wc}, "
                f"gc_width={gc_width} (use fused_fit before dispatch)")
        pt = n_pix  # interpreter has no lane-tiling constraint
    nsb = n_super_blocks(gc_width)
    nt = n_pix // pt

    starts = starts.astype(jnp.int32)
    # no-op while starts <= g (cols_padded guarantees room); same clamp
    # role as the unfused path's start_eff = min(start, cols - (gc + 2))
    s3 = jnp.minimum(starts // SC, np.int32(cols_p // SC - nsb))
    nr = jnp.reshape(jnp.asarray(n_real, jnp.int32), (1,))

    # the band start is data-dependent (scalar-prefetched), so the
    # histogram operand uses ELEMENT-offset (Unblocked) indexing: row
    # offset s3*SC is sublane-aligned, column offset t*pt lane-aligned
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # starts, s3, n_real
        grid=(C, 2, nt),
        in_specs=[
            pl.BlockSpec((nsb * SC, pt),
                         lambda c, ps, t, starts, s3, nr:
                         (s3[c] * SC, t * pt),
                         indexing_mode=pl.unblocked),
            pl.BlockSpec((1, wc), lambda c, ps, t, *_: (c, 0)),
            pl.BlockSpec((1, wc), lambda c, ps, t, *_: (c, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, wc, 5), lambda c, ps, t, *_: (c, 0, 0)),
            pl.BlockSpec((1, ipc, pt), lambda c, ps, t, *_: (c, 0, t)),
        ],
    )
    partials, principal = pl.pallas_call(
        partial(_fused_kernel, ipc=ipc, k=k, pt=pt),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((C, wc, 5), jnp.float32),
            jax.ShapeDtypeStruct((C, ipc, n_pix), jnp.float32),
        ],
        interpret=interpret,
    )(starts, s3, nr, whp, r_lo_loc, r_hi_loc)
    return partials, principal
