"""m/z quantization — the shared grid that makes backends bit-identical.

Both backends quantize m/z values and ppm-window bounds to int32 units of
1e-5 Da before matching.  Rationale (TPU-first design, SURVEY.md §7):

- TPU has no native f64 (emulated, slow); int32 compares are native.
- Quantizing *identically* on the host makes the numpy_ref and jax_tpu hit
  sets exactly equal — window-edge parity is by construction, not tolerance.
- 1e-5 Da = 0.01 ppm at m/z 1000; windows are ppm-scale, so the quantization
  error is far below instrument accuracy (the reference matches in f64
  [U, formula_imager_segm], a difference without scientific consequence).

int32 ceiling: 2**31 * 1e-5 = 21474 Da, far above any MS m/z range.
"""

from __future__ import annotations

import numpy as np

from ..analysis.numerics import numerics_surface

# Declared numerics contracts (ISSUE 15): the quantization grid IS the
# cross-backend bit-exactness mechanism — host f64 in, shared int32/f32
# grids out, identical for numpy_ref and jax_tpu by construction.  The
# extraction/metric parity tests are the committed proof.
NUMERICS = numerics_surface(__name__, {
    "quantize_mz":
        "contract=bit_exact; test=tests/test_jax_backend.py::"
        "test_extraction_parity",
    "quantize_window":
        "contract=bit_exact; test=tests/test_jax_backend.py::"
        "test_extraction_parity",
    "quantize_intensities":
        "contract=bit_exact; test=tests/test_jax_backend.py::"
        "test_backend_parity_metrics_and_ranks",
    "intensity_scale":
        "contract=bit_exact; test=tests/test_jax_backend.py::"
        "test_backend_parity_metrics_and_ranks",
    # resident-cube compaction (ISSUE 18): bf16 rounds the quantized
    # integer grid to 8 significant bits — still integers, still summed
    # exactly in any order, so the drift vs the f32 cube is DATA-level
    # (a coarser grid, ~2**-9 relative), not reduction-order: orders of
    # magnitude above the same-data ulp ceilings, which is why this
    # contract is wide.  What compaction must preserve is the RANKING —
    # FDR ranks bit-identical on the sentinel fixture (the test's hard
    # assertion) — with the measured component drift recorded in
    # NUMERICS_r02.json.  int8 uses per-tile power-of-two scales, so
    # dequantization itself is exact in f32.
    "compact_cube":
        "contract=ulp(4096); test=tests/test_score_pallas.py::"
        "test_quantized_cube_rank_identity",
    "expand_cube_jnp":
        "contract=bit_exact; test=tests/test_score_pallas.py::"
        "test_compact_expand_roundtrip",
})

MZ_SCALE = 1e5  # quantization steps per Da
MZ_MAX = (2**31 - 2) / MZ_SCALE
# padding sentinel for m/z cubes: larger than any real quantized m/z
MZ_PAD_Q = np.int32(2**31 - 1)


def quantize_mz(mz: np.ndarray) -> np.ndarray:
    """Host-side f64 -> int32 grid. Values beyond MZ_MAX (incl. +inf padding)
    saturate to the padding sentinel."""
    mz = np.asarray(mz, dtype=np.float64)
    q = np.rint(mz * MZ_SCALE)
    return np.where(q >= MZ_PAD_Q, MZ_PAD_Q, q).astype(np.int32)


def quantize_window(mzs: np.ndarray, ppm: float) -> tuple[np.ndarray, np.ndarray]:
    """ppm windows [mz*(1-ppm*1e-6), mz*(1+ppm*1e-6)) on the quantized grid.
    Computed in f64 on host, identically in both backends."""
    mzs = np.asarray(mzs, dtype=np.float64)
    lo = quantize_mz(mzs * (1.0 - ppm * 1e-6))
    hi = quantize_mz(mzs * (1.0 + ppm * 1e-6))
    return lo, hi


# -- intensity quantization: order-free exact accumulation --------------------
#
# Ion-image pixel values are sums of peak intensities.  Summation order on a
# TPU (scatter-add trees, MXU accumulation) is implementation-defined, so f32
# sums of arbitrary floats are NOT reproducible across backends or shard
# counts.  The fix is structural: snap intensities to an integer grid scaled
# so that every per-(pixel, window) sum stays below 2**24 — every partial sum
# is then an exactly-representable f32 integer and ANY summation order yields
# the same bits.  The scale is a power of two, so de-quantization (a
# division by 2**k) is also exact in f32 and all MSM metrics — which are
# scale-invariant (chaos thresholds relative to vmax; correlation and
# pattern match are cosines) — see identical values either way.

INT_SUM_BITS = 24  # f32 exact-integer range


def intensity_scale(
    mzs_flat: np.ndarray,      # (P,) f64, m/z per peak, sorted within pixel
    ints_flat: np.ndarray,     # (P,) intensities
    pixel_of_peak: np.ndarray,  # (P,) pixel index per peak (non-decreasing)
    ppm: float,
) -> float:
    """Power-of-two scale 2**k such that hmax * max(rint(i*2**k)) < 2**24,
    where hmax bounds the peak count inside any ppm window of any pixel."""
    if ints_flat.size == 0:
        return 1.0
    max_raw = float(np.max(ints_flat))
    if max_raw <= 0:
        return 1.0
    # exact per-pixel sliding-window occupancy on the quantized m/z grid:
    # key = pixel * 2**32 + mz_q is globally ascending; a window never spans
    # the 2**32 inter-pixel gap
    mz_q = quantize_mz(mzs_flat).astype(np.int64)
    key = pixel_of_peak.astype(np.int64) * (1 << 32) + mz_q
    # generous window bound (2.5x ppm covers any window whose left edge is
    # at this peak, including the center-to-edge asymmetry)
    width = np.ceil(np.asarray(mzs_flat, np.float64)
                    * (2.5 * ppm * 1e-6) * MZ_SCALE).astype(np.int64)
    hi = np.searchsorted(key, key + width, side="right")
    hmax = int(np.max(hi - np.arange(key.size)))
    target = (2**INT_SUM_BITS - 1) / (max(hmax, 1) + 1) / max_raw
    return float(2.0 ** np.floor(np.log2(target)))


def quantize_intensities(ints_flat: np.ndarray, scale: float) -> np.ndarray:
    """Snap to the integer grid; values stay integer-valued float32."""
    return np.rint(np.asarray(ints_flat, np.float64) * scale).astype(np.float32)


# -- resident-cube compaction (ISSUE 18) --------------------------------------
#
# The flat sorted-peaks cube is HBM-resident for the whole run (1.85 GB f32
# intensities at DESI scale).  Halving (bf16) or quartering (int8) it buys
# both capacity and scatter read bandwidth; the expanded f32 view exists
# only as a per-batch transient inside the scoring jit (XLA fuses the cast
# into the histogram scatter's operand read).
#
# bf16: a straight cast.  The intensities are already integer-valued f32
# (quantize_intensities); bf16 keeps 8 significant bits and rounds to
# NEAREST-EVEN, so every stored value is STILL an integer (e.g. 300 ->
# 75 * 2**2) and every per-(pixel, window) sum stays below 2**24 — the
# order-free exact-accumulation property survives, cross-backend identity
# survives, and the drift vs the f32 cube is a data-level regrid bounded
# by hmax * max_int * 2**-9 per pixel sum.
#
# int8: per-tile symmetric quantization with POWER-OF-TWO scales, tile =
# QTILE consecutive peaks of the m/z-sorted cube (peak arrays are padded
# to multiples of QTILE by the shape-bucket lattice: ops/buckets.PEAK_FLOOR
# and every pow2ish point are multiples of 1024).  Power-of-two scales make
# the dequantization multiply EXACT in f32 (code * 2**k), so the only loss
# is the rint to 8 bits — again integer-preserving at every scale step.

CUBE_DTYPES = ("f32", "bf16", "int8")
QTILE = 1024  # peaks per int8 scale tile


def compact_cube(in_s: np.ndarray, cube_dtype: str):
    """Host-side compaction of the (N,) f32 intensity cube.

    Returns ``(codes, scales)``: ``codes`` is the compact resident array
    (bf16 or int8), ``scales`` the (N // QTILE,) f32 per-tile power-of-two
    dequantization factors (None for bf16 — the cast needs none)."""
    if cube_dtype not in CUBE_DTYPES:
        raise ValueError(f"cube_dtype must be one of {CUBE_DTYPES}, "
                         f"got {cube_dtype!r}")
    in_s = np.ascontiguousarray(in_s, dtype=np.float32)
    if cube_dtype == "f32":
        return in_s, None
    if cube_dtype == "bf16":
        import ml_dtypes  # jax dependency; baked into the image
        return in_s.astype(ml_dtypes.bfloat16), None
    if in_s.size % QTILE != 0:
        raise ValueError(
            f"int8 cube needs a QTILE={QTILE}-aligned peak count "
            f"(lattice-padded), got {in_s.size}")
    tiles = in_s.reshape(-1, QTILE)
    m = np.max(np.abs(tiles), axis=1)
    # smallest 2**e with m / 2**e <= 127 (m == 0 -> scale 1)
    e = np.ceil(np.log2(np.maximum(m, 1e-30) / 127.0))
    scales = np.exp2(np.maximum(e, np.float64(-126.0))).astype(np.float32)
    codes = np.rint(tiles / scales[:, None]).astype(np.int8)
    return codes.reshape(-1), scales


def expand_cube(codes: np.ndarray, scales) -> np.ndarray:
    """Host-side inverse of :func:`compact_cube` (tests / oracle path)."""
    if codes.dtype == np.float32:
        return codes
    if scales is None:
        return np.asarray(codes, dtype=np.float32)
    return (codes.reshape(-1, QTILE).astype(np.float32)
            * scales[:, None]).reshape(-1)


def expand_cube_jnp(codes, scales):
    """In-graph f32 view of the compact resident cube — the first op of
    every scoring jit when ``parallel.cube_dtype != "f32"``.  Exact: the
    bf16->f32 cast is value-preserving, and the int8 path multiplies an
    integer <= 127 by a power of two."""
    import jax.numpy as jnp  # deferred: quantize.py is host-importable
    if codes.dtype == jnp.float32:
        return codes
    if scales is None:
        return codes.astype(jnp.float32)
    return (codes.astype(jnp.float32).reshape(-1, QTILE)
            * scales[:, None]).reshape(-1)
