"""m/z quantization — the shared grid that makes backends bit-identical.

Both backends quantize m/z values and ppm-window bounds to int32 units of
1e-5 Da before matching.  Rationale (TPU-first design, SURVEY.md §7):

- TPU has no native f64 (emulated, slow); int32 compares are native.
- Quantizing *identically* on the host makes the numpy_ref and jax_tpu hit
  sets exactly equal — window-edge parity is by construction, not tolerance.
- 1e-5 Da = 0.01 ppm at m/z 1000; windows are ppm-scale, so the quantization
  error is far below instrument accuracy (the reference matches in f64
  [U, formula_imager_segm], a difference without scientific consequence).

int32 ceiling: 2**31 * 1e-5 = 21474 Da, far above any MS m/z range.
"""

from __future__ import annotations

import numpy as np

MZ_SCALE = 1e5  # quantization steps per Da
MZ_MAX = (2**31 - 2) / MZ_SCALE
# padding sentinel for m/z cubes: larger than any real quantized m/z
MZ_PAD_Q = np.int32(2**31 - 1)


def quantize_mz(mz: np.ndarray) -> np.ndarray:
    """Host-side f64 -> int32 grid. Values beyond MZ_MAX (incl. +inf padding)
    saturate to the padding sentinel."""
    mz = np.asarray(mz, dtype=np.float64)
    q = np.rint(mz * MZ_SCALE)
    return np.where(q >= MZ_PAD_Q, MZ_PAD_Q, q).astype(np.int32)


def quantize_window(mzs: np.ndarray, ppm: float) -> tuple[np.ndarray, np.ndarray]:
    """ppm windows [mz*(1-ppm*1e-6), mz*(1+ppm*1e-6)) on the quantized grid.
    Computed in f64 on host, identically in both backends."""
    mzs = np.asarray(mzs, dtype=np.float64)
    lo = quantize_mz(mzs * (1.0 - ppm * 1e-6))
    hi = quantize_mz(mzs * (1.0 + ppm * 1e-6))
    return lo, hi
