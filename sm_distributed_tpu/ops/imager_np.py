"""Ion-image extraction, NumPy reference backend.

Reference: ``sm/engine/msm_basic/formula_imager_segm.py::compute_sf_images``
[U] (SURVEY.md #9, call stack §3.3) — THE hot kernel.  The reference sorts
each m/z segment's (pixel, mz, int) triples by m/z and, per theoretical peak,
takes the contiguous [searchsorted(lo), searchsorted(hi)) slice, then
shuffles hits into per-ion sparse images.  This backend keeps that exact
semantics with no Spark: one global m/z sort, two vectorized searchsorteds
for ALL windows at once, and a bincount scatter-add per window.

The ppm window matches the reference: [mz*(1-ppm*1e-6), mz*(1+ppm*1e-6)],
lower bound inclusive, upper bound exclusive ('left'/'left' sides).
"""

from __future__ import annotations

import numpy as np

from ..io.dataset import SpectralDataset
from .isocalc import IsotopePatternTable


def peak_bounds(mzs: np.ndarray, ppm: float) -> tuple[np.ndarray, np.ndarray]:
    """Lower/upper m/z window bounds (reference: Formulas.get_sf_peak_bounds [U]).
    Zero-padded (invalid) peaks produce empty windows."""
    lo = mzs * (1.0 - ppm * 1e-6)
    hi = mzs * (1.0 + ppm * 1e-6)
    return lo, hi


def extract_ion_images(
    ds: SpectralDataset,
    table: IsotopePatternTable,
    ppm: float,
) -> np.ndarray:
    """Dense ion images: (n_ions, max_peaks, n_pixels) float32.

    Padded (invalid) isotope peaks yield all-zero images, like the reference's
    missing sparse matrices.
    """
    # global m/z sort of all dataset peaks (the CSR layout is per-pixel sorted;
    # re-sorting globally once is the reference's per-segment sort, unsegmented)
    order = np.argsort(ds.mzs_flat, kind="stable")
    g_mzs = ds.mzs_flat[order]
    g_ints = ds.ints_flat[order]
    # recover each peak's dense pixel index from the CSR row pointers
    pixel_of_peak = np.repeat(
        np.arange(ds.n_pixels, dtype=np.int64), ds.row_lengths()
    )[order]

    lo, hi = peak_bounds(table.mzs, ppm)
    start = np.searchsorted(g_mzs, lo.ravel(), side="left").reshape(lo.shape)
    end = np.searchsorted(g_mzs, hi.ravel(), side="left").reshape(hi.shape)

    n_ions, max_peaks = table.mzs.shape
    images = np.zeros((n_ions, max_peaks, ds.n_pixels), dtype=np.float32)
    valid = np.arange(max_peaks)[None, :] < table.n_valid[:, None]
    for i in range(n_ions):
        for k in range(max_peaks):
            if not valid[i, k]:
                continue
            s, e = start[i, k], end[i, k]
            if e > s:
                images[i, k] = np.bincount(
                    pixel_of_peak[s:e], weights=g_ints[s:e], minlength=ds.n_pixels
                ).astype(np.float32)
    return images
