"""Ion-image extraction, NumPy reference backend.

Reference: ``sm/engine/msm_basic/formula_imager_segm.py::compute_sf_images``
[U] (SURVEY.md #9, call stack §3.3) — THE hot kernel.  The reference sorts
each m/z segment's (pixel, mz, int) triples by m/z and, per theoretical peak,
takes the contiguous [searchsorted(lo), searchsorted(hi)) slice, then
shuffles hits into per-ion sparse images.  This backend keeps that exact
semantics with no Spark: one global m/z sort, two vectorized searchsorteds
for ALL windows at once, and a bincount scatter-add per window.

The ppm window matches the reference: [mz*(1-ppm*1e-6), mz*(1+ppm*1e-6)],
lower bound inclusive, upper bound exclusive ('left'/'left' sides).
"""

from __future__ import annotations

import numpy as np

from dataclasses import dataclass

from ..io.dataset import SpectralDataset
from .isocalc import IsotopePatternTable
from .quantize import quantize_mz, quantize_window


@dataclass
class SortedPeakView:
    """Once-per-dataset prep: all dataset peaks globally m/z-sorted on the
    quantized grid (the reference's per-segment sort, unsegmented).  Built
    once and reused across formula batches."""

    n_pixels: int
    g_mzs_q: np.ndarray        # (P,) int32, ascending
    g_ints: np.ndarray         # (P,) f32 — integer-valued when ppm given
    pixel_of_peak: np.ndarray  # (P,) i64 — dense pixel index per sorted peak
    int_scale: float = 1.0     # power-of-two intensity-grid scale

    @classmethod
    def prepare(cls, ds: SpectralDataset, ppm: float | None = None) -> "SortedPeakView":
        """With ``ppm`` given, intensities come from the shared integer grid
        (ds.intensity_quantization) — bit-identical images vs the jax backend
        under any summation order.  Without it, raw intensities (legacy)."""
        if ppm is not None:
            ints, scale = ds.intensity_quantization(ppm)
        else:
            ints, scale = ds.ints_flat, 1.0
        g_mzs_q_unsorted = quantize_mz(ds.mzs_flat)
        order = np.argsort(g_mzs_q_unsorted, kind="stable")
        pixel_of_peak = np.repeat(
            np.arange(ds.n_pixels, dtype=np.int64), ds.row_lengths()
        )[order]
        return cls(
            n_pixels=ds.n_pixels,
            g_mzs_q=g_mzs_q_unsorted[order],
            g_ints=ints[order],
            pixel_of_peak=pixel_of_peak,
            int_scale=scale,
        )


def extract_ion_images(
    source: SpectralDataset | SortedPeakView,
    table: IsotopePatternTable,
    ppm: float,
) -> np.ndarray:
    """Dense ion images: (n_ions, max_peaks, n_pixels) float32.

    Matching happens on the shared quantized m/z grid (ops/quantize.py) so the
    hit set is exactly the jax_tpu backend's, and intensities on the shared
    integer grid so pixel SUMS are bit-identical too (order-free; see
    ops/quantize.py).  Output images are de-quantized back to raw units (an
    exact power-of-two division).  Padded (invalid) isotope peaks yield
    all-zero images, like the reference's missing sparse matrices.  Pass a
    prebuilt SortedPeakView when scoring many batches.
    """
    view = (source if isinstance(source, SortedPeakView)
            else SortedPeakView.prepare(source, ppm))

    lo, hi = quantize_window(table.mzs, ppm)
    start = np.searchsorted(view.g_mzs_q, lo.ravel(), side="left").reshape(lo.shape)
    end = np.searchsorted(view.g_mzs_q, hi.ravel(), side="left").reshape(hi.shape)

    n_ions, max_peaks = table.mzs.shape
    images = np.zeros((n_ions, max_peaks, view.n_pixels), dtype=np.float32)
    valid = np.arange(max_peaks)[None, :] < table.n_valid[:, None]
    for i in range(n_ions):
        for k in range(max_peaks):
            if not valid[i, k]:
                continue
            s, e = start[i, k], end[i, k]
            if e > s:
                images[i, k] = np.bincount(
                    view.pixel_of_peak[s:e], weights=view.g_ints[s:e],
                    minlength=view.n_pixels,
                ).astype(np.float32)
    if view.int_scale != 1.0:
        images /= np.float32(view.int_scale)   # exact: scale is a power of two
    return images
