"""Ion-image extraction, JAX/TPU backend.

TPU-first reformulation of the reference hot loop (SURVEY.md §3.3,
``formula_imager_segm.compute_sf_images`` [U]).  Instead of a cluster-wide
shuffle of (ion, pixel, intensity) hits, the spectral cube lives on device as
a padded (pixels x peaks) matrix and an ion image is computed with *static
shapes* through a per-batch WINDOW-BOUND HISTOGRAM:

1. Host: sort the 2·W quantized window bounds of the batch into one grid;
   record each window's (lo, hi) leftmost rank in the grid (exact, integer).
2. Device: bucket every cube peak into the grid — ONE shared-table
   ``searchsorted`` over the whole cube (sort-method: a per-row merge sort,
   no serialized binary-search gathers).
3. Device: weighted scatter-add histogram (pixels x grid-bins) of peak
   intensities.
4. ``img = wh @ D`` where ``D[g, w] = rank_lo(w) < g <= rank_hi(w)`` — ONE
   f32 matmul on the MXU sums each window's bins; no per-(pixel, window)
   gather at all.  Crucially this is exact-zero-preserving: an empty window
   multiplies only zero histogram bins, so the result is exactly 0.0 (a
   cumsum-then-subtract formulation is NOT — XLA's parallel-prefix cumsum
   uses different summation trees per position, leaving ~1e-4 residues that
   fabricate hit pixels).

Design note (measured on TPU v5e, 4096 px x 384 peaks x 2048 windows): the
naive two-vmapped-binary-searches + prefix-gather design costs ~1.8 s/batch —
XLA lowers per-lane binary-search gathers to near-scalar code.  This
histogram path runs the same batch in ~0.1-0.2 s and produces bit-identical
hit sets (the grid is exact integer quantized bounds).  The pixel axis stays
the sharding axis; each shard histograms its pixel slice independently
(collectives only in metrics).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..io.dataset import SpectralDataset
from .quantize import MZ_PAD_Q, quantize_mz

# windows per band chunk in the flat-banded extraction (each chunk's
# membership matmul covers ~2*BAND_WINDOWS grid columns)
BAND_WINDOWS = 512


def prepare_cube_arrays(
    ds: SpectralDataset,
    pad_to_multiple: int = 128,
    pixels_multiple: int = 1,
    ppm: float | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Host-side: (mz_q_cube int32 (P, L), int_cube float32 (P, L)).

    m/z rows are quantized (padding saturates to the MZ_PAD_Q sentinel, above
    every real window bound, so padded peaks land past every rank).  With
    ``ppm`` given, intensities come from the shared integer grid
    (ds.intensity_quantization): every per-(pixel, window) sum stays below
    2**24, so scatter-add and matmul accumulation are EXACT in f32 in any
    order — image bits equal the numpy oracle's."""
    mz_cube, int_cube, _lens = ds.padded_cube(pad_to_multiple, pixels_multiple)
    if ppm is not None:
        ints_q, _scale = ds.intensity_quantization(ppm)
        lens = ds.row_lengths()
        pixel_of_peak = np.repeat(np.arange(ds.n_pixels), lens)
        col_of_peak = np.arange(ints_q.size) - np.repeat(ds.row_ptr[:-1], lens)
        int_cube[pixel_of_peak, col_of_peak] = ints_q
    return quantize_mz(mz_cube), int_cube


def window_rank_grid(
    lo_q: np.ndarray, hi_q: np.ndarray
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host-side: (grid (2W,) int32 sorted, r_lo (W,), r_hi (W,) int32).

    ``grid`` is the sorted multiset of all window bounds; ``r_*`` are each
    bound's LEFTMOST rank in the grid.  Exactness: a peak lies in window w
    iff lo_q[w] <= mz_q < hi_q[w], and #\\{mz_q < b\\} == #peaks whose grid
    bin is <= leftmost_rank(b) (strictly-below counting survives duplicate
    bounds because equal bounds share the leftmost rank)."""
    # smlint: host-sync-ok[host window-bound prep; inputs are host numpy, not device values]
    lo_flat = np.ascontiguousarray(lo_q, dtype=np.int32).ravel()
    # smlint: host-sync-ok[host window-bound prep; inputs are host numpy, not device values]
    hi_flat = np.ascontiguousarray(hi_q, dtype=np.int32).ravel()
    # NOTE: the grid keeps duplicate bounds (fixed 2W size) on purpose — a
    # deduplicated grid has a data-dependent length, and every new length is
    # a new executable (measured: tens of seconds of XLA recompiles dwarfing
    # the ~nothing saved; real batches are >99.9% unique bounds anyway).
    grid = np.sort(np.concatenate([lo_flat, hi_flat]))
    r_lo = np.searchsorted(grid, lo_flat, side="left").astype(np.int32)
    r_hi = np.searchsorted(grid, hi_flat, side="left").astype(np.int32)
    return grid, r_lo, r_hi


def extract_images(
    mz_q_cube: jnp.ndarray,   # (P, L) int32, MZ_PAD_Q padding
    int_cube: jnp.ndarray,    # (P, L) f32, 0 at padding
    grid: jnp.ndarray,        # (G,) int32 sorted window bounds
    r_lo: jnp.ndarray,        # (W,) int32 leftmost rank of each lo bound
    r_hi: jnp.ndarray,        # (W,) int32 leftmost rank of each hi bound
) -> jnp.ndarray:
    """(W, P) f32 ion-window images on the current device/shard."""
    p, _l = mz_q_cube.shape
    g = grid.shape[0]
    # bin[p,j] = #{grid bounds <= mz[p,j]} — shared small table, merge-sort path
    bins = jnp.searchsorted(
        grid, mz_q_cube.ravel(), side="right", method="sort"
    ).reshape(p, -1)
    rows = jnp.arange(p, dtype=jnp.int32)[:, None]
    wh = jnp.zeros((p, g + 1), jnp.float32).at[rows, bins].add(int_cube)
    # window-membership matrix: bin gg contributes to window w iff
    # r_lo[w] < gg <= r_hi[w]  (== "mz < hi" minus "mz < lo" counting)
    gg = jnp.arange(g + 1, dtype=jnp.int32)[:, None]          # (G+1, 1)
    d = ((gg > r_lo[None, :]) & (gg <= r_hi[None, :])).astype(jnp.float32)
    img_pw = jnp.dot(wh, d, precision=jax.lax.Precision.HIGHEST)  # (P, W)
    return img_pw.T


# -- flat globally-sorted layout (single-device fast path) --------------------
#
# The padded cube pays for its padding: on the 64x64 bench workload the cube
# is (4096, 896) = 3.7M slots for 1.17M real peaks, and the per-batch
# ``searchsorted(..., method="sort")`` sorts ALL slots (47.8 ms measured on
# v5e) while the scatter-add histograms them (38.6 ms) — together ~80% of the
# fused graph.  Both shrink dramatically with a dataset-static GLOBALLY
# m/z-sorted flat peak list:
#
# 1. Host, once per dataset: sort all peaks by quantized m/z ->
#    (mz_sorted, pixel_sorted, int_sorted).
# 2. Device, per batch: ``pos = searchsorted(mz_sorted, grid)`` — G=8K binary
#    searches instead of a 3.7M-element sort — then every peak's grid bin
#    falls out of ONE cumsum: bins[n] = #{g: grid[g] <= mz[n]} = inclusive
#    cumsum of a delta array with +1 at each pos[g].  (Each bound's rank
#    among the sorted peaks IS the count of peaks below it.)
# 3. The histogram scatter-add touches only real peaks (1.17M, not 3.7M).
# 4. The membership matmul is unchanged.
#
# Exactness: bins equal the cube path's ``searchsorted(grid, mz, 'right')``
# by construction, the histogram sums the same (pixel, bin, intensity)
# multiset of exact integers, and the matmul is identical — images are
# bit-identical to the cube path (asserted in tests).  Measured: extraction
# 94 ms -> ~20 ms per 1024-ion batch.


def prepare_flat_sorted_arrays(
    ds: SpectralDataset,
    ppm: float,
    pad_to_multiple: int = 1024,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host-side: globally m/z-sorted flat peak arrays
    (mz_q (N,) int32 ascending, pixel (N,) int32, int (N,) f32 integer grid).

    Padding: m/z saturates to the MZ_PAD_Q sentinel, pixel points at an
    overflow row (``ds.n_pixels``, sliced off before the matmul), intensity 0.
    The single-device layout IS the 1-shard case of the sharded builder.
    """
    mz_s, px_s, in_s, _p_loc = prepare_flat_sharded_arrays(
        ds, ppm, n_shards=1, pad_to_multiple=pad_to_multiple)
    return mz_s[0], px_s[0], in_s[0]


def flat_bound_ranks(mz_sorted_host: np.ndarray, grid: np.ndarray) -> np.ndarray:
    """Host-side per-batch: rank of each grid bound among the sorted peaks,
    ``pos[g] = #{peaks with mz < grid[g]}``.  G binary searches into the
    host copy of the dataset-static sorted m/z array — sub-millisecond,
    replacing a ~10 ms device searchsorted; ships as (G,) int32 (32 KB).
    (Shipping the full per-peak bins array instead was tried: host cumsum is
    free but the N-sized uint16 transfer (~5 MB/batch) is slower through a
    tunneled TPU than the device cumsum it saves.)"""
    return np.searchsorted(mz_sorted_host, grid, side="left").astype(np.int32)


def extract_images_flat(
    pixel_sorted: jnp.ndarray,  # (N,) int32, n_pixels = overflow row
    int_sorted: jnp.ndarray,    # (N,) f32, 0 at padding
    pos: jnp.ndarray,           # (G,) int32 host-computed bound ranks
    r_lo: jnp.ndarray,          # (W,) int32 leftmost rank of each lo bound
    r_hi: jnp.ndarray,          # (W,) int32 leftmost rank of each hi bound
    *,
    n_pixels: int,
) -> jnp.ndarray:
    """(W, n_pixels) f32 ion-window images; bit-identical to extract_images.

    ``bins[j] = #{g: grid[g] <= mz[j]}`` == #bounds whose rank is <= j:
    +1 at every pos, one inclusive cumsum."""
    n = pixel_sorted.shape[0]
    g = pos.shape[0]
    delta = jnp.zeros(n + 1, jnp.int32).at[pos].add(1)
    bins = jnp.cumsum(delta[:-1])
    wh = jnp.zeros((n_pixels + 1, g + 1), jnp.float32).at[
        pixel_sorted, bins].add(int_sorted)
    gg = jnp.arange(g + 1, dtype=jnp.int32)[:, None]
    d = ((gg > r_lo[None, :]) & (gg <= r_hi[None, :])).astype(jnp.float32)
    img_pw = jnp.dot(wh[:n_pixels], d, precision=jax.lax.Precision.HIGHEST)
    return img_pw.T


def extract_images_flat_banded(
    pixel_sorted: jnp.ndarray,  # (N,) int32, n_pixels = overflow row
    int_sorted: jnp.ndarray,    # (N,) f32, 0 at padding
    pos: jnp.ndarray,           # (G,) int32 host-computed bound ranks
    starts: jnp.ndarray,        # (C,) int32 chunk grid offsets (window_chunks)
    r_lo_loc: jnp.ndarray,      # (C, Wc) int32 local lo ranks
    r_hi_loc: jnp.ndarray,      # (C, Wc) int32 local hi ranks
    inv: jnp.ndarray,           # (W,) int32 sorted-row -> input-order map
    *,
    gc_width: int,
    n_pixels: int,
) -> jnp.ndarray:
    """(W, n_pixels) flat extraction with a BANDED membership matmul.

    The dense membership matrix costs 2*P*(G+1)*W flops — quadratic in the
    batch size (G and W both scale with B*K), which is what forbids large
    batches even though the histogram scatter amortizes with B.  But each
    window's bins live in the narrow band (r_lo, r_hi] of the grid, so with
    windows m/z-sorted and chunked (the ``window_chunks`` plan), chunk c's
    512 windows only need grid columns [start_c, start_c + gc_width + 2):
    flops drop to 2*P*gc*W — LINEAR in the batch.  The histogram is built
    ONCE at full width (its cost is per-peak, not per-window), then each
    chunk dynamic-slices its band and runs a small MXU matmul.  Images are
    bit-identical: out-of-band bins have zero membership in the dense form.
    """
    n = pixel_sorted.shape[0]
    g = pos.shape[0]
    delta = jnp.zeros(n + 1, jnp.int32).at[pos].add(1)
    bins = jnp.cumsum(delta[:-1])
    # Scratch width: all bins live in [0, g], so max(g+1, gc+2) columns
    # suffice — chunk slices near the top CLAMP their start and shift the
    # local window ranks by the same delta (start + span <= g+1 <= cols
    # guarantees shifted ranks stay inside the gc+2-wide band, see below).
    # The scatter's FIXED cost is the operand zero-init/copy at ~38 GB/s
    # (measured: ~12 ns/update marginal + ~28 ns/column/1k-rows fixed on
    # v5e), so the old g+1+gc+2 layout paid ~2x the necessary fixed cost
    # on every 256-ion DESI batch (G ~= gc there).  Bit-exact: each
    # window still sums exactly its own bins' integers (any order — the
    # quantized grid keeps every sum < 2**24).
    cols = max(g + 1, gc_width + 2)
    # TRANSPOSED scratch (bins-major): measured on v5e at DESI shapes,
    # the (cols, P) layout scatters ~6% faster than (P, cols), its chunk
    # slice is a row-range, and the membership matmul d.T @ band emits
    # images already (W, P) — no per-chunk output transpose (together
    # ~15 ms per 256-ion DESI batch)
    wh = jnp.zeros((cols, n_pixels + 1), jnp.float32).at[
        bins, pixel_sorted].add(int_sorted)
    whp = wh[:, :n_pixels]
    gg = jnp.arange(gc_width + 2, dtype=jnp.int32)[:, None]

    def chunk(_, data):
        start, rlo, rhi = data
        # clamp keeps the static-width slice inside the scratch; the
        # chunk's windows span global cols [start, start+span] with
        # start+span <= g+1 <= cols, so shift + span <= gc+2 always
        start_eff = jnp.minimum(start, np.int32(cols - (gc_width + 2)))
        shift = start - start_eff
        band = jax.lax.dynamic_slice(
            whp, (start_eff, jnp.int32(0)), (gc_width + 2, n_pixels))
        d = ((gg > (rlo + shift)[None, :])
             & (gg <= (rhi + shift)[None, :])).astype(jnp.float32)
        return None, jnp.dot(
            d.T, band, precision=jax.lax.Precision.HIGHEST)

    _, imgs = jax.lax.scan(chunk, None, (starts, r_lo_loc, r_hi_loc))
    imgs = imgs.reshape(-1, n_pixels)                  # (C*Wc, P) sorted order
    if inv is None:
        # ion-major plans (ion_window_chunks): rows are already grouped
        # by ion — the caller un-permutes the tiny metric rows instead of
        # gathering the multi-GB image block
        return imgs
    return jnp.take(imgs, inv, axis=0)                 # (W, P) input order


def prepare_flat_sharded_arrays(
    ds: SpectralDataset,
    ppm: float,
    n_shards: int,
    pad_to_multiple: int = 1024,
    p_loc: int | None = None,
    slot_bucket=None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Host-side flat layout per PIXEL SHARD: (mz_q (S, Nmax) int32 ascending
    per row, px_local (S, Nmax) int32, ints (S, Nmax) f32, p_loc).

    Each shard owns a contiguous slice of ``p_loc = ceil(P/S)`` pixels and
    its peaks sorted by quantized m/z; rows pad to the max shard peak count
    (m/z -> MZ_PAD_Q sentinel, pixel -> the shard-local overflow row
    ``p_loc``, intensity 0).  Unlike the padded cube — whose row length is
    the MAX spectrum length, catastrophic for ragged DESI data — per-shard
    bytes track the actual peak count.  The m/z rows stay host-side (bound
    ranks are host-computed); only pixel + intensity rows go to HBM.

    ``p_loc`` (ISSUE 13 lattice): an explicit per-shard pixel capacity
    >= ceil(P/S) — the sharded backend passes a row-bucketed whole-row
    capacity so every dataset size in the bucket shares the executable
    (trailing shards may then be partially or wholly padding, exactly the
    padded-slot shape the slice above already uses).  ``slot_bucket``
    replaces the ``pad_to_multiple`` rounding of the peak-slot capacity
    with the shared lattice (``ops/buckets.peak_bucket``)."""
    if p_loc is None:
        p_pad = -(-ds.n_pixels // n_shards) * n_shards
        p_loc = p_pad // n_shards
    elif p_loc * n_shards < ds.n_pixels:
        raise ValueError(
            f"p_loc={p_loc} x {n_shards} shards cannot hold "
            f"{ds.n_pixels} pixels")
    mz_q = quantize_mz(ds.mzs_flat)
    ints_q, _scale = ds.intensity_quantization(ppm)
    lens = ds.row_lengths()
    pixel = np.repeat(np.arange(ds.n_pixels, dtype=np.int64), lens)
    shard = (pixel // p_loc).astype(np.int32)
    counts = np.bincount(shard, minlength=n_shards)
    if slot_bucket is not None:
        n_max = int(slot_bucket(max(int(counts.max()), 1)))
    else:
        n_max = -(-max(int(counts.max()), 1)
                  // pad_to_multiple) * pad_to_multiple
    mz_s = np.full((n_shards, n_max), MZ_PAD_Q, dtype=np.int32)
    px_s = np.full((n_shards, n_max), p_loc, dtype=np.int32)
    in_s = np.zeros((n_shards, n_max), dtype=np.float32)
    for s in range(n_shards):
        m = shard == s
        order = np.argsort(mz_q[m], kind="stable")
        c = int(counts[s])
        mz_s[s, :c] = mz_q[m][order]
        px_s[s, :c] = (pixel[m] - s * p_loc).astype(np.int32)[order]
        in_s[s, :c] = ints_q[m][order]
    return mz_s, px_s, in_s, p_loc


def gc_ladder(span: int) -> int:
    """Static chunk band width for a window span: smallest {1, 1.5} x
    pow-2 point >= span (shared by window_chunks and ion_window_chunks so
    the driver entry and the backend can never disagree on the plan)."""
    cap = 2
    while cap < span:
        cap <<= 1
    mid = (cap >> 2) * 3
    return mid if span <= mid and mid >= 2 else cap


def ions_per_chunk_for(b: int, k: int, window_budget: int) -> int:
    """Largest divisor of the static batch ``b`` whose k-window block
    stays within ``window_budget`` windows per chunk (the shared rule for
    ion-major chunk plans)."""
    ipc = max(1, min(window_budget // max(k, 1), b))
    while b % ipc:
        ipc -= 1
    return ipc


def band_bucket(width: int, floor: int = 1 << 21) -> int:
    """Static band-slice capacity for a band of ``width`` peaks: the
    smallest {1, 1.125..1.875 step 1/8} x pow-2 ladder point >= width
    (with a floor).  Each bucket is one (cached) executable; eighth
    points bound padded scatter waste at 12.5% (~6% expected — the r4
    {1, 1.5} ladder's 50% bound measured ~440M scatter slots/rep at DESI
    scale against ~318M actual band peaks; at ~12 ns per padded slot the
    finer ladder buys ~1 s/rep for ~10 one-time cached compiles; a /16
    ladder would only halve the residual ~6% while doubling the compile
    count)."""
    cap = floor
    while cap < width:
        cap <<= 1
    if cap > floor:
        for eighths in range(9, 16):
            mid = (cap >> 4) * eighths
            if width <= mid:
                return mid
    return cap


def batch_peak_band(mz_host: np.ndarray, lo_q: np.ndarray,
                    hi_q: np.ndarray) -> tuple[int, int]:
    """Host-side: the CONTIGUOUS rank band [start, start+width) of the
    sorted resident peaks spanned by a batch's window union.  For an
    m/z-ordered ion table every batch's union is m/z-localized, so the band
    is narrow; extraction can then scatter a dynamic slice of the resident
    arrays directly (no per-run gather) — see
    models/msm_jax.py::fused_score_fn_flat_banded_sliced."""
    flat = merged_window_bounds(lo_q, hi_q)
    if flat.size == 0:
        return 0, 0
    cuts = np.searchsorted(
        # smlint: host-sync-ok[host band-bound pair; mz_host is the host copy of the sorted peaks]
        mz_host, np.array([flat[0], flat[-1]], dtype=mz_host.dtype),
        side="left")
    return int(cuts[0]), int(cuts[1] - cuts[0])


def merged_window_bounds(lo_q: np.ndarray, hi_q: np.ndarray) -> np.ndarray:
    """Host-side: the union of half-open quantized windows [lo, hi) as a
    flat sorted boundary array [lo1, hi1, lo2, hi2, ...] of DISJOINT
    intervals.  Membership test: searchsorted(flat, mz, 'right') is odd."""
    # smlint: host-sync-ok[host window-bound prep; inputs are host numpy, not device values]
    lo = np.asarray(lo_q, dtype=np.int64).ravel()
    # smlint: host-sync-ok[host window-bound prep; inputs are host numpy, not device values]
    hi = np.asarray(hi_q, dtype=np.int64).ravel()
    real = lo < hi                       # drop empty windows (batch padding)
    lo, hi = lo[real], hi[real]
    if lo.size == 0:
        return np.zeros(0, dtype=np.int32)
    order = np.argsort(lo, kind="stable")
    lo, hi = lo[order], hi[order]
    run_hi = np.maximum.accumulate(hi)
    # a new disjoint interval starts where lo exceeds every prior hi
    # (touching intervals merge too, keeping the parity test valid)
    new = np.concatenate([[True], lo[1:] > run_hi[:-1]])
    starts = lo[new]
    ends = run_hi[np.concatenate([new[1:], [True]])]
    return np.stack([starts, ends], axis=1).ravel().astype(np.int32)


def window_union_member(mz_q: np.ndarray, flat_bounds: np.ndarray) -> np.ndarray:
    """Boolean mask: which quantized m/z values fall inside ANY window of
    the union (the reference's searchsorted hot loop only emits hits
    [U, formula_imager_segm]; this is the dataset-side equivalent —
    peaks outside every window of a SEARCH can never contribute and are
    dropped from the device arrays up front)."""
    if flat_bounds.size == 0:
        return np.zeros(mz_q.shape, dtype=bool)
    return (np.searchsorted(flat_bounds, mz_q, side="right") % 2) == 1


def restrict_flat_to_windows(
    mz_s: np.ndarray,    # (S, N) int32 per-shard sorted, MZ_PAD_Q padding
    px_s: np.ndarray,    # (S, N) int32
    in_s: np.ndarray,    # (S, N) f32
    lo_q: np.ndarray,    # window lo bounds (any shape; empty lo==hi dropped)
    hi_q: np.ndarray,
    overflow_row: int,
    pad_to_multiple: int = 1024,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Keep only peaks inside the union of the windows; re-pad each shard
    row to the new common length.  Returns (mz, px, ints, max_kept).

    Exact: dropped peaks match no window, so every image bit is unchanged;
    padding rows (MZ_PAD_Q sentinel) sit outside every real window and drop
    with the rest.  Table padding rows quantize to the empty window (0, 0),
    which merged_window_bounds already drops — callers pass raw bounds."""
    flat = merged_window_bounds(lo_q, hi_q)
    keeps = [window_union_member(mz_s[s], flat) for s in range(mz_s.shape[0])]
    n_eff = max((int(k.sum()) for k in keeps), default=1)
    n_pad = -(-max(n_eff, 1) // pad_to_multiple) * pad_to_multiple
    s_count = mz_s.shape[0]
    mz_k = np.full((s_count, n_pad), MZ_PAD_Q, dtype=np.int32)
    px_k = np.full((s_count, n_pad), overflow_row, dtype=np.int32)
    in_k = np.zeros((s_count, n_pad), dtype=np.float32)
    for s, k in enumerate(keeps):
        c = int(k.sum())
        mz_k[s, :c] = mz_s[s][k]
        px_k[s, :c] = px_s[s][k]
        in_k[s, :c] = in_s[s][k]
    return mz_k, px_k, in_k, n_eff


# -- per-batch peak compaction ------------------------------------------------
#
# The window-union restriction (restrict_flat_to_windows) drops peaks outside
# every window of the whole SEARCH, but the histogram scatter still touches
# every resident peak once per BATCH — with T batches, each peak is scattered
# T times while matching (typically) one batch's windows.  The reference has
# no such waste: its searchsorted loop emits only hits [U, formula_imager_segm].
# Per-batch compaction restores that property on TPU with static shapes:
#
# 1. Host, per batch: merge THIS batch's windows into disjoint m/z intervals
#    and cut the sorted peak array at their bounds -> contiguous kept RUNS
#    (run start + cumulative kept offset per run); n_b = total kept.
# 2. Device: materialize the source index of every kept slot with one small
#    scatter (one offset jump per run) + cumsum, then gather pixel/intensity
#    rows.  A host-shipped index array would be ~N_b*4 B/batch through the
#    tunnel; the run list is KBs.
# 3. The bound ranks are re-based to kept space (exact integer arithmetic on
#    the runs), and extraction proceeds unchanged on the compacted arrays.
#
# Exact: kept peaks are precisely those inside some window of the batch, so
# the (pixel, bin, intensity) hit multiset — and every image bit — is
# unchanged.  Scatter work drops from N_resident to ~N_resident/T per batch
# (large formula DBs run tens of batches), which is what makes the large-P
# regime (BASELINE #5) scatter-bound no more.


def batch_peak_runs(
    mz_host: np.ndarray,   # (N,) int32 sorted quantized m/z (resident peaks)
    lo_q: np.ndarray,      # batch window lo bounds (any shape)
    hi_q: np.ndarray,      # batch window hi bounds
    pos: np.ndarray,       # (G,) int32 source-space bound ranks (flat_bound_ranks)
) -> tuple[np.ndarray, np.ndarray, int, np.ndarray]:
    """Host-side compaction plan: (run_kept_start (R,) i32, run_delta (R,) i32,
    n_b, pos_b (G,) i32).

    ``run_kept_start`` is each run's first index in kept space, ``run_delta``
    the jump in (source - kept) offset at that index; ``pos_b`` re-bases the
    grid bound ranks to kept space: #kept peaks strictly below the bound."""
    flat = merged_window_bounds(lo_q, hi_q)
    cuts = np.searchsorted(mz_host, flat.astype(mz_host.dtype), side="left")
    starts, ends = cuts[0::2].astype(np.int64), cuts[1::2].astype(np.int64)
    lens = ends - starts
    keep = lens > 0
    starts, lens = starts[keep], lens[keep]
    if starts.size == 0:     # batch with no real windows (all padding)
        return (np.zeros(0, np.int32), np.zeros(0, np.int32), 0,
                # smlint: host-sync-ok[pos is the host-computed bound-rank array]
                np.zeros(np.asarray(pos).shape, np.int32))
    kept_start = np.zeros(starts.size + 1, dtype=np.int64)
    np.cumsum(lens, out=kept_start[1:])
    n_b = int(kept_start[-1])
    # kept rank of a source rank s: walk back to the last run starting <= s;
    # clamp inside the run (bounds between runs — possible only for empty
    # padding windows — snap to the nearest run edge, which keeps their
    # windows empty in kept space)
    r = np.searchsorted(starts, pos, side="right") - 1
    rc = np.clip(r, 0, None)
    pos_b = np.where(
        r < 0, 0,
        kept_start[rc] + np.clip(pos - starts[rc], 0, lens[rc]))
    offsets = starts - kept_start[:-1]
    run_delta = np.diff(offsets, prepend=0)
    return (kept_start[:-1].astype(np.int32), run_delta.astype(np.int32),
            n_b, pos_b.astype(np.int32))


def compact_peaks(
    px_s: jnp.ndarray,      # (N,) int32 resident pixel rows
    in_s: jnp.ndarray,      # (N,) f32 resident intensities
    run_pos: jnp.ndarray,   # (R_pad,) i32 kept-space run starts (pad: >= n_keep)
    run_delta: jnp.ndarray, # (R_pad,) i32 offset jumps (pad: 0)
    n_b: jnp.ndarray,       # () i32 kept count this batch
    *,
    n_keep: int,
    n_pixels: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Device-side gather of the kept peak slots: (px_b, in_b), both (n_keep,).

    Slots >= n_b are padding: pixel -> an OUT-OF-BOUNDS row so the
    histogram scatter DROPS them (default jnp scatter semantics), not the
    overflow row.  In-bounds padding was a measured pathology: every pad
    slot's bin is G (all bounds below it), so with a sticky ``n_keep``
    capacity above the batch's real keep, millions of pads scattered into
    the ONE cell (overflow_row, G) — and TPU scatter serializes colliding
    updates (~50 vs ~14 ns/peak; docs/PERF.md mechanism 2).  Dropped
    updates write nothing, so they can't collide.  Exact either way: pads
    carry intensity 0 into a bin no window sums.

    The (pixel, intensity) rows are gathered as ONE packed (N, 2) f32
    gather, not two scalar gathers: a 2-column row gather moves the same
    slot in one descriptor, measured 483 -> 181 ms for 7.7M slots on v5e
    (the gather is this function's whole cost; ``indices_are_sorted``
    hints measured no effect).  Exact while pixel ids < 2**24 (f32
    integer range) — the scale guard in models/msm_jax.py caps the flat
    path far below that; the sharded path's ids are shard-local."""
    j = jnp.arange(n_keep, dtype=jnp.int32)
    d = jnp.zeros(n_keep, jnp.int32).at[run_pos].add(run_delta, mode="drop")
    src = jnp.clip(j + jnp.cumsum(d), 0, px_s.shape[0] - 1)
    valid = j < n_b
    if n_pixels < 2**24:
        pk = jnp.stack([px_s.astype(jnp.float32), in_s], axis=1)
        got = pk[src]
        px_b = jnp.where(valid, got[:, 0].astype(jnp.int32), jnp.int32(2**30))
        in_b = jnp.where(valid, got[:, 1], jnp.float32(0.0))
    else:
        px_b = jnp.where(valid, px_s[src], jnp.int32(2**30))
        in_b = jnp.where(valid, in_s[src], jnp.float32(0.0))
    return px_b, in_b


# -- m/z-chunked extraction ---------------------------------------------------
#
# The reference segments the m/z range so each task's working set stays
# bounded (``formula_imager_segm`` m/z segmentation [U], SURVEY.md §2d/§5.7).
# The TPU analog: the histogram scratch above is (P, 2*B*K+1) f32 — ~3.3 GB
# for a >200k-pixel slide at formula_batch=512 (ADVICE r1) — so with
# ``ParallelConfig.mz_chunk`` set, windows are sorted by m/z and processed in
# chunks whose LOCAL bound-grid slice bounds the scratch at (P, gc_width+2).
# The global cube searchsorted happens ONCE (local bins are global bins minus
# the chunk's grid offset); only the scatter-add repeats per chunk, trading
# compute for an HBM ceiling.  Extracted images are bit-identical to the
# unchunked path: hit sets are exact integer-grid matches and sums are exact
# integers (ops/quantize.py) in any grouping.


def window_chunks(
    r_lo: np.ndarray, r_hi: np.ndarray, mz_chunk: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, int]:
    """Host-side chunk plan: (starts (C,), r_lo_loc (C, Wc), r_hi_loc (C, Wc),
    inv (W,), gc_width).

    Windows are ordered by lo rank and cut every ``mz_chunk`` windows; a
    chunk's grid offset is its first window's lo rank; ``gc_width`` (the
    max local rank span, rounded up to a power of two so recompiles are
    rare) sizes the scratch.  ``inv`` maps sorted rows back to input order.
    """
    w = int(r_lo.size)
    wc = max(1, int(mz_chunk))
    c = max(1, -(-w // wc))
    # EMPTY windows (lo == hi: batch padding quantized to (0,0), or windows
    # collapsed by quantization) sort LAST, not by their rank-0 bounds —
    # otherwise a partially-padded batch puts rank-0 empties and high-rank
    # real windows into one chunk whose span is the whole grid, and the
    # sticky gc_width then degrades every batch (measured: 8x band growth,
    # ~10x slowdown on the bench tail batch).  Their local ranks go
    # negative in a straddling chunk, which the membership test treats as
    # empty — exactly right.
    order = np.lexsort((r_lo, (r_lo == r_hi).astype(np.int8)))
    pad = c * wc - w
    r_lo_s = np.concatenate([r_lo[order], np.zeros(pad, r_lo.dtype)]).reshape(c, wc)
    r_hi_s = np.concatenate([r_hi[order], np.zeros(pad, r_hi.dtype)]).reshape(c, wc)
    starts = r_lo_s[:, 0].astype(np.int32)
    # padded tail windows: snap to the chunk offset -> empty local window
    if pad:
        r_lo_s[-1, wc - pad:] = starts[-1]
        r_hi_s[-1, wc - pad:] = starts[-1]
    r_lo_loc = (r_lo_s - starts[:, None]).astype(np.int32)
    r_hi_loc = (r_hi_s - starts[:, None]).astype(np.int32)
    # {1, 1.5} x pow-2 ladder (floor wc): gc is a STATIC matmul/slice width
    # shared by every chunk, so rounding 1026 -> 2048 (the old pure-pow-2
    # rule) paid ~33% extra membership-matmul flops and band-slice reads
    # on typical 512-window chunks; the half-point bounds that at 50% while
    # the sticky per-stream max keeps one executable per stream either way
    gc_width = gc_ladder(max(int(r_hi_loc.max()) if w else 1, wc, 2))
    inv = np.empty(w, dtype=np.int32)
    inv[order] = np.arange(w, dtype=np.int32)
    return starts, r_lo_loc, r_hi_loc, inv, gc_width


def ion_window_chunks(
    r_lo: np.ndarray, r_hi: np.ndarray, b: int, k: int,
    ions_per_chunk: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, int, np.ndarray]:
    """ION-MAJOR chunk plan: (starts (C,), r_lo_loc (C, Wc), r_hi_loc
    (C, Wc), inv_ions (b,), gc_width, order (b,)).

    Like ``window_chunks`` but whole IONS are sorted (by their first real
    window's lo rank; all-empty padding ions last) and chunked, all K
    windows of an ion staying adjacent — so the banded matmul emits image
    rows already ION-MAJOR: the (b, k, P) block needs NO (W, P) gather
    (``jnp.take`` of a 1 GB block per DESI batch, ~2.1 GB of pure HBM
    permutation traffic), only the final (b, 4) METRIC rows are
    un-permuted by ``inv_ions``.  Callers permute the per-ion side inputs
    (theor_ints, n_valid) by ``order`` to match.  Exact: each window
    still sums exactly its own bins (integer grid, any order/grouping).

    Requires ``ions_per_chunk`` to divide ``b`` (static batches are
    powers of two; callers clamp).  gc_width uses the same {1, 1.5} x
    pow-2 ladder as window_chunks."""
    # smlint: host-sync-ok[host chunk planning over the host bound-rank arrays]
    r_lo2 = np.asarray(r_lo).reshape(b, k)
    # smlint: host-sync-ok[host chunk planning over the host bound-rank arrays]
    r_hi2 = np.asarray(r_hi).reshape(b, k)
    empty = r_lo2 >= r_hi2
    all_empty = empty.all(axis=1)
    first_real = np.argmax(~empty, axis=1)
    first_lo = np.where(all_empty, 0, r_lo2[np.arange(b), first_real])
    order = np.lexsort((first_lo, all_empty.astype(np.int8)))
    ipc = ions_per_chunk
    c = b // ipc
    wc = ipc * k
    r_lo_s = r_lo2[order].reshape(c, wc)
    r_hi_s = r_hi2[order].reshape(c, wc)
    real_s = ~empty[order].reshape(c, wc)
    # chunk offset: min lo rank over the chunk's REAL windows (an all-
    # padding chunk keeps 0); empty windows' local ranks may go negative,
    # which the membership test already treats as empty
    big = np.int64(1) << 40
    lo_real = np.where(real_s, r_lo_s, big)
    starts = np.where(real_s.any(axis=1), lo_real.min(axis=1), 0).astype(
        np.int32)
    r_lo_loc = (r_lo_s - starts[:, None]).astype(np.int32)
    r_hi_loc = (r_hi_s - starts[:, None]).astype(np.int32)
    span = int(np.where(real_s, r_hi_loc, 0).max()) if b else 1
    gc_width = gc_ladder(max(span, wc, 2))
    inv_ions = np.empty(b, dtype=np.int32)
    inv_ions[order] = np.arange(b, dtype=np.int32)
    return (starts, r_lo_loc, r_hi_loc, inv_ions, gc_width,
            order.astype(np.int32))


def extract_images_mz_chunked(
    mz_q_cube: jnp.ndarray,   # (P, L) int32
    int_cube: jnp.ndarray,    # (P, L) f32
    grid: jnp.ndarray,        # (G,) int32 sorted window bounds (all chunks)
    starts: jnp.ndarray,      # (C,) int32 grid offset per chunk
    r_lo_loc: jnp.ndarray,    # (C, Wc) int32 local lo ranks
    r_hi_loc: jnp.ndarray,    # (C, Wc) int32 local hi ranks
    inv: jnp.ndarray,         # (W,) int32 sorted-row -> input-order map
    *,
    gc_width: int,
) -> jnp.ndarray:
    """(W, P) f32 ion-window images, scratch bounded at (P, gc_width+2)."""
    p, _l = mz_q_cube.shape
    bins_g = jnp.searchsorted(
        grid, mz_q_cube.ravel(), side="right", method="sort"
    ).reshape(p, -1)                                   # global bins, ONCE
    rows = jnp.arange(p, dtype=jnp.int32)[:, None]
    gg = jnp.arange(gc_width + 2, dtype=jnp.int32)[:, None]

    def chunk(_, data):
        start, rlo, rhi = data
        # out-of-chunk peaks clip to bins 0 / gc_width+1, excluded from every
        # window (local interiors are (rlo, rhi] with rlo >= 0, rhi <= gc_width)
        lb = jnp.clip(bins_g - start, 0, gc_width + 1)
        wh = jnp.zeros((p, gc_width + 2), jnp.float32).at[rows, lb].add(int_cube)
        d = ((gg > rlo[None, :]) & (gg <= rhi[None, :])).astype(jnp.float32)
        return None, jnp.dot(wh, d, precision=jax.lax.Precision.HIGHEST).T

    _, imgs = jax.lax.scan(chunk, None, (starts, r_lo_loc, r_hi_loc))
    imgs = imgs.reshape(-1, p)                         # (C*Wc, P) sorted order
    return jnp.take(imgs, inv, axis=0)                 # (W, P) input order


# -- roofline cost model ------------------------------------------------------

def fused_score_cost_model(
    n_pixels: int,
    resident_peaks: int,
    n_ions: int,
    max_peaks: int,
    formula_batch: int,
    nlevels: int = 30,
    ordered: bool = True,
    fused: bool = False,
    cube_dtype: str = "f32",
) -> dict:
    """Minimum-work estimate of one full scoring rep (all ions once), for
    the roofline probe (scripts/roofline_probe.py, ISSUE 3 satellite).

    Counts the traffic/flops the fused graph CANNOT avoid under its current
    algorithm, priced from the extraction design (this module) and the
    measured mechanism notes in docs/PERF.md:

    - histogram scatter: every scored peak slot is one 4 B intensity read,
      one index read, and one f32 read-modify-write on the scratch (~12 B).
      Ordered streams scatter each resident peak ~once in total (band-slice
      per-batch bands); unordered streams re-touch the residents per batch.
    - scratch zero-init: XLA scatter's fixed cost is the operand
      zero-init/copy (measured ~38 GB/s on v5e, PERF.md round 5) — one
      (P+1) x max(G+1, gc+2) f32 block per batch.
    - membership matmul: wh (P, G+1) @ D (G+1, B) per batch at f32.
    - image block: (n_ions, K, P) f32 written by extraction, then read by
      the moments pass (1x) and the chaos sweeps (>= ~2 effective passes of
      the label plane at span-32 with the cheap certificate).

    Returns bytes/flops totals; ``min_seconds(bw, flops)`` against measured
    device peaks is the roofline floor.  This is a LOWER bound on work (it
    prices no padding, no recompiles, no host/dispatch), so
    measured/modeled is an upper bound on remaining headroom.

    ``fused=True`` prices the ISSUE 18 single-pass Pallas variant
    (ops/score_pallas.py) instead of the unfused gather/segment-sum chain:
    the (B, K, P) image block never round-trips HBM — the kernel stages
    the histogram band in VMEM (two passes: moments, then centered
    epilogue), writes only the (C, Wc, 5) moment partials plus the (B, P)
    principal images the chaos sweep needs, and the epilogue reads
    principal rather than the full K-peak block.  ``cube_dtype`` prices
    the resident intensity read of the histogram scatter at the compacted
    width (ops/quantize.py: bf16 2 B, int8 1 B per peak).
    """
    n_batches = max(1, -(-n_ions // formula_batch))
    g = 2 * formula_batch * max_peaks
    scratch_cols = max(g + 1, 4098)
    scatter_slots = (resident_peaks if ordered
                     else resident_peaks * n_batches)
    int_bytes = {"f32": 4, "bf16": 2, "int8": 1}[cube_dtype]
    # per slot: intensity read + index read + f32 scratch read-modify-write
    scatter_bytes = (int_bytes + 8) * scatter_slots
    init_bytes = 4 * n_batches * (n_pixels + 1) * scratch_cols
    if fused:
        # two VMEM-staged passes over the (g+1, P) histogram band; chunk
        # band overlap (~16 rows per chunk) is noise at this granularity
        band_read_bytes = 2 * 4 * n_batches * (g + 1) * n_pixels
        image_bytes = 4 * n_ions * n_pixels          # principal write only
        metric_read_bytes = 2 * image_bytes          # chaos ~2 passes
        # membership dot runs in BOTH kernel passes; the centered-epilogue
        # dots add 2*2 flops per (ion, peak, pixel) cell
        matmul_flops = (2 * 2.0 * n_batches * n_pixels * (g + 1)
                        * formula_batch
                        + 4.0 * n_ions * max_peaks * n_pixels)
        total_bytes = (scatter_bytes + init_bytes + band_read_bytes
                       + image_bytes + metric_read_bytes)
        return dict(
            n_batches=n_batches,
            scatter_slots=int(scatter_slots),
            scatter_bytes=int(scatter_bytes),
            scratch_init_bytes=int(init_bytes),
            band_read_bytes=int(band_read_bytes),
            image_bytes=int(image_bytes),
            metric_read_bytes=int(metric_read_bytes),
            total_bytes=int(total_bytes),
            matmul_flops=float(matmul_flops),
        )
    image_bytes = 4 * n_ions * max_peaks * n_pixels
    metric_read_bytes = 3 * image_bytes    # moments 1x + chaos ~2 passes
    matmul_flops = 2.0 * n_batches * n_pixels * (g + 1) * formula_batch
    total_bytes = scatter_bytes + init_bytes + image_bytes + metric_read_bytes
    return dict(
        n_batches=n_batches,
        scatter_slots=int(scatter_slots),
        scatter_bytes=int(scatter_bytes),
        scratch_init_bytes=int(init_bytes),
        image_bytes=int(image_bytes),
        metric_read_bytes=int(metric_read_bytes),
        total_bytes=int(total_bytes),
        matmul_flops=float(matmul_flops),
    )
