"""Ion-image extraction, JAX/TPU backend.

TPU-first reformulation of the reference hot loop (SURVEY.md §3.3,
``formula_imager_segm.compute_sf_images`` [U]).  Instead of a cluster-wide
shuffle of (ion, pixel, intensity) hits, the spectral cube lives on device as
a padded (pixels x peaks) matrix sorted by m/z within each pixel row, and an
ion image is computed with *static shapes* as:

    img[w, p] = cumint[p, e(w,p)] - cumint[p, s(w,p)]

where s/e are vmapped binary searches of each window's quantized bounds into
each pixel's m/z row, and cumint is the per-row prefix sum of intensities.
No gather of ragged hit lists, no shuffle: two searchsorteds + one gather —
XLA fuses the lot.  The pixel axis is the sharding axis; each shard computes
its slice of every ion image independently (collectives only in metrics).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..io.dataset import SpectralDataset
from .quantize import MZ_PAD_Q, quantize_mz


def prepare_cube_arrays(
    ds: SpectralDataset, pad_to_multiple: int = 128, pixels_multiple: int = 1
) -> tuple[np.ndarray, np.ndarray]:
    """Host-side: (mz_q_cube int32 (P, L), int_cube float32 (P, L)).

    m/z rows are quantized (padding saturates to the MZ_PAD_Q sentinel so
    binary search always lands before padding)."""
    mz_cube, int_cube, _lens = ds.padded_cube(pad_to_multiple, pixels_multiple)
    return quantize_mz(mz_cube), int_cube


def cumulative_intensities(int_cube: jnp.ndarray) -> jnp.ndarray:
    """(P, L) -> (P, L+1) exclusive prefix sums per pixel row (device)."""
    zero = jnp.zeros((int_cube.shape[0], 1), dtype=int_cube.dtype)
    return jnp.concatenate([zero, jnp.cumsum(int_cube, axis=1)], axis=1)


def extract_images(
    mz_q_cube: jnp.ndarray,   # (P, L) int32, sorted rows, MZ_PAD_Q padding
    cum_int: jnp.ndarray,     # (P, L+1) f32
    lo_q: jnp.ndarray,        # (W,) int32 window lower bounds (inclusive)
    hi_q: jnp.ndarray,        # (W,) int32 window upper bounds (exclusive)
) -> jnp.ndarray:
    """(W, P) f32 ion-window images on the current device/shard."""

    def per_pixel(row, cum_row):
        s = jnp.searchsorted(row, lo_q, side="left")
        e = jnp.searchsorted(row, hi_q, side="left")
        return cum_row[e] - cum_row[s]          # (W,)

    imgs_pw = jax.vmap(per_pixel)(mz_q_cube, cum_int)   # (P, W)
    return imgs_pw.T
