"""Fused per-ion image moments: one HBM read for every metric reduction.

The MSM metric stage needs, per (ion, peak) image row of the (N, K, P)
block: the pixel sum (spectral pattern match + correlation means), the
centered norm and centered dot against the principal row (spatial
correlation), and per ion the principal row's max + positive count (chaos
thresholds / alive gating).  As separate XLA reductions those are ~2.5
passes over the block at the VPU reduce rate (~150 GB/s effective on a
tunneled v5e) — ~25-30 ms per 1 GB DESI batch, pure HBM traffic.

This Pallas kernel streams each ion's (K, P) row block through VMEM once
(grid over ions, block (1, K, P)) and computes ALL of them in-kernel,
reading the tile twice from VMEM (free) for the exact two-pass centered
formulas — the one-pass raw-moment identity (sum(x^2) - P*mean^2) is NOT
used: with integer-grid pixel values up to 2**24 it cancels
catastrophically in f32.  Reduction ORDER differs from XLA's tree, so
spatial/spectral values can move within the documented 1e-6 cross-backend
contract (chaos integer counts are unaffected — thresholds come from the
exact max).

Reference semantics: ``img_measures.py::isotope_image_correlation /
isotope_pattern_match [U]`` (SURVEY.md §3.4) — the math matches
ops/metrics_np.py; this file only changes where the flops run.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..analysis.numerics import numerics_surface
from ..analysis.surface import compile_surface

# Declared numerics contracts (ISSUE 15, analysis/numerics.py): the
# Pallas kernels reduce in a different order than XLA's tree (ulp-grade
# drift, the documented cross-backend contract); the masked jnp fallback
# is bit-exact vs unpadded by construction.  `padded=images` seeds the
# masked-reduction rule's taint — every raw reduction below carries its
# own pad-invariance argument as a masked-ok annotation.
NUMERICS = numerics_surface(__name__, {
    "batch_moments_pallas":
        "contract=ulp(16); test=tests/test_moments.py::"
        "test_moments_interpret_matches_f64",
    "batch_moments_pallas_masked":
        "contract=ulp(16); test=tests/test_buckets.py::"
        "test_masked_moments_match_unpadded; padded=images",
    "batch_moments_jnp":
        "contract=bit_exact; test=tests/test_buckets.py::"
        "test_masked_moments_match_unpadded; padded=images",
    "batch_moments":
        "contract=ulp(16); test=tests/test_moments.py::"
        "test_moments_jnp_fallback_matches_f64; padded=images",
})

# Declared compile surface (ISSUE 12, analysis/surface.py).
COMPILE_SURFACE = compile_surface(__name__, {
    "batch_moments_pallas":
        "statics=interpret; buckets=one executable per padded (N, K, P) "
        "batch shape — N/K ride the formula_batch padding, P is the "
        "row-bucketed pixel lattice point (ops/buckets.row_bucket)",
    "batch_moments_pallas_masked":
        "statics=interpret; buckets=same (N, K, P) lattice as the unmasked "
        "kernel; the real-pixel count is a TRACED operand, so every "
        "dataset size in a pixel bucket shares one executable (ISSUE 13)",
})

# VMEM budget for one ion's (K, P) row block, in f32 cells.  The block is
# sublane-padded to 8 rows (K=4 -> 2x), and the per-tile transients are
# small, so 2M cells =~ 8 MB padded stays well inside the 16 MB scoped
# limit alongside Mosaic's own buffers.
_MAX_CELLS = 2 * 1024 * 1024
# in-kernel VMEM tile width (lanes) for the two passes
_TILE = 16384


def moments_fit(k: int, n_pix: int) -> bool:
    """True when one ion's (K, P) block fits the kernel's VMEM budget."""
    return k * n_pix <= _MAX_CELLS and n_pix % 128 == 0


def _moments_kernel(img_ref, out_ref, *, k: int, p: int):
    nt = p // _TILE if p % _TILE == 0 else 1
    tw = _TILE if p % _TILE == 0 else p

    def pass1(i, acc):
        sums, vmax, nn = acc
        t = img_ref[0, :, pl.dslice(i * tw, tw)]        # (K, tw) f32
        sums = sums + jnp.sum(t, axis=1, keepdims=True)
        r0 = t[0:1]
        vmax = jnp.maximum(vmax, jnp.max(r0, axis=1, keepdims=True))
        nn = nn + jnp.sum((r0 > 0.0).astype(jnp.float32), axis=1,
                          keepdims=True)
        return sums, vmax, nn

    sums0 = jnp.zeros((k, 1), jnp.float32)
    vmax0 = jnp.full((1, 1), -jnp.inf, jnp.float32)
    nn0 = jnp.zeros((1, 1), jnp.float32)
    sums, vmax, nn = jax.lax.fori_loop(0, nt, pass1, (sums0, vmax0, nn0))
    mean = sums / np.float32(p)                          # (K, 1)

    def pass2(i, acc):
        normsq, dots = acc
        t = img_ref[0, :, pl.dslice(i * tw, tw)]
        c = t - mean                                     # (K, tw) centered
        c0 = c[0:1]                                      # principal row
        normsq = normsq + jnp.sum(c * c, axis=1, keepdims=True)
        dots = dots + jnp.sum(c0 * c, axis=1, keepdims=True)
        return normsq, dots

    z = jnp.zeros((k, 1), jnp.float32)
    normsq, dots = jax.lax.fori_loop(0, nt, pass2, (z, z))

    out = jnp.concatenate(
        [sums, normsq, dots,
         jnp.broadcast_to(vmax, (k, 1)), jnp.broadcast_to(nn, (k, 1))],
        axis=1)                                          # (K, 5)
    out_ref[0] = out


def _moments_kernel_masked(n_ref, img_ref, out_ref, *, k: int, p: int):
    """The masked sibling of ``_moments_kernel`` (ISSUE 13 lattice): the
    trailing ``p - n_real`` pixels are zero padding from the row bucket.
    Sums/max/positive-count are exactly invariant to zero pads; only the
    centering changes — the mean divides by the TRACED real count and the
    centered tile is masked back to zero past it, mirroring the masked
    XLA fallback (``batch_moments_jnp``) op for op."""
    nt = p // _TILE if p % _TILE == 0 else 1
    tw = _TILE if p % _TILE == 0 else p
    n_real = n_ref[0, 0]                                 # i32 scalar

    def pass1(i, acc):
        sums, vmax, nn = acc
        t = img_ref[0, :, pl.dslice(i * tw, tw)]        # (K, tw) f32
        sums = sums + jnp.sum(t, axis=1, keepdims=True)
        r0 = t[0:1]
        vmax = jnp.maximum(vmax, jnp.max(r0, axis=1, keepdims=True))
        nn = nn + jnp.sum((r0 > 0.0).astype(jnp.float32), axis=1,
                          keepdims=True)
        return sums, vmax, nn

    sums0 = jnp.zeros((k, 1), jnp.float32)
    vmax0 = jnp.full((1, 1), -jnp.inf, jnp.float32)
    nn0 = jnp.zeros((1, 1), jnp.float32)
    sums, vmax, nn = jax.lax.fori_loop(0, nt, pass1, (sums0, vmax0, nn0))
    mean = sums / n_real.astype(jnp.float32)             # (K, 1)

    def pass2(i, acc):
        normsq, dots = acc
        t = img_ref[0, :, pl.dslice(i * tw, tw)]
        cols = jax.lax.broadcasted_iota(jnp.int32, (k, tw), 1) + i * tw
        c = jnp.where(cols < n_real, t - mean, 0.0)      # (K, tw) centered
        c0 = c[0:1]                                      # principal row
        normsq = normsq + jnp.sum(c * c, axis=1, keepdims=True)
        dots = dots + jnp.sum(c0 * c, axis=1, keepdims=True)
        return normsq, dots

    z = jnp.zeros((k, 1), jnp.float32)
    normsq, dots = jax.lax.fori_loop(0, nt, pass2, (z, z))

    out = jnp.concatenate(
        [sums, normsq, dots,
         jnp.broadcast_to(vmax, (k, 1)), jnp.broadcast_to(nn, (k, 1))],
        axis=1)                                          # (K, 5)
    out_ref[0] = out


@partial(jax.jit, static_argnames=("interpret",))
def batch_moments_pallas_masked(images: jnp.ndarray, n_real,
                                interpret: bool = False):
    """Masked-moments Pallas route: like ``batch_moments_pallas`` but the
    real-pixel count is a traced (1, 1) i32 operand, so every dataset size
    inside one pixel bucket shares this executable (ISSUE 13)."""
    n, k, p = images.shape
    n_arr = jnp.asarray(n_real, jnp.int32).reshape(1, 1)
    out = pl.pallas_call(
        partial(_moments_kernel_masked, k=k, p=p),
        grid=(n,),
        in_specs=[pl.BlockSpec((1, 1), lambda i: (0, 0)),
                  pl.BlockSpec((1, k, p), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((1, k, 5), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, k, 5), jnp.float32),
        interpret=interpret,
    )(n_arr, images)
    sums = out[:, :, 0]
    normsq = out[:, :, 1]
    dots = out[:, :, 2]
    vmax = out[:, 0, 3]
    nn = out[:, 0, 4]
    return sums, normsq, dots, vmax, nn


@partial(jax.jit, static_argnames=("interpret",))
def batch_moments_pallas(images: jnp.ndarray, interpret: bool = False):
    """(sums (N,K), normsq (N,K), dots (N,K), vmax (N,), n_notnull (N,))
    from an (N, K, P) image block, one streaming pass."""
    n, k, p = images.shape
    out = pl.pallas_call(
        partial(_moments_kernel, k=k, p=p),
        grid=(n,),
        in_specs=[pl.BlockSpec((1, k, p), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((1, k, 5), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, k, 5), jnp.float32),
        interpret=interpret,
    )(images)
    sums = out[:, :, 0]
    normsq = out[:, :, 1]
    dots = out[:, :, 2]
    vmax = out[:, 0, 3]
    nn = out[:, 0, 4]
    return sums, normsq, dots, vmax, nn


def batch_moments_jnp(images: jnp.ndarray, n_real=None):
    """XLA fallback with identical semantics (non-TPU backends, or image
    rows past the VMEM budget).

    ``n_real`` (ISSUE 13 shape-bucket lattice): traced i32 scalar count of
    REAL pixels when the trailing pixels are lattice padding (whole zero
    rows appended by ``ops/buckets.row_bucket``).  Padded zeros are exact
    no-ops for sums/norms/dots/max/count, but the correlation's mean
    divides by the PIXEL COUNT — so the mean takes the real count and the
    centered block is masked back to zero on pad pixels.  With
    ``n_real == P`` (or None) the arithmetic is the unpadded sequence
    bit-for-bit: the mask keeps every value and the division sees the
    same operands."""
    # smlint: masked-ok[pad pixels are exact zeros and add exactly 0 to every f32 sum; only the MEAN divides by a count, and it takes n_real below]
    sums = images.sum(axis=-1)
    if n_real is None:
        mean = sums[..., None] / np.float32(images.shape[-1])
        cent = images - mean
    else:
        mean = sums[..., None] / n_real.astype(jnp.float32)
        real = (jnp.arange(images.shape[-1], dtype=jnp.int32)
                < n_real)[None, None, :]
        cent = jnp.where(real, images - mean, 0.0)
    # smlint: masked-ok[cent is masked back to exact zero past n_real, so pad slots contribute 0.0 to the squared norm]
    normsq = jnp.sum(cent * cent, axis=-1)
    # smlint: masked-ok[both einsum operands are zero-masked past n_real; pad products are exact zeros]
    dots = jnp.einsum("np,nkp->nk", cent[:, 0, :], cent)
    principal = images[:, 0, :]
    # smlint: masked-ok[zero pads never exceed a positive maximum; empty rows yield 0 either way]
    vmax = principal.max(axis=1)
    # smlint: masked-ok[zero pads are never > 0; the positive count is pad-invariant]
    nn = jnp.sum((principal > 0).astype(jnp.float32), axis=1)
    return sums, normsq, dots, vmax, nn


def batch_moments(images: jnp.ndarray, n_real=None):
    """Route to a Pallas kernel on TPU when the block shape fits.
    ``n_real`` (lattice-padded pixels, ISSUE 13) selects the masked
    kernel — the real-pixel count rides as a traced operand so the
    executable is shared across every dataset size in the bucket."""
    n, k, p = images.shape
    if jax.default_backend() == "tpu" and moments_fit(k, p):
        if n_real is None:
            return batch_moments_pallas(images)
        return batch_moments_pallas_masked(images, n_real)
    return batch_moments_jnp(images, n_real=n_real)
