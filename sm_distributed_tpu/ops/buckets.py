"""Canonical shape-bucket lattice (ISSUE 13 tentpole).

Every jitted executable's signature is a function of a handful of shape
parameters: the padded scoring batch ``b``, the resident peak count ``N``,
the pixel-grid geometry ``(nrows, ncols)``, and the sticky plan statics
(``gc_width``, ``n_keep``, ``w_cap`` — already laddered in
``ops/imager_jax.py``).  PR 12 made the surface *declared*; this module
makes it **closed under all traffic**: the raw dataset-dependent values are
snapped to one small power-of-two-ish lattice, so every dataset size maps
into a finite signature set that can be enumerated, AOT-compiled into the
persistent XLA cache (``service/primer.py``), and proven closed by
``scripts/compile_census.py``.

The lattice is the QUARTER-POINT ladder ``{1, 1.25, 1.5, 1.75} x 2^e``
(bounded padding waste 25%, expected ~11%, ~4 buckets per octave — the
coarser sibling of ``imager_jax.band_bucket``'s eighth ladder, chosen
because every extra point here is an extra executable the primer must
compile).  Three masked paddings ride it:

- **peaks** (``peak_bucket``): resident sorted-peak arrays pad with the
  existing ``MZ_PAD_Q`` sentinel / overflow-pixel / zero-intensity slots —
  the exact mechanism ``prepare_flat_sharded_arrays`` already uses for its
  1024-multiple rounding, just snapped to the shared ladder;
- **pixel rows** (``row_bucket``): the image grid pads with whole ZERO
  rows at the bottom; component counts, maxima and positive counts are
  exactly invariant, and the one non-invariant op — the correlation's
  mean over pixels — takes the REAL pixel count as a *traced* scalar
  (``ops/metrics_jax.batch_metrics(n_real=...)``), so padded scoring is
  bit-identical to unpadded.  Columns are the lattice's base dimension
  (bucketing them would renumber pixel indices); a bucket is therefore
  keyed ``(row_bucket(nrows), ncols)``;
- **batch** (``batch_bucket_down``): pad-to batch sizes snap DOWN (padding
  up could exceed a proven-fitting HBM footprint), so OOM-shrunk caps
  (``models/oom.py``) land on lattice points shared with the primer's
  enumeration.

``BucketSpec`` records one concrete executable's identity — variant,
statics, and argument shapes — into a process-global registry persisted
next to the persistent XLA cache (``bucket_manifest.json``), which is what
``scripts/prime_cache.py`` and the scheduler-idle primer enumerate and
``GET /debug/compile`` reports as primed vs missing.
"""

from __future__ import annotations

import json
import os
import threading
from pathlib import Path

# ---------------------------------------------------------------- lattice

# floors: below these the padding waste is noise and a single bucket is
# cheaper than many tiny executables
PEAK_FLOOR = 4096       # resident-peak arrays (slots are 8 bytes)
ROW_FLOOR = 8           # image rows
PIXEL_FLOOR = 64        # flat pixel counts (oom shape keys)


def pow2ish(n: int, floor: int = 1) -> int:
    """Smallest quarter-ladder point ({1, 1.25, 1.5, 1.75} x 2^e) >= n,
    with a floor.  The shared canonical rounding — every shape bucket in
    the engine goes through this one ladder."""
    n = max(int(n), 1)
    cap = max(int(floor), 1)
    while cap < n:
        cap <<= 1
    if cap > floor and cap >= 8:
        # quarter points live between cap/2 and cap
        for quarters in (5, 6, 7):
            mid = (cap >> 3) * quarters
            if n <= mid:
                return mid
    return cap


def pow2ish_down(n: int, floor: int = 1) -> int:
    """Largest quarter-ladder point <= n (>= floor) — the DOWN-snap used
    for pad-to batch sizes, where rounding up would grow a proven-fitting
    memory footprint.  Ladder points: powers of two, plus the 5/8, 6/8,
    7/8 points of every octave at or above 8 (matching ``pow2ish``)."""
    n = max(int(n), 1)
    f = max(int(floor), 1)
    if n <= f:
        return f
    best = f
    cap = 1
    while cap <= n:
        if cap >= f:
            best = max(best, cap)
        if cap >= 8 and cap > f:
            for eighths in (5, 6, 7):
                pt = (cap >> 3) * eighths
                if f <= pt <= n:
                    best = max(best, pt)
        cap <<= 1
    # the octave just above n can still hold in-range quarter points
    if cap >= 8 and cap > f:
        for eighths in (5, 6, 7):
            pt = (cap >> 3) * eighths
            if f <= pt <= n:
                best = max(best, pt)
    return best


def peak_bucket(n_peaks: int) -> int:
    """Lattice capacity for a resident sorted-peak array."""
    return pow2ish(n_peaks, PEAK_FLOOR)


def row_bucket(nrows: int) -> int:
    """Lattice row count for the image grid (columns stay exact)."""
    return pow2ish(nrows, ROW_FLOOR)


def pixel_bucket(n_pixels: int) -> int:
    """Lattice point for a flat pixel count — the oom safe-batch
    ``shape_key`` granularity, so a learned batch transfers to every
    dataset size sharing the bucket."""
    return pow2ish(n_pixels, PIXEL_FLOOR)


def batch_bucket_down(batch: int) -> int:
    """Largest lattice point <= ``batch`` — pad-to batch sizes and
    OOM-shrunk caps snap DOWN so padding never grows a proven-fitting
    HBM footprint."""
    return pow2ish_down(batch, 1)


def buckets_enabled(parallel_cfg) -> bool:
    """``parallel.shape_buckets`` knob: "auto"/"on" enable the lattice,
    "off" keeps the exact legacy shapes (tests compare the two)."""
    return getattr(parallel_cfg, "shape_buckets", "auto") != "off"


def effective_batch(parallel_cfg) -> int:
    """The pad-to scoring batch: ``parallel.formula_batch`` snapped DOWN
    to the lattice when buckets are on (both the slicing side —
    ``MSMBasicSearch`` — and the padding side — the jax backends — call
    this, so they can never disagree)."""
    b = max(1, parallel_cfg.formula_batch)
    return batch_bucket_down(b) if buckets_enabled(parallel_cfg) else b


# ---------------------------------------------------------- spec registry

_SPEC_KEYS = (
    # identity of one concrete executable in the lattice
    "kind",               # "flat" | "sharded" | "chunked"
    "variant",            # "plain" | "compact" | "band" | "fused" | "step"
    "nrows", "ncols",     # bucketed rows x exact columns (metric geometry)
    "nlevels", "do_preprocessing", "q",
    "n_resident",         # bucketed resident peak slots (per shard row)
    "b", "k",             # padded batch x isotope peaks
    "gc_width",           # sticky chunk-band ladder point
    "n_keep", "r_pad",    # compact-variant capacities (0 = n/a)
    "w_cap",              # band-variant capacity (0 = n/a)
    "g", "c", "wc",       # bound-grid / chunk-plan shapes
    "devices",            # lease shape: chip count (1 = single device)
    # sharded (mesh-shaped) executables only — absent (None) on flat specs
    # so pre-existing manifest keys stay stable within a kind:
    "mesh_pix", "mesh_form",  # mesh axis sizes (pixels x formulas)
    "p_loc",              # per-shard pixel capacity (whole bucketed rows)
    "w",                  # total window count (the inv permutation length)
    # compacted-cube executables only (ISSUE 18) — recorded only when
    # parallel.cube_dtype != "f32", so legacy spec keys stay byte-stable:
    "cube_dtype",         # "bf16" | "int8" resident intensity dtype
)


def spec_key(spec: dict) -> str:
    """Stable identity string for one BucketSpec (manifest/dedup key)."""
    return "|".join(f"{k}={spec.get(k)}" for k in _SPEC_KEYS)


class _SpecRegistry:
    """Process-global registry of observed bucket specs, write-through to
    ``<compile_cache>/bucket_manifest.json`` (smlint guarded-by)."""

    _GUARDED_BY = {"_specs": "_lock", "_dir": "_lock"}
    _MAX = 256                        # manifest bound (oldest dropped)

    def __init__(self):
        self._lock = threading.Lock()
        self._specs: dict[str, dict] = {}
        self._dir: Path | None = None

    def set_dir(self, cache_dir) -> None:
        """Bind the persistence directory (the persistent XLA cache dir)
        and fold any previously persisted manifest in."""
        if cache_dir is None:
            return
        path = Path(cache_dir) / "bucket_manifest.json"
        loaded: dict[str, dict] = {}
        try:
            raw = json.loads(path.read_text())
            for ent in raw.get("specs", []):
                if isinstance(ent, dict):
                    loaded[spec_key(ent)] = ent
        except (OSError, ValueError):
            pass                      # absent/corrupt manifest = empty
        with self._lock:
            self._dir = Path(cache_dir)
            for k, v in loaded.items():
                self._specs.setdefault(k, v)

    def record(self, spec: dict) -> bool:
        """Record one observed spec; returns True when it is new.  New
        specs write through to the manifest (atomic tmp+replace); a failed
        write is logged by the caller's layer, never raised."""
        key = spec_key(spec)
        with self._lock:
            if key in self._specs:
                return False
            self._specs[key] = dict(spec)
            while len(self._specs) > self._MAX:
                self._specs.pop(next(iter(self._specs)))
            snapshot = list(self._specs.values())
            directory = self._dir
        if directory is not None:
            _write_manifest(directory, snapshot)
        return True

    def specs(self) -> list[dict]:
        with self._lock:
            return [dict(s) for s in self._specs.values()]

    def reset(self) -> None:
        with self._lock:
            self._specs.clear()
            self._dir = None


def _write_manifest(directory: Path, specs: list[dict]) -> None:
    path = directory / "bucket_manifest.json"
    tmp = path.with_name(path.name + ".tmp")
    try:
        directory.mkdir(parents=True, exist_ok=True)
        tmp.write_text(json.dumps({"specs": specs}))
        os.replace(tmp, path)
    except OSError:
        from ..utils.logger import logger

        logger.warning("could not write bucket manifest %s", path,
                       exc_info=True)


_registry = _SpecRegistry()


def bind_manifest_dir(cache_dir) -> None:
    """Point the spec registry's persistence at the persistent XLA cache
    directory (called by the backends alongside enable_compile_cache)."""
    _registry.set_dir(cache_dir)


def record_spec(spec: dict) -> bool:
    """Record one observed executable spec (backends call this at
    dispatch time, deduped); returns True when new."""
    return _registry.record(spec)


def recorded_specs() -> list[dict]:
    return _registry.specs()


def load_manifest(cache_dir) -> list[dict]:
    """Read a persisted bucket manifest without touching the process
    registry (the prime_cache CLI's entry point)."""
    path = Path(cache_dir) / "bucket_manifest.json"
    try:
        raw = json.loads(path.read_text())
    except (OSError, ValueError):
        return []
    return [e for e in raw.get("specs", []) if isinstance(e, dict)]


def reset() -> None:
    """Forget recorded specs and the bound manifest dir (tests)."""
    _registry.reset()
