"""Theoretical isotope-pattern calculation (the reference's IsocalcWrapper).

Reference: ``sm/engine/isocalc_wrapper.py::IsocalcWrapper.isotope_peaks`` [U]
(SURVEY.md #6) wraps ``pyMSpec.pyisocalc``: exact isotopic fine structure →
gaussian blur at instrument resolution (``isocalc_sigma``,
``isocalc_pts_per_mz``) → centroid detection → top-``n_peaks`` centroided
(mzs[], ints[]) per (formula, adduct), intensities normalized to max=100.

We implement the same algorithm natively on NumPy (host-side precompute; the
result is packed into a device-resident tensor, see ``IsotopePatternTable``).
The per-(config) disk cache plays the role of the reference's ``theor_peaks``
Postgres table — a persistent cross-job cache where only missing
(formula, adduct) pairs are recomputed (``theor_peaks_gen.py`` [U],
SURVEY.md #7 and §5.4).
"""

from __future__ import annotations

import hashlib
import json
import zipfile
from dataclasses import dataclass
from functools import lru_cache
from pathlib import Path

import numpy as np

from . import elements
from .formula import FormulaError, apply_adduct, parse_formula
from ..utils.config import IsotopeGenerationConfig
from ..utils.logger import logger

# fine-structure pruning: drop states below this relative abundance
_PRUNE_ABUNDANCE = 1e-10
# merge fine-structure states closer than this [Da] (well below any
# instrument sigma we blur with; keeps convolutions small)
_MERGE_DA = 1e-5
# cap on states kept per convolution (keeps worst-case formulas bounded)
_MAX_STATES = 4096


def _merge_states(masses: np.ndarray, abunds: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Sort by mass; merge states within _MERGE_DA (abundance-weighted mass)."""
    order = np.argsort(masses)
    masses, abunds = masses[order], abunds[order]
    # group indices: new group wherever the gap exceeds the merge width
    group = np.concatenate([[0], np.cumsum(np.diff(masses) > _MERGE_DA)])
    n = group[-1] + 1
    # bincount == add.at here (same left-to-right accumulation order, so
    # identical f64 bits) at a fraction of the cost — add.at's unbuffered
    # ufunc loop was the fine-structure hot spot
    ab = np.bincount(group, weights=abunds, minlength=n)
    wm = np.bincount(group, weights=masses * abunds, minlength=n)
    return wm / ab, ab


def _prune(masses: np.ndarray, abunds: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    keep = abunds > _PRUNE_ABUNDANCE * abunds.max()
    masses, abunds = masses[keep], abunds[keep]
    if masses.size > _MAX_STATES:
        keep = np.argsort(abunds)[-_MAX_STATES:]
        keep.sort()
        masses, abunds = masses[keep], abunds[keep]
    return masses, abunds


def _convolve(a: tuple[np.ndarray, np.ndarray], b: tuple[np.ndarray, np.ndarray]):
    m = (a[0][:, None] + b[0][None, :]).ravel()
    p = (a[1][:, None] * b[1][None, :]).ravel()
    return _prune(*_merge_states(m, p))


@lru_cache(maxsize=8192)
def _element_distribution(el: str, n: int) -> tuple[np.ndarray, np.ndarray]:
    """Isotope distribution of n atoms of el, by exponentiation-by-squaring.

    Cached per (element, count): across a molecular DB the same (el, n)
    pairs recur constantly (profiled at 30% of pattern wall-clock when
    recomputed per formula — the cache is exact, the arrays are treated
    as read-only by every consumer).  Each worker process builds its own
    cache (cheap relative to a >=256-pattern batch)."""
    isos = elements.ISOTOPES[el]
    base = (np.array([m for m, _ in isos]), np.array([a for _, a in isos]))
    result: tuple[np.ndarray, np.ndarray] | None = None
    sq = base
    while n > 0:
        if n & 1:
            result = sq if result is None else _convolve(result, sq)
        n >>= 1
        if n:
            sq = _convolve(sq, sq)
    assert result is not None
    return result


def fine_structure(counts: dict[str, int]) -> tuple[np.ndarray, np.ndarray]:
    """Exact isotopic fine structure of a neutral molecule: (masses, abundances),
    sorted by mass, abundances summing to ~1 (minus pruned tail)."""
    acc: tuple[np.ndarray, np.ndarray] | None = None
    for el, n in sorted(counts.items()):
        dist = _element_distribution(el, n)
        acc = dist if acc is None else _convolve(acc, dist)
    assert acc is not None
    return acc


def centroids(
    counts: dict[str, int],
    charge: int,
    isocalc_sigma: float,
    isocalc_pts_per_mz: int,
    n_peaks: int,
) -> tuple[np.ndarray, np.ndarray]:
    """Centroided theoretical pattern of the ION with the given atom counts.

    Returns (mzs, ints): up to ``n_peaks`` peaks sorted by m/z ascending,
    intensities normalized so the strongest peak is 100.0 (the pyisocalc
    convention the reference stores in theor_peaks [U]).
    """
    masses, abunds = fine_structure(counts)
    # ion m/z per fine-structure state
    mzs_fs = (masses - charge * elements.ELECTRON_MASS) / abs(charge)

    # Only the low-mass end can contribute the top peaks: blurring merges
    # states within ~sigma, and isotope peaks are ~1/|z| apart. Keep a margin
    # of n_peaks+2 isotope spacings above the monoisotopic state.
    lo = mzs_fs.min()
    window = (n_peaks + 2) / abs(charge)
    keep = mzs_fs <= lo + window
    mzs_fs, abunds_fs = mzs_fs[keep], abunds[keep]

    # profile grid at pts_per_mz resolution, padded by 5 sigma
    pad = 5.0 * isocalc_sigma
    step = 1.0 / isocalc_pts_per_mz
    grid_lo = mzs_fs.min() - pad
    npts = int(np.ceil((mzs_fs.max() + pad - grid_lo) / step)) + 1
    half = int(np.ceil(pad / step))
    centers = np.rint((mzs_fs - grid_lo) / step).astype(np.int64)
    # COMPACT grid: states cluster at ~1/|z| isotope spacings, so >80% of
    # the full [lo, hi] grid is exactly zero (no state within 5 sigma) —
    # yet the zero stretches dominated the wall (local-max scan + arrays
    # over ~50k points for <=4 peaks).  Build the profile only over the
    # union of per-state windows padded by 1 point: every nonzero point
    # AND both its neighbors live inside (gap points have zero profile,
    # zero plateaus can never satisfy the strict right-side maximum test,
    # and the reference semantics truncate each state's contribution at
    # its window edge anyway), so peak indices/values are IDENTICAL to
    # the full-grid scan.  The zero-pad property is ARGUED here (pad
    # points sit outside every truncated window by construction), not
    # runtime-checked; the boundary masking below is what keeps the scan
    # exact even at the clipped grid edges.
    # states (and hence centers) are mass-ascending — fine_structure sorts
    # by mass and the keep mask preserves order — so segments merge with
    # one linear pass, no sort
    assert centers.size == 0 or np.all(np.diff(centers) >= 0)
    s_lo = np.maximum(centers - (half + 1), 0)
    s_hi = np.minimum(centers + (half + 1), npts - 1)
    run_hi = np.maximum.accumulate(s_hi)
    new = np.concatenate([[True], s_lo[1:] > run_hi[:-1] + 1])
    starts = s_lo[new]                       # disjoint covered segments
    ends = run_hi[np.concatenate([new[1:], [True]])]
    seg_off = np.concatenate([[0], np.cumsum(ends[:-1] - starts[:-1] + 1)])
    n_compact = int(seg_off[-1] + (ends[-1] - starts[-1] + 1))
    # each STATE's whole (clipped) window lies inside ONE segment, so the
    # full->compact map is a per-state offset — no per-point searchsorted
    seg_state = np.searchsorted(starts, centers, side="right") - 1
    state_shift = (seg_off - starts)[seg_state]          # (S,)

    # vectorized over states: every state adds a (2*half+1)-point gaussian
    # window (one bincount instead of a Python loop per state)
    # i32 indices: the profile grid is tens of thousands of points (far
    # below 2**31) and the half-width (S, W) index block is the hot
    # allocation — half the bytes of the default i64
    offs = np.arange(-half, half + 1, dtype=np.int32)
    idx = centers.astype(np.int32)[:, None] + offs[None, :]
    if int(centers[0]) < half or int(centers[-1]) + half > npts - 1:
        # out-of-range window points are TRUNCATED (zero contribution),
        # matching the per-state-window semantics — clamping alone would
        # pile tail terms onto profile[0]/profile[-1] at wrong x offsets
        # (ADVICE r2)
        in_range = (idx >= 0) & (idx < npts)
        np.clip(idx, 0, npts - 1, out=idx)
        # same bits as gathering from grid = grid_lo + step*arange(npts):
        # both compute grid_lo + step*k elementwise
        x = (grid_lo + step * idx) - mzs_fs[:, None]
        contrib = np.where(
            in_range,
            abunds_fs[:, None] * np.exp(-0.5 * (x / isocalc_sigma) ** 2), 0.0)
    else:
        # no window is clipped — identical bits without the mask/clip/
        # where passes over the (states, window) block; the in-place ufunc
        # chain runs the exact same op sequence with no extra temporaries.
        # Reachability: centers[0] == rint(pad/step) vs half ==
        # ceil(pad/step), so this path engages when pad/step is integral —
        # true for the shipped defaults (5*0.01 * 10000 = 500) — and
        # configs with fractional pad/step take the exact masked branch
        # above (re-anchoring the grid to force the fast path would change
        # result bits for those configs; not worth it)
        x = step * idx
        x += grid_lo
        x -= mzs_fs[:, None]
        x /= isocalc_sigma
        np.multiply(x, x, out=x)
        x *= -0.5
        np.exp(x, out=x)
        x *= abunds_fs[:, None]
        contrib = x
    # bincount over the raveled (state, window) grid accumulates in the same
    # row-major order as add.at — identical f64 bits (the compact mapping
    # is order-preserving within each bin's collision group)
    cidx = idx + state_shift[:, None]
    profile = np.bincount(cidx.ravel(), weights=contrib.ravel(),
                          minlength=n_compact)

    # local maxima per covered segment; cross-segment neighbors are zero
    mids = (profile[1:-1] >= profile[:-2]) & (profile[1:-1] > profile[2:])
    # mask out compact points that are segment BOUNDARIES (their full-grid
    # neighbors differ from their compact neighbors); their profile is 0
    # except at grid edges, and a boundary point adjacent to a positive
    # interior value can never be a strict local max of the full grid
    # unless it is positive itself — which only happens at the clipped
    # grid edges, exactly where the full scan's mids also excluded
    # (profile[0]/profile[-1] are never scanned)
    bounds_c = np.concatenate([seg_off, seg_off + (ends - starts)])
    interior = np.ones(n_compact, dtype=bool)
    interior[bounds_c] = False
    peak_idx = np.nonzero(mids & interior[1:-1])[0] + 1
    if peak_idx.size == 0:
        peak_idx = np.array([int(np.argmax(profile))])

    # parabolic interpolation around each maximum for sub-grid m/z + height
    y0, y1, y2 = profile[peak_idx - 1], profile[peak_idx], profile[peak_idx + 1]
    denom = y0 - 2 * y1 + y2
    delta = np.where(np.abs(denom) > 0, 0.5 * (y0 - y2) / np.where(denom == 0, 1, denom), 0.0)
    delta = np.clip(delta, -0.5, 0.5)
    # compact -> full-grid index, then the same grid_lo + step*k expression
    # the dense grid used (identical f64 bits)
    seg_of = np.searchsorted(seg_off, peak_idx, side="right") - 1
    full_ix = starts[seg_of] + (peak_idx - seg_off[seg_of])
    peak_mzs = (grid_lo + step * full_ix) + delta * step
    peak_ints = y1 - 0.25 * (y0 - y2) * delta

    # top n_peaks by intensity, then m/z-ascending; normalize max -> 100
    if peak_mzs.size > n_peaks:
        top = np.argsort(peak_ints)[-n_peaks:]
        top.sort()
        peak_mzs, peak_ints = peak_mzs[top], peak_ints[top]
    order = np.argsort(peak_mzs)
    peak_mzs, peak_ints = peak_mzs[order], peak_ints[order]
    peak_ints = 100.0 * peak_ints / peak_ints.max()
    return peak_mzs, peak_ints.astype(np.float64)


@dataclass
class IsotopePatternTable:
    """Device-friendly packed isotope patterns for a list of ions.

    The TPU-native replacement for the reference's ``theor_peaks`` table +
    Spark broadcast (``Formulas.get_sf_peak_*`` [U], SURVEY.md #8): fixed-shape
    (n_ions, max_peaks) arrays, zero-padded, ready to ship to device HBM and
    shard/replicate over the mesh.
    """

    sfs: list[str]            # sum formula per ion
    adducts: list[str]        # adduct per ion
    mzs: np.ndarray           # (n_ions, max_peaks) f64, 0-padded
    ints: np.ndarray          # (n_ions, max_peaks) f64, 0-padded, max=100 per row
    n_valid: np.ndarray       # (n_ions,) i32 — valid peak count per ion
    targets: np.ndarray       # (n_ions,) bool — target (vs decoy) ion

    @property
    def n_ions(self) -> int:
        return self.mzs.shape[0]

    @property
    def max_peaks(self) -> int:
        return self.mzs.shape[1]


def _compute_pattern_worker(args) -> tuple[str, np.ndarray, np.ndarray] | None:
    """Module-level worker for multiprocessing: ((sf, adduct), params)."""
    (sf, adduct), (charge, sigma, pts_per_mz, n_peaks) = args
    try:
        counts = apply_adduct(parse_formula(sf), adduct)
    except FormulaError:
        return None
    mzs, ints = centroids(counts, charge, sigma, pts_per_mz, n_peaks)
    return f"{sf}{adduct}", mzs, ints


# pairs below this count are computed inline (Pool startup isn't worth it)
_PARALLEL_THRESHOLD = 256


class IsocalcWrapper:
    """Same responsibility & knobs as the reference class of the same name [U].

    ``cache_dir`` (optional) persists computed patterns per parameter-set, the
    analog of the cross-job ``theor_peaks`` cache: only (formula, adduct)
    pairs missing from the cache are recomputed.  Two round-2 changes
    (VERDICT r1 item 5):

    - **Parallel generation**: large missing sets fan out over a
      ``multiprocessing.Pool`` — the analog of the reference's
      ``sc.parallelize(pairs).flatMap(isotope_peaks)`` [U]
      (``theor_peaks_gen.py``, SURVEY.md #7); pattern math is pure NumPy and
      embarrassingly parallel.  ``n_procs`` caps workers (default: all cores;
      env ``SM_ISOCALC_PROCS`` overrides).
    - **Incremental cache shards**: each save writes only the NEW entries to
      a fresh ``theor_peaks_<key>_<n>.npz`` shard instead of rewriting the
      whole store (formerly O(cache^2) bytes across a campaign); loads read
      every shard; shards are compacted into one file past a threshold.
    """

    _COMPACT_SHARDS = 64

    def __init__(
        self,
        cfg: IsotopeGenerationConfig,
        cache_dir: str | Path | None = None,
        n_procs: int | None = None,
    ):
        self.cfg = cfg
        self.cache_dir = Path(cache_dir) if cache_dir else None
        self.n_procs = n_procs
        self._cache: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        self._dirty: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        if self.cache_dir is not None:
            self.cache_dir.mkdir(parents=True, exist_ok=True)
            for path in self._shard_paths():
                # tolerate (a) a concurrent compactor unlinking a shard
                # between the glob and the load, (b) a corrupt/truncated
                # shard from a crashed writer — skip it; entries recompute
                try:
                    self._cache.update(self._load_shard(path))
                except (FileNotFoundError, zipfile.BadZipFile, ValueError, OSError) as e:
                    logger.warning("skipping unreadable isocalc shard %s: %s", path, e)

    @staticmethod
    def _load_shard(path) -> dict:
        """{ion: (mzs, ints)} from one cache shard.  Stacked format: 4
        arrays total (2 zip members per ion made a 21k-ion warm load take
        ~30 s); legacy per-ion-member shards still read."""
        out: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        with np.load(path, allow_pickle=False) as z:
            if "ions" in z.files:
                ions, lens = z["ions"], z["lens"]
                mzs, ints = z["mzs"], z["ints"]
                for i, ion in enumerate(ions):
                    ln = int(lens[i])
                    out[str(ion)] = (mzs[i, :ln].copy(), ints[i, :ln].copy())
            else:  # legacy per-ion-member shard
                for k in z.files:
                    if k.endswith("/mzs"):
                        ion = k[: -len("/mzs")]
                        out[ion] = (z[k], z[ion + "/ints"])
        return out

    def _param_key(self) -> str:
        c = self.cfg
        blob = json.dumps(
            [c.charge, c.isocalc_sigma, c.isocalc_pts_per_mz, c.n_peaks], sort_keys=True
        )
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    def _shard_paths(self) -> list[Path]:
        return sorted(self.cache_dir.glob(f"theor_peaks_{self._param_key()}*.npz"))

    @staticmethod
    def _stack_entries(entries: dict) -> dict[str, np.ndarray]:
        """Pack {ion: (mzs, ints)} into 4 stacked arrays (one npz member per
        ion scales zip overhead with cache size; stacked, a 21k-ion load
        drops from ~30 s to well under a second)."""
        ions = list(entries)
        width = max((entries[i][0].size for i in ions), default=1)
        n = len(ions)
        lens = np.zeros(n, dtype=np.int32)
        mzs = np.zeros((n, width), dtype=np.float64)
        ints = np.zeros((n, width), dtype=np.float64)
        for i, ion in enumerate(ions):
            m, t = entries[ion]
            lens[i] = m.size
            mzs[i, : m.size] = m
            ints[i, : t.size] = t
        return {"ions": np.array(ions), "lens": lens, "mzs": mzs, "ints": ints}

    def save_cache(self) -> None:
        """Persist NEW entries as one incremental shard (atomic rename)."""
        if self.cache_dir is None or not self._dirty:
            return
        import os
        import uuid

        # tmp names use a "tmp_" PREFIX so the constructor's
        # "theor_peaks_*" glob never sees a half-written file (np.savez
        # force-appends .npz, so a suffix-based tmp would still match and a
        # crashed/concurrent save would brick the cache with BadZipFile)
        shard = self.cache_dir / (
            f"theor_peaks_{self._param_key()}_{uuid.uuid4().hex[:8]}.npz")
        tmp = self.cache_dir / f"tmp_{uuid.uuid4().hex[:8]}.npz"
        np.savez(tmp, **self._stack_entries(self._dirty))
        os.replace(tmp, shard)
        self._dirty = {}
        shards = self._shard_paths()
        if len(shards) > self._COMPACT_SHARDS:
            # merge from the shard FILES, not this process's in-memory view:
            # a concurrent process may have written shards since our init,
            # and compacting from _cache alone would silently drop them
            merged: dict[str, tuple[np.ndarray, np.ndarray]] = {}
            for path in shards:
                try:
                    merged.update(self._load_shard(path))
                except Exception:
                    continue  # shard a concurrent compactor already removed
            merged.update(self._cache)
            base = self.cache_dir / f"theor_peaks_{self._param_key()}.npz"
            tmp = self.cache_dir / f"tmp_{uuid.uuid4().hex[:8]}.npz"
            np.savez(tmp, **self._stack_entries(merged))
            # replace base BEFORE unlinking shards: a kill in between loses
            # no entries (shards are only dropped once base holds them all)
            os.replace(tmp, base)
            for s in shards:
                if s != base:
                    s.unlink(missing_ok=True)  # concurrent compactor race

    def _params(self) -> tuple:
        c = self.cfg
        return (c.charge, c.isocalc_sigma, c.isocalc_pts_per_mz, c.n_peaks)

    def isotope_peaks(self, sf: str, adduct: str) -> tuple[np.ndarray, np.ndarray] | None:
        """Centroided (mzs, ints) for formula+adduct, or None if the chemistry
        is invalid (e.g. '-H' from an H-free formula) — the reference skips
        such ions the same way [U]."""
        ion = f"{sf}{adduct}"
        hit = self._cache.get(ion)
        if hit is not None:
            return hit
        out = _compute_pattern_worker(((sf, adduct), self._params()))
        if out is None:
            return None
        _, mzs, ints = out
        self._cache[ion] = (mzs, ints)
        self._dirty[ion] = (mzs, ints)
        return mzs, ints

    def _compute_missing(self, pairs: list[tuple[str, str]]) -> None:
        """Fill the cache for every missing pair, fanning out when large."""
        missing = [p for p in pairs
                   if f"{p[0]}{p[1]}" not in self._cache]
        missing = list(dict.fromkeys(missing))
        if not missing:
            return
        import os

        n_procs = self.n_procs or int(os.environ.get(
            "SM_ISOCALC_PROCS", os.cpu_count() or 1))
        if len(missing) < _PARALLEL_THRESHOLD or n_procs <= 1:
            for sf, adduct in missing:
                self.isotope_peaks(sf, adduct)
            return
        from multiprocessing import get_context

        params = self._params()
        work = [((sf, adduct), params) for sf, adduct in missing]
        chunk = max(8, len(work) // (n_procs * 8))
        # spawn, not fork: the engine process may already have initialized
        # JAX (daemon reuse), and fork() of a multithreaded process can
        # deadlock.  The worker's import chain is numpy-only, so spawn
        # startup is cheap relative to a >=256-pattern batch.
        with get_context("spawn").Pool(n_procs) as pool:
            for out in pool.imap_unordered(_compute_pattern_worker, work, chunk):
                if out is None:
                    continue
                ion, mzs, ints = out
                self._cache[ion] = (mzs, ints)
                self._dirty[ion] = (mzs, ints)

    def pattern_table(
        self,
        sf_adduct_pairs: list[tuple[str, str]],
        target_flags: list[bool] | None = None,
    ) -> IsotopePatternTable:
        """Compute/load patterns for all pairs and pack them into fixed-shape
        arrays (invalid-chemistry ions are dropped, like the reference)."""
        max_peaks = self.cfg.n_peaks
        self._compute_missing(list(sf_adduct_pairs))
        kept_sfs: list[str] = []
        kept_adducts: list[str] = []
        kept_targets: list[bool] = []
        rows_mz: list[np.ndarray] = []
        rows_int: list[np.ndarray] = []
        n_valid: list[int] = []
        flags = target_flags if target_flags is not None else [True] * len(sf_adduct_pairs)
        for (sf, adduct), is_target in zip(sf_adduct_pairs, flags):
            peaks = self._cache.get(f"{sf}{adduct}")
            if peaks is None:
                continue
            mzs, ints = peaks
            k = min(mzs.size, max_peaks)
            mz_row = np.zeros(max_peaks)
            int_row = np.zeros(max_peaks)
            mz_row[:k] = mzs[:k]
            int_row[:k] = ints[:k]
            kept_sfs.append(sf)
            kept_adducts.append(adduct)
            kept_targets.append(is_target)
            rows_mz.append(mz_row)
            rows_int.append(int_row)
            n_valid.append(k)
        self.save_cache()
        return IsotopePatternTable(
            sfs=kept_sfs,
            adducts=kept_adducts,
            mzs=np.array(rows_mz).reshape(len(rows_mz), max_peaks),
            ints=np.array(rows_int).reshape(len(rows_int), max_peaks),
            n_valid=np.array(n_valid, dtype=np.int32),
            targets=np.array(kept_targets, dtype=bool),
        )
