"""Theoretical isotope-pattern calculation (the reference's IsocalcWrapper).

Reference: ``sm/engine/isocalc_wrapper.py::IsocalcWrapper.isotope_peaks`` [U]
(SURVEY.md #6) wraps ``pyMSpec.pyisocalc``: exact isotopic fine structure →
gaussian blur at instrument resolution (``isocalc_sigma``,
``isocalc_pts_per_mz``) → centroid detection → top-``n_peaks`` centroided
(mzs[], ints[]) per (formula, adduct), intensities normalized to max=100.

We implement the same algorithm natively on NumPy (host-side precompute; the
result is packed into a device-resident tensor, see ``IsotopePatternTable``).
The per-(config) disk cache plays the role of the reference's ``theor_peaks``
Postgres table — a persistent cross-job cache where only missing
(formula, adduct) pairs are recomputed (``theor_peaks_gen.py`` [U],
SURVEY.md #7 and §5.4).

ISSUE 3 rebuilt COLD generation (this was 94.5% of the BASELINE #3 wall)
as a three-layer pipeline — a deterministic-chunk process pool with
CRC32-checksummed incremental cache shards and crash/retry failpoint
seams (``PatternStream``), an opt-in batched XLA blur->centroid stage
(ops/isocalc_jax.py), and incremental row publication so scoring can
overlap generation — see docs/ISOCALC.md.  The per-pattern math below is
unchanged and bit-identical to round 5.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import zipfile
import zlib
from dataclasses import dataclass
from functools import lru_cache
from pathlib import Path

import numpy as np

from . import elements
from .formula import FormulaError, apply_adduct, parse_formula
from ..utils import tracing
from ..utils.config import IsotopeGenerationConfig
from ..utils.failpoints import failpoint, record_recovery, register_failpoint
from ..utils.logger import logger

# fine-structure pruning: drop states below this relative abundance
_PRUNE_ABUNDANCE = 1e-10
# merge fine-structure states closer than this [Da] (well below any
# instrument sigma we blur with; keeps convolutions small)
_MERGE_DA = 1e-5
# cap on states kept per convolution (keeps worst-case formulas bounded)
_MAX_STATES = 4096


def _merge_states(masses: np.ndarray, abunds: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Sort by mass; merge states within _MERGE_DA (abundance-weighted mass)."""
    order = np.argsort(masses)
    masses, abunds = masses[order], abunds[order]
    # group indices: new group wherever the gap exceeds the merge width
    group = np.concatenate([[0], np.cumsum(np.diff(masses) > _MERGE_DA)])
    n = group[-1] + 1
    # bincount == add.at here (same left-to-right accumulation order, so
    # identical f64 bits) at a fraction of the cost — add.at's unbuffered
    # ufunc loop was the fine-structure hot spot
    ab = np.bincount(group, weights=abunds, minlength=n)
    wm = np.bincount(group, weights=masses * abunds, minlength=n)
    return wm / ab, ab


def _prune(masses: np.ndarray, abunds: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    keep = abunds > _PRUNE_ABUNDANCE * abunds.max()
    masses, abunds = masses[keep], abunds[keep]
    if masses.size > _MAX_STATES:
        keep = np.argsort(abunds)[-_MAX_STATES:]
        keep.sort()
        masses, abunds = masses[keep], abunds[keep]
    return masses, abunds


def _convolve(a: tuple[np.ndarray, np.ndarray], b: tuple[np.ndarray, np.ndarray]):
    m = (a[0][:, None] + b[0][None, :]).ravel()
    p = (a[1][:, None] * b[1][None, :]).ravel()
    return _prune(*_merge_states(m, p))


@lru_cache(maxsize=8192)
def _element_distribution(el: str, n: int) -> tuple[np.ndarray, np.ndarray]:
    """Isotope distribution of n atoms of el, by exponentiation-by-squaring.

    Cached per (element, count): across a molecular DB the same (el, n)
    pairs recur constantly (profiled at 30% of pattern wall-clock when
    recomputed per formula — the cache is exact, the arrays are treated
    as read-only by every consumer).  Each worker process builds its own
    cache (cheap relative to a >=256-pattern batch)."""
    isos = elements.ISOTOPES[el]
    base = (np.array([m for m, _ in isos]), np.array([a for _, a in isos]))
    result: tuple[np.ndarray, np.ndarray] | None = None
    sq = base
    while n > 0:
        if n & 1:
            result = sq if result is None else _convolve(result, sq)
        n >>= 1
        if n:
            sq = _convolve(sq, sq)
    assert result is not None
    return result


def fine_structure(counts: dict[str, int]) -> tuple[np.ndarray, np.ndarray]:
    """Exact isotopic fine structure of a neutral molecule: (masses, abundances),
    sorted by mass, abundances summing to ~1 (minus pruned tail)."""
    acc: tuple[np.ndarray, np.ndarray] | None = None
    for el, n in sorted(counts.items()):
        dist = _element_distribution(el, n)
        acc = dist if acc is None else _convolve(acc, dist)
    assert acc is not None
    return acc


def centroids(
    counts: dict[str, int],
    charge: int,
    isocalc_sigma: float,
    isocalc_pts_per_mz: int,
    n_peaks: int,
) -> tuple[np.ndarray, np.ndarray]:
    """Centroided theoretical pattern of the ION with the given atom counts.

    Returns (mzs, ints): up to ``n_peaks`` peaks sorted by m/z ascending,
    intensities normalized so the strongest peak is 100.0 (the pyisocalc
    convention the reference stores in theor_peaks [U]).
    """
    masses, abunds = fine_structure(counts)
    # ion m/z per fine-structure state
    mzs_fs = (masses - charge * elements.ELECTRON_MASS) / abs(charge)

    # Only the low-mass end can contribute the top peaks: blurring merges
    # states within ~sigma, and isotope peaks are ~1/|z| apart. Keep a margin
    # of n_peaks+2 isotope spacings above the monoisotopic state.
    lo = mzs_fs.min()
    window = (n_peaks + 2) / abs(charge)
    keep = mzs_fs <= lo + window
    mzs_fs, abunds_fs = mzs_fs[keep], abunds[keep]

    # profile grid at pts_per_mz resolution, padded by 5 sigma
    pad = 5.0 * isocalc_sigma
    step = 1.0 / isocalc_pts_per_mz
    grid_lo = mzs_fs.min() - pad
    npts = int(np.ceil((mzs_fs.max() + pad - grid_lo) / step)) + 1
    half = int(np.ceil(pad / step))
    centers = np.rint((mzs_fs - grid_lo) / step).astype(np.int64)
    # COMPACT grid: states cluster at ~1/|z| isotope spacings, so >80% of
    # the full [lo, hi] grid is exactly zero (no state within 5 sigma) —
    # yet the zero stretches dominated the wall (local-max scan + arrays
    # over ~50k points for <=4 peaks).  Build the profile only over the
    # union of per-state windows padded by 1 point: every nonzero point
    # AND both its neighbors live inside (gap points have zero profile,
    # zero plateaus can never satisfy the strict right-side maximum test,
    # and the reference semantics truncate each state's contribution at
    # its window edge anyway), so peak indices/values are IDENTICAL to
    # the full-grid scan.  The zero-pad property is ARGUED here (pad
    # points sit outside every truncated window by construction), not
    # runtime-checked; the boundary masking below is what keeps the scan
    # exact even at the clipped grid edges.
    # states (and hence centers) are mass-ascending — fine_structure sorts
    # by mass and the keep mask preserves order — so segments merge with
    # one linear pass, no sort
    assert centers.size == 0 or np.all(np.diff(centers) >= 0)
    s_lo = np.maximum(centers - (half + 1), 0)
    s_hi = np.minimum(centers + (half + 1), npts - 1)
    run_hi = np.maximum.accumulate(s_hi)
    new = np.concatenate([[True], s_lo[1:] > run_hi[:-1] + 1])
    starts = s_lo[new]                       # disjoint covered segments
    ends = run_hi[np.concatenate([new[1:], [True]])]
    seg_off = np.concatenate([[0], np.cumsum(ends[:-1] - starts[:-1] + 1)])
    n_compact = int(seg_off[-1] + (ends[-1] - starts[-1] + 1))
    # each STATE's whole (clipped) window lies inside ONE segment, so the
    # full->compact map is a per-state offset — no per-point searchsorted
    seg_state = np.searchsorted(starts, centers, side="right") - 1
    state_shift = (seg_off - starts)[seg_state]          # (S,)

    # vectorized over states: every state adds a (2*half+1)-point gaussian
    # window (one bincount instead of a Python loop per state)
    # i32 indices: the profile grid is tens of thousands of points (far
    # below 2**31) and the half-width (S, W) index block is the hot
    # allocation — half the bytes of the default i64
    offs = np.arange(-half, half + 1, dtype=np.int32)
    idx = centers.astype(np.int32)[:, None] + offs[None, :]
    if int(centers[0]) < half or int(centers[-1]) + half > npts - 1:
        # out-of-range window points are TRUNCATED (zero contribution),
        # matching the per-state-window semantics — clamping alone would
        # pile tail terms onto profile[0]/profile[-1] at wrong x offsets
        # (ADVICE r2)
        in_range = (idx >= 0) & (idx < npts)
        np.clip(idx, 0, npts - 1, out=idx)
        # same bits as gathering from grid = grid_lo + step*arange(npts):
        # both compute grid_lo + step*k elementwise
        x = (grid_lo + step * idx) - mzs_fs[:, None]
        contrib = np.where(
            in_range,
            abunds_fs[:, None] * np.exp(-0.5 * (x / isocalc_sigma) ** 2), 0.0)
    else:
        # no window is clipped — identical bits without the mask/clip/
        # where passes over the (states, window) block; the in-place ufunc
        # chain runs the exact same op sequence with no extra temporaries.
        # Reachability: centers[0] == rint(pad/step) vs half ==
        # ceil(pad/step), so this path engages when pad/step is integral —
        # true for the shipped defaults (5*0.01 * 10000 = 500) — and
        # configs with fractional pad/step take the exact masked branch
        # above (re-anchoring the grid to force the fast path would change
        # result bits for those configs; not worth it)
        x = step * idx
        x += grid_lo
        x -= mzs_fs[:, None]
        x /= isocalc_sigma
        np.multiply(x, x, out=x)
        x *= -0.5
        np.exp(x, out=x)
        x *= abunds_fs[:, None]
        contrib = x
    # bincount over the raveled (state, window) grid accumulates in the same
    # row-major order as add.at — identical f64 bits (the compact mapping
    # is order-preserving within each bin's collision group)
    cidx = idx + state_shift[:, None]
    profile = np.bincount(cidx.ravel(), weights=contrib.ravel(),
                          minlength=n_compact)

    # local maxima per covered segment; cross-segment neighbors are zero
    mids = (profile[1:-1] >= profile[:-2]) & (profile[1:-1] > profile[2:])
    # mask out compact points that are segment BOUNDARIES (their full-grid
    # neighbors differ from their compact neighbors); their profile is 0
    # except at grid edges, and a boundary point adjacent to a positive
    # interior value can never be a strict local max of the full grid
    # unless it is positive itself — which only happens at the clipped
    # grid edges, exactly where the full scan's mids also excluded
    # (profile[0]/profile[-1] are never scanned)
    bounds_c = np.concatenate([seg_off, seg_off + (ends - starts)])
    interior = np.ones(n_compact, dtype=bool)
    interior[bounds_c] = False
    peak_idx = np.nonzero(mids & interior[1:-1])[0] + 1
    if peak_idx.size == 0:
        peak_idx = np.array([int(np.argmax(profile))])

    # parabolic interpolation around each maximum for sub-grid m/z + height
    y0, y1, y2 = profile[peak_idx - 1], profile[peak_idx], profile[peak_idx + 1]
    denom = y0 - 2 * y1 + y2
    delta = np.where(np.abs(denom) > 0, 0.5 * (y0 - y2) / np.where(denom == 0, 1, denom), 0.0)
    delta = np.clip(delta, -0.5, 0.5)
    # compact -> full-grid index, then the same grid_lo + step*k expression
    # the dense grid used (identical f64 bits)
    seg_of = np.searchsorted(seg_off, peak_idx, side="right") - 1
    full_ix = starts[seg_of] + (peak_idx - seg_off[seg_of])
    peak_mzs = (grid_lo + step * full_ix) + delta * step
    peak_ints = y1 - 0.25 * (y0 - y2) * delta

    # top n_peaks by intensity, then m/z-ascending; normalize max -> 100
    if peak_mzs.size > n_peaks:
        top = np.argsort(peak_ints)[-n_peaks:]
        top.sort()
        peak_mzs, peak_ints = peak_mzs[top], peak_ints[top]
    order = np.argsort(peak_mzs)
    peak_mzs, peak_ints = peak_mzs[order], peak_ints[order]
    peak_ints = 100.0 * peak_ints / peak_ints.max()
    return peak_mzs, peak_ints.astype(np.float64)


@dataclass
class IsotopePatternTable:
    """Device-friendly packed isotope patterns for a list of ions.

    The TPU-native replacement for the reference's ``theor_peaks`` table +
    Spark broadcast (``Formulas.get_sf_peak_*`` [U], SURVEY.md #8): fixed-shape
    (n_ions, max_peaks) arrays, zero-padded, ready to ship to device HBM and
    shard/replicate over the mesh.
    """

    sfs: list[str]            # sum formula per ion
    adducts: list[str]        # adduct per ion
    mzs: np.ndarray           # (n_ions, max_peaks) f64, 0-padded
    ints: np.ndarray          # (n_ions, max_peaks) f64, 0-padded, max=100 per row
    n_valid: np.ndarray       # (n_ions,) i32 — valid peak count per ion
    targets: np.ndarray       # (n_ions,) bool — target (vs decoy) ion

    @property
    def n_ions(self) -> int:
        return self.mzs.shape[0]

    @property
    def max_peaks(self) -> int:
        return self.mzs.shape[1]


# Version salt for pairs-based checkpoint fingerprints (models/msm_basic.py
# hashes it instead of the full pattern table when scoring overlaps
# generation).  BUMP THIS whenever centroids()/fine_structure() change
# result bits — a stale value lets an old mid-search checkpoint resume
# against silently different patterns.
ISOCALC_PATTERN_VERSION = 1

# ---------------------------------------------------------------------------
# fine-structure segments (shared host prep for the device blur stage)
#
# Windowed states cluster at isotope spacings (~1/|z| Da) while the blur
# support is only 5*sigma, so the profile decomposes into a handful of short
# independent segments.  The device stage (ops/isocalc_jax.py) evaluates each
# segment DENSELY — profile[l] = sum_s ab_s * exp(-((g_l - m_s)/sigma)^2 / 2)
# — which needs no scatter (the XLA-CPU scatter formulation measured 5x
# SLOWER than numpy; the dense segment one measured ~3x faster).

# per-segment grid cap (points).  At the shipped 10k pts/mz this allows a
# ~53 mDa state span per segment; typical isotope clusters span a few mDa.
SEGMENT_GRID_CAP = 1536


def fine_structure_segments(
    counts: dict[str, int],
    charge: int,
    isocalc_sigma: float,
    isocalc_pts_per_mz: int,
    n_peaks: int,
) -> list[tuple[float, np.ndarray, np.ndarray, int]] | None:
    """Windowed ion fine structure, split into blur-independent segments.

    Returns ``[(seg_lo, m_rel, abunds, npts), ...]`` — per segment the f64
    grid origin (min state - 5 sigma), state positions relative to it, their
    abundances, and the segment grid length — or ``None`` when the ion does
    not fit the device stage's static caps (over ``n_peaks + 4`` segments, or
    a segment wider than SEGMENT_GRID_CAP): such heavy ions take the exact
    NumPy oracle instead.

    Segments are cut where the state gap exceeds ``2*pad + 2*step``: beyond
    that distance the oracle's truncated per-state windows cannot reach
    across the cut either, so evaluating segments independently drops only
    contributions the oracle drops too.
    """
    masses, abunds = fine_structure(counts)
    mzs = (masses - charge * elements.ELECTRON_MASS) / abs(charge)
    lo = mzs.min()
    keep = mzs <= lo + (n_peaks + 2) / abs(charge)
    mzs, abunds = mzs[keep], abunds[keep]
    step = 1.0 / isocalc_pts_per_mz
    pad = 5.0 * isocalc_sigma
    cuts = np.nonzero(np.diff(mzs) > 2 * pad + 2 * step)[0] + 1
    segs: list[tuple[float, np.ndarray, np.ndarray, int]] = []
    for s, e in zip(np.r_[0, cuts], np.r_[cuts, mzs.size]):
        m, a = mzs[s:e], abunds[s:e]
        seg_lo = float(m[0]) - pad
        npts = int(np.ceil((m[-1] + pad - seg_lo) / step)) + 1
        if npts > SEGMENT_GRID_CAP:
            return None
        segs.append((seg_lo, m - seg_lo, a, npts))
    if len(segs) > n_peaks + 4:
        return None
    return segs


# ---------------------------------------------------------------------------
# chunked generation engine (ISSUE 3 tentpole, layer 1)

FP_ISO_WORKER = register_failpoint(
    "isocalc.worker",
    "per-chunk isotope-pattern compute (pool-worker crash / chunk retry)")
FP_ISO_SHARD_SAVE = register_failpoint(
    "isocalc.shard_save",
    "between an isocalc cache shard's tmp savez and its os.replace")
FP_ISO_SHARD_LOAD = register_failpoint(
    "isocalc.shard_load",
    "per isocalc cache shard read at wrapper init (I/O error path)")

# pairs below this count are computed inline (pool startup isn't worth it)
_PARALLEL_THRESHOLD = 256
# (formula, adduct) pairs per work chunk == per incremental cache shard.
# Deterministic: serial and pooled generation use the SAME chunking, so
# shard boundaries (and bytes) are identical.  SM_ISOCALC_CHUNK overrides.
_DEFAULT_CHUNK = 2048
# pool rebuild attempts after a worker crash before falling back to inline
_POOL_ATTEMPTS = 2


def _chunk_size(configured: int = 0) -> int:
    import os

    if configured > 0:
        return configured
    return max(1, int(os.environ.get("SM_ISOCALC_CHUNK", _DEFAULT_CHUNK)))


def _pool_init(failpoint_spec: str | None) -> None:
    """Spawned-worker initializer: arm the parent's programmatic failpoint
    spec (env-var specs arrive via inheritance at import instead)."""
    if failpoint_spec:
        from ..utils import failpoints

        failpoints.configure(failpoint_spec)


def _compute_pattern_worker(args) -> tuple[str, np.ndarray, np.ndarray] | None:
    """Module-level worker for single-ion calls: ((sf, adduct), params)."""
    (sf, adduct), (charge, sigma, pts_per_mz, n_peaks) = args
    try:
        counts = apply_adduct(parse_formula(sf), adduct)
    except FormulaError:
        return None
    mzs, ints = centroids(counts, charge, sigma, pts_per_mz, n_peaks)
    return f"{sf}{adduct}", mzs, ints


def _compute_chunk(args):
    """Compute one deterministic chunk of (sf, adduct) pairs.

    Runs in a spawned pool worker (large jobs) or inline (small jobs / the
    after-retries fallback).  Returns ``(ci, outputs, trace_records)`` where
    each output is

    - ``("pat", ion, mzs, ints)`` — a finished host-computed pattern, or
    - ``("seg", ion, segments)`` — fine-structure segments for the device
      blur->centroid stage (device mode; heavy ions still arrive as "pat"
      via the exact oracle), or
    - ``None`` for invalid chemistry (callers pre-validate, so only single-
      ion paths ever see it).

    ``trace_records`` (ISSUE 5): when the driver passed a wire trace
    context, the chunk's span is recorded into a capture buffer — the
    worker process has no sinks — and returned for the driver to emit
    ("re-parented on return"; a crashed worker's records die with it, and
    the retried chunk traces again).
    """
    ci, pairs, params, device, wire = args
    ctx = tracing.TraceContext.from_wire(wire)
    if ctx is None:
        return ci, _compute_chunk_body(ci, pairs, params, device), []
    with tracing.capture() as records:
        with tracing.span("isocalc_chunk", ctx=ctx, ci=ci,
                          n_pairs=len(pairs), worker_pid=os.getpid()):
            out = _compute_chunk_body(ci, pairs, params, device)
    return ci, out, records


def _compute_chunk_body(ci, pairs, params, device):
    failpoint(FP_ISO_WORKER)
    charge, sigma, pts_per_mz, n_peaks = params
    out = []
    for sf, adduct in pairs:
        try:
            counts = apply_adduct(parse_formula(sf), adduct)
        except FormulaError:
            out.append(None)
            continue
        ion = f"{sf}{adduct}"
        if device:
            segs = fine_structure_segments(
                counts, charge, sigma, pts_per_mz, n_peaks)
            if segs is not None:
                out.append(("seg", ion, segs))
                continue
        mzs, ints = centroids(counts, charge, sigma, pts_per_mz, n_peaks)
        out.append(("pat", ion, mzs, ints))
    return out


# -- progress / metrics hooks (mirrors utils/failpoints.attach_metrics) ------

_metrics_lock = threading.Lock()
_metrics_registry = None
_patterns_total = 0


def attach_metrics(registry) -> None:
    """Export generation counters through a service ``MetricsRegistry``:
    ``sm_isocalc_patterns_total`` plus per-stream worker/rate gauges."""
    global _metrics_registry
    with _metrics_lock:
        _metrics_registry = registry
        total = _patterns_total
    c = registry.counter("sm_isocalc_patterns_total",
                         "Isotope patterns computed (cold, not cache hits)")
    if total:
        c.inc(total)


def patterns_total() -> int:
    """Monotone count of cold-computed patterns (service rate collector)."""
    with _metrics_lock:
        return _patterns_total


def _count_patterns(n: int, workers: int, rate: float) -> None:
    global _patterns_total
    with _metrics_lock:
        _patterns_total += n
        reg = _metrics_registry
    if reg is not None:
        reg.counter("sm_isocalc_patterns_total",
                    "Isotope patterns computed (cold, not cache hits)").inc(n)
        reg.gauge("sm_isocalc_workers",
                  "Process-pool size of the last isocalc generation"
                  ).set(workers)
        reg.gauge("sm_isocalc_patterns_per_s",
                  "Throughput of the current/last isocalc generation"
                  ).set(rate)


class PatternStream:
    """A running isotope-pattern generation (ISSUE 3 tentpole).

    Owns the three-layer cold path: a deterministic chunking of the missing
    (formula, adduct) work-list fanned out over a spawn ProcessPoolExecutor
    (layer 1), an optional batched device blur->centroid stage consuming the
    workers' fine-structure segments (layer 2), and incremental row
    publication — completed chunks commit a CRC32-checksummed cache shard
    and fill their rows of the final table arrays, advancing ``ready_rows``
    so a consumer can score the leading checkpoint groups while later
    patterns are still computing (layer 3).

    Chunk results are committed strictly in chunk order (out-of-order pool
    completions buffer in memory), so the shard sequence and every byte in
    it are identical between serial and pooled runs, and a crash leaves a
    clean shard prefix for the rerun to resume from.
    """

    # smlint guarded-by registry (docs/ANALYSIS.md): the publication
    # frontier + stream terminal state move only under _cond (row arrays
    # themselves are single-writer, published via the _ready_rows barrier)
    _GUARDED_BY = {"_ready_rows": "_cond", "_row_done": "_cond",
                   "_error": "_cond", "_done": "_cond"}

    def __init__(self, wrapper: "IsocalcWrapper",
                 pairs: list[tuple[str, str]],
                 flags: list[bool] | None):
        self.wrapper = wrapper
        if flags is None:
            flags = [True] * len(pairs)
        # dedup (first occurrence wins, like the reference) + validate
        # chemistry up front: the final table row order is then fixed before
        # any pattern exists, which is what lets scoring overlap generation
        seen: set[tuple[str, str]] = set()
        self.sfs: list[str] = []
        self.adducts: list[str] = []
        targets: list[bool] = []
        for (sf, adduct), flag in zip(pairs, flags):
            key = (sf, adduct)
            if key in seen:
                continue
            seen.add(key)
            try:
                apply_adduct(parse_formula(sf), adduct)
            except FormulaError:
                continue
            self.sfs.append(sf)
            self.adducts.append(adduct)
            targets.append(flag)
        self.targets = np.array(targets, dtype=bool)
        n = len(self.sfs)
        k = wrapper.cfg.n_peaks
        self.mzs = np.zeros((n, k))
        self.ints = np.zeros((n, k))
        self.n_valid = np.zeros(n, dtype=np.int32)
        self._row_done = np.zeros(n, dtype=bool)
        self._ready_rows = 0
        self._cond = threading.Condition()
        self._error: BaseException | None = None
        self._done = False
        self._cancel = threading.Event()
        self.gen_seconds = 0.0
        self.workers = 1
        self.patterns_per_s = 0.0
        self.cold_patterns = 0

        row_of = {f"{sf}{ad}": i
                  for i, (sf, ad) in enumerate(zip(self.sfs, self.adducts))}
        self._row_of = row_of
        missing: list[tuple[str, str]] = []
        with wrapper._lock:
            for sf, ad in zip(self.sfs, self.adducts):
                hit = wrapper._cache.get(f"{sf}{ad}")
                if hit is None:
                    missing.append((sf, ad))
                else:
                    self._fill_row_locked(row_of[f"{sf}{ad}"], *hit)
        self._advance_prefix_locked()
        chunk = _chunk_size(wrapper.chunk_size)
        self._chunks = [missing[s: s + chunk]
                        for s in range(0, len(missing), chunk)]
        self.n_missing = len(missing)
        # deterministic job tag: chunk shards of the same missing set (e.g.
        # a rerun after a crash) land on the SAME filenames — idempotent
        self._job_tag = hashlib.sha256(
            "\x00".join(f"{sf}{ad}" for sf, ad in missing).encode()
        ).hexdigest()[:8]
        # thread hop: generation runs in its own thread — capture the
        # caller's trace context so chunk/worker spans land in the job trace
        self._trace = tracing.current()
        self._thread = threading.Thread(
            target=self._run, name="isocalc-stream", daemon=True)
        self._thread.start()

    # -- consumer side -------------------------------------------------------

    @property
    def n_ions(self) -> int:
        return len(self.sfs)

    def ready_rows(self) -> int:
        with self._cond:
            return self._ready_rows

    def wait_rows(self, n: int, timeout: float | None = None) -> int:
        """Block until the first ``n`` table rows have patterns (or the
        stream errors — re-raised here)."""
        n = min(n, self.n_ions)
        with self._cond:
            self._cond.wait_for(
                lambda: self._ready_rows >= n or self._error is not None,
                timeout)
            if self._error is not None:
                raise self._error
            return self._ready_rows

    def table_view(self) -> "IsotopePatternTable":
        """The final table object over the stream's SHARED row arrays —
        valid up to ``ready_rows()`` while generation runs, complete once
        the stream finishes.  Lets a consumer score leading rows in place
        (ISSUE 3 layer 3)."""
        return IsotopePatternTable(
            sfs=self.sfs, adducts=self.adducts,
            mzs=self.mzs, ints=self.ints,
            n_valid=self.n_valid, targets=self.targets,
        )

    def result_table(self) -> "IsotopePatternTable":
        """Block until generation completes; return the packed table."""
        self._thread.join()
        with self._cond:
            if self._error is not None:
                raise self._error
        return self.table_view()

    def cancel(self) -> None:
        """Abort generation (job failed upstream): stop submitting chunks,
        drop pending work, join the driver thread."""
        self._cancel.set()
        self._thread.join()

    # -- generation side -----------------------------------------------------

    def _fill_row_locked(self, row: int, mzs: np.ndarray,
                         ints: np.ndarray) -> None:
        # caller holds self._cond (or is __init__, pre-publication)
        k = min(mzs.size, self.mzs.shape[1])
        self.mzs[row, :k] = mzs[:k]
        self.ints[row, :k] = ints[:k]
        self.n_valid[row] = k
        self._row_done[row] = True

    def _advance_prefix_locked(self) -> None:
        # caller holds self._cond (or is __init__, pre-publication)
        r = self._ready_rows
        n = self.n_ions
        while r < n and self._row_done[r]:
            r += 1
        self._ready_rows = r

    def _run(self) -> None:
        import time

        t0 = time.perf_counter()
        try:
            if self._chunks:
                with tracing.attach(self._trace), \
                        tracing.span("isocalc_gen", missing=self.n_missing,
                                     chunks=len(self._chunks)):
                    self._generate()
            with self.wrapper._lock:
                self.wrapper._maybe_compact()
        except BaseException as exc:  # noqa: BLE001 — consumer re-raises
            with self._cond:
                self._error = exc
                self._cond.notify_all()
            return
        self.gen_seconds = time.perf_counter() - t0
        if self.cold_patterns:
            self.patterns_per_s = self.cold_patterns / max(
                self.gen_seconds, 1e-9)
            _count_patterns(0, self.workers, self.patterns_per_s)
        self.wrapper.last_stats = dict(
            cold_patterns=self.cold_patterns,
            seconds=round(self.gen_seconds, 3),
            patterns_per_s=round(self.patterns_per_s, 2),
            workers=self.workers,
            device=self.wrapper.device_blur,
        )
        with self._cond:
            self._done = True
            self._cond.notify_all()

    def _deliver(self, ci: int, outputs: list,
                 records: list | None = None) -> None:
        """Commit one completed chunk: device-finish segment outputs, write
        the chunk's cache shard, fill its table rows, advance the prefix.
        ``records`` are the worker's captured trace spans — emitted here,
        in the driver that owns the sinks (re-parented on return)."""
        import time

        tracing.emit_records(records, tracing.current())
        entries: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        seg_ions = [(o[1], o[2]) for o in outputs
                    if o is not None and o[0] == "seg"]
        if seg_ions:
            finished = self.wrapper._device_stage().centroid_batch(
                [segs for _ion, segs in seg_ions])
            for (ion, _segs), (mzs, ints) in zip(seg_ions, finished):
                entries[ion] = (mzs, ints)
        for o in outputs:
            if o is not None and o[0] == "pat":
                _kind, ion, mzs, ints = o
                entries[ion] = (mzs, ints)
        self.wrapper._commit_chunk_shard(self._job_tag, ci, entries)
        with self._cond:
            for ion, (mzs, ints) in entries.items():
                self._fill_row_locked(self._row_of[ion], mzs, ints)
            self._advance_prefix_locked()
            self._cond.notify_all()
        self.cold_patterns += len(entries)
        now = time.perf_counter()
        if now - self._t_last_log >= 5.0 or ci == len(self._chunks) - 1:
            rate = self.cold_patterns / max(now - self._t_gen0, 1e-9)
            logger.info(
                "isocalc: %d/%d patterns (%.1f patterns/s, %d workers)",
                self.cold_patterns, self.n_missing, rate, self.workers)
            self._t_last_log = now
        _count_patterns(len(entries), self.workers, self.cold_patterns
                        / max(now - self._t_gen0, 1e-9))

    def _generate(self) -> None:
        import os
        import time

        self._t_gen0 = self._t_last_log = time.perf_counter()
        wrapper = self.wrapper
        n_procs = wrapper.n_procs or int(os.environ.get(
            "SM_ISOCALC_PROCS", os.cpu_count() or 1))
        params = wrapper._params()
        device = wrapper.device_blur
        use_pool = (self.n_missing >= _PARALLEL_THRESHOLD and n_procs > 1)
        self.workers = n_procs if use_pool else 1
        buffered: dict[int, tuple] = {}
        next_ci = 0
        # process-hop trace context for workers (ambient here = the
        # isocalc_gen span attached by _run); None keeps workers untraced
        ctx = tracing.current()
        wire = ctx.to_wire() if ctx is not None else None

        def commit_ready() -> None:
            nonlocal next_ci
            while next_ci in buffered:
                outputs, records = buffered.pop(next_ci)
                self._deliver(next_ci, outputs, records)
                next_ci += 1

        if not use_pool:
            for ci, chunk in enumerate(self._chunks):
                if self._cancel.is_set():
                    return
                _ci, outputs, records = _compute_chunk(
                    (ci, chunk, params, device, wire))
                buffered[ci] = (outputs, records)
                commit_ready()
            return

        from concurrent.futures import as_completed
        from concurrent.futures import ProcessPoolExecutor
        from concurrent.futures.process import BrokenProcessPool
        from multiprocessing import get_context
        from ..utils import failpoints

        remaining = set(range(len(self._chunks)))
        spec = failpoints.active_spec()
        # spawn, not fork: the engine process may already have initialized
        # JAX (daemon reuse / device blur), and fork() of a multithreaded
        # process can deadlock.  Workers import numpy only — startup is
        # cheap against a >=256-pattern batch.
        for attempt in range(_POOL_ATTEMPTS):
            if not remaining or self._cancel.is_set():
                break
            ex = ProcessPoolExecutor(
                max_workers=n_procs, mp_context=get_context("spawn"),
                initializer=_pool_init, initargs=(spec,))
            try:
                futs = {ex.submit(_compute_chunk,
                                  (ci, self._chunks[ci], params, device,
                                   wire)): ci
                        for ci in sorted(remaining)}
                for fut in as_completed(futs):
                    ci = futs[fut]
                    if self._cancel.is_set():
                        return
                    try:
                        _ci, outputs, records = fut.result()
                    except BrokenProcessPool:
                        # a worker died (crash/OOM): every pending future is
                        # poisoned — rebuild the pool for what's left
                        record_recovery("isocalc.pool_broken")
                        logger.warning(
                            "isocalc pool broken with %d chunks left "
                            "(attempt %d); rebuilding",
                            len(remaining), attempt + 1)
                        break
                    except Exception:
                        # chunk-level failure: leave it in `remaining` for
                        # the next pool attempt / inline fallback
                        record_recovery("isocalc.worker_retry")
                        logger.warning("isocalc chunk %d failed in a worker; "
                                       "will retry", ci, exc_info=True)
                        continue
                    remaining.discard(ci)
                    buffered[ci] = (outputs, records)
                    commit_ready()
            finally:
                ex.shutdown(wait=False, cancel_futures=True)
        # inline fallback: deterministic faults (or a broken host) must not
        # starve the job — the driver computes the leftovers itself
        for ci in sorted(remaining):
            if self._cancel.is_set():
                return
            record_recovery("isocalc.chunk_inline")
            _ci, outputs, records = _compute_chunk(
                (ci, self._chunks[ci], params, device, wire))
            buffered[ci] = (outputs, records)
            commit_ready()


class IsocalcWrapper:
    """Same responsibility & knobs as the reference class of the same name [U].

    ``cache_dir`` (optional) persists computed patterns per parameter-set, the
    analog of the cross-job ``theor_peaks`` cache: only (formula, adduct)
    pairs missing from the cache are recomputed.  The ISSUE 3 rebuild made
    cold generation a three-layer pipeline (see ``PatternStream`` and
    docs/ISOCALC.md):

    - **Process-parallel chunk pool**: the missing work-list is chunked
      deterministically and fanned out over a spawn ``ProcessPoolExecutor``
      (the analog of the reference's ``sc.parallelize(pairs).flatMap``
      [U], SURVEY.md #7), with crash/retry seams (``isocalc.worker``) and an
      inline fallback.  ``n_procs`` caps workers (default: all cores; env
      ``SM_ISOCALC_PROCS`` overrides).
    - **Incremental CRC32-checksummed cache shards**: every completed chunk
      commits one ``theor_peaks_<key>_<job>_c<ci>.npz`` shard immediately
      (atomic rename, checksum member).  Serial and pooled runs write
      byte-identical shard sequences; a crash leaves a clean prefix that the
      rerun loads instead of recomputing.  Corrupt/truncated shards degrade
      to recompute (and are unlinked); shards compact past a threshold.
    - **Optional device blur->centroid** (``device_blur=True`` or env
      ``SM_ISOCALC_DEVICE=1``): workers emit fine-structure segments and the
      gaussian blur + centroid detection runs batched in XLA
      (ops/isocalc_jax.py).  Results agree with the NumPy oracle to ~1e-5
      (not bit-exact), so device-mode caches live under a separate param
      key — never mixed with oracle-mode shards.
    """

    _COMPACT_SHARDS = 64

    # smlint guarded-by registry (docs/ANALYSIS.md): the in-memory pattern
    # cache + dirty set are shared between streams and single-ion callers
    _GUARDED_BY = {"_cache": "_lock", "_dirty": "_lock"}

    def __init__(
        self,
        cfg: IsotopeGenerationConfig,
        cache_dir: str | Path | None = None,
        n_procs: int | None = None,
        device_blur: bool | None = None,
        chunk_size: int = 0,
    ):
        import os

        self.cfg = cfg
        self.cache_dir = Path(cache_dir) if cache_dir else None
        self.n_procs = n_procs
        self.chunk_size = chunk_size
        if device_blur is None:
            device_blur = os.environ.get("SM_ISOCALC_DEVICE", "") not in ("", "0")
        self.device_blur = bool(device_blur)
        self._device = None
        self._lock = threading.RLock()
        self._cache: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        self._dirty: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        # stats of the last pattern_table()/stream_table() generation, for
        # bench/report plumbing (bench.py isocalc_* fields)
        self.last_stats: dict = {}
        if self.cache_dir is not None:
            self.cache_dir.mkdir(parents=True, exist_ok=True)
            self._sweep_stale_tmps()
            for path in self._shard_paths():
                # tolerate (a) a concurrent compactor unlinking a shard
                # between the glob and the load, (b) a corrupt/truncated
                # shard from a crashed writer — skip it; entries recompute
                try:
                    failpoint(FP_ISO_SHARD_LOAD, path=path)
                    self._cache.update(self._load_shard(path))
                except (zipfile.BadZipFile, ValueError, KeyError) as e:
                    # definitively corrupt (bad zip / bad checksum / bad
                    # members): recompute AND unlink, so the poison file
                    # does not outlive its entries
                    record_recovery("isocalc.corrupt_shard")
                    logger.warning(
                        "removing corrupt isocalc shard %s: %s", path, e)
                    path.unlink(missing_ok=True)
                except (FileNotFoundError, OSError) as e:
                    # possibly-transient read error: skip but KEEP the file
                    record_recovery("isocalc.unreadable_shard")
                    logger.warning(
                        "skipping unreadable isocalc shard %s: %s", path, e)

    def _sweep_stale_tmps(self, max_age_s: float = 3600.0) -> None:
        """Remove orphaned tmp files a crashed writer left behind (age-gated
        so a live concurrent writer's tmp survives)."""
        import os
        import time

        now = time.time()
        for p in self.cache_dir.glob("tmp_*.npz"):
            try:
                if now - p.stat().st_mtime > max_age_s:
                    p.unlink(missing_ok=True)
            except OSError:
                continue

    @staticmethod
    def _load_shard(path) -> dict:
        """{ion: (mzs, ints)} from one cache shard.  Stacked format: 5
        arrays total (2 zip members per ion made a 21k-ion warm load take
        ~30 s); legacy shards without the crc member still read."""
        out: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        with np.load(path, allow_pickle=False) as z:
            if "ions" in z.files:
                ions, lens = z["ions"], z["lens"]
                mzs, ints = z["mzs"], z["ints"]
                if "crc" in z.files and int(z["crc"]) != _entries_crc(
                        lens, mzs, ints):
                    # np.load happily returns arrays from a zip whose payload
                    # bytes were corrupted in place; the checksum catches
                    # what the container format does not (PR 2 hardening,
                    # extended to the isocalc cache by ISSUE 3)
                    raise ValueError("isocalc shard checksum mismatch")
                for i, ion in enumerate(ions):
                    ln = int(lens[i])
                    out[str(ion)] = (mzs[i, :ln].copy(), ints[i, :ln].copy())
            else:  # legacy per-ion-member shard
                for k in z.files:
                    if k.endswith("/mzs"):
                        ion = k[: -len("/mzs")]
                        out[ion] = (z[k], z[ion + "/ints"])
        return out

    def _param_key(self) -> str:
        c = self.cfg
        blob = json.dumps(
            [c.charge, c.isocalc_sigma, c.isocalc_pts_per_mz, c.n_peaks], sort_keys=True
        )
        key = hashlib.sha256(blob.encode()).hexdigest()[:16]
        # device-mode patterns agree with the oracle only to ~1e-5 — give
        # them their own cache namespace so the two never mix.  PREFIX, not
        # suffix: the shard glob is "theor_peaks_<key>*", and a suffixed
        # key would still match the other mode's files
        return f"dev{key}" if self.device_blur else key

    def _shard_paths(self) -> list[Path]:
        return sorted(self.cache_dir.glob(f"theor_peaks_{self._param_key()}*.npz"))

    @staticmethod
    def _stack_entries(entries: dict) -> dict[str, np.ndarray]:
        """Pack {ion: (mzs, ints)} into stacked arrays + a CRC32 of the
        payload (one npz member per ion scales zip overhead with cache size;
        stacked, a 21k-ion load drops from ~30 s to well under a second)."""
        ions = list(entries)
        width = max((entries[i][0].size for i in ions), default=1)
        n = len(ions)
        lens = np.zeros(n, dtype=np.int32)
        mzs = np.zeros((n, width), dtype=np.float64)
        ints = np.zeros((n, width), dtype=np.float64)
        for i, ion in enumerate(ions):
            m, t = entries[ion]
            lens[i] = m.size
            mzs[i, : m.size] = m
            ints[i, : t.size] = t
        return {"ions": np.array(ions), "lens": lens, "mzs": mzs, "ints": ints,
                "crc": np.int64(_entries_crc(lens, mzs, ints))}

    def _write_shard(self, shard: Path, entries: dict) -> None:
        """tmp savez -> failpoint seam -> atomic rename.  tmp names use a
        "tmp_" PREFIX so the constructor's "theor_peaks_*" glob never sees a
        half-written file (np.savez force-appends .npz, so a suffix-based
        tmp would still match and a crashed/concurrent save would brick the
        cache with BadZipFile).

        Disk pressure (ISSUE 10, service/resources.py): cache shards are
        an OPTIONAL write — under degrade level >= 2 the shard is skipped
        (patterns stay in this process's memory and simply recompute next
        time), and the essential-write preflight still guards the hard
        floor below that."""
        import os
        import uuid

        from ..service import resources as _resources

        if not _resources.allow_cache():
            return
        est = sum(m.nbytes + t.nbytes for m, t in entries.values()) + 8192
        _resources.preflight("isocalc.shard_save", est)
        tmp = self.cache_dir / f"tmp_{uuid.uuid4().hex[:8]}.npz"
        np.savez(tmp, **self._stack_entries(entries))
        failpoint(FP_ISO_SHARD_SAVE, path=tmp)
        os.replace(tmp, shard)

    def _commit_chunk_shard(self, job_tag: str, ci: int, entries: dict) -> None:
        """Commit one chunk's patterns: cache + one incremental shard with a
        DETERMINISTIC name, so a rerun of the same missing set overwrites
        (idempotent) and serial/pooled runs produce identical files."""
        with self._lock:
            self._cache.update(entries)
        if self.cache_dir is None or not entries:
            return
        shard = self.cache_dir / (
            f"theor_peaks_{self._param_key()}_{job_tag}_c{ci:05d}.npz")
        with self._lock:
            self._write_shard(shard, entries)

    def _maybe_compact(self) -> None:
        """Merge shards into one base file past the threshold (caller holds
        the lock).  Merges from the shard FILES, not this process's
        in-memory view: a concurrent process may have written shards since
        our init, and compacting from _cache alone would drop them."""
        import os
        import uuid

        from ..service import resources as _resources

        if self.cache_dir is None or not _resources.allow_cache():
            return                    # disk pressure: defer compaction too
        shards = self._shard_paths()
        if len(shards) <= self._COMPACT_SHARDS:
            return
        merged: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        for path in shards:
            try:
                merged.update(self._load_shard(path))
            except Exception as exc:
                # a concurrent compactor already removed/replaced the shard
                # (or it is corrupt — init's checksum pass unlinks those);
                # either way its entries live on in base or recompute
                logger.debug("isocalc compact: skipping shard %s (%s)",
                             path.name, exc)
                continue
        merged.update(self._cache)
        base = self.cache_dir / f"theor_peaks_{self._param_key()}.npz"
        tmp = self.cache_dir / f"tmp_{uuid.uuid4().hex[:8]}.npz"
        np.savez(tmp, **self._stack_entries(merged))
        # replace base BEFORE unlinking shards: a kill in between loses
        # no entries (shards are only dropped once base holds them all)
        os.replace(tmp, base)
        for s in shards:
            if s != base:
                s.unlink(missing_ok=True)  # concurrent compactor race

    def save_cache(self) -> None:
        """Persist entries from single-ion ``isotope_peaks`` calls as one
        incremental shard (atomic rename).  Table generation does NOT go
        through here — chunk shards commit incrementally instead."""
        import uuid

        with self._lock:
            if self.cache_dir is None or not self._dirty:
                return
            shard = self.cache_dir / (
                f"theor_peaks_{self._param_key()}_{uuid.uuid4().hex[:8]}.npz")
            self._write_shard(shard, self._dirty)
            self._dirty = {}
            self._maybe_compact()

    def _params(self) -> tuple:
        c = self.cfg
        return (c.charge, c.isocalc_sigma, c.isocalc_pts_per_mz, c.n_peaks)

    def _device_stage(self):
        """Lazy DeviceBlurCentroid (imports jax only in device mode)."""
        if self._device is None:
            from .isocalc_jax import DeviceBlurCentroid

            self._device = DeviceBlurCentroid(*self._params())
        return self._device

    def isotope_peaks(self, sf: str, adduct: str) -> tuple[np.ndarray, np.ndarray] | None:
        """Centroided (mzs, ints) for formula+adduct, or None if the chemistry
        is invalid (e.g. '-H' from an H-free formula) — the reference skips
        such ions the same way [U].  Single-ion path: host oracle unless
        device mode is on (whose cache namespace is separate)."""
        ion = f"{sf}{adduct}"
        with self._lock:
            hit = self._cache.get(ion)
        if hit is not None:
            return hit
        if self.device_blur:
            try:
                counts = apply_adduct(parse_formula(sf), adduct)
            except FormulaError:
                return None
            segs = fine_structure_segments(counts, *self._params())
            if segs is not None:
                mzs, ints = self._device_stage().centroid_batch([segs])[0]
            else:
                mzs, ints = centroids(counts, *self._params())
        else:
            out = _compute_pattern_worker(((sf, adduct), self._params()))
            if out is None:
                return None
            _, mzs, ints = out
        with self._lock:
            self._cache[ion] = (mzs, ints)
            self._dirty[ion] = (mzs, ints)
        return mzs, ints

    def stream_table(
        self,
        sf_adduct_pairs: list[tuple[str, str]],
        target_flags: list[bool] | None = None,
    ) -> PatternStream:
        """Start cold-path generation; returns immediately with a running
        ``PatternStream`` (see class docstring).  The caller scores leading
        rows via ``wait_rows``/``ready_rows`` or blocks on ``result_table``.
        """
        stream = PatternStream(self, list(sf_adduct_pairs), target_flags)
        self._last_stream = stream
        return stream

    def pattern_table(
        self,
        sf_adduct_pairs: list[tuple[str, str]],
        target_flags: list[bool] | None = None,
    ) -> IsotopePatternTable:
        """Compute/load patterns for all pairs and pack them into fixed-shape
        arrays (invalid-chemistry ions are dropped, like the reference).
        Blocking form of ``stream_table``."""
        return self.stream_table(sf_adduct_pairs, target_flags).result_table()


def _entries_crc(lens: np.ndarray, mzs: np.ndarray, ints: np.ndarray) -> int:
    """CRC32 over the stacked payload (shard integrity check)."""
    crc = zlib.crc32(np.ascontiguousarray(lens).tobytes())
    crc = zlib.crc32(np.ascontiguousarray(mzs).tobytes(), crc)
    return zlib.crc32(np.ascontiguousarray(ints).tobytes(), crc)
