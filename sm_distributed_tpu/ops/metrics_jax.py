"""MSM metrics, JAX/TPU backend.

Device-side counterparts of ops/metrics_np.py (the parity oracle):

- ``measure_of_chaos``: connected components without dynamic shapes — the
  genuinely hard TPU kernel (SURVEY.md §7 hard part 1).  Implemented as
  min-label propagation via SEGMENTED MIN-SCANS: labels start as pixel
  indices; one sweep runs four ``lax.associative_scan`` passes (rows
  left/right, columns down/up) whose combine op resets at mask boundaries,
  so a label floods an entire straight run in O(log n) steps; a
  ``lax.while_loop`` sweeps to the exact fixpoint (component count =
  #pixels whose final label equals their own index), matching
  scipy.ndimage.label exactly.  Design note: an earlier pointer-jumping
  variant (gather-based label compression) was ~200x slower on TPU — VPU
  scans beat gathers by orders of magnitude; iterations-to-fixpoint equals
  the component "zigzag depth", small for real ion images.
- correlation / pattern match: masked dot products, trivially vmapped.

All functions take a whole formula batch and are designed to live inside one
fused jit with the extraction kernel (north star: one fused XLA graph).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..analysis.numerics import numerics_surface

# Declared numerics contracts (ISSUE 15, analysis/numerics.py): per-site
# drift bound vs the numpy oracle, the committed test that proves it, and
# the parameters that receive lattice-padded blocks (ISSUE 13) — the
# masked-reduction rule seeds its taint from `padded=`, so a raw
# reduction over a padded axis that skips the n_real helpers is a lint
# error here, not a silent metric corruption at scale.
NUMERICS = numerics_surface(__name__, {
    "batch_metrics":
        "contract=ulp(16); test=tests/test_jax_backend.py::"
        "test_backend_parity_metrics_and_ranks; padded=images",
    "measure_of_chaos_batch":
        "contract=bit_exact; test=tests/test_jax_backend.py::"
        "test_chaos_batch_matches_numpy; padded=principal",
    "hotspot_clip_batch":
        "contract=bit_exact; test=tests/test_jax_backend.py::"
        "test_hotspot_clip_batch_matches_numpy; padded=images",
    "correlation_from_moments":
        "contract=ulp(16); test=tests/test_jax_backend.py::"
        "test_backend_parity_metrics_and_ranks",
    "isotope_image_correlation_batch":
        "contract=ulp(16); test=tests/test_jax_backend.py::"
        "test_backend_parity_metrics_and_ranks; padded=images",
    "isotope_pattern_match_batch":
        "contract=ulp(16); test=tests/test_jax_backend.py::"
        "test_backend_parity_metrics_and_ranks",
    "batch_metrics_from_partials":
        "contract=bit_exact; test=tests/test_score_pallas.py::"
        "test_epilogue_matches_batch_metrics; padded=principal",
})

# numpy scalar, NOT jnp: a module-level jnp value would initialize the XLA
# backend at import time, which forbids jax.distributed.initialize later
# (multi-host processes import this module before calling initialize)
_BIG = np.int32(2**30)


def _seg_min_scan(vals: jnp.ndarray, resets: jnp.ndarray, axis: int,
                  reverse: bool) -> jnp.ndarray:
    """Segmented running minimum: the min restarts wherever ``resets`` is
    True (mask boundaries), so labels flood only within contiguous runs."""

    def comb(a, b):
        av, ar = a
        bv, br = b
        return (jnp.where(br, bv, jnp.minimum(av, bv)), ar | br)

    v, _ = lax.associative_scan(comb, (vals, resets), axis=axis, reverse=reverse)
    return v


def _cc_count(mask_flat: jnp.ndarray, nrows: int, ncols: int) -> jnp.ndarray:
    """Exact 4-connectivity component count of a boolean (nrows*ncols,) mask."""
    m = mask_flat.reshape(nrows, ncols)
    iota = jnp.arange(nrows * ncols, dtype=jnp.int32).reshape(nrows, ncols)
    labels0 = jnp.where(m, iota, _BIG)
    resets = ~m

    def sweep(lab):
        lab = _seg_min_scan(lab, resets, axis=1, reverse=False)
        lab = _seg_min_scan(lab, resets, axis=1, reverse=True)
        lab = _seg_min_scan(lab, resets, axis=0, reverse=False)
        lab = _seg_min_scan(lab, resets, axis=0, reverse=True)
        return jnp.where(m, lab, _BIG)

    def cond(state):
        labels, prev = state
        return jnp.any(labels != prev)

    def body(state):
        labels, _ = state
        return sweep(labels), labels

    labels, _ = lax.while_loop(cond, body, (sweep(labels0), labels0))
    return jnp.sum((labels == iota) & m)


def measure_of_chaos_batch(
    principal: jnp.ndarray,   # (N, n_pix) f32, n_pix == nrows*ncols
    nrows: int,
    ncols: int,
    nlevels: int = 30,
    use_pallas: bool | None = None,
    vmax: jnp.ndarray | None = None,       # (N,) precomputed row max
    n_notnull: jnp.ndarray | None = None,  # (N,) precomputed positive count
) -> jnp.ndarray:
    """(N,) chaos scores; matches metrics_np.measure_of_chaos semantics:
    thresholds vmax * i/nlevels for i in 0..nlevels-1, 4-connectivity,
    chaos = max(0, 1 - mean(component counts)/n_nonzero), 0 for empty.

    Three routes, all exact (the dispatch cannot change results): on TPU,
    'packed' (whole image(s) VMEM-resident, ops/chaos_pallas.py) for
    in-budget shapes or 'strips' (HBM-resident labels, halo'd row strips
    through VMEM) past the lean budget; elsewhere — and for shapes even
    strips cannot fit — the associative-scan path below.
    ``use_pallas=True`` forces a pallas route and raises ValueError when
    no pallas route fits the shape; ``False`` forces the scan path.
    """
    from .chaos_pallas import chaos_route

    if use_pallas is None:
        # 'packed': whole image(s) resident in one VMEM block (fast path);
        # 'strips': beyond the lean whole-image budget (>~288k cells, e.g.
        # 1024x1024 whole-slide DESI) — HBM-resident labels, row strips
        # through VMEM; 'scan': associative-scan fallback (CPU meshes,
        # interpreters, absurd widths).  All three are exact, so the
        # dispatch cannot change results.
        route = (chaos_route(nrows, ncols)
                 if jax.default_backend() == "tpu" else "scan")
    elif use_pallas:
        route = chaos_route(nrows, ncols)
        if route == "scan":
            raise ValueError(
                f"no pallas chaos route fits {nrows}x{ncols} images")
    else:
        route = "scan"
    principal = jnp.maximum(principal, 0.0)
    if vmax is None:
        # smlint: masked-ok[lattice pad pixels are exact zeros, below every positive max — vmax is the real-pixel maximum]
        vmax = principal.max(axis=1)                   # (N,)
    if n_notnull is None:
        # smlint: masked-ok[zero pads are never > 0; the positive count is pad-invariant]
        n_notnull = jnp.sum(principal > 0, axis=1)     # (N,)

    if route == "packed":
        from .chaos_pallas import chaos_count_sums

        count_sums = chaos_count_sums(
            principal, nrows=nrows, ncols=ncols, nlevels=nlevels)
    elif route == "strips":
        from .chaos_pallas import chaos_count_sums_strips

        count_sums = chaos_count_sums_strips(
            principal, nrows=nrows, ncols=ncols, nlevels=nlevels)
    else:
        def per_level(_, frac):
            levels = vmax * frac                        # (N,)
            masks = principal > levels[:, None]         # (N, n_pix)
            counts = jax.vmap(partial(_cc_count, nrows=nrows, ncols=ncols))(masks)
            return _, counts.astype(jnp.float32)

        fracs = jnp.arange(nlevels, dtype=jnp.float32) / nlevels
        _, counts = lax.scan(per_level, None, fracs)    # (nlevels, N)
        count_sums = counts.sum(axis=0)                 # exact small integers
    # ONE division by a runtime denominator: "count_sums / nlevels" would let
    # XLA strength-reduce the constant divisor into a reciprocal multiply
    # (different rounding than numpy's true division — observed 1-ulp chaos
    # drift); nlevels * n_notnull is exact in f32 (< 2**24).  On CPU this
    # makes chaos bit-identical to the oracle; the TPU VPU's division is
    # itself reciprocal-based (not correctly rounded), so on TPU chaos can
    # still sit 1 ulp off — FDR ranks/levels remain exactly identical (the
    # north-star criterion; verified on-chip in round 2)
    denom = (nlevels * jnp.maximum(n_notnull, 1)).astype(jnp.float32)
    chaos = 1.0 - count_sums / denom
    chaos = jnp.clip(chaos, 0.0, 1.0)
    return jnp.where((vmax > 0) & (n_notnull > 0), chaos, 0.0)


def correlation_from_moments(
    normsq: jnp.ndarray,      # (N, K) centered squared norms
    dots: jnp.ndarray,        # (N, K) centered dot vs principal row
    weights: jnp.ndarray,     # (N, K) theoretical intensities
    valid: jnp.ndarray,       # (N, K) bool
) -> jnp.ndarray:
    """isotope_image_correlation_batch's exact epilogue, from precomputed
    moments (ops/moments_pallas.py) — the two must stay in lockstep."""
    norm = jnp.sqrt(normsq)
    denom = norm[:, 0:1] * norm
    corr = jnp.where(denom > 0,
                     dots / jnp.maximum(denom, np.float32(1e-30)), 0.0)
    w = jnp.where(valid, weights, 0.0).at[:, 0].set(0.0)
    wsum = w.sum(axis=1)
    out = jnp.where(
        wsum > 0,
        (corr * w).sum(axis=1) / jnp.maximum(wsum, np.float32(1e-30)), 0.0)
    return jnp.clip(out, 0.0, 1.0)


def isotope_image_correlation_batch(
    images: jnp.ndarray,      # (N, K, P) f32
    weights: jnp.ndarray,     # (N, K) theoretical intensities (weights[:,1:] used)
    valid: jnp.ndarray,       # (N, K) bool
) -> jnp.ndarray:
    """(N,) weighted mean Pearson correlation of peaks 1..K-1 vs peak 0,
    NaN-free (constant images count 0), clipped to [0,1]."""
    mean = images.mean(axis=-1, keepdims=True)
    cent = images - mean
    norm = jnp.sqrt(jnp.sum(cent * cent, axis=-1))          # (N, K)
    base = cent[:, 0, :]                                    # (N, P)
    dots = jnp.einsum("np,nkp->nk", base, cent)             # (N, K)
    denom = norm[:, 0:1] * norm                             # (N, K)
    corr = jnp.where(denom > 0,
                     dots / jnp.maximum(denom, np.float32(1e-30)), 0.0)
    w = jnp.where(valid, weights, 0.0).at[:, 0].set(0.0)    # exclude principal
    wsum = w.sum(axis=1)
    out = jnp.where(
        wsum > 0,
        (corr * w).sum(axis=1) / jnp.maximum(wsum, np.float32(1e-30)), 0.0)
    return jnp.clip(out, 0.0, 1.0)


def isotope_pattern_match_batch(
    totals: jnp.ndarray,      # (N, K) observed total intensity per isotope image
    theor: jnp.ndarray,       # (N, K) theoretical intensities
    valid: jnp.ndarray,       # (N, K) bool
) -> jnp.ndarray:
    """(N,) cosine between masked envelopes, in [0,1]."""
    obs = jnp.where(valid, totals, 0.0)
    th = jnp.where(valid, theor, 0.0)
    on = jnp.sqrt(jnp.sum(obs * obs, axis=1))
    tn = jnp.sqrt(jnp.sum(th * th, axis=1))
    dot = jnp.sum(obs * th, axis=1)
    out = jnp.where((on > 0) & (tn > 0),
                    dot / jnp.maximum(on * tn, np.float32(1e-30)), 0.0)
    return jnp.clip(out, 0.0, 1.0)


def hotspot_clip_batch(images: jnp.ndarray, q: float) -> jnp.ndarray:
    """Device-side hot-spot removal, BIT-IDENTICAL to the numpy oracle's
    ``hotspot_percentile_f32`` (the cross-backend cutoff definition): clip
    each (ion, peak) image at the q-th linear-interpolated percentile of
    its positive pixels; images with no positive pixels pass through.

    ``images``: (..., P).  Masked percentile without dynamic shapes: sort
    the row ascending (zeros first), the positives occupy the top m slots,
    and the percentile's interpolation base sits at integer index
    (P - m) + floor((q/100)*(m-1)).  The float arithmetic is the oracle's
    exact single-op sequence — the integer index offset stays in integer
    space (folding it into the float position changes rounding), and an
    optimization barrier keeps XLA from contracting the final mul+add into
    an FMA, whose different rounding would flip clipped-pixel bits."""
    p = images.shape[-1]
    srt = jnp.sort(images, axis=-1)
    # smlint: masked-ok[zero pads are never > 0 and sort to the low slots; m and the index arithmetic are pad-count invariant by construction]
    m = jnp.sum(images > 0, axis=-1).astype(jnp.int32)     # (...,)
    t = np.float32(q) / np.float32(100.0)                  # host f32 constant
    pos = t * jnp.maximum(m - 1, 0).astype(jnp.float32)    # one rounded mul
    lo = jnp.floor(pos)                                    # exact
    frac = (pos - lo)[..., None]                           # exact
    i_lo = (p - m) + lo.astype(jnp.int32)                  # integer index math
    i_hi = jnp.minimum(i_lo + 1, p - 1)
    v_lo = jnp.take_along_axis(srt, jnp.clip(i_lo, 0, p - 1)[..., None], axis=-1)
    v_hi = jnp.take_along_axis(srt, i_hi[..., None], axis=-1)
    prod = jax.lax.optimization_barrier((v_hi - v_lo) * frac)
    cutoff = v_lo + prod                                   # (..., 1)
    clipped = jnp.minimum(images, cutoff)
    return jnp.where((m > 0)[..., None], clipped, images)


def batch_metrics(
    images: jnp.ndarray,      # (N, K, n_pix) f32 — n_pix == nrows*ncols
    theor_ints: jnp.ndarray,  # (N, K) f32
    n_valid: jnp.ndarray,     # (N,) i32
    nrows: int,
    ncols: int,
    nlevels: int = 30,
    do_preprocessing: bool = False,
    q: float = 99.0,
    n_real=None,              # traced i32 scalar: REAL pixels (lattice pad)
) -> jnp.ndarray:
    """(N, 4) of (chaos, spatial, spectral, msm) for a formula batch.

    ``n_real`` (ISSUE 13 shape-bucket lattice): when ``nrows`` is the
    ROW-BUCKETED grid (ops/buckets.row_bucket) the trailing rows are zero
    padding and ``n_real`` carries the dataset's true pixel count as a
    TRACED scalar.  Zero pads are exactly invariant for every metric op
    except the correlation's mean over pixels — which divides by
    ``n_real`` with the centered block masked back to zero past it
    (moments_pallas.batch_moments) — and the hotspot percentile, whose
    sorted-index arithmetic is pad-count invariant by construction (the
    positives occupy the top ``m`` slots wherever the zeros sit).  Chaos
    runs on the padded grid unmasked: zero pixels are below every
    threshold, so component counts, ``vmax`` and ``n_notnull`` are exact
    integers either way.  Result: metrics are bit-identical to unpadded
    scoring while every dataset size in a bucket shares ONE executable."""
    k = images.shape[1]
    valid = jnp.arange(k, dtype=jnp.int32)[None, :] < n_valid[:, None]
    images = jnp.where(valid[:, :, None], images, 0.0)
    if do_preprocessing:
        images = hotspot_clip_batch(images, q)

    # every per-pixel reduction the metrics need, in ONE streaming pass
    # over the image block (ops/moments_pallas.py; XLA fallback identical
    # semantics) — separate XLA reductions measured ~25-30 ms per 1 GB
    # DESI batch against ~3 ms fused
    from .moments_pallas import batch_moments

    sums, normsq, dots, vmax, n_notnull = batch_moments(images,
                                                        n_real=n_real)
    chaos = measure_of_chaos_batch(
        images[:, 0, :], nrows, ncols, nlevels,
        vmax=vmax, n_notnull=n_notnull)
    spatial = correlation_from_moments(normsq, dots, theor_ints, valid)
    spectral = isotope_pattern_match_batch(sums, theor_ints, valid)

    alive = (n_valid > 0) & (vmax > 0)
    chaos = jnp.where(alive, chaos, 0.0)
    spatial = jnp.where(alive, spatial, 0.0)
    spectral = jnp.where(alive, spectral, 0.0)
    msm = chaos * spatial * spectral
    return jnp.stack([chaos, spatial, spectral, msm], axis=1)


def batch_metrics_from_partials(
    partials: jnp.ndarray,    # (N, K, 5) moment columns (sums, normsq,
                              # dots, vmax, nn) per window row
    principal: jnp.ndarray,   # (N, n_pix) f32 principal (peak-0) images
    theor_ints: jnp.ndarray,  # (N, K) f32
    n_valid: jnp.ndarray,     # (N,) i32
    nrows: int,
    ncols: int,
    nlevels: int = 30,
) -> jnp.ndarray:
    """``batch_metrics`` epilogue from PRECOMPUTED moments — the fused
    Pallas scoring kernel's exit (ops/score_pallas.py, ISSUE 18).

    ``batch_metrics`` masks invalid window rows to zero BEFORE the
    moment pass; the fused kernel computes moments unmasked, so the mask
    moves here onto the moment columns — exactly equivalent: an invalid
    row's masked image is all-zero, hence its sums/normsq/dots are
    exactly 0.0, which is what the ``where`` below writes; valid rows'
    moments never see the mask in either order.  ``vmax``/``nn``/the
    principal image come from window 0, valid iff ``n_valid > 0`` — the
    same predicate the alive gate applies — so masking them by that
    predicate reproduces the masked-image values bit-for-bit.  The pad
    columns of ``principal`` are exact zeros (pad peaks scatter 0.0 and
    pad pixels receive nothing), so chaos needs no ``n_real`` masking —
    the same argument as ``batch_metrics``'s padded-grid chaos.  No
    hotspot preprocessing: the fused route is gated on
    ``do_preprocessing=False`` (clipping needs full materialized images).
    """
    k = partials.shape[1]
    valid = jnp.arange(k, dtype=jnp.int32)[None, :] < n_valid[:, None]
    # smlint: masked-ok[moment columns are per-row scalars; the pixel axis was already reduced under the kernel's n_real mask]
    sums = jnp.where(valid, partials[..., 0], 0.0)
    normsq = jnp.where(valid, partials[..., 1], 0.0)
    dots = jnp.where(valid, partials[..., 2], 0.0)
    alive0 = n_valid > 0
    vmax = jnp.where(alive0, partials[:, 0, 3], 0.0)
    n_notnull = jnp.where(alive0, partials[:, 0, 4], 0.0)
    principal = jnp.where(alive0[:, None], principal, 0.0)

    chaos = measure_of_chaos_batch(
        principal, nrows, ncols, nlevels, vmax=vmax, n_notnull=n_notnull)
    spatial = correlation_from_moments(normsq, dots, theor_ints, valid)
    spectral = isotope_pattern_match_batch(sums, theor_ints, valid)

    alive = alive0 & (vmax > 0)
    chaos = jnp.where(alive, chaos, 0.0)
    spatial = jnp.where(alive, spatial, 0.0)
    spectral = jnp.where(alive, spectral, 0.0)
    msm = chaos * spatial * spectral
    return jnp.stack([chaos, spatial, spectral, msm], axis=1)
