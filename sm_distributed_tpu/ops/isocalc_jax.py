"""Batched device-side gaussian blur -> centroid detection (ISSUE 3, layer 2).

The isotope cold path's post-convolution math — blur the fine structure at
instrument sigma, find local maxima, parabolic-refine the top ``n_peaks`` —
was 85%+ of host pattern cost and ran one tiny NumPy array at a time.  Here
it runs VECTORIZED over a packed batch of fine-structure SEGMENTS in JAX.

Formulation (why dense, not scatter): windowed fine-structure states cluster
at isotope spacings (~1/|z| Da) while the blur support is only 5*sigma, so
each ion's profile decomposes into <= n_peaks+4 short independent segments
(``ops.isocalc.fine_structure_segments``).  Per segment the profile is
evaluated densely::

    profile[l] = sum_s ab[s] * exp(-((l*step - m_rel[s]) / sigma)^2 / 2)

— one fused exp + einsum, no scatter.  Measured on this host (XLA CPU,
single core): the literal scatter-add port of the oracle ran 5x SLOWER than
NumPy (XLA CPU serializes scatter), while this dense form runs ~3x FASTER;
on TPU the einsum maps to the MXU.

Batching is over SEGMENTS, not ions: segments are flattened across the ion
batch and grouped by their OWN state-count bucket, so a light 10-state
segment never pays a heavy neighbor's padding (the first, ion-padded version
of this kernel measured only 1.26x over the oracle on the decoy-adduct-heavy
full-DB corpus because per-ion C_CAP x max-state padding wasted ~4x the exp
work; packed segments recover it).  The per-batch row count scales inversely
with the state bucket so the dense (B, LC, S) block stays ~50 MB.

All device math is f32 in segment-local coordinates (range < 0.16 Da, so
f32 carries ~1e-8 Da resolution); absolute m/z assembly, cross-segment
top-k selection, and intensity normalization happen on host in f64
(vectorized numpy, no per-ion Python loop).

Parity contract: results agree with the NumPy oracle (``isocalc.centroids``)
to ~3e-7 Da in m/z and ~1e-5 in normalized intensity (measured over 1,800
real formula/adduct ions), NOT bit-exactly — device-mode caches therefore
live under a separate parameter key.  Determinism: each segment's result
depends only on its own (state-bucket) padded row, and buckets are chosen
per SEGMENT, so the same ion produces the same bits regardless of which
chunk or batch it rides in (the parallel==serial guarantee).
"""

from __future__ import annotations

import functools

import numpy as np

from ..analysis.numerics import numerics_surface
from ..analysis.surface import compile_surface
from .isocalc import SEGMENT_GRID_CAP

# Declared numerics contract (ISSUE 15): the dense blur->centroid kernel
# is a different ALGORITHM than the oracle's scatter-add (module doc:
# ~3e-7 Da m/z, ~1e-5 relative intensity over 1,800 real ions), so the
# declared budget is ulp(128) — ~1e-5 relative in f32 — with the
# measured-parity test as its proof.  Device-mode caches key separately
# for exactly this reason.
NUMERICS = numerics_surface(__name__, {
    "run":
        "contract=ulp(128); test=tests/test_isocalc_parallel.py::"
        "test_device_blur_centroid_matches_oracle",
})

# Declared compile surface (ISSUE 12, analysis/surface.py): the blur->
# centroid kernel closes over its (grid, states, rows, k) shape — one
# executable per (state-bucket x grid-bucket) cell of the FIXED ladders
# below, so the family is bounded by len(_STATE_BUCKETS) x
# len(_GRID_BUCKETS) regardless of corpus.
COMPILE_SURFACE = compile_surface(__name__, {
    "run":
        "statics=closure(lc,sc,b,k); buckets=one executable per "
        "(_STATE_BUCKETS x _GRID_BUCKETS) cell — fixed ladders, row count "
        "derived from the bucket (_BLOCK_ROWS), k from config n_peaks",
})

# per-segment state-count buckets: padding within a bucket costs masked
# zeros, a new bucket costs one XLA compile.  Finer at the small end, where
# the distribution mass lives (per-seg states p50=10, p90=28 on the full-DB
# corpus): exp cost is linear in the bucket, so a 10-state segment in a
# 16-bucket wastes 60% where a 12-bucket wastes 20%
_STATE_BUCKETS = (4, 8, 12, 16, 24, 32, 48, 64, 128, 256, 512)
# per-segment grid-length buckets: a typical isotope cluster needs ~1050
# points (5-sigma support + a few-mDa span), so padding everything to the
# 1536 cap wasted ~45% of the dense block
_GRID_BUCKETS = (1152, SEGMENT_GRID_CAP)
# dense-block budget: rows per batch = max(16, _BLOCK_ROWS // bucket), so
# the (B, LC, S) f32 block stays ~50 MB
_BLOCK_ROWS = 8192


def _state_bucket(n: int) -> int:
    for b in _STATE_BUCKETS:
        if n <= b:
            return b
    return _STATE_BUCKETS[-1]


def _grid_bucket(npts: int) -> int:
    for b in _GRID_BUCKETS:
        if npts <= b:
            return b
    return _GRID_BUCKETS[-1]


@functools.lru_cache(maxsize=None)
def _kernel(lc: int, sc: int, b: int, k: int,
            step: float, sigma: float, pad: float):
    """Jitted per-segment blur->centroid for one (state bucket, rows) shape."""
    import jax
    import jax.numpy as jnp

    inv2s2 = np.float32(-0.5 / (sigma * sigma))
    win = np.float32(pad + step)
    stepf = np.float32(step)

    def run(m_rel, ab, seg_len):
        # m_rel, ab: (B, Sc) f32 (padding: m_rel huge, ab 0)
        # seg_len:   (B,) i32 grid length per segment
        g = jnp.arange(lc, dtype=jnp.float32) * stepf          # (LC,)
        # truncation mirrors the oracle's per-state windows (|x| > pad
        # contributes zero there; the half-step slack admits at most one
        # extra ~e^-12.5 tail point per edge)
        if sc <= 64:
            # UNROLLED accumulation: XLA fuses each state's x/exp/where
            # chain into one pass over the (B, LC) accumulator — no
            # (B, LC, Sc) intermediate ever materializes.  Measured 4.2x
            # over the einsum form on XLA-CPU (9.4 vs 39.7 ms on the
            # typical bucket; the einsum materialized x, x^2, exp, where
            # blocks and went memory-bound at ~4.3 ns/element)
            p = jnp.zeros((b, lc), jnp.float32)
            for s in range(sc):
                x = g[None, :] - m_rel[:, s: s + 1]
                p = p + ab[:, s: s + 1] * jnp.where(
                    jnp.abs(x) <= win, jnp.exp(inv2s2 * x * x), 0.0)
        else:
            # rare huge-cluster buckets: unrolling would bloat the program;
            # the dense einsum is acceptable on the <1% of segments here
            x = g[None, :, None] - m_rel[:, None, :]           # (B, LC, Sc)
            w = jnp.where(jnp.abs(x) <= win, jnp.exp(inv2s2 * x * x), 0.0)
            p = jnp.einsum("bls,bs->bl", w, ab)                # (B, LC)
        # strict local maxima, excluding segment-boundary points (the
        # oracle's `interior` mask) and the padded tail
        larange = jnp.arange(lc, dtype=jnp.int32)
        interior = ((larange[None, :] >= 1)
                    & (larange[None, :] < seg_len[:, None] - 1))
        mids = ((p[:, 1:-1] >= p[:, :-2]) & (p[:, 1:-1] > p[:, 2:])
                & interior[:, 1:-1])
        cand = jnp.where(mids, p[:, 1:-1], -1.0)
        v, li = jax.lax.top_k(cand, k)                         # (B, k)
        li = li + 1
        rows = jnp.arange(b, dtype=jnp.int32)[:, None]
        y0, y1, y2 = p[rows, li - 1], p[rows, li], p[rows, li + 1]
        # fallback support: the profile argmax (oracle: "no local max ->
        # argmax"), with its parabola neighbors
        gm = jnp.clip(jnp.argmax(p, axis=1), 1, lc - 2)
        r = jnp.arange(b, dtype=jnp.int32)
        fb = jnp.stack([p[r, gm], p[r, gm - 1], p[r, gm + 1]], axis=1)
        return v, li, y0, y1, y2, gm, fb

    return jax.jit(run)


def _parabola(y0, y1, y2, li):
    """Vectorized sub-grid refinement — same arithmetic as the oracle.
    Returns (height, grid_offset) f64 arrays."""
    y0 = y0.astype(np.float64)
    y1 = y1.astype(np.float64)
    y2 = y2.astype(np.float64)
    denom = y0 - 2.0 * y1 + y2
    delta = np.where(np.abs(denom) > 0,
                     0.5 * (y0 - y2) / np.where(denom == 0, 1.0, denom), 0.0)
    delta = np.clip(delta, -0.5, 0.5)
    height = y1 - 0.25 * (y0 - y2) * delta
    return height, li.astype(np.float64) + delta


class DeviceBlurCentroid:
    """Packed-segment blur->centroid (see module doc).

    One instance per isotope-generation parameter set; jitted executables
    are cached per state bucket.  ``centroid_batch`` consumes the per-ion
    segment lists produced by ``isocalc.fine_structure_segments`` and
    returns oracle-compatible ``(mzs, ints)`` f64 pairs (m/z ascending,
    intensities normalized to max=100).
    """

    def __init__(self, charge: int, isocalc_sigma: float,
                 isocalc_pts_per_mz: int, n_peaks: int):
        self.charge = charge
        self.sigma = float(isocalc_sigma)
        self.step = 1.0 / isocalc_pts_per_mz
        self.pad = 5.0 * self.sigma
        self.n_peaks = n_peaks
        self.c_cap = n_peaks + 4
        self.lc = SEGMENT_GRID_CAP

    def centroid_batch(
        self, seg_lists: list[list[tuple[float, np.ndarray, np.ndarray, int]]]
    ) -> list[tuple[np.ndarray, np.ndarray]]:
        """Blur + centroid every ion; returns one (mzs, ints) per input."""
        k = self.n_peaks
        # flatten (ion, position) -> segment; group by per-SEGMENT bucket
        seg_ion: list[int] = []
        seg_pos: list[int] = []
        seg_lo: list[float] = []
        segs: list[tuple[np.ndarray, np.ndarray, int]] = []
        for i, sl in enumerate(seg_lists):
            for ci, (lo, m, a, npts) in enumerate(sl):
                seg_ion.append(i)
                seg_pos.append(ci)
                seg_lo.append(lo)
                segs.append((m, a, npts))
        n_seg = len(segs)
        v = np.empty((n_seg, k), np.float32)
        li = np.empty((n_seg, k), np.int32)
        y0 = np.empty((n_seg, k), np.float32)
        y1 = np.empty((n_seg, k), np.float32)
        y2 = np.empty((n_seg, k), np.float32)
        gm = np.empty(n_seg, np.int32)
        fb = np.empty((n_seg, 3), np.float32)

        by_bucket: dict[tuple[int, int], list[int]] = {}
        for si, (m, _a, npts) in enumerate(segs):
            key = (_state_bucket(m.size), _grid_bucket(npts))
            by_bucket.setdefault(key, []).append(si)
        for (sc, lc), idxs in sorted(by_bucket.items()):
            b = max(16, _BLOCK_ROWS // sc)
            kern = _kernel(lc, sc, b, k, self.step, self.sigma, self.pad)
            for s in range(0, len(idxs), b):
                group = idxs[s: s + b]
                m_rel = np.full((b, sc), 1e6, np.float32)
                ab = np.zeros((b, sc), np.float32)
                ln = np.zeros(b, np.int32)
                for bi, si in enumerate(group):
                    m, a, npts = segs[si]
                    m_rel[bi, : m.size] = m
                    ab[bi, : a.size] = a
                    ln[bi] = npts
                outs = kern(m_rel, ab, ln)
                # smlint: host-sync-ok[host index list, not a device value]
                g = np.asarray(group)
                for dst, src in zip((v, li, y0, y1, y2, gm, fb), outs):
                    # smlint: host-sync-ok[per-bucket kernel-result fetch; top-k selection and f64 assembly are host-side by design]
                    dst[g] = np.asarray(src)[: len(group)]
        # smlint: host-sync-ok[host segment bookkeeping lists, not device values]
        seg_maps = (np.asarray(seg_ion), np.asarray(seg_pos), np.asarray(seg_lo))
        return self._assemble(seg_lists, *seg_maps,
                              v, li, y0, y1, y2, gm, fb)

    def _assemble(self, seg_lists, seg_ion, seg_pos, seg_lo,
                  v, li, y0, y1, y2, gm, fb):
        """Vectorized host f64 finish: parabolic refinement, cross-segment
        top-k by intensity, m/z-ascending order, max-100 normalization —
        the exact oracle conventions, no per-ion Python loop."""
        k = self.n_peaks
        n_ions = len(seg_lists)
        n_seg = seg_ion.size
        h, off = _parabola(y0, y1, y2, li)                     # (Nseg, k)
        mz = seg_lo[:, None] + self.step * off
        valid = v > 0.0
        # per-ion candidate matrices (n_ions, c_cap*k), -inf padded
        cand_h = np.full((n_ions, self.c_cap * k), -np.inf)
        cand_mz = np.zeros((n_ions, self.c_cap * k))
        cols = (seg_pos[:, None] * k + np.arange(k)[None, :])  # (Nseg, k)
        rows = np.broadcast_to(seg_ion[:, None], cols.shape)
        cand_h[rows, cols] = np.where(valid, h, -np.inf)
        cand_mz[rows, cols] = mz
        # top n_peaks by height (descending), then m/z-ascending
        order = np.argsort(-cand_h, axis=1, kind="stable")[:, :k]
        rix = np.arange(n_ions)[:, None]
        sel_h = cand_h[rix, order]
        sel_mz = cand_mz[rix, order]
        n_valid = (sel_h > -np.inf).sum(axis=1)
        # fallback (oracle: "no local max -> argmax"): best segment by
        # profile max, parabola at its argmax
        none = n_valid == 0
        if none.any():
            seg_best = np.full(n_ions, -1, np.int64)
            best_val = np.full(n_ions, -np.inf)
            np.maximum.at(best_val, seg_ion, fb[:, 0].astype(np.float64))
            match = fb[:, 0].astype(np.float64)[...] == best_val[seg_ion]
            # last matching segment wins deterministically
            seg_best[seg_ion[match]] = np.nonzero(match)[0]
            for i in np.nonzero(none)[0]:
                si = seg_best[i]
                # smlint: host-sync-ok[gm was fetched with its bucket above; this is host numpy indexing]
                gm_i = np.asarray(gm[si])
                hh, oo = _parabola(fb[si, 1], fb[si, 0], fb[si, 2], gm_i)
                sel_h[i, 0] = float(hh)
                sel_mz[i, 0] = seg_lo[si] + self.step * float(oo)
                n_valid[i] = 1
        # m/z-ascending among the selected peaks (pad slots sort to the end)
        sort_mz = np.where(sel_h > -np.inf, sel_mz, np.inf)
        mz_order = np.argsort(sort_mz, axis=1, kind="stable")
        sel_h = sel_h[rix, mz_order]
        sel_mz = sel_mz[rix, mz_order]
        out = []
        for i in range(n_ions):
            n = int(n_valid[i])
            hi = sel_h[i, :n]
            out.append((sel_mz[i, :n].copy(), 100.0 * hi / hi.max()))
        return out
