"""Sum-formula and adduct parsing/arithmetic.

The reference delegates formula parsing to ``pyMSpec.pyisocalc`` inside
``sm/engine/isocalc_wrapper.py::IsocalcWrapper.isotope_peaks`` [U] (SURVEY.md
#6); adduct strings like ``+H``/``+Na``/``-H`` come straight from the
per-dataset config (``isotope_generation.adducts``).  We implement parsing
natively: a sum formula is a flat dict ``{element: count}``; adducts add or
remove atoms before isotope-pattern computation.
"""

from __future__ import annotations

import re

from . import elements


class FormulaError(ValueError):
    """Raised on unparseable formulas/adducts or unknown elements."""


def parse_formula(formula: str) -> dict[str, int]:
    """Parse a sum formula like ``C6H12O6`` or ``Ca(NO3)2`` into {element: count}.

    Raises FormulaError on syntax errors or elements missing from the isotope
    table (the reference behaves the same way: pyisocalc raises on unknown
    elements and the job skips/fails that formula).
    """
    if not formula or not isinstance(formula, str):
        raise FormulaError(f"empty or non-string formula: {formula!r}")
    counts: dict[str, int] = {}
    stack: list[dict[str, int]] = [counts]
    i = 0
    while i < len(formula):
        ch = formula[i]
        if ch == "(":
            stack.append({})
            i += 1
        elif ch == ")":
            if len(stack) == 1:
                raise FormulaError(f"unbalanced ')' in {formula!r}")
            group = stack.pop()
            m = re.match(r"\d+", formula[i + 1:])
            mult = int(m.group(0)) if m else 1
            if m and mult == 0:
                raise FormulaError(f"zero group count in {formula!r}")
            i += 1 + (m.end() if m else 0)
            for el, n in group.items():
                stack[-1][el] = stack[-1].get(el, 0) + n * mult
        else:
            m = re.match(r"([A-Z][a-z]?)(\d*)", formula[i:])
            if not m:
                raise FormulaError(f"cannot parse {formula!r} at position {i}")
            el = m.group(1)
            if not elements.is_known(el):
                raise FormulaError(f"unknown element {el!r} in {formula!r}")
            n = int(m.group(2)) if m.group(2) else 1
            if n == 0:
                raise FormulaError(f"zero count for {el!r} in {formula!r}")
            stack[-1][el] = stack[-1].get(el, 0) + n
            i += m.end()
    if len(stack) != 1:
        raise FormulaError(f"unbalanced '(' in {formula!r}")
    if not counts:
        raise FormulaError(f"empty formula {formula!r}")
    return counts


def parse_adduct(adduct: str) -> tuple[int, dict[str, int]]:
    """Parse an adduct string ``+H``, ``-H``, ``+Na`` -> (sign, {element: count})."""
    if not adduct or adduct[0] not in "+-":
        raise FormulaError(f"adduct must start with '+' or '-': {adduct!r}")
    sign = 1 if adduct[0] == "+" else -1
    atoms = parse_formula(adduct[1:])
    return sign, atoms


def apply_adduct(counts: dict[str, int], adduct: str) -> dict[str, int]:
    """Return atom counts of formula+adduct; raises if subtraction goes negative."""
    sign, atoms = parse_adduct(adduct)
    out = dict(counts)
    for el, n in atoms.items():
        c = out.get(el, 0) + sign * n
        if c < 0:
            raise FormulaError(f"adduct {adduct!r} removes more {el} than present")
        if c == 0:
            out.pop(el, None)
        else:
            out[el] = c
    if not out:
        raise FormulaError(f"adduct {adduct!r} empties the formula")
    return out


def format_formula(counts: dict[str, int]) -> str:
    """Hill-system formatting: with carbon, C then H then alphabetical;
    without carbon, strictly alphabetical (so HCl formats as 'ClH')."""
    if "C" in counts:
        keys = sorted(counts, key=lambda el: (el != "C", el != "H", el))
    else:
        keys = sorted(counts)
    return "".join(f"{el}{counts[el] if counts[el] != 1 else ''}" for el in keys)


def monoisotopic_mass(counts: dict[str, int]) -> float:
    return sum(elements.monoisotopic_mass(el) * n for el, n in counts.items())


def ion_mz(counts: dict[str, int], charge: int) -> float:
    """m/z of the monoisotopic ion at the given (signed, nonzero) charge."""
    if charge == 0:
        raise FormulaError("charge must be nonzero for an ion")
    m = monoisotopic_mass(counts) - charge * elements.ELECTRON_MASS
    return m / abs(charge)
