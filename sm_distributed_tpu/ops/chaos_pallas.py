"""measure_of_chaos connected components — Pallas TPU kernel.

The round-1 implementation (ops/metrics_jax.py) runs the min-label flood as
``lax.associative_scan`` sweeps over the WHOLE formula batch inside one
``lax.while_loop``: every sweep round-trips (batch, nrows, ncols) labels
through HBM and the loop iterates until the *worst* image in the batch
converges.  A profile of the 512-ion bench batch put ~113 ms of the ~190 ms
batch in these whiles (VERDICT r1 "what's weak" #1).

This kernel keeps the same exact algorithm — min-label flooding by
segmented min-scans, fixpoint detection, count = #pixels whose final label
equals their own index, bit-equal to ``scipy.ndimage.label`` — but runs it
entirely in VMEM with convergence tracked per PROGRAM (a handful of images),
not per batch:

- Layout: images side by side along the lane axis — block (R, IB*C) where
  IB*C is a multiple of 128.  Label floods never cross image boundaries
  because the row-scan "open" flags are seeded with a boundary guard
  (``col % C != 0`` forward, ``!= C-1`` backward).
- All ``nlevels`` thresholds are processed inside the kernel (fori over
  levels); per level a ``lax.while_loop`` sweeps to the exact fixpoint of
  the IB images only — empty decoy images exit after one sweep instead of
  riding the batch worst case.
- Segmented min-scan = Hillis–Steele distance doubling with an int32
  "open" flag (TPU cannot rotate i1 vectors): after step d, ``open[i]``
  means "window (i-d, i] is fully masked and crosses no image boundary".
- HBM traffic: each image is read ONCE (f32) and one count row is written —
  everything else (labels, flags, masks) lives in registers/VMEM.

Reference semantics: ``pyImagingMSpec.measure_of_chaos`` per-level component
counts [U] (SURVEY.md #11); oracle: ops/metrics_np.py::measure_of_chaos.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..analysis.numerics import numerics_surface
from ..analysis.surface import compile_surface

# Declared numerics contracts (ISSUE 15): all chaos routes are EXACT —
# integer component counts off exact thresholds — so the dispatch can
# never change results; pad pixels are below every positive threshold
# and join no component, so the kernels are pad-invariant without
# masking (the batch_metrics docstring carries the argument).
NUMERICS = numerics_surface(__name__, {
    "chaos_count_sums":
        "contract=bit_exact; test=tests/test_chaos_pallas.py::"
        "test_matches_full_chaos_oracle",
    "chaos_count_sums_strips":
        "contract=bit_exact; test=tests/test_chaos_pallas.py::"
        "test_strip_kernel_matches_scipy",
})

# Declared compile surface (ISSUE 12, analysis/surface.py): both kernels'
# statics are per-dataset image geometry plus fixed tuning constants, so
# each dataset config compiles exactly one executable per kernel.
COMPILE_SURFACE = compile_surface(__name__, {
    "chaos_count_sums":
        "statics=nrows,ncols,nlevels,lane_width,interpret,work_span; "
        "buckets=one executable per dataset — geometry is per-dataset "
        "static, lane_width/work_span/nlevels are config constants",
    "chaos_count_sums_strips":
        "statics=nrows,ncols,nlevels,interpret,work_span,strip_rows; "
        "buckets=one executable per dataset — strip_rows derives from the "
        "fixed strip geometry of (nrows, ncols)",
})

_BIG = np.int32(2**30)


def _shift(x: jnp.ndarray, d: int, axis: int, reverse: bool, fill) -> jnp.ndarray:
    """Non-circular shift by static d (fill at the exposed edge)."""
    n = x.shape[axis]
    rolled = pltpu.roll(x, (n - d) if reverse else d, axis=axis)
    idx = lax.broadcasted_iota(jnp.int32, x.shape, axis)
    keep = (idx < n - d) if reverse else (idx >= d)
    return jnp.where(keep, rolled, fill)


def _seg_min_scan(v: jnp.ndarray, o: jnp.ndarray, axis: int, reverse: bool,
                  span: int | None = None) -> jnp.ndarray:
    """Segmented prefix-min along ``axis`` (Hillis–Steele): o[i]=1 iff the
    pull window behind i is fully open (masked, no image boundary).

    ``span`` caps the scan distance: lane blocks pack several images side by
    side, and a flood can never propagate further than one image's column
    width (the boundary guard kills longer windows anyway), so scanning to
    the full block width wastes log2(block/span) doubling steps."""
    d = 1
    n = min(span, v.shape[axis]) if span is not None else v.shape[axis]
    while d < n:
        vs = _shift(v, d, axis, reverse, _BIG)
        os_ = _shift(o, d, axis, reverse, np.int32(0))
        v = jnp.minimum(v, jnp.where(o > 0, vs, _BIG))
        o = o * os_
        d *= 2
    return v


def _chaos_kernel(img_ref, vmax_ref, out_ref, *, ncols: int, nlevels: int,
                  lean: bool = False, work_span: int = 0):
    """One program: IB images of shape (R, ncols) packed as (R, IB*ncols).

    ``lean``: rematerialize the mask/open-flag arrays inside every sweep
    instead of hoisting them per level.  Hoisting is faster (flags computed
    once per level) but keeps three extra (R, IBC) i32 arrays live across
    the fixpoint while-loop; the lean variant trades ~3 extra vector ops
    per sweep for that VMEM, which is what lets WIDE images (512x512 —
    beyond the packed budget) run in the kernel instead of falling back to
    the ~10x-slower associative-scan path (VERDICT r2 item 3)."""
    img = img_ref[:]                                   # (R, IBC) f32
    shape = img.shape
    row = lax.broadcasted_iota(jnp.int32, shape, 0)
    col = lax.broadcasted_iota(jnp.int32, shape, 1)
    incol = col % ncols                                # column within image
    iota = row * ncols + incol                         # per-image pixel id
    vmax = vmax_ref[:]                                 # (1, IBC) f32, per-lane

    def level_body(li_rev, carry):
        # Levels run DESCENDING (highest threshold first): masks only GROW
        # going down, so components only MERGE and the previous level's
        # final labels are exact warm-start labels — each old component's
        # label is the iota of one of its pixels, so the flood min over a
        # merged component is still its true min-iota, and that root pixel
        # stays in the mask (root counting stays valid).  Newly exposed
        # pixels start at their own iota.  Warm starts pre-merge most of
        # the structure, cutting sweeps-to-fixpoint on the dense low levels.
        acc, prev_lab = carry
        li = nlevels - 1 - li_rev
        # threshold grid identical to the oracle: vmax * li/nlevels,
        # f32 arithmetic (li/nlevels rounds exactly as arange/nlevels)
        thr = vmax * (li.astype(jnp.float32) / np.float32(nlevels))
        mask = img > thr

        def flags():
            mi = mask.astype(jnp.int32)
            return mi, mi * (incol != 0), mi * (incol != ncols - 1)

        if not lean:
            mi_h, o_fwd_h, o_bwd_h = flags()
        lab0 = jnp.where(mask, jnp.minimum(prev_lab, iota), _BIG)

        def sweep(lab, span=None):
            mi, o_fwd, o_bwd = flags() if lean else (mi_h, o_fwd_h, o_bwd_h)
            lab = _seg_min_scan(lab, o_fwd, 1, False,
                                span=min(span or ncols, ncols))
            lab = _seg_min_scan(lab, o_bwd, 1, True,
                                span=min(span or ncols, ncols))
            lab = _seg_min_scan(lab, mi, 0, False, span=span)
            lab = _seg_min_scan(lab, mi, 0, True, span=span)
            return jnp.where(mask, lab, _BIG)

        # Fixpoint loop with a CHEAP certificate: min-label flow moves only
        # along adjacency, so stability under a span-2 sweep (one shift per
        # direction, 4 steps) IS global stability — the expensive work
        # sweep (span ``work_span`` or full; any span is correct, the
        # certificate carries exactness) runs only when the cheap sweep
        # found motion.  Warm-started levels whose labels are already final
        # cost 4 steps instead of a full proof sweep (measured ~1.6x).
        def body(st):
            lab, _ = st
            c = sweep(lab, span=2)
            changed = jnp.any(c != lab)
            lab = lax.cond(
                changed, lambda l: sweep(l, span=work_span or None),
                lambda l: l, c)
            return lab, changed

        lab, _ = lax.while_loop(lambda st: st[1], body, (lab0, True))
        cnt = jnp.sum(((lab == iota) & mask).astype(jnp.int32), axis=0,
                      keepdims=True)                   # (1, IBC) per-lane
        return acc + cnt, lab

    acc = jnp.zeros((1, shape[1]), jnp.int32)
    big = jnp.full(shape, _BIG, jnp.int32)
    out_ref[:] = lax.fori_loop(0, nlevels, level_body, (acc, big))[0]


# Scoped-VMEM budget for one program's block, in CELLS (rows x lanes).  The
# hoisted-flag kernel's live intermediates (labels, open flags, masks,
# shifted copies) cost ~133 B/cell against the 16 MB scoped limit (measured:
# a 256x512 block = 131072 cells OOMed at 17.46 MB), so cap blocks at
# ~13 MB.  The LEAN kernel (flags rematerialized per sweep) drops the
# per-level hoisted arrays and fits ~3x more cells — 512x512 = 262144 cells
# verified on v5e — at ~10-20% more vector ops per sweep.
_MAX_CELLS = 96 * 1024
_MAX_CELLS_LEAN = 288 * 1024

# Strip-kernel budget: cells of ONE strip block (strip_rows + 2*_HALO rows x
# padded cols).  Live arrays per strip visit: the two persistent scratches
# (image f32 + labels i32) plus the sweep transients (lab_in, shifted
# copies, flags) — leaner liveness than the packed kernel's per-level
# hoists, but two resident scratches, so the budget sits between _MAX_CELLS
# and _MAX_CELLS_LEAN.
_MAX_CELLS_STRIP = 192 * 1024
_HALO = 8                     # halo rows above/below a strip: 8 keeps every
                              # DMA row offset (s*strip and s*strip+_HALO)
                              # provably sublane-aligned for Mosaic; the
                              # extra halo rows only help propagation


def _pack_geometry(nrows: int, ncols: int, lane_width: int,
                   max_cells: int = _MAX_CELLS) -> tuple[int, int, int]:
    """(R_pad, C_pad, IB): pad cols so IB*C_pad == lane block width.

    The lane width shrinks when rows are tall so R_pad * lanes stays within
    the scoped-VMEM budget; images whose padded column span still exceeds
    the budget don't fit — callers check ``fits_vmem`` and fall back to the
    associative-scan path."""
    rp = -(-nrows // 8) * 8
    budget = max(128, (max_cells // rp) // 128 * 128)
    lane_width = min(lane_width, budget)
    if ncols <= lane_width:
        cp = ncols
        # smallest divisor layout: pad cols up until it divides the lane width
        while lane_width % cp != 0:
            cp += 1
        ib = lane_width // cp
    else:
        cp = -(-ncols // 128) * 128
        ib = 1
    return rp, cp, ib


def fits_vmem(nrows: int, ncols: int, lane_width: int = 512) -> bool:
    """True when one program's block fits SOME kernel variant's budget
    (packed fast kernel, or the lean wide-image kernel)."""
    rp, cp, ib = _pack_geometry(nrows, ncols, lane_width, _MAX_CELLS_LEAN)
    return rp * cp * ib <= _MAX_CELLS_LEAN


@functools.partial(jax.jit, static_argnames=(
    "nrows", "ncols", "nlevels", "lane_width", "interpret", "work_span"))
def chaos_count_sums(
    principal: jnp.ndarray,   # (N, n_pix) f32, n_pix == nrows*ncols
    *,
    nrows: int,
    ncols: int,
    nlevels: int = 30,
    lane_width: int = 512,
    interpret: bool = False,
    # 32 measured best on blob-heavy 256x256 batches (1377 -> 1010 ms/512
    # ions vs full-span; spans are result-invariant — the span-2 certificate
    # carries exactness, work sweeps only accelerate)
    work_span: int = 32,
) -> jnp.ndarray:
    """(N,) f32: per-image SUM over levels of connected-component counts.

    chaos = 1 - (sum/nlevels)/n_notnull is applied by the caller (exact: the
    sums are small integers, f32-representable).
    """
    n = principal.shape[0]
    rp, cp, ib = _pack_geometry(nrows, ncols, lane_width)
    lean = rp * cp * ib > _MAX_CELLS
    if lean:
        # wide image: re-pack against the lean kernel's larger budget
        rp, cp, ib = _pack_geometry(nrows, ncols, lane_width, _MAX_CELLS_LEAN)
    if rp * cp * ib > _MAX_CELLS_LEAN and not interpret:
        raise ValueError(
            f"chaos kernel block ({rp}x{cp * ib} cells) exceeds the scoped-"
            f"VMEM budget ({_MAX_CELLS_LEAN}); check fits_vmem() and use the "
            "associative-scan path (measure_of_chaos_batch use_pallas=False)"
        )
    n_pad = -(-n // ib) * ib
    img = jnp.zeros((n_pad, rp, cp), jnp.float32)
    img = img.at[:n, :nrows, :ncols].set(
        jnp.maximum(principal.reshape(n, nrows, ncols), 0.0))
    vmax = img.max(axis=(1, 2))                        # (n_pad,)

    # lanes-of-images layout: (R, n_pad*C); image i occupies lanes [i*C,(i+1)*C)
    img_l = img.transpose(1, 0, 2).reshape(rp, n_pad * cp)
    vmax_l = jnp.repeat(vmax, cp).reshape(1, n_pad * cp)

    grid = (n_pad // ib,)
    ibc = ib * cp
    counts = pl.pallas_call(
        functools.partial(_chaos_kernel, ncols=cp, nlevels=nlevels, lean=lean,
                          work_span=work_span),
        out_shape=jax.ShapeDtypeStruct((1, n_pad * cp), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rp, ibc), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, ibc), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, ibc), lambda i: (0, i), memory_space=pltpu.VMEM),
        interpret=interpret,
    )(img_l, vmax_l)
    # per-image count sum: reduce each image's cp lanes
    return counts.reshape(n_pad, cp).sum(axis=1)[:n].astype(jnp.float32)


# ---------------------------------------------------------------------------
# Strip-processed kernel: images beyond the lean whole-image budget
# (>~288k cells, e.g. 1024x1024 whole-slide DESI) — VERDICT r3 item 4b.
#
# The image and a label plane live in HBM; row strips (with _HALO read-only
# halo rows on each side) stream through VMEM, each swept to its LOCAL
# fixpoint with the same segmented min-scans as the packed kernel.  Passes
# alternate top-down / bottom-up over the strips and repeat until one
# complete pass changes no core label — a valid GLOBAL certificate: every
# halo row is some neighbor's core row, so any pixel unstable against the
# end-of-pass state would have changed during its own strip's visit.
#
# Correctness anchors:
# - labels only ever DECREASE toward the component min (min-label flood);
#   reading a STALE halo value is therefore always an upper bound of the
#   true min and can never poison a component (monotone convergence);
# - the on-load transform  lab = where(mask, min(lab, iota), BIG)  is
#   idempotent and level-monotone (masks only grow descending levels), so
#   warm starts across levels need no per-level init or write-back: a strip
#   whose sweep changed nothing is simply not written, and the count pass
#   re-applies the transform on load;
# - empty strips (per-strip max <= threshold) are skipped without DMA:
#   masks grow monotonically going down levels, so a strip empty at this
#   level was empty at every earlier level and its labels are still the
#   init-pass BIG.
# ---------------------------------------------------------------------------


def _chaos_strip_kernel(smax_ref, img_ref, out_ref, lab_hbm, img_vmem,
                        lab_vmem, sems, *, ncols: int, nrows_pad: int,
                        strip_rows: int, nlevels: int, work_span: int):
    """One program: one image, (nrows_pad + 2*_HALO, ncols) in HBM."""
    pid = pl.program_id(0)
    n_strips = nrows_pad // strip_rows
    rb = strip_rows + 2 * _HALO                       # block rows
    shape = (rb, ncols)
    lrow = lax.broadcasted_iota(jnp.int32, shape, 0)
    col = lax.broadcasted_iota(jnp.int32, shape, 1)
    core = (lrow >= _HALO) & (lrow < _HALO + strip_rows)
    vmax = smax_ref[pid, n_strips]

    def load_strip(s, *, want_img: bool):
        r0 = pl.multiple_of(s * strip_rows, 8)
        cp_l = pltpu.make_async_copy(
            lab_hbm.at[pl.ds(r0, rb), :], lab_vmem, sems.at[0])
        cp_l.start()
        if want_img:
            cp_i = pltpu.make_async_copy(
                img_ref.at[pid, pl.ds(r0, rb), :], img_vmem, sems.at[1])
            cp_i.start()
            cp_i.wait()
        cp_l.wait()

    def giota(s):
        # global pixel id of each block cell (halo rows get their true ids
        # too — assigning a masked halo pixel its own iota is always a valid
        # upper bound of its component min, and accelerates convergence)
        return (s * strip_rows + lrow - _HALO) * ncols + col

    # ---- init: labels <- BIG everywhere (strip writes overlap on halos;
    # same value, so overlap is harmless) ----
    lab_vmem[:] = jnp.full(shape, _BIG, jnp.int32)

    def init_body(s, _):
        cp = pltpu.make_async_copy(
            lab_vmem,
            lab_hbm.at[pl.ds(pl.multiple_of(s * strip_rows, 8), rb), :],
            sems.at[0])
        cp.start()
        cp.wait()
        return _

    lax.fori_loop(0, n_strips, init_body, 0)

    def sweep_strip(mask, lab, span):
        mi = mask.astype(jnp.int32)
        lab = _seg_min_scan(lab, mi, 1, False,
                            span=min(span or ncols, ncols))
        lab = _seg_min_scan(lab, mi, 1, True,
                            span=min(span or ncols, ncols))
        lab = _seg_min_scan(lab, mi, 0, False, span=min(span or rb, rb))
        lab = _seg_min_scan(lab, mi, 0, True, span=min(span or rb, rb))
        return jnp.where(mask, lab, _BIG)

    def level_body(li_rev, acc):
        li = nlevels - 1 - li_rev                     # descending thresholds
        thr = vmax * (li.astype(jnp.float32) / np.float32(nlevels))

        def visit(s):
            """Returns True when the strip's core labels changed (written)."""
            load_strip(s, want_img=True)
            mask = img_vmem[:] > thr
            lab_in = jnp.where(mask, jnp.minimum(lab_vmem[:], giota(s)), _BIG)

            def body(st):
                lab, _ = st
                c = sweep_strip(mask, lab, 2)         # cheap certificate
                moved = jnp.any(c != lab)
                lab = lax.cond(
                    moved, lambda l: sweep_strip(mask, l, work_span),
                    lambda l: l, c)
                return lab, moved

            lab_fin, _ = lax.while_loop(lambda st: st[1], body,
                                        (lab_in, jnp.array(True, dtype=jnp.bool_)))
            changed = jnp.any((lab_fin != lab_in) & core)

            @pl.when(changed)
            def _():
                lab_vmem[:] = lab_fin
                cp = pltpu.make_async_copy(
                    lab_vmem.at[pl.ds(_HALO, strip_rows), :],
                    lab_hbm.at[pl.ds(
                        pl.multiple_of(s * strip_rows + _HALO, 8),
                        strip_rows), :],
                    sems.at[0])
                cp.start()
                cp.wait()

            return changed

        def pass_body(st):
            p, _ = st

            def strip_body(i, any_changed):
                # alternate top-down / bottom-up passes so flows in either
                # direction cascade across all boundaries within one pass
                s = jnp.where(p % 2 == 0, i, n_strips - 1 - i)
                nonempty = smax_ref[pid, s] > thr
                ch = lax.cond(nonempty, visit, lambda _s: jnp.array(False, dtype=jnp.bool_), s)
                return jnp.logical_or(any_changed, ch)

            changed = lax.fori_loop(0, n_strips, strip_body, jnp.array(False, dtype=jnp.bool_))
            return p + 1, changed

        lax.while_loop(lambda st: st[1], pass_body,
                       (jnp.int32(0), jnp.array(True, dtype=jnp.bool_)))

        # ---- count roots: label == own iota (transform re-applied on load
        # because converged strips skip write-back) ----
        def count_body(s, lvl_acc):
            def counted(s):
                load_strip(s, want_img=True)
                mask = img_vmem[:] > thr
                gi = giota(s)
                lab = jnp.where(mask, jnp.minimum(lab_vmem[:], gi), _BIG)
                return jnp.sum((core & mask & (lab == gi)).astype(jnp.int32))

            return lvl_acc + lax.cond(smax_ref[pid, s] > thr, counted,
                                      lambda _s: jnp.int32(0), s)

        return acc + lax.fori_loop(0, n_strips, count_body, jnp.int32(0))

    out_ref[pid, 0] = lax.fori_loop(0, nlevels, level_body, jnp.int32(0))


def _strip_geometry(nrows: int, ncols: int,
                    strip_rows: int | None = None) -> tuple[int, int, int]:
    """(nrows_pad, ncols_pad, strip_rows) for the strip kernel.

    ``strip_rows`` overrides the budget-derived strip height (multiple of 8;
    tests use it to exercise multi-strip flows on small images)."""
    cp = -(-ncols // 128) * 128
    strip = (_MAX_CELLS_STRIP // cp - 2 * _HALO) // 8 * 8
    if strip_rows is not None:
        strip = strip_rows
    if (strip < 8 or strip % 8
            or (strip + 2 * _HALO) * cp > _MAX_CELLS_STRIP):
        raise ValueError(
            f"no valid strip height for the strip chaos kernel: {ncols} "
            f"cols (padded {cp}) with strip_rows={strip} against the "
            f"{_MAX_CELLS_STRIP}-cell budget")
    strip = min(strip, -(-nrows // 8) * 8)
    rp = -(-nrows // strip) * strip
    return rp, cp, strip


def chaos_route(nrows: int, ncols: int, lane_width: int = 512) -> str:
    """'packed' (whole image(s) in VMEM), 'strips' (HBM-resident labels,
    strips through VMEM), or 'scan' (associative-scan fallback)."""
    if fits_vmem(nrows, ncols, lane_width):
        return "packed"
    try:
        _strip_geometry(nrows, ncols)
        return "strips"
    except ValueError:
        return "scan"


@functools.partial(jax.jit, static_argnames=(
    "nrows", "ncols", "nlevels", "interpret", "work_span", "strip_rows"))
def chaos_count_sums_strips(
    principal: jnp.ndarray,   # (N, n_pix) f32, n_pix == nrows*ncols
    *,
    nrows: int,
    ncols: int,
    nlevels: int = 30,
    interpret: bool = False,
    work_span: int = 32,
    strip_rows: int | None = None,
) -> jnp.ndarray:
    """(N,) f32 per-image SUM over levels of component counts — the strip
    kernel's twin of chaos_count_sums, for images beyond the lean budget."""
    n = principal.shape[0]
    rp, cp, strip = _strip_geometry(nrows, ncols, strip_rows)
    n_strips = rp // strip
    # guard/pad fill is -1: masks are img > thr with thr >= 0, so guard
    # rows, halo overhang and col padding can never enter a component
    img = jnp.full((n, rp + 2 * _HALO, cp), -1.0, jnp.float32)
    img = img.at[:, _HALO:_HALO + nrows, :ncols].set(
        jnp.maximum(principal.reshape(n, nrows, ncols), 0.0))
    body = img[:, _HALO:_HALO + rp, :]
    smax = body.reshape(n, n_strips, strip * cp).max(axis=2)   # (N, S)
    vmax = smax.max(axis=1, keepdims=True)                     # (N, 1)
    smax_v = jnp.concatenate([smax, vmax], axis=1)             # (N, S+1)

    counts, _labels = pl.pallas_call(
        functools.partial(_chaos_strip_kernel, ncols=cp, nrows_pad=rp,
                          strip_rows=strip, nlevels=nlevels,
                          work_span=work_span),
        # the label plane is an OUTPUT in compiler-managed (HBM) memory,
        # not a scratch: Mosaic only allocates vmem/smem/semaphore scratch.
        # It is shared by all (sequential) grid steps — each program
        # re-inits it — and its final value is discarded.
        out_shape=(jax.ShapeDtypeStruct((n, 1), jnp.int32),
                   jax.ShapeDtypeStruct((rp + 2 * _HALO, cp), jnp.int32)),
        grid=(n,),
        in_specs=[
            # whole-array SMEM block (scalars): TPU lowering forbids partial
            # blocks that aren't 8x128-aligned, so index by program id
            pl.BlockSpec((n, n_strips + 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=(
            # whole-array SMEM out block (scalar per program) for the same
            # TPU alignment reason; each program writes its own row
            pl.BlockSpec((n, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ),
        scratch_shapes=[
            pltpu.VMEM((strip + 2 * _HALO, cp), jnp.float32),
            pltpu.VMEM((strip + 2 * _HALO, cp), jnp.int32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(smax_v, img)
    return counts.reshape(n).astype(jnp.float32)
