"""MSM metrics, NumPy reference backend.

Reference: ``sm/engine/msm_basic/formula_img_validator.py`` [U] (SURVEY.md
#11, call stack §3.4) computes, per ion, via ``pyImagingMSpec``:

- ``measure_of_chaos(img, nlevels)`` — spatial informativeness: 1 minus the
  mean connected-component count of the principal-peak image thresholded at
  ``nlevels`` levels, normalized by the nonzero-pixel count (Palmer et al.
  2017, Nature Methods 14:57, "measure of spatial chaos");
- ``isotope_image_correlation(imgs, weights)`` — intensity-weighted mean
  Pearson correlation between the principal image and each higher-isotope
  image;
- ``isotope_pattern_match(imgs_total_ints, theor_ints)`` — cosine agreement
  between theoretical and observed total-intensity isotope envelopes.

MSM = chaos * spatial * spectral.  Optional hot-spot removal first: clip each
image at its q-th percentile of positive values (``do_preprocessing``/``q``).

This module is the parity oracle for the TPU backend (ops/metrics_jax.py):
the exact threshold grid, connectivity (4-neighbour), and clipping rules here
are the spec.
"""

from __future__ import annotations

import numpy as np
from scipy import ndimage

# 4-connectivity (cross) — scipy.ndimage.label default, matches the reference.
_STRUCTURE4 = np.array([[0, 1, 0], [1, 1, 1], [0, 1, 0]], dtype=int)


def hotspot_percentile_f32(pos_sorted: np.ndarray, q: float) -> np.float32:
    """q-th linear-interpolated percentile of the sorted positive pixels,
    computed as a fixed sequence of SINGLE f32 operations.

    This sequence is the cross-backend DEFINITION of the hotspot cutoff
    (VERDICT r2 item 4): every step is either exact in f32 (floor,
    fraction < 2**23, differences of grid integers) or one correctly-rounded
    IEEE op, so numpy here and XLA on TPU produce the same bits; and because
    image values are integers times a power-of-two scale
    (ops/quantize.py), the arithmetic commutes with the scale — the jax
    backend computes it in quantized units, this oracle in raw units, and
    the clipped images still match bit for bit."""
    m = pos_sorted.size
    t = np.float32(q) / np.float32(100.0)
    pos = t * np.float32(m - 1)                   # one rounded mul
    lo = np.floor(pos)                            # exact
    frac = np.float32(pos - lo)                   # exact (pos < 2**23)
    i_lo = int(lo)
    v_lo = np.float32(pos_sorted[i_lo])
    v_hi = np.float32(pos_sorted[min(i_lo + 1, m - 1)])
    prod = np.float32(v_hi - v_lo) * frac         # exact diff, one mul
    return v_lo + prod                            # one rounded add


def hotspot_clip(img: np.ndarray, q: float = 99.0) -> np.ndarray:
    """Hot-spot removal (reference img_gen.do_preprocessing [U]): clip at the
    q-th percentile of the positive pixels; no-op on empty images."""
    pos = np.sort(img[img > 0])
    if pos.size == 0:
        return img
    return np.minimum(img, hotspot_percentile_f32(pos, q))


def measure_of_chaos(img: np.ndarray, nlevels: int = 30) -> float:
    """Spatial chaos of a 2-D image in [0, 1]; 0 for empty images.

    Thresholds: ``nlevels`` levels evenly spaced in (0, max) — level i is
    ``vmax * i/nlevels`` (level 0 counts the support's components).
    Connectivity: 4-neighbour.

    The threshold grid and the final mean/normalize arithmetic are computed
    in float32, mirroring the TPU kernel bit for bit: at integer-grid image
    magnitudes (up to 2**24) the f32/f64 threshold representations can differ
    by ~0.5, enough to flip a mask pixel — the f32 grid is the definition,
    in both backends (exact-FDR-rank requirement).
    """
    img = np.nan_to_num(np.asarray(img, dtype=np.float32))
    img = np.where(img > 0, img, np.float32(0.0))
    vmax = np.float32(img.max())
    n_notnull = int((img > 0).sum())
    if vmax <= 0 or n_notnull == 0:
        return 0.0
    count_sum = 0
    for i in range(nlevels):
        lev = vmax * (np.float32(i) / np.float32(nlevels))
        _, n = ndimage.label(img > lev, structure=_STRUCTURE4)
        count_sum += n
    # single division, mirroring the TPU kernel (see metrics_jax: a constant
    # divisor would be strength-reduced to a reciprocal multiply by XLA)
    chaos = np.float32(1.0) - np.float32(count_sum) / np.float32(
        nlevels * max(n_notnull, 1))
    return float(np.clip(chaos, np.float32(0.0), np.float32(1.0)))


def isotope_image_correlation(
    images_flat: np.ndarray, weights: np.ndarray
) -> float:
    """Weighted mean Pearson correlation of higher-isotope images vs the
    principal image.  ``images_flat``: (n_peaks, n_pixels); ``weights``:
    theoretical intensities of peaks 1..n-1 (reference passes
    ``theor_ints[1:]`` [U]).  NaN correlations (constant images) count as 0;
    result clipped to [0, 1]."""
    images_flat = np.asarray(images_flat, dtype=np.float64)
    n_peaks = images_flat.shape[0]
    if n_peaks < 2:
        return 0.0
    base = images_flat[0]
    corrs = np.zeros(n_peaks - 1)
    bc = base - base.mean()
    bn = np.sqrt((bc * bc).sum())
    for k in range(1, n_peaks):
        x = images_flat[k]
        xc = x - x.mean()
        xn = np.sqrt((xc * xc).sum())
        if bn > 0 and xn > 0:
            corrs[k - 1] = (bc * xc).sum() / (bn * xn)
    weights = np.asarray(weights, dtype=np.float64)[: n_peaks - 1]
    wsum = weights.sum()
    if wsum <= 0:
        return 0.0
    return float(np.clip((corrs * weights).sum() / wsum, 0.0, 1.0))


def isotope_pattern_match(
    image_total_ints: np.ndarray, theor_ints: np.ndarray
) -> float:
    """Cosine similarity between observed total-intensity envelope and the
    theoretical envelope, in [0, 1]; 0 if either is empty."""
    obs = np.asarray(image_total_ints, dtype=np.float64)
    theor = np.asarray(theor_ints, dtype=np.float64)
    on = np.linalg.norm(obs)
    tn = np.linalg.norm(theor)
    if on == 0 or tn == 0:
        return 0.0
    return float(np.clip(np.dot(obs, theor) / (on * tn), 0.0, 1.0))


def ion_metrics(
    images: np.ndarray,
    theor_ints: np.ndarray,
    n_valid: int,
    nrows: int,
    ncols: int,
    nlevels: int = 30,
    do_preprocessing: bool = False,
    q: float = 99.0,
) -> tuple[float, float, float, float]:
    """(chaos, spatial, spectral, msm) for one ion.

    ``images``: (max_peaks, n_pixels) dense; only the first ``n_valid`` rows
    are real isotope peaks.  Mirrors the reference's per-ion map function
    ``get_compute_img_metrics`` [U].
    """
    imgs = images[:n_valid].astype(np.float64)
    if n_valid == 0 or imgs[0].max() <= 0:
        return 0.0, 0.0, 0.0, 0.0
    if do_preprocessing:
        imgs = np.stack([hotspot_clip(im, q) for im in imgs])
    chaos = measure_of_chaos(imgs[0].reshape(nrows, ncols), nlevels)
    spatial = isotope_image_correlation(imgs, weights=theor_ints[1:n_valid])
    spectral = isotope_pattern_match(imgs.sum(axis=1), theor_ints[:n_valid])
    msm = chaos * spatial * spectral
    return chaos, spatial, spectral, msm
