"""Native imzML + ibd reader/writer.

The reference parses imzML via the external ``pyimzML`` library inside
``sm/engine/imzml_txt_converter.py::ImzmlTxtConverter.convert`` [U]
(SURVEY.md #4) and round-trips through a line-per-spectrum text file for
Spark.  We parse the binary format natively and keep everything as numpy
arrays — there is no text intermediate; the cube builder (io/dataset.py)
consumes the arrays directly.

Format essentials (imzML 1.1, built on mzML 1.1):
- ``.imzML``: XML; file-level cvParam IMS:1000030 (continuous) or
  IMS:1000031 (processed); per-spectrum scan position IMS:1000050/51 (x/y);
  per-binaryDataArray external byte offset IMS:1000102, array length
  IMS:1000103, encoded length IMS:1000104; array kind MS:1000514 (m/z) /
  MS:1000515 (intensity); dtype MS:1000521/523/519/522 (f32/f64/i32/i64).
  Array kind + dtype commonly live in a referenceableParamGroup.
- ``.ibd``: 16-byte UUID (must match imzML IMS:1000080), then raw arrays.
  Continuous mode: one shared m/z array, per-spectrum intensity arrays.
"""

from __future__ import annotations

import uuid as uuid_mod
import xml.etree.ElementTree as ET
from dataclasses import dataclass
from pathlib import Path

import numpy as np

from ..utils.failpoints import failpoint, register_failpoint

FP_IMZML_PARSE = register_failpoint(
    "io.imzml_parse", "start of imzML XML parse (corrupt/unreadable imzML)")
FP_IBD_READ = register_failpoint(
    "io.ibd_read", "per-array ibd read (I/O error / truncation mid-ingest)")

_DTYPES = {
    "MS:1000521": np.dtype("<f4"),
    "MS:1000523": np.dtype("<f8"),
    "MS:1000519": np.dtype("<i4"),
    "MS:1000522": np.dtype("<i8"),
    # IMS legacy aliases seen in the wild
    "IMS:1000101": np.dtype("<f4"),
}
_MZ_ARRAY = "MS:1000514"
_INT_ARRAY = "MS:1000515"
_CONTINUOUS = "IMS:1000030"
_PROCESSED = "IMS:1000031"
_UUID = "IMS:1000080"
_POS_X = "IMS:1000050"
_POS_Y = "IMS:1000051"
_EXT_OFFSET = "IMS:1000102"
_EXT_ARR_LEN = "IMS:1000103"


def _local(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


@dataclass
class _ArrayRef:
    offset: int
    length: int
    dtype: np.dtype


@dataclass
class SpectrumRef:
    """Lazy handle to one spectrum's arrays in the ibd file."""
    x: int
    y: int
    mz: _ArrayRef
    intensity: _ArrayRef


class ImzMLParseError(ValueError):
    pass


class ImzMLReader:
    """Streams spectra out of an imzML/ibd pair.

    Usage::
        rd = ImzMLReader("ds.imzML")
        for i in range(rd.n_spectra):
            x, y = rd.coordinates[i]
            mzs, ints = rd.read_spectrum(i)
    """

    def __init__(self, imzml_path: str | Path, ibd_path: str | Path | None = None):
        self.imzml_path = Path(imzml_path)
        self.ibd_path = Path(ibd_path) if ibd_path else self.imzml_path.with_suffix(".ibd")
        if not self.ibd_path.exists():
            # handle .imzml/.IBD case variants
            for cand in self.imzml_path.parent.glob("*"):
                if cand.suffix.lower() == ".ibd" and cand.stem == self.imzml_path.stem:
                    self.ibd_path = cand
                    break
        if not self.ibd_path.exists():
            raise FileNotFoundError(f"ibd file for {self.imzml_path} not found")
        self.continuous: bool | None = None
        self.uuid: str | None = None
        self.spectra: list[SpectrumRef] = []
        self._parse_xml()
        self._ibd = open(self.ibd_path, "rb")
        self._check_uuid()

    # -- parsing ---------------------------------------------------------

    def _parse_xml(self) -> None:
        failpoint(FP_IMZML_PARSE, path=self.imzml_path)
        param_groups: dict[str, list[tuple[str, str]]] = {}
        cur_group: str | None = None
        in_spectrum = False
        pos_x = pos_y = None
        arrays: list[dict] = []
        cur_array: dict | None = None

        for event, elem in ET.iterparse(self.imzml_path, events=("start", "end")):
            tag = _local(elem.tag)
            if event == "start":
                if tag == "referenceableParamGroup":
                    cur_group = elem.get("id")
                    param_groups[cur_group] = []
                elif tag == "spectrum":
                    in_spectrum = True
                    pos_x = pos_y = None
                    arrays = []
                elif tag == "binaryDataArray" and in_spectrum:
                    cur_array = {"accessions": {}}
                continue

            # end events
            if tag == "cvParam":
                acc = elem.get("accession", "")
                val = elem.get("value", "")
                if cur_group is not None and not in_spectrum:
                    param_groups[cur_group].append((acc, val))
                elif cur_array is not None:
                    cur_array["accessions"][acc] = val
                elif in_spectrum:
                    if acc == _POS_X:
                        pos_x = int(float(val))
                    elif acc == _POS_Y:
                        pos_y = int(float(val))
                else:
                    if acc == _CONTINUOUS:
                        self.continuous = True
                    elif acc == _PROCESSED:
                        self.continuous = False
                    elif acc == _UUID:
                        self.uuid = val.strip("{}").replace("-", "").lower()
            elif tag == "referenceableParamGroupRef" and cur_array is not None:
                ref = elem.get("ref")
                for acc, val in param_groups.get(ref, []):
                    cur_array["accessions"].setdefault(acc, val)
            elif tag == "binaryDataArray" and cur_array is not None:
                arrays.append(cur_array)
                cur_array = None
            elif tag == "spectrum":
                self._finish_spectrum(pos_x, pos_y, arrays)
                in_spectrum = False
                elem.clear()
            elif tag in ("spectrumList", "run", "mzML"):
                elem.clear()

        if self.continuous is None:
            raise ImzMLParseError(
                f"{self.imzml_path}: neither continuous ({_CONTINUOUS}) nor "
                f"processed ({_PROCESSED}) file-content cvParam found"
            )
        if not self.spectra:
            raise ImzMLParseError(f"{self.imzml_path}: no spectra")

    def _finish_spectrum(self, pos_x, pos_y, arrays) -> None:
        if pos_x is None or pos_y is None:
            raise ImzMLParseError(
                f"{self.imzml_path}: spectrum {len(self.spectra)} missing scan position"
            )
        mz_ref = int_ref = None
        for arr in arrays:
            acc = arr["accessions"]
            dtype = None
            for code, dt in _DTYPES.items():
                if code in acc:
                    dtype = dt
                    break
            if dtype is None or _EXT_OFFSET not in acc or _EXT_ARR_LEN not in acc:
                raise ImzMLParseError(
                    f"{self.imzml_path}: binaryDataArray missing dtype/offset/length"
                )
            ref = _ArrayRef(
                offset=int(acc[_EXT_OFFSET]), length=int(acc[_EXT_ARR_LEN]), dtype=dtype
            )
            if _MZ_ARRAY in acc:
                mz_ref = ref
            elif _INT_ARRAY in acc:
                int_ref = ref
        if mz_ref is None or int_ref is None:
            raise ImzMLParseError(
                f"{self.imzml_path}: spectrum {len(self.spectra)} lacks m/z or intensity array"
            )
        self.spectra.append(SpectrumRef(x=pos_x, y=pos_y, mz=mz_ref, intensity=int_ref))

    def _check_uuid(self) -> None:
        raw = self._ibd.read(16)
        if len(raw) != 16:
            raise ImzMLParseError(f"{self.ibd_path}: shorter than the 16-byte UUID header")
        if self.uuid and raw.hex() != self.uuid:
            raise ImzMLParseError(
                f"ibd UUID {raw.hex()} does not match imzML UUID {self.uuid}"
            )

    # -- access ----------------------------------------------------------

    @property
    def n_spectra(self) -> int:
        return len(self.spectra)

    @property
    def coordinates(self) -> np.ndarray:
        """(n_spectra, 2) int array of raw (x, y) scan positions."""
        return np.array([(s.x, s.y) for s in self.spectra], dtype=np.int64)

    def _read_array(self, ref: _ArrayRef) -> np.ndarray:
        failpoint(FP_IBD_READ, path=self.ibd_path)
        self._ibd.seek(ref.offset)
        raw = self._ibd.read(ref.length * ref.dtype.itemsize)
        if len(raw) != ref.length * ref.dtype.itemsize:
            raise ImzMLParseError(f"{self.ibd_path}: truncated read at offset {ref.offset}")
        return np.frombuffer(raw, dtype=ref.dtype)

    def spectrum_lengths(self) -> np.ndarray:
        """(n_spectra,) int64 peak counts WITHOUT touching the ibd data —
        lengths come from the XML array metadata, which is what lets
        ingestion preallocate exact CSR arrays and stream spectra into them
        with bounded working memory (SpectralDataset.from_imzml)."""
        return np.array([s.mz.length for s in self.spectra], dtype=np.int64)

    def read_spectrum(self, i: int) -> tuple[np.ndarray, np.ndarray]:
        """(mzs float64, intensities float32) of spectrum i."""
        s = self.spectra[i]
        mzs = self._read_array(s.mz).astype(np.float64)
        ints = self._read_array(s.intensity).astype(np.float32)
        if mzs.shape != ints.shape:
            raise ImzMLParseError(f"spectrum {i}: mz/intensity length mismatch")
        return mzs, ints

    def close(self) -> None:
        self._ibd.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ImzMLWriter:
    """Writes spectra to an imzML/ibd pair (both modes). Used by the synthetic
    fixture generator and by tests; also gives users a migration path off
    text dumps."""

    def __init__(self, path: str | Path, continuous: bool = False,
                 mz_dtype=np.float64, int_dtype=np.float32):
        self.imzml_path = Path(path)
        self.ibd_path = self.imzml_path.with_suffix(".ibd")
        self.continuous = continuous
        self.mz_dtype = np.dtype(mz_dtype)
        self.int_dtype = np.dtype(int_dtype)
        self._uuid = uuid_mod.uuid4()
        self._ibd = open(self.ibd_path, "wb")
        self._ibd.write(self._uuid.bytes)
        self._offset = 16
        self._shared_mz_ref: _ArrayRef | None = None
        self._entries: list[tuple[int, int, _ArrayRef, _ArrayRef]] = []

    def _write_array(self, data: np.ndarray, dtype: np.dtype) -> _ArrayRef:
        buf = np.ascontiguousarray(data, dtype=dtype).tobytes()
        self._ibd.write(buf)
        ref = _ArrayRef(offset=self._offset, length=len(data), dtype=dtype)
        self._offset += len(buf)
        return ref

    def add_spectrum(self, x: int, y: int, mzs: np.ndarray, ints: np.ndarray) -> None:
        if len(mzs) != len(ints):
            raise ValueError("mzs and ints must have equal length")
        if self.continuous:
            if self._shared_mz_ref is None:
                self._shared_mz_ref = self._write_array(mzs, self.mz_dtype)
            elif self._shared_mz_ref.length != len(mzs):
                raise ValueError("continuous mode requires identical m/z axes")
            mz_ref = self._shared_mz_ref
        else:
            mz_ref = self._write_array(mzs, self.mz_dtype)
        int_ref = self._write_array(ints, self.int_dtype)
        self._entries.append((x, y, mz_ref, int_ref))

    _DTYPE_CV = {
        np.dtype("<f4"): ('MS:1000521', '32-bit float'),
        np.dtype("<f8"): ('MS:1000523', '64-bit float'),
        np.dtype("<i4"): ('MS:1000519', '32-bit integer'),
        np.dtype("<i8"): ('MS:1000522', '64-bit integer'),
    }

    def close(self) -> None:
        self._ibd.close()
        mode_acc, mode_name = (
            (_CONTINUOUS, "continuous") if self.continuous else (_PROCESSED, "processed")
        )
        mz_cv, mz_cv_name = self._DTYPE_CV[self.mz_dtype]
        int_cv, int_cv_name = self._DTYPE_CV[self.int_dtype]
        xs = [e[0] for e in self._entries]
        ys = [e[1] for e in self._entries]
        out = []
        w = out.append
        w('<?xml version="1.0" encoding="ISO-8859-1"?>')
        w('<mzML xmlns="http://psi.hupo.org/ms/mzml" version="1.1">')
        w('  <cvList count="2">')
        w('    <cv id="MS" fullName="Proteomics Standards Initiative Mass Spectrometry Ontology"/>')
        w('    <cv id="IMS" fullName="Imaging MS Ontology"/>')
        w('  </cvList>')
        w('  <fileDescription><fileContent>')
        w(f'    <cvParam cvRef="IMS" accession="{mode_acc}" name="{mode_name}"/>')
        w(f'    <cvParam cvRef="IMS" accession="{_UUID}" name="universally unique identifier" '
          f'value="{{{self._uuid}}}"/>')
        w('  </fileContent></fileDescription>')
        w('  <referenceableParamGroupList count="2">')
        w('    <referenceableParamGroup id="mzArray">')
        w('      <cvParam cvRef="MS" accession="MS:1000514" name="m/z array"/>')
        w(f'      <cvParam cvRef="MS" accession="{mz_cv}" name="{mz_cv_name}"/>')
        w('    </referenceableParamGroup>')
        w('    <referenceableParamGroup id="intensityArray">')
        w('      <cvParam cvRef="MS" accession="MS:1000515" name="intensity array"/>')
        w(f'      <cvParam cvRef="MS" accession="{int_cv}" name="{int_cv_name}"/>')
        w('    </referenceableParamGroup>')
        w('  </referenceableParamGroupList>')
        w('  <scanSettingsList count="1"><scanSettings id="scan1">')
        w(f'    <cvParam cvRef="IMS" accession="IMS:1000042" name="max count of pixels x" '
          f'value="{max(xs) if xs else 0}"/>')
        w(f'    <cvParam cvRef="IMS" accession="IMS:1000043" name="max count of pixels y" '
          f'value="{max(ys) if ys else 0}"/>')
        w('  </scanSettings></scanSettingsList>')
        w('  <run id="run1">')
        w(f'  <spectrumList count="{len(self._entries)}">')
        for i, (x, y, mz_ref, int_ref) in enumerate(self._entries):
            w(f'    <spectrum id="spectrum={i}" index="{i}" defaultArrayLength="{mz_ref.length}">')
            w('      <scanList count="1"><scan>')
            w(f'        <cvParam cvRef="IMS" accession="{_POS_X}" name="position x" value="{x}"/>')
            w(f'        <cvParam cvRef="IMS" accession="{_POS_Y}" name="position y" value="{y}"/>')
            w('      </scan></scanList>')
            w('      <binaryDataArrayList count="2">')
            for group, ref in (("mzArray", mz_ref), ("intensityArray", int_ref)):
                w('        <binaryDataArray encodedLength="0">')
                w(f'          <referenceableParamGroupRef ref="{group}"/>')
                w(f'          <cvParam cvRef="IMS" accession="{_EXT_OFFSET}" '
                  f'name="external offset" value="{ref.offset}"/>')
                w(f'          <cvParam cvRef="IMS" accession="{_EXT_ARR_LEN}" '
                  f'name="external array length" value="{ref.length}"/>')
                w(f'          <cvParam cvRef="IMS" accession="IMS:1000104" '
                  f'name="external encoded length" value="{ref.length * ref.dtype.itemsize}"/>')
                w('          <binary/>')
                w('        </binaryDataArray>')
            w('      </binaryDataArrayList>')
            w('    </spectrum>')
        w('  </spectrumList>')
        w('  </run>')
        w('</mzML>')
        self.imzml_path.write_text("\n".join(out))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
