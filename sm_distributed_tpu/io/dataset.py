"""Dataset: ragged spectra -> device-friendly spectral-cube layouts.

Reference: ``sm/engine/dataset.py::Dataset`` [U] (SURVEY.md #5) reads the
converted text dump into an ``RDD[(sp_id, mzs, ints)]``, maps scattered (x,y)
scan coordinates to a dense row-major pixel index (``_define_pixels_order``),
and exposes the sample-area mask.  Here the same responsibilities are
TPU-first: spectra land in a flat CSR layout over the *dense* pixel grid
(empty pixels = empty rows), sorted by m/z within each pixel, plus a
prefix-sum array — so ion-image extraction becomes two vmapped
``searchsorted`` calls and a cumulative-sum difference per (pixel, window)
with fully static shapes (see ops/imager_jax.py).  The pixel axis is the
sharding axis: ``NamedSharding(mesh, P("pixels"))`` over the padded cube.
"""

from __future__ import annotations

from dataclasses import dataclass
from pathlib import Path

import numpy as np

from .imzml import ImzMLReader


@dataclass
class SpectralDataset:
    """Host-side dataset in flat-CSR-over-dense-pixel-grid layout."""

    nrows: int
    ncols: int
    pixel_inds: np.ndarray    # (n_spectra,) i64 — dense row-major pixel index per spectrum
    mask: np.ndarray          # (nrows, ncols) bool — sample-area mask (pixels with spectra)
    mzs_flat: np.ndarray      # (P,) f64 — all peaks, grouped by pixel, m/z-sorted per pixel
    ints_flat: np.ndarray     # (P,) f32
    row_ptr: np.ndarray       # (n_pixels+1,) i64 — CSR offsets over dense pixel grid

    @property
    def n_pixels(self) -> int:
        return self.nrows * self.ncols

    # -- order-free exact intensity grid (ops/quantize.py) ---------------

    def intensity_quantization(self, ppm: float) -> tuple[np.ndarray, float]:
        """(integer-valued f32 intensities, power-of-two scale) for ``ppm``.

        Both backends extract ion images from this shared grid, which makes
        image pixel values bit-identical regardless of summation order,
        backend, or shard count (the exact-FDR-rank requirement).  Cached
        per ppm.
        """
        from ..ops.quantize import intensity_scale, quantize_intensities

        cache = getattr(self, "_int_q_cache", None)
        if cache is None:
            cache = {}
            object.__setattr__(self, "_int_q_cache", cache)
        if ppm not in cache:
            pixel_of_peak = np.repeat(
                np.arange(self.n_pixels, dtype=np.int64), self.row_lengths())
            scale = intensity_scale(self.mzs_flat, self.ints_flat, pixel_of_peak, ppm)
            cache[ppm] = (quantize_intensities(self.ints_flat, scale), scale)
        return cache[ppm]

    @property
    def n_spectra(self) -> int:
        return int(self.pixel_inds.size)

    @property
    def n_peaks(self) -> int:
        return int(self.mzs_flat.size)

    # -- construction ----------------------------------------------------

    @staticmethod
    def _pixel_grid(coords: np.ndarray, n_spectra: int):
        """(nrows, ncols, pixel_inds, mask) from raw scan coordinates.

        Pixel-order normalization mirrors the reference's
        ``_define_pixels_order`` [U]: coordinates are mapped through their
        sorted unique values (robust to offsets and uniform step sizes), and
        the dense pixel index is row-major ``row * ncols + col``.
        """
        coords = np.asarray(coords, dtype=np.int64)
        if coords.ndim != 2 or coords.shape[1] != 2 or coords.shape[0] != n_spectra:
            raise ValueError("coords must be (n_spectra, 2) matching spectra list")
        ux = np.unique(coords[:, 0])
        uy = np.unique(coords[:, 1])
        ncols, nrows = ux.size, uy.size
        col = np.searchsorted(ux, coords[:, 0])
        row = np.searchsorted(uy, coords[:, 1])
        pixel_inds = row * ncols + col
        if np.unique(pixel_inds).size != pixel_inds.size:
            raise ValueError("duplicate scan coordinates map to the same pixel")
        mask = np.zeros(nrows * ncols, dtype=bool)
        mask[pixel_inds] = True
        return nrows, ncols, pixel_inds, mask.reshape(nrows, ncols)

    @staticmethod
    def _row_ptr(n_pixels: int, pixel_inds: np.ndarray, lens: np.ndarray):
        counts = np.zeros(n_pixels, dtype=np.int64)
        counts[pixel_inds] = lens
        row_ptr = np.zeros(n_pixels + 1, dtype=np.int64)
        np.cumsum(counts, out=row_ptr[1:])
        return row_ptr

    @staticmethod
    def _sort_rows_inplace(mzs_flat, ints_flat, row_ptr) -> None:
        """Ensure ascending m/z within each CSR row, touching only rows that
        need it.  Centroided imzML stores m/z ascending in practice, so the
        vectorized violation scan usually finds nothing and this is O(N)
        with no extra copies (vs a full-array lexsort at ~2.5x N bytes)."""
        if mzs_flat.size < 2:
            return
        viol = mzs_flat[1:] < mzs_flat[:-1]
        # a drop across a row boundary is not a violation
        starts = row_ptr[1:-1]
        viol[starts[(starts > 0) & (starts < mzs_flat.size)] - 1] = False
        if not viol.any():
            return
        bad = np.unique(
            np.searchsorted(row_ptr, np.nonzero(viol)[0] + 1, side="right") - 1)
        for r in bad:
            s, e = row_ptr[r], row_ptr[r + 1]
            order = np.argsort(mzs_flat[s:e], kind="stable")
            mzs_flat[s:e] = mzs_flat[s:e][order]
            ints_flat[s:e] = ints_flat[s:e][order]

    @classmethod
    def from_arrays(
        cls,
        coords: np.ndarray,
        spectra: list[tuple[np.ndarray, np.ndarray]],
    ) -> "SpectralDataset":
        """Build from raw (x, y) scan coords + per-spectrum (mzs, ints)."""
        nrows, ncols, pixel_inds, mask = cls._pixel_grid(coords, len(spectra))
        lens = np.fromiter((len(m) for m, _ in spectra), dtype=np.int64,
                           count=len(spectra))
        row_ptr = cls._row_ptr(nrows * ncols, pixel_inds, lens)

        # vectorized flat build: concatenate everything, then ONE lexsort
        # keyed on (pixel, mz) groups peaks by dense pixel and m/z-sorts
        mz_all = (np.concatenate([np.asarray(m, np.float64) for m, _ in spectra])
                  if spectra else np.empty(0, np.float64))
        int_all = (np.concatenate([np.asarray(i, np.float32) for _, i in spectra])
                   if spectra else np.empty(0, np.float32))
        pix_all = np.repeat(pixel_inds, lens)
        order = np.lexsort((mz_all, pix_all))
        mzs_flat = mz_all[order]
        ints_flat = int_all[order]

        return cls(
            nrows=nrows,
            ncols=ncols,
            pixel_inds=pixel_inds,
            mask=mask,
            mzs_flat=mzs_flat,
            ints_flat=ints_flat,
            row_ptr=row_ptr,
        )

    @classmethod
    def from_imzml(cls, path: str | Path) -> "SpectralDataset":
        """STREAMING ingest: peak host memory stays ~(12 bytes x total peaks)
        plus one spectrum, instead of the eager build's ~4x that.

        The reference streams spectrum-by-spectrum through its converter and
        reader (``imzml_txt_converter``/``dataset_reader`` [U], SURVEY.md
        #4-5); a >200k-pixel DESI slide (BASELINE #5) can exceed host RAM
        under an eager whole-dataset materialization long before HBM matters.
        Here: pass 1 reads per-spectrum peak COUNTS from the XML metadata
        and preallocates the exact CSR arrays; pass 2 streams each
        spectrum's bytes directly into its CSR slot (no intermediate list,
        no concat, no full-array lexsort — per-row m/z order is verified
        and repaired only where violated).  Bit-identical to from_arrays."""
        with ImzMLReader(path) as rd:
            lens = rd.spectrum_lengths()
            nrows, ncols, pixel_inds, mask = cls._pixel_grid(
                rd.coordinates, rd.n_spectra)
            row_ptr = cls._row_ptr(nrows * ncols, pixel_inds, lens)
            total = int(lens.sum())
            mzs_flat = np.empty(total, dtype=np.float64)
            ints_flat = np.empty(total, dtype=np.float32)
            for i in range(rd.n_spectra):
                m, t = rd.read_spectrum(i)
                s = row_ptr[pixel_inds[i]]
                if m.size != lens[i]:
                    raise ValueError(
                        f"spectrum {i}: ibd length {m.size} != XML metadata "
                        f"length {lens[i]}")
                mzs_flat[s : s + m.size] = m
                ints_flat[s : s + t.size] = t
            cls._sort_rows_inplace(mzs_flat, ints_flat, row_ptr)
            return cls(
                nrows=nrows,
                ncols=ncols,
                pixel_inds=pixel_inds,
                mask=mask,
                mzs_flat=mzs_flat,
                ints_flat=ints_flat,
                row_ptr=row_ptr,
            )

    # -- device layouts --------------------------------------------------

    def row_lengths(self) -> np.ndarray:
        return np.diff(self.row_ptr)

    def padded_cube(
        self, pad_to_multiple: int = 128, pixels_multiple: int = 1
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Dense (n_pixels_padded, L) m/z + intensity cube for the TPU path.

        m/z rows are padded with +inf (so searchsorted puts windows before the
        padding), intensities with 0.  L is the max spectrum length rounded up
        to ``pad_to_multiple`` (lane-friendly).  ``pixels_multiple`` pads the
        pixel axis so it divides the mesh's pixel-shard count.  Returns
        (mz_cube f64, int_cube f32, lens i32); padded pixels have length 0.
        """
        lens = self.row_lengths()
        L = int(max(1, lens.max())) if lens.size else 1
        L = -(-L // pad_to_multiple) * pad_to_multiple
        npix = self.n_pixels
        npix_pad = -(-npix // pixels_multiple) * pixels_multiple
        mz_cube = np.full((npix_pad, L), np.inf, dtype=np.float64)
        int_cube = np.zeros((npix_pad, L), dtype=np.float32)
        # vectorized scatter (no per-pixel Python loop; VERDICT r1 weak #5)
        pixel_of_peak = np.repeat(np.arange(npix), lens)
        col_of_peak = np.arange(self.n_peaks) - np.repeat(self.row_ptr[:-1], lens)
        mz_cube[pixel_of_peak, col_of_peak] = self.mzs_flat
        int_cube[pixel_of_peak, col_of_peak] = self.ints_flat
        out_lens = np.zeros(npix_pad, dtype=np.int32)
        out_lens[:npix] = lens
        return mz_cube, int_cube, out_lens

    def norm_img_pixel_inds(self) -> np.ndarray:
        """Dense pixel index per spectrum (reference:
        ``Dataset.get_norm_img_pixel_inds`` [U])."""
        return self.pixel_inds

    def get_dims(self) -> tuple[int, int]:
        """(nrows, ncols), as the reference's ``Dataset.get_dims`` [U]."""
        return self.nrows, self.ncols

    def get_sample_area_mask(self) -> np.ndarray:
        return self.mask
