"""Synthetic dataset generation — the offline stand-in for the reference's
test fixtures.

The reference tests against a bundled micro imzML dataset and the downloaded
"spheroid" scientific-regression dataset (SURVEY.md §4; BASELINE config #1).
With no network, we generate a procedural spheroid-like dataset with known
ground truth: a subset of target ions get spatially-structured signal
(informative images -> high measure_of_chaos), the rest and all decoys see
only noise -> the FDR ranking has a known right answer.
"""

from __future__ import annotations

from dataclasses import dataclass
from pathlib import Path

import numpy as np

from ..ops.isocalc import IsocalcWrapper
from ..utils.config import IsotopeGenerationConfig
from .imzml import ImzMLWriter

# 50 plausible small-molecule sum formulas (metabolite-like, HMDB-style).
FIXTURE_FORMULAS: list[str] = [
    "C6H12O6", "C6H13NO2", "C5H9NO4", "C9H11NO2", "C3H7NO3",
    "C4H9NO3", "C5H11NO2", "C6H14N4O2", "C6H9N3O2", "C11H12N2O2",
    "C4H7NO4", "C5H5N5", "C5H5N5O", "C10H13N5O4", "C10H13N5O5",
    "C9H13N3O5", "C10H12N2O6", "C4H6O5", "C4H6O4", "C6H8O7",
    "C3H4O3", "C4H4O4", "C5H8O5", "C7H6O2", "C7H8N4O2",
    "C8H10N4O2", "C10H16N5O13P3", "C10H15N5O10P2", "C10H14N5O7P", "C21H27N7O14P2",
    "C16H32O2", "C18H36O2", "C18H34O2", "C18H32O2", "C20H32O2",
    "C5H11O8P", "C6H13O9P", "C3H9O6P", "C8H20NO6P", "C5H14NO4P",
    "C23H38N7O17P3S", "C9H16O4", "C24H50NO7P", "C26H54NO7P", "C42H82NO8P",
    "C40H80NO8P", "C44H84NO8P", "C27H46O", "C19H28O2", "C18H24O2",
]


def expand_formula_list(n: int) -> list[str]:
    """Deterministic list of ``n`` plausible CHNO sum formulas for scale
    benchmarks (BASELINE configs #2/#3 need thousands of ions; the bundled
    50-formula fixture alone underfills a 1024-ion batch)."""
    out = list(dict.fromkeys(FIXTURE_FORMULAS))
    c, h_off, nn, o = 7, 0, 0, 2
    while len(out) < n:
        h = c + 2 - h_off % 5 + nn
        sf = f"C{c}H{max(2, h)}" + (f"N{nn}" if nn else "") + (f"O{o}" if o else "")
        if sf not in out:
            out.append(sf)
        # walk composition space deterministically
        c += 1
        if c > 40:
            c = 7
            o += 1
            if o > 12:
                o = 0
                nn += 1
            h_off += 1
    return out[:n]


@dataclass
class SyntheticGroundTruth:
    formulas: list[str]          # all target formulas written to the mol DB
    present: list[str]           # subset given real spatial signal
    adduct: str
    nrows: int
    ncols: int


def _spatial_pattern(kind: int, nrows: int, ncols: int, rng: np.random.Generator) -> np.ndarray:
    """An informative (spatially structured) intensity image in [0, 1]."""
    yy, xx = np.mgrid[0:nrows, 0:ncols]
    cy, cx = nrows / 2, ncols / 2
    r = np.hypot(yy - cy, xx - cx) / (min(nrows, ncols) / 2)
    if kind % 3 == 0:       # filled blob (spheroid core)
        img = np.clip(1.0 - r, 0, 1) ** 1.5
    elif kind % 3 == 1:     # ring (spheroid rim)
        img = np.exp(-(((r - 0.6) / 0.15) ** 2))
    else:                   # half-gradient (polarized tissue)
        img = np.clip(xx / ncols + 0.1 * np.sin(yy / 3), 0, 1)
    img = img * (0.8 + 0.4 * rng.random(img.shape))  # mild multiplicative noise
    return img / img.max()


def generate_synthetic_dataset(
    out_dir: str | Path,
    nrows: int = 32,
    ncols: int = 32,
    formulas: list[str] | None = None,
    present_fraction: float = 0.6,
    adduct: str = "+H",
    iso_cfg: IsotopeGenerationConfig | None = None,
    noise_peaks: int = 200,
    mz_jitter_ppm: float = 0.5,
    seed: int = 7,
    name: str = "synthetic_spheroid",
    reuse: bool = False,
) -> tuple[Path, SyntheticGroundTruth]:
    """Write a processed-mode imzML/ibd pair with known ground truth.

    Returns (imzml_path, ground_truth).  ``present_fraction`` of the formulas
    receive structured spatial signal at their theoretical isotope m/z values
    (intensities following the theoretical envelope); everything else only
    ever matches background noise.  With ``reuse=True`` an existing output is
    kept when a parameter-marker file matches (generation is deterministic in
    ``seed``, so the ground truth can be rebuilt without rewriting spectra).
    """
    import json

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    rng = np.random.default_rng(seed)
    formulas = list(formulas if formulas is not None else FIXTURE_FORMULAS)
    iso_cfg = iso_cfg or IsotopeGenerationConfig(adducts=(adduct,))
    calc = IsocalcWrapper(iso_cfg)

    marker = out_dir / f"{name}.params.json"
    params = {
        "nrows": nrows, "ncols": ncols, "formulas": formulas,
        "present_fraction": present_fraction, "adduct": adduct,
        "noise_peaks": noise_peaks, "mz_jitter_ppm": mz_jitter_ppm,
        "seed": seed, "iso": [list(iso_cfg.adducts), iso_cfg.charge,
                              iso_cfg.isocalc_sigma, iso_cfg.isocalc_pts_per_mz],
    }
    imzml_path = out_dir / f"{name}.imzML"
    ibd_path = imzml_path.with_suffix(".ibd")
    if reuse and marker.exists() and imzml_path.exists() and ibd_path.exists():
        try:
            if json.loads(marker.read_text()) == params:
                n_present = max(1, int(round(present_fraction * len(formulas))))
                present = list(rng.permutation(formulas)[:n_present])
                return imzml_path, SyntheticGroundTruth(
                    formulas=formulas, present=present, adduct=adduct,
                    nrows=nrows, ncols=ncols)
        except (json.JSONDecodeError, OSError):
            pass
    # invalidate before regenerating: a killed run must not leave a marker
    # that validates partially-written files on the next reuse=True call
    marker.unlink(missing_ok=True)

    n_present = max(1, int(round(present_fraction * len(formulas))))
    present = list(rng.permutation(formulas)[:n_present])

    patterns = {}
    images = {}
    for i, sf in enumerate(present):
        peaks = calc.isotope_peaks(sf, adduct)
        if peaks is None:
            continue
        patterns[sf] = peaks
        images[sf] = _spatial_pattern(i, nrows, ncols, rng)

    mz_lo, mz_hi = 80.0, 1000.0
    with ImzMLWriter(imzml_path, continuous=False) as wr:
        for y in range(nrows):
            for x in range(ncols):
                mzs_parts = []
                ints_parts = []
                for sf, (pk_mzs, pk_ints) in patterns.items():
                    a = images[sf][y, x]
                    if a <= 0.02:
                        continue
                    jitter = 1.0 + mz_jitter_ppm * 1e-6 * rng.standard_normal(pk_mzs.size)
                    mzs_parts.append(pk_mzs * jitter)
                    ints_parts.append(a * pk_ints * (0.9 + 0.2 * rng.random(pk_ints.size)))
                # background noise: uniform random m/z, exponential intensity
                noise_mz = rng.uniform(mz_lo, mz_hi, size=noise_peaks)
                noise_int = rng.exponential(2.0, size=noise_peaks).astype(np.float64)
                mzs_parts.append(noise_mz)
                ints_parts.append(noise_int)
                mzs = np.concatenate(mzs_parts)
                ints = np.concatenate(ints_parts)
                order = np.argsort(mzs)
                # imzML scan positions are conventionally 1-based
                wr.add_spectrum(x + 1, y + 1, mzs[order], ints[order])

    truth = SyntheticGroundTruth(
        formulas=formulas, present=present, adduct=adduct, nrows=nrows, ncols=ncols
    )
    marker.write_text(json.dumps(params))
    return imzml_path, truth
