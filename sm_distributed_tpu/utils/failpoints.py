"""Deterministic failpoint fault injection (ISSUE 2 tentpole).

The engine's failure model is "any exception marks the job FAILED and
re-running is idempotent" (SURVEY.md §5.3), backed by atomic renames, retries,
heartbeats, checkpoints, and dead-lettering — but a recovery path that is
never executed is a recovery path that does not work.  This module gives every
recovery-relevant seam a *named injection point*::

    from ..utils.failpoints import failpoint, register_failpoint

    FP_SHARD_WRITE = register_failpoint(
        "ckpt.shard_write", "between checkpoint tmp savez and os.replace")
    ...
    failpoint(FP_SHARD_WRITE, path=tmp)   # no-op unless activated

Activation comes from the ``SM_FAILPOINTS`` environment variable (read once at
import, so spawned daemons/workers inherit faults) or programmatically via
``configure()``.  The spec grammar, ``;``-separated::

    SM_FAILPOINTS="storage.results_rename=crash@2;ckpt.shard_write=torn;
                   device.score_batch=raise:RuntimeError@3;spool.heartbeat=raise:OSError?0.5"

    name=action[:arg][@N][?P]

Actions:
    raise[:ExcName]  raise the named exception (allowlist below; default
                     ``FailpointError``) with a recognizable message
    crash[:code]     ``os._exit(code)`` — a hard process death with no cleanup,
                     no atexit, no finally blocks (default exit code 21)
    sleep:seconds    delay (races, heartbeat staleness, timeout paths)
    torn[:fraction]  truncate the file handed to ``failpoint(..., path=)`` to
                     ``fraction`` of its bytes (default 0.5) and CONTINUE —
                     simulating a torn write that later commits garbage
    enospc           raise ``OSError(errno.ENOSPC, "No space left on
                     device")`` — a full disk at exactly this write seam
                     (ISSUE 10: every seam the disk-budget governor guards
                     is chaos-testable with the same fault the kernel
                     would deliver)

Triggers (both deterministic):
    @N       fire on the Nth hit of this failpoint only (1-based, per process)
    ?P       fire with probability P per hit, from a ``random.Random`` seeded
             by ``crc32(name) ^ SM_FAILPOINTS_SEED`` — the same seed replays
             the same fault schedule

Every fired injection writes a ``FAILPOINT-FIRED name=... action=...`` line to
stderr (before crashing, for ``crash``) so the chaos sweep driver can assert
the fault actually happened, and counts into ``injected_counts()``.  Recovery
paths report themselves through ``record_recovery(event)``; both counter
families are exported through an attached service ``MetricsRegistry``
(``attach_metrics``) as ``sm_failpoints_injected_total{name=}`` and
``sm_recovery_events_total{event=}``.

Zero overhead when disabled: ``failpoint()`` is a single global read + ``is
None`` test before returning.
"""

from __future__ import annotations

import os
import random
import re
import sys
import threading
import time
import zlib
from dataclasses import dataclass
from pathlib import Path


class FailpointError(RuntimeError):
    """Default exception injected by a ``raise`` failpoint."""


# Injectable exception types — a deliberate allowlist (the spec comes from an
# env var; eval'ing arbitrary names would be a foot-gun).
_EXCEPTIONS: dict[str, type[BaseException]] = {
    "FailpointError": FailpointError,
    "RuntimeError": RuntimeError,
    "OSError": OSError,
    "IOError": OSError,
    "ValueError": ValueError,
    "TimeoutError": TimeoutError,
    "ConnectionError": ConnectionError,
    "MemoryError": MemoryError,
}

_SPEC_RE = re.compile(
    r"(?P<action>[a-z]+)"
    r"(?::(?P<arg>[^@?;]*))?"
    r"(?:@(?P<nth>\d+))?"
    r"(?:\?(?P<prob>[0-9.]+))?"
)

_lock = threading.RLock()
_registry: dict[str, str] = {}          # name -> one-line description
_injected: dict[str, int] = {}          # name -> fired count
_recovered: dict[str, int] = {}         # event -> recovery-action count
_metrics = None                         # attached MetricsRegistry (optional)

# None = disabled; the failpoint() fast path is one read + None test
_active: "dict[str, _Spec] | None" = None
_active_spec: str | None = None            # the spec text behind _active


def register_failpoint(name: str, description: str = "") -> str:
    """Declare an injection point.  Names are global and must be unique —
    a duplicate registration is a programming error (two seams would be
    indistinguishable in specs, docs, and metrics)."""
    with _lock:
        if name in _registry:
            raise ValueError(f"duplicate failpoint name: {name!r}")
        _registry[name] = description
    return name


def registered_failpoints() -> dict[str, str]:
    """{name: description} of every registered injection point.  Only
    complete once the modules hosting the seams have been imported."""
    with _lock:
        return dict(_registry)


@dataclass
class _Spec:
    name: str
    action: str                  # raise | crash | sleep | torn
    arg: str | None = None
    nth: int | None = None       # fire on this hit only (1-based)
    prob: float | None = None    # seeded per-hit probability
    hits: int = 0
    rng: random.Random | None = None


def _parse_one(name: str, rhs: str) -> _Spec:
    m = _SPEC_RE.fullmatch(rhs)
    if not m:
        raise ValueError(f"failpoint {name}: unparseable spec {rhs!r}")
    action = m.group("action")
    arg = m.group("arg")
    nth = int(m.group("nth")) if m.group("nth") else None
    prob = float(m.group("prob")) if m.group("prob") else None
    if action not in ("raise", "crash", "sleep", "torn", "enospc"):
        raise ValueError(f"failpoint {name}: unknown action {action!r}")
    if action == "enospc" and arg:
        raise ValueError(f"failpoint {name}: enospc takes no argument")
    if action == "raise" and arg and arg not in _EXCEPTIONS:
        raise ValueError(
            f"failpoint {name}: exception {arg!r} not in "
            f"{sorted(_EXCEPTIONS)}")
    if action == "sleep":
        if not arg:
            raise ValueError(f"failpoint {name}: sleep needs a seconds arg")
        float(arg)
    if action == "torn" and arg:
        f = float(arg)
        if not 0.0 <= f < 1.0:
            raise ValueError(f"failpoint {name}: torn fraction must be in [0,1)")
    if action == "crash" and arg:
        int(arg)
    if nth is not None and nth < 1:
        raise ValueError(f"failpoint {name}: @N is 1-based")
    if prob is not None and not 0.0 < prob <= 1.0:
        raise ValueError(f"failpoint {name}: ?P must be in (0,1]")
    rng = None
    if prob is not None:
        seed = zlib.crc32(name.encode()) ^ int(
            os.environ.get("SM_FAILPOINTS_SEED", "0"))
        rng = random.Random(seed)
    return _Spec(name=name, action=action, arg=arg or None,
                 nth=nth, prob=prob, rng=rng)


def parse_failpoints(text: str) -> dict[str, _Spec]:
    """Parse a full ``SM_FAILPOINTS`` spec string; raises ``ValueError`` with
    the offending name on any malformed entry."""
    out: dict[str, _Spec] = {}
    for part in text.split(";"):
        part = part.strip()
        if not part:
            continue
        name, sep, rhs = part.partition("=")
        name = name.strip()
        if not sep or not name or not rhs.strip():
            raise ValueError(f"malformed failpoint entry {part!r} "
                             "(want name=action[:arg][@N][?P])")
        if name in out:
            raise ValueError(f"failpoint {name} specified twice")
        out[name] = _parse_one(name, rhs.strip())
    return out


def configure(spec: str | None) -> None:
    """Activate a spec string (env-var grammar); ``None``/empty disables.
    Replaces any previous activation and resets hit counters."""
    global _active, _active_spec
    with _lock:
        _active_spec = spec or None
        if not spec:
            _active = None
            return
        _active = parse_failpoints(spec)


def active_spec() -> str | None:
    """The currently-armed spec string (or None).  Process pools spawned by
    the engine pass this to their worker initializers so a programmatic
    ``configure()`` in the parent reaches spawned children the same way the
    ``SM_FAILPOINTS`` env var does (children re-read the env at import, but
    never see the parent's in-process configuration)."""
    with _lock:
        return _active_spec


def reset() -> None:
    """Disable injection and clear the injected/recovery counters (tests)."""
    global _active, _active_spec
    with _lock:
        _active = None
        _active_spec = None
        _injected.clear()
        _recovered.clear()


def injected_counts() -> dict[str, int]:
    with _lock:
        return dict(_injected)


def recovery_counts() -> dict[str, int]:
    with _lock:
        return dict(_recovered)


def record_recovery(event: str, n: int = 1) -> None:
    """Called by recovery paths (corrupt-shard skip, orphan-tmp sweep, stale
    requeue, ...) so chaos runs can prove recovery actually engaged, and so
    the service exports ``sm_recovery_events_total{event=}``."""
    if n <= 0:
        return
    with _lock:
        _recovered[event] = _recovered.get(event, 0) + n
        m = _metrics
    if m is not None:
        m.counter("sm_recovery_events_total",
                  "Recovery actions taken, by event",
                  ("event",)).labels(event=event).inc(n)


def attach_metrics(registry) -> None:
    """Export both counter families through a service ``MetricsRegistry``.
    Counts recorded before attachment are backfilled."""
    global _metrics
    with _lock:
        _metrics = registry
        inj = dict(_injected)
        rec = dict(_recovered)
    fam = registry.counter("sm_failpoints_injected_total",
                           "Faults injected by failpoint name", ("name",))
    for name, n in inj.items():
        fam.labels(name=name).inc(n)
    fam_r = registry.counter("sm_recovery_events_total",
                             "Recovery actions taken, by event", ("event",))
    for event, n in rec.items():
        fam_r.labels(event=event).inc(n)


def _should_fire(spec: _Spec) -> bool:
    spec.hits += 1
    if spec.nth is not None and spec.hits != spec.nth:
        return False
    if spec.rng is not None and spec.rng.random() >= spec.prob:
        return False
    return True


def failpoint(name: str, path: str | os.PathLike | None = None) -> None:
    """The injection point.  ``path`` is the file a ``torn`` action mangles;
    seams that move/commit a file should pass it."""
    active = _active
    if active is None:
        return                      # disabled: the zero-overhead fast path
    spec = active.get(name)
    if spec is None:
        return
    with _lock:
        if not _should_fire(spec):
            return
        _injected[name] = _injected.get(name, 0) + 1
        m = _metrics
    if m is not None:
        m.counter("sm_failpoints_injected_total",
                  "Faults injected by failpoint name",
                  ("name",)).labels(name=name).inc()
    sys.stderr.write(
        f"FAILPOINT-FIRED name={name} action={spec.action} "
        f"hit={spec.hits} path={path or ''}\n")
    sys.stderr.flush()
    # attach the trigger to the owning span (ISSUE 5) — emitted (and the
    # trace line flushed) before the action runs, so even a crash action
    # leaves its mark in the job's trace for the post-mortem
    from . import tracing

    tracing.event("failpoint", name=name, action=spec.action, hit=spec.hits)
    if spec.action == "raise":
        exc = _EXCEPTIONS[spec.arg or "FailpointError"]
        raise exc(f"injected failpoint {name} (hit {spec.hits})")
    if spec.action == "enospc":
        import errno

        raise OSError(
            errno.ENOSPC,
            f"No space left on device [injected failpoint {name} "
            f"(hit {spec.hits})]", str(path) if path is not None else None)
    if spec.action == "crash":
        os._exit(int(spec.arg or 21))
    if spec.action == "sleep":
        time.sleep(float(spec.arg))
        return
    if spec.action == "torn":
        if path is None:
            raise FailpointError(
                f"failpoint {name}: torn action but the seam passed no path")
        p = Path(path)
        size = p.stat().st_size
        keep = int(size * float(spec.arg or 0.5))
        with open(p, "r+b") as f:
            f.truncate(keep)
        return


# Env activation happens once at import: every process in a chaos run (driver
# -> daemon -> scheduler workers) sees the same spec without plumbing.
configure(os.environ.get("SM_FAILPOINTS"))
