"""Logging setup, mirroring ``sm/engine/util.py::init_logger`` + conf/sm_log.cfg [U].

One engine-wide logger named ``sm-tpu`` (the reference's is ``sm-engine``),
console + optional file handler, phase-timing helper used by the orchestrator
for the reference's step-level wall-clock logging (SURVEY.md §5.1).

ISSUE 5 additions:

- ``phase_timer`` emits a tracing span for the phase (utils/tracing.py) —
  when an ambient trace context exists, every phase of every job lands in
  that job's trace for free;
- phase observers are a LIST with exception-safe dispatch (the old
  single-slot global silently replaced any prior observer, so the service's
  metrics observer and a test's observer could never coexist);
- ``JsonLogFormatter`` (``logs.json: true``): one JSON object per line with
  ``trace_id``/``job_id``/``span`` injected from the ambient trace context,
  so log aggregation can join every record from every layer to its job.
"""

from __future__ import annotations

import contextlib
import json
import logging
import time
from pathlib import Path

from . import tracing

LOGGER_NAME = "sm-tpu"
_FMT = "%(asctime)s - %(levelname)s - %(name)s - %(message)s"


class JsonLogFormatter(logging.Formatter):
    """Structured JSON log lines with trace correlation fields.

    Every record carries ``trace_id``/``job_id``/``span`` from the ambient
    trace context (empty strings when the emitting thread is untraced), so
    one grep joins scheduler, engine, backend, and spool lines for a job.
    """

    def format(self, record: logging.LogRecord) -> str:
        ctx = tracing.current()
        out = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
            "trace_id": ctx.trace_id if ctx else "",
            "job_id": ctx.job_id if ctx else "",
            "span": ctx.span_id if ctx else "",
        }
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def _formatter(json_logs: bool) -> logging.Formatter:
    return JsonLogFormatter() if json_logs else logging.Formatter(_FMT)


def init_logger(logs_dir: str | None = None, level: int = logging.INFO,
                json_logs: bool = False) -> logging.Logger:
    logger = logging.getLogger(LOGGER_NAME)
    logger.setLevel(level)
    if not logger.handlers:
        sh = logging.StreamHandler()
        logger.addHandler(sh)
    if logs_dir:
        path = Path(logs_dir)
        path.mkdir(parents=True, exist_ok=True)
        if not any(isinstance(h, logging.FileHandler) for h in logger.handlers):
            logger.addHandler(logging.FileHandler(path / "sm-tpu.log"))
    # (re)apply the format to every handler: a later init_logger call with
    # json_logs flips existing handlers too (the CLI/service own the config)
    for h in logger.handlers:
        h.setFormatter(_formatter(json_logs))
    return logger


logger = logging.getLogger(LOGGER_NAME)

# Observers called as fn(phase, seconds) on every phase_timer exit.  The
# service installs one feeding its per-phase latency histogram
# (sm_distributed_tpu.service.metrics) so /metrics sees every job's phases
# without the engine importing the service.  A LIST (ISSUE 5 satellite):
# the old single slot silently dropped any prior observer.
_phase_observers: list = []


def add_phase_observer(fn) -> None:
    """Register a phase-duration observer (idempotent per function)."""
    if fn not in _phase_observers:
        _phase_observers.append(fn)


def remove_phase_observer(fn) -> None:
    """Remove a previously registered observer (missing = no-op)."""
    with contextlib.suppress(ValueError):
        _phase_observers.remove(fn)


def set_phase_observer(fn) -> None:
    """Legacy single-slot installer: replaces ALL observers with ``fn``
    (or clears them with ``None``).  Prefer add/remove_phase_observer —
    this survives only for callers that relied on the replace semantics."""
    _phase_observers.clear()
    if fn is not None:
        _phase_observers.append(fn)


def _notify_phase(phase: str, dt: float) -> None:
    """Exception-safe dispatch: an observer that raises must not break
    phase_timer (or starve the observers after it)."""
    for fn in list(_phase_observers):
        try:
            fn(phase, dt)
        except Exception:  # observability must never fail the pipeline
            logger.warning("phase observer %r failed for %s", fn, phase,
                           exc_info=True)


@contextlib.contextmanager
def phase_timer(phase: str, timings: dict[str, float] | None = None):
    """Log wall-clock of a pipeline phase (the reference logs around each
    SearchJob phase [U]); optionally record into a timings dict for
    bench/trace, notify observers, and emit a tracing span when the thread
    carries an ambient trace context."""
    t0 = time.perf_counter()
    logger.info("phase %s ...", phase)
    try:
        with tracing.span(phase, phase=True):
            yield
    finally:
        dt = time.perf_counter() - t0
        logger.info("phase %s done in %.3fs", phase, dt)
        if timings is not None:
            timings[phase] = timings.get(phase, 0.0) + dt
        _notify_phase(phase, dt)
