"""Logging setup, mirroring ``sm/engine/util.py::init_logger`` + conf/sm_log.cfg [U].

One engine-wide logger named ``sm-tpu`` (the reference's is ``sm-engine``),
console + optional file handler, phase-timing helper used by the orchestrator
for the reference's step-level wall-clock logging (SURVEY.md §5.1).
"""

from __future__ import annotations

import contextlib
import logging
import time
from pathlib import Path

LOGGER_NAME = "sm-tpu"
_FMT = "%(asctime)s - %(levelname)s - %(name)s - %(message)s"


def init_logger(logs_dir: str | None = None, level: int = logging.INFO) -> logging.Logger:
    logger = logging.getLogger(LOGGER_NAME)
    logger.setLevel(level)
    if not logger.handlers:
        sh = logging.StreamHandler()
        sh.setFormatter(logging.Formatter(_FMT))
        logger.addHandler(sh)
    if logs_dir:
        path = Path(logs_dir)
        path.mkdir(parents=True, exist_ok=True)
        if not any(isinstance(h, logging.FileHandler) for h in logger.handlers):
            fh = logging.FileHandler(path / "sm-tpu.log")
            fh.setFormatter(logging.Formatter(_FMT))
            logger.addHandler(fh)
    return logger


logger = logging.getLogger(LOGGER_NAME)

# Optional observer called as fn(phase, seconds) on every phase_timer exit.
# The service layer installs one feeding its per-phase latency histogram
# (sm_distributed_tpu.service.metrics) so /metrics sees every job's phases
# without the engine importing the service.
_phase_observer = None


def set_phase_observer(fn) -> None:
    """Install (or with ``None`` remove) the global phase-duration observer."""
    global _phase_observer
    _phase_observer = fn


@contextlib.contextmanager
def phase_timer(phase: str, timings: dict[str, float] | None = None):
    """Log wall-clock of a pipeline phase (the reference logs around each
    SearchJob phase [U]); optionally record into a timings dict for bench/trace."""
    t0 = time.perf_counter()
    logger.info("phase %s ...", phase)
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        logger.info("phase %s done in %.3fs", phase, dt)
        if timings is not None:
            timings[phase] = timings.get(phase, 0.0) + dt
        if _phase_observer is not None:
            try:
                _phase_observer(phase, dt)
            except Exception:  # observability must never fail the pipeline
                logger.warning("phase observer failed for %s", phase,
                               exc_info=True)
