"""End-to-end job tracing + flight recorder (ISSUE 5 tentpole).

The reference engine's only visibility was step-level wall-clock log lines
around each SearchJob phase (SURVEY.md §5.1); nothing correlated what the
scheduler, admission controller, device backend, isocalc pool workers, spool
daemon, breaker, and failpoints did *for one job*.  This module gives every
job a **trace**: a tree of spans sharing a ``trace_id`` minted at ``POST
/submit`` (or at CLI entry for offline runs), propagated scheduler →
``JobContext`` → ``SearchJob`` → ``MSMBasicSearch`` → both scoring backends
→ isocalc pool workers (serialized across the spawn boundary, re-parented on
return) → spool publish/claim/complete, with retry / cancel / deadline /
admission-shed / breaker-transition / failpoint events attached to the
owning span.

Model
-----
Two record kinds, each one JSON object (see docs/OBSERVABILITY.md for the
schema):

- ``span``:  ``{kind, trace_id, span_id, parent_id, name, ts, dur, pid,
  tid, attrs}`` — a timed operation.  ``ts`` is epoch seconds at entry,
  ``dur`` wall seconds.
- ``event``: ``{kind, trace_id, span_id, name, ts, pid, tid, attrs}`` — an
  instant attached to its owning span (``span_id`` = the span it happened
  under; both ids empty for traceless service-level events, which still
  reach the flight recorder).

Sinks
-----
- a bounded in-memory **flight recorder** ring (``GET /debug/events?n=``),
  process-global, thread-safe;
- a per-job **JSONL file** under the trace dir (append-only, one flushed
  line per record, so a crash loses at most the line being written and a
  restarted job/attempt APPENDS to the same file — the trace id and file
  travel inside the spool message, surviving requeue and process death).

Propagation
-----------
The current span is ambient via a ``contextvars.ContextVar``.  New threads
start without a context, so every thread hop attaches explicitly::

    ctx = tracing.current()            # capture in the spawning thread
    ...
    with tracing.attach(ctx):          # in the spawned thread
        with tracing.span("phase"):
            ...

Process hops (the isocalc spawn pool) serialize ``ctx.to_wire()`` into the
worker args; the worker rebuilds the context, records its spans into a
``capture()`` buffer (no sinks exist in the worker), and returns them with
the chunk result — the driver emits them via ``emit_records`` ("re-parented
on return": the records already carry the parent ids, the driver just owns
the sinks).

Overhead
--------
``span()``/``event()`` with no ambient context and no explicit one return a
no-op immediately — untraced hot paths (bench floors, raw backend calls)
pay one ContextVar read.  File emission caches one append handle per path
and writes a single flushed line per record.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import os
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from pathlib import Path

from .failpoints import failpoint, register_failpoint

_log = logging.getLogger("sm-tpu")

FP_TRACE_APPEND = register_failpoint(
    "trace.append",
    "inside a per-job trace file append (I/O error / ENOSPC) — trace "
    "emission must never fail the pipeline")

RECORD_KINDS = ("span", "event")
# required keys per record kind (validate_records + the smoke gate)
_SPAN_KEYS = ("kind", "trace_id", "span_id", "parent_id", "name", "ts",
              "dur", "pid", "tid")
_EVENT_KEYS = ("kind", "trace_id", "span_id", "name", "ts", "pid", "tid")

_CTX: contextvars.ContextVar["TraceContext | None"] = contextvars.ContextVar(
    "sm_trace_ctx", default=None)
_CAPTURE: contextvars.ContextVar["list | None"] = contextvars.ContextVar(
    "sm_trace_capture", default=None)

_enabled = True


def new_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclass(frozen=True)
class TraceContext:
    """Position in a trace: ids + the per-job sink every child inherits."""

    trace_id: str
    span_id: str
    job_id: str = ""
    file: str = ""                # per-job JSONL sink ("" = ring only)

    def child(self, span_id: str | None = None) -> "TraceContext":
        return TraceContext(trace_id=self.trace_id,
                            span_id=span_id or new_id(),
                            job_id=self.job_id, file=self.file)

    def to_wire(self) -> dict:
        """Minimal dict for a process hop (no file — workers have no sinks)."""
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "job_id": self.job_id}

    @staticmethod
    def from_wire(d: dict | None) -> "TraceContext | None":
        if not d or not d.get("trace_id"):
            return None
        return TraceContext(trace_id=str(d["trace_id"]),
                            span_id=str(d.get("span_id", "")),
                            job_id=str(d.get("job_id", "")))


# --------------------------------------------------------- flight recorder
class FlightRecorder:
    """Bounded ring of the most recent records, process-wide."""

    # smlint guarded-by registry (docs/ANALYSIS.md)
    _GUARDED_BY = {"_ring": "_lock"}

    def __init__(self, maxlen: int = 2048):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=maxlen)

    def record(self, rec: dict) -> None:
        with self._lock:
            self._ring.append(rec)

    def recent(self, n: int | None = None) -> list[dict]:
        with self._lock:
            items = list(self._ring)
        return items if n is None else items[-max(0, int(n)):]

    def resize(self, maxlen: int) -> None:
        with self._lock:
            self._ring = deque(self._ring, maxlen=max(1, int(maxlen)))

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    @property
    def maxlen(self) -> int:
        with self._lock:
            return self._ring.maxlen or 0


flight_recorder = FlightRecorder()


def configure(enabled: bool = True, ring_size: int | None = None) -> None:
    """Apply ``SMConfig.tracing`` knobs (service/CLI startup)."""
    global _enabled
    _enabled = bool(enabled)
    if ring_size is not None and ring_size != flight_recorder.maxlen:
        flight_recorder.resize(ring_size)


def enabled() -> bool:
    return _enabled


# replica identity (ISSUE 8): stamped on every record this process emits so
# a trace continued across a takeover shows WHICH replica ran each span
_replica_id = ""


def set_replica(replica_id: str) -> None:
    """Set the process-wide replica id (service startup; "" disables)."""
    global _replica_id
    _replica_id = str(replica_id or "")


def replica() -> str:
    return _replica_id


# pod process identity (ISSUE 17): a cross-process mesh runs one scheduler
# process per host — records carry (process_id, host) so a trace spanning a
# host loss shows which process emitted each span
_process_id = -1
_host = ""


def set_process(process_id: int, host: str = "") -> None:
    """Set the pod identity stamped on every record (-1/"" disables)."""
    global _process_id, _host
    _process_id = int(process_id)
    _host = str(host or "")


def process() -> tuple[int, str]:
    return _process_id, _host


# --------------------------------------------------------------- file sink
# cached append handles: one flushed line per record, no per-record open()
_files_lock = threading.Lock()
_files: dict[str, object] = {}

# disk-pressure gate (ISSUE 10): the resource governor installs a callable
# consulted before every FILE write — under disk pressure trace writes are
# the FIRST thing dropped (ring records keep flowing).  None = always write.
# The gate must be cheap and non-raising; it is called outside _files_lock.
_file_gate = None


def set_file_gate(fn) -> None:
    """Install (or clear, with ``None``) the trace-file write gate.  The
    service wires this to ``ResourceGovernor.trace_gate`` so a disk-budget
    breach drops trace APPENDS before anything essential degrades."""
    global _file_gate
    _file_gate = fn


def _file_handle_locked(path: str):
    """Caller holds ``_files_lock``."""
    f = _files.get(path)
    if f is None or f.closed:
        if len(_files) >= 64:         # bound fd usage across many jobs
            for stale in list(_files):
                with contextlib.suppress(OSError):
                    _files[stale].close()
                del _files[stale]
        Path(path).parent.mkdir(parents=True, exist_ok=True)
        f = _files[path] = open(path, "a", encoding="utf-8")
    return f


def close_files() -> None:
    """Close cached trace-file handles (tests / shutdown)."""
    with _files_lock:
        for f in _files.values():
            with contextlib.suppress(OSError):
                f.close()
        _files.clear()


def close_file(path: str | Path) -> None:
    """Drop one cached append handle (the GC sweeper calls this before
    unlinking an aged trace file, so a later append to the same trace id
    reopens instead of writing to an unlinked inode)."""
    key = str(path)
    with _files_lock:
        f = _files.pop(key, None)
        if f is not None:
            with contextlib.suppress(OSError):
                f.close()


# reentrancy guard for the trace.append failpoint: a FIRED failpoint emits
# its own "failpoint" trace event, which re-enters _emit — without the
# guard an every-hit spec would recurse without bound
_appending = threading.local()


def _emit(rec: dict, file: str) -> None:
    buf = _CAPTURE.get()
    if buf is not None:               # worker-side capture: no sinks here
        buf.append(rec)
        return
    flight_recorder.record(rec)
    if file and (_file_gate is None or _file_gate()):
        line = json.dumps(rec, default=str) + "\n"
        try:
            # the governed-write seam (ISSUE 10): ENOSPC/I/O faults here
            # must degrade to a lost trace line, never a failed job
            if not getattr(_appending, "active", False):
                _appending.active = True
                try:
                    failpoint(FP_TRACE_APPEND, path=file)
                finally:
                    _appending.active = False
            with _files_lock:         # whole-line writes, never interleaved
                f = _file_handle_locked(file)
                f.write(line)
                f.flush()
        except OSError:               # tracing must never fail the pipeline
            _log.warning("trace emit to %s failed", file, exc_info=True)


# ------------------------------------------------------------ context + API
def current() -> TraceContext | None:
    return _CTX.get()


@contextlib.contextmanager
def attach(ctx: TraceContext | None):
    """Make ``ctx`` the ambient trace context for this thread/block."""
    token = _CTX.set(ctx)
    try:
        yield ctx
    finally:
        _CTX.reset(token)


def new_trace(job_id: str = "", trace_dir: str | Path | None = None,
              trace_id: str | None = None,
              span_id: str | None = None) -> TraceContext:
    """Mint a root context (does not emit anything).  ``trace_dir`` selects
    the per-job JSONL sink: ``<trace_dir>/<trace_id>.jsonl``."""
    tid = trace_id or new_id()
    file = str(trace_path(trace_dir, tid)) if trace_dir else ""
    return TraceContext(trace_id=tid, span_id=span_id or new_id(),
                        job_id=job_id, file=file)


def trace_path(trace_dir: str | Path, trace_id: str) -> Path:
    return Path(trace_dir) / f"{trace_id}.jsonl"


def _base(ctx: TraceContext, name: str, kind: str) -> dict:
    rec = {
        "kind": kind, "trace_id": ctx.trace_id, "span_id": ctx.span_id,
        "name": name, "ts": time.time(), "pid": os.getpid(),
        "tid": threading.get_ident(),
    }
    if ctx.job_id:
        rec["job_id"] = ctx.job_id
    if _replica_id:
        rec["replica"] = _replica_id
    if _process_id >= 0:
        rec["process"] = _process_id
    if _host:
        rec["host"] = _host
    return rec


@contextlib.contextmanager
def span(name: str, /, ctx: TraceContext | None = None, **attrs):
    """Timed child span of ``ctx`` (or the ambient context).  No-op without
    either — untraced paths stay at one ContextVar read.  Yields the child
    context (ambient inside the block), emits the span record on exit; a
    raising body is recorded with ``error`` in attrs and re-raised."""
    parent = ctx if ctx is not None else _CTX.get()
    if parent is None or not _enabled:
        yield None
        return
    child = parent.child()
    rec = _base(child, name, "span")
    rec["parent_id"] = parent.span_id
    if attrs:
        rec["attrs"] = attrs
    token = _CTX.set(child)
    t0 = time.perf_counter()
    try:
        yield child
    except BaseException as exc:
        rec.setdefault("attrs", {})["error"] = f"{type(exc).__name__}: {exc}"
        raise
    finally:
        _CTX.reset(token)
        rec["dur"] = time.perf_counter() - t0
        _emit(rec, parent.file)


def emit_span(ctx: TraceContext, name: str, /, ts: float = 0.0,
              dur: float = 0.0,
              span_id: str | None = None, parent_id: str = "",
              **attrs) -> None:
    """Emit a span record with explicit timing — for spans whose body ran
    elsewhere (the scheduler's attempt span measured around a join, the
    root job span closed at the terminal outcome, bench's retroactive
    phase spans)."""
    if ctx is None or not _enabled:
        return
    rec = {
        "kind": "span", "trace_id": ctx.trace_id,
        "span_id": span_id or new_id(), "parent_id": parent_id,
        "name": name, "ts": ts, "dur": dur, "pid": os.getpid(),
        "tid": threading.get_ident(),
    }
    if ctx.job_id:
        rec["job_id"] = ctx.job_id
    if _replica_id:
        rec["replica"] = _replica_id
    if _process_id >= 0:
        rec["process"] = _process_id
    if _host:
        rec["host"] = _host
    if attrs:
        rec["attrs"] = attrs
    _emit(rec, ctx.file)


def event(name: str, /, ctx: TraceContext | None = None, **attrs) -> None:
    """Instant event attached to the owning span (``ctx`` or ambient).
    With neither, the event still lands in the flight recorder with empty
    ids — service-level happenings (admission sheds, breaker flips) stay
    observable without a job trace."""
    if not _enabled:
        return
    owner = ctx if ctx is not None else _CTX.get()
    if owner is None:
        owner = TraceContext(trace_id="", span_id="")
    rec = _base(owner, name, "event")
    if attrs:
        rec["attrs"] = attrs
    _emit(rec, owner.file)


# ----------------------------------------------- process-hop (pool workers)
@contextlib.contextmanager
def capture():
    """Redirect this thread's emissions into a list instead of the sinks —
    the worker side of a process hop.  Yields the list; the driver passes
    it to ``emit_records`` after the hop returns."""
    buf: list[dict] = []
    token = _CAPTURE.set(buf)
    try:
        yield buf
    finally:
        _CAPTURE.reset(token)


def emit_records(records: list[dict] | None,
                 ctx: TraceContext | None = None) -> None:
    """Emit records captured in a worker ("re-parented on return": they
    already carry trace/parent ids from the wire context — the driver owns
    the sinks the worker never had).  ``ctx`` supplies the file sink."""
    if not records or not _enabled:
        return
    file = ctx.file if ctx is not None else ""
    for rec in records:
        if isinstance(rec, dict) and rec.get("kind") in RECORD_KINDS:
            _emit(rec, file)


# ------------------------------------------------------- reading + exports
def read_trace(path: str | Path) -> list[dict]:
    """Parse a per-job JSONL trace file; tolerates a torn trailing line
    (the crash-in-flight case the append-only format exists for)."""
    out: list[dict] = []
    try:
        text = Path(path).read_text(encoding="utf-8")
    except OSError:
        return out
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue                  # torn trailing write
        if isinstance(rec, dict):
            out.append(rec)
    return out


def validate_records(records: list[dict]) -> list[str]:
    """Schema check; returns problem strings (empty = valid).  The trace
    smoke gate and tests run every emitted trace through this."""
    problems = []
    for i, rec in enumerate(records):
        if not isinstance(rec, dict):
            problems.append(f"record {i}: not an object")
            continue
        kind = rec.get("kind")
        if kind not in RECORD_KINDS:
            problems.append(f"record {i}: bad kind {kind!r}")
            continue
        keys = _SPAN_KEYS if kind == "span" else _EVENT_KEYS
        missing = [k for k in keys if k not in rec]
        if missing:
            problems.append(f"record {i} ({kind} {rec.get('name')!r}): "
                            f"missing {missing}")
        if kind == "span" and not isinstance(rec.get("dur"), (int, float)):
            problems.append(f"record {i}: span dur not numeric")
        if "attrs" in rec and not isinstance(rec["attrs"], dict):
            problems.append(f"record {i}: attrs not an object")
    return problems


def to_chrome_trace(records: list[dict]) -> dict:
    """Convert trace records to Chrome trace-event JSON (Perfetto-loadable:
    chrome://tracing and ui.perfetto.dev both open it).  Spans become
    complete ``"X"`` events (µs timestamps), instants become thread-scoped
    ``"i"`` events; a ``jax_profile`` event surfaces the correlated
    ``jax.profiler`` trace dir in ``otherData``."""
    events: list[dict] = []
    other: dict = {}
    pids = set()
    for rec in records:
        pid = int(rec.get("pid", 0))
        pids.add(pid)
        args = dict(rec.get("attrs") or {})
        args["trace_id"] = rec.get("trace_id", "")
        args["span_id"] = rec.get("span_id", "")
        base = {
            "name": str(rec.get("name", "")),
            "cat": "span" if rec.get("kind") == "span" else "event",
            "pid": pid, "tid": int(rec.get("tid", 0)),
            "ts": round(float(rec.get("ts", 0.0)) * 1e6, 3),
            "args": args,
        }
        if rec.get("kind") == "span":
            base["ph"] = "X"
            base["dur"] = round(float(rec.get("dur", 0.0)) * 1e6, 3)
            if rec.get("parent_id"):
                base["args"]["parent_id"] = rec["parent_id"]
        else:
            base["ph"] = "i"
            base["s"] = "t"
            if rec.get("name") == "jax_profile" and "dir" in args:
                other["jax_profile_dir"] = args["dir"]
        events.append(base)
        if rec.get("trace_id") and "trace_id" not in other:
            other["trace_id"] = rec["trace_id"]
        if rec.get("job_id"):
            other.setdefault("job_id", rec["job_id"])
    for pid in sorted(pids):
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": f"sm-tpu pid {pid}"}})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": other}
