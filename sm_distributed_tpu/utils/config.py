"""Two-level configuration, mirroring the reference's config system.

The reference uses a process-global ``SMConfig`` singleton loading
``conf/config.json`` (services + spark + fdr settings) and a per-dataset
``ds_config.json`` (database, isotope_generation, image_generation) —
``sm/engine/util.py::SMConfig`` [U], SURVEY.md #1/#20.  Every numerical knob
keeps its reference name and default: ``ppm``, ``nlevels=30``, ``q=99``,
``do_preprocessing``, ``decoy_sample_size=20``, ``isocalc_sigma``,
``isocalc_pts_per_mz``, ``adducts``, ``charge``.

One deliberate addition, demanded by the north star (BASELINE.json): the
``backend`` selector — ``numpy_ref`` (CPU parity oracle, the stand-in for the
reference's Spark-RDD executor) or ``jax_tpu`` (the fused-XLA-graph TPU path).
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, ClassVar

VALID_BACKENDS = ("numpy_ref", "jax_tpu")


def _from_dict(cls, d: dict[str, Any]):
    """Build a dataclass from a dict, recursing into dataclass fields and
    rejecting unknown keys (catches config typos early, unlike the reference's
    raw-dict access which fails deep inside a Spark task)."""
    # "__doc__"-style keys are comments (JSON has none; the shipped
    # conf/*.template files use them), skipped by load & validation
    d = {k: v for k, v in d.items() if not k.startswith("__")}
    names = {f.name for f in dataclasses.fields(cls)}
    unknown = set(d) - names
    if unknown:
        raise ValueError(f"unknown {cls.__name__} config keys: {sorted(unknown)}")
    kwargs = {}
    for key, val in d.items():
        target = _DATACLASS_FIELDS.get((cls.__name__, key))
        if target is not None and isinstance(val, dict):
            kwargs[key] = _from_dict(target, val)
        elif isinstance(val, list):
            # JSON arrays land in tuple-typed fields; keep frozen configs hashable.
            kwargs[key] = tuple(val)
        else:
            kwargs[key] = val
    return cls(**kwargs)


@dataclass(frozen=True)
class IsotopeGenerationConfig:
    """Mirrors ds_config['isotope_generation'] [U]."""
    adducts: tuple[str, ...] = ("+H", "+Na", "+K")
    charge: int = 1                      # signed; reference: {polarity:'+', n_charges:1}
    isocalc_sigma: float = 0.01          # gaussian sigma of instrument blur [Da]
    isocalc_pts_per_mz: int = 10000      # resolution of the profile grid
    n_peaks: int = 4                     # top isotope peaks kept per ion (reference: 4)

    def __post_init__(self):
        if self.charge == 0:
            raise ValueError("isotope_generation.charge must be nonzero")
        if self.isocalc_sigma <= 0 or self.isocalc_pts_per_mz <= 0 or self.n_peaks <= 0:
            raise ValueError("isotope_generation: sigma/pts_per_mz/n_peaks must be positive")


@dataclass(frozen=True)
class ImageGenerationConfig:
    """Mirrors ds_config['image_generation'] [U]."""
    ppm: float = 3.0                     # half-width of the m/z match window
    nlevels: int = 30                    # thresholds in measure_of_chaos
    do_preprocessing: bool = False       # hot-spot removal before chaos
    q: float = 99.0                      # hot-spot clipping percentile

    def __post_init__(self):
        if self.ppm <= 0 or self.nlevels <= 0 or not (0 < self.q <= 100):
            raise ValueError("image_generation: ppm/nlevels/q out of range")


@dataclass(frozen=True)
class DatabaseConfig:
    """Mirrors ds_config['database'] [U]."""
    name: str = "HMDB"
    version: str = "2016"


@dataclass(frozen=True)
class DSConfig:
    """Per-dataset config (the reference's ds_config.json [U])."""
    database: DatabaseConfig = field(default_factory=DatabaseConfig)
    isotope_generation: IsotopeGenerationConfig = field(default_factory=IsotopeGenerationConfig)
    image_generation: ImageGenerationConfig = field(default_factory=ImageGenerationConfig)

    @staticmethod
    def load(path: str | Path) -> "DSConfig":
        return _from_dict(DSConfig, json.loads(Path(path).read_text()))

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "DSConfig":
        return _from_dict(DSConfig, d)


@dataclass(frozen=True)
class FDRConfig:
    """Mirrors sm_config['fdr'] [U]."""
    decoy_sample_size: int = 20
    seed: int = 42                       # decoy sampling made explicit/seeded (SURVEY §7 hard part 3)


@dataclass(frozen=True)
class ParallelConfig:
    """TPU-native replacement for sm_config['spark'] [U]: mesh geometry instead
    of master/executor-memory. axis sizes of -1 mean 'use all devices'."""
    pixels_axis: int = -1                # mesh axis sharding the pixel dimension
    formulas_axis: int = 1               # mesh axis sharding the formula dimension
    # ions scored per fused-graph invocation: 2048 balances histogram-
    # scatter amortization against padding waste (measured sweep on v5e,
    # docs/PERF.md); batches pad to this so small jobs may prefer less
    formula_batch: int = 2048
    mz_chunk: int = 0                    # 0 = no m/z chunking inside the kernel
    # per-batch peak compaction on the flat path: histogram only the peaks
    # inside the current batch's window union (auto = on when the planned
    # batches keep <70% of resident peaks; on/off force it)
    peak_compaction: str = "auto"
    # ion-table ordering before batching: "mz" sorts ions by principal-peak
    # m/z so each batch's window union is an m/z-LOCALIZED band (total
    # histogram-scatter work across a many-batch stream drops from
    # ~n_batches x resident toward ~resident — the BASELINE #5 regime);
    # "table" keeps the caller's order (targets first); "auto" (default)
    # orders at >=6 batches (measured: 6-batch 65k-px stream +20%, 41-batch
    # 262k-px stream +8.3x, 3-batch 4k-px stream -17%).  Per-ion results
    # are identical either way.
    order_ions: str = "auto"
    # contiguous band-slice extraction: when a batch's window union spans a
    # contiguous slice of the m/z-sorted resident peaks (ordered streams),
    # scatter a dynamic slice instead of gathering a packed run list —
    # scatter-only cost, no 23 ns/slot gather.  auto = picked per batch by
    # measured-cost estimate vs plain/compaction; on/off force or disable.
    band_slice: str = "auto"
    # multi-host (DCN) runtime — jax.distributed.initialize; the analog of
    # the reference's spark.master cluster address (SURVEY.md §5.8).  Env
    # vars SM_COORDINATOR / SM_NUM_PROCESSES / SM_PROCESS_ID override.
    coordinator_address: str = ""        # "" = single-process (no-op init)
    num_processes: int = 1
    process_id: int = -1                 # -1 = resolve from env/launcher
    # coordinator launch race (ISSUE 17): every host process races the
    # coordinator's bind at pod startup, so jax.distributed.initialize
    # retries with exponential backoff (base * 2^attempt, capped at 30 s)
    # before the failure is considered real
    init_retries: int = 5                # attempts AFTER the first; 0 = one
                                         # shot (fail fast)
    init_backoff_s: float = 1.0          # first retry delay; doubles per
                                         # attempt
    # mid-search resume (SURVEY §5.4): checkpoint scored metrics every N
    # formula batches; 0 disables.  A killed multi-hour search (BASELINE
    # configs #3/#5) resumes from the last complete group.
    checkpoint_every: int = 0
    # persistent XLA compilation cache: "" = <work_dir>/xla_cache (repeat
    # datasets with the same shapes skip the ~15-20s TPU compile entirely),
    # "off" = disabled, anything else = explicit directory
    compile_cache_dir: str = ""
    # --- isotope-pattern cold path (ops/isocalc.py, docs/ISOCALC.md) ---
    # process-pool size for cold pattern generation: 0 = all cores
    # (env SM_ISOCALC_PROCS overrides a 0 here)
    isocalc_workers: int = 0
    # (formula, adduct) pairs per generation chunk == per incremental cache
    # shard: 0 = default (2048; env SM_ISOCALC_CHUNK overrides a 0 here)
    isocalc_chunk: int = 0
    # batched device (XLA) blur->centroid stage: "on" routes the
    # post-convolution math through ops/isocalc_jax.py.  Results match the
    # NumPy oracle to ~1e-5 (NOT bit-exact; separate cache namespace), so
    # the default stays "off" — the pinned golden report is oracle bits.
    isocalc_device: str = "off"
    # overlap isotope generation with the rest of the job: SearchJob stages/
    # parses concurrently with isocalc, and (numpy_ref backend) scoring
    # starts on the leading checkpoint groups while later patterns are
    # still computing.  "off" restores strictly serial phases.
    overlap_isocalc: str = "auto"
    # daemon service mode: how many datasets' parsed layouts + compiled
    # backends stay resident across queue messages (LRU; 0 disables) —
    # engine/residency.py
    resident_datasets: int = 2
    # shape-bucket lattice (ISSUE 13, ops/buckets.py): "auto"/"on" snap
    # dataset-dependent shapes (pixel rows, resident peak slots, pad-to
    # batch) to the canonical power-of-two-ish lattice so every dataset
    # size maps into a closed, primeable signature set; "off" keeps exact
    # legacy shapes (one executable family per dataset size)
    shape_buckets: str = "auto"
    # resident-cube intensity dtype (ISSUE 18, ops/quantize.compact_cube):
    # "bf16" halves / "int8" quarters the HBM-resident flat sorted-peaks
    # cube (1.85 GB f32 at DESI scale); the f32 view is a per-batch
    # transient expanded inside the scoring jits.  Declared NUMERICS
    # contracts bound the drift (NUMERICS_r02.json); "f32" is the exact
    # legacy off-ramp.
    cube_dtype: str = "f32"
    # fused Pallas scoring kernel (ISSUE 18, ops/score_pallas.py): one
    # VMEM-staged pass does window-gather + per-ion MSM moment partials,
    # replacing the multi-dispatch gather/segment-sum chain.  "auto"
    # fuses plain-variant batches on TPU when the plan shape fits the
    # kernel's VMEM budget; "on" forces it everywhere (interpret-mode
    # off-TPU — tests/sentinel); "off" keeps the unfused XLA chain.
    fused_metrics: str = "auto"


@dataclass(frozen=True)
class AdmissionConfig:
    """Overload protection for ``POST /submit`` (docs/SERVICE.md "Overload &
    degradation model").  A shed submit gets a structured 429/503 with a
    ``Retry-After`` header instead of joining an unbounded backlog."""
    max_queue_depth: int = 512           # admitted-but-not-terminal bound
                                         # across all tenants; 0 = unlimited
    max_tenant_inflight: int = 128       # per-tenant admitted-but-not-
                                         # terminal bound; 0 = unlimited
    ewma_alpha: float = 0.2              # weight of the newest job latency
    latency_shed_s: float = 0.0          # EWMA job latency that starts
                                         # shedding (503); 0 disables
    latency_resume_s: float = 0.0        # hysteresis floor: resume accepting
                                         # below this (0 = 0.75 * shed)
    retry_after_s: float = 1.0           # Retry-After hint on shed responses

    def __post_init__(self):
        if self.max_queue_depth < 0 or self.max_tenant_inflight < 0:
            raise ValueError("admission: depth/quota bounds must be >= 0")
        if not 0.0 < self.ewma_alpha <= 1.0:
            raise ValueError("admission: ewma_alpha must be in (0, 1]")
        if self.latency_shed_s < 0 or self.latency_resume_s < 0:
            raise ValueError("admission: latency thresholds must be >= 0")
        if self.retry_after_s < 0:
            raise ValueError("admission: retry_after_s must be >= 0")

    @property
    def effective_resume_s(self) -> float:
        return self.latency_resume_s or 0.75 * self.latency_shed_s


@dataclass(frozen=True)
class FleetConfig:
    """Elastic replica fleet (service/fleet.py, docs/SERVICE.md "Elasticity
    model").  A FleetController supervises replica subprocesses and makes
    hysteresis-damped scale decisions between ``min_replicas`` and
    ``max_replicas`` from the live signals the service already exports:
    ``/slo`` error-budget burn, admission queue depth, and device-pool
    occupancy.  Scale-down is a zero-loss *drain*: the victim stops
    claiming, finishes or releases in-flight work, acks, and retires —
    rendezvous hashing re-owns its shards and fenced leases make the
    handoff safe by construction."""
    enabled: bool = False                # serve --fleet (or this knob) runs
                                         # the controller beside replica r0
    min_replicas: int = 1                # repair floor (crash replacement
                                         # bypasses hysteresis + cooldown)
    max_replicas: int = 4                # scale ceiling
    decide_interval_s: float = 5.0       # controller decision cadence
    cooldown_s: float = 60.0             # min gap between scale events, so
                                         # flapping traffic can't thrash
    hysteresis_ticks: int = 2            # consecutive decide ticks a signal
                                         # must hold before acting
    scale_up_burn: float = 1.0           # worst /slo error-budget burn at or
                                         # above this is scale-up pressure
    scale_down_burn: float = 0.5         # burn must be at or below this for
                                         # scale-down relief
    queue_high_per_replica: float = 8.0  # pending depth / alive replicas at
                                         # or above this is pressure
    queue_low_per_replica: float = 1.0   # ... at or below this is relief
    occupancy_high: float = 0.95         # pool occupancy at or above this is
                                         # pressure (0 disables the signal)
    spawn_timeout_s: float = 30.0        # a spawned replica must register a
                                         # heartbeat within this or count as
                                         # a failed spawn
    drain_timeout_s: float = 120.0       # drain ack + process exit deadline
                                         # before the victim is force-killed

    def __post_init__(self):
        if self.min_replicas <= 0 or self.max_replicas < self.min_replicas:
            raise ValueError("fleet: need 1 <= min_replicas <= max_replicas")
        if self.decide_interval_s <= 0 or self.cooldown_s < 0 or \
                self.hysteresis_ticks < 1:
            raise ValueError("fleet: decide_interval_s must be positive, "
                             "cooldown_s >= 0, hysteresis_ticks >= 1")
        if self.scale_up_burn <= 0 or self.scale_down_burn < 0 or \
                self.scale_down_burn > self.scale_up_burn:
            raise ValueError("fleet: need 0 <= scale_down_burn <= "
                             "scale_up_burn")
        if self.queue_high_per_replica <= 0 or \
                self.queue_low_per_replica < 0 or \
                self.queue_low_per_replica > self.queue_high_per_replica:
            raise ValueError("fleet: need 0 <= queue_low_per_replica <= "
                             "queue_high_per_replica")
        if not 0.0 <= self.occupancy_high <= 1.0:
            raise ValueError("fleet: occupancy_high must be in [0, 1]")
        if self.spawn_timeout_s <= 0 or self.drain_timeout_s <= 0:
            raise ValueError("fleet: spawn/drain timeouts must be positive")


@dataclass(frozen=True)
class PrimeConfig:
    """Ahead-of-time XLA cache priming (ISSUE 13, service/primer.py,
    docs/PERF.md "Cold start"): a scheduler-idle background thread AOT-
    compiles the recorded (config, bucket, lease-shape) lattice into the
    persistent compilation cache, so a cold submit loads executables from
    disk instead of paying the compile.  ``GET /debug/compile`` reports
    primed vs missing buckets; ``scripts/prime_cache.py`` is the offline
    equivalent."""

    enabled: bool = False                # start the idle primer thread
    idle_after_s: float = 5.0            # spool must be idle this long
                                         # before a prime cycle starts
    interval_s: float = 30.0             # rescan cadence for new bucket
                                         # specs once everything known is
                                         # primed
    max_specs_per_cycle: int = 0         # compile at most N specs per
                                         # idle cycle (0 = no cap); the
                                         # primer re-checks idleness
                                         # between specs either way

    def __post_init__(self):
        if self.idle_after_s < 0 or self.interval_s <= 0:
            raise ValueError("prime: idle_after_s must be >= 0 and "
                             "interval_s positive")
        if self.max_specs_per_cycle < 0:
            raise ValueError("prime: max_specs_per_cycle must be >= 0")


@dataclass(frozen=True)
class ReadPathConfig:
    """Result read path (ISSUE 16, service/readpath.py, docs/SERVICE.md
    "Read path"): the queryable annotation index + ion-image tile service +
    governed LRU cache behind the ``GET /datasets*`` endpoints.  Reads shed
    independently of writes: more than ``max_concurrent`` in-flight reads
    get a structured 429 + Retry-After, and cache fills stop (reads still
    answer from the source segments) when the disk governor degrades past
    the read-cache floor."""
    enabled: bool = True                 # serve the read endpoints
    cache_max_bytes: int = 64 << 20      # in-memory LRU result/tile cache
                                         # byte cap (0 disables caching)
    cache_max_entries: int = 1024        # ... entry cap
    cache_disk_max_bytes: int = 128 << 20  # on-disk tile cache byte cap
                                         # under <work_dir>/read_cache
                                         # (0 disables the disk tier)
    max_concurrent: int = 32             # in-flight read bound; excess reads
                                         # shed with 429 (0 = unlimited)
    retry_after_s: float = 1.0           # Retry-After hint on shed reads
    page_size: int = 100                 # default annotations page length
    page_size_max: int = 1000            # hard cap on ?limit=

    def __post_init__(self):
        if min(self.cache_max_bytes, self.cache_max_entries,
               self.cache_disk_max_bytes, self.max_concurrent) < 0:
            raise ValueError("read: cache/concurrency bounds must be >= 0")
        if self.retry_after_s < 0:
            raise ValueError("read: retry_after_s must be >= 0")
        if not 0 < self.page_size <= self.page_size_max:
            raise ValueError(
                "read: need 0 < page_size <= page_size_max")


@dataclass(frozen=True)
class StreamConfig:
    """Live-acquisition streaming ingest (ISSUE 19, docs/SERVICE.md
    "Streaming model"): ``mode=stream`` submits + ``POST
    /datasets/<id>/pixels`` chunk appends into the crash-safe chunk log,
    provisional re-scoring as coverage grows, and batch-identical
    convergence at ``POST /datasets/<id>/finish``."""
    idle_timeout_s: float = 300.0        # cancel an acquisition when no NEW
                                         # chunk commits for this long (the
                                         # stream analog of deadline_s —
                                         # stream jobs are exempt from the
                                         # submit-pinned absolute deadline);
                                         # 0 waits forever
    poll_interval_s: float = 0.25        # stream attempt's manifest poll
                                         # cadence while waiting for chunks
    rescore_min_chunks: int = 1          # provisional re-scores run only
                                         # when at least this many NEW
                                         # chunks committed since the last
                                         # one (1 = re-score every commit)
    retention_age_s: float = 3600.0      # finished chunk logs idle past
                                         # this are removed by the
                                         # governor's GC sweep; abandoned
                                         # (never-finished) logs after
                                         # retention_age_s + idle_timeout_s
                                         # idle (0 = keep forever)

    def __post_init__(self):
        if self.idle_timeout_s < 0 or self.retention_age_s < 0:
            raise ValueError(
                "stream: idle_timeout_s/retention_age_s must be >= 0")
        if self.poll_interval_s <= 0 or self.rescore_min_chunks < 1:
            raise ValueError("stream: poll_interval_s must be positive and "
                             "rescore_min_chunks >= 1")


@dataclass(frozen=True)
class FleetViewConfig:
    """Fleet observability plane (ISSUE 20, service/fleetview.py,
    docs/OBSERVABILITY.md "Fleet plane"): the serving replica scrapes live
    peers (admin addresses gossiped through registry heartbeats), merges
    their exposition, and answers ``GET /fleet/metrics|slo|status`` with a
    fleet-wide view that degrades to partial-with-evidence when a peer dies
    mid-scrape."""
    enabled: bool = True                 # serve the /fleet/* endpoints
    scrape_timeout_s: float = 2.0        # per-peer HTTP scrape budget; a
                                         # peer slower than this counts as a
                                         # scrape error, not a fleet 500
    cache_ttl_s: float = 1.0             # merged-view reuse window so N
                                         # dashboard readers cost one fleet
                                         # scrape (0 = scrape every request)

    def __post_init__(self):
        if self.scrape_timeout_s <= 0 or self.cache_ttl_s < 0:
            raise ValueError("fleetview: scrape_timeout_s must be positive "
                             "and cache_ttl_s >= 0")


@dataclass(frozen=True)
class ServiceConfig:
    """Annotation-service knobs (scheduler + failure policy + admin API) —
    the serving-side analog of the reference's rabbitmq/daemon settings.
    Consumed by ``sm_distributed_tpu.service`` (the ``serve`` CLI command)."""
    workers: int = 2                     # concurrent job slots (CPU phases
                                         # overlap; device phases serialize
                                         # through the scheduler's TPU token)
    poll_interval_s: float = 0.5         # pending/ scan cadence when idle
    job_timeout_s: float = 21600.0       # per-attempt wall clock (6 h — the
                                         # 80k-formula DESI job is 32-67 min)
    max_attempts: int = 3                # attempts before dead-letter
    backoff_base_s: float = 1.0          # retry delay = base * 2^(n-1) ...
    backoff_max_s: float = 60.0          # ... capped here ...
    backoff_jitter: float = 0.1          # ... times 1 + U[0, jitter]
    heartbeat_interval_s: float = 5.0    # claim heartbeat touch cadence
    stale_after_s: float = 30.0          # claims with no heartbeat this old
                                         # are requeued by crash recovery
    drain_timeout_s: float = 30.0        # graceful-shutdown wait for running
    http_host: str = "127.0.0.1"         # admin API bind (healthz/metrics/
    http_port: int = 8685                # jobs/submit); port 0 = ephemeral
    # --- cooperative cancellation (utils/cancel.py, docs/SERVICE.md) ---
    cancel_grace_s: float = 15.0         # after a cancel is delivered, how
                                         # long the worker waits for the
                                         # attempt thread to unwind before
                                         # declaring it abandoned
    watchdog_interval_s: float = 5.0     # stall-watchdog scan cadence
    watchdog_stall_s: float = 0.0        # cancel attempts whose progress
                                         # heartbeat is older than this;
                                         # 0 disables the watchdog
    # --- poison-job quarantine ---
    quarantine_after: int = 8            # claims without a terminal outcome
                                         # before a message moves to
                                         # quarantine/; 0 disables
    # --- multi-chip device pool (service/device_pool.py, ISSUE 7) ---
    device_pool_size: int = 0            # chips the scheduler leases out;
                                         # 0 = auto (local jax device count
                                         # when the backend uses jax, else 1
                                         # — the old single-token behavior)
    devices_per_job: int = 1             # chips a job claims by default; a
                                         # per-submit "devices" field
                                         # overrides.  1 = pack small jobs
                                         # onto distinct chips; >1 = claim a
                                         # contiguous sub-mesh and score
                                         # through the pjit-sharded path
    device_pool_max_bypass: int = 64     # grants that may jump a waiting
                                         # larger lease before it seals the
                                         # queue (anti-starvation for
                                         # sub-mesh jobs under small-job
                                         # traffic)
    device_pool_hosts: int = 1           # host dimension of the pool (a
                                         # jax.distributed-style host×chip
                                         # topology, simulated on CPU): the
                                         # pool's chips split into this many
                                         # equal failure domains; 1-host
                                         # leases are preferred, a sub-mesh
                                         # lease may span hosts and reports
                                         # them (DeviceLease.hosts)
    lease_reap_after_s: float = 300.0    # an abandoned (zombie) attempt's
                                         # device lease is reclaimed when
                                         # its thread exits, or forcibly
                                         # after this TTL; 0 = wait for the
                                         # thread forever
    # --- per-chip device health (service/health.py, ISSUE 14) ---
    health_probe_on_lease: bool = True   # probe every granted chip with a
                                         # device round-trip before the job
                                         # touches it (no-op without jax)
    health_fault_quarantine: int = 3     # consecutive transient /
                                         # unattributed-sticky strikes on a
                                         # chip before it is quarantined
                                         # (an attributed sticky fault
                                         # quarantines immediately)
    health_reprobe_after_s: float = 60.0 # quarantine -> half-open re-probe
                                         # cooldown; a passing re-probe
                                         # readmits the chip (0 = never
                                         # re-probe)
    health_host_evict_fraction: float = 0.75  # fraction of a host domain's
                                         # chips quarantined at which the
                                         # WHOLE host is evicted (>= 1.0
                                         # disables host eviction)
    # --- pod host watchdog (service/scheduler.py, ISSUE 17) ---
    host_watchdog_interval_s: float = 0.0  # cadence of the per-host process-
                                         # heartbeat scan; 0 disables the
                                         # watchdog (single-process pods)
    host_stale_after_s: float = 10.0     # a host whose EVERY process beat is
                                         # older than this is evicted: its
                                         # chips quarantine as one unit and
                                         # in-flight attempts on them cancel
                                         # into the normal retry path
    # --- multi-replica scheduling (service/leases.py, ISSUE 8) ---
    replica_id: str = "r0"               # this scheduler process's identity
                                         # (serve --replica-id); leases and
                                         # heartbeats carry it
    replicas: int = 1                    # expected replica count (serve
                                         # --replicas) — informational; the
                                         # LIVE set comes from heartbeats
    spool_shards: int = 8                # logical spool partitions; claims
                                         # filter by crc32(msg_id) % shards
                                         # and rendezvous-hash ownership
    replica_heartbeat_interval_s: float = 2.0   # registry beat cadence
    replica_stale_after_s: float = 8.0   # a peer whose beat is older drops
                                         # from the alive set (its shards
                                         # redistribute to survivors)
    takeover_interval_s: float = 2.0     # takeover/orphan scan cadence
    # --- device-backend circuit breaker (models/breaker.py) ---
    breaker_threshold: int = 3           # consecutive device errors → open
    breaker_cooldown_s: float = 30.0     # open → half-open probe delay
    breaker_degraded_batch: int = 512    # numpy-fallback formula batch while
                                         # the breaker is open (reduced from
                                         # parallel.formula_batch)
    admission: AdmissionConfig = field(default_factory=AdmissionConfig)
    fleet: FleetConfig = field(default_factory=FleetConfig)
    prime: PrimeConfig = field(default_factory=PrimeConfig)
    read: ReadPathConfig = field(default_factory=ReadPathConfig)
    stream: StreamConfig = field(default_factory=StreamConfig)
    fleetview: FleetViewConfig = field(default_factory=FleetViewConfig)

    def __post_init__(self):
        if self.workers <= 0 or self.max_attempts <= 0:
            raise ValueError("service: workers/max_attempts must be positive")
        if self.backoff_base_s < 0 or self.backoff_max_s < 0 or self.backoff_jitter < 0:
            raise ValueError("service: backoff knobs must be non-negative")
        if self.cancel_grace_s < 0 or self.watchdog_interval_s <= 0 or \
                self.watchdog_stall_s < 0 or self.quarantine_after < 0:
            raise ValueError("service: cancel/watchdog/quarantine knobs out of range")
        if self.breaker_threshold <= 0 or self.breaker_cooldown_s < 0 or \
                self.breaker_degraded_batch <= 0:
            raise ValueError("service: breaker knobs out of range")
        if self.device_pool_size < 0 or self.devices_per_job <= 0 or \
                self.device_pool_max_bypass < 0:
            raise ValueError("service: device-pool knobs out of range "
                             "(device_pool_size >= 0, devices_per_job >= 1, "
                             "device_pool_max_bypass >= 0)")
        if self.device_pool_hosts <= 0 or self.lease_reap_after_s < 0:
            raise ValueError("service: device_pool_hosts must be >= 1 and "
                             "lease_reap_after_s >= 0")
        if self.health_fault_quarantine < 1 or \
                self.health_reprobe_after_s < 0 or \
                not 0.0 < self.health_host_evict_fraction:
            raise ValueError(
                "service: health_fault_quarantine must be >= 1, "
                "health_reprobe_after_s >= 0, and "
                "health_host_evict_fraction > 0 (>= 1.0 disables eviction)")
        if self.host_watchdog_interval_s < 0 or self.host_stale_after_s <= 0:
            raise ValueError("service: host_watchdog_interval_s must be >= 0 "
                             "and host_stale_after_s positive")
        if not self.replica_id or self.replicas <= 0 or self.spool_shards <= 0:
            raise ValueError("service: replica_id must be non-empty and "
                             "replicas/spool_shards positive")
        if self.replica_heartbeat_interval_s <= 0 or \
                self.replica_stale_after_s <= 0 or \
                self.takeover_interval_s <= 0:
            raise ValueError("service: replica heartbeat/staleness/takeover "
                             "intervals must be positive")


@dataclass(frozen=True)
class ProfileConfig:
    """On-demand device profiling (ISSUE 20, service/fleetview.py,
    docs/OBSERVABILITY.md "Device profiles"): ``GET /debug/profile?seconds=``
    runs a ``jax.profiler`` capture around in-flight work, attributes device
    time per kernel, and injects ``device_kernel`` spans into live job
    traces."""
    enabled: bool = True                 # serve /debug/profile
    default_seconds: float = 2.0         # capture window when ?seconds= is
                                         # omitted
    max_seconds: float = 30.0            # hard cap on a requested window (a
                                         # profile holds the single-flight
                                         # slot for its whole duration)
    dir: str = ""                        # capture dir; "" = <work_dir>/profiles

    def __post_init__(self):
        if not 0 < self.default_seconds <= self.max_seconds:
            raise ValueError("profile: need 0 < default_seconds <= "
                             "max_seconds")


@dataclass(frozen=True)
class TelemetryConfig:
    """Quantitative telemetry (service/telemetry.py, docs/OBSERVABILITY.md):
    the device/HBM monitor + metric-snapshot time-series ring behind
    ``GET /debug/timeseries``, and the SLO objectives ``GET /slo`` reports
    attainment/error-budget burn against."""
    enabled: bool = True                 # start the sampling thread
    sample_interval_s: float = 5.0       # device/occupancy sample cadence
    timeseries_len: int = 720            # snapshot ring capacity (1 h @ 5 s)
    retrace: bool = True                 # compile-attribution tracer
                                         # (analysis/retrace.py): sm_compile_*
                                         # metrics + `compile` trace events
    # SLO objectives: latency threshold (seconds) + attainment target
    # (fraction of jobs that must land under the threshold)
    slo_queue_wait_s: float = 30.0       # submit -> first attempt start
    slo_first_annotation_s: float = 120.0  # submit -> first scored group
    slo_e2e_s: float = 600.0             # submit -> terminal outcome
    slo_read_s: float = 0.25             # read request -> response (ISSUE 16)
    slo_stream_partial_s: float = 30.0   # stream chunk commit -> provisional
                                         # partial served (ISSUE 19)
    slo_target: float = 0.99
    profile: ProfileConfig = field(default_factory=ProfileConfig)

    def __post_init__(self):
        if self.sample_interval_s <= 0 or self.timeseries_len <= 0:
            raise ValueError(
                "telemetry: sample_interval_s/timeseries_len must be positive")
        if min(self.slo_queue_wait_s, self.slo_first_annotation_s,
               self.slo_e2e_s, self.slo_read_s,
               self.slo_stream_partial_s) <= 0:
            raise ValueError("telemetry: SLO thresholds must be positive")
        if not 0.0 < self.slo_target < 1.0:
            raise ValueError("telemetry: slo_target must be in (0, 1)")


@dataclass(frozen=True)
class TracingConfig:
    """End-to-end job tracing (utils/tracing.py, docs/OBSERVABILITY.md):
    per-job JSONL span logs + the in-memory flight recorder behind
    ``GET /jobs/<id>/trace`` and ``GET /debug/events``."""
    enabled: bool = True                 # span/event emission on traced jobs
    dir: str = ""                        # trace-file dir; "" = <work_dir>/traces
    ring_size: int = 2048                # flight-recorder record capacity
    # bounded retention for the ON-DISK per-job trace files (the flight-
    # recorder ring is already bounded; the files were not — ISSUE 10
    # satellite).  Enforced by the resource governor's GC sweeper
    # (service/resources.py): files older than retention_age_s are removed,
    # and when the trace dir exceeds retention_max_bytes the oldest files
    # go first.  0 disables that dimension.
    retention_age_s: float = 0.0
    retention_max_bytes: int = 0

    def __post_init__(self):
        if self.ring_size <= 0:
            raise ValueError("tracing.ring_size must be positive")
        if self.retention_age_s < 0 or self.retention_max_bytes < 0:
            raise ValueError("tracing.retention_* must be >= 0")


@dataclass(frozen=True)
class ResourcesConfig:
    """Resource-exhaustion survival (service/resources.py, docs/RECOVERY.md
    "Resource exhaustion"): disk-budget governor + bounded-retention GC.
    The governor preflights every governed write seam and degrades in a
    configured order as headroom shrinks — trace writes drop first
    (remaining < trace_floor_bytes), then isocalc cache writes
    (< cache_floor_bytes), then new submits shed with a structured 507
    (< submit_floor_bytes); essential writes (checkpoints, results, spool)
    are denied only when the floor itself would be breached."""
    min_free_bytes: int = 0              # filesystem free-space reserve the
                                         # governor protects (0 disables the
                                         # statvfs constraint)
    disk_budget_bytes: int = 0           # cap on bytes under the governed
                                         # roots (work/results/queue);
                                         # 0 = free-space constraint only
    trace_floor_bytes: int = 32 << 20    # remaining headroom below which
                                         # trace-file writes are dropped
    cache_floor_bytes: int = 16 << 20    # ... below which isocalc cache
                                         # shard writes are dropped
    read_cache_floor_bytes: int = 12 << 20  # ... below which read-path
                                         # result/tile cache fills stop
                                         # (reads answer from source)
    submit_floor_bytes: int = 8 << 20    # ... below which POST /submit
                                         # sheds with 507 + Retry-After
    gc_interval_s: float = 30.0          # retention sweep + usage rescan
                                         # cadence (scheduler replica loop)
    done_retention_age_s: float = 0.0    # spool done/ messages older than
                                         # this are removed (0 = keep)
    failed_retention_age_s: float = 0.0  # dead-letter/quarantine evidence
                                         # older than this is removed
                                         # (0 = keep)
    cache_retention_max_bytes: int = 0   # isocalc cache size cap — oldest
                                         # shards removed first (0 = keep)
    registry_retention_age_s: float = 3600.0  # crashed replicas' registry
                                         # heartbeat files older than this
                                         # are removed (they never retire)

    def __post_init__(self):
        if min(self.min_free_bytes, self.disk_budget_bytes,
               self.cache_retention_max_bytes) < 0:
            raise ValueError("resources: byte knobs must be >= 0")
        if not (self.trace_floor_bytes >= self.cache_floor_bytes
                >= self.read_cache_floor_bytes
                >= self.submit_floor_bytes >= 0):
            raise ValueError(
                "resources: degrade floors must be ordered "
                "trace_floor_bytes >= cache_floor_bytes >= "
                "read_cache_floor_bytes >= submit_floor_bytes >= 0 "
                "(traces drop first, then isocalc cache, then read-cache "
                "fills, then submits)")
        if self.gc_interval_s <= 0:
            raise ValueError("resources.gc_interval_s must be positive")
        if min(self.done_retention_age_s, self.failed_retention_age_s,
               self.registry_retention_age_s) < 0:
            raise ValueError("resources: retention ages must be >= 0")


@dataclass(frozen=True)
class LogsConfig:
    """Structured logging: ``json: true`` switches every handler to one
    JSON object per line with ``trace_id``/``job_id``/``span`` injected from
    the ambient trace context (utils/logger.py::JsonLogFormatter)."""
    json: bool = False


@dataclass(frozen=True)
class StorageConfig:
    """Replaces sm_config['db'/'elasticsearch'] service blocks: pluggable local
    sinks (parquet results + sqlite index) instead of Postgres/ES."""
    results_dir: str = "results"
    store_images: bool = True
    image_format: str = "npz"            # npz (sparse) | png


@dataclass(frozen=True)
class SMConfig:
    """Engine-global config (the reference's conf/config.json via
    sm/engine/util.py::SMConfig [U])."""
    backend: str = "jax_tpu"
    fdr: FDRConfig = field(default_factory=FDRConfig)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    service: ServiceConfig = field(default_factory=ServiceConfig)
    tracing: TracingConfig = field(default_factory=TracingConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    resources: ResourcesConfig = field(default_factory=ResourcesConfig)
    logs: LogsConfig = field(default_factory=LogsConfig)
    work_dir: str = "/tmp/sm_tpu_work"
    logs_dir: str = ""                   # "" = console only

    @property
    def trace_dir(self) -> str:
        """Resolved per-job trace-file directory (tracing.dir wins)."""
        return self.tracing.dir or str(Path(self.work_dir) / "traces")
    # fault injection for chaos/recovery testing (utils/failpoints.py,
    # docs/RECOVERY.md): same grammar as the SM_FAILPOINTS env var, which
    # always wins when set; "" disables.  NEVER set in production configs.
    failpoints: str = ""

    def __post_init__(self):
        if self.backend not in VALID_BACKENDS:
            raise ValueError(f"backend must be one of {VALID_BACKENDS}, got {self.backend!r}")
        for knob, valid in (("order_ions", ("auto", "mz", "table")),
                            ("band_slice", ("auto", "on", "off")),
                            ("peak_compaction", ("auto", "on", "off")),
                            ("isocalc_device", ("on", "off")),
                            ("overlap_isocalc", ("auto", "on", "off")),
                            ("cube_dtype", ("f32", "bf16", "int8")),
                            ("fused_metrics", ("auto", "on", "off"))):
            v = getattr(self.parallel, knob)
            if v not in valid:
                raise ValueError(
                    f"parallel.{knob} must be one of {valid}, got {v!r}")

    # -- singleton access, mirroring SMConfig.set_path()/get_conf() [U] --
    _instance: ClassVar["SMConfig | None"] = None

    @staticmethod
    def set_path(path: str | Path) -> "SMConfig":
        SMConfig._instance = _from_dict(SMConfig, json.loads(Path(path).read_text()))
        return SMConfig._instance

    @staticmethod
    def set(conf: "SMConfig") -> "SMConfig":
        SMConfig._instance = conf
        return conf

    @staticmethod
    def get_conf() -> "SMConfig":
        if SMConfig._instance is None:
            SMConfig._instance = SMConfig()
        return SMConfig._instance

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "SMConfig":
        return _from_dict(SMConfig, d)


# nested-field -> dataclass routing for _from_dict
_DATACLASS_FIELDS = {
    ("DSConfig", "database"): DatabaseConfig,
    ("DSConfig", "isotope_generation"): IsotopeGenerationConfig,
    ("DSConfig", "image_generation"): ImageGenerationConfig,
    ("SMConfig", "fdr"): FDRConfig,
    ("SMConfig", "parallel"): ParallelConfig,
    ("SMConfig", "storage"): StorageConfig,
    ("SMConfig", "service"): ServiceConfig,
    ("SMConfig", "tracing"): TracingConfig,
    ("SMConfig", "telemetry"): TelemetryConfig,
    ("SMConfig", "resources"): ResourcesConfig,
    ("SMConfig", "logs"): LogsConfig,
    ("ServiceConfig", "admission"): AdmissionConfig,
    ("ServiceConfig", "fleet"): FleetConfig,
    ("ServiceConfig", "prime"): PrimeConfig,
    ("ServiceConfig", "read"): ReadPathConfig,
    ("ServiceConfig", "stream"): StreamConfig,
    ("ServiceConfig", "fleetview"): FleetViewConfig,
    ("TelemetryConfig", "profile"): ProfileConfig,
}
