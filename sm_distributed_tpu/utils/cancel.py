"""Cooperative cancellation + deadline propagation (ISSUE 4 tentpole).

The scheduler used to *abandon* a timed-out attempt thread (Python cannot
kill a thread): the zombie kept running, kept holding the TPU device token,
and kept writing results while its message was already requeued.  The fix is
a ``CancelToken`` threaded from the scheduler through ``SearchJob`` and both
scoring backends, checked at phase and checkpoint-group boundaries::

    token.check("score")      # raises JobCancelledError once cancelled

so a cancelled attempt unwinds cooperatively: the device token is released
by the normal ``with`` exit, no partial results are stored (the store phase
is guarded by a check), and the worker requeues or terminates the message
cleanly instead of leaking a zombie.

Cancellation sources (``token.reason`` records the first winner):

- per-attempt **timeout** (the scheduler's join deadline elapsed);
- an absolute **deadline** carried by the submit (``deadline_s`` →
  ``service.deadline_at``): ``check()`` trips itself once the wall clock
  passes it, with no scheduler involvement;
- an explicit **user cancel** (``DELETE /jobs/<id>``);
- the **watchdog** (per-phase progress heartbeat stalled — ``check()``
  doubles as the progress touch, so a job that keeps reaching boundaries
  is never considered stalled).
"""

from __future__ import annotations

import contextlib
import threading
import time


class JobCancelledError(RuntimeError):
    """Raised inside a job when its CancelToken has been tripped."""


class DeadlineExceededError(JobCancelledError):
    """The job's absolute deadline passed (terminal — never retried)."""


class StreamIdleError(JobCancelledError):
    """A live acquisition went silent: no chunk was committed for
    ``service.stream.idle_timeout_s`` (ISSUE 19).  Stream jobs are exempt
    from the absolute submit deadline — an acquisition has no known length
    — so THIS is their liveness bound.  Terminal like a deadline trip:
    retrying cannot conjure the missing chunks."""


class CancelToken:
    """Thread-safe one-shot cancellation flag with an optional absolute
    deadline and a progress heartbeat for the scheduler's stall watchdog."""

    # smlint guarded-by registry (docs/ANALYSIS.md): the first-cancel-wins
    # reason may only be written under _lock; last_progress/progress_phase
    # are deliberately unsynchronized heartbeat fields (benign races)
    _GUARDED_BY = {"reason": "_lock"}

    def __init__(self, deadline_at: float | None = None):
        self._event = threading.Event()
        self._lock = threading.Lock()
        self.reason = ""
        self.deadline_at = deadline_at or None
        self.last_progress = time.time()
        self.progress_phase = ""

    def cancel(self, reason: str) -> bool:
        """Trip the token.  The first cancel wins (its reason sticks);
        returns True when THIS call did the tripping."""
        with self._lock:
            if self._event.is_set():
                return False
            self.reason = reason
            self._event.set()
            return True

    def cancelled(self) -> bool:
        """True once cancelled — including by a passed deadline, which is
        detected lazily here so pure pollers see it without a watcher."""
        if self._event.is_set():
            return True
        if self.deadline_at is not None and time.time() >= self.deadline_at:
            self.cancel(f"deadline exceeded ({self.deadline_at:.3f})")
            return True
        return False

    def deadline_exceeded(self) -> bool:
        return self.deadline_at is not None and time.time() >= self.deadline_at

    def remaining_s(self) -> float | None:
        """Seconds until the deadline (None when no deadline is set)."""
        if self.deadline_at is None:
            return None
        return self.deadline_at - time.time()

    def touch(self, phase: str = "") -> None:
        """Progress heartbeat: the watchdog cancels attempts whose last
        touch is older than ``service.watchdog_stall_s``."""
        self.last_progress = time.time()
        if phase:
            self.progress_phase = phase

    def check(self, phase: str = "") -> None:
        """The cooperative checkpoint: record progress, then raise if the
        token is tripped (``DeadlineExceededError`` for deadline trips so
        the scheduler can tell terminal from retryable)."""
        self.touch(phase)
        if self.cancelled():
            if self.reason.startswith("deadline"):
                raise DeadlineExceededError(self.reason)
            raise JobCancelledError(self.reason or "cancelled")


@contextlib.contextmanager
def hold_cancellable(lock, cancel: CancelToken | None, poll_s: float = 0.1,
                     phase: str = "device_token"):
    """``with lock:`` that stays cancellable while WAITING for the lock —
    a cancelled job must not sit in the device-token queue forever.  With no
    lock or no token this degrades to the plain context manager forms."""
    if lock is None:
        if cancel is not None:
            cancel.check(phase)
        yield
        return
    if cancel is None:
        with lock:
            yield
        return
    while not lock.acquire(timeout=poll_s):
        cancel.check(f"{phase}_wait")
    try:
        cancel.check(phase)
        yield
    finally:
        lock.release()
