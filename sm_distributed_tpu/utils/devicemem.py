"""Device memory introspection (HBM occupancy) with a graceful CPU fallback.

TPU/GPU PJRT devices expose ``Device.memory_stats()`` — a dict with
``bytes_in_use`` / ``peak_bytes_in_use`` / ``bytes_limit`` (names vary
slightly by runtime; the accessors below normalize the common aliases).
CPU devices return ``None`` (or raise), and a process that never imported
jax has nothing to report at all: every function here degrades to
``None``-valued fields instead of failing, so telemetry and bench pinning
work identically on a laptop and on a v5e pod slice.

Deliberately import-light: ``jax`` is only touched if it is ALREADY
imported (``sys.modules`` check) — sampling device stats from the
service's telemetry thread must never be the thing that initializes a
PJRT client (which would break fork-based floors and pay a multi-second
startup inside a metrics scrape).
"""

from __future__ import annotations

import sys

from .logger import logger

# memory_stats key aliases across PJRT runtimes
_IN_USE_KEYS = ("bytes_in_use", "bytes_used")
_PEAK_KEYS = ("peak_bytes_in_use", "peak_bytes")
_LIMIT_KEYS = ("bytes_limit", "bytes_reservable_limit")


def _pick(stats: dict, keys: tuple[str, ...]):
    for k in keys:
        v = stats.get(k)
        if isinstance(v, (int, float)):
            return int(v)
    return None


def jax_if_loaded():
    """The jax module if this process already initialized it, else None."""
    return sys.modules.get("jax")


def device_stats(force_import: bool = False) -> list[dict]:
    """One dict per local device: ``{id, kind, platform, bytes_in_use,
    peak_bytes, limit_bytes}`` — the byte fields are ``None`` when the
    platform exposes no memory stats (CPU, or a runtime without the API).

    Returns ``[]`` when jax is unavailable or uninitializable.  By default
    only an ALREADY-imported jax is used (see module docstring);
    ``force_import`` opts into importing it (bench, CLI probes).
    """
    jax = jax_if_loaded()
    if jax is None:
        if not force_import:
            return []
        try:
            import jax  # noqa: F811
        except Exception as exc:
            logger.debug("devicemem: jax import failed (%s); no device "
                         "stats", exc)
            return []
    try:
        devices = jax.local_devices()
    except Exception as exc:
        logger.debug("devicemem: jax.local_devices() failed (%s); no "
                     "device stats", exc)
        return []
    out = []
    for d in devices:
        stats = None
        try:
            stats = d.memory_stats()
        except Exception as exc:  # CPU backends raise or return None
            logger.debug("devicemem: memory_stats() unavailable on %r (%s)",
                         d, exc)
            stats = None
        stats = stats if isinstance(stats, dict) else {}
        out.append({
            "id": int(getattr(d, "id", len(out))),
            "kind": str(getattr(d, "device_kind", "unknown")),
            "platform": str(getattr(d, "platform", "unknown")),
            "bytes_in_use": _pick(stats, _IN_USE_KEYS),
            "peak_bytes": _pick(stats, _PEAK_KEYS),
            "limit_bytes": _pick(stats, _LIMIT_KEYS),
        })
    return out


def hbm_summary(force_import: bool = False) -> dict:
    """Cross-device roll-up for bench pinning and phase capture:
    ``{device_kind, device_count, hbm_bytes_in_use, hbm_peak_bytes,
    hbm_limit_bytes}``.  Byte fields are ``None`` when NO device reports
    memory stats (the pinned-``null`` contract in bench JSON); in_use/limit
    sum across devices, peak takes the max (peaks are per-device
    high-water marks and do not add meaningfully)."""
    per = device_stats(force_import=force_import)
    in_use = [d["bytes_in_use"] for d in per if d["bytes_in_use"] is not None]
    peaks = [d["peak_bytes"] for d in per if d["peak_bytes"] is not None]
    limits = [d["limit_bytes"] for d in per if d["limit_bytes"] is not None]
    return {
        "device_kind": per[0]["kind"] if per else None,
        "device_count": len(per),
        "hbm_bytes_in_use": sum(in_use) if in_use else None,
        "hbm_peak_bytes": max(peaks) if peaks else None,
        "hbm_limit_bytes": sum(limits) if limits else None,
    }


def hbm_peak_bytes() -> int | None:
    """Max per-device peak HBM, or ``None`` without memory stats — the
    one-liner phase capture calls on every phase exit."""
    peaks = [d["peak_bytes"] for d in device_stats()
             if d["peak_bytes"] is not None]
    return max(peaks) if peaks else None
