"""The shipped smlint rules (docs/ANALYSIS.md has the catalog).

Every rule is a pure function over a parsed :class:`~.core.Project` and
ships a firing + passing fixture (``--self-check`` re-proves both, so a
rule that silently stops firing is itself a lint failure).

Rules:

- ``fence-gate``        — replicated write seams dominated by a fence guard
- ``failpoint-registry``— failpoints registered, called, documented, chaos-covered
- ``metrics-conventions``— ``sm_`` prefix, one kind per name, documented
- ``config-drift``      — SMConfig knobs <-> template <-> docs, both ways
- ``guarded-by``        — declared shared attrs mutated only under their lock
- ``broad-except``      — no silent ``except Exception`` swallows
- ``atomic-write``      — spool/lease/registry writes use unique-tmp + os.replace
- ``jit-compile-surface``— every jit/pjit/shard_map site declared in COMPILE_SURFACE
- ``retrace-hazard``    — raw shapes/lengths can't flow into static args unbucketed
- ``host-sync``         — device->host syncs in hot scoring modules are annotated
- ``dtype-flow``        — implicit-promotion hazards in NUMERICS-declaring modules
- ``masked-reduction``  — reductions over lattice-padded axes use the n_real helpers
- ``ulp-contract``      — every compile-surface site declares a test-backed contract

The local-variable taint walks (``fence-gate``, ``retrace-hazard``,
``dtype-flow``, ``masked-reduction``) all ride the shared forward-dataflow
engine in ``dataflow.py`` (ISSUE 15): one walker, per-rule source/
sanitizer predicates, single-level call summaries.
"""

from __future__ import annotations

import ast
import json
import re
import struct

from . import dataflow
from . import numerics as numerics_mod
from .core import Finding, Project, rule
from .dataflow import TaintTracker

# findings are created with rule/severity placeholders; core.Rule.run stamps
# the registered values over them
def _finding(mod, node, message: str) -> Finding:
    return Finding("", "", mod.path, getattr(node, "lineno", 0), message,
                   anchor=mod.anchor(node))


# ------------------------------------------------------------- AST helpers
def _attr_chain(node: ast.AST) -> str:
    """Dotted name of an attribute/name chain (``self.leases.check``), or
    "" when the expression is not a plain chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _call_name(call: ast.Call) -> str:
    """Terminal callee name: ``failpoint`` for both ``failpoint(...)`` and
    ``x.failpoint(...)``."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _subtree_strs(node: ast.AST) -> set[str]:
    return {n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)}


def _self_attr(node: ast.AST) -> str | None:
    """``X`` for an expression ``self.X``, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


# =========================================================== 1. fence-gate
# the fenced write seams, anchored on their failpoint constants (PR 2/8
# placed a failpoint at exactly these seams, so the anchor cannot drift
# away from the write it marks)
_FENCED_FAILPOINTS = {
    "spool.complete": "spool complete (running/ -> done/)",
    "sched.retry_publish": "retry republish into pending/",
}
# terminal-spool dirs whose writes are dead-letter/quarantine seams
_TERMINAL_DIRS = ("failed", "quarantine")


def _terminal_dir_source(node: ast.AST) -> bool:
    """Taint source for the fence-gate walk: a string constant naming a
    terminal spool directory (the same subtree-string test the rule's
    original in-line walk applied to assignment RHSs)."""
    return isinstance(node, ast.Constant) and node.value in _TERMINAL_DIRS
# storage-layer commits gated at their CALL SITE (the storage module itself
# is the layer below the fence; its callers own the guard)
_GATED_CALLS = ("finish_job",)
_FENCE_GUARDS = ("fence", "_fence_ok")

_FENCE_FIXTURE_FAIL = {
    "sm_distributed_tpu/service/x.py": (
        "from u import register_failpoint, failpoint\n"
        "FP_C = register_failpoint('spool.complete', 'seam')\n"
        "class S:\n"
        "    def _finish(self, claimed):\n"
        "        failpoint(FP_C, path=claimed)\n"
        "        move(claimed)\n"
        "    def _dead_letter(self, claimed):\n"
        "        (self.root / 'failed' / claimed.name).write_text('x')\n"
        "    def _commit(self):\n"
        "        self.ledger.finish_job(1)\n"
    ),
}
_FENCE_FIXTURE_PASS = {
    "sm_distributed_tpu/service/x.py": (
        "from u import register_failpoint, failpoint\n"
        "FP_C = register_failpoint('spool.complete', 'seam')\n"
        "class S:\n"
        "    def _finish(self, claimed, rec):\n"
        "        if not self._fence_ok(rec, 'complete'):\n"
        "            return\n"
        "        failpoint(FP_C, path=claimed)\n"
        "        move(claimed)\n"
        "    def _dead_letter(self, claimed, rec):\n"
        "        if not self._fence_ok(rec, 'dead_letter'):\n"
        "            return\n"
        "        dst = self.root / 'failed' / claimed.name\n"
        "        dst.write_text('x')\n"
        "    def _commit(self):\n"
        "        if self.fence is not None:\n"
        "            self.fence()\n"
        "        self.ledger.finish_job(1)\n"
    ),
}


def _fp_const_map(project: Project) -> dict[str, str]:
    """{constant name: failpoint name} from every
    ``FP_X = register_failpoint("name", ...)`` assignment."""
    out: dict[str, str] = {}
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    isinstance(node.value, ast.Call) and \
                    _call_name(node.value) == "register_failpoint" and \
                    node.value.args:
                name = _const_str(node.value.args[0])
                if name:
                    out[node.targets[0].id] = name
    return out


@rule("fence-gate", severity="error",
      doc="Replicated write seams (spool complete/republish, dead-letter/"
          "quarantine writes, result store, ledger commit) must be "
          "dominated by a fence guard (LeaseStore.check via _fence_ok or "
          "a JobContext/SearchJob fence call) in the same function.",
      fixture_fail=_FENCE_FIXTURE_FAIL, fixture_pass=_FENCE_FIXTURE_PASS)
def fence_gate(project: Project):
    fp_names = _fp_const_map(project)
    for mod in project.modules:
        if not mod.path.startswith("sm_distributed_tpu/"):
            continue                  # scripts/benches drive, they don't own
                                      # replicated spool state
        if mod.path.endswith("engine/storage.py"):
            continue                  # the layer below the gate: its callers
                                      # (SearchJob, scheduler) own the guard
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            guards: list[int] = []    # linenos of fence-guard calls
            seams: list[tuple[ast.AST, str]] = []
            # shared dataflow engine (ISSUE 15): locals assigned from
            # expressions naming a terminal dir become tainted paths
            taint = TaintTracker(source=_terminal_dir_source)
            for node in taint.walk(mod, fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = _call_name(node)
                if callee in _FENCE_GUARDS:
                    guards.append(node.lineno)
                elif callee == "check" and isinstance(node.func, ast.Attribute) \
                        and "leases" in _attr_chain(node.func):
                    guards.append(node.lineno)
                elif callee == "failpoint" and node.args and \
                        isinstance(node.args[0], ast.Name):
                    seam = _FENCED_FAILPOINTS.get(
                        fp_names.get(node.args[0].id, ""))
                    if seam:
                        seams.append((node, seam))
                elif callee == "write_text" and isinstance(node.func, ast.Attribute):
                    recv = node.func.value
                    hit = _subtree_strs(recv) & set(_TERMINAL_DIRS)
                    if not hit and isinstance(recv, ast.Name) and \
                            recv.id in taint.names:
                        hit = {"(tainted path)"}
                    if hit:
                        seams.append(
                            (node, f"terminal-spool write ({sorted(hit)[0]})"))
                elif callee == "replace" and \
                        _attr_chain(node.func) == "os.replace" and any(
                            _subtree_strs(a) & set(_TERMINAL_DIRS) or (
                                isinstance(a, ast.Name) and a.id in taint.names)
                            for a in node.args):
                    seams.append((node, "terminal-spool move"))
                elif callee in _GATED_CALLS:
                    seams.append((node, f"ledger commit ({callee})"))
                elif callee == "store" and isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Attribute) and \
                        node.func.value.attr == "store":
                    seams.append((node, "result store (store.store)"))
            for node, what in seams:
                if not any(g <= node.lineno for g in guards):
                    yield _finding(
                        mod, node,
                        f"{what} is not dominated by a fence guard "
                        f"(_fence_ok / fence() / leases.check) in "
                        f"{mod.qualname(node) or 'module scope'}")


# ==================================================== 2. failpoint-registry
_FPREG_FIXTURE_FAIL = {
    "sm_distributed_tpu/x.py": (
        "from u import register_failpoint, failpoint\n"
        "FP_A = register_failpoint('seam.a', 'covered')\n"
        "FP_DEAD = register_failpoint('seam.dead', 'never called')\n"
        "def f(p):\n"
        "    failpoint(FP_A, path=p)\n"
        "    failpoint(FP_GHOST)\n"
    ),
    "aux": {"docs/RECOVERY.md": "only `seam.a` is documented here\n",
            "scripts/chaos_sweep.py": "SCENARIOS = []\n"},
}
_FPREG_FIXTURE_PASS = {
    "sm_distributed_tpu/x.py": (
        "from u import register_failpoint, failpoint\n"
        "FP_A = register_failpoint('seam.a', 'covered')\n"
        "def f(p):\n"
        "    failpoint(FP_A, path=p)\n"
    ),
    "aux": {"docs/RECOVERY.md": "`seam.a` does X\n",
            "scripts/chaos_sweep.py": "Scenario('seam.a', ...)\n"},
}


@rule("failpoint-registry", severity="error",
      doc="Every registered failpoint must have >=1 call site (no dead "
          "entries), be documented in docs/RECOVERY.md, and be covered by "
          "a chaos_sweep scenario; every failpoint() call site must "
          "reference a registered constant.  Subsumes chaos_sweep "
          "--check-docs.",
      fixture_fail=_FPREG_FIXTURE_FAIL, fixture_pass=_FPREG_FIXTURE_PASS)
def failpoint_registry(project: Project):
    fp_names = _fp_const_map(project)
    registered: dict[str, tuple] = {}   # name -> (mod, node)
    called: set[str] = set()
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if _call_name(node) == "register_failpoint" and node.args:
                name = _const_str(node.args[0])
                if not name:
                    yield _finding(mod, node,
                                   "register_failpoint name must be a "
                                   "string literal")
                elif name in registered:
                    yield _finding(
                        mod, node,
                        f"failpoint {name!r} registered twice (also at "
                        f"{registered[name][0].path}:"
                        f"{registered[name][1].lineno})")
                else:
                    registered[name] = (mod, node)
            elif _call_name(node) == "failpoint" and node.args and \
                    mod.path != "sm_distributed_tpu/utils/failpoints.py":
                arg = node.args[0]
                name = _const_str(arg) or (
                    fp_names.get(arg.id) if isinstance(arg, ast.Name)
                    else None)
                if name is None:
                    yield _finding(
                        mod, node,
                        "failpoint() called with an argument that does not "
                        "resolve to a register_failpoint constant")
                else:
                    called.add(name)
    recovery = project.read("docs/RECOVERY.md") or ""
    chaos_mod = project.module("scripts/chaos_sweep.py")
    chaos_src = chaos_mod.source if chaos_mod else (
        project.read("scripts/chaos_sweep.py") or "")
    for name, (mod, node) in sorted(registered.items()):
        if name not in called:
            yield _finding(mod, node,
                           f"failpoint {name!r} is registered but never "
                           f"reached by a failpoint() call site (dead entry)")
        if name not in recovery:
            yield _finding(mod, node,
                           f"failpoint {name!r} is not documented in "
                           f"docs/RECOVERY.md")
        if name not in chaos_src:
            yield _finding(mod, node,
                           f"failpoint {name!r} has no chaos_sweep scenario")


# ================================================== 3. metrics-conventions
_METRIC_KINDS = ("counter", "gauge", "histogram")
_METRIC_NAME_RE = re.compile(r"^sm_[a-z0-9_]+$")
_METRIC_DOCS = ("docs/OBSERVABILITY.md", "docs/SERVICE.md")

_METRICS_FIXTURE_FAIL = {
    "sm_distributed_tpu/x.py": (
        "def f(m):\n"
        "    m.counter('jobs_total', 'no prefix').inc()\n"
        "    m.gauge('sm_thing', 'kind conflict').set(1)\n"
        "    m.counter('sm_thing', 'kind conflict').inc()\n"
        "    m.counter('sm_undocumented_total', 'not in docs').inc()\n"
    ),
    "aux": {"docs/OBSERVABILITY.md": "`sm_thing` is documented\n"},
}
_METRICS_FIXTURE_PASS = {
    "sm_distributed_tpu/x.py": (
        "def f(m):\n"
        "    m.counter('sm_jobs_total', 'documented').inc()\n"
    ),
    "aux": {"docs/OBSERVABILITY.md": "`sm_jobs_total` counts jobs\n"},
}


@rule("metrics-conventions", severity="error",
      doc="Every metric registered by literal name must be sm_-prefixed, "
          "keep ONE kind (counter/gauge/histogram) across the tree, and be "
          "documented in docs/OBSERVABILITY.md or docs/SERVICE.md.",
      fixture_fail=_METRICS_FIXTURE_FAIL, fixture_pass=_METRICS_FIXTURE_PASS)
def metrics_conventions(project: Project):
    docs = project.doc_text(*_METRIC_DOCS)
    seen: dict[str, tuple[str, object, object]] = {}  # name -> (kind, mod, node)
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and
                    _call_name(node) in _METRIC_KINDS and node.args):
                continue
            name = _const_str(node.args[0])
            if name is None:
                continue              # dynamic names (registry internals)
            kind = _call_name(node)
            if not _METRIC_NAME_RE.match(name):
                yield _finding(mod, node,
                               f"metric {name!r} violates the sm_ naming "
                               f"convention (^sm_[a-z0-9_]+$)")
            prior = seen.get(name)
            if prior is None:
                seen[name] = (kind, mod, node)
                if name not in docs:
                    yield _finding(
                        mod, node,
                        f"metric {name!r} is not documented in "
                        f"{' or '.join(_METRIC_DOCS)}")
            elif prior[0] != kind:
                yield _finding(
                    mod, node,
                    f"metric {name!r} registered as {kind} here but as "
                    f"{prior[0]} at {prior[1].path}:{prior[2].lineno} — "
                    f"one name, one kind")


# ========================================================= 4. config-drift
_CONFIG_MODULE = "utils/config.py"
_TEMPLATES = {"SMConfig": "conf/config.json.template",
              "DSConfig": "conf/ds_config.json.template"}

_CONFIG_FIXTURE_FAIL = {
    "sm_distributed_tpu/utils/config.py": (
        "from dataclasses import dataclass, field\n"
        "@dataclass\n"
        "class SubConfig:\n"
        "    knob_a: int = 1\n"
        "@dataclass\n"
        "class SMConfig:\n"
        "    backend: str = 'x'\n"
        "    missing_from_template: int = 0\n"
        "    sub: SubConfig = field(default_factory=SubConfig)\n"
    ),
    "aux": {
        "conf/config.json.template": json.dumps(
            {"backend": "x", "sub": {"knob_a": 1, "ghost_key": 2}}),
        "README.md": "backend knob_a ghost_key docs\n",
    },
}
_CONFIG_FIXTURE_PASS = {
    "sm_distributed_tpu/utils/config.py": (
        "from dataclasses import dataclass, field\n"
        "@dataclass\n"
        "class SMConfig:\n"
        "    backend: str = 'x'\n"
    ),
    "aux": {"conf/config.json.template": json.dumps({"backend": "x"}),
            "README.md": "the backend knob is documented\n"},
}


def _dataclass_fields(mod) -> dict[str, list[tuple[str, str, int]]]:
    """{ClassName: [(field, annotation_name, lineno)]} for @dataclass
    classes (ClassVar and properties excluded)."""
    out: dict[str, list[tuple[str, str, int]]] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        if not any("dataclass" in _attr_chain(d) or (
                isinstance(d, ast.Call) and "dataclass" in _attr_chain(d.func))
                for d in node.decorator_list):
            continue
        fields = []
        for stmt in node.body:
            if not (isinstance(stmt, ast.AnnAssign) and
                    isinstance(stmt.target, ast.Name)):
                continue
            ann = stmt.annotation
            ann_name = ann.id if isinstance(ann, ast.Name) else (
                _const_str(ann) or "")
            if "ClassVar" in ast.dump(ann):
                continue
            fields.append((stmt.target.id, ann_name.strip('"'), stmt.lineno))
        out[node.name] = fields
    return out


def _knob_tree(classes: dict, cls: str, prefix: str = "") -> dict[str, int]:
    """{dotted knob path: lineno}; nested dataclass fields recurse."""
    out: dict[str, int] = {}
    for name, ann, lineno in classes.get(cls, []):
        ann = ann.strip("'\" ")
        if ann in classes:
            out.update(_knob_tree(classes, ann, prefix + name + "."))
        else:
            out[prefix + name] = lineno
    return out


def _template_keys(data: dict, prefix: str = "") -> set[str]:
    out: set[str] = set()
    for k, v in data.items():
        if k.startswith("__"):
            continue                  # template comment keys
        if isinstance(v, dict):
            out |= _template_keys(v, prefix + k + ".")
        else:
            out.add(prefix + k)
    return out


@rule("config-drift", severity="error",
      doc="Every SMConfig/DSConfig knob must appear in its conf/*.template "
          "and in the docs (docs/*.md or README), and every template key "
          "must be a real knob.",
      fixture_fail=_CONFIG_FIXTURE_FAIL, fixture_pass=_CONFIG_FIXTURE_PASS)
def config_drift(project: Project):
    mod = project.module(_CONFIG_MODULE)
    if mod is None:
        return
    classes = _dataclass_fields(mod)
    docs = [project.read("README.md") or ""]
    if project.root is not None:
        docs += [p.read_text() for p in sorted(
            (project.root / "docs").glob("*.md"))]
    docs += [v for k, v in project.aux.items()
             if k.startswith("docs/") and k != "README.md"]
    doc_text = "\n".join(docs)
    for cls, tmpl_path in _TEMPLATES.items():
        if cls not in classes:
            continue
        knobs = _knob_tree(classes, cls)
        raw = project.read(tmpl_path)
        if raw is None:
            yield _finding(mod, mod.tree, f"missing template {tmpl_path}")
            continue
        tmpl = _template_keys(json.loads(raw))
        for knob, lineno in sorted(knobs.items()):
            if knob not in tmpl:
                yield Finding("", "", mod.path, lineno,
                              f"{cls} knob {knob!r} is missing from "
                              f"{tmpl_path}", anchor=f"{cls}.{knob}")
            leaf = knob.split(".")[-1]
            if leaf not in doc_text:
                yield Finding("", "", mod.path, lineno,
                              f"{cls} knob {knob!r} is not documented "
                              f"anywhere under docs/ or README.md",
                              anchor=f"{cls}.{knob}.docs")
        for key in sorted(tmpl - set(knobs)):
            yield Finding("", "", mod.path, 0,
                          f"{tmpl_path} key {key!r} is not a {cls} knob "
                          f"(typo or removed config?)",
                          anchor=f"{cls}.template.{key}")


# ============================================================ 5. guarded-by
_MUTATORS = {"append", "extend", "insert", "remove", "pop", "popitem",
             "clear", "update", "add", "discard", "setdefault",
             "move_to_end", "appendleft", "popleft", "sort", "reverse"}

_GUARDED_FIXTURE_FAIL = {
    "sm_distributed_tpu/x.py": (
        "import threading\n"
        "class C:\n"
        "    _GUARDED_BY = {'_items': '_lock', '_count': '_lock'}\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._items = []\n"
        "        self._count = 0\n"
        "    def bad(self, x):\n"
        "        self._items.append(x)\n"
        "        self._count += 1\n"
    ),
}
_GUARDED_FIXTURE_PASS = {
    "sm_distributed_tpu/x.py": (
        "import threading\n"
        "class C:\n"
        "    _GUARDED_BY = {'_items': '_lock', '_count': '_lock'}\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._items = []\n"
        "        self._count = 0\n"
        "    def good(self, x):\n"
        "        with self._lock:\n"
        "            self._items.append(x)\n"
        "            self._count += 1\n"
        "    def _drain_locked(self):\n"
        "        self._items.clear()\n"
    ),
}


def _guarded_decls(cls: ast.ClassDef) -> dict[str, str]:
    """The class's ``_GUARDED_BY = {attr: lock}`` declaration, if any."""
    for stmt in cls.body:
        targets = stmt.targets if isinstance(stmt, ast.Assign) else (
            [stmt.target] if isinstance(stmt, ast.AnnAssign) else [])
        if any(isinstance(t, ast.Name) and t.id == "_GUARDED_BY"
               for t in targets) and isinstance(
                   getattr(stmt, "value", None), ast.Dict):
            out = {}
            for k, v in zip(stmt.value.keys, stmt.value.values):
                ks, vs = _const_str(k), _const_str(v)
                if ks and vs:
                    out[ks] = vs
            return out
    return {}


def _mutated_attr(node: ast.AST) -> str | None:
    """``X`` when ``node`` mutates ``self.X``: assignment/augassign/del of
    ``self.X`` (or a subscript of it), or a mutating method call on it."""
    if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign, ast.Delete)):
        targets = getattr(node, "targets", None) or \
            [getattr(node, "target", None)]
        for t in targets:
            if t is None:
                continue
            base = t.value if isinstance(t, ast.Subscript) else t
            attr = _self_attr(base)
            if attr:
                return attr
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr in _MUTATORS:
        return _self_attr(node.func.value)
    return None


def _holds_lock(mod, node: ast.AST, lock: str) -> bool:
    """Is ``node`` lexically inside ``with self.<lock>:``?"""
    for anc in mod.ancestors(node):
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                if _self_attr(item.context_expr) == lock:
                    return True
    return False


@rule("guarded-by", severity="error",
      doc="Attributes declared in a class's _GUARDED_BY registry may only "
          "be mutated inside `with self.<lock>:` — except in __init__ "
          "(happens-before publication) and in methods named *_locked "
          "(documented caller-holds-lock convention).",
      fixture_fail=_GUARDED_FIXTURE_FAIL, fixture_pass=_GUARDED_FIXTURE_PASS)
def guarded_by(project: Project):
    for mod in project.modules:
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            decls = _guarded_decls(cls)
            if not decls:
                continue
            for fn in cls.body:
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                if fn.name == "__init__" or fn.name.endswith("_locked"):
                    continue
                for node in ast.walk(fn):
                    attr = _mutated_attr(node)
                    if attr is None or attr not in decls:
                        continue
                    lock = decls[attr]
                    if not _holds_lock(mod, node, lock):
                        yield _finding(
                            mod, node,
                            f"{cls.name}.{attr} is declared guarded by "
                            f"self.{lock} but is mutated in {fn.name}() "
                            f"without holding it")


# ========================================================== 6. atomic-write
# directories whose contents other processes/threads read CONCURRENTLY by
# glob: a non-atomic write here is a torn-JSON/BadZipFile waiting for a
# reader (the spool states, the fenced-lease files, the replica registry).
# The convention (PR 1/2/8): write a unique tmp name, then os.replace /
# Path.replace into place.
_AW_DIRS = ("pending", "running", "done", "failed", "quarantine",
            "leases", "replicas")
_AW_WRITE_METHODS = ("write_text", "write_bytes")

_AW_FIXTURE_FAIL = {
    "sm_distributed_tpu/service/x.py": (
        "class S:\n"
        "    def bad_direct(self, msg_id, data):\n"
        "        (self.root / 'failed' / msg_id).write_text(data)\n"
        "    def bad_open(self, msg_id, data):\n"
        "        dst = self.root / 'pending' / msg_id\n"
        "        with open(dst, 'w') as f:\n"
        "            f.write(data)\n"
        "    def bad_tmp_no_replace(self, msg_id, data):\n"
        "        tmp = self.root / 'pending' / f'.{msg_id}.tmp'\n"
        "        tmp.write_text(data)\n"
    ),
}
_AW_FIXTURE_PASS = {
    "sm_distributed_tpu/service/x.py": (
        "import os\n"
        "class S:\n"
        "    def good(self, msg_id, data):\n"
        "        tmp = self.root / 'pending' / f'.{msg_id}.tmp'\n"
        "        tmp.write_text(data)\n"
        "        os.replace(tmp, self.root / 'pending' / f'{msg_id}.json')\n"
        "    def good_path_replace(self, msg_id, data):\n"
        "        tmp = self.root / 'leases' / f'.{msg_id}.tmp'\n"
        "        tmp.write_text(data)\n"
        "        tmp.replace(self.root / 'leases' / f'{msg_id}.json')\n"
        "    def reader(self):\n"
        "        return (self.root / 'done' / 'x.json').read_text()\n"
    ),
}


def _open_write_mode(call: ast.Call) -> bool:
    """``open(..., 'w'/'wb'/...)`` — any truncating/creating text/binary
    write mode (append keeps prior bytes but still tears concurrent
    readers; included)."""
    mode = None
    if len(call.args) >= 2:
        mode = _const_str(call.args[1])
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = _const_str(kw.value)
    return bool(mode) and any(c in mode for c in "wax")


@rule("atomic-write", severity="error",
      doc="Any open-for-write landing in a concurrently-globbed spool/"
          "lease/registry directory (pending, running, done, failed, "
          "quarantine, leases, replicas) must follow the unique-tmp + "
          "os.replace convention: the write target must be a tmp name and "
          "the same function must replace it into place afterwards.",
      fixture_fail=_AW_FIXTURE_FAIL, fixture_pass=_AW_FIXTURE_PASS)
def atomic_write(project: Project):
    for mod in project.modules:
        if not mod.path.startswith("sm_distributed_tpu/"):
            continue                  # scripts/benches are single-actor
                                      # drivers over their own sandboxes
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            # locals assigned from expressions naming a protected dir;
            # value = whether the SAME expression names a tmp component
            tainted: dict[str, bool] = {}
            replaces: list[int] = []
            writes: list[tuple[ast.AST, str, bool]] = []
            for node in ast.walk(fn):
                if mod.enclosing_function(node) is not fn and node is not fn:
                    continue          # skip nested defs/lambdas
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    strs = _subtree_strs(node.value)
                    if strs & set(_AW_DIRS):
                        tainted[node.targets[0].id] = any(
                            "tmp" in s for s in strs)
                if not isinstance(node, ast.Call):
                    continue
                callee = _call_name(node)
                if callee == "replace":
                    replaces.append(node.lineno)
                    continue
                target = None
                if callee in _AW_WRITE_METHODS and \
                        isinstance(node.func, ast.Attribute):
                    target = node.func.value
                elif callee == "open" and node.args and \
                        _open_write_mode(node):
                    target = node.args[0]
                if target is None:
                    continue
                strs = _subtree_strs(target)
                is_tmp = any("tmp" in s for s in strs)
                hit = bool(strs & set(_AW_DIRS))
                if not hit and isinstance(target, ast.Name) and \
                        target.id in tainted:
                    hit = True
                    is_tmp = is_tmp or tainted[target.id]
                if hit:
                    writes.append((node, callee, is_tmp))
            for node, callee, is_tmp in writes:
                if not is_tmp:
                    yield _finding(
                        mod, node,
                        f"{callee}() writes directly into a concurrently-"
                        f"globbed spool/lease/registry directory — use a "
                        f"unique tmp name + os.replace (torn writes become "
                        f"reader-visible garbage)")
                elif not any(ln > node.lineno for ln in replaces):
                    yield _finding(
                        mod, node,
                        f"{callee}() writes a tmp file in a spool/lease/"
                        f"registry directory but "
                        f"{mod.qualname(node) or 'module scope'} never "
                        f"os.replace()s it into place — half a convention "
                        f"leaks orphan tmps")


# ==================================================== 7. jit-compile-surface
# The cold-start invariant (ROADMAP item 1): every jax.jit / pjit /
# shard_map call site must be covered by a module-level COMPILE_SURFACE
# registry (analysis/surface.py) naming its shape-bucket policy, and must
# declare its statics (static_argnames/static_argnums or donation) or be
# registered as statics=none / statics=closure(...).  The runtime half is
# the retrace tracer + scripts/compile_census.py.
_JIT_CALLEES = ("jit", "pjit")
_STATIC_KWARGS = ("static_argnames", "static_argnums",
                  "donate_argnums", "donate_argnames")
_POLICY_TOKENS = ("statics=", "buckets=")     # analysis/surface.POLICY_TOKENS

_JCS_FIXTURE_FAIL = {
    "sm_distributed_tpu/ops/x_jax.py": (
        "import jax\n"
        "from functools import partial\n"
        "def score(x, *, b):\n"
        "    return x\n"
        "class B:\n"
        "    def __init__(self):\n"
        "        self._fn = jax.jit(partial(score, b=1))\n"
    ),
}
_JCS_FIXTURE_PASS = {
    "sm_distributed_tpu/ops/x_jax.py": (
        "import jax\n"
        "from functools import partial\n"
        "from ..analysis.surface import compile_surface\n"
        "COMPILE_SURFACE = compile_surface(__name__, {\n"
        "    'score': 'statics=b; buckets=b padded to formula_batch',\n"
        "    'plain': 'statics=none; buckets=single static shape',\n"
        "})\n"
        "def score(x, *, b):\n"
        "    return x\n"
        "def plain(x):\n"
        "    return x\n"
        "class B:\n"
        "    def __init__(self):\n"
        "        self._fn = jax.jit(partial(score, b=1),\n"
        "                           static_argnames=('b',))\n"
        "        self._fp = jax.jit(plain)\n"
    ),
}


def _surface_decl(mod) -> tuple[dict[str, tuple[str, int]] | None, int]:
    """The module's ``COMPILE_SURFACE = compile_surface(_, {...})``
    declaration: ({site: (policy, lineno)}, decl lineno), or (None, 0)."""
    for node in mod.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1 and
                isinstance(node.targets[0], ast.Name) and
                node.targets[0].id == "COMPILE_SURFACE"):
            continue
        if not (isinstance(node.value, ast.Call) and
                _call_name(node.value) == "compile_surface" and
                len(node.value.args) >= 2 and
                isinstance(node.value.args[1], ast.Dict)):
            return {}, node.lineno    # declared but not the literal grammar
        out = {}
        for k, v in zip(node.value.args[1].keys,
                        node.value.args[1].values):
            ks, vs = _const_str(k), _const_str(v)
            if ks is not None:
                out[ks] = (vs or "", getattr(k, "lineno", node.lineno))
        return out, node.lineno
    return None, 0


def _jit_sites(mod):
    """Yield ``(call node, site name, static names | None, kind)`` for
    every jit/pjit/shard_map call site in ``mod``.  ``static names`` is
    the literal static_argnames tuple when given, () when a static/donate
    kwarg exists but is not a literal name tuple, None when the call
    declares no statics at all.  ``kind``: "jit" or "shard_map"."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = _call_name(node)
        kws = node.keywords
        kind = None
        if callee in _JIT_CALLEES:
            kind = "jit"
        elif callee == "shard_map":
            fn = mod.enclosing_function(node)
            if fn is not None and fn.name == "shard_map":
                continue              # the version-compat shim itself
            kind = "shard_map"
        elif callee == "partial" and node.args and \
                _attr_chain(node.args[0]).split(".")[-1] in _JIT_CALLEES:
            kind = "jit"              # @partial(jax.jit, static_argnames=...)
        if kind is None:
            continue
        statics: tuple | None = None
        for kw in kws:
            if kw.arg in _STATIC_KWARGS:
                names = []
                if isinstance(kw.value, (ast.Tuple, ast.List)):
                    names = [s for s in map(_const_str, kw.value.elts)
                             if s is not None]
                statics = tuple(sorted(set(list(statics or ()) + names)))
        if kind == "shard_map" and statics is None and any(
                kw.arg in ("in_specs", "out_specs") for kw in kws):
            statics = ()              # specs are the shard_map declaration
        yield node, _jit_site_name(mod, node), statics, kind


def _jit_site_name(mod, node: ast.Call) -> str:
    """Stable registry key for one jit site: the wrapped function's name
    when resolvable (decorated def, ``jax.jit(f)``, ``jax.jit(partial(f,
    ...))`` — plain or the name-preserving ``named_partial`` variant —
    ``jax.jit(shard_map(f, ...))``), else the assignment target
    (``self._fn = jax.jit(...)`` -> ``_fn``), else the enclosing
    qualname."""
    parent = mod.parents.get(node)
    # decorator (plain or partial-form): key on the decorated function
    if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
            node in parent.decorator_list:
        return parent.name
    wrapped = node.args[0] if node.args else None
    for _ in range(3):                # unwrap partial(...)/shard_map(...)
        if isinstance(wrapped, ast.Call) and \
                _call_name(wrapped) in ("partial", "named_partial",
                                        "shard_map") and \
                wrapped.args:
            wrapped = wrapped.args[0]
        else:
            break
    if isinstance(wrapped, ast.Name):
        return wrapped.id
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
        t = parent.targets[0]
        if isinstance(t, ast.Attribute):
            return t.attr
        if isinstance(t, ast.Name):
            return t.id
    return mod.qualname(node) or "<module>"


def _policy_statics(policy: str) -> str:
    """The ``statics=...`` clause of a policy string ("" when absent)."""
    for part in policy.split(";"):
        part = part.strip()
        if part.startswith("statics="):
            return part[len("statics="):].strip()
    return ""


@rule("jit-compile-surface", severity="error",
      doc="Every jax.jit / pjit / shard_map call site must be covered by "
          "a module-level COMPILE_SURFACE = compile_surface(__name__, "
          "{site: policy}) registry (analysis/surface.py) whose policy "
          "carries statics= and buckets= clauses; statics declared at the "
          "call site must match the registered statics, and sites with no "
          "static/donate declaration must register statics=none or "
          "statics=closure(...).  Dead registry entries are findings too.",
      fixture_fail=_JCS_FIXTURE_FAIL, fixture_pass=_JCS_FIXTURE_PASS)
def jit_compile_surface(project: Project):
    for mod in project.modules:
        if not mod.path.startswith("sm_distributed_tpu/"):
            continue                  # scripts/benches drive declared
                                      # surfaces; they don't own one
        sites = list(_jit_sites(mod))
        if not sites:
            continue
        decl, decl_line = _surface_decl(mod)
        if decl is None:
            yield Finding(
                "", "", mod.path, sites[0][0].lineno,
                f"module has {len(sites)} jit/shard_map call site(s) but "
                f"no COMPILE_SURFACE = compile_surface(__name__, "
                f"{{...}}) registry declaring its shape-bucket policy",
                anchor="COMPILE_SURFACE")
            continue
        used: set[str] = set()
        for node, site, statics, kind in sites:
            entry = decl.get(site)
            if entry is None:
                yield _finding(
                    mod, node,
                    f"{kind} call site {site!r} is not registered in this "
                    f"module's COMPILE_SURFACE (declare its statics and "
                    f"shape-bucket policy)")
                continue
            used.add(site)
            policy, _ln = entry
            missing = [t for t in _POLICY_TOKENS if t not in policy]
            if missing:
                yield _finding(
                    mod, node,
                    f"COMPILE_SURFACE entry {site!r} lacks the "
                    f"{'/'.join(missing)} clause(s) of the policy grammar")
                continue
            declared = _policy_statics(policy)
            if statics is None and not (
                    declared == "none" or declared.startswith("closure(")):
                yield _finding(
                    mod, node,
                    f"{kind} call site {site!r} declares no static_argnames"
                    f"/donation but its COMPILE_SURFACE entry says "
                    f"statics={declared!r} — declare the statics at the "
                    f"call site or register statics=none / closure(...)")
            elif statics:
                reg = tuple(sorted(s.strip() for s in declared.split(",")
                                   if s.strip()))
                if reg and reg != statics:
                    yield _finding(
                        mod, node,
                        f"{site!r} statics drift: call site declares "
                        f"{sorted(statics)} but COMPILE_SURFACE registers "
                        f"statics={declared!r}")
        for site, (policy, lineno) in sorted(decl.items()):
            if site not in used:
                yield Finding(
                    "", "", mod.path, lineno,
                    f"COMPILE_SURFACE entry {site!r} matches no jit/"
                    f"shard_map call site (dead entry — remove it or fix "
                    f"the site name)", anchor=f"COMPILE_SURFACE.{site}")


def compile_surface_census(project: Project) -> dict[str, int]:
    """Static totals for the perf_sentinel-comparable smlint artifact:
    jit/shard_map call sites, registered COMPILE_SURFACE entries, and
    modules carrying a registry."""
    sites = entries = modules = 0
    for mod in project.modules:
        if not mod.path.startswith("sm_distributed_tpu/"):
            continue
        mod_sites = list(_jit_sites(mod))
        sites += len(mod_sites)
        decl, _ = _surface_decl(mod)
        if decl:
            modules += 1
            entries += len(decl)
    return {"sites": sites, "entries": entries, "modules": modules}


# ========================================================= 8. retrace-hazard
# Raw runtime-shape reads (`x.shape[...]`, `len(x)`, `x.size`) flowing
# into a jitted callable's STATIC argument mint one executable per
# distinct value — the unbounded-signature family behind r4's 81-308 s
# cold compiles.  Static values must pass a bucketing/padding helper
# first so every dataset size lands in a small closed set.
_BUCKET_HELPERS = ("ions_per_chunk_for", "shape_key", "window_chunks",
                   "ion_window_chunks")

_RH_FIXTURE_FAIL = {
    "sm_distributed_tpu/ops/x_jax.py": (
        "import jax\n"
        "fn = jax.jit(score, static_argnames=('b', 'w'))\n"
        "def go(x):\n"
        "    return fn(x, b=x.shape[0])\n"
        "def go2(x):\n"
        "    n = len(x)\n"
        "    return fn(x, w=n)\n"
    ),
}
_RH_FIXTURE_PASS = {
    "sm_distributed_tpu/ops/x_jax.py": (
        "import jax\n"
        "fn = jax.jit(score, static_argnames=('b', 'w'))\n"
        "def go(x):\n"
        "    return fn(x, b=size_bucket(x.shape[0]))\n"
        "def go2(x):\n"
        "    n = round_up(len(x), 256)\n"
        "    return fn(x, w=n)\n"
    ),
}


def _is_shape_source(node: ast.AST) -> bool:
    """A raw runtime-shape read: ``.shape`` / ``.size`` attribute access
    or a ``len(...)`` call."""
    if isinstance(node, ast.Attribute) and node.attr in ("shape", "size"):
        return True
    return isinstance(node, ast.Call) and _call_name(node) == "len"


def _is_bucketing_call(node: ast.AST) -> bool:
    """A call through a recognized bucketing/padding helper: name contains
    ``bucket``/``round``/``pad``, or one of the named shape-plan helpers."""
    if not isinstance(node, ast.Call):
        return False
    callee = _call_name(node)
    return (callee in _BUCKET_HELPERS or
            any(t in callee for t in ("bucket", "round", "pad")))


@rule("retrace-hazard", severity="error",
      doc="Raw runtime-shape reads (.shape / .size / len()) must not flow "
          "into a jitted callable's static arguments (the kwarg names a "
          "module's jit sites declare via static_argnames) without "
          "passing a bucketing/padding helper — one executable per "
          "distinct value is the unbounded cold-compile family.",
      fixture_fail=_RH_FIXTURE_FAIL, fixture_pass=_RH_FIXTURE_PASS)
def retrace_hazard(project: Project):
    for mod in project.modules:
        if not mod.path.startswith("sm_distributed_tpu/"):
            continue
        # the module's static-arg namespace: every literal static name any
        # of its jit sites declares (per-module scoping keeps a common
        # kwarg like `b` in OTHER modules out of the sink set)
        static_names: set[str] = set()
        for _node, _site, statics, _kind in _jit_sites(mod):
            static_names |= set(statics or ())
        if not static_names:
            continue
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            # shared dataflow engine (ISSUE 15): raw shape reads taint
            # locals; ONE bucketing call anywhere in an expression
            # sanitizes the whole expression (the legacy flat contract)
            taint = TaintTracker(source=_is_shape_source,
                                 sanitizer=_is_bucketing_call)
            for node in taint.walk(mod, fn):
                if not isinstance(node, ast.Call):
                    continue
                for kw in node.keywords:
                    if kw.arg not in static_names:
                        continue
                    if taint.expr_tainted(kw.value):
                        yield _finding(
                            mod, node,
                            f"static argument {kw.arg!r} receives a raw "
                            f"runtime shape (.shape/.size/len) without a "
                            f"bucketing/padding helper — every distinct "
                            f"value compiles a new executable "
                            f"(retrace hazard)")


# ============================================================== 9. host-sync
# Device->host synchronization points in the HOT scoring modules: each
# np.asarray/np.array/device_get/block_until_ready/.item() stalls the
# async dispatch pipeline, so every one must be a deliberate, argued
# fetch point — annotated `# smlint: host-sync-ok[reason]`.
_HS_MODULES_EXACT = ("models/msm_jax.py", "parallel/sharded.py")
_HS_NP_CALLS = ("asarray", "array", "ascontiguousarray")
_HS_METHOD_CALLS = ("block_until_ready", "item")

_HS_FIXTURE_FAIL = {
    "sm_distributed_tpu/ops/x_jax.py": (
        "import numpy as np\n"
        "import jax\n"
        "def score(fn, x):\n"
        "    out = fn(x)\n"
        "    out.block_until_ready()\n"
        "    v = float(fn(x)[0])\n"
        "    return np.asarray(out), v\n"
    ),
}
_HS_FIXTURE_PASS = {
    "sm_distributed_tpu/ops/x_jax.py": (
        "import numpy as np\n"
        "def score(fn, x):\n"
        "    out = fn(x)\n"
        "    # smlint: host-sync-ok[the designed per-group fetch point]\n"
        "    return np.asarray(out)\n"
        "def host_prep(rows):\n"
        "    return [r + 1 for r in rows]\n"
    ),
}


def _is_hot_module(path: str) -> bool:
    if any(path.endswith(m) for m in _HS_MODULES_EXACT):
        return True
    return "/ops/" in path and path.endswith("_jax.py")


def _host_sync_call(node: ast.Call) -> str | None:
    """The sync kind when ``node`` is a device->host synchronization:
    np.asarray/np.array/..., jax.device_get, .block_until_ready(),
    .item(), or float()/int() directly over a call result."""
    callee = _call_name(node)
    chain = _attr_chain(node.func)
    if callee in _HS_NP_CALLS and chain.split(".")[0] in ("np", "numpy"):
        return f"np.{callee}"
    if callee == "device_get" and "jax" in chain:
        return "jax.device_get"
    if callee in _HS_METHOD_CALLS and isinstance(node.func, ast.Attribute):
        return f".{callee}()"
    # float() directly over a call result forces the value to host; int()
    # is excluded — it is overwhelmingly host-side index arithmetic
    # (int(np.searchsorted(...))), not a device sync
    if callee == "float" and len(node.args) == 1 and \
            isinstance(node.args[0], (ast.Call, ast.Subscript)) and any(
            isinstance(n, ast.Call) for n in ast.walk(node.args[0])):
        return "float() on a call result"
    return None


@rule("host-sync", severity="error",
      doc="Device->host syncs (np.asarray / np.array / jax.device_get / "
          ".block_until_ready() / .item() / float() on a call result) in "
          "the hot scoring modules (models/msm_jax.py, parallel/"
          "sharded.py, ops/*_jax.py) must carry a `# smlint: "
          "host-sync-ok[reason]` annotation — each sync is a deliberate "
          "pipeline stall that must be argued, not an accident.",
      fixture_fail=_HS_FIXTURE_FAIL, fixture_pass=_HS_FIXTURE_PASS)
def host_sync(project: Project):
    for mod in project.modules:
        if not mod.path.startswith("sm_distributed_tpu/") or \
                not _is_hot_module(mod.path):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _host_sync_call(node)
            if kind is None:
                continue
            reason = mod.host_sync_reason(node.lineno)
            if reason:
                continue
            if reason == "":
                yield _finding(
                    mod, node,
                    f"host-sync-ok annotation for {kind} has an empty "
                    f"reason — the reason is the point")
            else:
                yield _finding(
                    mod, node,
                    f"{kind} in a hot scoring module is a device->host "
                    f"sync point — annotate `# smlint: host-sync-ok"
                    f"[reason]` (why this stall is deliberate) or move it "
                    f"off the hot path")


# ========================================================== 10. broad-except
_LOG_METHODS = {"debug", "info", "warning", "error", "exception", "critical",
                "log", "write"}

_BROAD_FIXTURE_FAIL = {
    "sm_distributed_tpu/x.py": (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"
        "    try:\n"
        "        g()\n"
        "    except:\n"
        "        return None\n"
    ),
}
_BROAD_FIXTURE_PASS = {
    "sm_distributed_tpu/x.py": (
        "from .logger import logger\n"
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        logger.warning('g failed', exc_info=True)\n"
        "    try:\n"
        "        g()\n"
        "    except Exception as exc:\n"
        "        record(exc)\n"
        "        raise\n"
        "    try:\n"
        "        g()\n"
        "    except (OSError, ValueError):\n"
        "        pass\n"
    ),
}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True                   # bare except:
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    return any(isinstance(n, ast.Name) and
               n.id in ("Exception", "BaseException") for n in names)


def _handler_swallows(handler: ast.ExceptHandler) -> bool:
    """True when the body neither re-raises, nor logs, nor uses the bound
    exception (recording it somewhere counts as handling)."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return False
        if isinstance(node, ast.Name) and handler.name and \
                node.id == handler.name and isinstance(node.ctx, ast.Load):
            return False
        if isinstance(node, ast.Call):
            callee = _call_name(node)
            chain = _attr_chain(node.func)
            if callee in _LOG_METHODS and ("logger" in chain or
                                           "logging" in chain or
                                           "stderr" in chain or
                                           "stdout" in chain):
                return False
            if callee in ("record_recovery", "format_exc", "print_exc"):
                return False
    return True


@rule("broad-except", severity="error",
      doc="No `except Exception` / bare `except` that swallows silently: "
          "the handler must re-raise, log, or use the caught exception — "
          "or the except type must be narrowed.",
      fixture_fail=_BROAD_FIXTURE_FAIL, fixture_pass=_BROAD_FIXTURE_PASS)
def broad_except(project: Project):
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ExceptHandler) and _is_broad(node) and \
                    _handler_swallows(node):
                yield _finding(
                    mod, node,
                    "broad except swallows the exception without logging, "
                    "re-raising, or recording it — narrow the type or add "
                    "context (trace/job id) to a log line")


# ============================================================ 11. dtype-flow
# Implicit-promotion hazards in the NUMERICS-declaring (jitting) modules
# (ISSUE 15): a dtype-less jnp constructor mints a weak/x64-dependent
# dtype, a float64 value flowing into a jnp op silently promotes the
# declared-f32 graph (and flips ULP behavior the committed contracts
# pin), and a non-f32-exact bare float literal inside a jnp call changes
# value the moment someone flips jax_enable_x64.  Deliberate escapes are
# annotated `# smlint: dtype-ok[reason]`.
_JNP_CONSTRUCTORS = {
    # name -> positional index where dtype may legally appear (None =
    # keyword-only, because the positional form is ambiguous)
    "zeros": 1, "ones": 1, "empty": 1, "full": 2, "asarray": 1, "array": 1,
    "arange": None, "linspace": None, "eye": None,
}
_DTYPE_CAST_NAMES = ("float32", "float16", "bfloat16", "int8", "int16",
                     "int32", "int64", "uint8", "uint32", "bool_",
                     "float64", "double")
_F64_NAMES = ("float64", "double")


def _jnp_chain(chain: str) -> bool:
    """Is ``chain`` a jax-numpy/lax callable path (jnp.*, lax.*, jax.*)?"""
    root = chain.split(".")[0]
    return root in ("jnp", "lax") or chain.startswith("jax.")


def _numerics_decl(mod) -> tuple[dict[str, tuple[str, int]] | None, int]:
    """The module's ``NUMERICS = numerics_surface(_, {...})`` declaration:
    ({site: (policy, lineno)}, decl lineno), or (None, 0) — the exact
    mirror of ``_surface_decl``."""
    for node in mod.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1 and
                isinstance(node.targets[0], ast.Name) and
                node.targets[0].id == "NUMERICS"):
            continue
        if not (isinstance(node.value, ast.Call) and
                _call_name(node.value) == "numerics_surface" and
                len(node.value.args) >= 2 and
                isinstance(node.value.args[1], ast.Dict)):
            return {}, node.lineno    # declared but not the literal grammar
        out = {}
        for k, v in zip(node.value.args[1].keys, node.value.args[1].values):
            ks, vs = _const_str(k), _const_str(v)
            if ks is not None:
                out[ks] = (vs or "", getattr(k, "lineno", node.lineno))
        return out, node.lineno
    return None, 0


def _f32_exact(v: float) -> bool:
    """Is ``v`` exactly representable in float32 (so its value is
    identical at every promotion width)?"""
    try:
        return struct.unpack("f", struct.pack("f", v))[0] == v
    except (OverflowError, struct.error):
        return False


def _is_f64_dtype_expr(e: ast.AST) -> bool:
    """``np.float64`` / ``jnp.float64`` / ``"float64"`` / bare ``float``
    used as a dtype value."""
    chain = _attr_chain(e)
    if chain.split(".")[-1] in _F64_NAMES:
        return True
    if isinstance(e, ast.Name) and e.id == "float":
        return True
    return _const_str(e) in ("float64", "double")


def _f64_source(node: ast.AST) -> bool:
    """Taint source for the f64-flow walk: a ``np.float64``/``np.double``
    scalar mint, an ``.astype(float64-ish)`` cast, or any call carrying a
    ``dtype=float64-ish`` keyword."""
    if not isinstance(node, ast.Call):
        return False
    callee = _call_name(node)
    if callee in _F64_NAMES and \
            _attr_chain(node.func).split(".")[0] in ("np", "numpy", "jnp"):
        return True
    if callee == "astype" and node.args and _is_f64_dtype_expr(node.args[0]):
        return True
    return any(kw.arg == "dtype" and _is_f64_dtype_expr(kw.value)
               for kw in node.keywords)


_DF_FIXTURE_FAIL = {
    "sm_distributed_tpu/ops/x_jax.py": (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "from ..analysis.numerics import numerics_surface\n"
        "NUMERICS = numerics_surface(__name__, {\n"
        "    'score': 'contract=ulp(4); test=tests/test_x.py::test_score',\n"
        "})\n"
        "def score(x):\n"
        "    idx = jnp.arange(x.shape[0])\n"
        "    w = np.float64(0.5)\n"
        "    y = jnp.where(x > 0, x * 1e-30, 0.0)\n"
        "    return jnp.sum(y * w) + idx\n"
    ),
}
_DF_FIXTURE_PASS = {
    "sm_distributed_tpu/ops/x_jax.py": (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "from ..analysis.numerics import numerics_surface\n"
        "NUMERICS = numerics_surface(__name__, {\n"
        "    'score': 'contract=ulp(4); test=tests/test_x.py::test_score',\n"
        "})\n"
        "def score(x):\n"
        "    idx = jnp.arange(x.shape[0], dtype=jnp.int32)\n"
        "    w = np.float32(0.5)\n"
        "    y = jnp.where(x > 0, x * np.float32(1e-30), 0.0)\n"
        "    # smlint: dtype-ok[f64 epilogue runs on host after the fetch]\n"
        "    z = jnp.asarray(np.float64(2.0), dtype=jnp.float32)\n"
        "    return jnp.sum(y * w) * z + idx\n"
    ),
}


@rule("dtype-flow", severity="error",
      doc="In NUMERICS-declaring (jitting) modules: jnp constructors "
          "(zeros/ones/full/arange/asarray/...) must pass an explicit "
          "dtype (a dtype-less constructor mints a weak/x64-dependent "
          "type); float64 values (np.float64/np.double mints, "
          ".astype(float64), dtype=float64 kwargs) must not flow into "
          "jnp/lax calls — tracked through locals and single-level call "
          "summaries by the shared dataflow engine; and non-f32-exact "
          "bare float literals inside jnp/lax call arguments must be "
          "wrapped in an explicit dtype cast.  Deliberate escapes carry "
          "`# smlint: dtype-ok[reason]` (empty reason = finding).",
      fixture_fail=_DF_FIXTURE_FAIL, fixture_pass=_DF_FIXTURE_PASS)
def dtype_flow(project: Project):
    for mod in project.modules:
        if not mod.path.startswith("sm_distributed_tpu/"):
            continue
        decl, _ = _numerics_decl(mod)
        if decl is None:
            continue                  # not a declared-precision module

        def annotated(node) -> tuple[bool, bool]:
            """(skip, empty_reason) for the dtype-ok annotation."""
            reason = mod.annotation_reason("dtype", node.lineno)
            return reason is not None and reason != "", reason == ""

        # (a) dtype-less jnp constructors + (c) non-exact bare literals
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                chain = _attr_chain(node.func)
                callee = _call_name(node)
                if _jnp_chain(chain) and callee in _JNP_CONSTRUCTORS:
                    pos = _JNP_CONSTRUCTORS[callee]
                    has_dtype = any(kw.arg == "dtype"
                                    for kw in node.keywords) or (
                        pos is not None and len(node.args) > pos)
                    if not has_dtype:
                        ok, empty = annotated(node)
                        if ok:
                            continue
                        yield _finding(
                            mod, node,
                            f"dtype-less jnp.{callee}() in a declared-"
                            f"precision module mints a weak/x64-dependent "
                            f"dtype — pass dtype= explicitly or annotate "
                            f"`# smlint: dtype-ok[reason]`"
                            + (" (annotation reason is empty)" if empty
                               else ""))
                continue
            if not (isinstance(node, ast.Constant) and
                    isinstance(node.value, float)):
                continue
            if _f32_exact(node.value):
                continue              # value identical at every width
            in_jnp, sanitized = False, False
            for anc in mod.ancestors(node):
                if isinstance(anc, ast.Call):
                    if _call_name(anc) in _DTYPE_CAST_NAMES:
                        sanitized = True   # np.float32(lit): explicit width
                        break
                    if _jnp_chain(_attr_chain(anc.func)):
                        in_jnp = True
                        break
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    break
            if in_jnp and not sanitized:
                ok, empty = annotated(node)
                if ok:
                    continue
                yield _finding(
                    mod, node,
                    f"bare float literal {node.value!r} is not exactly "
                    f"representable in float32 but rides a jnp/lax call — "
                    f"its weak-f64 value changes under jax_enable_x64; "
                    f"wrap it in np.float32(...) or annotate "
                    f"`# smlint: dtype-ok[reason]`"
                    + (" (annotation reason is empty)" if empty else ""))
        # (b) float64 values flowing into jnp/lax calls (dataflow taint,
        # single-level call summaries)
        summaries = dataflow.summaries.get(mod)
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            taint = TaintTracker(source=_f64_source, summaries=summaries)
            for node in taint.walk(mod, fn):
                if not isinstance(node, ast.Call) or \
                        not _jnp_chain(_attr_chain(node.func)):
                    continue
                parts = list(node.args) + [kw.value for kw in node.keywords]
                mints_f64 = any(kw.arg == "dtype" and
                                _is_f64_dtype_expr(kw.value)
                                for kw in node.keywords)
                if not (mints_f64 or
                        any(taint.expr_tainted(p) for p in parts)):
                    continue
                ok, empty = annotated(node)
                if ok:
                    continue
                yield _finding(
                    mod, node,
                    f"a float64 value flows into {_attr_chain(node.func) or _call_name(node)}() "
                    f"in a declared-f32 jitting module — the implicit "
                    f"promotion silently changes the graph's precision; "
                    f"cast to the declared dtype first or annotate "
                    f"`# smlint: dtype-ok[reason]`"
                    + (" (annotation reason is empty)" if empty else ""))


# ======================================================= 12. masked-reduction
# PR 13's shape-bucket lattice pads pixel rows and resident peaks; any
# reduction over an axis carrying that padding that skips the n_real
# masked helpers (batch_metrics(n_real=) / ops/moments_pallas.batch_
# moments family) produces wrong-but-plausible metrics.  Taint enters a
# function through parameters the NUMERICS entry declares `padded=` and
# through ops/buckets padding-helper calls; raw reductions over tainted
# values fire unless annotated `# smlint: masked-ok[reason]` (the
# argument why THIS reduction is pad-invariant).
_MASKED_HELPERS = ("batch_metrics", "batch_moments", "batch_moments_jnp",
                   "batch_moments_pallas_masked")
_REDUCTION_METHODS = ("sum", "mean", "max", "min", "prod", "std", "var",
                      "dot")
_REDUCTION_FUNCS = _REDUCTION_METHODS + (
    "einsum", "tensordot", "segment_sum", "vdot", "inner", "matmul",
    "average", "nansum", "nanmean", "amax", "amin")
_BUCKET_PAD_HELPERS = ("row_bucket", "peak_bucket", "pixel_bucket",
                       "pow2ish", "batch_bucket_down")


def _bucket_pad_source(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and \
        _call_name(node) in _BUCKET_PAD_HELPERS


def _masked_helper_clears(call: ast.Call) -> bool:
    """A masked-helper call consuming the padded block TOGETHER with its
    real-element count launders the taint: batch_metrics/batch_moments*
    with an n_real keyword, or the masked Pallas kernel's positional
    (images, n_real) form."""
    callee = _call_name(call)
    if callee not in _MASKED_HELPERS:
        return False
    if any(kw.arg == "n_real" for kw in call.keywords):
        return True
    return callee == "batch_moments_pallas_masked" and len(call.args) >= 2


_MR_FIXTURE_FAIL = {
    "sm_distributed_tpu/ops/x_jax.py": (
        "import jax.numpy as jnp\n"
        "from ..analysis.numerics import numerics_surface\n"
        "NUMERICS = numerics_surface(__name__, {\n"
        "    'score': 'contract=bit_exact; test=tests/test_x.py::test_s; "
        "padded=images',\n"
        "})\n"
        "def score(images, n_real):\n"
        "    mean = images.mean(axis=-1)\n"
        "    return mean\n"
    ),
}
_MR_FIXTURE_PASS = {
    "sm_distributed_tpu/ops/x_jax.py": (
        "import jax.numpy as jnp\n"
        "from ..analysis.numerics import numerics_surface\n"
        "from ..ops.metrics_jax import batch_metrics\n"
        "NUMERICS = numerics_surface(__name__, {\n"
        "    'score': 'contract=bit_exact; test=tests/test_x.py::test_s; "
        "padded=images',\n"
        "})\n"
        "def score(images, theor, nv, n_real):\n"
        "    out = batch_metrics(images, theor, nv, 8, 8, n_real=n_real)\n"
        "    # smlint: masked-ok[zero pads are never positive; the count "
        "is exact]\n"
        "    npos = jnp.sum(images > 0, axis=-1)\n"
        "    return out, npos\n"
    ),
}


@rule("masked-reduction", severity="error",
      doc="In NUMERICS-declaring modules, reductions (sum/mean/max/dot/"
          "einsum/segment_sum/...) over values tainted by lattice "
          "padding — parameters the site's NUMERICS entry declares "
          "`padded=`, or locals derived from ops/buckets padding helpers "
          "(row_bucket/peak_bucket/pow2ish/...) — must flow through the "
          "n_real masked helpers (batch_metrics(n_real=), the "
          "batch_moments family) or carry a `# smlint: masked-ok[reason]` "
          "annotation arguing pad-invariance.  Taint is structural: a "
          "masked-helper call's RESULT is clean; everything else "
          "propagates.",
      fixture_fail=_MR_FIXTURE_FAIL, fixture_pass=_MR_FIXTURE_PASS)
def masked_reduction(project: Project):
    for mod in project.modules:
        if not mod.path.startswith("sm_distributed_tpu/"):
            continue
        decl, _ = _numerics_decl(mod)
        if not decl:
            continue
        padded: dict[str, set[str]] = {}
        for site, (policy, _ln) in decl.items():
            try:
                parsed = numerics_mod.parse_policy(policy)
            except ValueError:
                continue              # ulp-contract owns grammar findings
            if "padded" in parsed:
                padded[site] = {p.strip()
                                for p in parsed["padded"].split(",")}
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            taint = TaintTracker(source=_bucket_pad_source,
                                 call_clears=_masked_helper_clears,
                                 structural=True)
            params = {a.arg for a in (fn.args.posonlyargs + fn.args.args +
                                      fn.args.kwonlyargs)}
            taint.names |= padded.get(fn.name, set()) & params
            for node in taint.walk(mod, fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = _call_name(node)
                chain = _attr_chain(node.func)
                root = chain.split(".")[0]
                what = None
                if callee in _REDUCTION_FUNCS and (
                        root in ("jnp", "np", "numpy", "lax") or
                        chain.startswith("jax.")):
                    # function form: jnp.sum(x) / np.mean(x) / lax....
                    parts = list(node.args) + \
                        [kw.value for kw in node.keywords]
                    if any(taint.expr_tainted_rec(p) for p in parts):
                        what = f"{chain}()"
                elif isinstance(node.func, ast.Attribute) and \
                        callee in _REDUCTION_METHODS:
                    # method form: x.sum() / x.mean() on a tainted receiver
                    if taint.expr_tainted_rec(node.func.value):
                        what = f".{callee}()"
                if what is None:
                    continue
                reason = mod.annotation_reason("masked", node.lineno)
                if reason:
                    continue
                if reason == "":
                    yield _finding(
                        mod, node,
                        f"masked-ok annotation for {what} has an empty "
                        f"reason — the pad-invariance argument is the "
                        f"point")
                else:
                    yield _finding(
                        mod, node,
                        f"{what} reduces over a lattice-padded axis "
                        f"without the n_real masked helpers "
                        f"(batch_metrics(n_real=)/batch_moments) — pad "
                        f"slots silently join the reduction; route "
                        f"through a masked helper or annotate "
                        f"`# smlint: masked-ok[why pad-invariant]`")


# ========================================================== 13. ulp-contract
_UC_FIXTURE_FAIL = {
    "sm_distributed_tpu/ops/x_jax.py": (
        "from ..analysis.surface import compile_surface\n"
        "from ..analysis.numerics import numerics_surface\n"
        "COMPILE_SURFACE = compile_surface(__name__, {\n"
        "    'score': 'statics=none; buckets=single shape',\n"
        "    'other': 'statics=none; buckets=single shape',\n"
        "})\n"
        "NUMERICS = numerics_surface(__name__, {\n"
        "    'score': 'contract=ulp(4); test=tests/test_x.py::test_gone',\n"
        "    'ghost': 'contract=bit_exact; test=tests/test_x.py::test_a',\n"
        "})\n"
        "def score(x):\n"
        "    return x\n"
        "def other(x):\n"
        "    return x\n"
    ),
    "aux": {"tests/test_x.py": "def test_a():\n    pass\n"},
}
_UC_FIXTURE_PASS = {
    "sm_distributed_tpu/ops/x_jax.py": (
        "from ..analysis.surface import compile_surface\n"
        "from ..analysis.numerics import numerics_surface\n"
        "COMPILE_SURFACE = compile_surface(__name__, {\n"
        "    'score': 'statics=none; buckets=single shape',\n"
        "})\n"
        "NUMERICS = numerics_surface(__name__, {\n"
        "    'score': 'contract=bit_exact; test=tests/test_x.py::test_a',\n"
        "})\n"
        "def score(x):\n"
        "    return x\n"
    ),
    "aux": {"tests/test_x.py": "def test_a():\n    assert True\n"},
}


@rule("ulp-contract", severity="error",
      doc="Every COMPILE_SURFACE site must declare a numerics contract in "
          "the module's NUMERICS = numerics_surface(__name__, {...}) "
          "registry — `contract=bit_exact|ulp(N); test=<file>.py::<name>` "
          "— and every contract must be cross-referenced by a committed "
          "test that asserts it (the file must exist and define the "
          "test).  Dead NUMERICS entries (naming neither a surface site "
          "nor a function in the module), grammar violations, and "
          "padded= parameters that don't exist on the named function are "
          "findings too.",
      fixture_fail=_UC_FIXTURE_FAIL, fixture_pass=_UC_FIXTURE_PASS)
def ulp_contract(project: Project):
    for mod in project.modules:
        if not mod.path.startswith("sm_distributed_tpu/"):
            continue
        surface, surface_line = _surface_decl(mod)
        decl, decl_line = _numerics_decl(mod)
        if decl is None:
            if surface is not None:
                yield Finding(
                    "", "", mod.path, surface_line or 1,
                    f"module declares a COMPILE_SURFACE ({len(surface or {})} "
                    f"site(s)) but no NUMERICS = numerics_surface(__name__, "
                    f"{{...}}) registry — every compiled site needs a "
                    f"declared numerics contract (bit_exact or ulp(N)) "
                    f"before precision work can touch it",
                    anchor="NUMERICS")
            continue
        fns: dict[str, ast.AST] = {
            n.name: n for n in ast.walk(mod.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        # surface sites must carry contracts
        for site in sorted(surface or {}):
            if site not in decl:
                yield Finding(
                    "", "", mod.path, (surface or {})[site][1],
                    f"COMPILE_SURFACE site {site!r} has no NUMERICS "
                    f"contract — declare contract=bit_exact or ulp(N) "
                    f"with its proving test",
                    anchor=f"NUMERICS.{site}")
        for site, (policy, lineno) in sorted(decl.items()):
            try:
                parsed = numerics_mod.parse_policy(policy)
            except ValueError as exc:
                yield Finding(
                    "", "", mod.path, lineno,
                    f"NUMERICS entry {site!r}: {exc}",
                    anchor=f"NUMERICS.{site}")
                continue
            if site not in (surface or {}) and site not in fns:
                yield Finding(
                    "", "", mod.path, lineno,
                    f"NUMERICS entry {site!r} names neither a "
                    f"COMPILE_SURFACE site nor a function in this module "
                    f"(dead entry — remove it or fix the site name)",
                    anchor=f"NUMERICS.{site}")
                continue
            test_path, _, test_name = parsed["test"].partition("::")
            src = project.read(test_path)
            if src is None:
                tmod = project.module(test_path)
                src = tmod.source if tmod is not None else None
            if src is None:
                yield Finding(
                    "", "", mod.path, lineno,
                    f"NUMERICS entry {site!r}: contract test file "
                    f"{test_path!r} does not exist — a contract without "
                    f"its proving test is an unbacked promise",
                    anchor=f"NUMERICS.{site}.test")
            elif f"def {test_name}(" not in src:
                yield Finding(
                    "", "", mod.path, lineno,
                    f"NUMERICS entry {site!r}: {test_path!r} does not "
                    f"define {test_name!r} — the contract's "
                    f"cross-referenced test is gone",
                    anchor=f"NUMERICS.{site}.test")
            if "padded" in parsed:
                fn = fns.get(site)
                if fn is None:
                    yield Finding(
                        "", "", mod.path, lineno,
                        f"NUMERICS entry {site!r} declares padded= but "
                        f"names no function in this module the parameters "
                        f"could belong to",
                        anchor=f"NUMERICS.{site}.padded")
                else:
                    params = {a.arg for a in (
                        fn.args.posonlyargs + fn.args.args +
                        fn.args.kwonlyargs)}
                    for p in parsed["padded"].split(","):
                        if p.strip() not in params:
                            yield Finding(
                                "", "", mod.path, lineno,
                                f"NUMERICS entry {site!r}: padded "
                                f"parameter {p.strip()!r} is not a "
                                f"parameter of {site}()",
                                anchor=f"NUMERICS.{site}.padded")


def numerics_census(project: Project) -> dict[str, int]:
    """Static totals for the analysis drift sentinel: declared numerics
    contracts and the modules carrying a registry (scripts/smlint.py
    emits them as sm_numerics_* fields; rising counts diff across the
    ANALYSIS_r*.json history like any other surface growth)."""
    contracts = modules = 0
    for mod in project.modules:
        if not mod.path.startswith("sm_distributed_tpu/"):
            continue
        decl, _ = _numerics_decl(mod)
        if decl:
            modules += 1
            contracts += len(decl)
    return {"contracts": contracts, "modules": modules}
