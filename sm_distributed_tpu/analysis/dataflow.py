"""Forward-dataflow / taint engine over the parsed Project AST (ISSUE 15).

The ad-hoc taint walks that grew inside individual rules — ``fence-gate``'s
terminal-dir path locals, ``retrace-hazard``'s raw-shape locals — share one
shape: walk a function's own nodes in ``ast.walk`` order, grow a set of
tainted single-target locals as assignments stream past, and let sink
checks consult the set mid-walk.  This module is that shape, factored out
so new rules (``dtype-flow``, ``masked-reduction``) get dataflow for the
price of a seed predicate instead of another bespoke walker:

- :func:`function_nodes` — the nodes belonging to one function itself
  (nested defs/lambdas excluded), in the same breadth-first order the
  original rules used.  Sink checks interleaved with taint growth keep
  the legacy semantics exactly: a sink that appears before its taint
  assignment in walk order stays unflagged, which is what the refactored
  rules' snapshot-parity test (tests/test_dataflow.py) pins;
- :class:`TaintTracker` — the per-function taint state: a *source*
  predicate over AST nodes, an optional *sanitizer* that clears a whole
  expression (the retrace rule's "any bucketing call kills the expr"
  semantics), flat (`expr_tainted`) and structural (`expr_tainted_rec`)
  queries, and assignment observation (single-target names; tuple
  targets in structural mode);
- :func:`def_use` — per-function def-use chains over single-target
  locals (the inspection surface tests/test_dataflow.py exercises, and
  the base of the call summaries);
- :func:`module_summaries` / :class:`SummaryCache` — SINGLE-LEVEL call
  summaries: for each function defined in a module, which parameters
  flow into its return value (through single-target locals).  A tracker
  given summaries lets taint cross exactly one call boundary —
  ``helper(x)`` is tainted when ``x`` is tainted and ``helper`` returns
  a param-derived value.  Summaries of summaries are deliberately NOT
  taken: the engine stays intra-procedural with one-level summaries, as
  the rule catalog documents.

The summary cache is process-shared mutable state (smlint, its
``--self-check`` fixture replays, and the in-process test harness all
lint concurrently-parsed projects), so it carries a ``_GUARDED_BY``
registry and a leaf lock like every other shared structure in the tree.
"""

from __future__ import annotations

import ast
import threading
from dataclasses import dataclass, field


def function_nodes(mod, fn):
    """Yield the nodes that belong to ``fn`` ITSELF — the function node,
    then its body in ``ast.walk`` (breadth-first) order — skipping
    anything owned by a nested def/lambda.  This is the shared walk
    every dataflow-backed rule iterates."""
    for node in ast.walk(fn):
        if mod.enclosing_function(node) is not fn and node is not fn:
            continue
        yield node


# ------------------------------------------------------------------- taint
class TaintTracker:
    """Forward taint over one function's locals.

    ``source(node) -> bool`` marks primitive taint origins (a raw
    ``.shape`` read, a terminal-dir string constant, an ``np.float64``
    call).  ``sanitizer(node) -> bool`` marks calls that launder a whole
    expression — in flat mode, ONE sanitizer anywhere in an expression
    clears it entirely (the legacy ``retrace-hazard`` contract).

    ``summaries`` ({fn name: (param names, flowing-param set)}) lets the
    structural query cross one call level; ``call_clears(call) -> bool``
    marks calls whose RESULT is clean regardless of arguments (e.g. the
    masked-metrics helpers consuming a padded block together with its
    real-pixel count)."""

    def __init__(self, source=None, sanitizer=None, summaries=None,
                 call_clears=None, structural: bool = False):
        self.source = source
        self.sanitizer = sanitizer
        self.summaries = summaries or {}
        self.call_clears = call_clears
        self.structural = structural
        self.names: set[str] = set()

    # ------------------------------------------------------------- queries
    def expr_tainted(self, expr: ast.AST) -> bool:
        """Flat query, legacy parity: a sanitizer anywhere in ``expr``
        clears it; otherwise any source node or tainted-name load taints
        the whole expression."""
        if self.sanitizer is not None and any(
                self.sanitizer(n) for n in ast.walk(expr)):
            return False
        for n in ast.walk(expr):
            if self.source is not None and self.source(n):
                return True
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) and \
                    n.id in self.names:
                return True
            if self._summary_call_tainted(n):
                return True
        return False

    def expr_tainted_rec(self, expr: ast.AST) -> bool:
        """Structural query: calls are evaluated as calls — a clearing
        call's result is clean even when its arguments are tainted, and
        summaries decide whether taint passes through a known callee."""
        if self.sanitizer is not None and self.sanitizer(expr):
            return False
        if isinstance(expr, ast.Call):
            if self.call_clears is not None and self.call_clears(expr):
                return False
            if self.source is not None and self.source(expr):
                return True           # e.g. a padding-helper call IS taint
            callee = expr.func.id if isinstance(expr.func, ast.Name) else (
                expr.func.attr if isinstance(expr.func, ast.Attribute)
                else "")
            if callee in self.summaries:
                # a summarized callee is AUTHORITATIVE: taint passes only
                # through parameters that flow to its return value
                return self._summary_call_tainted(expr)
            parts = list(expr.args) + [kw.value for kw in expr.keywords]
            if isinstance(expr.func, ast.Attribute):
                parts.append(expr.func.value)   # method receiver
            return any(self.expr_tainted_rec(p) for p in parts)
        if isinstance(expr, ast.Name):
            return isinstance(expr.ctx, ast.Load) and expr.id in self.names
        if self.source is not None and self.source(expr):
            return True
        return any(self.expr_tainted_rec(c)
                   for c in ast.iter_child_nodes(expr))

    def _summary_call_tainted(self, node: ast.AST) -> bool:
        """A call through a summarized function is tainted iff an
        argument bound to a return-flowing parameter is tainted."""
        if not (isinstance(node, ast.Call) and self.summaries):
            return False
        callee = node.func.id if isinstance(node.func, ast.Name) else (
            node.func.attr if isinstance(node.func, ast.Attribute) else "")
        summary = self.summaries.get(callee)
        if summary is None:
            return False
        params, flowing = summary
        check = self.expr_tainted_rec if self.structural else \
            self.expr_tainted
        for i, a in enumerate(node.args):
            if i < len(params) and params[i] in flowing and check(a):
                return True
        for kw in node.keywords:
            if kw.arg in flowing and check(kw.value):
                return True
        return False

    # ----------------------------------------------------------- mutation
    def observe(self, node: ast.AST) -> None:
        """Grow the taint set from one statement: single-target name
        assignments always; tuple-unpack targets in structural mode (a
        tainted call result taints every unpacked name)."""
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            return
        t = node.targets[0]
        check = self.expr_tainted_rec if self.structural else \
            self.expr_tainted
        if isinstance(t, ast.Name):
            if check(node.value):
                self.names.add(t.id)
        elif self.structural and isinstance(t, ast.Tuple):
            if check(node.value):
                for el in t.elts:
                    if isinstance(el, ast.Name):
                        self.names.add(el.id)

    def walk(self, mod, fn):
        """Observe-then-yield every node of ``fn``: the rule's sink
        checks run against exactly the taint state the legacy in-line
        walks maintained."""
        for node in function_nodes(mod, fn):
            self.observe(node)
            yield node


# --------------------------------------------------------------- def-use
@dataclass
class DefUse:
    """Per-function def-use chains over single-target local names."""

    defs: dict[str, list[ast.Assign]] = field(default_factory=dict)
    uses: dict[str, list[ast.Name]] = field(default_factory=dict)

    def chain(self, name: str) -> tuple[list[ast.Assign], list[ast.Name]]:
        return self.defs.get(name, []), self.uses.get(name, [])


def def_use(mod, fn) -> DefUse:
    """Def-use chains for ``fn``: definitions are single-target name
    assignments (the only binding form the taint engine propagates
    through), uses are name LOADS."""
    du = DefUse()
    for node in function_nodes(mod, fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            du.defs.setdefault(node.targets[0].id, []).append(node)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            du.uses.setdefault(node.id, []).append(node)
    return du


# --------------------------------------------------- single-level summaries
def _fn_summary(mod, fn) -> tuple[tuple[str, ...], frozenset[str]]:
    """(parameter names, subset that flows to a return value) — flow is
    through single-target locals, one forward pass in walk order."""
    params = tuple(a.arg for a in (
        fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs))
    reaching: dict[str, set[str]] = {p: {p} for p in params}
    flowing: set[str] = set()

    def roots(expr: ast.AST) -> set[str]:
        out: set[str] = set()
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                out |= reaching.get(n.id, set())
        return out

    for node in function_nodes(mod, fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            reaching[node.targets[0].id] = roots(node.value)
        elif isinstance(node, ast.Return) and node.value is not None:
            flowing |= roots(node.value)
    return params, frozenset(flowing & set(params))


def module_summaries(mod) -> dict[str, tuple[tuple[str, ...], frozenset]]:
    """{function name: (params, return-flowing params)} for every def in
    ``mod`` — the SINGLE call level a tracker may cross.  Later
    definitions of a reused name win (matching runtime shadowing)."""
    out: dict[str, tuple] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = _fn_summary(mod, node)
    return out


class SummaryCache:
    """Bounded process-wide memo of per-module call summaries (smlint
    re-lints the same parsed modules across rules and fixture replays).
    Keyed on (path, source hash) so a re-parsed module with edited
    source never serves a stale summary."""

    _GUARDED_BY = {"_cache": "_lock"}
    _MAX = 512

    def __init__(self):
        self._lock = threading.Lock()
        self._cache: dict[tuple, dict] = {}

    def get(self, mod) -> dict[str, tuple]:
        key = (mod.path, hash(mod.source))
        with self._lock:
            hit = self._cache.get(key)
        if hit is not None:
            return hit
        val = module_summaries(mod)
        with self._lock:
            self._cache[key] = val
            while len(self._cache) > self._MAX:
                self._cache.pop(next(iter(self._cache)))
        return val

    def clear(self) -> None:
        with self._lock:
            self._cache.clear()


summaries = SummaryCache()
