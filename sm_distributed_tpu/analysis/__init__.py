"""Project-invariant static analysis (smlint) + runtime lock-order detection.

ISSUE 9 tentpole.  PRs 1-8 accumulated cross-cutting invariants that were
enforced only by reviewer memory: every spool/ledger write seam fenced,
every failpoint documented and chaos-covered, every metric ``sm_``-prefixed
and documented, every SMConfig knob mirrored into the template and docs,
every shared attribute mutated under its declared lock, no exception
swallowed silently.  The reference SM_distributed engine had exactly this
failure mode — convention-only consistency between its Spark pipeline and
its Postgres/ES bookkeeping — and multi-replica scale-out multiplies the
cost of a miss: an unfenced write becomes a cross-replica double-commit, a
lock-order cycle a fleet-wide deadlock.

Six parts:

- ``core`` + ``rules`` — a stdlib-``ast`` lint framework (rule registry,
  per-rule severity, committed suppression baseline, per-rule firing
  fixtures) behind the ``scripts/smlint.py`` CLI.  Docs: docs/ANALYSIS.md.
- ``dataflow`` — the shared forward-dataflow/taint engine (ISSUE 15):
  per-function walks, source/sanitizer taint tracking, def-use chains,
  single-level call summaries; ``fence-gate``, ``retrace-hazard``,
  ``dtype-flow`` and ``masked-reduction`` all ride it.
- ``numerics`` — the declarative ``NUMERICS`` contract registry
  (``contract=bit_exact|ulp(N)`` + proving test + padded operands) and
  the float32 ULP measurement helpers behind
  ``scripts/ulp_sentinel.py``'s committed-drift gate.
- ``lockorder`` — opt-in runtime instrumentation of ``threading.Lock`` /
  ``RLock`` / ``Condition`` ("tsan-lite") that records the lock
  acquisition-order graph across scheduler / device-pool / admission /
  metrics / telemetry threads and reports cycles, wired into the chaos and
  load sweeps.
- ``surface`` — the declarative ``COMPILE_SURFACE`` registry (ISSUE 12):
  every module that jits/``shard_map``s declares each call site's statics
  and shape-bucket policy; the ``jit-compile-surface`` rule cross-checks
  the declarations against the AST.
- ``retrace`` — the runtime half: a ``jax.monitoring`` hook attributing
  every XLA compilation to its call site + abstract signature
  (``sm_compile_*`` metrics, ``compile`` trace events), proven closed by
  ``scripts/compile_census.py``.
"""

from .core import (  # noqa: F401
    Finding,
    Project,
    Rule,
    RULES,
    load_baseline,
    run_lint,
    rule,
)
