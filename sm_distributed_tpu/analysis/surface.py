"""COMPILE_SURFACE: the declared compile surface of the engine (ISSUE 12).

ROADMAP item 1 (cold-start annihilation) only holds if every dataset size
hits a small CLOSED set of compiled signatures — and nothing proved,
statically or at runtime, that a code path can't mint an unbounded family
of ``jax.jit`` signatures (r4 measured 81–308 s cold compiles at scale).
This module is the declarative half of that proof:

- every module that creates jitted/``shard_map``-ped executables declares
  a module-level ``COMPILE_SURFACE = compile_surface(__name__, {...})``
  mapping each **site name** (the wrapped function's name — see the
  ``jit-compile-surface`` rule in ``rules.py`` for the resolution order)
  to a **policy string** in the annotation grammar::

      "statics=<n1,n2,...>|none|closure(<names>); buckets=<how the static
       shapes are bounded>"

  e.g. ``"statics=gc_width,b,k; buckets=sticky gc_width + formula_batch
  ladder (b in {batch, 256})"``.  The ``buckets=`` clause names the
  shape-bucketing policy that keeps the signature family finite — the
  thing a reviewer must argue when adding a call site (the same move as
  GSPMD treating sharding annotations as statically checkable program
  properties, arXiv:2105.04663);

- the ``jit-compile-surface`` smlint rule statically cross-checks the
  registry against the actual call sites (missing/dead entries, statics
  drift) so the declaration cannot rot;

- the runtime retrace tracer (``retrace.py``) and the census gate
  (``scripts/compile_census.py``) check the OBSERVED compile surface —
  every XLA compilation attributed to a call site in a registered module,
  and the signature set closed under repeated same-shaped traffic.

The registry is import-time write-once state: modules register as they
are imported, readers only iterate.  One leaf lock guards the map (the
census reads while scheduler worker threads may still be importing
backends lazily).
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
_SURFACES: dict[str, dict[str, str]] = {}

# tokens every policy string must carry (the jit-compile-surface rule
# enforces the same grammar statically; keep them in lockstep)
POLICY_TOKENS = ("statics=", "buckets=")


def compile_surface(module: str, entries: dict[str, str]) -> dict[str, str]:
    """Declare ``module``'s compile surface and return ``entries`` (so the
    declaration doubles as the module-level ``COMPILE_SURFACE`` constant).

    ``entries`` maps site name -> policy string; malformed policies raise
    at import time — a bad declaration must not wait for the lint run."""
    for site, policy in entries.items():
        if not isinstance(policy, str) or not all(
                t in policy for t in POLICY_TOKENS):
            raise ValueError(
                f"compile_surface({module!r}): entry {site!r} must be a "
                f"policy string carrying {' and '.join(POLICY_TOKENS)} "
                f"clauses, got {policy!r}")
    with _lock:
        _SURFACES[module] = dict(entries)
    return dict(entries)


def registered() -> dict[str, dict[str, str]]:
    """{module name: {site: policy}} of every imported declaration."""
    with _lock:
        return {m: dict(e) for m, e in _SURFACES.items()}


def module_for_path(rel_path: str) -> str:
    """``sm_distributed_tpu/models/msm_jax.py`` -> the module name its
    ``compile_surface(__name__, ...)`` call registered under."""
    p = rel_path[:-3] if rel_path.endswith(".py") else rel_path
    return p.replace("/", ".")


def is_registered_path(rel_path: str) -> bool:
    return module_for_path(rel_path) in registered()
