"""smlint framework: modules, rule registry, suppressions, baseline.

Design (docs/ANALYSIS.md):

- a **Project** parses every target file once (stdlib ``ast`` with parent
  links) and abstracts doc/template reads, so rules stay pure functions
  ``rule(project) -> [Finding]`` and tests can lint synthetic in-memory
  projects (each rule ships a firing fixture and a passing fixture);
- **Findings** carry a *stable anchor* — the enclosing ``Class.method``
  qualname where one exists, else the stripped source line — so the
  committed baseline survives unrelated line drift;
- **suppressions** come from two places: inline
  ``# smlint: ignore[rule-name]`` on the finding line (or the line above),
  and the committed baseline file (``conf/smlint_baseline.json``), whose
  entries match on ``(rule, path, anchor)`` and MUST each carry a
  ``justification``.  ``--self-check`` fails on any suppression that
  matches zero findings (a minimal baseline is the point: dead entries are
  how baselines rot into allow-everything lists) and re-proves every
  rule's firing fixture.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path

SEVERITIES = ("error", "warning")

_IGNORE_RE = re.compile(r"#\s*smlint:\s*ignore\[([a-z0-9_,\- ]+)\]")
# reasoned `-ok` annotations: `# smlint: <kind>-ok[reason]` marks a
# deliberate instance of a flagged pattern — a device->host sync (ISSUE
# 12), a dtype escape or a pad-axis reduction (ISSUE 15).  The REASON is
# mandatory in every case — the annotation is an argument, not a mute
# button — and each rule treats an empty reason as a finding.
_ANNOT_RES: dict[str, re.Pattern] = {}


def _annot_re(kind: str) -> re.Pattern:
    if kind not in _ANNOT_RES:
        _ANNOT_RES[kind] = re.compile(
            r"#\s*smlint:\s*" + re.escape(kind) + r"-ok\[([^\]]*)\]")
    return _ANNOT_RES[kind]


# ------------------------------------------------------------------ findings
@dataclass
class Finding:
    """One rule violation at one source location."""

    rule: str
    severity: str
    path: str                 # repo-relative, POSIX separators
    line: int
    message: str
    anchor: str = ""          # enclosing qualname (or source line) — the
                              # stable key baseline suppressions match on

    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.anchor)

    def render(self) -> str:
        sev = "" if self.severity == "error" else " (warning)"
        return f"{self.path}:{self.line}: [{self.rule}]{sev} {self.message}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "path": self.path, "line": self.line,
                "message": self.message, "anchor": self.anchor}


# ------------------------------------------------------------------- modules
class Module:
    """One parsed source file: tree with parent/qualname maps precomputed."""

    def __init__(self, path: str, source: str):
        self.path = path                      # repo-relative
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def qualname(self, node: ast.AST) -> str:
        """``Class.method`` path of the scopes enclosing ``node`` ("" at
        module level)."""
        parts: list[str] = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(parts))

    def anchor(self, node: ast.AST) -> str:
        q = self.qualname(node)
        return q or self.line_text(getattr(node, "lineno", 0))

    def enclosing_function(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    def ancestors(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def ignored_rules(self, lineno: int) -> set[str]:
        """Inline suppressions on the line or the line above."""
        out: set[str] = set()
        for ln in (lineno, lineno - 1):
            m = _IGNORE_RE.search(self.line_text(ln))
            if m:
                out |= {r.strip() for r in m.group(1).split(",") if r.strip()}
        return out

    def annotation_reason(self, kind: str, lineno: int) -> str | None:
        """The ``# smlint: <kind>-ok[reason]`` annotation on the line or
        the line above — None when unannotated, "" when the reason is
        empty (rules treat an empty reason as a violation too)."""
        pat = _annot_re(kind)
        for ln in (lineno, lineno - 1):
            m = pat.search(self.line_text(ln))
            if m:
                return m.group(1).strip()
        return None

    def host_sync_reason(self, lineno: int) -> str | None:
        return self.annotation_reason("host-sync", lineno)


class Project:
    """The lint target: parsed modules + doc/template accessors.

    ``aux`` overrides file reads for synthetic fixture projects (rule
    tests inject their own docs/RECOVERY.md or config template content
    without touching disk)."""

    def __init__(self, root: str | Path | None = None,
                 modules: dict[str, str] | None = None,
                 aux: dict[str, str] | None = None):
        self.root = Path(root) if root is not None else None
        self.aux = dict(aux or {})
        self.modules: list[Module] = []
        self.errors: list[Finding] = []
        for path, source in (modules or {}).items():
            self._add(path, source)

    # ---------------------------------------------------------------- build
    @staticmethod
    def load(root: str | Path, paths: list[str | Path]) -> "Project":
        root = Path(root).resolve()
        proj = Project(root)
        seen: set[str] = set()
        for target in paths:
            t = (root / target).resolve() if not Path(target).is_absolute() \
                else Path(target)
            files = sorted(t.rglob("*.py")) if t.is_dir() else [t]
            for f in files:
                if "__pycache__" in f.parts:
                    continue
                rel = f.relative_to(root).as_posix()
                if rel in seen:
                    continue
                seen.add(rel)
                proj._add(rel, f.read_text())
        return proj

    def _add(self, path: str, source: str) -> None:
        try:
            self.modules.append(Module(path, source))
        except SyntaxError as exc:
            self.errors.append(Finding(
                "parse-error", "error", path, exc.lineno or 0,
                f"cannot parse: {exc.msg}", anchor="parse"))

    # ------------------------------------------------------------ accessors
    def module(self, suffix: str) -> Module | None:
        for m in self.modules:
            if m.path.endswith(suffix):
                return m
        return None

    def read(self, rel_path: str) -> str | None:
        """Aux-file contents (docs, templates): fixture override first,
        then the real file under the project root."""
        if rel_path in self.aux:
            return self.aux[rel_path]
        if self.root is not None:
            p = self.root / rel_path
            if p.exists():
                return p.read_text()
        return None

    def doc_text(self, *rel_paths: str) -> str:
        return "\n".join(self.read(p) or "" for p in rel_paths)


# --------------------------------------------------------------------- rules
@dataclass
class Rule:
    """A registered rule: pure function + severity + firing/passing
    fixtures (the fixtures double as the ``--self-check`` proof that the
    rule can actually fire)."""

    name: str
    severity: str
    doc: str
    fn: object = field(repr=False, default=None)
    # {path: source} module fixtures (+ optional "aux" dict entry routed to
    # Project.aux) that must produce >=1 finding / exactly 0 findings
    fixture_fail: dict = field(repr=False, default_factory=dict)
    fixture_pass: dict = field(repr=False, default_factory=dict)

    def run(self, project: Project) -> list[Finding]:
        out = []
        for f in self.fn(project):
            f.rule = self.name
            f.severity = self.severity
            out.append(f)
        return out

    def run_fixture(self, fixture: dict) -> list[Finding]:
        fx = dict(fixture)
        aux = fx.pop("aux", {})
        return self.run(Project(modules=fx, aux=aux))


RULES: dict[str, Rule] = {}


def rule(name: str, severity: str = "error", doc: str = "",
         fixture_fail: dict | None = None, fixture_pass: dict | None = None):
    """Register a rule.  ``fn(project) -> iterable[Finding]`` — the
    decorator stamps rule name/severity onto each finding."""
    if severity not in SEVERITIES:
        raise ValueError(f"rule {name}: bad severity {severity!r}")

    def deco(fn):
        if name in RULES:
            raise ValueError(f"duplicate rule name {name!r}")
        RULES[name] = Rule(name=name, severity=severity,
                           doc=doc or (fn.__doc__ or "").strip(), fn=fn,
                           fixture_fail=fixture_fail or {},
                           fixture_pass=fixture_pass or {})
        return fn

    return deco


# ------------------------------------------------------------------ baseline
def load_baseline(path: str | Path | None) -> list[dict]:
    """Committed suppressions: ``[{rule, path, anchor, justification}]``.
    Entries without a justification are rejected — the baseline is a list
    of *argued* exemptions, not a mute button."""
    if path is None or not Path(path).exists():
        return []
    data = json.loads(Path(path).read_text())
    entries = data.get("suppressions", []) if isinstance(data, dict) else data
    out = []
    for i, e in enumerate(entries):
        if not isinstance(e, dict) or not all(
                isinstance(e.get(k), str) and e.get(k)
                for k in ("rule", "path", "anchor", "justification")):
            raise ValueError(
                f"baseline entry #{i} must be an object with non-empty "
                f"rule/path/anchor/justification: {e!r}")
        out.append(e)
    return out


@dataclass
class LintResult:
    findings: list[Finding]               # all, before baseline filtering
    new: list[Finding]                    # not matched by the baseline
    suppressed: list[Finding]             # matched by the baseline
    unused_suppressions: list[dict]       # baseline entries matching nothing

    def counts(self, which: str = "all") -> dict[str, int]:
        """Per-rule finding counts — the ``sm_analysis_findings_total``
        summary ``scripts/smlint.py --json`` emits."""
        src = {"all": self.findings, "new": self.new,
               "suppressed": self.suppressed}[which]
        out: dict[str, int] = {}
        for f in src:
            out[f.rule] = out.get(f.rule, 0) + 1
        return dict(sorted(out.items()))


def run_lint(project: Project, baseline: list[dict] | None = None,
             only: set[str] | None = None) -> LintResult:
    """Run every registered rule (importing ``rules`` registers the
    shipped set), apply inline + baseline suppressions."""
    from . import rules as _rules  # noqa: F401 — registration side effect

    findings = list(project.errors)
    for r in RULES.values():
        if only is not None and r.name not in only:
            continue
        findings.extend(r.run(project))
    # inline suppressions
    by_path = {m.path: m for m in project.modules}
    kept = []
    for f in findings:
        mod = by_path.get(f.path)
        if mod is not None and f.rule in mod.ignored_rules(f.line):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    # baseline suppressions
    baseline = baseline or []
    used = [False] * len(baseline)
    new, suppressed = [], []
    for f in kept:
        hit = None
        for i, e in enumerate(baseline):
            if (e["rule"], e["path"], e["anchor"]) == f.key():
                hit = i
                break
        if hit is None:
            new.append(f)
        else:
            used[hit] = True
            suppressed.append(f)
    unused = [e for i, e in enumerate(baseline) if not used[i]]
    return LintResult(findings=kept, new=new, suppressed=suppressed,
                      unused_suppressions=unused)


def self_check(project: Project, baseline: list[dict]) -> list[str]:
    """``--self-check``: (1) the committed baseline is minimal — every
    suppression matches >=1 current finding; (2) every rule's firing
    fixture still fires and its passing fixture stays clean — a rule that
    can no longer fire is a rule that silently stopped guarding."""
    from . import rules as _rules  # noqa: F401

    errs = []
    result = run_lint(project, baseline)
    for e in result.unused_suppressions:
        errs.append(
            f"baseline suppression matches zero findings (stale — remove "
            f"it): {e['rule']} @ {e['path']} :: {e['anchor']}")
    for r in RULES.values():
        if r.fixture_fail:
            if not r.run_fixture(r.fixture_fail):
                errs.append(f"rule {r.name}: firing fixture produced no "
                            f"findings — the rule cannot fire")
        if r.fixture_pass:
            got = r.run_fixture(r.fixture_pass)
            if got:
                errs.append(f"rule {r.name}: passing fixture produced "
                            f"findings: {[f.render() for f in got]}")
    return errs
