"""Runtime lock-order detection ("tsan-lite") — ISSUE 9 tentpole, half 2.

The static ``guarded-by`` rule proves mutations happen under the right
lock; it cannot prove locks are taken in a consistent ORDER.  With the
scheduler dispatcher, worker pool, watchdog, replica loop, telemetry
sampler, HTTP handlers, and device-pool waiters all taking locks, one
inverted pair (thread A: records-lock → pool-cond, thread B: pool-cond →
records-lock) is a fleet-wide deadlock that no amount of single-thread
testing finds.

``enable()`` monkeypatches ``threading.Lock`` / ``RLock`` /
``Condition`` with instrumented factories.  Each lock created by code in
*scope* (filename substring match on the allocation site — third-party
and interpreter-internal locks stay untouched raw primitives) is wrapped;
every acquire records, per thread, the edge ``site(already-held lock) →
site(acquiring lock)`` into a process-global graph **at acquire-intent
time** (before blocking — so a cycle is reported even when the schedule
would really deadlock).  A cycle in the site graph is a potential
deadlock regardless of whether this run interleaved badly: that is the
whole value over testing.

Semantics and deliberate approximations:

- lock identity is the ALLOCATION SITE (``file:line``), so two instances
  of the same class alias to one node.  Same-site nesting (A1 held while
  acquiring A2 created at the same line) is recorded separately in
  ``same_site`` and excluded from cycles — per-instance nesting is
  usually address-ordered by construction and site aliasing would make
  every such pattern a false self-loop;
- RLock re-entry by the owning thread records no edge (it cannot block);
- ``Condition.wait`` releases the underlying lock: the wrapper forwards
  ``_release_save`` / ``_acquire_restore`` / ``_is_owned`` with held-set
  bookkeeping, so the wait window neither leaks a phantom hold nor loses
  the re-acquire edge;
- edges are recorded for timed/non-blocking acquires too (intent is what
  orders, not success).

Modes: ``record`` (default) accumulates the graph — sweeps call
``assert_no_cycles()`` at the end; ``raise`` throws ``LockOrderError``
in the acquiring thread the moment a new edge closes a cycle (the chaos
harness runs children this way via ``SM_LOCK_ORDER=raise``, where a
mid-job exception surfaces as a failed scenario).

Wired in: ``scripts/load_sweep.py`` (every mix), ``scripts/
multichip_smoke.py``, and ``scripts/chaos_sweep.py`` (driver + consumer
children).  Locks created BEFORE ``enable()`` (module-level locks of
already-imported modules) are not instrumented — the sweeps enable first,
and the interesting graph (scheduler/pool/admission/metrics/telemetry
instance locks) is created per-service anyway.
"""

from __future__ import annotations

import os
import sys
import threading

_SM_ROOT = "sm_distributed_tpu"
DEFAULT_SCOPE = (_SM_ROOT, "scripts/")

_real_lock = threading.Lock
_real_rlock = threading.RLock
_real_condition = threading.Condition


class LockOrderError(RuntimeError):
    """A lock acquisition-order cycle (potential deadlock) was detected."""


class _Detector:
    def __init__(self, scope: tuple[str, ...], mode: str):
        self.scope = tuple(scope)
        self.mode = mode
        # site graph: (from_site, to_site) -> witness
        self.edges: dict[tuple[str, str], dict] = {}
        self.same_site: dict[str, int] = {}
        self.locks_created = 0
        self._mu = _real_lock()       # raw primitive: never instrumented
        self._tls = threading.local()

    # ------------------------------------------------------------ held set
    def _held(self) -> list:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    def note_intent(self, tracked) -> None:
        """Record ordering edges BEFORE blocking on ``tracked``."""
        held = self._held()
        new_cycle = None
        with self._mu:
            for h in held:
                if h is tracked:
                    return            # re-entry handled by caller
                if h.site == tracked.site:
                    self.same_site[h.site] = \
                        self.same_site.get(h.site, 0) + 1
                    continue
                edge = (h.site, tracked.site)
                if edge not in self.edges:
                    self.edges[edge] = {
                        "thread": threading.current_thread().name,
                        "held": h.label, "acquiring": tracked.label,
                    }
                    cyc = self._find_cycle_locked(tracked.site, h.site)
                    if cyc is not None:
                        new_cycle = cyc + [tracked.site]
        if new_cycle is not None and self.mode == "raise":
            raise LockOrderError(
                "lock-order cycle (potential deadlock): "
                + " -> ".join(new_cycle)
                + f" [thread {threading.current_thread().name}]")

    def note_acquired(self, tracked) -> None:
        self._held().append(tracked)

    def note_released(self, tracked) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is tracked:
                del held[i]
                return

    # --------------------------------------------------------------- graph
    def _adj_locked(self) -> dict[str, list[str]]:
        adj: dict[str, list[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, []).append(b)
        return adj

    def _find_cycle_locked(self, start: str, goal: str) -> list | None:
        """Path start -> ... -> goal in the edge graph (the new edge
        goal -> start then closes the cycle)."""
        adj = self._adj_locked()
        stack = [(start, [start])]
        seen = set()
        while stack:
            node, path = stack.pop()
            if node == goal:
                return path
            if node in seen:
                continue
            seen.add(node)
            for nxt in adj.get(node, ()):
                stack.append((nxt, path + [nxt]))
        return None

    def cycles(self) -> list[list[str]]:
        """Elementary cycles in the recorded site graph (self-loops are
        tracked in ``same_site`` and never enter the edge set)."""
        with self._mu:
            adj = self._adj_locked()
        out, seen_keys = [], set()
        for root in sorted(adj):
            stack = [(root, [root])]
            while stack:
                node, path = stack.pop()
                for nxt in adj.get(node, ()):
                    if nxt == root:
                        key = frozenset(path)
                        if key not in seen_keys:
                            seen_keys.add(key)
                            out.append(path + [root])
                    elif nxt not in path and nxt > root:
                        # only walk nodes > root so each cycle is found
                        # once, from its smallest node
                        stack.append((nxt, path + [nxt]))
        return out

    def report(self) -> dict:
        with self._mu:
            n_edges = len(self.edges)
            same = dict(self.same_site)
            created = self.locks_created
        return {"mode": self.mode, "locks_instrumented": created,
                "edges": n_edges, "cycles": self.cycles(),
                "same_site_nesting": same}


_detector: _Detector | None = None


# ------------------------------------------------------------ lock wrappers
class _TrackedBase:
    def __init__(self, inner, site: str, label: str):
        self._inner = inner
        self.site = site
        self.label = label

    def locked(self):
        return self._inner.locked()

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<tracked {self.label} wrapping {self._inner!r}>"


class TrackedLock(_TrackedBase):
    """Instrumented ``threading.Lock``."""

    def acquire(self, blocking=True, timeout=-1):
        det = _detector
        if det is not None:
            det.note_intent(self)
        ok = self._inner.acquire(blocking, timeout)
        if ok and det is not None:
            det.note_acquired(self)
        return ok

    def release(self):
        self._inner.release()
        det = _detector
        if det is not None:
            det.note_released(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class TrackedRLock(_TrackedBase):
    """Instrumented ``threading.RLock`` (Condition-compatible)."""

    def __init__(self, inner, site, label):
        super().__init__(inner, site, label)
        self._depth = threading.local()

    def _d(self) -> int:
        return getattr(self._depth, "n", 0)

    def acquire(self, blocking=True, timeout=-1):
        det = _detector
        first = self._d() == 0
        if first and det is not None:
            det.note_intent(self)     # re-entry cannot block: no edge
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._depth.n = self._d() + 1
            if first and det is not None:
                det.note_acquired(self)
        return ok

    def release(self):
        self._inner.release()
        self._depth.n = max(0, self._d() - 1)
        if self._d() == 0:
            det = _detector
            if det is not None:
                det.note_released(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # Condition support: wait() fully releases the lock via _release_save
    # and re-takes it via _acquire_restore — mirror that in the held set
    def _release_save(self):
        state = self._inner._release_save()
        det = _detector
        if det is not None:
            det.note_released(self)
        saved_depth = self._d()
        self._depth.n = 0
        return (state, saved_depth)

    def _acquire_restore(self, saved):
        state, depth = saved
        det = _detector
        if det is not None:
            det.note_intent(self)
        self._inner._acquire_restore(state)
        self._depth.n = depth
        if det is not None:
            det.note_acquired(self)

    def _is_owned(self):
        return self._inner._is_owned()

    def locked(self):
        # RLock has no locked() before 3.12; Condition never calls it
        fn = getattr(self._inner, "locked", None)
        return fn() if fn is not None else self._d() > 0


# ----------------------------------------------------------------- factories
def _caller_site() -> tuple[str, int] | None:
    """First stack frame outside this module — the allocation site."""
    f = sys._getframe(2)
    here = __file__
    while f is not None and f.f_code.co_filename == here:
        f = f.f_back
    if f is None:
        return None
    return f.f_code.co_filename, f.f_lineno


def _site_label(filename: str, lineno: int) -> str:
    name = filename.replace("\\", "/")
    for marker in (_SM_ROOT, "scripts/", "tests/"):
        i = name.rfind("/" + marker)
        if i >= 0:
            name = name[i + 1:]
            break
    return f"{name}:{lineno}"


def _in_scope(filename: str) -> bool:
    det = _detector
    if det is None:
        return False
    name = filename.replace("\\", "/")
    return any(s in name for s in det.scope)


def _make_lock():
    inner = _real_lock()
    det = _detector
    site = _caller_site()
    if det is None or site is None or not _in_scope(site[0]):
        return inner
    label = _site_label(*site)
    with det._mu:
        det.locks_created += 1
    return TrackedLock(inner, label, label)


def _make_rlock():
    inner = _real_rlock()
    det = _detector
    site = _caller_site()
    if det is None or site is None or not _in_scope(site[0]):
        return inner
    label = _site_label(*site)
    with det._mu:
        det.locks_created += 1
    return TrackedRLock(inner, label, label)


def _make_condition(lock=None):
    # threading.Condition() allocates its RLock from inside threading.py,
    # which the scope filter would skip — allocate it HERE so the lock is
    # attributed (and instrumented) at the Condition caller's site
    if lock is None:
        lock = _make_rlock()
    return _real_condition(lock)


# -------------------------------------------------------------------- public
def enable(scope: tuple[str, ...] = DEFAULT_SCOPE,
           mode: str = "record") -> None:
    """Patch the ``threading`` lock factories.  Idempotent; ``disable()``
    restores.  ``mode``: ``record`` (inspect later) or ``raise`` (throw
    ``LockOrderError`` at the acquire that closes a cycle)."""
    global _detector
    if mode not in ("record", "raise"):
        raise ValueError(f"lockorder mode must be record|raise, got {mode!r}")
    if _detector is not None:
        return
    _detector = _Detector(scope, mode)
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    threading.Condition = _make_condition


def enable_from_env() -> bool:
    """Opt-in via ``SM_LOCK_ORDER`` (""/0 = off, "raise" = raise mode,
    anything else = record).  Called by the sweep entrypoints before they
    import/build the service stack."""
    val = os.environ.get("SM_LOCK_ORDER", "")
    if val in ("", "0"):
        return False
    enable(mode="raise" if val == "raise" else "record")
    return True


def disable() -> dict:
    """Restore the real factories; returns the final ``report()``.  Locks
    already handed out keep their (functionally transparent) wrappers."""
    global _detector
    threading.Lock = _real_lock
    threading.RLock = _real_rlock
    threading.Condition = _real_condition
    rep = _detector.report() if _detector is not None else {
        "mode": "off", "locks_instrumented": 0, "edges": 0, "cycles": [],
        "same_site_nesting": {}}
    _detector = None
    return rep


def enabled() -> bool:
    return _detector is not None


def report() -> dict:
    if _detector is None:
        return {"mode": "off", "locks_instrumented": 0, "edges": 0,
                "cycles": [], "same_site_nesting": {}}
    return _detector.report()


def assert_no_cycles(context: str = "") -> dict:
    """Raise ``LockOrderError`` if the recorded graph has a cycle; returns
    the report otherwise (sweeps log the edge/lock counts as evidence the
    detector actually watched something)."""
    rep = report()
    if rep["cycles"]:
        lines = [" -> ".join(c) for c in rep["cycles"]]
        raise LockOrderError(
            f"lock-order cycle(s) detected{f' in {context}' if context else ''}: "
            + "; ".join(lines))
    return rep
