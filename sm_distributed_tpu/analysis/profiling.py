"""On-demand device profiling: capture, attribution, measured roofline.

The modeled roofline (``fused_score_cost_model``) says where the fused
scoring kernel SHOULD sit; nothing had ever measured where it actually
does.  This module closes that loop with a ``jax.profiler`` capture around
in-flight work and a parser for the Chrome-trace artifact it writes
(``<dir>/plugins/profile/<ts>/<host>.trace.json.gz``): device-op events are
``ph == "X"`` slices whose ``args`` carry ``hlo_module`` / ``hlo_op``, with
``ts``/``dur`` in microseconds on the profiler's own clock.

Three consumers share it (docs/OBSERVABILITY.md "Device profiles"):

- ``GET /debug/profile?seconds=`` (service/fleetview.py) captures around
  whatever the scheduler is running and injects ``device_kernel`` spans
  into the live job traces, so Perfetto shows host spans and device
  kernels on one timeline;
- ``bench.py`` captures one scored stream and pins
  ``measured_roofline_frac`` (cost-model floor over MEASURED kernel time)
  next to the modeled ``roofline_frac``;
- ``scripts/fleet_smoke.py`` asserts a capture during a sharded job
  attributes >= 1 named scoring kernel.

Kernel classes are name-driven, matching how the engine builds its jits:
the fused Pallas path dispatches through ``fused_score_fn_flat_fused`` /
``fused_window_moments`` (models/msm_jax.py), the unfused chain through
gather/segment-sum HLO ops inside the plain score modules.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import time
from pathlib import Path

KERNEL_CLASSES = ("fused_kernel", "score_chain", "transfer", "other")

# module-name fragments that identify the fused Pallas scoring kernel's
# jit (mode "on" forces it everywhere, interpret off-TPU — the smoke gate
# relies on that to profile it on CPU)
_FUSED_FRAGMENTS = ("fused_score_fn", "fused_window_moments")
# the unfused scoring chain: plain score jits + the gather/segment-sum ops
_SCORE_FRAGMENTS = ("score_fn", "score_batch", "spectral_metrics")
_SCORE_OPS = ("gather", "scatter", "segment", "reduce-window")
_TRANSFER_OPS = ("copy", "transpose", "all-gather", "all-reduce",
                 "collective-permute", "infeed", "outfeed")


def classify_kernel(module: str, op: str) -> str:
    """Map an (hlo_module, hlo_op) pair to its kernel class."""
    mod = (module or "").lower()
    op_l = (op or "").lower()
    if any(f in mod for f in _FUSED_FRAGMENTS):
        return "fused_kernel"
    if any(op_l.startswith(t) for t in _TRANSFER_OPS):
        return "transfer"
    if any(f in mod for f in _SCORE_FRAGMENTS) or \
            any(t in op_l for t in _SCORE_OPS):
        return "score_chain"
    return "other"


def find_trace_file(profile_dir: str | Path,
                    exclude: set[str] | frozenset[str] = frozenset()) -> Path | None:
    """Newest ``*.trace.json.gz`` under ``profile_dir`` not in ``exclude``
    — the capture that just stopped, not a stale one from a prior run."""
    pattern = os.path.join(str(profile_dir),
                           "plugins", "profile", "*", "*.trace.json.gz")
    fresh = [p for p in glob.glob(pattern) if p not in exclude]
    if not fresh:
        return None
    return Path(max(fresh, key=lambda p: os.path.getmtime(p)))


def parse_trace_file(path: str | Path) -> list[dict]:
    """Device-op events from a profiler Chrome trace: every complete slice
    (``ph == "X"``) whose args name an ``hlo_module``, as
    ``{"module", "op", "class", "ts_us", "dur_us"}``.  Events without HLO
    attribution (host runtime slices) are skipped — they are not device
    kernel time."""
    with gzip.open(path, "rt") as fh:
        data = json.load(fh)
    events = []
    for e in data.get("traceEvents", ()):
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        module = args.get("hlo_module")
        if not module:
            continue
        op = args.get("hlo_op") or e.get("name", "")
        events.append({
            "module": module, "op": op,
            "class": classify_kernel(module, op),
            "ts_us": float(e.get("ts", 0.0)),
            "dur_us": float(e.get("dur", 0.0)),
        })
    return events


def attribute_device_time(events: list[dict], top_n: int = 20) -> dict:
    """Aggregate parsed events into the attribution the endpoints serve:
    per-class device seconds + fractions, and a per-kernel table (grouped
    by (module, op), top ``top_n`` by time)."""
    by_class = {c: 0.0 for c in KERNEL_CLASSES}
    by_kernel: dict[tuple[str, str], dict] = {}
    for e in events:
        dur_s = e["dur_us"] / 1e6
        by_class[e["class"]] += dur_s
        k = (e["module"], e["op"])
        slot = by_kernel.get(k)
        if slot is None:
            slot = by_kernel[k] = {"module": k[0], "op": k[1],
                                   "class": e["class"],
                                   "device_s": 0.0, "count": 0}
        slot["device_s"] += dur_s
        slot["count"] += 1
    total_s = sum(by_class.values())
    kernels = sorted(by_kernel.values(),
                     key=lambda k: k["device_s"], reverse=True)
    for k in kernels:
        k["device_s"] = round(k["device_s"], 9)
    fractions = {c: (round(by_class[c] / total_s, 6) if total_s else 0.0)
                 for c in KERNEL_CLASSES}
    return {
        "total_device_s": round(total_s, 9),
        "by_class_s": {c: round(v, 9) for c, v in by_class.items()},
        "by_class_frac": fractions,
        "kernels": kernels[:top_n],
        "n_events": len(events),
    }


def wall_clock_events(events: list[dict], t0_wall: float) -> list[dict]:
    """Re-base profiler-clock events onto the wall clock: the earliest
    event is pinned to the capture's ``start_trace`` wall time, preserving
    relative offsets — the correlation ``device_kernel`` trace spans need
    to line up with host spans in Perfetto."""
    if not events:
        return []
    ts0 = min(e["ts_us"] for e in events)
    out = []
    for e in events:
        out.append({**e, "ts_wall": t0_wall + (e["ts_us"] - ts0) / 1e6,
                    "dur_s": e["dur_us"] / 1e6})
    return out


class ProfileSession:
    """One ``jax.profiler`` capture: ``start()`` begins the trace (noting
    wall time and pre-existing trace files), ``stop()`` ends it and returns
    the parsed attribution.  Raises ``RuntimeError`` when jax is missing —
    callers surface that as a structured error, never a crash."""

    def __init__(self, profile_dir: str | Path):
        self.dir = Path(profile_dir)
        self.t0_wall = 0.0
        self._preexisting: frozenset[str] = frozenset()
        self._started = False

    def start(self) -> None:
        try:
            import jax
        except ImportError as exc:           # pragma: no cover - jax baked in
            raise RuntimeError(f"profiling needs jax: {exc}") from exc
        self.dir.mkdir(parents=True, exist_ok=True)
        pattern = os.path.join(str(self.dir),
                               "plugins", "profile", "*", "*.trace.json.gz")
        self._preexisting = frozenset(glob.glob(pattern))
        jax.profiler.start_trace(str(self.dir))
        self.t0_wall = time.time()
        self._started = True

    def stop(self) -> dict:
        """Stop the capture; returns ``{"attribution", "events", "trace_file",
        "t0_wall", "duration_s"}`` with wall-mapped events.  A capture that
        produced no trace file (profiler unavailable on this runtime)
        returns empty attribution rather than raising."""
        if not self._started:
            raise RuntimeError("ProfileSession.stop() before start()")
        import jax

        t1 = time.time()
        jax.profiler.stop_trace()
        self._started = False
        trace_file = find_trace_file(self.dir, self._preexisting)
        events = parse_trace_file(trace_file) if trace_file else []
        return {
            "attribution": attribute_device_time(events),
            "events": wall_clock_events(events, self.t0_wall),
            "trace_file": str(trace_file) if trace_file else "",
            "t0_wall": self.t0_wall,
            "duration_s": round(t1 - self.t0_wall, 6),
        }


def measured_roofline(floor_s_per_call: float, kernel_s_per_call: float) -> float:
    """The measured analog of bench's modeled ``roofline_frac``: the cost
    model's floor time for one scoring call over the MEASURED device time
    one call actually took.  1.0 = the kernel runs at the memory/compute
    bound; the modeled fraction uses end-to-end wall time and so mixes in
    host overhead this number excludes."""
    if kernel_s_per_call <= 0 or floor_s_per_call <= 0:
        return 0.0
    return min(1.0, floor_s_per_call / kernel_s_per_call)
