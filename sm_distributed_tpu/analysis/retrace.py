"""Runtime retrace tracer: attribute every XLA compilation (ISSUE 12).

The static ``jit-compile-surface`` rule proves call sites DECLARE a
bounded compile surface; this module proves the surface observed at
runtime matches.  ``enable()`` registers a ``jax.monitoring`` listener for
the backend-compile duration event — fired synchronously inside every
compile-cache miss — and, per compile:

- walks the Python stack to the innermost frame inside this repo (the
  **call site** that dispatched the jitted callable — ``_dispatch``,
  ``warmup``, a test body, ...);
- pulls the **abstract signature** from the in-flight pjit frame
  (``_pjit_call_impl_python`` carries the closed jaxpr and executable
  name as locals; absent — e.g. an AOT ``.compile()`` path — the
  signature degrades to ``<opaque>`` rather than losing the event);
- records ``(site, signature)`` into a process-global census,
  increments ``sm_compile_events_total{site=}``, updates the
  ``sm_compile_signatures{site=}`` distinct-signature gauge, and emits a
  ``compile`` trace event onto the ambient job trace (so a cold-start
  compile shows up INSIDE the job that paid for it).

The listener cannot be unregistered in this jax version, so ``enable()``
registers exactly once per process and ``disable()`` just de-activates;
both are idempotent.  A listener fault must never fail a compile: the
handler catches everything and logs once per process.

``scripts/compile_census.py`` drives a real service with this tracer on
and asserts the observed surface is attributed (every site's module has a
``COMPILE_SURFACE`` registration) and CLOSED (a second same-shaped job
adds zero new signatures).
"""

from __future__ import annotations

import sys
import threading
from pathlib import Path

from ..utils import tracing
from ..utils.logger import logger

# the jax monitoring event fired once per backend-compile REQUEST.  It
# wraps ``compile_or_get_cached``, so it fires on persistent-cache HITS
# too (jax 0.4.x) — the hit is announced by a separate cache-hits event
# just before the duration event lands on the same thread, which is how
# the listener below tells a real compile from a cache load (ISSUE 13:
# a primed cache must show up as loads, not compiles).
COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
# warm-start attribution (ISSUE 18): the other places a "warm" compile_s
# actually goes.  jaxpr tracing and jaxpr->MLIR lowering run on EVERY
# compile-cache miss (even when the executable then loads off the
# persistent cache — the cache key needs the lowered module), and the
# cache-retrieval event times the disk read + deserialize alone.  The
# census accumulates all four buckets so bench.py / trace_report.py can
# split warm compile seconds into trace / lower / cache-load / backend-
# compile instead of one opaque number.
TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
LOWER_EVENT = "/jax/core/compile/jaxpr_to_mlir_module_duration"
CACHE_LOAD_EVENT = "/jax/compilation_cache/cache_retrieval_time_sec"
# census duration buckets, keyed by the reported field name
_DURATION_KEYS = ("trace_s", "lower_s", "cache_load_s", "backend_compile_s")
_EVENT_BUCKET = {TRACE_EVENT: "trace_s", LOWER_EVENT: "lower_s",
                 CACHE_LOAD_EVENT: "cache_load_s"}

_REPO_ROOT = Path(__file__).resolve().parents[2]
_SELF = Path(__file__).resolve()

# per-site cap on STORED signature strings (the distinct count keeps
# counting past it; the census only needs the set to prove closure, and an
# unbounded-retrace bug is exactly when storage would explode)
MAX_STORED_SIGNATURES = 128


class _Census:
    """Process-global compile census (smlint guarded-by)."""

    _GUARDED_BY = {"_sites": "_lock", "_events_total": "_lock",
                   "_overflow": "_lock", "_cache_hits_total": "_lock",
                   "_durations": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._sites: dict[str, dict] = {}   # site -> {signatures:set, events:int}
        self._events_total = 0
        self._cache_hits_total = 0          # persistent-cache loads (primed)
        self._overflow = 0                  # signatures dropped past the cap
        self._durations = dict.fromkeys(_DURATION_KEYS, 0.0)

    def _entry_locked(self, site: str) -> dict:
        return self._sites.setdefault(
            site, {"signatures": set(), "events": 0, "cache_hits": 0})

    def record(self, site: str, signature: str) -> tuple[bool, int]:
        """A REAL backend compile.  Returns (is_new_signature,
        distinct_count_for_site)."""
        with self._lock:
            ent = self._entry_locked(site)
            ent["events"] += 1
            self._events_total += 1
            new = signature not in ent["signatures"]
            if new:
                if len(ent["signatures"]) >= MAX_STORED_SIGNATURES:
                    self._overflow += 1
                else:
                    ent["signatures"].add(signature)
            return new, len(ent["signatures"])

    def record_cache_hit(self, site: str) -> None:
        """A persistent-cache LOAD: the executable came off disk — the
        outcome priming buys — so it must not count as a compile."""
        with self._lock:
            self._entry_locked(site)["cache_hits"] += 1
            self._cache_hits_total += 1

    def record_duration(self, bucket: str, seconds: float) -> None:
        """Accumulate one compile-pipeline stage duration (ISSUE 18
        warm-start attribution)."""
        with self._lock:
            self._durations[bucket] += seconds

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "events_total": self._events_total,
                "cache_hits_total": self._cache_hits_total,
                "durations": {k: round(v, 6)
                              for k, v in self._durations.items()},
                "signatures_total": sum(
                    len(e["signatures"]) for e in self._sites.values()),
                "overflow": self._overflow,
                "sites": {
                    s: {"events": e["events"],
                        "cache_hits": e.get("cache_hits", 0),
                        "signatures": sorted(e["signatures"])}
                    for s, e in sorted(self._sites.items())
                },
            }

    def reset(self) -> None:
        with self._lock:
            self._sites.clear()
            self._events_total = 0
            self._cache_hits_total = 0
            self._overflow = 0
            self._durations = dict.fromkeys(_DURATION_KEYS, 0.0)


_census = _Census()
_state_lock = threading.Lock()
_active = False
_registered = False
_metrics = None
_warned = False
# per-thread persistent-cache-hit flag: jax announces a hit with
# CACHE_HIT_EVENT just before the wrapping COMPILE_EVENT duration lands on
# the same thread; the duration listener consumes the flag to classify
_tls = threading.local()


def _site_of_frame(frame) -> str | None:
    """``relpath:function`` when ``frame`` is repo code, else None."""
    try:
        path = Path(frame.f_code.co_filename).resolve()
    except OSError:
        return None
    if path == _SELF or "site-packages" in path.parts:
        return None
    try:
        rel = path.relative_to(_REPO_ROOT)
    except ValueError:
        return None
    return f"{rel.as_posix()}:{frame.f_code.co_name}"


def _attribute() -> tuple[str, str, str]:
    """(site, executable name, abstract signature) for the in-flight
    compile, from the listener's own stack."""
    site, fn_name, sig = "<external>", "", "<opaque>"
    f = sys._getframe(2)            # skip _attribute + the listener
    while f is not None:
        if f.f_code.co_name == "_pjit_call_impl_python":
            loc = f.f_locals
            name = loc.get("name")
            if isinstance(name, str):
                fn_name = name
            jaxpr = loc.get("jaxpr")
            avals = getattr(jaxpr, "in_avals", None)
            if avals is not None:
                sig = "(" + ", ".join(str(a) for a in avals) + ")"
        if site == "<external>":
            s = _site_of_frame(f)
            if s is not None:
                site = s
        f = f.f_back
    return site, fn_name, sig


def _on_event(name: str, **_kw) -> None:
    """record_event listener: flags a persistent-cache hit for the
    duration event that follows on this thread."""
    if name == CACHE_HIT_EVENT and _active:
        _tls.cache_hit = True


def _on_event_duration(name: str, duration: float, **_kw) -> None:
    global _warned
    if not _active:
        return
    if name in _EVENT_BUCKET:
        # compile-pipeline stage durations (warm-start attribution): one
        # firing per compile-cache miss / cache read — census totals plus
        # a trace event so a job trace shows where its warm seconds went
        try:
            bucket = _EVENT_BUCKET[name]
            _census.record_duration(bucket, float(duration))
            tracing.event(f"compile_{bucket.removesuffix('_s')}",
                          dur_s=round(float(duration), 4))
        except Exception:
            if not _warned:
                _warned = True
                logger.warning("retrace tracer: attribution failed (disabled "
                               "for this event only)", exc_info=True)
        return
    if name != COMPILE_EVENT:
        return
    try:
        cached = bool(getattr(_tls, "cache_hit", False))
        _tls.cache_hit = False
        if not cached:
            # a cached firing's duration is the retrieval (already in the
            # cache_load_s bucket via CACHE_LOAD_EVENT) — only a real
            # backend compile lands here
            _census.record_duration("backend_compile_s", float(duration))
        site, fn_name, sig = _attribute()
        signature = f"{fn_name}{sig}" if fn_name else sig
        m = _metrics
        if cached:
            # the executable came off the persistent cache — the primed
            # outcome, NOT a compile: counted separately so the census
            # (and the coldstart smoke) can assert "loads, not compiles"
            _census.record_cache_hit(site)
            if m is not None:
                m.counter(
                    "sm_compile_cache_hits_total",
                    "Persistent-XLA-cache executable loads (primed/warm "
                    "cache) by attributed call site",
                    ("site",)).labels(site=site).inc()
            tracing.event("compile", site=site, fn=fn_name,
                          signature=sig[:500],
                          dur_s=round(float(duration), 4), cached=True)
            return
        new, distinct = _census.record(site, signature)
        if m is not None:
            m.counter(
                "sm_compile_events_total",
                "XLA backend compilations (compile-cache misses) by "
                "attributed call site", ("site",)).labels(site=site).inc()
            m.gauge(
                "sm_compile_signatures",
                "Distinct abstract signatures compiled, by attributed "
                "call site", ("site",)).labels(site=site).set(distinct)
        tracing.event("compile", site=site, fn=fn_name,
                      signature=sig[:500], dur_s=round(float(duration), 4),
                      new_signature=bool(new), cached=False)
    except Exception:
        # a tracer fault must never fail the compile it observes
        if not _warned:
            _warned = True
            logger.warning("retrace tracer: attribution failed (disabled "
                           "for this event only)", exc_info=True)


def enable(metrics=None) -> None:
    """Start attributing compiles.  Idempotent; the jax listener is
    registered once per process (this jax version has no unregister), so
    repeated enable/disable cycles only flip the active flag.  ``metrics``
    (a service MetricsRegistry) rebinds the ``sm_compile_*`` export —
    the latest caller wins, matching the oom/breaker attach pattern."""
    global _active, _registered, _metrics
    with _state_lock:
        if metrics is not None:
            _metrics = metrics
        if not _registered:
            try:
                from jax import monitoring
            except ImportError:
                logger.warning("retrace tracer: jax.monitoring unavailable; "
                               "compile attribution disabled")
                return
            monitoring.register_event_duration_secs_listener(
                _on_event_duration)
            monitoring.register_event_listener(_on_event)
            _registered = True
        _active = True


def disable() -> dict:
    """Stop recording; returns the final census snapshot."""
    global _active
    with _state_lock:
        _active = False
    return _census.snapshot()


def enabled() -> bool:
    return _active


def snapshot() -> dict:
    """Census contents: ``{events_total, cache_hits_total, durations:
    {trace_s, lower_s, cache_load_s, backend_compile_s}, signatures_total,
    overflow, sites: {site: {events, cache_hits, signatures}}}``."""
    return _census.snapshot()


def reset() -> None:
    """Forget recorded compiles (tests / census phases)."""
    _census.reset()
