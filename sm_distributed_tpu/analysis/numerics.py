"""Numerics contracts + ULP instrumentation (ISSUE 15 "numlint").

ROADMAP item 3 (fused Pallas scoring + bf16/int8 intensity compaction) is
gated on one invariant: FDR ranks stay bit-identical — or within a
*declared* tolerance — to the fp32/numpy oracle.  This module is the
declarative half of that gate, mirroring ``analysis/surface.py``:

- every jitting module declares a module-level ``NUMERICS =
  numerics_surface(__name__, {...})`` mapping each site (its
  ``COMPILE_SURFACE`` sites, plus any public numeric function the module
  wants covered) to a **contract string** in the grammar::

      "contract=bit_exact|ulp(N); test=tests/<file>.py::<test_name>
       [; padded=<param,param>]"

  ``contract=`` is the declared drift bound versus the site's reference
  (the numpy oracle, the unpadded program, or the sibling variant —
  whichever the named test asserts): ``bit_exact`` means every bit, and
  ``ulp(N)`` means at most N float32 units-in-the-last-place.
  ``test=`` names the committed test that PROVES the contract — the
  ``ulp-contract`` smlint rule statically cross-checks that the file
  exists and defines that test, so a contract can never outlive its
  proof.  ``padded=`` names the parameters that receive lattice-padded
  blocks (ops/buckets, ISSUE 13): the ``masked-reduction`` rule seeds
  its taint from them, so a raw reduction over a padded axis that skips
  the ``n_real`` masked helpers is a lint error, not a silent metric
  corruption;

- the runtime half is ``scripts/ulp_sentinel.py``: it scores the
  spheroid fixture on both backends, measures per-MSM-component max-ULP
  drift with the helpers below, hard-gates FDR-rank identity, enforces
  the per-component ceilings in :data:`COMPONENT_CONTRACTS`, and bands
  the drift against the committed ``NUMERICS_r*.json`` history
  (perf_sentinel-style: rising drift regresses).

The registry is import-time write-once state like the compile surface;
one leaf lock guards the map and the class carries a ``_GUARDED_BY``
registry for the smlint ``guarded-by`` rule.  Only numpy is imported —
jitting modules pull ``numerics_surface`` at import time, before any
backend initialization.
"""

from __future__ import annotations

import re
import threading

import numpy as np

# contract grammar (keep in lockstep with the ulp-contract rule's static
# validation in rules.py — same regexes, one checked at import, one in lint)
CONTRACT_RE = re.compile(r"^(bit_exact|ulp\((\d+)\))$")
TEST_RE = re.compile(r"^[\w./-]+\.py::\w+$")
PADDED_RE = re.compile(r"^\w+(,\w+)*$")
POLICY_KEYS = ("contract", "test")          # mandatory clauses
OPTIONAL_KEYS = ("padded",)

# The per-MSM-component drift ceilings the runtime sentinel enforces on
# the spheroid fixture (jax lattice-bucketed scoring vs the numpy
# oracle, float32 ULPs).  chaos is integer-derived (component counts /
# exact maxima) => bit-exact by construction; spatial (image
# correlation) and spectral (pattern match) reduce f32 in a different
# association order than numpy, so they carry a small declared budget;
# msm is their product.  Measured on the committed fixture
# (NUMERICS_r01.json, XLA-CPU): chaos 0 / spatial 2 / spectral 1 / msm 2
# ULPs — the integer-grid intensity quantization (ops/quantize.py) makes
# the image sums exact, and the residual drift is reduction-order in the
# metric epilogues.  The budgets below are the DECLARED cross-backend
# ceilings (the same 1e-6-grade bound tests assert on TPU); the
# committed-history banding in ulp_sentinel catches drift long before a
# ceiling is reached.
COMPONENTS = ("chaos", "spatial", "spectral", "msm")
COMPONENT_CONTRACTS = {"chaos": 0, "spatial": 16, "spectral": 16, "msm": 32}


def parse_policy(policy: str) -> dict[str, str]:
    """Parse one contract policy string; raises ``ValueError`` on any
    grammar violation (missing clause, bad contract form, malformed test
    reference or padded list)."""
    if not isinstance(policy, str):
        raise ValueError(f"policy must be a string, got {policy!r}")
    out: dict[str, str] = {}
    for part in policy.split(";"):
        part = part.strip()
        if not part:
            continue
        key, eq, value = part.partition("=")
        key, value = key.strip(), value.strip()
        if not eq or key not in POLICY_KEYS + OPTIONAL_KEYS:
            raise ValueError(f"unknown policy clause {part!r}")
        out[key] = value
    for key in POLICY_KEYS:
        if key not in out:
            raise ValueError(f"policy lacks the {key}= clause: {policy!r}")
    if not CONTRACT_RE.match(out["contract"]):
        raise ValueError(
            f"contract must be bit_exact or ulp(N), got {out['contract']!r}")
    if not TEST_RE.match(out["test"]):
        raise ValueError(
            f"test must be <path>.py::<test_name>, got {out['test']!r}")
    if "padded" in out and not PADDED_RE.match(out["padded"]):
        raise ValueError(
            f"padded must be a comma list of parameter names, got "
            f"{out['padded']!r}")
    return out


def contract_ulps(contract: str) -> int:
    """Declared float32 ULP budget: 0 for ``bit_exact``, N for ``ulp(N)``."""
    m = CONTRACT_RE.match(contract)
    if not m:
        raise ValueError(f"not a contract: {contract!r}")
    return int(m.group(2)) if m.group(2) is not None else 0


class _NumericsRegistry:
    """Process-global {module: {site: policy}} map (import-time
    write-once, reader-iterated — same protocol as the compile surface)."""

    _GUARDED_BY = {"_surfaces": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._surfaces: dict[str, dict[str, str]] = {}

    def declare(self, module: str, entries: dict[str, str]) -> None:
        with self._lock:
            self._surfaces[module] = dict(entries)

    def registered(self) -> dict[str, dict[str, str]]:
        with self._lock:
            return {m: dict(e) for m, e in self._surfaces.items()}


_registry = _NumericsRegistry()


def numerics_surface(module: str, entries: dict[str, str]) -> dict[str, str]:
    """Declare ``module``'s numerics contracts and return ``entries`` (the
    declaration doubles as the module-level ``NUMERICS`` constant).
    Malformed policies raise at import time — a bad contract must not
    wait for the lint run."""
    for site, policy in entries.items():
        try:
            parse_policy(policy)
        except ValueError as exc:
            raise ValueError(
                f"numerics_surface({module!r}): entry {site!r}: {exc}"
            ) from exc
    _registry.declare(module, entries)
    return dict(entries)


def registered() -> dict[str, dict[str, str]]:
    """{module name: {site: policy}} of every imported declaration."""
    return _registry.registered()


# --------------------------------------------------------- ULP measurement
def _lex_f32(x: np.ndarray) -> np.ndarray:
    """Monotone int64 image of float32 values: consecutive floats map to
    consecutive integers (the ULP number line), with -0.0 == +0.0."""
    bits = np.ascontiguousarray(x, dtype=np.float32).view(np.int32)
    bits = bits.astype(np.int64)
    return np.where(bits >= 0, bits, np.int64(-(2**31)) - bits)


def ulp_distance(a, b) -> np.ndarray:
    """Elementwise float32 ULP distance (int64).  Inputs are cast to f32
    first — the engine's device dtype — so a float64 oracle value and
    its f32 rounding compare at distance 0 when they share the f32 bit
    pattern.  NaNs (none expected from the metric epilogues, which clip
    to [0, 1]) compare as +inf-like: any NaN pairing maps to 2**62."""
    fa = np.asarray(a, dtype=np.float32)
    fb = np.asarray(b, dtype=np.float32)
    dist = np.abs(_lex_f32(fa) - _lex_f32(fb))
    nan = np.isnan(fa) | np.isnan(fb)
    both = np.isnan(fa) & np.isnan(fb)
    return np.where(both, 0, np.where(nan, np.int64(2**62), dist))


def max_ulp(a, b) -> int:
    """Max elementwise float32 ULP distance between two arrays."""
    d = ulp_distance(a, b)
    return int(d.max()) if d.size else 0


def component_drift(got: np.ndarray, want: np.ndarray) -> dict[str, int]:
    """Per-MSM-component max-ULP drift between two (N, 4) metric blocks
    ordered (chaos, spatial, spectral, msm) — the sentinel's unit of
    record."""
    got = np.asarray(got)
    want = np.asarray(want)
    if got.shape != want.shape or got.ndim != 2 or got.shape[1] != 4:
        raise ValueError(
            f"metric blocks must share an (N, 4) shape, got {got.shape} "
            f"vs {want.shape}")
    return {comp: max_ulp(got[:, i], want[:, i])
            for i, comp in enumerate(COMPONENTS)}
