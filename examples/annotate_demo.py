"""End-to-end demo: synthesize an imzML dataset, annotate it on the
configured backend, and query the results.

Run from the repo root (no external data or services needed):

    python examples/annotate_demo.py                 # jax_tpu backend
    python examples/annotate_demo.py --backend numpy_ref
    python examples/annotate_demo.py --nrows 128 --ncols 128
"""

from __future__ import annotations

import argparse
import sys
import tempfile
from pathlib import Path

# runnable as `python examples/annotate_demo.py` without installation
sys.path.insert(0, str(Path(__file__).parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default="jax_tpu",
                    choices=["jax_tpu", "numpy_ref"])
    ap.add_argument("--nrows", type=int, default=32)
    ap.add_argument("--ncols", type=int, default=32)
    ap.add_argument("--out", default=None,
                    help="working directory (default: a temp dir)")
    args = ap.parse_args()

    from sm_distributed_tpu.engine.search_job import SearchJob
    from sm_distributed_tpu.engine.storage import AnnotationIndex, JobLedger
    from sm_distributed_tpu.io.fixtures import generate_synthetic_dataset
    from sm_distributed_tpu.utils.config import DSConfig, SMConfig
    from sm_distributed_tpu.utils.logger import init_logger

    init_logger()
    root = Path(args.out) if args.out else Path(tempfile.mkdtemp(prefix="smtpu_demo_"))

    # 1. a synthetic dataset with known ground truth (half the formulas are
    #    spatially structured signal, the rest are absent)
    imzml, truth = generate_synthetic_dataset(
        root / "dataset", nrows=args.nrows, ncols=args.ncols,
        present_fraction=0.5, noise_peaks=100, seed=7)
    print(f"dataset: {args.nrows}x{args.ncols} px at {imzml}")
    print(f"ground truth: {len(truth.present)}/{len(truth.formulas)} formulas present")

    # 2. configure + run the annotation job (target/decoy FDR included)
    sm_config = SMConfig.from_dict({
        "backend": args.backend,
        "work_dir": str(root / "work"),
        "storage": {"results_dir": str(root / "results")},
        "fdr": {"decoy_sample_size": 10, "seed": 42},
    })
    ds_config = DSConfig.from_dict({
        "isotope_generation": {"adducts": ["+H"]},
        "image_generation": {"ppm": 3.0},
    })
    job = SearchJob("demo", "demo dataset", imzml, ds_config,
                    sm_config=sm_config, formulas=list(truth.formulas))
    bundle = job.run()

    # 3. query the index the way the reference's webapp queries ES
    index = AnnotationIndex(JobLedger(sm_config.storage.results_dir))
    hits = index.search(ds_id="demo", max_fdr_level=0.1)
    got = set(hits.sf)
    tp = got & set(truth.present)
    fp = got - set(truth.present)
    print(f"\nannotations at FDR<=10%: {len(hits)} "
          f"(true positives {len(tp)}/{len(truth.present)}, false {len(fp)})")
    print(hits[["sf", "adduct", "mz", "msm", "fdr_level"]]
          .head(10).to_string(index=False))
    print(f"\nresults under {root}/results (parquet + sqlite + PNGs); "
          f"timings: {bundle.timings}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
