"""Service-layer tests: scheduler, failure policy, metrics, admin API.

Everything runs on CPU with FAKE job callbacks (no JAX, no search) — the
service contract (admission, concurrency, retry/backoff, dead-letter,
heartbeats, drain, exposition) is independent of what the jobs compute.
"""

import json
import threading
import time
import urllib.request
from pathlib import Path

import pytest

from sm_distributed_tpu.engine.daemon import (
    QueuePublisher,
    heartbeat_path,
)
from sm_distributed_tpu.engine.residency import DatasetResidency
from sm_distributed_tpu.service import AnnotationService, JobScheduler, RetryPolicy
from sm_distributed_tpu.service.metrics import MetricsRegistry
from sm_distributed_tpu.utils.config import ServiceConfig, SMConfig
from sm_distributed_tpu.utils.logger import phase_timer


def _fast_cfg(**kw) -> ServiceConfig:
    base = dict(workers=3, poll_interval_s=0.02, job_timeout_s=5.0,
                max_attempts=3, backoff_base_s=0.05, backoff_max_s=0.5,
                backoff_jitter=0.0, heartbeat_interval_s=0.05,
                stale_after_s=0.5, drain_timeout_s=10.0, http_port=0)
    base.update(kw)
    return ServiceConfig(**base)


def _sm(tmp_path, **service_kw) -> SMConfig:
    import dataclasses

    return dataclasses.replace(
        SMConfig.from_dict({"work_dir": str(tmp_path / "work")}),
        service=_fast_cfg(**service_kw))


class FakeJobs:
    """Callback recording per-message attempt history; behavior is driven
    by message fields: ``fail_times`` (raise on the first N attempts),
    ``sleep_s`` (hold the worker), plus the shared residency exercised via
    ``phase_timer`` so the metric plumbing runs exactly as real jobs do."""

    def __init__(self, residency=None):
        self.residency = residency
        self.attempts: dict[str, list[float]] = {}
        self.device_tokens = []
        self._lock = threading.Lock()

    def __call__(self, msg, ctx=None):
        ds = msg["ds_id"]
        with self._lock:
            self.attempts.setdefault(ds, []).append(time.time())
            n_attempt = len(self.attempts[ds])
            if ctx is not None:
                self.device_tokens.append(ctx.device_token)
        with phase_timer("stage_input"):
            time.sleep(float(msg.get("sleep_s", 0.0)))
        if self.residency is not None:
            with phase_timer("read_dataset"):
                self.residency.dataset(("ds", ds), lambda: object())
        if n_attempt <= int(msg.get("fail_times", 0)):
            raise RuntimeError(f"boom on attempt {n_attempt} of {ds}")
        with phase_timer("search"):
            if ctx is not None and ctx.device_token is not None:
                with ctx.device_token:
                    pass


def test_service_integration_scheduler_retry_metrics_shutdown(tmp_path):
    """ISSUE acceptance: >= 8 jobs (one raising, one exceeding its timeout)
    through the scheduler — terminal states, retry-with-backoff then
    dead-letter, /metrics histograms + residency counters, and a
    SIGTERM-equivalent shutdown leaving nothing in running/."""
    residency = DatasetResidency(max_datasets=8, max_backends=8)
    jobs = FakeJobs(residency)
    service = AnnotationService(
        tmp_path / "q", jobs, sm_config=_sm(tmp_path),
        residency=residency, with_api=False)
    pub = service.publisher

    for i in range(6):                       # 6 plain jobs (2 repeat ds keys)
        pub.publish({"ds_id": f"ok{i % 4}", "input_path": "/in",
                     "msg_id": f"ok{i}"})
    # one job that raises on every attempt at bounded attempts=2
    pub.publish({"ds_id": "always_fails", "input_path": "/in",
                 "msg_id": "always_fails", "fail_times": 99,
                 "service": {"max_attempts": 2}})
    # one job that raises once, then succeeds (retry with backoff)
    pub.publish({"ds_id": "flaky", "input_path": "/in", "msg_id": "flaky",
                 "fail_times": 1})
    # one job that exceeds its per-job timeout (single attempt → dead-letter)
    pub.publish({"ds_id": "too_slow", "input_path": "/in", "msg_id": "slow",
                 "sleep_s": 3.0,
                 "service": {"timeout_s": 0.3, "max_attempts": 1}})

    service.start()
    assert service.scheduler.wait_for_terminal(9, timeout_s=30.0), \
        service.scheduler.stats()

    root = tmp_path / "q" / "sm_annotate"
    done = {p.stem for p in root.glob("done/*.json")}
    failed = {p.stem for p in root.glob("failed/*.json")}
    assert done == {f"ok{i}" for i in range(6)} | {"flaky"}
    assert failed == {"always_fails", "slow"}

    # retried with backoff: two attempts spaced >= base_s, then a third
    # never happened for the bounded job; flaky's retry also >= base_s
    assert len(jobs.attempts["always_fails"]) == 2
    assert len(jobs.attempts["flaky"]) == 2
    base = service.sm_config.service.backoff_base_s
    for ds in ("always_fails", "flaky"):
        t1, t2 = jobs.attempts[ds]
        assert t2 - t1 >= base, f"{ds} retried before its backoff elapsed"

    # dead-letter evidence: traceback + attempt count recorded
    dl = json.loads((root / "failed" / "always_fails.json").read_text())
    assert dl["attempts"] == 2
    assert "RuntimeError" in dl["traceback"] and "boom" in dl["error"]
    slow = json.loads((root / "failed" / "slow.json").read_text())
    assert "timeout" in slow["error"]

    # /metrics: per-phase histograms + residency hit/miss counters
    text = service.metrics.expose()
    assert 'sm_phase_seconds_bucket{le="+Inf",phase="stage_input"}' in text
    assert 'sm_phase_seconds_count{phase="search"}' in text
    assert 'sm_residency_hits_total{cache="dataset"}' in text
    # 6 distinct ds keys (ok0-3, flaky, always_fails; the timed-out job's
    # abandoned attempt may add a 7th later) → 4 hits from the ok0/ok1
    # repeats and the flaky/always_fails second attempts
    stats = residency.stats
    assert stats["dataset_hits"] == 4 and stats["dataset_misses"] >= 6
    assert 'sm_jobs_total{state="done"} 7' in text
    assert 'sm_jobs_total{state="failed"} 2' in text
    assert "sm_job_retries_total 2" in text
    assert "sm_job_timeouts_total 1" in text
    assert "sm_job_duration_seconds_count" in text

    # SIGTERM-equivalent: drain leaves nothing stranded in running/
    assert service.shutdown()
    assert list(root.glob("running/*")) == [], "message stranded in running/"
    # all 9 records reached terminal states
    states = {j["msg_id"]: j["state"] for j in service.scheduler.jobs()}
    assert len(states) == 9
    assert all(s in ("done", "failed") for s in states.values()), states


def test_scheduler_concurrency_and_device_token_serialization(tmp_path):
    """Workers overlap CPU phases; on a 1-chip pool (the old single-token
    configuration, pinned explicitly now that the pool auto-sizes to the
    visible devices) device holders still serialize."""
    active = []
    peak = [0]
    token_overlap = [0]
    lock = threading.Lock()

    def cb(msg, ctx):
        with lock:
            active.append(msg["ds_id"])
            peak[0] = max(peak[0], len(active))
        time.sleep(0.15)             # CPU phase — overlaps across workers
        with ctx.device_token:       # device phase — must serialize
            with lock:
                token_overlap[0] += 1
                assert token_overlap[0] == 1, "two jobs inside the TPU token"
            time.sleep(0.03)
            with lock:
                token_overlap[0] -= 1
        with lock:
            active.remove(msg["ds_id"])

    sched = JobScheduler(tmp_path / "q", cb,
                         config=_fast_cfg(workers=3, device_pool_size=1))
    pub = QueuePublisher(tmp_path / "q")
    for i in range(6):
        pub.publish({"ds_id": f"j{i}", "input_path": "/in", "msg_id": f"j{i}"})
    sched.start()
    assert sched.wait_for_terminal(6, timeout_s=20.0)
    assert sched.shutdown()
    assert peak[0] >= 2, "workers never overlapped"


def test_scheduler_priority_and_tenant_fairness(tmp_path):
    """Priority classes run first; within a class, the tenant with fewer
    in-flight jobs is preferred over a burst tenant."""
    order = []
    lock = threading.Lock()

    def cb(msg, ctx=None):
        with lock:
            order.append(msg["msg_id"])
        time.sleep(0.02)

    pub = QueuePublisher(tmp_path / "q")
    # burst tenant floods 4 normal jobs, then tenant B adds one normal and
    # one high; publish everything BEFORE the scheduler starts
    for i in range(4):
        pub.publish({"ds_id": f"a{i}", "input_path": "/in", "msg_id": f"a{i}",
                     "tenant": "burst"})
    pub.publish({"ds_id": "b0", "input_path": "/in", "msg_id": "b_norm",
                 "tenant": "B"})
    pub.publish({"ds_id": "b1", "input_path": "/in", "msg_id": "b_high",
                 "tenant": "B", "priority": "high"})
    pub.publish({"ds_id": "c", "input_path": "/in", "msg_id": "c_low",
                 "priority": "low"})

    sched = JobScheduler(tmp_path / "q", cb, config=_fast_cfg(workers=1))
    sched.start()
    assert sched.wait_for_terminal(7, timeout_s=20.0)
    assert sched.shutdown()
    assert order[0] == "b_high", f"high priority did not run first: {order}"
    assert order[-1] == "c_low", f"low priority did not run last: {order}"
    # fairness: tenant B's normal job is not stuck behind the whole burst —
    # it runs within the first three normal-class slots
    assert order.index("b_norm") <= 3, order


def test_scheduler_poison_message_dead_letters(tmp_path):
    def cb(msg, ctx=None):
        pass

    pub = QueuePublisher(tmp_path / "q")
    pub.publish({"ds_id": "ok", "input_path": "/in", "msg_id": "ok"})
    (tmp_path / "q" / "sm_annotate" / "pending" / "poison.json").write_text("{nope")
    sched = JobScheduler(tmp_path / "q", cb, config=_fast_cfg(workers=1))
    sched.start()
    assert sched.wait_for_terminal(2, timeout_s=10.0)
    assert sched.shutdown()
    root = tmp_path / "q" / "sm_annotate"
    dl = json.loads((root / "failed" / "poison.json").read_text())
    assert "poison" in dl["error"] and "{nope" in dl["raw"]
    assert {p.stem for p in root.glob("done/*.json")} == {"ok"}


def test_scheduler_heartbeats_live_during_job(tmp_path):
    saw_hb = []

    def cb(msg, ctx=None):
        p = tmp_path / "q" / "sm_annotate" / "running" / f"{msg['msg_id']}.json"
        deadline = time.time() + 2.0
        while time.time() < deadline and not heartbeat_path(p).exists():
            time.sleep(0.01)
        saw_hb.append(heartbeat_path(p).exists())
        time.sleep(0.15)             # > heartbeat interval → refreshed

    sched = JobScheduler(tmp_path / "q", cb,
                         config=_fast_cfg(workers=1, heartbeat_interval_s=0.05))
    QueuePublisher(tmp_path / "q").publish(
        {"ds_id": "hb", "input_path": "/in", "msg_id": "hb"})
    sched.start()
    assert sched.wait_for_terminal(1, timeout_s=10.0)
    assert sched.shutdown()
    assert saw_hb == [True]
    # terminal move cleaned the heartbeat up
    root = tmp_path / "q" / "sm_annotate"
    assert not list(root.glob("running/*")), "running/ not empty"


def test_shutdown_requeues_claimed_but_unstarted(tmp_path):
    """With one slow worker and a full hand-off buffer, shutdown must move
    claimed-but-unstarted messages back to pending/ — nothing stranded."""
    release = threading.Event()

    def cb(msg, ctx=None):
        release.wait(5.0)

    sched = JobScheduler(tmp_path / "q", cb, config=_fast_cfg(workers=1))
    pub = QueuePublisher(tmp_path / "q")
    for i in range(4):
        pub.publish({"ds_id": f"d{i}", "input_path": "/in", "msg_id": f"d{i}"})
    sched.start()
    # wait until one job is running and at least one more is claimed
    deadline = time.time() + 5.0
    root = tmp_path / "q" / "sm_annotate"
    while time.time() < deadline:
        if sched.stats()["states"].get("running", 0) >= 1 and \
                len(list(root.glob("running/*.json"))) >= 2:
            break
        time.sleep(0.01)
    release.set()
    assert sched.shutdown()
    assert not list(root.glob("running/*")), "claimed message stranded"
    done = len(list(root.glob("done/*.json")))
    pending = len(list(root.glob("pending/*.json")))
    assert done + pending == 4 and done >= 1


def test_retry_policy_backoff_shape():
    pol = RetryPolicy(max_attempts=5, base_s=1.0, max_s=8.0, jitter=0.0)
    assert [pol.backoff_s(n) for n in (1, 2, 3, 4, 5)] == \
        [1.0, 2.0, 4.0, 8.0, 8.0]
    jittered = RetryPolicy(base_s=1.0, max_s=60.0, jitter=0.5)
    for n in (1, 2, 3):
        d = jittered.backoff_s(n)
        assert 2.0 ** (n - 1) <= d <= 2.0 ** (n - 1) * 1.5


def test_metrics_registry_exposition_format():
    m = MetricsRegistry()
    c = m.counter("sm_test_total", "help text", ("kind",))
    c.labels(kind="a").inc()
    c.labels(kind="a").inc(2)
    g = m.gauge("sm_test_gauge", "a gauge")
    g.set(1.5)
    h = m.histogram("sm_test_seconds", "a histogram", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = m.expose()
    assert "# TYPE sm_test_total counter" in text
    assert 'sm_test_total{kind="a"} 3' in text
    assert "sm_test_gauge 1.5" in text
    assert 'sm_test_seconds_bucket{le="0.1"} 1' in text
    assert 'sm_test_seconds_bucket{le="1"} 2' in text
    assert 'sm_test_seconds_bucket{le="+Inf"} 3' in text
    assert "sm_test_seconds_count 3" in text
    assert "sm_test_seconds_sum 5.55" in text
    # re-registration returns the same family; type clashes are rejected
    assert m.counter("sm_test_total", labelnames=("kind",)) is c
    with pytest.raises(ValueError):
        m.gauge("sm_test_total")


def _get(url: str):
    with urllib.request.urlopen(url, timeout=5.0) as r:
        return r.status, r.read()


def test_admin_api_endpoints(tmp_path):
    jobs = FakeJobs()
    service = AnnotationService(tmp_path / "q", jobs, sm_config=_sm(tmp_path))
    service.start()
    try:
        host, port = service.api.address
        base = f"http://{host}:{port}"
        status, body = _get(base + "/healthz")
        health = json.loads(body)
        assert status == 200 and health["status"] == "ok"
        assert health["queue"] == {"pending": 0, "running": 0,
                                   "done": 0, "failed": 0, "quarantine": 0}
        assert health["admission"]["depth"] == 0

        # POST /submit → spooled + eventually done
        req = urllib.request.Request(
            base + "/submit", method="POST",
            data=json.dumps({"ds_id": "api1", "input_path": "/in"}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5.0) as r:
            assert r.status == 202
            msg_id = json.loads(r.read())["msg_id"]
        assert service.scheduler.wait_for_terminal(1, timeout_s=10.0)

        status, body = _get(base + f"/jobs?state=done")
        rows = json.loads(body)
        assert [r["msg_id"] for r in rows] == [msg_id]
        assert rows[0]["ds_id"] == "api1" and rows[0]["attempts"] == 1

        status, body = _get(base + "/metrics")
        assert status == 200
        assert 'sm_jobs_total{state="done"} 1' in body.decode()
        assert 'sm_queue_depth{state="done"} 1' in body.decode()

        # bad submit → 400, unknown route → 404
        bad = urllib.request.Request(base + "/submit", method="POST",
                                     data=b"{not json")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(bad, timeout=5.0)
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(base + "/nope", timeout=5.0)
        assert e.value.code == 404
    finally:
        service.shutdown()
    # after shutdown the API socket is closed
    with pytest.raises(OSError):
        _get(f"http://{host}:{port}/healthz")


def test_serve_cli_smoke(tmp_path, capsys):
    """`sm-tpu serve` end to end with a real (tiny) SearchJob through the
    service scheduler — the CPU-exercisable service-mode path."""
    from sm_distributed_tpu.engine.cli import main
    from sm_distributed_tpu.io.fixtures import generate_synthetic_dataset

    path, truth = generate_synthetic_dataset(
        tmp_path / "ds", nrows=8, ncols=8, formulas=None,
        present_fraction=0.5, noise_peaks=30, seed=11)
    sm_json = tmp_path / "sm.json"
    sm_json.write_text(json.dumps({
        "backend": "numpy_ref",
        "fdr": {"decoy_sample_size": 2, "seed": 1},
        "storage": {"results_dir": str(tmp_path / "res")},
        "work_dir": str(tmp_path / "work"),
        "service": {"workers": 2, "poll_interval_s": 0.02,
                    "backoff_base_s": 0.05, "http_port": 0},
    }))
    pub = QueuePublisher(tmp_path / "q")
    pub.publish({"ds_id": "srv1", "input_path": str(path),
                 "formulas": truth.formulas[:3],
                 "ds_config": {"isotope_generation": {"adducts": ["+H"]}}})
    pub.publish({"ds_id": "srv_bad", "input_path": "/nope.imzML",
                 "service": {"max_attempts": 2}})
    rc = main(["serve", str(tmp_path / "q"), "--sm-config", str(sm_json),
               "--max-jobs", "2"])
    assert rc == 0
    root = tmp_path / "q" / "sm_annotate"
    assert len(list(root.glob("done/*.json"))) == 1
    assert len(list(root.glob("failed/*.json"))) == 1
    assert not list(root.glob("running/*"))
    dl = json.loads(next(iter(root.glob("failed/*.json"))).read_text())
    assert dl["attempts"] == 2      # the retry policy ran a real SearchJob
    from sm_distributed_tpu.engine.storage import JobLedger

    assert (JobLedger(tmp_path / "res").jobs("srv1").status == "FINISHED").all()
