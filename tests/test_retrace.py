"""Retrace tracer + compile-surface registry tests (ISSUE 12).

The tracer's contract: every XLA compilation is attributed to the repo
call site that dispatched it, with its abstract signature — so a
deliberately UNBUCKETED toy jit fn shows one signature per distinct input
shape at THIS file's call site, while its bucketed equivalent (inputs
padded to one static shape) shows exactly one.  That pair is the
miniature of the whole cold-start argument (ROADMAP item 1): bucketing is
what turns an unbounded signature family into a closed set, and the
tracer is what makes the difference observable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sm_distributed_tpu.analysis import retrace, surface
from sm_distributed_tpu.service.metrics import MetricsRegistry

SITE_FILE = "tests/test_retrace.py"


@pytest.fixture
def tracer():
    m = MetricsRegistry()
    retrace.enable(metrics=m)
    retrace.reset()
    yield m
    retrace.disable()
    retrace.reset()


def _my_sites(snap):
    return {s: e for s, e in snap["sites"].items()
            if s.startswith(SITE_FILE)}


def _pad16(x: np.ndarray) -> np.ndarray:
    out = np.zeros(16, dtype=np.float32)
    out[: x.size] = x
    return out


def test_unbucketed_fn_mints_one_signature_per_shape(tracer):
    f = jax.jit(lambda x: x * 2.0)
    sizes = range(3, 8)
    for n in sizes:
        f(jnp.ones(n, jnp.float32))
    snap = retrace.snapshot()
    mine = _my_sites(snap)
    # attribution: the compiles land on THIS test function's site
    assert any(s.endswith(":test_unbucketed_fn_mints_one_signature_per_shape")
               for s in mine), sorted(snap["sites"])
    site, ent = next(iter(mine.items()))
    lam = [s for s in ent["signatures"] if s.startswith("<lambda>")]
    assert len(lam) == len(list(sizes)), lam   # one signature per shape
    assert ent["events"] >= len(lam)


def test_bucketed_equivalent_compiles_exactly_once(tracer):
    g = jax.jit(lambda x: x * 2.0)
    for n in range(3, 8):
        g(jnp.asarray(_pad16(np.ones(n, np.float32))))
    snap = retrace.snapshot()
    mine = _my_sites(snap)
    assert mine, sorted(snap["sites"])
    (_site, ent), = mine.items()
    lam = [s for s in ent["signatures"] if s.startswith("<lambda>")]
    assert len(lam) == 1, lam                  # bucketing closes the set
    assert "float32[16]" in lam[0]


def test_events_grow_but_signatures_close_on_rejit(tracer):
    """A NEW jit wrapper of the same fn may recompile (fresh executable)
    but must not mint a new (site, signature) pair — the census's
    closed-set check keys on exactly this."""
    def go(fn):
        return fn(jnp.ones(4, jnp.float32))

    go(jax.jit(lambda x: x + 1.0))
    first = retrace.snapshot()
    go(jax.jit(lambda x: x + 1.0))
    second = retrace.snapshot()

    def sigset(snap):
        return {(s, sig) for s, e in snap["sites"].items()
                for sig in e["signatures"] if s.startswith(SITE_FILE)}

    assert sigset(second) == sigset(first)
    assert second["events_total"] >= first["events_total"]


def test_metrics_and_disable(tracer):
    f = jax.jit(lambda x: x - 1.0)
    f(jnp.ones(5, jnp.float32))
    text = tracer.expose()
    assert "sm_compile_events_total{" in text
    assert "sm_compile_signatures{" in text
    snap = retrace.disable()
    assert snap["events_total"] >= 1
    # de-activated: further compiles are not recorded
    before = retrace.snapshot()["events_total"]
    jax.jit(lambda x: x / 2.0)(jnp.ones(6, jnp.float32))
    assert retrace.snapshot()["events_total"] == before
    retrace.enable()                           # restore for the fixture


def test_compile_trace_event_emitted(tracer):
    from sm_distributed_tpu.utils import tracing

    tracing.configure(enabled=True, ring_size=64)
    ctx = tracing.new_trace(job_id="j1")
    with tracing.attach(ctx):
        with tracing.span("score"):
            jax.jit(lambda x: x * 3.0)(jnp.ones(7, jnp.float32))
    events = [r for r in tracing.flight_recorder.recent(64)
              if r.get("name") == "compile"]
    assert events, "no compile event reached the flight recorder"
    ev = events[-1]
    assert ev["attrs"]["site"].startswith(SITE_FILE)
    assert "signature" in ev["attrs"] and "dur_s" in ev["attrs"]


def test_duration_buckets_accumulate(tracer):
    """Warm-start attribution (ISSUE 18): a fresh compile lands nonzero
    jaxpr-trace and backend-compile seconds in the census duration
    buckets, and reset() zeroes them."""
    zero = retrace.snapshot()["durations"]
    assert set(zero) == {"trace_s", "lower_s", "cache_load_s",
                         "backend_compile_s"}
    assert all(v == 0.0 for v in zero.values())
    jax.jit(lambda x: x * 5.0)(jnp.ones(9, jnp.float32))
    dur = retrace.snapshot()["durations"]
    assert dur["trace_s"] > 0.0
    assert dur["backend_compile_s"] > 0.0
    retrace.reset()
    assert all(v == 0.0
               for v in retrace.snapshot()["durations"].values())


# ------------------------------------------------------ surface registry
def test_compile_surface_registers_and_validates():
    entries = {"fn": "statics=none; buckets=one shape"}
    got = surface.compile_surface("tests.fake_mod", entries)
    assert got == entries
    assert surface.registered()["tests.fake_mod"] == entries
    with pytest.raises(ValueError):
        surface.compile_surface("tests.bad_mod", {"fn": "no grammar here"})


def test_surface_path_mapping():
    assert surface.module_for_path(
        "sm_distributed_tpu/models/msm_jax.py"
    ) == "sm_distributed_tpu.models.msm_jax"
    import sm_distributed_tpu.models.msm_jax  # noqa: F401 — registers
    assert surface.is_registered_path("sm_distributed_tpu/models/msm_jax.py")
    assert not surface.is_registered_path("scripts/load_sweep.py")


def test_hot_backends_declare_their_surface():
    """Every module the census depends on registers on import."""
    import sm_distributed_tpu.models.msm_jax  # noqa: F401
    import sm_distributed_tpu.ops.isocalc_jax  # noqa: F401
    import sm_distributed_tpu.parallel.sharded  # noqa: F401

    reg = surface.registered()
    for mod in ("sm_distributed_tpu.models.msm_jax",
                "sm_distributed_tpu.parallel.sharded",
                "sm_distributed_tpu.ops.isocalc_jax"):
        assert mod in reg, sorted(reg)
        for site, policy in reg[mod].items():
            assert "statics=" in policy and "buckets=" in policy, (mod, site)
