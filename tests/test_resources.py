"""Resource-exhaustion survival (ISSUE 10): the enospc failpoint action
over every governed write seam, the disk-budget governor's degrade order,
the bounded-retention GC, and the 507 admission shed."""

from __future__ import annotations

import errno
import json
import os
import time
from pathlib import Path

import numpy as np
import pandas as pd
import pytest

from sm_distributed_tpu.engine.daemon import QueueConsumer, QueuePublisher
from sm_distributed_tpu.service import resources as res_mod
from sm_distributed_tpu.service.resources import (
    ResourceBudgetError,
    ResourceGovernor,
)
from sm_distributed_tpu.utils import failpoints, tracing
from sm_distributed_tpu.utils.config import (
    AdmissionConfig,
    ResourcesConfig,
    TracingConfig,
)


@pytest.fixture(autouse=True)
def _reset():
    failpoints.reset()
    res_mod.set_governor(None)
    tracing.set_file_gate(None)
    yield
    failpoints.reset()
    res_mod.set_governor(None)
    tracing.set_file_gate(None)


# -------------------------------------------------- the enospc action itself
def test_enospc_action_parses_and_rejects_args():
    spec = failpoints.parse_failpoints("x.y=enospc@2")
    assert spec["x.y"].action == "enospc" and spec["x.y"].nth == 2
    with pytest.raises(ValueError, match="takes no argument"):
        failpoints.parse_failpoints("x.y=enospc:9")


def test_enospc_raises_oserror_with_enospc_errno(tmp_path):
    failpoints.configure("spool.publish_rename=enospc@1")
    pub = QueuePublisher(tmp_path)
    with pytest.raises(OSError) as ei:
        pub.publish({"ds_id": "d", "input_path": "x", "msg_id": "m1"})
    assert ei.value.errno == errno.ENOSPC
    assert "No space left on device" in str(ei.value)


# ------------------------------------------------ ENOSPC at every governed seam
def test_enospc_at_publish_recovers_clean(tmp_path):
    """Publish fails mid-flight; the orphan tmp is swept and the client's
    republish lands — zero debris."""
    failpoints.configure("spool.publish_rename=enospc@1")
    pub = QueuePublisher(tmp_path)
    with pytest.raises(OSError):
        pub.publish({"ds_id": "d", "input_path": "x", "msg_id": "m1"})
    root = pub.root
    assert list((root / "pending").glob(".*.tmp"))  # the torn-publish debris
    assert QueueConsumer(tmp_path, callback=None).sweep_orphans(
        max_age_s=0.0) == 1
    dst = pub.publish({"ds_id": "d", "input_path": "x", "msg_id": "m1"})
    assert dst.exists()
    assert not list((root / "pending").glob(".*.tmp"))


def test_enospc_at_checkpoint_shard_then_rerun(tmp_path):
    from sm_distributed_tpu.models.msm_basic import SearchCheckpoint

    ckpt = SearchCheckpoint(tmp_path, "fp")
    metrics = np.arange(8.0).reshape(2, 4)
    ranges = [(0, 2)]
    failpoints.configure("ckpt.shard_write=enospc@1")
    with pytest.raises(OSError) as ei:
        ckpt.save(metrics, 0, 1, ranges)
    assert ei.value.errno == errno.ENOSPC
    # the retry (failpoint spent) overwrites the same tmp name and commits
    ckpt.save(metrics, 0, 1, ranges)
    restored = np.zeros_like(metrics)
    assert ckpt.load(restored, 1, ranges) == 1
    np.testing.assert_array_equal(restored, metrics)
    ckpt.finalize()
    assert not list(tmp_path.glob("*.npz"))


def test_enospc_at_results_store_then_rerun(tmp_path):
    from sm_distributed_tpu.engine.storage import JobLedger, SearchResultsStore
    from sm_distributed_tpu.models.msm_basic import SearchResultsBundle

    ledger = JobLedger(tmp_path / "results")
    store = SearchResultsStore(ledger, store_images=False)
    ann = pd.DataFrame({"sf": ["H2O"], "adduct": ["+H"], "msm": [0.5],
                        "fdr": [0.1], "fdr_level": [0.1], "chaos": [0.9],
                        "spatial": [0.8], "spectral": [0.7]})
    allm = ann.assign(is_target=True)[
        ["sf", "adduct", "is_target", "chaos", "spatial", "spectral", "msm"]]
    bundle = SearchResultsBundle(annotations=ann, all_metrics=allm)
    job = ledger.start_job("ds1")
    failpoints.configure("storage.results_rename=enospc@1")
    with pytest.raises(OSError):
        store.store("ds1", job, bundle)
    # rerun sweeps the stale tmps and commits
    d = store.store("ds1", job, bundle)
    assert (d / "annotations.parquet").exists()
    assert not list(d.glob("*.tmp"))
    ledger.close()


def test_enospc_at_isocalc_shard_then_rerun(tmp_path):
    from sm_distributed_tpu.ops.isocalc import IsocalcWrapper
    from sm_distributed_tpu.utils.config import IsotopeGenerationConfig

    w = IsocalcWrapper(IsotopeGenerationConfig(), cache_dir=str(tmp_path))
    entries = {"H2O|+H": (np.array([18.01]), np.array([1.0]))}
    shard = tmp_path / "theor_peaks_test_c00000.npz"
    failpoints.configure("isocalc.shard_save=enospc@1")
    with pytest.raises(OSError):
        w._write_shard(shard, entries)
    assert not shard.exists()
    w._write_shard(shard, entries)      # failpoint spent: commits
    assert shard.exists()
    loaded = w._load_shard(shard)
    np.testing.assert_allclose(loaded["H2O|+H"][0], [18.01])


def test_enospc_at_trace_append_never_fails_the_pipeline(tmp_path):
    failpoints.configure("trace.append=enospc@1")
    ctx = tracing.new_trace(job_id="j1", trace_dir=tmp_path)
    with tracing.attach(ctx):
        with tracing.span("unlucky"):    # first append: injected ENOSPC
            pass
        with tracing.span("lucky"):      # second append lands
            pass
    tracing.close_files()
    records = tracing.read_trace(tracing.trace_path(tmp_path, ctx.trace_id))
    names = [r["name"] for r in records]
    assert "lucky" in names and "unlucky" not in names
    # the dropped span still reached the flight recorder
    assert any(r.get("name") == "unlucky"
               for r in tracing.flight_recorder.recent(64))


# ------------------------------------------------------- governor: degrade order
def _governor(tmp_path, **cfg_over) -> ResourceGovernor:
    cfg = ResourcesConfig(**{
        "disk_budget_bytes": 1_000_000, "trace_floor_bytes": 600_000,
        "cache_floor_bytes": 400_000, "read_cache_floor_bytes": 300_000,
        "submit_floor_bytes": 200_000,
        **cfg_over})
    work = tmp_path / "work"
    work.mkdir(exist_ok=True)
    return ResourceGovernor(cfg, work_dir=work,
                            trace_dir=tmp_path / "work" / "traces",
                            queue_root=tmp_path / "queue")


def _fill(tmp_path, total_bytes: int) -> None:
    (tmp_path / "work" / "filler.bin").write_bytes(b"\0" * total_bytes)


def test_degrade_order_traces_then_cache_then_submits(tmp_path):
    g = _governor(tmp_path)
    assert g.level() == res_mod.LEVEL_OK
    assert g.trace_gate() and g.allow_cache() and not g.submits_shed()

    _fill(tmp_path, 500_000)            # remaining 500k < 600k trace floor
    g.rescan_usage()
    assert g.level() == res_mod.LEVEL_NO_TRACES
    assert not g.trace_gate() and g.allow_cache() and not g.submits_shed()

    _fill(tmp_path, 650_000)            # remaining 350k < 400k cache floor
    g.rescan_usage()
    assert g.level() == res_mod.LEVEL_NO_CACHE
    assert not g.trace_gate() and not g.allow_cache()
    assert g.allow_read_cache_fill() and not g.submits_shed()

    _fill(tmp_path, 750_000)            # remaining 250k < 300k read floor
    g.rescan_usage()
    assert g.level() == res_mod.LEVEL_NO_READ_CACHE
    assert not g.allow_read_cache_fill() and not g.submits_shed()

    _fill(tmp_path, 900_000)            # remaining 100k < 200k submit floor
    g.rescan_usage()
    assert g.level() == res_mod.LEVEL_SHED_SUBMITS
    assert g.submits_shed()

    (tmp_path / "work" / "filler.bin").unlink()
    g.rescan_usage()
    assert g.level() == res_mod.LEVEL_OK
    snap = g.snapshot()
    assert snap["degraded_writes"]["trace"] >= 2
    assert snap["degraded_writes"]["cache"] >= 1
    assert snap["degraded_writes"]["read_cache"] >= 1


def test_preflight_denies_at_the_floor_and_tracks_pending(tmp_path):
    g = _governor(tmp_path)
    g.preflight("seamA", 300_000)       # ok; pending advances
    g.preflight("seamA", 300_000)
    with pytest.raises(ResourceBudgetError) as ei:
        g.preflight("seamB", 500_000)   # 400k remaining < 500k estimate
    assert ei.value.errno == errno.ENOSPC and ei.value.seam == "seamB"
    snap = g.snapshot()
    assert snap["pending_bytes"] == 600_000
    assert snap["denied_writes"] == {"seamB": 1}


def test_min_free_constraint_uses_statvfs(tmp_path):
    g = _governor(tmp_path, disk_budget_bytes=0, min_free_bytes=2**62)
    assert g.submits_shed()             # no real disk has 4 EiB free
    with pytest.raises(ResourceBudgetError):
        g.preflight("any", 1)
    g2 = _governor(tmp_path, disk_budget_bytes=0, min_free_bytes=1)
    g2.preflight("any", 1)              # any sane test box clears 1 byte


def test_disabled_governor_is_inert(tmp_path):
    g = _governor(tmp_path, disk_budget_bytes=0, min_free_bytes=0)
    assert not g.enabled
    g.preflight("x", 2**62)             # nothing to enforce
    assert g.trace_gate() and g.allow_cache() and not g.submits_shed()


def test_module_gates_noop_without_governor():
    res_mod.preflight("x", 2**62)
    assert res_mod.allow_cache()


# -------------------------------------------------------------- retention GC
def _age(path: Path, seconds: float = 3600.0) -> None:
    old = time.time() - seconds
    os.utime(path, (old, old))


def test_gc_retention_classes_and_shard_scoping(tmp_path):
    g = _governor(tmp_path, done_retention_age_s=10.0,
                  failed_retention_age_s=10.0,
                  registry_retention_age_s=10.0)
    q = tmp_path / "queue"
    for sub in ("done", "failed", "quarantine", "replicas"):
        (q / sub).mkdir(parents=True, exist_ok=True)
    aged_owned = q / "done" / "old_owned.json"
    aged_peer = q / "done" / "old_peer.json"
    fresh = q / "done" / "fresh.json"
    dead_letter = q / "failed" / "old_dl.json"
    quarantined = q / "quarantine" / "old_q.json"
    dead_replica = q / "replicas" / "r9.json"
    for p in (aged_owned, aged_peer, fresh, dead_letter, quarantined,
              dead_replica):
        p.write_text(json.dumps({"x": 1}))
    for p in (aged_owned, aged_peer, dead_letter, quarantined, dead_replica):
        _age(p)
    g.gc_tick(owns_msg=lambda mid: mid != "old_peer")
    assert not aged_owned.exists()
    assert aged_peer.exists()           # a peer's shard — not ours to reap
    assert fresh.exists()               # age gate
    assert not dead_letter.exists() and not quarantined.exists()
    assert not dead_replica.exists()
    snap = g.snapshot()
    assert snap["gc"]["classes"]["done"]["files"] == 1
    assert snap["gc"]["classes"]["failed"]["files"] == 2
    assert snap["gc"]["classes"]["registry"]["files"] == 1


def test_gc_trace_retention_age_and_size_cap(tmp_path):
    g = _governor(tmp_path)
    g.tracing_cfg = TracingConfig(retention_age_s=10.0,
                                  retention_max_bytes=1500)
    traces = tmp_path / "work" / "traces"
    traces.mkdir(parents=True, exist_ok=True)
    aged = traces / "aged.jsonl"
    aged.write_text("x" * 100)
    _age(aged)
    sized = []
    for i in range(4):                  # 4 x 1000 B, oldest first past cap
        p = traces / f"t{i}.jsonl"
        p.write_text("y" * 1000)
        _age(p, seconds=5 - i)          # within age retention, distinct mtimes
        sized.append(p)
    g.gc_tick()
    assert not aged.exists()
    survivors = sorted(p.name for p in traces.glob("*.jsonl"))
    assert survivors == ["t3.jsonl"]    # 1500 B cap keeps only the newest
    assert g.snapshot()["gc"]["classes"]["traces"]["files"] == 4


def test_gc_cache_size_cap_oldest_shards_first(tmp_path):
    g = _governor(tmp_path, cache_retention_max_bytes=2500)
    cache = tmp_path / "cache"
    cache.mkdir()
    g.cache_dir = cache
    shards = []
    for i in range(4):
        p = cache / f"theor_peaks_k_{i}.npz"
        p.write_bytes(b"z" * 1000)
        _age(p, seconds=40 - i)
        shards.append(p)
    stale_tmp = cache / "tmp_deadbeef.npz"
    stale_tmp.write_bytes(b"t")
    _age(stale_tmp)
    g.gc_tick()
    assert not stale_tmp.exists()
    left = sorted(p.name for p in cache.glob("theor_peaks_*.npz"))
    assert left == ["theor_peaks_k_2.npz", "theor_peaks_k_3.npz"]


# --------------------------------------------------------- 507 admission shed
def test_admission_sheds_507_when_disk_exhausted(tmp_path):
    from sm_distributed_tpu.service.admission import AdmissionController

    g = _governor(tmp_path)
    _fill(tmp_path, 900_000)
    g.rescan_usage()
    res_mod.set_governor(g)
    adm = AdmissionController(AdmissionConfig(retry_after_s=2.5))
    d = adm.try_admit("tenant1")
    assert not d.accepted and d.status == 507
    assert d.reason == "disk_exhausted" and d.retry_after_s == 2.5
    assert "retry_after_s" in d.body() and "error" in d.body()
    # space freed -> admissions resume
    (tmp_path / "work" / "filler.bin").unlink()
    g.rescan_usage()
    assert adm.try_admit("tenant1").accepted


# ------------------------------------------------------ tracing gate plumbing
def test_trace_file_gate_drops_file_writes_not_ring(tmp_path):
    g = _governor(tmp_path)
    _fill(tmp_path, 500_000)            # level 1: traces dropped
    g.rescan_usage()
    tracing.set_file_gate(g.trace_gate)
    ctx = tracing.new_trace(job_id="j", trace_dir=tmp_path / "traces")
    with tracing.attach(ctx), tracing.span("gated"):
        pass
    assert not tracing.trace_path(tmp_path / "traces",
                                  ctx.trace_id).exists()
    assert any(r.get("name") == "gated"
               for r in tracing.flight_recorder.recent(64))
    assert g.snapshot()["degraded_writes"]["trace"] >= 1
