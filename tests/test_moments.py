"""Fused image-moments kernel (ops/moments_pallas.py) parity.

The kernel feeds the spatial/spectral metrics and chaos thresholds from
ONE streaming pass; its contract is the cross-backend one: sums/vmax/nn
track the f64 reference to f32 rounding, and the ASSEMBLED correlation
(what actually lands in MSM) stays within 1e-6 of the f64 oracle — raw
centered sums are compared loosely (their error divides out against the
norms).
"""

import numpy as np
import pytest

from sm_distributed_tpu.ops.moments_pallas import (
    batch_moments_jnp,
    batch_moments_pallas,
    moments_fit,
)


def _f64_reference(img):
    i64 = img.astype(np.float64)
    sums = i64.sum(-1)
    cent = i64 - i64.mean(-1, keepdims=True)
    normsq = (cent * cent).sum(-1)
    dots = (cent[:, 0:1, :] * cent).sum(-1)
    vmax = i64[:, 0, :].max(-1)
    nn = (i64[:, 0, :] > 0).sum(-1)
    return sums, normsq, dots, vmax, nn


def _corr(normsq, dots):
    normsq = np.asarray(normsq, np.float64)
    dots = np.asarray(dots, np.float64)
    denom = np.sqrt(np.maximum(normsq[:, 0:1] * normsq, 0))
    return np.where(denom > 0, dots / np.maximum(denom, 1e-30), 0.0)


@pytest.mark.parametrize("shape", [(8, 4, 4096), (3, 4, 8192), (5, 2, 2048)])
def test_moments_interpret_matches_f64(shape):
    rng = np.random.default_rng(7)
    n, k, p = shape
    img = (rng.integers(0, 1 << 20, shape).astype(np.float32)
           * (rng.random(shape) < 0.3))
    got = batch_moments_pallas(np.asarray(img), interpret=True)
    ref = _f64_reference(img)
    np.testing.assert_allclose(got[0], ref[0], rtol=1e-6)        # sums
    np.testing.assert_array_equal(np.asarray(got[3]), ref[3])    # vmax exact
    np.testing.assert_array_equal(np.asarray(got[4]), ref[4])    # count exact
    # assembled correlation within the cross-backend contract
    np.testing.assert_allclose(
        _corr(got[1], got[2]), _corr(ref[1], ref[2]), atol=1e-6, rtol=0)


def test_moments_jnp_fallback_matches_f64():
    rng = np.random.default_rng(3)
    shape = (6, 4, 4096)
    img = (rng.integers(0, 1 << 20, shape).astype(np.float32)
           * (rng.random(shape) < 0.4))
    got = batch_moments_jnp(np.asarray(img))
    ref = _f64_reference(img)
    np.testing.assert_allclose(got[0], ref[0], rtol=1e-6)
    # the fallback is the pre-existing XLA formula (einsum over a
    # materialized centered block); on this deliberately harsh synthetic
    # (dense 40%, values to 2**20) its single-tree f32 reduce carries a
    # few-1e-6 — real ion images sit well inside 1e-6 (backend parity
    # tests); the Pallas kernel's tiled accumulation is tighter (above)
    np.testing.assert_allclose(
        _corr(got[1], got[2]), _corr(ref[1], ref[2]), atol=5e-6, rtol=0)


def test_moments_fit_budget():
    assert moments_fit(4, 262144)           # DESI 512x512
    assert not moments_fit(4, 1024 * 1024)  # 1024x1024 -> fallback
    assert not moments_fit(4, 100)          # non-128-multiple -> fallback


def test_all_zero_and_single_pixel_rows():
    """Empty images (padding ions) and constant rows must not NaN."""
    img = np.zeros((2, 4, 2048), np.float32)
    img[1, 0, 5] = 3.0
    got = batch_moments_pallas(np.asarray(img), interpret=True)
    sums, normsq, dots, vmax, nn = [np.asarray(x) for x in got]
    assert np.all(np.isfinite(sums)) and np.all(np.isfinite(normsq))
    assert vmax[0] == 0.0 and vmax[1] == 3.0
    assert nn[0] == 0 and nn[1] == 1
