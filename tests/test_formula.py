"""Unit tests for sum-formula/adduct parsing (reference analog: the parsing
half of tests/test_isocalc_wrapper.py [U], SURVEY.md §4 pure-unit row)."""

import pytest

from sm_distributed_tpu.ops import elements
from sm_distributed_tpu.ops.formula import (
    FormulaError,
    apply_adduct,
    format_formula,
    ion_mz,
    monoisotopic_mass,
    parse_adduct,
    parse_formula,
)


def test_parse_simple():
    assert parse_formula("C6H12O6") == {"C": 6, "H": 12, "O": 6}
    assert parse_formula("H2O") == {"H": 2, "O": 1}
    assert parse_formula("CH4") == {"C": 1, "H": 4}
    assert parse_formula("NaCl") == {"Na": 1, "Cl": 1}


def test_parse_two_letter_elements():
    assert parse_formula("C27H46ClNO2") == {"C": 27, "H": 46, "Cl": 1, "N": 1, "O": 2}
    assert parse_formula("Se") == {"Se": 1}


def test_parse_parentheses():
    assert parse_formula("Ca(NO3)2") == {"Ca": 1, "N": 2, "O": 6}
    assert parse_formula("(CH3)3N") == {"C": 3, "H": 9, "N": 1}


def test_parse_errors():
    with pytest.raises(FormulaError):
        parse_formula("")
    with pytest.raises(FormulaError):
        parse_formula("C6H12O6)")
    with pytest.raises(FormulaError):
        parse_formula("(C6H12O6")
    with pytest.raises(FormulaError):
        parse_formula("Xx2")  # unknown element
    with pytest.raises(FormulaError):
        parse_formula("c6")  # lowercase start


def test_adducts():
    assert parse_adduct("+H") == (1, {"H": 1})
    assert parse_adduct("-H") == (-1, {"H": 1})
    assert apply_adduct({"C": 6, "H": 12, "O": 6}, "+Na") == {"C": 6, "H": 12, "O": 6, "Na": 1}
    assert apply_adduct({"C": 6, "H": 12, "O": 6}, "-H") == {"C": 6, "H": 11, "O": 6}
    with pytest.raises(FormulaError):
        apply_adduct({"C": 1, "H": 4}, "-O")
    with pytest.raises(FormulaError):
        parse_adduct("H")


def test_monoisotopic_masses():
    # Hand-checked exact masses.
    assert monoisotopic_mass(parse_formula("H2O")) == pytest.approx(18.0105646863, abs=1e-6)
    assert monoisotopic_mass(parse_formula("C6H12O6")) == pytest.approx(180.0633881, abs=1e-5)
    assert monoisotopic_mass(parse_formula("CH4")) == pytest.approx(16.0313001, abs=1e-6)


def test_ion_mz_accounts_for_electron():
    counts = apply_adduct(parse_formula("C6H12O6"), "+H")
    mz = ion_mz(counts, charge=1)
    # [M+H]+ of glucose = 181.070665 (M + 1.007276 proton mass)
    assert mz == pytest.approx(181.070665, abs=1e-5)
    neutral = monoisotopic_mass(counts)
    assert mz < neutral  # electron removed for positive ion


def test_format_formula_hill_order():
    assert format_formula({"O": 6, "C": 6, "H": 12}) == "C6H12O6"
    assert format_formula({"Cl": 1, "Na": 1}) == "ClNa"
    assert format_formula({"H": 1}) == "H"
    # carbon-free: strictly alphabetical (Hill), H not promoted
    assert format_formula({"H": 1, "Cl": 1}) == "ClH"


def test_zero_counts_rejected():
    with pytest.raises(FormulaError):
        parse_formula("C0")
    with pytest.raises(FormulaError):
        parse_formula("H(CO3)0")


def test_config_tuple_coercion():
    from sm_distributed_tpu.utils.config import DSConfig

    ds = DSConfig.from_dict({"isotope_generation": {"adducts": ["+H", "+Na"]}})
    assert ds.isotope_generation.adducts == ("+H", "+Na")
    hash(ds.isotope_generation)  # frozen config stays hashable


def test_shipped_config_templates_load():
    """conf/*.template must parse to pure-default configs (reference ships
    conf/config.json.template [U], SURVEY #20); ``__doc__`` comment keys are
    skipped by validation."""
    from pathlib import Path

    from sm_distributed_tpu.utils.config import DSConfig, SMConfig

    conf = Path(__file__).parent.parent / "conf"
    import json

    sm = SMConfig.from_dict(
        json.loads((conf / "config.json.template").read_text()))
    assert sm == SMConfig()
    ds = DSConfig.from_dict(
        json.loads((conf / "ds_config.json.template").read_text()))
    assert ds == DSConfig()


def test_isotope_table_sane():
    # Abundances sum to ~1, masses ascending, for every element.
    for el, isos in elements.ISOTOPES.items():
        total = sum(a for _, a in isos)
        assert abs(total - 1.0) < 5e-3, f"{el} abundance sum {total}"
        masses = [m for m, _ in isos]
        assert masses == sorted(masses), f"{el} masses not ascending"


def test_config_roundtrip(tmp_path):
    from sm_distributed_tpu.utils.config import DSConfig, SMConfig

    cfg = SMConfig.get_conf()
    assert cfg.backend == "jax_tpu"
    assert cfg.fdr.decoy_sample_size == 20

    p = tmp_path / "conf.json"
    p.write_text('{"backend": "numpy_ref", "fdr": {"decoy_sample_size": 5}}')
    cfg2 = SMConfig.set_path(p)
    assert cfg2.backend == "numpy_ref"
    assert cfg2.fdr.decoy_sample_size == 5
    assert SMConfig.get_conf() is cfg2

    ds = DSConfig.from_dict(
        {
            "database": {"name": "HMDB", "version": "4"},
            "isotope_generation": {"adducts": ["+H"], "charge": 1},
            "image_generation": {"ppm": 2.0},
        }
    )
    assert ds.image_generation.nlevels == 30
    assert ds.image_generation.ppm == 2.0
    assert ds.isotope_generation.isocalc_pts_per_mz == 10000


def test_config_rejects_unknown_keys_and_bad_values(tmp_path):
    from sm_distributed_tpu.utils.config import DSConfig, SMConfig

    with pytest.raises(ValueError):
        SMConfig.from_dict({"backennd": "jax_tpu"})
    with pytest.raises(ValueError):
        SMConfig.from_dict({"backend": "spark"})
    with pytest.raises(ValueError):
        DSConfig.from_dict({"image_generation": {"ppm": -1}})
    with pytest.raises(ValueError):
        DSConfig.from_dict({"isotope_generation": {"charge": 0}})
