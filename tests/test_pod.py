"""Pod-scale runtime tests (ISSUE 17): the managed ``parallel/distributed``
runtime (retry ladder, shutdown/reset latch, process identity), the
cross-process global device order, host-range attribution for ragged pools,
whole-host eviction/return on the health tracker, and the bit-identity of
FDR-ranked annotations between a plain single-process run and the same job
under the simulated 2-process pod contract.

The REAL 2-OS-process coordinator handshake is covered by the slow test in
tests/test_distributed.py; everything here runs in-process at tier-1 speed
via the ``SM_DIST_SIMULATE`` seam (the same one scripts/host_chaos.py's
single-box "hosts" use)."""

import logging
import random
import types

import pytest

from sm_distributed_tpu.utils.config import ParallelConfig
from sm_distributed_tpu.utils.logger import LOGGER_NAME

POD_ENV = {
    "SM_DIST_SIMULATE": "1",
    "SM_COORDINATOR": "127.0.0.1:12399",
    "SM_NUM_PROCESSES": "2",
    "SM_PROCESS_ID": "0",
}


def _pod_env(monkeypatch, **extra):
    for k, v in {**POD_ENV, **extra}.items():
        monkeypatch.setenv(k, v)


# ---------------------------------------------------------------------------
# managed runtime: retry ladder, shutdown/reset latch, identity (satellite 2)
# ---------------------------------------------------------------------------

def test_simulated_init_retries_then_shutdown_resets_latch(monkeypatch):
    """The coordinator launch race: attempt 1 raises (injected), the backoff
    ladder retries, the runtime comes up, and ``shutdown()`` clears the
    idempotence latch so a second init starts clean."""
    from sm_distributed_tpu.parallel import distributed
    from sm_distributed_tpu.utils import failpoints

    _pod_env(monkeypatch)
    cfg = ParallelConfig(init_retries=5, init_backoff_s=0.0)
    base = failpoints.recovery_counts().get("dist.init_retry", 0)
    failpoints.configure("dist.initialize=raise:ConnectionError@1")
    try:
        assert distributed.maybe_initialize_distributed(cfg) is True
        assert distributed.is_initialized()
        # the retried-then-successful init reported itself
        assert failpoints.recovery_counts()["dist.init_retry"] == base + 1
        # idempotent while up: no second init attempt (the failpoint would
        # not fire again anyway — @1 already consumed — but the latch
        # short-circuits before the ladder entirely)
        assert distributed.maybe_initialize_distributed(cfg) is True

        distributed.shutdown()
        assert not distributed.is_initialized()
        # the latch really reset: a fresh init runs the ladder again
        assert distributed.maybe_initialize_distributed(cfg) is True
        assert distributed.is_initialized()
    finally:
        failpoints.configure(None)
        distributed.shutdown()
    assert not distributed.is_initialized()


def test_init_retries_exhausted_raises_and_leaves_latch_clear(monkeypatch):
    from sm_distributed_tpu.parallel import distributed
    from sm_distributed_tpu.utils import failpoints

    _pod_env(monkeypatch)
    cfg = ParallelConfig(init_retries=2, init_backoff_s=0.0)
    failpoints.configure("dist.initialize=raise:ConnectionError")  # every hit
    try:
        with pytest.raises(ConnectionError):
            distributed.maybe_initialize_distributed(cfg)
        assert not distributed.is_initialized()
    finally:
        failpoints.configure(None)
        distributed.shutdown()


def test_process_identity_env_contract(monkeypatch):
    from sm_distributed_tpu.parallel.distributed import process_identity

    monkeypatch.setenv("SM_PROCESS_ID", "3")
    monkeypatch.setenv("SM_HOST_NAME", "hx")
    assert process_identity() == {"process_id": 3, "host": "hx"}

    # unparseable SM_PROCESS_ID degrades to 0, never raises
    monkeypatch.setenv("SM_PROCESS_ID", "not-an-int")
    assert process_identity()["process_id"] == 0

    # no env, no runtime: process 0 on the real hostname
    monkeypatch.delenv("SM_PROCESS_ID")
    monkeypatch.delenv("SM_HOST_NAME")
    import socket

    ident = process_identity()
    assert ident["process_id"] == 0
    assert ident["host"] == socket.gethostname()


# ---------------------------------------------------------------------------
# cross-process global device order + host-range attribution (satellite 4)
# ---------------------------------------------------------------------------

def _fake_devices(n_proc=2, per_proc=4):
    return [types.SimpleNamespace(process_index=p, id=i)
            for p in range(n_proc) for i in range(per_proc)]


def test_global_device_order_stable_under_permuted_enumeration():
    """JAX documents no enumeration order across processes; the pool's chip
    index -> Device map must not depend on it."""
    from sm_distributed_tpu.parallel.mesh import global_device_order

    devs = _fake_devices(n_proc=3, per_proc=4)
    want = global_device_order(devs)
    for seed in range(5):
        shuffled = list(devs)
        random.Random(seed).shuffle(shuffled)
        assert global_device_order(shuffled) == want
    # host-major: each process's chips form one contiguous index run,
    # ids ascending within it
    assert [d.process_index for d in want] == [0] * 4 + [1] * 4 + [2] * 4
    for p in range(3):
        assert [d.id for d in want[p * 4:(p + 1) * 4]] == [0, 1, 2, 3]


def test_split_host_ranges_ragged_and_clamp(caplog):
    from sm_distributed_tpu.service.health import (
        host_of_ranges,
        split_host_ranges,
    )

    with caplog.at_level(logging.WARNING, logger=LOGGER_NAME):
        assert split_host_ranges(8, 2) == ((0, 4), (4, 8))
    assert not caplog.records  # rectangular pods are silent

    # ragged: the first `size % hosts` hosts absorb the extra chips — chip 6
    # lands on host 1, not the nonexistent host 2 that 7 // (7 // 2) implied
    with caplog.at_level(logging.WARNING, logger=LOGGER_NAME):
        assert split_host_ranges(7, 2) == ((0, 4), (4, 7))
    assert any("raggedly" in r.getMessage() for r in caplog.records)

    # more hosts than chips clamps to single-chip domains
    caplog.clear()
    with caplog.at_level(logging.WARNING, logger=LOGGER_NAME):
        assert split_host_ranges(3, 5) == ((0, 1), (1, 2), (2, 3))
    assert any("clamping" in r.getMessage() for r in caplog.records)

    assert host_of_ranges(((0, 2), (2, 3))) == [0, 0, 1]
    assert split_host_ranges(8, 3) == ((0, 3), (3, 6), (6, 8))


def test_host_topology_with_explicit_ranges():
    from sm_distributed_tpu.parallel.mesh import host_topology

    ranges = ((0, 4), (4, 7))               # ragged 7-chip / 2-host pool
    assert host_topology(range(7), ranges) == {0: (0, 1, 2, 3),
                                               1: (4, 5, 6)}
    assert host_topology([6], ranges) == {1: (6,)}      # the old int-division
    assert host_topology([6], 3) == {2: (6,)}           # guess got this wrong


def test_lease_spans_two_simulated_processes():
    """An 8-chip lease on a 2-host pool spans both host failure domains
    host-major; a half-pool lease is confined to one."""
    from sm_distributed_tpu.parallel.mesh import host_topology
    from sm_distributed_tpu.service.device_pool import DevicePool
    from sm_distributed_tpu.service.health import HealthTracker

    pool = DevicePool(8, hosts=2,
                      health=HealthTracker(8, hosts=2, probe_on_lease=False))
    assert pool.host_ranges == ((0, 4), (4, 8))

    wide = pool.lease(8, msg_id="span")
    assert wide.acquire(timeout=5.0)
    try:
        topo = host_topology(wide.devices, pool.host_ranges)
        assert topo == {0: (0, 1, 2, 3), 1: (4, 5, 6, 7)}
    finally:
        wide.release()

    narrow = pool.lease(4, msg_id="one-host")
    assert narrow.acquire(timeout=5.0)
    try:
        assert len(host_topology(narrow.devices, pool.host_ranges)) == 1
    finally:
        narrow.release()


def test_health_host_evict_and_return_roundtrip():
    """Whole-host eviction fences every chip of the domain in one unit;
    ``host_returned`` zeroes the re-probe cooldown so the half-open pass
    readmits immediately instead of waiting out ``reprobe_after_s``."""
    from sm_distributed_tpu.service.health import HealthTracker

    h = HealthTracker(8, hosts=2, probe_on_lease=False,
                      reprobe_after_s=60.0,
                      probe_fn=lambda c: (True, "ok"))
    evicted = h.evict_host(1, "host h1 (process 1) missed heartbeats")
    assert evicted == [4, 5, 6, 7]
    snap = h.snapshot()
    assert snap["host_evictions_total"] == 1
    assert [c["device"] for c in snap["chips"]
            if c["state"] == "quarantined"] == [4, 5, 6, 7]
    # idempotent; out-of-range host ids are refused, not crashed
    assert h.evict_host(1, "again") == []
    assert h.evict_host(7, "no such host") == []

    # cooldown (60 s) has not elapsed: nothing due yet
    assert h.reprobe_due() == []
    # ...until the host's process heartbeats again
    assert h.host_returned(1) == [4, 5, 6, 7]
    assert sorted(h.reprobe_due()) == [4, 5, 6, 7]
    assert h.snapshot()["quarantined"] == 0


# ---------------------------------------------------------------------------
# FDR-rank bit-identity: plain vs simulated 2-process pod (satellite 4)
# ---------------------------------------------------------------------------

def test_fdr_ranks_bit_identical_plain_vs_simulated_pod(
        tmp_path, monkeypatch):
    """The managed pod runtime must not perturb science: the same search on
    the spheroid-like fixture produces bit-identical FDR-ranked annotations
    whether it runs plain single-process or under the simulated 2-process
    launch contract (env + init ladder + identity stamping engaged)."""
    import pandas.testing as pdt

    from sm_distributed_tpu.io.dataset import SpectralDataset
    from sm_distributed_tpu.io.fixtures import generate_synthetic_dataset
    from sm_distributed_tpu.models.msm_basic import MSMBasicSearch
    from sm_distributed_tpu.parallel import distributed
    from sm_distributed_tpu.utils.config import DSConfig, SMConfig

    path, truth = generate_synthetic_dataset(
        tmp_path / "ds", nrows=8, ncols=8, present_fraction=0.5,
        noise_peaks=30, seed=17)
    ds = SpectralDataset.from_imzml(path)
    ds_config = DSConfig.from_dict(
        {"isotope_generation": {"adducts": ["+H"]},
         "image_generation": {"ppm": 3.0}})
    formulas = list(truth.formulas)[:8]
    sm = SMConfig.from_dict({
        "backend": "jax_tpu",
        "fdr": {"decoy_sample_size": 3, "seed": 5},
        "parallel": {"formula_batch": 8, "pixels_axis": 2,
                     "formulas_axis": 1},
    })

    plain = MSMBasicSearch(ds, formulas, ds_config, sm).search()
    assert not distributed.is_initialized()

    _pod_env(monkeypatch)
    try:
        pod = MSMBasicSearch(ds, formulas, ds_config, sm).search()
        assert distributed.is_initialized()   # the search went through init
    finally:
        distributed.shutdown()

    pdt.assert_frame_equal(pod.annotations, plain.annotations,
                           check_exact=True)
    assert list(pod.annotations["fdr"]) == list(plain.annotations["fdr"])
