"""Schema pin for bench.py's JSON report (the driver parses the one JSON
line; BENCH_r*.json is the judged table of record, so silently dropping a
field is a protocol break, not a refactor)."""

from bench import report


def _fake_inputs():
    class Obj:
        pass

    table = Obj()
    table.n_ions = 100
    ds = Obj()
    ds.n_pixels = 4096
    prep = {"table": table, "ds": ds, "isocalc_dt": 0.5}
    floor = dict(np_rate=50.0, mp_rate=50.0, n_procs=1, floor_n_ions=100,
                 floor_spread=0.1, floor_spread_mid5=0.05)
    jaxr = dict(jax_rate=5000.0, compile_dt=12.0, jax_spread=0.02,
                cache_entries=7)
    return prep, floor, jaxr


def test_report_schema_and_values():
    out = report(*_fake_inputs())
    assert set(out) == {
        "value", "jax_spread", "vs_baseline", "numpy_floor_ions_per_s",
        "numpy_floor_spread", "numpy_floor_spread_mid5",
        "numpy_floor_n_ions", "floor_procs",
        "numpy_floor_multiproc_ions_per_s", "vs_baseline_multiproc",
        "compile_s", "warmup_retried", "warmup_skipped",
        "cold_compile_s", "first_annotation_cold_s",
        "hbm_peak_bytes", "device_kind",
        "xla_cache_entries_before",
        "n_ions", "n_pixels", "pixels_per_s", "isocalc_s",
        "isocalc_cold_s", "isocalc_workers", "patterns_per_s",
        "phases",
        # ISSUE 18: roofline + resident-cube-compaction pins
        "roofline_frac", "roofline_floor_s", "roofline_bound",
        "fused", "cube_dtype", "resident_cube_bytes",
        "resident_cube_bytes_f32",
        # ISSUE 20: profiler-measured roofline (device time attributed to
        # the scoring kernels by HLO module name, not wall-clock)
        "measured_roofline_frac", "kernel_time_frac", "device_kernel_s",
    }
    # per-phase wall (ISSUE 5 satellite): the trajectory explains WHERE
    # time moved; stream_s appears only when the case config is passed
    assert out["phases"] == {"isocalc_s": 0.5, "floor_rep_s": 2.0,
                             "compile_s": 12.0}
    assert out["value"] == 5000.0
    assert out["vs_baseline"] == 100.0
    assert out["jax_spread"] == 0.02
    assert out["compile_s"] == 12.0
    # warmup_retried defaults False when absent and passes through when set
    assert out["warmup_retried"] is False
    assert out["warmup_skipped"] is False
    assert out["xla_cache_entries_before"] == 7
    assert out["numpy_floor_ions_per_s"] == 50.0
    assert out["numpy_floor_spread_mid5"] == 0.05
    assert out["floor_procs"] == 1
    assert out["vs_baseline_multiproc"] == 100.0
    assert out["n_ions"] == 100 and out["n_pixels"] == 4096
    assert out["pixels_per_s"] == 5000.0 * 4096
    assert out["isocalc_s"] == 0.5
    # cold-path fields are None on cases that skip the regeneration
    assert out["isocalc_cold_s"] is None
    assert out["isocalc_workers"] is None
    assert out["patterns_per_s"] is None
    # cleared-cache cold-start pins (ISSUE 13): None under --skip-cold,
    # rounded pass-throughs when measured
    assert out["cold_compile_s"] is None
    assert out["first_annotation_cold_s"] is None
    prep, floor, jaxr = _fake_inputs()
    out2 = report(prep, floor, jaxr,
                  cold={"cold_compile_s": 31.456,
                        "first_annotation_cold_s": 4.321})
    assert out2["cold_compile_s"] == 31.46
    assert out2["first_annotation_cold_s"] == 4.32
    # HBM pinning (ISSUE 6 satellite): null when the platform exposes no
    # memory stats, passed through when measure_jax captured them
    assert out["hbm_peak_bytes"] is None
    assert out["device_kind"] is None
    # roofline/compaction pins (ISSUE 18): null when measure_roofline did
    # not run, passed through when measured
    assert out["roofline_frac"] is None
    assert out["resident_cube_bytes"] is None


def test_report_roofline_fields_pass_through():
    prep, floor, jaxr = _fake_inputs()
    jaxr.update(roofline_frac=0.62, roofline_floor_s=0.484,
                roofline_bound="bandwidth", fused=True, cube_dtype="bf16",
                resident_cube_bytes=462_000_000,
                resident_cube_bytes_f32=924_000_000)
    out = report(prep, floor, jaxr)
    assert out["roofline_frac"] == 0.62
    assert out["roofline_bound"] == "bandwidth"
    assert out["fused"] is True and out["cube_dtype"] == "bf16"
    # the compaction acceptance pin: compacted bytes at most half of f32
    assert out["resident_cube_bytes"] * 2 <= out["resident_cube_bytes_f32"]


def test_report_compile_split_phases():
    prep, floor, jaxr = _fake_inputs()
    jaxr["compile_split"] = {"trace_s": 0.4, "lower_s": 0.1,
                             "cache_load_s": 0.0, "backend_compile_s": 1.5,
                             "warmup_exec_s": 10.0}
    out = report(prep, floor, jaxr)
    assert out["phases"]["compile_trace_s"] == 0.4
    assert out["phases"]["compile_lower_s"] == 0.1
    assert out["phases"]["compile_cache_load_s"] == 0.0
    assert out["phases"]["compile_backend_s"] == 1.5
    assert out["phases"]["warmup_exec_s"] == 10.0


def test_report_hbm_fields_pass_through():
    prep, floor, jaxr = _fake_inputs()
    jaxr["hbm_peak_bytes"] = 1_940_000_000
    jaxr["device_kind"] = "TPU v5 lite"
    out = report(prep, floor, jaxr)
    assert out["hbm_peak_bytes"] == 1_940_000_000
    assert out["device_kind"] == "TPU v5 lite"


def test_report_flags_retried_warmup():
    prep, floor, jaxr = _fake_inputs()
    jaxr["warmup_retried"] = True
    jaxr["warmup_skipped"] = True
    out = report(prep, floor, jaxr)
    assert out["warmup_retried"] is True
    assert out["warmup_skipped"] is True


def test_report_isocalc_cold_fields():
    prep, floor, jaxr = _fake_inputs()
    iso = dict(isocalc_cold_s=12.345, isocalc_workers=4,
               patterns_per_s=812.5)
    out = report(prep, floor, jaxr, iso)
    assert out["isocalc_cold_s"] == 12.35
    assert out["isocalc_workers"] == 4
    assert out["patterns_per_s"] == 812.5


def test_transient_warmup_error_matcher():
    from bench import _is_transient_warmup_error

    assert _is_transient_warmup_error(
        RuntimeError("response body closed before all bytes were read"))
    assert _is_transient_warmup_error(ConnectionResetError("Connection reset"))
    # non-transient failures must NOT be retried (ADVICE r5)
    assert not _is_transient_warmup_error(ValueError("bad formula_batch"))
    assert not _is_transient_warmup_error(
        RuntimeError("RESOURCE_EXHAUSTED: out of memory on TPU"))
