"""Fleet observability plane (ISSUE 20): exposition parse/merge semantics,
fleet SLO bit-equality with a single tracker observing the union, the
partial-view-with-evidence contract when a peer is unreachable, and the
device profiler's request-validation paths.

The 3-replica live-fleet behavior (mid-scrape SIGKILL, profile capture
during a sharded job) is gated end-to-end by scripts/fleet_smoke.py; these
tests pin the pure logic those gates are built on.
"""

from __future__ import annotations

import random
import socket
import sys
import types
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from sm_distributed_tpu.service.fleetview import (  # noqa: E402
    DeviceProfiler,
    FleetView,
    merge_expositions,
    parse_exposition,
    slo_report_from_registry,
)
from sm_distributed_tpu.service.leases import ReplicaRegistry  # noqa: E402
from sm_distributed_tpu.service.metrics import (  # noqa: E402
    MetricsRegistry,
)
from sm_distributed_tpu.service.telemetry import SLOTracker  # noqa: E402
from sm_distributed_tpu.utils.config import (  # noqa: E402
    FleetViewConfig,
    ProfileConfig,
    TelemetryConfig,
)


def _dyadic(rng: random.Random) -> float:
    # multiples of 1/1024 add exactly in binary floating point, so summed
    # histogram `sum` fields are bit-equal however the adds are grouped
    return rng.randrange(0, 8192) / 1024.0


# ------------------------------------------------------------------ parsing
def test_parse_exposition_roundtrip():
    reg = MetricsRegistry()
    c = reg.counter("sm_x_jobs_total", "jobs", ("state",))
    c.labels(state="done").inc(3)
    c.labels(state="failed").inc(1)
    reg.gauge("sm_x_depth", "queue depth").set(7.5)
    h = reg.histogram("sm_x_wait_seconds", "waits")
    h.observe(0.3)
    h.observe(2.0)

    fams = parse_exposition(reg.expose())
    assert fams["sm_x_jobs_total"]["kind"] == "counter"
    assert fams["sm_x_depth"]["kind"] == "gauge"
    assert fams["sm_x_wait_seconds"]["kind"] == "histogram"
    counter_vals = {tuple(sorted(lab.items())): v
                    for suffix, lab, v in fams["sm_x_jobs_total"]["samples"]
                    if suffix == ""}
    assert counter_vals[(("state", "done"),)] == 3.0
    assert counter_vals[(("state", "failed"),)] == 1.0
    # histogram series resolve to their suffixes, +Inf bucket == count
    suffixes = {s for s, _, _ in fams["sm_x_wait_seconds"]["samples"]}
    assert suffixes == {"_bucket", "_sum", "_count"}
    inf = [v for s, lab, v in fams["sm_x_wait_seconds"]["samples"]
           if s == "_bucket" and lab.get("le") == "+Inf"]
    assert inf == [2.0]


def test_parse_exposition_skips_garbage_lines():
    text = ("# TYPE sm_ok_total counter\n"
            "sm_ok_total 4\n"
            "this line is not exposition at all {{{\n"
            "sm_no_value{label=\"x\"}\n")
    fams = parse_exposition(text)
    assert fams["sm_ok_total"]["samples"] == [("", {}, 4.0)]


# ------------------------------------------------------------------ merging
def test_merge_counters_summed_gauges_relabelled():
    scrapes = {}
    for rid, jobs, depth in (("r0", 5, 2.0), ("r1", 7, 9.0)):
        reg = MetricsRegistry()
        reg.counter("sm_y_jobs_total", "jobs").inc(jobs)
        reg.gauge("sm_y_depth", "depth").set(depth)
        scrapes[rid] = reg.expose()

    merged = merge_expositions(scrapes)
    text = merged.expose()
    # counters: one fleet total
    assert "sm_y_jobs_total 12" in text
    # gauges: one series per replica, re-labelled — a fleet-summed gauge
    # would be meaningless (occupancy, depth are per-replica states)
    assert 'sm_y_depth{replica="r0"} 2' in text
    assert 'sm_y_depth{replica="r1"} 9' in text


def test_merge_histograms_bit_equal_with_observing_union():
    rng = random.Random(20)
    per_replica = {f"r{i}": [_dyadic(rng) for _ in range(200)]
                   for i in range(3)}

    scrapes = {}
    for rid, samples in per_replica.items():
        reg = MetricsRegistry()
        h = reg.histogram("sm_z_lat_seconds", "lat")
        for s in samples:
            h.observe(s)
        scrapes[rid] = reg.expose()

    union = MetricsRegistry()
    hu = union.histogram("sm_z_lat_seconds", "lat")
    for samples in per_replica.values():
        for s in samples:
            hu.observe(s)

    merged = merge_expositions(scrapes)
    hm = merged._metrics["sm_z_lat_seconds"]
    for thr in (0.1, 1.0, 5.0, 1e9):
        assert hm.fraction_below(thr) == hu.fraction_below(thr)
    # the merged exposition's histogram series are identical too
    def series(reg):
        return sorted(line for line in reg.expose().splitlines()
                      if line.startswith("sm_z_lat_seconds"))
    assert series(merged) == series(union)


# ---------------------------------------------------------------- fleet SLO
def test_fleet_slo_bit_equal_with_single_tracker_on_union():
    """slo_report_from_registry over merged scrapes == SLOTracker.report of
    one tracker that observed every replica's samples — the /fleet/slo
    bit-equality contract the smoke gate re-checks live."""
    rng = random.Random(21)
    cfg = TelemetryConfig()

    union_reg = MetricsRegistry()
    union_tracker = SLOTracker(union_reg, cfg)

    scrapes = {}
    for rid in ("r0", "r1", "r2"):
        reg = MetricsRegistry()
        tracker = SLOTracker(reg, cfg)
        for _ in range(150):
            v = _dyadic(rng)
            tracker.h_queue_wait.observe(v)
            union_tracker.h_queue_wait.observe(v)
        for _ in range(80):
            v = _dyadic(rng)
            tracker.h_e2e.observe(v)
            union_tracker.h_e2e.observe(v)
        for _ in range(40):
            v = _dyadic(rng)
            tracker.h_read.observe(v)
            union_tracker.h_read.observe(v)
        scrapes[rid] = reg.expose()
    # first_annotation / stream_partial stay empty: count==0 SLIs must
    # report attainment None on both sides, not crash either

    merged = merge_expositions(scrapes)
    fleet = slo_report_from_registry(merged, cfg)
    single = union_tracker.report()
    assert fleet == single
    assert fleet["slos"]["first_annotation"]["attainment"] is None
    assert fleet["slos"]["queue_wait"]["count"] == 450


# ------------------------------------------- partial view, never an error
def _fake_service(tmp_path, rid="r0"):
    reg = MetricsRegistry()
    reg.counter("sm_fake_jobs_total", "jobs").inc(2)
    registry = ReplicaRegistry(tmp_path, rid, stale_after_s=8.0)
    registry.register()
    sched = types.SimpleNamespace(
        replica_id=rid, registry=registry, _evicted_hosts=set(),
        jobs=lambda: [])
    svc = types.SimpleNamespace(
        metrics=reg, scheduler=sched,
        sm_config=types.SimpleNamespace(
            telemetry=TelemetryConfig(), work_dir=str(tmp_path)),
        trace_dir=None)
    return svc


def _closed_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_fleetview_partial_view_with_dead_peer(tmp_path):
    """An alive-looking peer whose admin endpoint is gone (killed between
    heartbeats) yields a 200 partial view with per-replica evidence and a
    bumped sm_fleetview_scrape_errors_total — never a 500."""
    svc = _fake_service(tmp_path)
    # fake peer: fresh heartbeat (alive=True) but its admin port is closed
    peer = ReplicaRegistry(tmp_path, "r_dead", stale_after_s=8.0)
    peer.register()
    peer.beat({"admin": f"127.0.0.1:{_closed_port()}", "host": "host-b"})
    # and one peer that never gossiped an admin address at all
    legacy = ReplicaRegistry(tmp_path, "r_legacy", stale_after_s=8.0)
    legacy.register()

    # one shared round across the endpoint calls below (cache_ttl_s), so
    # the evidence counter's value stays the single failed scrape's
    fv = FleetView(svc, FleetViewConfig(scrape_timeout_s=0.5,
                                        cache_ttl_s=60.0))
    rnd = fv.collect(force=True)

    assert rnd.partial
    assert set(rnd.scrape_errors) == {"r_dead", "r_legacy"}
    assert "no admin address gossiped" in rnd.scrape_errors["r_legacy"]
    assert rnd.replicas["r_dead"]["alive"]
    assert rnd.replicas["r_dead"]["scraped"] is False
    assert rnd.replicas["r0"]["scraped"] is True

    code, slo = fv.slo()
    assert code == 200
    assert slo["fleet"]["partial"] is True
    assert slo["fleet"]["replicas_merged"] == 1
    assert slo["fleet"]["replicas_known"] == 3
    assert "r_dead" in slo["fleet"]["scrape_errors"]

    text = fv.metrics_text()
    assert "# fleetview: merged 3 replica(s), partial=true" in text
    assert "# fleetview: scrape of r_dead failed:" in text
    # local families still merged (self-scrape cannot fail)
    assert "sm_fake_jobs_total 2" in text
    # evidence counter carries the peer label
    assert 'sm_fleetview_scrape_errors_total{replica="r_dead"} 1' \
        in svc.metrics.expose()

    code, status = fv.status()
    assert code == 200
    assert status["partial"] is True
    assert status["alive"] == 3
    assert status["hosts"].get("host-b") == ["r_dead"]


def test_fleetview_cache_reuses_round(tmp_path):
    svc = _fake_service(tmp_path)
    fv = FleetView(svc, FleetViewConfig(cache_ttl_s=60.0))
    r1 = fv.collect()
    r2 = fv.collect()
    assert r2 is r1
    assert fv.collect(force=True) is not r1


# ------------------------------------------------------------ profiler API
def test_profiler_validation_paths(tmp_path):
    svc = _fake_service(tmp_path)

    disabled = DeviceProfiler(svc, ProfileConfig(enabled=False))
    code, body = disabled.run(1.0)
    assert code == 404 and body["reason"] == "not_found"

    prof = DeviceProfiler(svc, ProfileConfig(max_seconds=5.0))
    code, body = prof.run(-1.0)
    assert code == 400 and body["reason"] == "invalid_request"
    code, body = prof.run(0)
    assert code == 400

    # single-flight: a held capture lock means 409, never a queued stall
    assert prof._busy.acquire(blocking=False)
    try:
        code, body = prof.run(0.1)
        assert code == 409 and body["reason"] == "busy"
    finally:
        prof._busy.release()


@pytest.mark.slow
def test_profiler_capture_smoke(tmp_path):
    """A real (idle) capture returns 200 with a trace file or an empty
    attribution — never an exception."""
    svc = _fake_service(tmp_path)
    prof = DeviceProfiler(svc, ProfileConfig(default_seconds=0.2))
    code, body = prof.run(0.2)
    assert code == 200
    assert body["seconds"] == 0.2
    assert "attribution" in body and "injected_spans" in body
