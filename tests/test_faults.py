"""Device-fault taxonomy (ISSUE 14, models/faults.py): classification
matrix, the breaker/oom overlap regression, per-chip breaker semantics,
and the listener seam."""

from __future__ import annotations

import pytest

from sm_distributed_tpu.models import breaker as breaker_mod
from sm_distributed_tpu.models import faults
from sm_distributed_tpu.utils import failpoints


@pytest.fixture(autouse=True)
def _reset_failpoints():
    failpoints.reset()
    yield
    failpoints.reset()


# ------------------------------------------------------------ classification
def test_classification_matrix():
    # OOM stays the sizing signal (models/oom.py is the authority)
    assert faults.classify(MemoryError("boom")) == faults.FAULT_OOM
    assert faults.classify(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "2147483648 bytes")) == faults.FAULT_OOM
    # known-transient runtime hiccups: class-based and status-text-based
    assert faults.classify(TimeoutError("rpc")) == faults.FAULT_TRANSIENT
    assert faults.classify(ConnectionError("peer")) == faults.FAULT_TRANSIENT
    assert faults.classify(RuntimeError(
        "DEADLINE_EXCEEDED: collective all-reduce timed out after "
        "120s")) == faults.FAULT_TRANSIENT
    assert faults.classify(RuntimeError(
        "UNAVAILABLE: socket closed")) == faults.FAULT_TRANSIENT
    assert faults.classify(OSError(
        "device tunnel died: connection reset")) == faults.FAULT_TRANSIENT
    # everything else at the device seam is sticky
    assert faults.classify(RuntimeError(
        "INTERNAL: failed to enqueue program")) == faults.FAULT_STICKY
    assert faults.classify(RuntimeError(
        "injected failpoint backend.chip_fault (hit 1)")) == \
        faults.FAULT_STICKY
    assert faults.classify(ValueError("bad shape")) == faults.FAULT_STICKY


def test_transient_xla_error_does_not_feed_breaker(tmp_path):
    """THE overlap regression (ISSUE 14 satellite): an XlaRuntimeError
    that is NOT RESOURCE_EXHAUSTED but IS a known-transient collective
    timeout used to count toward the breaker.  Routed through
    models/faults.py it must fail the attempt for the retry policy with
    the breaker untouched (threshold 1 would have opened on one count)."""
    from sm_distributed_tpu.io.dataset import SpectralDataset
    from sm_distributed_tpu.io.fixtures import generate_synthetic_dataset
    from sm_distributed_tpu.models.msm_basic import MSMBasicSearch
    from sm_distributed_tpu.utils.config import DSConfig, SMConfig

    path, truth = generate_synthetic_dataset(
        tmp_path / "ds", nrows=8, ncols=8, formulas=None,
        present_fraction=0.5, noise_peaks=30, seed=11)
    ds = SpectralDataset.from_imzml(path)
    ds_config = DSConfig.from_dict({"isotope_generation": {"adducts": ["+H"]}})
    sm = SMConfig.from_dict(
        {"backend": "jax_tpu", "fdr": {"decoy_sample_size": 2, "seed": 1},
         "parallel": {"formula_batch": 8, "overlap_isocalc": "off"},
         "service": {"breaker_threshold": 1},
         "work_dir": str(tmp_path / "work")})
    # ConnectionError at the chip-fault seam = the collective-timeout class
    failpoints.configure("backend.chip_fault=raise:ConnectionError")
    with pytest.raises(ConnectionError, match="backend.chip_fault"):
        MSMBasicSearch(ds, truth.formulas[:4], ds_config, sm).search()
    assert breaker_mod.get_device_breaker().state == "closed", \
        "a transient fault must never count toward the breaker"
    failpoints.configure(None)
    # the same seam with a sticky class still opens the threshold-1 breaker
    failpoints.configure("backend.chip_fault=raise:RuntimeError@1")
    MSMBasicSearch(ds, truth.formulas[:4], ds_config, sm).search()
    assert breaker_mod.get_device_breaker().state == "open"


# --------------------------------------------------------- per-chip breakers
def test_per_chip_breakers_are_independent():
    cfg = type("C", (), {"breaker_threshold": 1, "breaker_cooldown_s": 60.0})
    lease_a = breaker_mod.get_device_breaker(cfg, devices=(0, 1))
    assert lease_a.allow_device() and lease_a.state == "closed"
    assert lease_a.record_failure()          # threshold 1: both chips open
    assert lease_a.state == "open" and not lease_a.allow_device()
    # a DIFFERENT lease over healthy chips is unaffected
    lease_b = breaker_mod.get_device_breaker(cfg, devices=(2, 3))
    assert lease_b.allow_device() and lease_b.state == "closed"
    # ...and so is the un-leased "*" singleton
    assert breaker_mod.get_device_breaker().state == "closed"
    # any lease sharing a tripped chip sees the open state
    lease_c = breaker_mod.get_device_breaker(cfg, devices=(1, 2))
    assert lease_c.state == "open"
    snap = breaker_mod.breakers_snapshot()
    assert snap["0"]["state"] == "open" and snap["2"]["state"] == "closed"


def test_breaker_metrics_carry_device_label():
    from sm_distributed_tpu.service.metrics import MetricsRegistry

    m = MetricsRegistry()
    breaker_mod.attach_metrics(m)
    cfg = type("C", (), {"breaker_threshold": 1, "breaker_cooldown_s": 60.0})
    breaker_mod.get_device_breaker(cfg, devices=(5,)).record_failure()
    text = m.expose()
    assert 'sm_breaker_state{device="5"} 2' in text
    assert 'sm_breaker_transitions_total{device="5",to="open"} 1' in text


# ------------------------------------------------------------- listener seam
def test_fault_listener_dispatch_and_clear():
    class Sink:
        def __init__(self):
            self.faults = []
            self.oks = []

        def report_fault(self, devices, kind, error):
            self.faults.append((devices, kind))

        def report_ok(self, devices):
            self.oks.append(devices)

    sink = Sink()
    faults.set_fault_listener(sink)
    faults.report_device_fault((0, 1), faults.FAULT_STICKY, "boom")
    faults.report_device_ok((0, 1))
    # un-leased reports have nothing to attribute
    faults.report_device_fault(None, faults.FAULT_STICKY, "boom")
    assert sink.faults == [((0, 1), faults.FAULT_STICKY)]
    assert sink.oks == [(0, 1)]
    # clear-if-ours: someone else's registration survives a stale clear
    other = Sink()
    faults.set_fault_listener(other)
    faults.clear_fault_listener(sink)
    faults.report_device_fault((2,), faults.FAULT_TRANSIENT, "t")
    assert other.faults == [((2,), faults.FAULT_TRANSIENT)]
    faults.clear_fault_listener(other)
    faults.report_device_fault((3,), faults.FAULT_STICKY, "x")
    assert len(other.faults) == 1
