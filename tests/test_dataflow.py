"""Shared forward-dataflow / taint engine tests (ISSUE 15).

The engine (``analysis/dataflow.py``) replaces the ad-hoc taint walks
that grew inside ``fence-gate`` and ``retrace-hazard``; the contract is
(1) the primitives behave — def-use chains, taint through single-target
locals and dict-call sinks, sanitizer laundering, structural clearing
calls, single-level call summaries, the guarded summary cache — and
(2) the refactored rules produce FINDING-FOR-FINDING parity with the
pre-refactor walks on the current tree (the committed snapshot below).
"""

from __future__ import annotations

import ast
from pathlib import Path

from sm_distributed_tpu.analysis import dataflow
from sm_distributed_tpu.analysis import rules as rules_mod  # noqa: F401
from sm_distributed_tpu.analysis.core import Module, Project, run_lint
from sm_distributed_tpu.analysis.dataflow import (
    SummaryCache,
    TaintTracker,
    def_use,
    function_nodes,
    module_summaries,
)

REPO_ROOT = Path(__file__).resolve().parent.parent


def _mod(src: str, path: str = "sm_distributed_tpu/x.py") -> Module:
    return Module(path, src)


def _fn(mod: Module, name: str):
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    raise AssertionError(f"no function {name}")


# ------------------------------------------------------------ function_nodes
def test_function_nodes_excludes_nested_defs():
    mod = _mod(
        "def outer(x):\n"
        "    a = x + 1\n"
        "    def inner(y):\n"
        "        b = y + 2\n"
        "        return b\n"
        "    return inner(a)\n"
    )
    names = {n.targets[0].id for n in function_nodes(mod, _fn(mod, "outer"))
             if isinstance(n, ast.Assign)}
    assert names == {"a"}              # inner's `b` belongs to inner


# ------------------------------------------------------------------- def-use
def test_def_use_chains():
    mod = _mod(
        "def f(x):\n"
        "    n = x.shape[0]\n"
        "    m = n + 1\n"
        "    n = m\n"
        "    return n\n"
    )
    du = def_use(mod, _fn(mod, "f"))
    defs, uses = du.chain("n")
    assert len(defs) == 2              # both single-target assignments
    assert len(uses) == 2              # n + 1, return n
    assert du.chain("m")[0][0].lineno == 3


# --------------------------------------------------------------- flat taint
def test_taint_through_single_target_locals():
    mod = _mod(
        "def f(x):\n"
        "    n = x.shape[0]\n"
        "    m = n + 1\n"
        "    k = unrelated()\n"
    )
    taint = TaintTracker(source=rules_mod._is_shape_source)
    for _ in taint.walk(mod, _fn(mod, "f")):
        pass
    assert taint.names == {"n", "m"}


def test_sanitizer_clears_whole_expression():
    mod = _mod(
        "def f(x):\n"
        "    n = x.shape[0]\n"
        "    b = size_bucket(n) + n\n"   # one bucketing call launders all
    )
    taint = TaintTracker(source=rules_mod._is_shape_source,
                         sanitizer=rules_mod._is_bucketing_call)
    for _ in taint.walk(mod, _fn(mod, "f")):
        pass
    assert taint.names == {"n"}


def test_dict_call_keyword_sink_taint():
    """The retrace-hazard dict-sink shape: `statics = dict(b=n)` keeps the
    keyword visible to sink checks while `statics` itself is tainted."""
    mod = _mod(
        "def go(x):\n"
        "    n = x.shape[0]\n"
        "    statics = dict(b=n)\n"
        "    return fn(x, **statics)\n"
    )
    taint = TaintTracker(source=rules_mod._is_shape_source)
    hits = []
    for node in taint.walk(mod, _fn(mod, "go")):
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg == "b" and taint.expr_tainted(kw.value):
                    hits.append(node.lineno)
    assert hits == [3] and "statics" in taint.names


# --------------------------------------------------------- structural taint
def test_structural_clearing_call_launders():
    mod = _mod(
        "def f(images, n_real):\n"
        "    out = batch_metrics(images, n_real=n_real)\n"
        "    raw = other(images)\n"
    )
    taint = TaintTracker(
        call_clears=rules_mod._masked_helper_clears, structural=True)
    taint.names.add("images")
    for _ in taint.walk(mod, _fn(mod, "f")):
        pass
    assert "out" not in taint.names    # masked helper result is clean
    assert "raw" in taint.names        # arbitrary calls propagate


def test_structural_tuple_unpack_taints_all_targets():
    mod = _mod(
        "def f(images):\n"
        "    a, b = split(images)\n"
    )
    taint = TaintTracker(structural=True)
    taint.names.add("images")
    for _ in taint.walk(mod, _fn(mod, "f")):
        pass
    assert {"a", "b"} <= taint.names


# ------------------------------------------------------------- call summaries
def test_module_summaries_param_flows_through_local():
    mod = _mod(
        "def keep(v):\n"
        "    w = v * 2\n"
        "    return w\n"
        "def drop(v):\n"
        "    return 1\n"
    )
    s = module_summaries(mod)
    assert s["keep"] == (("v",), frozenset({"v"}))
    assert s["drop"] == (("v",), frozenset())


def test_summaries_are_authoritative_in_structural_mode():
    mod = _mod(
        "def keep(v):\n"
        "    return v\n"
        "def drop(v):\n"
        "    return 1\n"
        "def go(x):\n"
        "    a = keep(x)\n"
        "    b = drop(x)\n"
    )
    taint = TaintTracker(summaries=module_summaries(mod), structural=True)
    taint.names.add("x")
    for _ in taint.walk(mod, _fn(mod, "go")):
        pass
    assert "a" in taint.names          # flows through keep's param
    assert "b" not in taint.names      # drop's param never reaches return


def test_summary_keyword_argument_flow():
    mod = _mod(
        "def helper(u, v=0):\n"
        "    return v\n"
        "def go(x):\n"
        "    a = helper(1, v=x)\n"
        "    b = helper(x, v=2)\n"
    )
    taint = TaintTracker(summaries=module_summaries(mod), structural=True)
    taint.names.add("x")
    for _ in taint.walk(mod, _fn(mod, "go")):
        pass
    assert "a" in taint.names and "b" not in taint.names


def test_summary_cache_hits_and_clear():
    cache = SummaryCache()
    mod = _mod("def f(v):\n    return v\n")
    first = cache.get(mod)
    assert cache.get(mod) is first     # memoized by (path, source hash)
    edited = _mod("def f(v):\n    return 1\n")
    assert cache.get(edited) is not first
    cache.clear()
    assert cache.get(mod) is not first
    assert dataflow.summaries._GUARDED_BY == {"_cache": "_lock"}


# ------------------------------------------- refactor parity (the snapshot)
# The findings the PRE-refactor in-line walks produced on this tree,
# keyed line-independently as (path, anchor, seam prefix).  The
# refactored rules must reproduce them finding-for-finding.
_FENCE_SNAPSHOT = {
    ("sm_distributed_tpu/engine/daemon.py", "QueueConsumer.process_one",
     "terminal-spool write (failed)"),
    ("sm_distributed_tpu/engine/daemon.py", "QueueConsumer.process_one",
     "spool complete (running/ -> done/)"),
    ("sm_distributed_tpu/service/scheduler.py", "JobScheduler.cancel",
     "terminal-spool move"),
    ("sm_distributed_tpu/service/scheduler.py", "JobScheduler.cancel",
     "terminal-spool write ((tainted path))"),
    ("sm_distributed_tpu/service/scheduler.py", "JobScheduler._quarantine",
     "terminal-spool write (quarantine)"),
}


def test_refactored_rules_match_prerefactor_snapshot():
    """Finding-for-finding parity on the current tree: the dataflow-engine
    rewrites of fence-gate and retrace-hazard report exactly the findings
    the ad-hoc walks did (fence-gate's five baselined seams, zero retrace
    hazards)."""
    proj = Project.load(REPO_ROOT, ["sm_distributed_tpu", "scripts",
                                    "bench.py"])
    res = run_lint(proj, only={"fence-gate", "retrace-hazard"})
    fence = {(f.path, f.anchor, f.message.split(" is not dominated")[0])
             for f in res.new if f.rule == "fence-gate"}
    assert fence == _FENCE_SNAPSHOT
    assert [f for f in res.new if f.rule == "retrace-hazard"] == []
