"""Queue crash-recovery tests (ISSUE 1 satellite): heartbeat-aware
``requeue_stale`` and the two-consumer claim race on the shared spool."""

import json
import os
import threading
import time

from sm_distributed_tpu.engine.daemon import (
    ClaimHeartbeat,
    QueueConsumer,
    QueuePublisher,
    heartbeat_path,
    touch_heartbeat,
)


def _age(path, seconds):
    old = time.time() - seconds
    os.utime(path, (old, old))


def test_requeue_stale_live_heartbeat_vs_dead_claim(tmp_path):
    """A slow-but-alive claim (fresh heartbeat) must survive recovery; a
    crashed claim (stale heartbeat) and a heartbeat-less claim older than
    the threshold must be requeued."""
    consumer = QueueConsumer(tmp_path / "q", callback=None)
    root = tmp_path / "q" / "sm_annotate"

    alive = root / "running" / "alive.json"
    alive.write_text(json.dumps({"ds_id": "alive"}))
    _age(alive, 600)                       # claimed long ago ...
    touch_heartbeat(alive)                 # ... but its job is still beating

    crashed = root / "running" / "crashed.json"
    crashed.write_text(json.dumps({"ds_id": "crashed"}))
    _age(crashed, 600)
    touch_heartbeat(crashed)
    _age(heartbeat_path(crashed), 600)     # heartbeat died with the process

    no_hb = root / "running" / "no_hb.json"
    no_hb.write_text(json.dumps({"ds_id": "no_hb"}))
    _age(no_hb, 600)                       # pre-heartbeat-era claim

    assert consumer.requeue_stale(max_age_s=30.0) == 2
    assert sorted(p.name for p in root.glob("pending/*.json")) == [
        "crashed.json", "no_hb.json"]
    assert [p.name for p in root.glob("running/*.json")] == ["alive.json"]
    # requeued claims carry no leftover heartbeat sidecars
    assert not list(root.glob("pending/*.hb"))
    assert not heartbeat_path(crashed).exists()

    # once the live job's heartbeat goes stale too, it is recovered as well
    _age(heartbeat_path(alive), 600)
    assert consumer.requeue_stale(max_age_s=30.0) == 1
    assert not list(root.glob("running/*.json"))

    # default max_age_s=0 keeps the recover-everything cold-start behavior
    fresh = root / "running" / "fresh.json"
    fresh.write_text(json.dumps({"ds_id": "fresh"}))
    assert consumer.requeue_stale() == 1


def test_claim_heartbeat_thread_keeps_claim_alive(tmp_path):
    consumer = QueueConsumer(tmp_path / "q", callback=None)
    root = tmp_path / "q" / "sm_annotate"
    msg = root / "running" / "beating.json"
    msg.write_text(json.dumps({"ds_id": "b"}))
    _age(msg, 600)
    hb = ClaimHeartbeat(msg, interval_s=0.05)
    hb.start()
    try:
        time.sleep(0.2)                    # several beats
        assert consumer.requeue_stale(max_age_s=0.15) == 0, \
            "live heartbeat was treated as stale"
    finally:
        hb.stop()
    assert not heartbeat_path(msg).exists(), "stop() must clear the sidecar"
    # with the heartbeat stopped the claim goes stale and is recovered
    time.sleep(0.2)
    assert consumer.requeue_stale(max_age_s=0.15) == 1


def test_two_consumers_race_each_message_claimed_once(tmp_path):
    """Publisher/consumer race: two consumers drain one spool concurrently;
    every message is processed exactly once (the atomic-rename claim)."""
    pub = QueuePublisher(tmp_path / "q")
    n_msgs = 24
    for i in range(n_msgs):
        pub.publish({"ds_id": f"m{i:02d}", "input_path": "/in",
                     "msg_id": f"m{i:02d}"})

    seen: list[str] = []
    lock = threading.Lock()

    def make_cb(name):
        def cb(msg):
            with lock:
                seen.append(msg["ds_id"])
            time.sleep(0.001)          # widen the race window
        return cb

    consumers = [
        QueueConsumer(tmp_path / "q", make_cb(f"c{k}"), poll_interval=0.01)
        for k in range(2)
    ]

    def drain(c):
        while c.process_one():
            pass

    threads = [threading.Thread(target=drain, args=(c,)) for c in consumers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)

    assert sorted(seen) == [f"m{i:02d}" for i in range(n_msgs)], \
        "a message was double-claimed or lost"
    root = tmp_path / "q" / "sm_annotate"
    assert len(list(root.glob("done/*.json"))) == n_msgs
    assert not list(root.glob("pending/*.json"))
    assert not list(root.glob("running/*.json"))


def test_consumer_and_scheduler_share_one_spool(tmp_path):
    """A legacy blocking consumer and the service scheduler can drain the
    SAME queue concurrently without double-processing (mixed-fleet rollout:
    old daemons and new service instances during a deploy)."""
    from sm_distributed_tpu.service import JobScheduler
    from sm_distributed_tpu.utils.config import ServiceConfig

    pub = QueuePublisher(tmp_path / "q")
    n_msgs = 16
    for i in range(n_msgs):
        pub.publish({"ds_id": f"x{i:02d}", "input_path": "/in",
                     "msg_id": f"x{i:02d}"})
    seen: list[str] = []
    lock = threading.Lock()

    def cb(msg, ctx=None):
        with lock:
            seen.append(msg["ds_id"])
        time.sleep(0.002)

    sched = JobScheduler(
        tmp_path / "q", cb,
        config=ServiceConfig(workers=2, poll_interval_s=0.01,
                             backoff_base_s=0.05, http_port=0))
    legacy = QueueConsumer(tmp_path / "q", cb, poll_interval=0.01)
    sched.start()
    t = threading.Thread(target=lambda: [legacy.process_one() or time.sleep(0.005)
                                         for _ in range(200)])
    t.start()
    deadline = time.time() + 30.0
    root = tmp_path / "q" / "sm_annotate"
    while time.time() < deadline:
        if len(list(root.glob("done/*.json"))) == n_msgs:
            break
        time.sleep(0.02)
    t.join(timeout=30.0)
    assert sched.shutdown()
    assert sorted(seen) == [f"x{i:02d}" for i in range(n_msgs)]
    assert len(list(root.glob("done/*.json"))) == n_msgs
