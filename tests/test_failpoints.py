"""Unit tests for the failpoint fault-injection subsystem (ISSUE 2 tentpole):
spec parsing, deterministic triggers, actions, env activation in a child
process, and the zero-overhead disabled path."""

import subprocess
import sys

import pytest

from sm_distributed_tpu.utils import failpoints as fp


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.reset()
    yield
    fp.reset()


def test_parse_grammar_roundtrip():
    specs = fp.parse_failpoints(
        "storage.results_rename=crash@2; ckpt.shard_write=torn;"
        "device.score_batch=raise:RuntimeError@3;a.b=sleep:0.25;"
        "c.d=raise?0.5;e.f=torn:0.25@4")
    assert specs["storage.results_rename"].action == "crash"
    assert specs["storage.results_rename"].nth == 2
    assert specs["ckpt.shard_write"].action == "torn"
    assert specs["device.score_batch"].arg == "RuntimeError"
    assert specs["a.b"].arg == "0.25"
    assert specs["c.d"].prob == 0.5 and specs["c.d"].rng is not None
    assert specs["e.f"].arg == "0.25" and specs["e.f"].nth == 4


@pytest.mark.parametrize("bad", [
    "x.y",                      # no action
    "x.y=explode",              # unknown action
    "x.y=raise:Exception",      # not in the allowlist
    "x.y=sleep",                # missing seconds
    "x.y=torn:1.5",             # fraction out of range
    "x.y=crash@0",              # @N is 1-based
    "x.y=raise?2.0",            # probability out of range
    "x.y=raise;x.y=crash",      # duplicate name
])
def test_parse_rejects_malformed(bad):
    with pytest.raises(ValueError):
        fp.parse_failpoints(bad)


def test_nth_hit_fires_exactly_once():
    fp.configure("t.nth=raise@3")
    fp.failpoint("t.nth")
    fp.failpoint("t.nth")
    with pytest.raises(fp.FailpointError):
        fp.failpoint("t.nth")
    for _ in range(5):                      # @N means the Nth hit ONLY
        fp.failpoint("t.nth")
    assert fp.injected_counts() == {"t.nth": 1}


def test_raise_injects_the_named_type():
    fp.configure("t.raise=raise:OSError")
    with pytest.raises(OSError, match="injected failpoint t.raise"):
        fp.failpoint("t.raise")


def test_seeded_probability_is_deterministic(monkeypatch):
    def schedule():
        fp.configure("t.prob=raise?0.4")
        fired = []
        for i in range(50):
            try:
                fp.failpoint("t.prob")
                fired.append(False)
            except fp.FailpointError:
                fired.append(True)
        return fired

    a, b = schedule(), schedule()
    assert a == b, "same seed must replay the same fault schedule"
    assert 5 < sum(a) < 45
    monkeypatch.setenv("SM_FAILPOINTS_SEED", "12345")
    assert schedule() != a, "a different seed gives a different schedule"


def test_torn_truncates_and_continues(tmp_path):
    f = tmp_path / "victim.bin"
    f.write_bytes(b"x" * 1000)
    fp.configure("t.torn=torn:0.25")
    fp.failpoint("t.torn", path=f)          # must NOT raise
    assert f.stat().st_size == 250
    # torn with no path is a hard programming error at the seam
    fp.configure("t.torn=torn")
    with pytest.raises(fp.FailpointError, match="no path"):
        fp.failpoint("t.torn")


def test_disabled_is_inert_and_counts_nothing(tmp_path):
    f = tmp_path / "untouched.bin"
    f.write_bytes(b"x" * 10)
    for _ in range(1000):
        fp.failpoint("ckpt.shard_write", path=f)
    assert f.stat().st_size == 10
    assert fp.injected_counts() == {}


def test_env_activation_crashes_child_process():
    """SM_FAILPOINTS is read at import, so any spawned worker inherits the
    fault; crash = os._exit with the spec'd code, skipping all cleanup."""
    from pathlib import Path

    repo_root = str(Path(__file__).resolve().parent.parent)
    code = ("from sm_distributed_tpu.utils.failpoints import failpoint\n"
            "failpoint('x.y', path=None)\n"
            "print('unreachable')\n")
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env={"SM_FAILPOINTS": "x.y=crash:7", "PATH": "/usr/bin:/bin",
             "PYTHONPATH": repo_root},
        capture_output=True, text=True, cwd=repo_root)
    assert proc.returncode == 7
    assert "FAILPOINT-FIRED name=x.y action=crash" in proc.stderr
    assert "unreachable" not in proc.stdout


def test_duplicate_registration_rejected():
    name = "test.dup_probe"
    fp.register_failpoint(name, "probe")
    try:
        with pytest.raises(ValueError, match="duplicate"):
            fp.register_failpoint(name)
    finally:
        fp._registry.pop(name, None)


def test_metrics_export_and_backfill():
    from sm_distributed_tpu.service.metrics import MetricsRegistry

    fp.configure("t.m=raise@1")
    with pytest.raises(fp.FailpointError):
        fp.failpoint("t.m")
    fp.record_recovery("unit.recovery", 3)
    reg = MetricsRegistry()
    fp.attach_metrics(reg)                  # pre-attachment counts backfill
    fp.record_recovery("unit.recovery")     # post-attachment increments live
    text = reg.expose()
    assert 'sm_failpoints_injected_total{name="t.m"} 1' in text
    assert 'sm_recovery_events_total{event="unit.recovery"} 4' in text


def test_every_registered_failpoint_is_documented_and_covered():
    """The satellite check, runnable from pytest too: unique names (register
    raises on duplicates at import), every name documented in
    docs/RECOVERY.md, every name exercised by a chaos scenario."""
    import scripts.chaos_sweep as cs

    errs = cs.check_docs()
    assert errs == [], "\n".join(errs)
