"""End-to-end tracing subsystem tests (ISSUE 5).

Covers the tentpole seams: span nesting + wire round-trip, Chrome-trace
schema validity, flight-recorder bounds under concurrent writers,
cross-process worker span re-parenting (a real spawn pool), trace
continuation across attempts/restarts, JSON-log record fields, the
multi-observer phase dispatch, and the service integration acceptance
shape (root submit span → phases → batch spans → worker span →
store_results via GET /jobs/<id>/trace).
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request
from pathlib import Path

import pytest

from sm_distributed_tpu.utils import tracing


@pytest.fixture(autouse=True)
def _fresh_tracing():
    """Isolate ring + file-handle cache + enablement between tests."""
    tracing.configure(enabled=True, ring_size=2048)
    tracing.flight_recorder.clear()
    yield
    tracing.close_files()
    tracing.configure(enabled=True, ring_size=2048)
    tracing.flight_recorder.clear()


# ------------------------------------------------------------ span basics
def test_span_nesting_and_parentage(tmp_path):
    ctx = tracing.new_trace(job_id="j1", trace_dir=tmp_path)
    with tracing.attach(ctx):
        with tracing.span("outer") as outer:
            with tracing.span("inner", depth=2) as inner:
                tracing.event("mark", note="x")
            assert inner.trace_id == ctx.trace_id
    recs = tracing.read_trace(ctx.file)
    assert [r["name"] for r in recs] == ["mark", "inner", "outer"]
    by_name = {r["name"]: r for r in recs}
    assert by_name["outer"]["parent_id"] == ctx.span_id
    assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
    # the event is attached to the span it happened under
    assert by_name["mark"]["span_id"] == by_name["inner"]["span_id"]
    assert by_name["inner"]["attrs"]["depth"] == 2
    assert all(r["job_id"] == "j1" for r in recs)
    assert not tracing.validate_records(recs)


def test_span_records_error_and_reraises(tmp_path):
    ctx = tracing.new_trace(trace_dir=tmp_path)
    with pytest.raises(ValueError):
        with tracing.attach(ctx), tracing.span("boom"):
            raise ValueError("nope")
    (rec,) = tracing.read_trace(ctx.file)
    assert rec["attrs"]["error"].startswith("ValueError")


def test_span_is_noop_without_context():
    before = len(tracing.flight_recorder.recent())
    with tracing.span("untraced") as got:
        assert got is None
    assert len(tracing.flight_recorder.recent()) == before


def test_disabled_tracing_emits_nothing(tmp_path):
    tracing.configure(enabled=False)
    ctx = tracing.new_trace(trace_dir=tmp_path)
    with tracing.attach(ctx), tracing.span("s"):
        tracing.event("e")
    assert not Path(ctx.file).exists()
    assert not tracing.flight_recorder.recent()


def test_wire_round_trip():
    ctx = tracing.new_trace(job_id="jobX")
    back = tracing.TraceContext.from_wire(ctx.to_wire())
    assert (back.trace_id, back.span_id, back.job_id) == \
        (ctx.trace_id, ctx.span_id, "jobX")
    assert back.file == ""            # sinks never cross the wire
    assert tracing.TraceContext.from_wire(None) is None
    assert tracing.TraceContext.from_wire({}) is None


def test_traceless_event_reaches_ring_only():
    tracing.event("admission.shed", reason="queue_full")
    (rec,) = tracing.flight_recorder.recent()
    assert rec["name"] == "admission.shed" and rec["trace_id"] == ""


# ------------------------------------------------------------ chrome export
def test_chrome_trace_schema(tmp_path):
    ctx = tracing.new_trace(job_id="j2", trace_dir=tmp_path)
    with tracing.attach(ctx):
        with tracing.span("work", ions=5):
            tracing.event("jax_profile", dir="/tmp/prof")
    out = tracing.to_chrome_trace(tracing.read_trace(ctx.file))
    evts = out["traceEvents"]
    assert evts and isinstance(evts, list)
    for e in evts:
        assert e["ph"] in ("X", "i", "M")
        assert "name" in e and "pid" in e
        if e["ph"] == "X":
            assert isinstance(e["ts"], (int, float)) and "dur" in e
        if e["ph"] == "i":
            assert e["s"] == "t"
    assert out["otherData"]["trace_id"] == ctx.trace_id
    assert out["otherData"]["jax_profile_dir"] == "/tmp/prof"
    json.dumps(out)                    # must be plain-JSON serializable


def test_torn_trailing_line_is_tolerated(tmp_path):
    ctx = tracing.new_trace(trace_dir=tmp_path)
    with tracing.attach(ctx), tracing.span("kept"):
        pass
    with open(ctx.file, "a") as f:
        f.write('{"kind": "span", "name": "torn-mid-wr')  # crash mid-write
    recs = tracing.read_trace(ctx.file)
    assert [r["name"] for r in recs] == ["kept"]


# ----------------------------------------------------------- ring bounds
def test_ring_bounds_under_concurrent_writers():
    tracing.configure(ring_size=100)
    n_threads, per_thread = 8, 200

    def writer(i: int) -> None:
        for k in range(per_thread):
            tracing.event(f"w{i}", k=k)

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    recent = tracing.flight_recorder.recent()
    assert len(recent) == 100          # bounded, and full
    assert all(r["kind"] == "event" for r in recent)
    assert tracing.flight_recorder.recent(7)[-1] == recent[-1]
    assert len(tracing.flight_recorder.recent(7)) == 7


# ------------------------------------- cross-process worker re-parenting
def test_worker_capture_and_emit_records(tmp_path):
    """The capture/emit halves of the process hop, in-process."""
    ctx = tracing.new_trace(job_id="j3", trace_dir=tmp_path)
    with tracing.capture() as buf:
        with tracing.span("isocalc_chunk", ctx=ctx, ci=0):
            tracing.event("failpoint", name="isocalc.worker")
    assert len(buf) == 2
    assert not Path(ctx.file).exists()          # capture bypassed the sinks
    assert not tracing.flight_recorder.recent()
    tracing.emit_records(buf, ctx)
    recs = tracing.read_trace(ctx.file)
    assert {r["name"] for r in recs} == {"isocalc_chunk", "failpoint"}
    chunk = next(r for r in recs if r["name"] == "isocalc_chunk")
    assert chunk["parent_id"] == ctx.span_id    # re-parented under the job
    assert chunk["trace_id"] == ctx.trace_id


@pytest.mark.slow
def test_worker_spans_cross_spawn_boundary(tmp_path):
    """A REAL spawned worker computes a chunk and returns its spans."""
    from concurrent.futures import ProcessPoolExecutor
    from multiprocessing import get_context

    from sm_distributed_tpu.ops.isocalc import _compute_chunk

    ctx = tracing.new_trace(job_id="spawned", trace_dir=tmp_path)
    args = (3, [("H2O", "+H"), ("C6H12O6", "+Na")],
            (1, 0.01, 10000, 4), False, ctx.to_wire())
    with ProcessPoolExecutor(max_workers=1,
                             mp_context=get_context("spawn")) as ex:
        ci, outputs, records = ex.submit(_compute_chunk, args).result()
    assert ci == 3 and len(outputs) == 2
    assert records, "worker returned no trace records"
    (chunk,) = [r for r in records if r["name"] == "isocalc_chunk"]
    assert chunk["trace_id"] == ctx.trace_id
    assert chunk["parent_id"] == ctx.span_id
    assert chunk["pid"] != __import__("os").getpid()
    tracing.emit_records(records, ctx)
    assert any(r["name"] == "isocalc_chunk"
               for r in tracing.read_trace(ctx.file))


def test_pattern_stream_traces_inline_chunks(tmp_path):
    """A traced (small, inline) generation emits gen + chunk spans into the
    job trace through the stream thread hop."""
    from sm_distributed_tpu.ops.isocalc import IsocalcWrapper
    from sm_distributed_tpu.utils.config import IsotopeGenerationConfig

    ctx = tracing.new_trace(job_id="iso", trace_dir=tmp_path)
    calc = IsocalcWrapper(IsotopeGenerationConfig(adducts=("+H",)))
    with tracing.attach(ctx):
        table = calc.pattern_table([("H2O", "+H"), ("CO2", "+H")])
    assert table.n_ions == 2
    names = [r["name"] for r in tracing.read_trace(ctx.file)]
    assert "isocalc_gen" in names and "isocalc_chunk" in names


# ------------------------------------------------- continuation / restart
def test_trace_continues_across_attempts_and_restart(tmp_path):
    """Retry in scheduler A, then a NEW scheduler (simulating a restarted
    process) finishes the job — one trace file, one trace_id, two attempt
    spans, a retry event, and one root submit span."""
    from sm_distributed_tpu.service.scheduler import JobScheduler
    from sm_distributed_tpu.utils.config import ServiceConfig

    queue_dir = tmp_path / "q"
    trace_dir = tmp_path / "traces"
    from sm_distributed_tpu.engine.daemon import QueuePublisher

    pub = QueuePublisher(queue_dir)
    trace = {"trace_id": tracing.new_id(), "span": tracing.new_id(),
             "start": __import__("time").time()}
    pub.publish({"ds_id": "d1", "msg_id": "m1", "input_path": "x",
                 "service": {"trace": dict(trace)}})

    calls = {"n": 0}

    def flaky(msg, ctx=None):
        calls["n"] += 1
        with tracing.span("work"):
            if calls["n"] == 1:
                raise RuntimeError("first attempt fails")

    cfg = ServiceConfig(workers=1, poll_interval_s=0.02, max_attempts=3,
                        backoff_base_s=0.05, backoff_max_s=0.05,
                        backoff_jitter=0.0, http_port=0)
    s1 = JobScheduler(queue_dir, flaky, config=cfg, trace_dir=trace_dir)
    s1.start()
    # wait for the first (failing) attempt to be recorded, then "crash"
    deadline = __import__("time").time() + 20
    while calls["n"] < 1 and __import__("time").time() < deadline:
        __import__("time").sleep(0.01)
    # let the retry republish land before shutting down
    while __import__("time").time() < deadline:
        if list((queue_dir / "sm_annotate" / "pending").glob("*.json")):
            break
        __import__("time").sleep(0.01)
    s1.shutdown()

    s2 = JobScheduler(queue_dir, flaky, config=cfg, trace_dir=trace_dir)
    s2.start()
    assert s2.wait_for_terminal(1, timeout_s=30)
    s2.shutdown()

    path = tracing.trace_path(trace_dir, trace["trace_id"])
    recs = tracing.read_trace(path)
    assert not tracing.validate_records(recs)
    assert {r["trace_id"] for r in recs} == {trace["trace_id"]}
    names = [r["name"] for r in recs]
    attempts = [r for r in recs
                if r["kind"] == "span" and r["name"] == "attempt"]
    assert len(attempts) == 2, names
    assert names.count("retry") == 1
    roots = [r for r in recs
             if r["kind"] == "span" and r["name"] == "submit"]
    assert len(roots) == 1
    assert roots[0]["attrs"]["state"] == "done"
    # both claims (one per scheduler incarnation) are in the one file
    assert sum(1 for r in recs
               if r["kind"] == "event" and r["name"] == "claim") == 2


# ------------------------------------------------------------ JSON logging
def test_json_log_formatter_injects_trace_fields():
    from sm_distributed_tpu.utils.logger import JsonLogFormatter

    fmt = JsonLogFormatter()
    rec = logging.LogRecord("sm-tpu", logging.INFO, __file__, 1,
                            "phase %s done", ("score",), None)
    ctx = tracing.new_trace(job_id="jobZ")
    with tracing.attach(ctx):
        line = fmt.format(rec)
    out = json.loads(line)
    assert out["msg"] == "phase score done"
    assert out["trace_id"] == ctx.trace_id
    assert out["job_id"] == "jobZ"
    assert out["span"] == ctx.span_id
    assert out["level"] == "INFO" and out["logger"] == "sm-tpu"
    # untraced thread: fields present but empty
    out2 = json.loads(fmt.format(rec))
    assert out2["trace_id"] == "" and out2["job_id"] == ""


def test_init_logger_json_switch(tmp_path, capsys):
    from sm_distributed_tpu.utils import logger as logmod

    lg = logmod.init_logger(json_logs=True)
    try:
        assert all(isinstance(h.formatter, logmod.JsonLogFormatter)
                   for h in lg.handlers)
    finally:
        logmod.init_logger(json_logs=False)
        assert not any(isinstance(h.formatter, logmod.JsonLogFormatter)
                       for h in lg.handlers)


# ----------------------------------------------------- phase observers
def test_phase_observers_multi_and_exception_safe():
    from sm_distributed_tpu.utils import logger as logmod

    seen_a, seen_b = [], []

    def obs_a(phase, dt):
        seen_a.append(phase)
        raise RuntimeError("observer bug")     # must not break anything

    def obs_b(phase, dt):
        seen_b.append((phase, dt))

    logmod.add_phase_observer(obs_a)
    logmod.add_phase_observer(obs_b)
    logmod.add_phase_observer(obs_b)           # idempotent
    try:
        with logmod.phase_timer("p1"):
            pass
        assert seen_a == ["p1"]
        assert [p for p, _ in seen_b] == ["p1"]    # a's raise didn't starve b
        logmod.remove_phase_observer(obs_a)
        with logmod.phase_timer("p2"):
            pass
        assert seen_a == ["p1"] and len(seen_b) == 2
        # legacy single-slot semantics still replace everything
        logmod.set_phase_observer(obs_a)
        assert logmod._phase_observers == [obs_a]
    finally:
        logmod.set_phase_observer(None)
    assert logmod._phase_observers == []


def test_phase_timer_emits_span(tmp_path):
    from sm_distributed_tpu.utils.logger import phase_timer

    ctx = tracing.new_trace(trace_dir=tmp_path)
    timings = {}
    with tracing.attach(ctx):
        with phase_timer("stage_input", timings):
            pass
    (rec,) = tracing.read_trace(ctx.file)
    assert rec["name"] == "stage_input" and rec["attrs"]["phase"] is True
    assert "stage_input" in timings


# ------------------------------------------------------ /metrics satellite
def test_build_info_and_process_gauges():
    from sm_distributed_tpu.service.metrics import (
        MetricsRegistry,
        build_info_collector,
        process_collector,
    )

    reg = MetricsRegistry()
    build_info_collector(reg, backend="numpy_ref")
    process_collector(reg)
    text = reg.expose()
    assert 'sm_build_info{' in text and 'backend="numpy_ref"' in text
    assert "jax_version=" in text
    assert "sm_process_threads" in text
    assert "sm_process_resident_memory_bytes" in text
    assert "sm_process_open_fds" in text


# ----------------------------------------------- service integration shape
def _service_harness(tmp_path):
    import sys

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from scripts.load_sweep import Harness, build_fixtures

    fx = build_fixtures(tmp_path)
    return Harness(tmp_path, "svc"), fx


def test_service_end_to_end_trace(tmp_path):
    """Acceptance shape: spheroid fixture through the REAL in-process
    service → one root submit span covering claim → phases → ≥1 per-batch
    scoring span → ≥1 isocalc worker span → store_results, served as
    Perfetto-loadable Chrome JSON by GET /jobs/<id>/trace."""
    import sys

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from scripts.load_sweep import _msg

    h, fx = _service_harness(tmp_path)
    try:
        status, _hd, body = h.submit(_msg(fx, "fast", "traced"))
        assert status == 202 and body["trace_id"]
        rows = h.wait_terminal([body["msg_id"]])
        assert rows[body["msg_id"]]["state"] == "done", rows
        assert rows[body["msg_id"]]["trace_id"] == body["trace_id"]

        with urllib.request.urlopen(
                f"{h.base}/jobs/{body['msg_id']}/trace?raw=1",
                timeout=30.0) as r:
            records = json.loads(r.read())["records"]
        assert not tracing.validate_records(records)
        spans = {r["name"] for r in records if r["kind"] == "span"}
        for required in ("submit", "attempt", "stage_input", "read_dataset",
                         "score", "score_batch", "isocalc_chunk",
                         "store_results"):
            assert required in spans, (required, sorted(spans))
        (root,) = [r for r in records
                   if r["kind"] == "span" and r["name"] == "submit"]
        lo, hi = root["ts"] - 0.05, root["ts"] + root["dur"] + 0.05
        for r in records:
            if r["kind"] == "span":
                assert lo <= r["ts"] <= hi, (r["name"], r["ts"], lo, hi)

        with urllib.request.urlopen(
                f"{h.base}/jobs/{body['msg_id']}/trace", timeout=30.0) as r:
            chrome = json.loads(r.read())
        assert chrome["traceEvents"]
        assert chrome["otherData"]["trace_id"] == body["trace_id"]

        # flight recorder endpoint
        with urllib.request.urlopen(f"{h.base}/debug/events?n=10",
                                    timeout=30.0) as r:
            ring = json.loads(r.read())
        assert isinstance(ring, list) and len(ring) <= 10 and ring
    finally:
        h.shutdown()


def test_trace_report_renders_service_trace(tmp_path, capsys):
    import sys

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from scripts import trace_report
    from scripts.load_sweep import _msg

    h, fx = _service_harness(tmp_path)
    try:
        status, _hd, body = h.submit(_msg(fx, "fast", "rpt"))
        assert status == 202
        rows = h.wait_terminal([body["msg_id"]])
        assert rows[body["msg_id"]]["state"] == "done"
        path = tracing.trace_path(h.service.trace_dir, body["trace_id"])
        assert trace_report.main([str(path), "--validate"]) == 0
        text = capsys.readouterr().out
        assert "phase breakdown" in text and "store_results" in text
        assert trace_report.main([str(path), "--json"]) == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["state"] == "done"
        assert summary["phases"]["score"]["seconds"] > 0
        assert summary["n_batches"] >= 1
        assert summary["n_isocalc_worker_spans"] >= 1
        assert summary["accounting"]["queue_wait_s"] is not None
    finally:
        h.shutdown()
