"""The result read path (ISSUE 16): columnar segment queries vs a
brute-force scan, cross-dataset cohorts, atomic republish, tile
bit-identity against engine/png.py, the governed LRU cache, and read
admission."""

from __future__ import annotations

import itertools
import json

import numpy as np
import pandas as pd
import pytest

from sm_distributed_tpu.engine.index import (
    CursorError,
    SegmentReader,
    publish_segment,
)
from sm_distributed_tpu.engine.png import PngGenerator
from sm_distributed_tpu.engine.storage import SearchResultsStore
from sm_distributed_tpu.service.readpath import ReadCache, ReadPath
from sm_distributed_tpu.utils import failpoints
from sm_distributed_tpu.utils.config import ReadPathConfig


@pytest.fixture(autouse=True)
def _reset_failpoints():
    failpoints.reset()
    yield
    failpoints.reset()


# ------------------------------------------------------------------ fixtures
def _annotations(n: int, seed: int = 0) -> pd.DataFrame:
    """A synthetic annotation table with ties, NaNs, and repeated formulas —
    the shapes that break naive sort/filter/pagination code."""
    rng = np.random.default_rng(seed)
    sfs = [f"C{i % 7 + 1}H{i % 5 + 2}O{i % 3}" for i in range(n)]
    adducts = [("+H", "+Na", "+K")[i % 3] for i in range(n)]
    msm = np.round(rng.uniform(0, 1, n), 2)       # rounding makes ties
    msm[:: max(1, n // 5)] = 0.5                  # and guarantees a few
    fdr = np.round(rng.uniform(0, 0.5, n), 3)
    fdr_level = rng.choice([0.05, 0.1, 0.2, 0.5, np.nan], n)
    return pd.DataFrame({
        "sf": sfs, "adduct": adducts, "msm": msm, "fdr": fdr,
        "fdr_level": fdr_level,
        "chaos": rng.uniform(0, 1, n), "spatial": rng.uniform(0, 1, n),
        "spectral": rng.uniform(0, 1, n)})


def _publish(results_dir, ds_id: str, n: int, seed: int = 0,
             job_id: int = 1) -> pd.DataFrame:
    d = results_dir / ds_id
    d.mkdir(parents=True, exist_ok=True)
    df = _annotations(n, seed)
    mzs = {(r.sf, r.adduct): 100.0 + i
           for i, r in enumerate(df.itertuples())}
    publish_segment(d, ds_id, job_id, df, mzs)
    return df


def _brute_rows(df: pd.DataFrame) -> list[dict]:
    """Row dicts straight off the pandas table (NaN -> None) — the
    independent ground truth the segment must reproduce."""
    rows = []
    for i, r in enumerate(df.itertuples()):
        rows.append({"sf": r.sf, "adduct": r.adduct, "mz": 100.0 + i,
                     "msm": r.msm, "fdr": r.fdr,
                     "fdr_level": None if np.isnan(r.fdr_level)
                     else r.fdr_level,
                     "chaos": r.chaos, "spatial": r.spatial,
                     "spectral": r.spectral})
    return rows


def _brute_query(rows, *, sf=None, adduct=None, max_fdr_level=None,
                 min_msm=None, mz_min=None, mz_max=None,
                 order="msm", direction="desc"):
    """Filter + total-order sort, written independently of the engine."""
    out = []
    for r in rows:
        if sf is not None and r["sf"] != sf:
            continue
        if adduct is not None and r["adduct"] != adduct:
            continue
        if max_fdr_level is not None and (
                r["fdr_level"] is None or r["fdr_level"] > max_fdr_level):
            continue
        if min_msm is not None and (
                r["msm"] is None or r["msm"] < min_msm):
            continue
        if mz_min is not None and (r["mz"] is None or r["mz"] < mz_min):
            continue
        if mz_max is not None and (r["mz"] is None or r["mz"] > mz_max):
            continue
        out.append(r)

    def key(r):
        v = r[order]
        if order != "sf" and v is None:
            v = float("-inf")
        return (v, r["sf"], r["adduct"])

    out.sort(key=key, reverse=(direction == "desc"))
    return out


def _paged(reader, ds_id, *, limit=7, **kw):
    """Walk every page through the cursor protocol, collecting rows."""
    rows, cursor, pages = [], None, 0
    while True:
        res = reader.query(ds_id, limit=limit, cursor=cursor, **kw)
        rows.extend(res["rows"])
        pages += 1
        assert pages < 100, "cursor never terminated"
        if res["next_cursor"] is None:
            return rows, res["total"]
        cursor = res["next_cursor"]


# --------------------------------------------------- parity vs brute force
def test_query_parity_vs_brute_force_scan(tmp_path):
    df = _publish(tmp_path, "ds1", n=60, seed=3)
    truth = _brute_rows(df)
    reader = SegmentReader(tmp_path)
    filters = [
        {},
        {"sf": truth[0]["sf"]},
        {"adduct": "+Na"},
        {"max_fdr_level": 0.1},
        {"min_msm": 0.5},
        {"mz_min": 110.0, "mz_max": 140.0},
        {"sf": truth[0]["sf"], "adduct": truth[0]["adduct"],
         "max_fdr_level": 0.5},
    ]
    for kw, order, direction in itertools.product(
            filters, ("msm", "mz", "fdr", "sf"), ("asc", "desc")):
        expect = _brute_query(truth, order=order, direction=direction, **kw)
        got, total = _paged(reader, "ds1", limit=7, order=order,
                            direction=direction, **kw)
        strip = [{k: v for k, v in r.items()
                  if k not in ("ds_id", "job_id")} for r in got]
        approx = [{k: (pytest.approx(v) if isinstance(v, float) else v)
                   for k, v in r.items()} for r in strip]
        assert total == len(expect), (kw, order, direction)
        assert approx == expect, (kw, order, direction)


def test_pagination_is_stable_and_duplicate_free(tmp_path):
    _publish(tmp_path, "ds1", n=41, seed=5)
    reader = SegmentReader(tmp_path)
    rows, total = _paged(reader, "ds1", limit=4, order="msm",
                         direction="desc")
    assert total == 41 and len(rows) == 41
    keys = [(r["msm"], r["sf"], r["adduct"]) for r in rows]
    assert len(set(keys)) == len(keys)          # keyset: no dup, no skip
    assert keys == sorted(keys, reverse=True)


def test_cursor_minted_under_other_order_rejected(tmp_path):
    _publish(tmp_path, "ds1", n=10)
    reader = SegmentReader(tmp_path)
    res = reader.query("ds1", order="msm", direction="desc", limit=3)
    cur = res["next_cursor"]
    assert cur is not None
    with pytest.raises(CursorError):
        reader.query("ds1", order="mz", direction="desc", cursor=cur)
    with pytest.raises(CursorError):
        reader.query("ds1", order="msm", direction="asc", cursor=cur)
    with pytest.raises(CursorError):
        reader.query("ds1", cursor="!!!not-a-cursor!!!")


# ------------------------------------------------------------------ cohort
def test_cohort_across_three_datasets(tmp_path):
    dfs = {ds: _publish(tmp_path, ds, n=30, seed=i)
           for i, ds in enumerate(("a", "b", "c"))}
    reader = SegmentReader(tmp_path)
    sf = dfs["a"]["sf"].iloc[0]                  # formula grid is shared
    res = reader.cohort(sf)
    assert res["sf"] == sf and res["n_datasets"] == 3
    per_ds = {d["ds_id"]: d["rows"] for d in res["datasets"]}
    assert set(per_ds) == {"a", "b", "c"}
    for ds, df in dfs.items():
        assert len(per_ds[ds]) == int((df["sf"] == sf).sum())
        assert all(r["sf"] == sf for r in per_ds[ds])
        msms = [r["msm"] for r in per_ds[ds]]
        assert msms == sorted(msms, reverse=True)
    assert res["n_rows"] == sum(len(v) for v in per_ds.values())


# --------------------------------------------------------- atomic republish
def test_reannotation_atomically_replaces_segment(tmp_path):
    _publish(tmp_path, "ds1", n=20, seed=1, job_id=1)
    reader = SegmentReader(tmp_path)
    v1 = reader.query("ds1")
    _publish(tmp_path, "ds1", n=35, seed=2, job_id=2)
    v2 = reader.query("ds1")
    assert (v1["job_id"], v1["total"]) == (1, 20)
    assert (v2["job_id"], v2["total"]) == (2, 35)
    assert v2["published_at"] >= v1["published_at"]
    assert not list((tmp_path / "ds1").glob("*.tmp"))


def test_crashed_publish_leaves_previous_segment_served(tmp_path):
    _publish(tmp_path, "ds1", n=12, seed=1, job_id=1)
    failpoints.configure("index.segment_commit=raise:OSError@1")
    with pytest.raises(OSError):
        _publish(tmp_path, "ds1", n=30, seed=2, job_id=2)
    reader = SegmentReader(tmp_path)
    res = reader.query("ds1")
    assert (res["job_id"], res["total"]) == (1, 12)   # old segment intact


# ----------------------------------------------------------------- tiles
def _store_images(tmp_path, ds_id="ds1", n_ions=3, k=2, nrows=6, ncols=5):
    rng = np.random.default_rng(7)
    images = rng.uniform(0, 1, (n_ions, k, nrows * ncols)).astype(np.float32)
    images[images < 0.3] = 0.0                  # sparsity, like real tiles
    ions = [(f"C{i}H{i + 1}", "+H") for i in range(n_ions)]
    store = SearchResultsStore.__new__(SearchResultsStore)
    store.results_dir = tmp_path
    store.image_format = "npz"
    d = tmp_path / ds_id
    d.mkdir(parents=True, exist_ok=True)
    store.ds_dir = lambda _ds: d
    store.store_ion_images(ds_id, images, ions, nrows, ncols)
    return images.reshape(n_ions, k, nrows, ncols), ions


def test_tile_bytes_bit_identical_to_direct_render(tmp_path):
    images, ions = _store_images(tmp_path)
    rp = ReadPath(tmp_path, ReadPathConfig())
    for i, (sf, adduct) in enumerate(ions):
        for k in range(images.shape[1]):
            status, body, _hd = rp.handle_tile(
                "ds1", f"{sf}|{adduct}", {"k": [str(k)]})
            assert status == 200
            assert body == PngGenerator().render(images[i, k])
    status, _body, _hd = rp.handle_tile("ds1", "XX|+H", {})
    assert status == 404
    status, _body, _hd = rp.handle_tile("ds1", f"{ions[0][0]}|+H",
                                        {"k": ["99"]})
    assert status == 404
    status, _body, _hd = rp.handle_tile("ds1", "no-pipe-here", {})
    assert status == 400


def test_tile_disk_tier_round_trip(tmp_path):
    images, ions = _store_images(tmp_path)
    disk = tmp_path / "tile_cache"
    rp = ReadPath(tmp_path, ReadPathConfig(), disk_dir=disk)
    sf, adduct = ions[0]
    status, body, _hd = rp.handle_tile("ds1", f"{sf}|{adduct}", {})
    assert status == 200
    spilled = list(disk.glob("*.png"))
    assert len(spilled) == 1 and spilled[0].read_bytes() == body
    # a fresh ReadPath (restart) serves the same bytes from the disk tier
    rp2 = ReadPath(tmp_path, ReadPathConfig(), disk_dir=disk)
    status, body2, _hd = rp2.handle_tile("ds1", f"{sf}|{adduct}", {})
    assert status == 200 and body2 == body
    assert rp2.snapshot()["cache"]["entries"] == 1


# ------------------------------------------------------------------ cache
def test_read_cache_lru_eviction_and_bounds():
    c = ReadCache(max_bytes=100, max_entries=3)
    c.put(("a",), "A", 40)
    c.put(("b",), "B", 40)
    assert c.get(("a",)) == "A"                 # refresh a
    c.put(("c",), "C", 40)                      # 120 > 100: evict LRU = b
    assert c.get(("b",)) is None and c.get(("a",)) == "A"
    c.put(("d",), "D", 10)
    c.put(("e",), "E", 10)                      # entry cap 3: evict oldest
    s = c.stats()
    assert s["entries"] <= 3 and s["bytes"] <= 100 and s["evictions"] >= 2
    c.put(("huge",), "X", 1000)                 # can never fit: not cached
    assert c.get(("huge",)) is None


def test_warm_query_is_a_cache_hit_and_republish_invalidates(tmp_path):
    _publish(tmp_path, "ds1", n=10, seed=1, job_id=1)
    rp = ReadPath(tmp_path, ReadPathConfig())
    s1, b1, _h = rp.handle_annotations("ds1", {})
    s2, b2, _h = rp.handle_annotations("ds1", {})
    assert s1 == s2 == 200 and b2 is b1         # literally the cached object
    stats = rp.snapshot()["cache"]
    assert stats["hits"] == 1 and stats["misses"] >= 1
    _publish(tmp_path, "ds1", n=25, seed=2, job_id=2)
    s3, b3, _h = rp.handle_annotations("ds1", {})
    assert s3 == 200 and b3["job_id"] == 2 and b3["total"] == 25


def test_cache_fill_failure_never_fails_the_read(tmp_path):
    _publish(tmp_path, "ds1", n=10)
    rp = ReadPath(tmp_path, ReadPathConfig())
    failpoints.configure("read.cache_fill=raise:OSError@1")
    s1, b1, _h = rp.handle_annotations("ds1", {})
    assert s1 == 200 and b1["total"] == 10      # read answered anyway
    assert rp.snapshot()["cache"]["entries"] == 0
    s2, b2, _h = rp.handle_annotations("ds1", {})   # retry warms it
    assert s2 == 200
    assert rp.snapshot()["cache"]["entries"] == 1


class _DenyingGovernor:
    def __init__(self):
        self.calls = 0

    def allow_read_cache_fill(self):
        self.calls += 1
        return False


def test_governor_denied_fill_serves_but_does_not_cache(tmp_path):
    _publish(tmp_path, "ds1", n=10)
    gov = _DenyingGovernor()
    rp = ReadPath(tmp_path, ReadPathConfig(), governor=gov)
    for _ in range(2):
        status, body, _h = rp.handle_annotations("ds1", {})
        assert status == 200 and body["total"] == 10
    assert gov.calls == 2                       # both reads tried to fill
    assert rp.snapshot()["cache"]["entries"] == 0


# --------------------------------------------------------------- admission
def test_read_admission_sheds_structured_429(tmp_path):
    _publish(tmp_path, "ds1", n=10)
    rp = ReadPath(tmp_path, ReadPathConfig(max_concurrent=1,
                                           retry_after_s=2.0))
    assert rp._admit()                          # occupy the only slot
    try:
        status, body, headers = rp.handle_annotations("ds1", {})
        assert status == 429
        assert body["reason"] == "read_overload" and not body["accepted"]
        assert body["retry_after_s"] == 2.0
        assert headers["Retry-After"] == "2"
        assert rp.snapshot()["sheds"] == 1
    finally:
        rp._release()
    status, _b, _h = rp.handle_annotations("ds1", {})   # slot free again
    assert status == 200


def test_bad_requests_are_structured_400s(tmp_path):
    _publish(tmp_path, "ds1", n=10)
    rp = ReadPath(tmp_path, ReadPathConfig(page_size=20, page_size_max=50))
    for params in ({"limit": ["0"]}, {"limit": ["9999"]},
                   {"limit": ["nope"]}, {"fdr": ["zz"]},
                   {"order": ["bogus"]}, {"dir": ["sideways"]},
                   {"cursor": ["@@@"]}):
        status, body, _h = rp.handle_annotations("ds1", params)
        assert status == 400, params
        assert body["error"] == "bad_request" and body["detail"]
    status, body, _h = rp.handle_cohort({})     # cohort requires sf
    assert status == 400
    status, body, _h = rp.handle_annotations("never-published", {})
    assert status == 404 and body["error"] == "not_found"


def test_metrics_and_snapshot_surface_read_activity(tmp_path):
    from sm_distributed_tpu.service.metrics import MetricsRegistry

    _publish(tmp_path, "ds1", n=10)
    reg = MetricsRegistry()
    rp = ReadPath(tmp_path, ReadPathConfig(), metrics=reg)
    rp.handle_annotations("ds1", {})
    rp.handle_annotations("ds1", {})
    rp.handle_annotations("missing", {})
    text = reg.expose()
    assert 'sm_read_requests_total{endpoint="annotations",outcome="ok"} 2' \
        in text
    assert 'outcome="http_404"' in text
    assert 'sm_read_cache_hits_total{kind="annotations"} 1' in text
    assert "sm_read_latency_seconds_bucket" in text
    assert "sm_read_cache_entries 1" in text
